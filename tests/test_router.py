"""Serving fleet tier tests (ISSUE 17): the session router's pinning /
membership / drain state machine as pure units, the routed wire path
(verbatim forwarding, exactly-once replay through the extra hop,
failover and spill), drain-not-kill semantics against the decode
oracle (bit-identical completion, deadline-overrun re-prefill
failover, killed-replica pinned-session failover), and the SLO-burn
autoscaler's hysteresis/cooldown schedule on the virtual clock.

The in-process tests drive real sockets but fabricate membership and
load signals directly on the ServeRouter object (no collector, no
refresh races); the one slow CLI lane goes through ``launch.py
--route`` end to end.
"""
import importlib.util
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import fault
from mxnet_tpu.base import MXNetError
from mxnet_tpu.serve import (BucketTable, Servable, ServeClient,
                             ServeServer, serve_forever)
from mxnet_tpu.serve.demo import DEMO_IN, demo_block, demo_example, \
    demo_expected
from mxnet_tpu.serve.router import ServeRouter, serve_router_forever
from mxnet_tpu.telemetry import registry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    fault.clear()
    yield
    fault.clear()


# ---------------------------------------------------------------------------
# routing state machine (no sockets)
# ---------------------------------------------------------------------------


def test_route_least_loaded_then_pins():
    rt = ServeRouter(replicas=["a:1", "b:2"])
    rt._signals = {"a:1": {"queue_rows": 9.0},
                   "b:2": {"queue_rows": 1.0, "active_slots": 1.0}}
    assert rt.route("cid") == "b:2"          # least loaded wins
    rt._signals = {"a:1": {"queue_rows": 0.0},
                   "b:2": {"queue_rows": 99.0}}
    # the pin outlives the load signal flipping: sessions stick
    assert rt.route("cid") == "b:2"
    # a different session sees the new signals
    assert rt.route("other") == "a:1"


def test_pin_cap_lru_evicts_oldest(monkeypatch):
    monkeypatch.setenv("MX_ROUTER_PIN_CAP", "2")
    rt = ServeRouter(replicas=["a:1"])
    rt.route("s1")
    rt.route("s2")
    rt.route("s1")                           # LRU touch: s1 is recent
    rt.route("s3")                           # over cap: s2 evicted
    assert set(rt._pins) == {"s1", "s3"}


def test_membership_reconcile_lifecycle():
    rt = ServeRouter(replicas=["a:1", "b:2"])
    rt.route("cid")                          # pin somewhere
    pinned = rt._pins["cid"]
    other = "a:1" if pinned == "b:2" else "b:2"
    # the pinned replica leaves the authoritative list: it drains (the
    # autoscaler DRAINs the process; the router just stops admitting)
    rt.set_replicas([other])
    assert rt._replicas[pinned] == "draining"
    assert "cid" not in rt._pins             # moved off the leaver
    assert rt.route("cid") == other
    # dead members that left are forgotten entirely
    rt.mark_dead(pinned)
    rt.set_replicas([other])
    assert pinned not in rt._replicas
    # a returning addr rejoins up (optimistically)
    rt.set_replicas([other, pinned])
    assert rt._replicas[pinned] == "up"


def test_mark_dead_unpins_sessions():
    rt = ServeRouter(replicas=["a:1", "b:2"])
    rt._signals = {"b:2": {"queue_rows": 50.0}}
    assert rt.route("cid") == "a:1"
    u0 = registry.value("router.sessions_unpinned")
    rt.mark_dead("a:1")
    assert "cid" not in rt._pins
    assert registry.value("router.sessions_unpinned") == u0 + 1
    # the session fails over to the survivor despite its load
    assert rt.route("cid") == "b:2"
    # no live replica at all: route must say so, not hang
    rt.mark_dead("b:2")
    assert rt.route("cid") is None


def test_router_drain_admits_only_pinned_first_deadline_wins():
    with fault.use_virtual_time() as clk:
        rt = ServeRouter(replicas=["a:1"])
        rt.route("old")                      # pinned before retirement
        assert rt.admits("old") and rt.admits("new") and rt.admits(None)
        st = rt.drain(5.0)
        assert st["status"] == "draining" and rt.draining
        assert rt.admits("old")              # pinned sessions keep flowing
        assert not rt.admits("new") and not rt.admits(None)
        clk.advance(4.0)
        assert not rt.drain_expired()
        rt.drain(100.0)                      # a retried DRAIN must not
        clk.advance(2.0)                     # extend the first deadline
        assert rt.drain_expired()


# ---------------------------------------------------------------------------
# routed wire path (real sockets, fabricated membership)
# ---------------------------------------------------------------------------


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_port(port, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port),
                                     timeout=0.2).close()
            return
        except OSError:
            time.sleep(0.05)
    raise RuntimeError("nothing came up on %d" % port)


def _start_replica(port, buckets=(1, 4), abort_event=None):
    state = ServeServer()
    state.host.deploy(
        Servable(demo_block(), version=1, buckets=BucketTable(buckets)),
        example=demo_example())
    stop_ev = threading.Event()
    t = threading.Thread(
        target=serve_forever,
        kwargs=dict(port=port, state=state, stop_event=stop_ev,
                    abort_event=abort_event),
        daemon=True)
    t.start()
    _wait_port(port)
    return state, stop_ev, t


def _start_router(port, replicas):
    rt = ServeRouter(replicas=replicas, refresh=0.1)
    stop_ev = threading.Event()
    t = threading.Thread(
        target=serve_router_forever,
        kwargs=dict(port=port, router=rt, stop_event=stop_ev),
        daemon=True)
    t.start()
    _wait_port(port)
    return rt, stop_ev, t


@pytest.fixture
def fast_retry(monkeypatch):
    monkeypatch.setenv("MX_KVSTORE_RETRY_DEADLINE", "20")
    monkeypatch.setenv("MX_KVSTORE_RETRY_BASE", "0.05")
    monkeypatch.setenv("MX_KVSTORE_RETRY_MAX", "0.25")
    yield


def test_routed_predict_round_trip(fast_retry):
    p1, rp = _free_port(), _free_port()
    _state, ev1, t1 = _start_replica(p1)
    rt, rev, trt = _start_router(rp, ["127.0.0.1:%d" % p1])
    try:
        cli = ServeClient(["127.0.0.1:%d" % rp], timeout=15)
        net = demo_block()
        x = np.random.RandomState(2).randn(3, DEMO_IN).astype(np.float32)
        version, outs = cli.predict([x])
        assert version == 1
        np.testing.assert_allclose(outs[0], demo_expected(x, net=net),
                                   rtol=1e-5, atol=1e-6)
        # HEALTH is answered by the ROUTER itself (fleet-tier state)
        h = cli.health()
        assert h["role"] == "router" and h["status"] == "routing"
        assert h["replicas"] == {"127.0.0.1:%d" % p1: "up"}
        cli.close()
    finally:
        rev.set()
        ev1.set()
        trt.join(timeout=10)
        t1.join(timeout=10)


@pytest.mark.chaos
def test_replay_through_router_is_exactly_once(fast_retry):
    """A reply lost between router and client: the client replays the
    same seq through the router, the router forwards it VERBATIM, and
    the REPLICA's exactly-once cache answers — no second dispatch."""
    p1, rp = _free_port(), _free_port()
    _state, ev1, t1 = _start_replica(p1)
    _rt, rev, trt = _start_router(rp, ["127.0.0.1:%d" % p1])
    try:
        cli = ServeClient(["127.0.0.1:%d" % rp], timeout=15)
        x = np.ones((1, DEMO_IN), np.float32)
        cli.predict([x])                     # connection warm
        b0 = registry.value("serve.batches")
        r0 = registry.value("serve.server_replays")
        fault.inject("serve.client.recv", action="close", after=0,
                     count=1)
        version, _outs = cli.predict([x])
        assert version == 1
        assert registry.value("serve.server_replays") == r0 + 1
        assert registry.value("serve.batches") == b0 + 1, \
            "the replay through the router burned a second dispatch"
        cli.close()
    finally:
        rev.set()
        ev1.set()
        trt.join(timeout=10)
        t1.join(timeout=10)


@pytest.mark.chaos
def test_killed_replica_fails_over_pinned_sessions(fast_retry):
    """SIGKILL analog: sever the pinned replica mid-conversation.  The
    router absorbs the failover (dead mark, unpin, replay on the
    survivor) — the client never sees an error."""
    p1, p2, rp = _free_port(), _free_port(), _free_port()
    ab1 = threading.Event()
    _s1, _ev1, t1 = _start_replica(p1, buckets=(2,), abort_event=ab1)
    _s2, ev2, t2 = _start_replica(p2, buckets=(2,))
    a1, a2 = "127.0.0.1:%d" % p1, "127.0.0.1:%d" % p2
    rt, rev, trt = _start_router(rp, [a1])   # pin lands on replica 1
    try:
        cli = ServeClient(["127.0.0.1:%d" % rp], timeout=15)
        net = demo_block()
        rng = np.random.RandomState(4)
        x = rng.randn(2, DEMO_IN).astype(np.float32)
        cli.predict([x])
        assert list(rt._pins.values()) == [a1]
        rt.set_replicas([a1, a2])            # survivor joins
        f0 = registry.value("router.failovers")
        cf0 = registry.value("serve.client_failovers")
        ab1.set()                            # kill the pinned replica
        for _ in range(3):
            x = rng.randn(2, DEMO_IN).astype(np.float32)
            version, outs = cli.predict([x])
            np.testing.assert_allclose(outs[0],
                                       demo_expected(x, net=net),
                                       rtol=1e-5, atol=1e-6)
        assert registry.value("router.failovers") > f0
        assert rt._replicas[a1] == "dead"
        assert list(rt._pins.values()) == [a2]
        # the failover happened ROUTER-side: the client saw nothing
        assert registry.value("serve.client_failovers") == cf0
        cli.close()
    finally:
        ab1.set()
        ev2.set()
        rev.set()
        trt.join(timeout=10)
        t1.join(timeout=10)
        t2.join(timeout=10)


@pytest.mark.chaos
def test_draining_refusal_spills_and_repins(fast_retry):
    """A replica that starts draining refuses with a NORMAL reply; the
    router believes it before the membership file catches up, spills
    the request to the next-best replica, and re-pins the session."""
    p1, p2, rp = _free_port(), _free_port(), _free_port()
    s1, ev1, t1 = _start_replica(p1, buckets=(2,))
    _s2, ev2, t2 = _start_replica(p2, buckets=(2,))
    a1, a2 = "127.0.0.1:%d" % p1, "127.0.0.1:%d" % p2
    rt, rev, trt = _start_router(rp, [a1])
    try:
        cli = ServeClient(["127.0.0.1:%d" % rp], timeout=15)
        net = demo_block()
        x = np.random.RandomState(5).randn(2, DEMO_IN).astype(np.float32)
        cli.predict([x])
        assert list(rt._pins.values()) == [a1]
        rt.set_replicas([a1, a2])
        sp0 = registry.value("router.spills")
        s1.drain(timeout=30.0)               # replica 1 starts retiring
        version, outs = cli.predict([x])
        np.testing.assert_allclose(outs[0], demo_expected(x, net=net),
                                   rtol=1e-5, atol=1e-6)
        assert registry.value("router.spills") == sp0 + 1
        assert rt._replicas[a1] == "draining"
        assert list(rt._pins.values()) == [a2]
        cli.close()
    finally:
        rev.set()
        ev1.set()
        ev2.set()
        trt.join(timeout=10)
        t1.join(timeout=10)
        t2.join(timeout=10)


# ---------------------------------------------------------------------------
# drain-not-kill vs the decode oracle
# ---------------------------------------------------------------------------


DCFG = dict(dim=16, heads=2, layers=2, slots=4, max_tokens=12,
            prompt_buckets=(4, 8))


@pytest.fixture(scope="module")
def decode_ref():
    from mxnet_tpu.serve.decode import DecodeConfig, DecodeServable
    cfg = DecodeConfig(**DCFG)
    sv = DecodeServable(config=cfg)
    return sv.params, cfg


def _start_decode_replica(port, params, cfg, abort_event=None,
                          on_tick=None):
    from mxnet_tpu.serve.decode import DecodeBatcher, DecodeServable
    sv = DecodeServable(params=params, config=cfg)
    state = ServeServer(decode=DecodeBatcher(sv, on_tick=on_tick))
    stop_ev = threading.Event()
    t = threading.Thread(
        target=serve_forever,
        kwargs=dict(port=port, state=state, stop_event=stop_ev,
                    abort_event=abort_event),
        daemon=True)
    t.start()
    _wait_port(port)
    return state, stop_ev, t


@pytest.mark.chaos
def test_drain_completes_inflight_bit_identical(fast_retry, decode_ref):
    """Mid-generation retirement must DRAIN: the in-flight sequence
    finishes bit-identical to the uninterrupted oracle, new work is
    refused with a normal reply, and the serve loop exits cleanly once
    the replica is empty."""
    from mxnet_tpu.serve.decode import reference_generate
    params, cfg = decode_ref
    port = _free_port()
    # ~20ms/step pump so the DRAIN lands MID-generation, not after it
    state, _stop, t = _start_decode_replica(
        port, params, cfg, on_tick=lambda: time.sleep(0.02))
    addr = "127.0.0.1:%d" % port
    ref = reference_generate([6, 2, 8], 12, params=params, config=cfg)
    result = {}

    def call():
        with ServeClient([addr], timeout=30) as cli:
            result["out"] = cli.generate([6, 2, 8], max_tokens=12)

    gen = threading.Thread(target=call, daemon=True)
    gen.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if state.decode.active_count() > 0:
            break
        time.sleep(0.001)
    assert state.decode.active_count() > 0, "generation never started"
    with ServeClient([addr], timeout=15) as dc:
        st = dc.drain(timeout=30.0)
        assert st["status"] == "draining"
        # admission is CLOSED while the in-flight generation finishes
        with pytest.raises(MXNetError, match="draining"):
            dc.generate([1, 2], max_tokens=2)
    gen.join(timeout=60)
    assert "out" in result, "drain lost the in-flight generation"
    _version, toks = result["out"]
    assert toks == ref, "drained generation diverged from the oracle"
    # drained clean: the serve loop exits by itself, no STOP needed
    t.join(timeout=30)
    assert not t.is_alive(), "serve loop kept running after drain"
    state.close()


@pytest.mark.chaos
def test_drain_deadline_overrun_fails_over_stragglers(fast_retry,
                                                      decode_ref):
    """A drain deadline too short for the in-flight generation: the
    straggler's connection is severed with NO reply, the ROUTER marks
    the replica dead and replays the envelope on the survivor, which
    re-prefills — the caller still gets the exact sequence and never
    sees the failover."""
    from mxnet_tpu.serve.decode import reference_generate
    params, cfg = decode_ref
    p1, p2, rp = _free_port(), _free_port(), _free_port()
    # replica 1 is slow (~50ms/step) so the overrun is guaranteed;
    # replica 2 (same params) is the survivor
    state1, _st1, t1 = _start_decode_replica(
        p1, params, cfg, on_tick=lambda: time.sleep(0.05))
    state2, st2, t2 = _start_decode_replica(p2, params, cfg)
    a1, a2 = "127.0.0.1:%d" % p1, "127.0.0.1:%d" % p2
    rt, rev, trt = _start_router(rp, [a1])   # session pins on replica 1
    ref = reference_generate([6, 2, 8], 12, params=params, config=cfg)
    f0 = registry.value("router.failovers")
    cf0 = registry.value("serve.client_failovers")
    result = {}

    def call():
        with ServeClient(["127.0.0.1:%d" % rp], timeout=60) as cli:
            result["out"] = cli.generate([6, 2, 8], max_tokens=12)

    gen = threading.Thread(target=call, daemon=True)
    gen.start()
    try:
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if state1.decode.active_count() > 0:
                break
            time.sleep(0.001)
        assert state1.decode.active_count() > 0
        rt.set_replicas([a1, a2])            # survivor joins
        # a deadline far shorter than the generation: overrun is the
        # point — the straggler must be severed and fail over
        with ServeClient([a1], timeout=15) as dc:
            dc.drain(timeout=0.05)
        gen.join(timeout=60)
        assert "out" in result, "generation lost in the overrun"
        _version, toks = result["out"]
        assert toks == ref
        assert registry.value("router.failovers") > f0
        # the failover was absorbed router-side
        assert registry.value("serve.client_failovers") == cf0
    finally:
        rev.set()
        st2.set()
        trt.join(timeout=10)
        t1.join(timeout=15)
        t2.join(timeout=10)
        state1.close()
        state2.close()


# ---------------------------------------------------------------------------
# autoscaler hysteresis on the virtual clock
# ---------------------------------------------------------------------------


def _load_launch():
    spec = importlib.util.spec_from_file_location(
        "mx_launch_router_test", os.path.join(REPO, "tools", "launch.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


launch = _load_launch()


class _FakeSLO:
    def __init__(self):
        self.resets = 0

    def reset(self):
        self.resets += 1


class _FakeFleet:
    """snapshot()-shaped stand-in: tests fabricate scrape rounds."""

    def __init__(self):
        self.snap = None
        self.added = []
        self.retired = []
        self.slo = _FakeSLO()

    def snapshot(self):
        return self.snap

    def add_member(self, m):
        self.added.append(m)

    def retire(self, key):
        self.retired.append(key)


def _mk_autoscaled(tmp_path, monkeypatch, replicas=1, mn=1, mx=3,
                   hold=2, cooldown=10.0):
    monkeypatch.setenv("MX_AUTOSCALE_HOLD", str(hold))
    monkeypatch.setenv("MX_AUTOSCALE_COOLDOWN", str(cooldown))
    logs = []
    sup = launch.Supervisor(restart="never",
                            log=lambda m: logs.append(m))
    sup._fault = fault                  # _now() rides the virtual clock
    sup.autoscale = (mn, mx)
    sup.replicas_file = str(tmp_path / "replicas")
    spawned = []
    monkeypatch.setattr(launch.Supervisor, "_spawn",
                        lambda self, sp: spawned.append(sp.name))

    def factory(idx):
        addr = "127.0.0.1:%d" % (9700 + idx)
        return "serve-%d" % idx, ["true"], {}, addr, None

    sup.serve_factory = factory
    fl = _FakeFleet()
    sup.fleet = fl
    for i in range(replicas):
        sup.add("serve-%d" % i, ["true"], {},
                role="serve", addr="127.0.0.1:%d" % (9700 + i))
    sup._as_next_index = replicas
    sup._write_replicas_file()
    return sup, fl, spawned, logs


def _round(sup, fl, burn):
    fl.snap = {"scrape": getattr(fl, "_round", 0) + 1,
               "slo": {"burn": {"serve_p99_ms": burn}}}
    fl._round = fl.snap["scrape"]
    sup._check_autoscale()


def _replicas_file(sup):
    with open(sup.replicas_file) as f:
        return [ln.strip() for ln in f if ln.strip()]


def test_autoscaler_spawns_after_hold_then_cools_down(tmp_path,
                                                      monkeypatch):
    with fault.use_virtual_time() as clk:
        sup, fl, spawned, logs = _mk_autoscaled(tmp_path, monkeypatch)
        _round(sup, fl, 2.0)
        assert spawned == []                 # one breach round: held
        _round(sup, fl, 2.0)
        assert spawned == ["serve-1"]        # held MX_AUTOSCALE_HOLD
        assert _replicas_file(sup) == ["127.0.0.1:9700",
                                       "127.0.0.1:9701"]
        assert [m.key for m in fl.added]     # registered with the plane
        # burn stays breached, but the cooldown gates the next action
        _round(sup, fl, 2.0)
        _round(sup, fl, 2.0)
        assert spawned == ["serve-1"]
        clk.advance(100.0)                   # cooldown over
        _round(sup, fl, 2.0)
        assert spawned == ["serve-1", "serve-2"]
        # MAX replicas: breach forever, never exceed the bound
        clk.advance(100.0)
        for _ in range(5):
            _round(sup, fl, 2.0)
        assert len(sup._serve_procs()) == 3
        assert any("spawning serve-1" in m for m in logs)


def test_autoscaler_retires_drain_not_kill(tmp_path, monkeypatch):
    with fault.use_virtual_time() as clk:
        sup, fl, _spawned, logs = _mk_autoscaled(tmp_path, monkeypatch,
                                                 replicas=2)
        drained = []
        monkeypatch.setattr(launch, "_send_drain",
                            lambda addr, **kw: drained.append(addr))
        _round(sup, fl, 0.0)
        _round(sup, fl, 0.0)
        sp1 = sup.procs[-1]
        assert sp1.draining                  # newest replica retires
        # admission closed at the ROUTER first: the file shrank BEFORE
        # (well, with) the DRAIN courtesy to the replica itself
        assert _replicas_file(sup) == ["127.0.0.1:9700"]
        assert drained == ["127.0.0.1:9701"]
        assert fl.slo.resets == 1            # stale latches un-latched
        assert any("drain-not-kill" in m for m in logs)
        # MIN floor: burn stays low forever, the last replica survives
        clk.advance(100.0)
        for _ in range(5):
            _round(sup, fl, 0.0)
        assert len(sup._serve_procs()) == 1


def test_autoscaler_hysteresis_band_holds_steady(tmp_path, monkeypatch):
    with fault.use_virtual_time():
        sup, fl, spawned, _logs = _mk_autoscaled(tmp_path, monkeypatch)
        drained = []
        monkeypatch.setattr(launch, "_send_drain",
                            lambda addr, **kw: drained.append(addr))
        # a band round (between DOWN_BURN and UP_BURN) resets BOTH
        # holds: breach-band-breach never accumulates to an action
        for burn in (2.0, 0.75, 2.0, 0.75, 0.0, 0.75, 0.0):
            _round(sup, fl, burn)
        assert spawned == [] and drained == []


def test_autoscaler_drain_failure_falls_back_to_kill(tmp_path,
                                                     monkeypatch):
    with fault.use_virtual_time():
        sup, fl, _spawned, logs = _mk_autoscaled(tmp_path, monkeypatch,
                                                 replicas=2)

        def boom(addr, **kw):
            raise OSError("connection refused")

        monkeypatch.setattr(launch, "_send_drain", boom)
        killed = []
        monkeypatch.setattr(launch.Supervisor, "_kill",
                            lambda self, sp: killed.append(sp.name))
        _round(sup, fl, 0.0)
        _round(sup, fl, 0.0)
        assert killed == ["serve-1"]
        assert any("DRAIN failed" in m for m in logs)


# ---------------------------------------------------------------------------
# catalog + CLI lane
# ---------------------------------------------------------------------------


def test_router_env_knobs_are_cataloged():
    from mxnet_tpu.base import ENV_CATALOG
    for name in ("MX_ROUTER_PORT", "MX_ROUTER_REPLICAS",
                 "MX_ROUTER_REPLICAS_FILE", "MX_ROUTER_REFRESH",
                 "MX_ROUTER_FLEET", "MX_ROUTER_PIN_CAP",
                 "MX_ROUTER_DRAIN_TIMEOUT", "MX_AUTOSCALE_UP_BURN",
                 "MX_AUTOSCALE_DOWN_BURN", "MX_AUTOSCALE_HOLD",
                 "MX_AUTOSCALE_COOLDOWN"):
        assert name in ENV_CATALOG, name
        default, doc = ENV_CATALOG[name]
        assert doc


@pytest.mark.slow
@pytest.mark.chaos
def test_cli_router_drain_not_kill_mid_load(tmp_path):
    """The slow CLI lane: `launch.py --route` fronting two demo
    replicas, 40 verified predicts through the router while one replica
    is DRAINed mid-load (clean exit, no restart), then STOP through the
    router folds the whole fleet to exit 0."""
    while True:
        base = _free_port()
        try:
            s = socket.socket()
            s.bind(("", base + 1))
            s.close()
            break
        except OSError:
            continue
    rport = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("MX_FAULT_INJECT", None)
    env.update(JAX_PLATFORMS="cpu", MX_FORCE_CPU="1",
               PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""))
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", "--restart", "on-failure",
         "--hang-timeout", "60",
         "--serve-port-base", str(base), "--route", str(rport), "--",
         sys.executable, "-m", "mxnet_tpu.serve", "--demo",
         "--port-base", str(base)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    try:
        for port in (base, base + 1, rport):
            _wait_port(port, timeout=180.0)
        net = demo_block()
        rng = np.random.RandomState(6)
        cli = ServeClient(["127.0.0.1:%d" % rport], timeout=30)
        for i in range(40):
            if i == 15:
                with ServeClient(["127.0.0.1:%d" % base],
                                 timeout=15) as dc:
                    st = dc.drain(timeout=20.0)
                    assert st["status"] == "draining"
            x = rng.randn(2, DEMO_IN).astype(np.float32)
            _version, outs = cli.predict([x])
            np.testing.assert_allclose(outs[0],
                                       demo_expected(x, net=net),
                                       rtol=1e-5, atol=1e-6)
        cli.stop()
        cli.close()
        out, _ = proc.communicate(timeout=120)
    except Exception:
        proc.kill()
        raise
    assert proc.returncode == 0, out
