"""gluon.contrib.estimator (reference:
tests/python/unittest/test_gluon_estimator.py /
test_gluon_event_handler.py patterns)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.gluon.contrib.estimator import (
    Estimator, BatchEnd, CheckpointHandler, EarlyStoppingHandler,
    LoggingHandler, StoppingHandler)


def _data(n=192, d=8, k=3, batch=32, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    W = rng.randn(d, k).astype(np.float32)
    Y = (X @ W).argmax(1).astype(np.float32)
    ds = gluon.data.ArrayDataset(nd.array(X), nd.array(Y))
    return gluon.data.DataLoader(ds, batch_size=batch, shuffle=True), \
        gluon.data.DataLoader(ds, batch_size=batch)


def _est(lr=0.05):
    net = gluon.nn.Dense(3)
    net.initialize(mx.init.Xavier())
    return Estimator(net, loss=gluon.loss.SoftmaxCrossEntropyLoss(),
                     train_metrics=mx.metric.Accuracy(),
                     trainer=gluon.Trainer(net.collect_params(), "adam",
                                           {"learning_rate": lr}))


def test_fit_converges_and_validates():
    train, val = _data()
    est = _est()
    est.fit(train, val_data=val, epochs=5)
    assert est.train_metrics[0].get()[1] > 0.85
    vals = dict(m.get() for m in est.evaluate(val))
    assert vals["accuracy"] > 0.85


def test_stop_on_batches():
    train, _ = _data()
    est = _est()
    seen = []

    class Counter(BatchEnd):
        def batch_end(self, estimator, *a, **kw):
            seen.append(1)

    est.fit(train, batches=4, event_handlers=[Counter()])
    assert len(seen) == 4


def test_checkpoint_handler(tmp_path):
    train, _ = _data()
    est = _est()
    est.fit(train, epochs=2, event_handlers=[
        CheckpointHandler(str(tmp_path), monitor=est.train_metrics[0],
                          save_best=True)])
    names = sorted(os.listdir(str(tmp_path)))
    assert "model-best.params" in names
    assert "model-epoch2.params" in names
    # best weights load back into a fresh net
    net2 = gluon.nn.Dense(3)
    net2.load_parameters(str(tmp_path / "model-best.params"))


def test_early_stopping():
    train, _ = _data()
    est = _est(lr=0.0)      # frozen learning -> metric never improves
    stopper = EarlyStoppingHandler(est.train_metrics[0], patience=1)
    est.fit(train, epochs=50, event_handlers=[stopper])
    assert stopper.stop_training
    assert stopper.current_epoch < 10


def test_default_handlers_dedupe():
    train, _ = _data()
    est = _est()
    handlers = est._prepare_handlers(None, 2, None,
                                     [StoppingHandler(max_epoch=2),
                                      LoggingHandler()])
    assert sum(isinstance(h, StoppingHandler) for h in handlers) == 1
    assert sum(isinstance(h, LoggingHandler) for h in handlers) == 1


def test_evaluate_resets_dataiter_val_data():
    """A DataIter-style val_data (iter() returns self, no rewind) must be
    reset by evaluate(), or epoch-2+ validation sees zero batches and the
    metrics silently freeze."""
    rng = np.random.RandomState(0)
    X = rng.randn(96, 8).astype(np.float32)
    Y = rng.randint(0, 3, 96).astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=32)
    est = _est()
    first = dict(m.get() for m in est.evaluate(it))
    second = dict(m.get() for m in est.evaluate(it))
    assert not np.isnan(second["accuracy"])
    assert second["accuracy"] == first["accuracy"]


def test_val_metric_monitors_read_current_epoch():
    """Validation runs before user epoch-end handlers, so a handler
    monitoring a val metric sees THIS epoch's value (not nan/stale)."""
    train, val = _data()
    est = _est()
    stopper = EarlyStoppingHandler(est.val_metrics[0], patience=3,
                                   mode="max")
    est.fit(train, val_data=val, epochs=4, event_handlers=[stopper])
    # the monitor must have seen real values (best updated from -inf)
    assert stopper.best > 0.0, stopper.best
