"""Program-contract verifier (ISSUE 11): device-free donation/HBM/
trace-closure proofs.

Layers, bottom-up:

  * the SHIPPED manifest: every declared contract builds, lowers and
    compiles under JAX_PLATFORMS=cpu, >= 15 registered programs verify
    with ZERO findings (contract findings are never baselined), and
    every declared donation is accounted (aliased + pruned == expected);
  * reinjection — the acceptance criterion verbatim: a dropped donation
    (dtype-mismatched donated leaf), a budget overrun (1-byte budget),
    and an unbucketed shape (closure point outside the case set) each
    trip the right finding class, the closure miss rendered through the
    retrace-explainer diff.  (The unhandled-wire-verb reinjection lives
    in tests/test_mxlint.py with the other AST-rule fixtures.);
  * the CLI (`python -m tools.mxlint --contracts`): exit contract,
    --format json schema, --select narrowing, and the manifest
    round-trip that tools/bench_compare.py --check-schema validates.
"""
import json
import os
import subprocess
import sys
import uuid

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax                                              # noqa: E402
import jax.numpy as jnp                                 # noqa: E402

from mxnet_tpu import programs                          # noqa: E402
from tools.mxlint import contracts as lane              # noqa: E402
from tools import bench_compare                         # noqa: E402


def _name(tag):
    return "test.%s.%s" % (tag, uuid.uuid4().hex[:8])


def _shipped_names():
    """The shipped contract set: everything the declaring modules
    register, minus any test-declared 'test.*' contracts this process
    accumulated."""
    return [c.name for c in lane.load_contracts()
            if not c.name.startswith("test.")]


@pytest.fixture(scope="module")
def shipped():
    """One full run of the lane over the shipped tree (module-scoped:
    every lowering is cached by jax afterwards, so the per-test cost is
    paid once)."""
    diags, results, verified = lane.verify(_shipped_names(), root=REPO)
    return diags, results, verified


# ---------------------------------------------------------------------------
# the shipped tree proves clean
# ---------------------------------------------------------------------------

def test_shipped_contracts_verify_15_programs_zero_findings(shipped):
    diags, results, verified = shipped
    assert diags == [], "\n".join(map(repr, diags))
    assert len(set(verified)) >= 15, sorted(verified)
    # the headline surfaces are all in the proven set
    assert {"step.step", "step.window", "optimizer.fused_adam",
            "kvstore.exchange_int8"} <= set(verified)
    assert any(p.startswith("serve.demo.b") for p in verified)


def test_shipped_donations_fully_accounted(shipped):
    _diags, results, _verified = shipped
    donating = [r for r in results if r.donated_expected]
    assert donating, "no donating contract cases found"
    for r in donating:
        assert r.aliased + r.pruned == r.donated_expected, vars(r)
        assert r.dropped == 0, vars(r)
    # the step programs donate all six state groups with nothing pruned
    step_rows = [r for r in results if r.program.startswith("step.")]
    assert step_rows and all(r.pruned == 0 and r.aliased ==
                             r.donated_expected for r in step_rows)


def test_shipped_budgets_hold_with_headroom(shipped):
    _diags, results, _verified = shipped
    for r in results:
        if r.budget is not None and r.temp_bytes is not None:
            assert r.temp_bytes <= r.budget, vars(r)


def test_pruned_donation_noted_not_flagged(shipped):
    """The mp Adam/AdamW weights are donated but value-unused (the new
    weights derive from the fp32 masters): jax prunes them, the lane
    NOTES the no-op donation in the pruned column without flagging."""
    _diags, results, _verified = shipped
    mp_rows = [r for r in results if r.label.endswith("_mp")]
    assert mp_rows and all(r.pruned == 3 for r in mp_rows), \
        [vars(r) for r in mp_rows]


def test_contract_schema_constants_agree():
    assert bench_compare.CONTRACT_SCHEMA == programs.CONTRACT_SCHEMA


# ---------------------------------------------------------------------------
# reinjection: each check trips
# ---------------------------------------------------------------------------

def test_reinjected_dropped_donation_trips():
    """A donated f32 buffer whose only same-shape output is bf16: XLA
    cannot alias it, jax warns at lowering, and the lane must flag it —
    this is the exact failure that doubles HBM on TPU while CPU stays
    green."""
    name = _name("drop")

    def body(w, g):
        return (w - g).astype(jnp.bfloat16)

    sds = jax.ShapeDtypeStruct((64,), jnp.float32)
    programs.declare_contract(
        name,
        lambda: [programs.ContractCase(name, (sds, sds), fn=body,
                                       jit_kw={"donate_argnums": (0,)})],
        donate_argnums=(0,))
    diags, results, _ = lane.verify([name], root=REPO)
    assert [d.rule for d in diags] == [lane.RULE_DONATION]
    assert "donations dropped" in diags[0].message
    assert "not usable" in diags[0].message          # jax's warning rides
    (r,) = results
    assert r.donated_expected == 1 and r.aliased == 0 and r.dropped == 1


def test_reinjected_budget_overrun_trips():
    """A 1-byte temp budget against a kernel with real scratch: the
    static HBM-creep gate fires with both numbers in the message."""
    from mxnet_tpu.ops import quantization as q
    import functools
    name = _name("budget")
    sds = jax.ShapeDtypeStruct((4096,), jnp.float32)
    programs.declare_contract(
        name,
        lambda: [programs.ContractCase(
            name, (sds, sds),
            fn=functools.partial(q._quantize_int8_kernel, block=256),
            jit_kw={"donate_argnums": (1,)})],
        donate_argnums=(1,), temp_budget_bytes=1)
    diags, results, _ = lane.verify([name], root=REPO)
    assert [d.rule for d in diags] == [lane.RULE_BUDGET]
    assert "1-byte budget" in diags[0].message
    (r,) = results
    assert r.temp_bytes and r.temp_bytes > 1


def test_reinjected_unbucketed_shape_trips_with_explainer_diff():
    """A closure point resolving to a shape outside the declared case
    set: the zero-retrace proof fails and the finding carries the
    retrace explainer's structured diff naming the offending arg."""
    name = _name("closure")

    def body(x):
        return x.sum()

    def args_for(n):
        return (jax.ShapeDtypeStruct((n, 16), jnp.float32),)

    closure = programs.ContractClosure(
        points=[4, 5],                      # 5 pads to... nothing: leak
        resolve=lambda n: args_for(n))
    programs.declare_contract(
        name,
        lambda: [programs.ContractCase(name, args_for(4), label="b4",
                                       fn=body, jit_kw={})],
        closure=closure)
    diags, _results, _ = lane.verify([name], root=REPO)
    assert [d.rule for d in diags] == [lane.RULE_CLOSURE]
    msg = diags[0].message
    assert "point 5" in msg and "retrace" in msg
    # the explainer diff names the changed leaf and both shapes
    assert "shape" in msg and "(5, 16)" in msg and "(4, 16)" in msg


def test_reinjected_declaration_spec_mismatch_trips():
    """A contract declaring fewer donations than the jit site actually
    donates: the aliasing arithmetic cannot attribute aliases across
    the mismatch, so the lane flags the divergence itself."""
    name = _name("mismatch")
    prog = programs.register_program(name, lambda w, s: (w + 1, s + 1),
                                     donate_argnums=(0, 1))
    sds = jax.ShapeDtypeStruct((16,), jnp.float32)
    programs.declare_contract(
        name,
        lambda: [programs.ContractCase(name, (sds, sds), target=prog)],
        donate_argnums=(0,))
    diags, _r, _v = lane.verify([name], root=REPO)
    assert any(d.rule == lane.RULE_DONATION and
               "mismatched spec" in d.message for d in diags), \
        "\n".join(map(repr, diags))


def test_step_window_closure_covers_configured_scan(monkeypatch):
    """The step contract's closure proves the CONFIGURED window set: an
    MX_STEP_SCAN outside the contracted windows fails statically
    instead of retracing at runtime."""
    from mxnet_tpu import step as step_mod
    step_mod._step_contract_built.cache_clear()
    monkeypatch.setenv("MX_STEP_SCAN", "7")
    try:
        diags, _r, _v = lane.verify(["step.train"], root=REPO)
    finally:
        step_mod._step_contract_built.cache_clear()
    closure_hits = [d for d in diags if d.rule == lane.RULE_CLOSURE]
    assert closure_hits and "point 7" in closure_hits[0].message
    # and the explainer diff names the reshaped batch leaves
    assert "(7, 8, 16)" in closure_hits[0].message


def test_broken_builder_is_a_finding_not_a_crash():
    name = _name("broken")

    def build():
        raise RuntimeError("model zoo offline")

    programs.declare_contract(name, build)
    diags, results, verified = lane.verify([name], root=REPO)
    assert [d.rule for d in diags] == [lane.RULE_ERROR]
    assert "model zoo offline" in diags[0].message
    assert results == [] and verified == []


# ---------------------------------------------------------------------------
# manifest + CLI
# ---------------------------------------------------------------------------

def test_manifest_roundtrip_and_bench_compare_validation(tmp_path,
                                                         shipped):
    _diags, results, _verified = shipped
    doc = lane.manifest(results)
    assert doc["schema"] == programs.CONTRACT_SCHEMA
    assert len(doc["programs"]) >= 15
    # multi-case programs keep EVERY lowering (the mp adam row must not
    # shadow the plain one)
    adam = doc["programs"]["optimizer.fused_adam"]
    assert sorted(c["label"] for c in adam["cases"]) == \
        ["adam", "adam_mp"]
    p = tmp_path / "contracts.json"
    p.write_text(json.dumps(doc))
    assert bench_compare.check_contract_manifest(str(p)) == 0
    # schema drift fails
    bad = dict(doc, schema=99)
    p.write_text(json.dumps(bad))
    assert bench_compare.check_contract_manifest(str(p)) == 1
    # a case row missing a required field fails
    bad = json.loads(json.dumps(doc))
    next(iter(bad["programs"].values()))["cases"][0].pop("aliased")
    p.write_text(json.dumps(bad))
    assert bench_compare.check_contract_manifest(str(p)) == 1
    # absent manifest is fine (fresh checkout before the first run)
    assert bench_compare.check_contract_manifest(
        str(tmp_path / "absent.json")) == 0


def test_checked_in_manifest_is_valid():
    assert os.path.isfile(lane.DEFAULT_MANIFEST), \
        "tools/mxlint/contracts.json missing — run " \
        "python -m tools.mxlint --contracts --write-manifest"
    assert bench_compare.check_contract_manifest(lane.DEFAULT_MANIFEST) \
        == 0


def test_budget_table_renders_every_case(shipped):
    _diags, results, _verified = shipped
    table = lane.budget_table(results)
    lines = table.splitlines()
    assert lines[0].startswith("program")
    for r in results:
        assert any(r.program in ln and r.label in ln for ln in lines)


@pytest.mark.slow
def test_cli_contracts_json_and_select():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", "tools.mxlint", "--contracts",
         "--select", "quant.gradient_wire", "--format", "json"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    doc = json.loads(out.stdout)
    assert doc["contract_schema"] == programs.CONTRACT_SCHEMA
    assert doc["violations"] == []
    assert set(doc["verified_programs"]) == \
        {"quant.q8_256", "quant.rt8_256", "quant.q2"}
    # a typo'd --select is a usage error (2), never "clean" (0)
    out = subprocess.run(
        [sys.executable, "-m", "tools.mxlint", "--contracts",
         "--select", "no.such.contract"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 2
    assert "unknown contract" in out.stderr
    # --select + --write-manifest is refused: a partial write would
    # silently drop the unselected programs' snapshot rows
    out = subprocess.run(
        [sys.executable, "-m", "tools.mxlint", "--contracts",
         "--select", "quant.gradient_wire", "--write-manifest"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 2
    assert "cannot be combined" in out.stderr
