"""Subgraph backend-property registry (reference:
src/operator/subgraph/subgraph_property.h SubgraphPropertyRegistry,
HybridBlock.optimize_for; tests/python/unittest/test_subgraph.py pattern).

Key invariant: properties are PER BLOCK — two blocks with different
backends coexist without clobbering each other or the process default.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import nn
from mxnet_tpu.subgraph import (SubgraphProperty, register_backend,
                                get_backend, list_backends)


def test_registry_has_builtin_properties():
    names = list_backends()
    for expected in ("pallas", "xla", "amp_bf16", "amp_float16"):
        assert expected in names, names
    assert get_backend("pallas").cache_token() == "pallas"
    with pytest.raises(KeyError, match="unknown subgraph backend"):
        get_backend("tensorrt")


def test_register_custom_property_and_scope_runs():
    seen = []

    @register_backend("_test_prop")
    class _P(SubgraphProperty):
        def scope(self):
            import contextlib

            @contextlib.contextmanager
            def cm():
                seen.append("enter")
                yield
                seen.append("exit")
            return cm()

    net = nn.Dense(3, in_units=4)
    net.initialize()
    x = nd.ones((2, 4))
    net.optimize_for(x, backend="_test_prop")
    assert net._backend == "_test_prop"
    net(x).wait_to_read()
    assert seen and seen.count("enter") == seen.count("exit")


def test_per_block_attention_isolation():
    """Block A forced 'pallas', block B forced 'xla', plain calls default:
    the scoped impl must be visible only inside each block's execution."""
    from mxnet_tpu.ops import attention as att

    class AttnBlock(nn.HybridBlock):
        def __init__(self):
            super().__init__()
            self.seen = []

        def hybrid_forward(self, F, x):
            self.seen.append(att.current_attention_impl())
            return x * 2

    a, b = AttnBlock(), AttnBlock()
    for blk in (a, b):
        blk.initialize()
    x = nd.ones((2, 4))
    a.optimize_for(x, backend="pallas")
    b.optimize_for(x, backend="xla")
    assert att.current_attention_impl() is None   # nothing leaked
    a(x).wait_to_read()
    b(x).wait_to_read()
    assert "pallas" in a.seen and "xla" not in a.seen
    assert "xla" in b.seen and "pallas" not in b.seen
    assert att.current_attention_impl() is None


def test_backend_cache_key_separation():
    """Same block re-targeted: executables must not be shared across
    lowering configs (the backend is part of the cached-op key)."""
    net = nn.Dense(3, in_units=4)
    net.initialize()
    x = nd.ones((2, 4))
    net.optimize_for(x, backend="pallas")
    net(x).wait_to_read()
    keys_pallas = set(net._cache)
    net.optimize_for(x, backend="xla", clear=False)
    net(x).wait_to_read()
    assert set(net._cache) != keys_pallas        # new entries, old intact
    # key layout: (..., property_token, global_attention_default)
    assert all(k[-2] in ("pallas", "xla") for k in net._cache)
    assert all(k[-1] is None for k in net._cache)


def test_amp_bf16_property_casts_inside_block_only():
    import mxnet_tpu.amp as amp

    net = nn.Dense(8, in_units=8)
    net.initialize()
    x = nd.ones((2, 8))
    out_plain = net(x)
    assert str(out_plain.dtype) == "float32"
    net.optimize_for(x, backend="amp_bf16")
    out_amp = net(x)
    assert amp.STATE is None                      # scope did not leak
    assert "bfloat16" in str(out_amp.dtype)
    # numerics stay close at bf16 precision
    np.testing.assert_allclose(out_amp.asnumpy().astype(np.float32),
                               out_plain.asnumpy(), rtol=2e-2, atol=2e-2)
