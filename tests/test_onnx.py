"""ONNX export/import round-trip (reference: python/mxnet/onnx mx2onnx/
onnx2mx).  No onnx package offline: the wire format is written/read
directly; the round-trip (export -> import -> numerically identical
forward) pins both directions against each other."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import symbol as sym


def _forward(symbol, params, x):
    args = {"data": nd.array(x)}
    for k, v in params.items():
        args[k] = v if isinstance(v, nd.NDArray) else nd.array(v)
    exe = symbol.bind(mx.cpu(), args)
    return exe.forward()[0].asnumpy()


def _mlp():
    data = sym.Variable("data")
    h = sym.FullyConnected(data, sym.Variable("fc1_weight"),
                           sym.Variable("fc1_bias"), num_hidden=8,
                           name="fc1")
    h = sym.Activation(h, act_type="relu", name="relu1")
    out = sym.FullyConnected(h, sym.Variable("fc2_weight"),
                             sym.Variable("fc2_bias"), num_hidden=3,
                             name="fc2")
    return sym.softmax(out, name="prob")


def test_mlp_roundtrip(tmp_path):
    rng = np.random.RandomState(0)
    params = {
        "fc1_weight": nd.array(rng.randn(8, 4).astype(np.float32)),
        "fc1_bias": nd.array(rng.randn(8).astype(np.float32)),
        "fc2_weight": nd.array(rng.randn(3, 8).astype(np.float32)),
        "fc2_bias": nd.array(rng.randn(3).astype(np.float32)),
    }
    s = _mlp()
    path = str(tmp_path / "mlp.onnx")
    mx.onnx.export_model(s, params, input_shapes=[(2, 4)],
                         onnx_file_path=path)
    s2, arg2, aux2 = mx.onnx.import_model(path)
    x = rng.randn(2, 4).astype(np.float32)
    np.testing.assert_allclose(_forward(s2, arg2, x),
                               _forward(s, params, x), rtol=1e-5, atol=1e-6)


def test_conv_roundtrip(tmp_path):
    rng = np.random.RandomState(1)
    data = sym.Variable("data")
    c = sym.Convolution(data, sym.Variable("conv_weight"),
                        sym.Variable("conv_bias"), kernel=(3, 3),
                        pad=(1, 1), num_filter=4, name="conv")
    r = sym.Activation(c, act_type="relu", name="crelu")
    p = sym.Pooling(r, kernel=(2, 2), stride=(2, 2), pool_type="max",
                    name="pool")
    f = sym.flatten(p, name="flat")
    out = sym.FullyConnected(f, sym.Variable("fc_weight"),
                             sym.Variable("fc_bias"), num_hidden=2,
                             name="fc")
    params = {
        "conv_weight": nd.array(rng.randn(4, 3, 3, 3).astype(np.float32)),
        "conv_bias": nd.array(rng.randn(4).astype(np.float32)),
        "fc_weight": nd.array(rng.randn(2, 64).astype(np.float32)),
        "fc_bias": nd.array(rng.randn(2).astype(np.float32)),
    }
    path = str(tmp_path / "cnn.onnx")
    mx.onnx.export_model(out, params, input_shapes=[(1, 3, 8, 8)],
                         onnx_file_path=path)
    s2, arg2, aux2 = mx.onnx.import_model(path)
    x = rng.randn(1, 3, 8, 8).astype(np.float32)
    np.testing.assert_allclose(_forward(s2, arg2, x),
                               _forward(out, params, x),
                               rtol=1e-4, atol=1e-5)


def test_metadata(tmp_path):
    params = {"fc1_weight": nd.array(np.zeros((8, 4), np.float32)),
              "fc1_bias": nd.array(np.zeros(8, np.float32)),
              "fc2_weight": nd.array(np.zeros((3, 8), np.float32)),
              "fc2_bias": nd.array(np.zeros(3, np.float32))}
    path = str(tmp_path / "m.onnx")
    mx.onnx.export_model(_mlp(), params, input_shapes=[(2, 4)],
                         onnx_file_path=path)
    meta = mx.onnx.get_model_metadata(path)
    assert meta["input_tensor_data"] == [("data", (2, 4))]
    assert len(meta["output_tensor_data"]) == 1


def test_export_unsupported_op_raises(tmp_path):
    import pytest
    data = sym.Variable("data")
    weird = sym.GridGenerator(data, transform_type="affine",
                              target_shape=(4, 4))
    with pytest.raises(Exception, match="no ONNX mapping"):
        mx.onnx.export_model(weird, {}, input_shapes=[(1, 6)],
                             onnx_file_path=str(tmp_path / "x.onnx"))


def test_export_after_hybridize_forward(tmp_path):
    """The standard deploy flow: hybridize + forward (cache active) then
    export -> onnx -> import must match the original outputs (regression:
    nested cached blocks used to leak jit tracers into the symbol trace)."""
    import os
    rng = np.random.RandomState(3)
    net = mx.gluon.nn.HybridSequential()
    net.add(mx.gluon.nn.Dense(16, activation="relu"),
            mx.gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    x = nd.array(rng.randn(2, 8).astype(np.float32))
    ref = net(x).asnumpy()
    net.hybridize()
    net(x)
    sym_file, params_file = net.export(str(tmp_path / "mlp"), epoch=0)
    onnx_path = mx.onnx.export_model(
        sym_file, params_file, input_shapes=[(2, 8)],
        onnx_file_path=str(tmp_path / "mlp.onnx"))
    s2, arg2, aux2 = mx.onnx.import_model(onnx_path)
    args = {"data": x}
    args.update(arg2)
    out = s2.bind(mx.cpu(), args).forward()[0].asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_tied_weight_flatten_false_roundtrip(tmp_path):
    """One weight shared by two flatten=False FC heads: exported ONCE in
    transposed form, imported with a SINGLE transpose and the right
    num_hidden (regressions: dropped initializer / double transpose /
    stale num_hidden / None bias into symbol compose)."""
    data = sym.Variable("data")
    w = sym.Variable("w")
    h1 = sym.FullyConnected(data, w, num_hidden=8, no_bias=True,
                            flatten=False, name="fc1")
    h2 = sym.FullyConnected(sym.Activation(data, act_type="relu"), w,
                            num_hidden=8, no_bias=True, flatten=False,
                            name="fc2")
    out = h1 + h2
    params = {"w": nd.array(
        np.random.RandomState(0).randn(8, 4).astype(np.float32))}
    path = str(tmp_path / "tied.onnx")
    mx.onnx.export_model(out, params, input_shapes=[(2, 3, 4)],
                         onnx_file_path=path)
    s2, arg2, _ = mx.onnx.import_model(path)
    x = np.random.RandomState(1).randn(2, 3, 4).astype(np.float32)
    np.testing.assert_allclose(_forward(s2, arg2, x),
                               _forward(out, params, x),
                               rtol=1e-5, atol=1e-6)


def test_no_bias_gemm_roundtrip(tmp_path):
    g = sym.FullyConnected(sym.Variable("data"), sym.Variable("w2"),
                           num_hidden=3, no_bias=True, name="g")
    params = {"w2": nd.array(
        np.random.RandomState(2).randn(3, 4).astype(np.float32))}
    path = str(tmp_path / "nb.onnx")
    mx.onnx.export_model(g, params, input_shapes=[(2, 4)],
                         onnx_file_path=path)
    s2, arg2, _ = mx.onnx.import_model(path)
    x = np.random.RandomState(3).randn(2, 4).astype(np.float32)
    np.testing.assert_allclose(_forward(s2, arg2, x),
                               _forward(g, params, x), rtol=1e-5)


# -- round-4 widening: LSTM / attention / LayerNorm+gelu / resize -----------

def test_lstm_roundtrip(tmp_path):
    """RNN(mode=lstm) -> ONNX LSTM(+Squeeze) -> RNN: identical outputs
    (VERDICT r3 #7; gate-order translation ifgo<->iofc is the hard part)."""
    from mxnet_tpu.ops.rnn import rnn_param_size
    rng = np.random.RandomState(2)
    T, N, I, H = 5, 3, 4, 6
    psize = rnn_param_size(1, I, H, "lstm")
    params = {
        "lstm_parameters": nd.array(
            rng.randn(psize).astype(np.float32) * 0.3),
    }
    data = sym.Variable("data")
    h0 = sym.Variable("h0")
    c0 = sym.Variable("c0")
    out = sym.RNN(data, sym.Variable("lstm_parameters"), h0, c0,
                  state_size=H, num_layers=1, mode="lstm",
                  state_outputs=True, name="lstm")[0]
    path = str(tmp_path / "lstm.onnx")
    mx.onnx.export_model(out, params, input_shapes=[(T, N, I), (1, N, H),
                                                    (1, N, H)],
                         onnx_file_path=path)
    s2, arg2, aux2 = mx.onnx.import_model(path)

    x = rng.randn(T, N, I).astype(np.float32)
    h = np.zeros((1, N, H), np.float32)
    c = np.zeros((1, N, H), np.float32)

    def run(symbol, prm):
        args = {"data": nd.array(x), "h0": nd.array(h), "c0": nd.array(c)}
        for k, v in prm.items():
            args[k] = v if isinstance(v, nd.NDArray) else nd.array(v)
        exe = symbol.bind(mx.cpu(), args)
        return exe.forward()[0].asnumpy()

    got = run(s2, arg2)
    want = run(out, params)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def _encoder_block(units=8, heads=2):
    """BERT-style block: MHA + residual + LayerNorm + gelu FFN."""
    x = sym.Variable("data")
    q = sym.FullyConnected(x, sym.Variable("q_weight"),
                           sym.Variable("q_bias"), num_hidden=units,
                           flatten=False, name="q")
    k = sym.FullyConnected(x, sym.Variable("k_weight"),
                           sym.Variable("k_bias"), num_hidden=units,
                           flatten=False, name="k")
    v = sym.FullyConnected(x, sym.Variable("v_weight"),
                           sym.Variable("v_bias"), num_hidden=units,
                           flatten=False, name="v")
    att = sym.multi_head_attention(q, k, v, num_heads=heads, scaled=True,
                                   units=units, name="att")
    res = sym.elemwise_add(att, x, name="res")
    ln = sym.LayerNorm(res, sym.Variable("ln_gamma"),
                       sym.Variable("ln_beta"), name="ln")
    ff = sym.FullyConnected(ln, sym.Variable("ff_weight"),
                            sym.Variable("ff_bias"), num_hidden=units,
                            flatten=False, name="ff")
    return sym.gelu(ff, name="act")


def _encoder_params(units=8):
    rng = np.random.RandomState(3)
    p = {}
    for nm in ("q", "k", "v", "ff"):
        p[nm + "_weight"] = nd.array(
            rng.randn(units, units).astype(np.float32) * 0.2)
        p[nm + "_bias"] = nd.array(rng.randn(units).astype(np.float32) * 0.1)
    p["ln_gamma"] = nd.array(np.ones(units, np.float32))
    p["ln_beta"] = nd.array(np.zeros(units, np.float32))
    return p


def test_bert_encoder_block_roundtrip(tmp_path):
    units, heads = 8, 2
    s = _encoder_block(units, heads)
    params = _encoder_params(units)
    path = str(tmp_path / "encoder.onnx")
    mx.onnx.export_model(s, params, input_shapes=[(2, 5, units)],
                         onnx_file_path=path)
    s2, arg2, aux2 = mx.onnx.import_model(path)
    rng = np.random.RandomState(4)
    x = rng.randn(2, 5, units).astype(np.float32)
    np.testing.assert_allclose(_forward(s2, arg2, x),
                               _forward(s, params, x),
                               rtol=1e-4, atol=1e-5)


def test_resize_upsample_roundtrip(tmp_path):
    data = sym.Variable("data")
    up = sym.UpSampling(data, scale=2, sample_type="nearest", name="up")
    bl = sym.BilinearResize2D(up, height=5, width=7, name="bl")
    path = str(tmp_path / "resize.onnx")
    mx.onnx.export_model(bl, {}, input_shapes=[(1, 2, 3, 3)],
                         onnx_file_path=path)
    s2, arg2, aux2 = mx.onnx.import_model(path)
    rng = np.random.RandomState(5)
    x = rng.randn(1, 2, 3, 3).astype(np.float32)
    np.testing.assert_allclose(_forward(s2, arg2, x), _forward(bl, {}, x),
                               rtol=1e-5, atol=1e-6)


def test_embedding_gather_roundtrip(tmp_path):
    data = sym.Variable("data")
    emb = sym.Embedding(data, sym.Variable("emb_weight"), input_dim=11,
                        output_dim=6, name="emb")
    rng = np.random.RandomState(6)
    params = {"emb_weight": nd.array(rng.randn(11, 6).astype(np.float32))}
    path = str(tmp_path / "emb.onnx")
    mx.onnx.export_model(emb, params, input_shapes=[(3, 4)],
                         onnx_file_path=path)
    s2, arg2, aux2 = mx.onnx.import_model(path)
    idx = rng.randint(0, 11, (3, 4)).astype(np.float32)
    np.testing.assert_allclose(_forward(s2, arg2, idx),
                               _forward(emb, params, idx),
                               rtol=1e-6, atol=1e-6)


def test_golden_fixture_bytes(tmp_path):
    """Golden wire-format fixtures: the exported bytes for a pinned LSTM
    cell and encoder block must match the checked-in .onnx files EXACTLY —
    conformance without onnxruntime (VERDICT r3 #7).  Regenerate with
    tools/make_onnx_goldens.py when the exporter intentionally changes."""
    import os
    golden_dir = os.path.join(os.path.dirname(__file__), "fixtures")
    for name, build in (("golden_lstm", _golden_lstm),
                        ("golden_encoder", _golden_encoder)):
        path = str(tmp_path / (name + ".onnx"))
        build(path)
        golden = os.path.join(golden_dir, name + ".onnx")
        assert os.path.exists(golden), \
            "missing fixture %s — run tools/make_onnx_goldens.py" % golden
        with open(path, "rb") as f:
            got = f.read()
        with open(golden, "rb") as f:
            want = f.read()
        assert got == want, \
            "%s: exported bytes diverge from the golden fixture" % name


def _golden_lstm(path):
    from mxnet_tpu.ops.rnn import rnn_param_size
    T, N, I, H = 4, 2, 3, 5
    psize = rnn_param_size(1, I, H, "lstm")
    flat = (np.arange(psize, dtype=np.float32) % 7 - 3) / 10.0
    params = {"lstm_parameters": nd.array(flat)}
    data = sym.Variable("data")
    h0, c0 = sym.Variable("h0"), sym.Variable("c0")
    out = sym.RNN(data, sym.Variable("lstm_parameters"), h0, c0,
                  state_size=H, num_layers=1, mode="lstm",
                  state_outputs=True, name="lstm")[0]
    mx.onnx.export_model(out, params,
                         input_shapes=[(T, N, I), (1, N, H), (1, N, H)],
                         onnx_file_path=path)


def _golden_encoder(path):
    units = 8
    s = _encoder_block(units, 2)
    rng = np.random.RandomState(0)
    p = {}
    for nm in ("q", "k", "v", "ff"):
        p[nm + "_weight"] = nd.array(
            (np.arange(units * units, dtype=np.float32).reshape(units,
                                                                units)
             % 5 - 2) / 10.0)
        p[nm + "_bias"] = nd.array(np.zeros(units, np.float32))
    p["ln_gamma"] = nd.array(np.ones(units, np.float32))
    p["ln_beta"] = nd.array(np.zeros(units, np.float32))
    mx.onnx.export_model(s, p, input_shapes=[(2, 4, units)],
                         onnx_file_path=path)


def _rnn_mode_roundtrip(tmp_path, mode):
    from mxnet_tpu.ops.rnn import rnn_param_size
    rng = np.random.RandomState(5)
    T, N, I, H = 5, 3, 4, 6
    psize = rnn_param_size(1, I, H, mode)
    params = {"rnn_parameters": nd.array(
        rng.randn(psize).astype(np.float32) * 0.3)}
    data = sym.Variable("data")
    h0 = sym.Variable("h0")
    out = sym.RNN(data, sym.Variable("rnn_parameters"), h0,
                  state_size=H, num_layers=1, mode=mode,
                  state_outputs=True, name="rnn")[0]
    path = str(tmp_path / (mode + ".onnx"))
    mx.onnx.export_model(out, params,
                         input_shapes=[(T, N, I), (1, N, H)],
                         onnx_file_path=path)
    s2, arg2, aux2 = mx.onnx.import_model(path)

    x = rng.randn(T, N, I).astype(np.float32)
    h = np.zeros((1, N, H), np.float32)

    def run(symbol, prm):
        args = {"data": nd.array(x), "h0": nd.array(h)}
        for k, v in prm.items():
            args[k] = v if isinstance(v, nd.NDArray) else nd.array(v)
        exe = symbol.bind(mx.cpu(), args)
        return exe.forward()[0].asnumpy()

    np.testing.assert_allclose(run(s2, arg2), run(out, params),
                               rtol=1e-5, atol=1e-5)


def test_gru_roundtrip(tmp_path):
    """GRU gate-order translation rzn<->zrn + linear_before_reset=1."""
    _rnn_mode_roundtrip(tmp_path, "gru")


def test_vanilla_rnn_roundtrips(tmp_path):
    """ONNX RNN op with activations=[Tanh]/[Relu]."""
    _rnn_mode_roundtrip(tmp_path, "rnn_tanh")
    _rnn_mode_roundtrip(tmp_path, "rnn_relu")


def test_dynamic_batch_axis_export(tmp_path):
    """dynamic=True writes symbolic dim_params so ONE exported model
    serves any batch size; the importer treats them as free dims."""
    rng = np.random.RandomState(0)
    params = {
        "fc1_weight": rng.randn(8, 4).astype(np.float32),
        "fc1_bias": rng.randn(8).astype(np.float32),
        "fc2_weight": rng.randn(3, 8).astype(np.float32),
        "fc2_bias": rng.randn(3).astype(np.float32),
    }
    path = str(tmp_path / "dyn.onnx")
    mx.onnx.export_model(_mlp(), params, onnx_file_path=path,
                         dynamic=True, dynamic_input_shapes=[(None, 4)])
    s2, arg2, aux2 = mx.onnx.import_model(path)
    for n in (2, 7):     # same imported graph, different batch sizes
        x = rng.randn(n, 4).astype(np.float32)
        np.testing.assert_allclose(_forward(s2, arg2, x),
                                   _forward(_mlp(), params, x),
                                   rtol=1e-5, atol=1e-5)
    # dynamic without the axis spec is refused (the reference contract:
    # guessing would free the wrong axis of TNC/state inputs)
    import pytest
    with pytest.raises(Exception, match="dynamic_input_shapes"):
        mx.onnx.export_model(_mlp(), params, input_shapes=[(2, 4)],
                             onnx_file_path=str(tmp_path / "dyn3.onnx"),
                             dynamic=True)


def test_deconvolution_clip_pad_roundtrip(tmp_path):
    """Deconvolution<->ConvTranspose (incl. adj/output_padding), clip and
    Pad round-trip numerically."""
    rng = np.random.RandomState(4)
    data = sym.Variable("data")
    h = sym.Deconvolution(data, sym.Variable("dc_weight"), kernel=(3, 3),
                          stride=(2, 2), pad=(1, 1), adj=(1, 1),
                          num_filter=5, no_bias=True, name="dc")
    h = sym.clip(h, a_min=-0.4, a_max=0.6, name="cl")
    out = sym.Pad(h, mode="constant", constant_value=0.25,
                  pad_width=(0, 0, 0, 0, 1, 2, 1, 2), name="pd")
    params = {"dc_weight": rng.randn(4, 5, 3, 3).astype(np.float32) * 0.3}
    path = str(tmp_path / "dcp.onnx")
    mx.onnx.export_model(out, params, input_shapes=[(2, 4, 7, 7)],
                         onnx_file_path=path)
    s2, arg2, aux2 = mx.onnx.import_model(path)
    x = rng.randn(2, 4, 7, 7).astype(np.float32)
    np.testing.assert_allclose(_forward(s2, arg2, x),
                               _forward(out, params, x),
                               rtol=1e-5, atol=1e-5)
    # edge-mode Pad too (no constant_value input)
    out2 = sym.Pad(sym.Variable("data"), mode="edge",
                   pad_width=(0, 0, 0, 0, 2, 2, 2, 2), name="pe")
    path2 = str(tmp_path / "pe.onnx")
    mx.onnx.export_model(out2, {}, input_shapes=[(1, 2, 4, 4)],
                         onnx_file_path=path2)
    s3, arg3, _ = mx.onnx.import_model(path2)
    x2 = rng.randn(1, 2, 4, 4).astype(np.float32)
    np.testing.assert_allclose(_forward(s3, arg3, x2),
                               _forward(out2, {}, x2), rtol=1e-6)
