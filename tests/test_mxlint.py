"""mxlint (ISSUE 4): the TPU-invariant static analyzer.

Three layers, bottom-up:

  * fixture snippets per rule — positive hit (right rule id, right
    line), suppressed hit (`# mxlint: disable=`), baselined hit, clean
    code — all through ``lint_source`` with no filesystem;
  * the CLI contract (`python -m tools.mxlint`): exit 0 clean / 1 new
    violations / 2 usage error, ``--format json``, ``--write-baseline``
    round-trip, plus ``tools/gen_env_docs.py --check`` consistency;
  * the tier-1 gate: the SHIPPED tree lints clean against the checked-in
    baseline, and intentionally reintroducing the historical violations
    (an ``asnumpy()`` in ``Trainer._update``, a raw ``time.time()`` in
    the kvstore connect-retry loop) trips the right rule id — the
    acceptance criteria of the issue, verbatim.

Pure stdlib + pytest: no jax import, so this file costs milliseconds.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.mxlint import (lint_source, lint_sources, lint_paths,    # noqa: E402
                          load_baseline, load_baseline_whys,
                          write_baseline, collect_env_reads, RULES)
from tools.mxlint.core import apply_baseline                        # noqa: E402

BASELINE = os.path.join(REPO, "tools", "mxlint", "baseline.json")
RUNTIME_PATHS = [os.path.join(REPO, "mxnet_tpu"),
                 os.path.join(REPO, "tools", "launch.py")]


def rules_of(diags):
    return [d.rule for d in diags]


def src(text):
    return textwrap.dedent(text).lstrip("\n")


# ---------------------------------------------------------------------------
# host-sync-in-hot-path
# ---------------------------------------------------------------------------

HOT_PATH = "mxnet_tpu/gluon/trainer.py"

def test_host_sync_positive_direct_and_via_helper():
    code = src("""
    class Trainer:
        def step(self, batch_size):
            self._update()

        def _update(self):
            for p in self.params:
                self._drain(p)

        def _drain(self, p):
            return float(p.grad.asnumpy()[0])
    """)
    diags = lint_source(code, HOT_PATH)
    assert rules_of(diags) == ["host-sync-in-hot-path"]
    assert diags[0].line == 10
    # message names the reachable root, not just the containing helper
    assert "Trainer" in diags[0].message and "_drain" in diags[0].message


def test_host_sync_suppressed():
    code = src("""
    class Trainer:
        def _update(self):
            return self.g.asnumpy()  # mxlint: disable=host-sync-in-hot-path
    """)
    assert lint_source(code, HOT_PATH) == []


def test_host_sync_clean_and_out_of_hot_path():
    clean = src("""
    class Trainer:
        def _update(self):
            self.w = self.w - self.lr * self.g

    def offline_report(arrs):
        return [a.asnumpy() for a in arrs]
    """)
    assert lint_source(clean, HOT_PATH) == []
    # same sync outside any hot-path file: no rule applies
    sync = "def f(a):\n    return a.asnumpy()\n"
    assert lint_source(sync, "mxnet_tpu/visualization.py") == []


def test_host_sync_metric_update_root():
    code = src("""
    class Accuracy:
        def update(self, labels, preds):
            import numpy as np
            self.sum_metric += float(np.asarray(preds).sum())
    """)
    diags = lint_source(code, "mxnet_tpu/metric.py")
    assert rules_of(diags) == ["host-sync-in-hot-path"]


# ---------------------------------------------------------------------------
# jit-purity
# ---------------------------------------------------------------------------

def test_jit_purity_decorated():
    code = src("""
    import time
    import jax

    @jax.jit
    def kernel(x):
        print("tracing")
        t = time.time()
        if x > 0:
            return x
        return -x
    """)
    diags = lint_source(code, "mxnet_tpu/ops/extra.py")
    kinds = rules_of(diags)
    assert kinds == ["jit-purity"] * 3
    msgs = " | ".join(d.message for d in diags)
    assert "print()" in msgs and "wall-clock" in msgs and \
        "data-dependent" in msgs


def test_jit_purity_static_args_and_shape_branches_ok():
    code = src("""
    import jax
    from functools import partial

    @partial(jax.jit, static_argnames=("mode",))
    def kernel(x, mode, axis=0):
        if mode == "fast":      # static_argnames: fine
            return x
        if axis:                # defaulted param: static by contract
            return x.sum(axis)
        if x.ndim > 2:          # shape attr: static under trace
            return x.reshape(-1)
        if x is None:           # sentinel: fine
            return x
        return x
    """)
    assert lint_source(code, "mxnet_tpu/ops/extra.py") == []


def test_jit_purity_registered_op_and_env_read():
    code = src("""
    import os
    from .registry import register

    @register("myop")
    def _k(x):
        if os.environ.get("MX_DEBUG_FLAG"):
            return x
        return x + 1

    @register("dynop", no_jit=True)
    def _d(x):
        print(x)   # eager op: prints are legal
        return x
    """)
    diags = lint_source(code, "mxnet_tpu/ops/extra.py",
                        catalog={"MX_DEBUG_FLAG"})
    # the same read trips BOTH rules: ad-hoc env read (env-var-registry)
    # and trace-time env read (jit-purity)
    assert sorted(set(rules_of(diags))) == ["env-var-registry", "jit-purity"]
    jp = [d for d in diags if d.rule == "jit-purity"]
    assert "os.environ" in jp[0].message


def test_jit_purity_by_name_jit_call():
    code = src("""
    import jax
    import random

    def make(fn):
        def step(x):
            return x * random.random()
        return jax.jit(step)
    """)
    diags = lint_source(code, "mxnet_tpu/parallel/foo.py")
    assert rules_of(diags) == ["jit-purity"]
    assert "RNG" in diags[0].message


# ---------------------------------------------------------------------------
# wall-clock-in-fault-path
# ---------------------------------------------------------------------------

def test_wall_clock_positive_alias_and_from_import():
    code = src("""
    import time as _time
    from time import monotonic

    def retry_loop():
        deadline = _time.time() + 60
        while monotonic() < deadline:
            _time.sleep(0.2)
    """)
    diags = lint_source(code, "mxnet_tpu/kvstore/kvstore.py")
    assert rules_of(diags) == ["wall-clock-in-fault-path"] * 3
    assert "fault.now()" in diags[0].message
    assert "fault.sleep()" in diags[-1].message


def test_wall_clock_suppressed_and_clean_and_scoped():
    sup = src("""
    import time as _time

    class _RealClock:
        now = staticmethod(_time.monotonic)  # mxlint: disable=wall-clock-in-fault-path
    """)
    assert lint_source(sup, "mxnet_tpu/fault.py") == []
    clean = src("""
    from .. import fault as _fault

    def retry_loop():
        deadline = _fault.now() + 60
        _fault.sleep(0.2)
    """)
    assert lint_source(clean, "mxnet_tpu/kvstore/kvstore.py") == []
    # time.time is legal outside the fault-path files
    other = "import time\ndef f():\n    return time.time()\n"
    assert lint_source(other, "mxnet_tpu/callback.py") == []


# ---------------------------------------------------------------------------
# env-var-registry
# ---------------------------------------------------------------------------

def test_env_registry_adhoc_read_flagged():
    code = src("""
    import os

    def f():
        a = os.environ.get("MX_SOME_FLAG")
        b = os.getenv("MX_OTHER")
        c = os.environ["MX_THIRD"]
        return a, b, c
    """)
    diags = lint_source(code, "mxnet_tpu/foo.py",
                        catalog={"MX_SOME_FLAG", "MX_OTHER", "MX_THIRD"})
    assert rules_of(diags) == ["env-var-registry"] * 3
    assert all("get_env" in d.message for d in diags)


def test_env_registry_submodule_import_does_not_blind():
    # `import os.path` binds the name `os`; the alias map must not remap
    # it to "os.path" or every os.environ detector goes blind
    code = src("""
    import os.path

    def f():
        return os.environ.get("MX_SOME_FLAG")
    """)
    diags = lint_source(code, "mxnet_tpu/foo.py", catalog={"MX_SOME_FLAG"})
    assert rules_of(diags) == ["env-var-registry"]


def test_env_registry_unregistered_and_clean_and_writes_ok():
    code = src("""
    from .base import get_env

    def f():
        return get_env("MX_NOT_IN_CATALOG")
    """)
    diags = lint_source(code, "mxnet_tpu/foo.py", catalog={"MX_KNOWN"})
    assert rules_of(diags) == ["env-var-registry"]
    assert "ENV_CATALOG" in diags[0].message
    clean = src("""
    import os
    from .base import get_env

    def f():
        os.environ["MX_FORCE_CPU"] = "1"   # writes are fine
        return get_env("MX_KNOWN"), os.environ.get("PATH")
    """)
    assert lint_source(clean, "mxnet_tpu/foo.py", catalog={"MX_KNOWN",
                                                           "MX_FORCE_CPU"}) \
        == []
    # base.py itself is the accessor: exempt
    accessor = 'import os\nv = os.environ.get("MX_FORCE_CPU")\n'
    assert lint_source(accessor, "mxnet_tpu/base.py") == []


# ---------------------------------------------------------------------------
# donation-after-use
# ---------------------------------------------------------------------------

def test_donation_after_use_positive():
    code = src("""
    import jax

    def f(g, a, b):
        fn = jax.jit(g, donate_argnums=(0,))
        out = fn(a, b)
        return a + out
    """)
    diags = lint_source(code, "mxnet_tpu/parallel/foo.py")
    assert rules_of(diags) == ["donation-after-use"]
    assert "'a'" in diags[0].message


def test_donation_after_use_rebind_and_nondonated_ok():
    code = src("""
    import jax

    def f(g, a, b):
        fn = jax.jit(g, donate_argnums=(0,))
        a = fn(a, b)      # rebound: old buffer unreachable
        return a + b      # b was not donated
    """)
    assert lint_source(code, "mxnet_tpu/parallel/foo.py") == []


def test_donation_after_use_self_attr_and_conditional_donate():
    code = src("""
    import jax

    class Step:
        def __init__(self, fn, donate):
            self._step = jax.jit(fn, donate_argnums=(0, 1) if donate else ())

        def run(self, params, opt, batch):
            new_p, new_o = self._step(params, opt, batch)
            self.stale = params.copy()
            return new_p, new_o
    """)
    diags = lint_source(code, "mxnet_tpu/parallel/foo.py")
    assert rules_of(diags) == ["donation-after-use"]
    assert "'params'" in diags[0].message


# ---------------------------------------------------------------------------
# concurrency rules (ISSUE 6): whole-program pass fixtures
# ---------------------------------------------------------------------------

CONC = "mxnet_tpu/foo.py"

SHARED_HIT = src("""
import threading

class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def bump(self):
        self._n += 1

    def _run(self):
        while True:
            x = self._n
""")


def test_unguarded_shared_write_hit():
    diags = lint_source(SHARED_HIT, CONC)
    assert rules_of(diags) == ["unguarded-shared-write"]
    d = diags[0]
    assert d.line == 11 and "Pump._n" in d.message
    # both thread roots named, and the peer read site carried separately
    assert "thread:Pump._run" in d.threads and "main" in d.threads
    assert d.peer == "mxnet_tpu/foo.py:15"


def test_unguarded_shared_write_suppressed_baselined_clean(tmp_path):
    sup = SHARED_HIT.replace(
        "self._n += 1",
        "self._n += 1  # mxlint: disable=unguarded-shared-write")
    assert lint_source(sup, CONC) == []
    bl = tmp_path / "bl.json"
    write_baseline(str(bl), lint_source(SHARED_HIT, CONC))
    new, old, stale = apply_baseline(lint_source(SHARED_HIT, CONC),
                                     load_baseline(str(bl)))
    assert new == [] and len(old) == 1 and stale == []
    clean = SHARED_HIT.replace(
        "        self._n += 1",
        "        with self._lock:\n            self._n += 1").replace(
        "            x = self._n",
        "            with self._lock:\n                x = self._n")
    assert lint_source(clean, CONC) == []


def test_unguarded_shared_write_init_is_prepublication():
    # writes in __init__ (and private helpers only it calls) happen
    # before the thread starts: never a conflict
    code = src("""
    import threading

    class Pump:
        def __init__(self):
            self._setup()
            threading.Thread(target=self._run, daemon=True).start()

        def _setup(self):
            self._n = 0

        def _run(self):
            return self._n
    """)
    assert lint_source(code, CONC) == []


def test_unguarded_shared_write_handler_multi_instance():
    # one socketserver handler root is MANY threads: a shared object it
    # writes without a lock conflicts with itself
    code = src("""
    import socketserver

    class Store:
        def note(self, k):
            self._seen[k] = 1

    store = Store()

    class Handler(socketserver.BaseRequestHandler):
        def handle(self):
            store.note(self.request)
    """)
    diags = lint_source(code, CONC)
    assert rules_of(diags) == ["unguarded-shared-write"]
    assert "handler:Handler" in diags[0].threads


def test_inconsistent_guard_quad(tmp_path):
    code = src("""
    import threading

    class Pump:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0
            threading.Thread(target=self._run, daemon=True).start()

        def bump(self):
            with self._lock:
                self._n += 1

        def _run(self):
            return self._n
    """)
    diags = lint_source(code, CONC)
    assert rules_of(diags) == ["inconsistent-guard"]
    # anchored on the UNGUARDED side, naming the guarded peer's lock
    assert diags[0].line == 14
    assert "Pump._lock" in diags[0].message
    sup = code.replace("return self._n",
                       "return self._n  # mxlint: disable=inconsistent-guard")
    assert lint_source(sup, CONC) == []
    bl = tmp_path / "bl.json"
    write_baseline(str(bl), diags)
    new, old, _ = apply_baseline(lint_source(code, CONC),
                                 load_baseline(str(bl)))
    assert new == [] and len(old) == 1
    clean = code.replace("return self._n",
                         "with self._lock:\n            return self._n")
    assert lint_source(clean, CONC) == []


def test_guard_propagates_through_private_callee():
    # a helper called ONLY with the lock held inherits the guard — the
    # _try_release_barrier pattern must not false-positive
    code = src("""
    import threading

    class Pump:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0
            threading.Thread(target=self._run, daemon=True).start()

        def bump(self):
            with self._lock:
                self._bump_locked()

        def _bump_locked(self):
            self._n += 1

        def _run(self):
            with self._lock:
                return self._n
    """)
    assert lint_source(code, CONC) == []


def test_lock_order_cycle_quad(tmp_path):
    code = src("""
    import threading

    class AB:
        def __init__(self):
            self._a_lock = threading.Lock()
            self._b_lock = threading.Lock()
            threading.Thread(target=self._w, daemon=True).start()

        def fwd(self):
            with self._a_lock:
                with self._b_lock:
                    pass

        def _w(self):
            with self._b_lock:
                with self._a_lock:
                    pass
    """)
    diags = lint_source(code, CONC)
    assert rules_of(diags) == ["lock-order-cycle"]
    assert "AB._a_lock" in diags[0].message and \
        "AB._b_lock" in diags[0].message
    anchor = diags[0].line
    lines = code.splitlines()
    lines[anchor - 1] += "  # mxlint: disable=lock-order-cycle"
    assert lint_source("\n".join(lines) + "\n", CONC) == []
    bl = tmp_path / "bl.json"
    write_baseline(str(bl), diags)
    new, old, _ = apply_baseline(lint_source(code, CONC),
                                 load_baseline(str(bl)))
    assert new == [] and len(old) == 1
    clean = code.replace(
        "with self._b_lock:\n            with self._a_lock:",
        "with self._a_lock:\n            with self._b_lock:")
    assert lint_source(clean, CONC) == []


def test_blocking_wait_unbounded_quad(tmp_path):
    code = src("""
    import threading

    class W:
        def __init__(self):
            self._ev = threading.Event()
            self._lk = threading.Lock()

        def park(self):
            self._ev.wait()

        def grab(self):
            self._lk.acquire()

        def park_ok(self):
            self._ev.wait(1.0)
            self._lk.acquire(timeout=2.0)
    """)
    path = "mxnet_tpu/kvstore/foo.py"
    diags = lint_source(code, path)
    assert rules_of(diags) == ["blocking-wait-unbounded"] * 2
    assert "Event.wait" in diags[0].message
    assert "acquire" in diags[1].message
    # out of the fault/kvstore/health/launch scope: not checked
    assert lint_source(code, "mxnet_tpu/callback.py") == []
    sup = code.replace(
        "self._ev.wait()",
        "self._ev.wait()  # mxlint: disable=blocking-wait-unbounded"
    ).replace(
        "self._lk.acquire()",
        "self._lk.acquire()  # mxlint: disable=blocking-wait-unbounded")
    assert lint_source(sup, path) == []
    bl = tmp_path / "bl.json"
    write_baseline(str(bl), diags)
    new, old, _ = apply_baseline(lint_source(code, path),
                                 load_baseline(str(bl)))
    assert new == [] and len(old) == 2


def test_thread_leak_quad(tmp_path):
    hit = src("""
    import threading

    def work():
        pass

    def spawn():
        t = threading.Thread(target=work)
        t.start()
    """)
    diags = lint_source(hit, CONC)
    assert rules_of(diags) == ["thread-leak"]
    sup = hit.replace(
        "t = threading.Thread(target=work)",
        "t = threading.Thread(target=work)  # mxlint: disable=thread-leak")
    assert lint_source(sup, CONC) == []
    bl = tmp_path / "bl.json"
    write_baseline(str(bl), diags)
    new, old, _ = apply_baseline(lint_source(hit, CONC),
                                 load_baseline(str(bl)))
    assert new == [] and len(old) == 1
    # clean: daemon=True, an (even bounded) join, or a stop-event loop
    assert lint_source(hit.replace("target=work", "target=work, daemon=True"),
                       CONC) == []
    joined = hit + "\n    t.join(timeout=5)\n"
    assert lint_source(joined, CONC) == []
    stop_ev = src("""
    import threading

    _stop = threading.Event()

    def work():
        while not _stop.wait(0.5):
            pass

    def spawn():
        threading.Thread(target=work).start()
    """)
    assert lint_source(stop_ev, CONC) == []


def test_grad_hook_callback_is_thread_root():
    # `X._grad_hook = partial(self._cb, ...)` marks _cb as an overlap
    # callback root (fires mid-backward) — unguarded state it shares
    # with the step path is flagged
    code = src("""
    import functools

    class Trainer:
        def arm(self, grads):
            self._sess = object()
            for i, g in enumerate(grads):
                g._grad_hook = functools.partial(self._on_ready, i)

        def _on_ready(self, i):
            s = self._sess
            return s
    """)
    diags = lint_source(code, "mxnet_tpu/gluon/trainer.py")
    assert "unguarded-shared-write" in rules_of(diags)
    assert any("hook:Trainer._on_ready" in d.threads for d in diags)


def test_pool_submit_target_is_thread_root():
    code = src("""
    from concurrent.futures import ThreadPoolExecutor

    class Loader:
        def __init__(self):
            self._pool = ThreadPoolExecutor(4)
            self._epoch = 0

        def reset(self):
            self._epoch += 1

        def fetch(self, keys):
            return list(self._pool.map(self._load, keys))

        def _load(self, k):
            return (k, self._epoch)
    """)
    diags = lint_source(code, CONC)
    assert rules_of(diags) == ["unguarded-shared-write"]
    assert any("pool:Loader._load" in d.threads for d in diags)


def test_lock_order_same_named_locals_do_not_collide():
    # same-named function-local locks in two files are DIFFERENT locks:
    # their tokens must not merge into one graph node and fabricate a
    # cross-file cycle
    a = src("""
    import threading
    my_lock = threading.Lock()
    my_sem = threading.Semaphore()

    def f():
        with my_lock:
            with my_sem:
                pass
    """)
    b = src("""
    import threading
    my_lock = threading.Lock()
    my_sem = threading.Semaphore()

    def g():
        with my_sem:
            with my_lock:
                pass
    """)
    assert lint_sources({"mxnet_tpu/x.py": a, "mxnet_tpu/y.py": b}) == []


def test_blocking_wait_per_method_timeout_semantics():
    # a positional arg is not always a timeout: wait_for's first arg is
    # the predicate, and acquire(blocking=True) is explicitly unbounded
    code = src("""
    import threading

    class W:
        def __init__(self):
            self._cv = threading.Condition()
            self._lk = threading.Lock()

        def bad(self):
            with self._cv:
                self._cv.wait_for(lambda: True)
            self._lk.acquire(blocking=True)

        def ok(self):
            with self._cv:
                self._cv.wait_for(lambda: True, 5.0)
            self._lk.acquire(False)
            self._lk.acquire(True, 5.0)
            self._lk.acquire(timeout=1.0)
    """)
    diags = lint_source(code, "mxnet_tpu/kvstore/foo.py")
    assert rules_of(diags) == ["blocking-wait-unbounded"] * 2
    assert [d.line for d in diags] == [10, 11]


def test_thread_leak_join_matching_is_file_scoped():
    # an unrelated `t.join()` in ANOTHER file must not silence a leak
    # bound to a bare local name; a class-qualified binding still
    # matches project-wide
    leak = src("""
    import threading

    def work():
        pass

    def spawn():
        t = threading.Thread(target=work)
        t.start()
    """)
    other = src("""
    class Other:
        def stop(self):
            t = self.worker
            t.join()
    """)
    out = lint_sources({"mxnet_tpu/m.py": leak, "mxnet_tpu/n.py": other})
    assert rules_of(out) == ["thread-leak"]


# ---------------------------------------------------------------------------
# cross-file anchoring (the two-site satellite): write site anchors the
# diagnostic, the peer read in ANOTHER file rides in message/peer only —
# so suppression and the baseline fingerprint stay stable under peer drift
# ---------------------------------------------------------------------------

XFILE_A = src("""
class Base:
    def set(self, v):
        self._n = v
""")

XFILE_B = src("""
import threading
from .a import Base

class Worker(Base):
    def __init__(self):
        threading.Thread(target=self._run, daemon=True).start()

    def _run(self):
        return self._n
""")


def test_cross_file_conflict_anchors_on_write_site():
    diags = lint_sources({"mxnet_tpu/a.py": XFILE_A,
                          "mxnet_tpu/b.py": XFILE_B})
    assert rules_of(diags) == ["unguarded-shared-write"]
    d = diags[0]
    assert d.path == "mxnet_tpu/a.py" and d.line == 3
    assert d.peer == "mxnet_tpu/b.py:9"
    assert "mxnet_tpu/b.py:9" in d.message


def test_cross_file_fingerprint_survives_peer_drift(tmp_path):
    diags = lint_sources({"mxnet_tpu/a.py": XFILE_A,
                          "mxnet_tpu/b.py": XFILE_B})
    # shift the PEER file by 5 lines: fingerprint (and thus a baseline
    # entry / suppression) must not change, only the peer pointer
    shifted = lint_sources({"mxnet_tpu/a.py": XFILE_A,
                            "mxnet_tpu/b.py": "\n" * 5 + XFILE_B})
    assert diags[0].fingerprint() == shifted[0].fingerprint()
    assert diags[0].fingerprint_id() == shifted[0].fingerprint_id()
    assert shifted[0].peer == "mxnet_tpu/b.py:14"
    bl = tmp_path / "bl.json"
    write_baseline(str(bl), diags)
    new, old, stale = apply_baseline(shifted, load_baseline(str(bl)))
    assert new == [] and len(old) == 1 and stale == []


def test_cross_file_suppression_on_write_site():
    sup_a = XFILE_A.replace(
        "self._n = v",
        "self._n = v  # mxlint: disable=unguarded-shared-write")
    assert lint_sources({"mxnet_tpu/a.py": sup_a,
                         "mxnet_tpu/b.py": XFILE_B}) == []


# ---------------------------------------------------------------------------
# baseline mechanics
# ---------------------------------------------------------------------------

def test_baseline_roundtrip(tmp_path):
    code = src("""
    class Trainer:
        def _update(self):
            return self.g.asnumpy()
    """)
    diags = lint_source(code, HOT_PATH)
    assert len(diags) == 1
    bl = tmp_path / "baseline.json"
    write_baseline(str(bl), diags)
    new, old, stale = apply_baseline(lint_source(code, HOT_PATH),
                                     load_baseline(str(bl)))
    assert new == [] and len(old) == 1 and stale == []
    # a SECOND violation with a different line text is NOT absorbed
    code2 = code + "\n    def update(self):\n        return self.w.asnumpy()\n"
    new2, old2, _ = apply_baseline(lint_source(code2, HOT_PATH),
                                   load_baseline(str(bl)))
    assert len(new2) == 1 and len(old2) == 1
    # fixing the violation leaves the entry stale (reported, not fatal)
    fixed = "class Trainer:\n    def _update(self):\n        return 0\n"
    new3, old3, stale3 = apply_baseline(lint_source(fixed, HOT_PATH),
                                        load_baseline(str(bl)))
    assert new3 == [] and old3 == [] and len(stale3) == 1


def test_parse_error_is_a_diagnostic():
    diags = lint_source("def broken(:\n", "mxnet_tpu/foo.py")
    assert rules_of(diags) == ["mxlint-parse"]


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------

def _fake_repo(tmp_path, bad=True):
    pkg = tmp_path / "mxnet_tpu"
    (pkg / "kvstore").mkdir(parents=True)
    (pkg / "base.py").write_text("ENV_CATALOG = {'MX_KNOWN': ('', 'd')}\n")
    body = "import time as _time\n\ndef retry():\n    return _time.time()\n" \
        if bad else "def retry():\n    return 0\n"
    (pkg / "kvstore" / "mod.py").write_text(body)
    return pkg


def _run_cli(args, cwd=REPO):
    return subprocess.run([sys.executable, "-m", "tools.mxlint"] + args,
                          cwd=cwd, capture_output=True, text=True)


def test_cli_exit_codes_and_json(tmp_path):
    pkg = _fake_repo(tmp_path, bad=True)
    r = _run_cli([str(pkg), "--no-baseline", "--format", "json"])
    assert r.returncode == 1, r.stderr
    payload = json.loads(r.stdout)
    assert [v["rule"] for v in payload["violations"]] == \
        ["wall-clock-in-fault-path"]
    assert payload["violations"][0]["path"] == "mxnet_tpu/kvstore/mod.py"

    clean = _fake_repo(tmp_path / "c", bad=False)
    r = _run_cli([str(clean), "--no-baseline"])
    assert r.returncode == 0, r.stdout + r.stderr

    assert _run_cli(["/nonexistent/path"]).returncode == 2
    assert _run_cli([str(pkg), "--select", "no-such-rule"]).returncode == 2
    assert _run_cli(["--list-rules"]).returncode == 0

    # a typo'd --baseline is a usage error (2), NOT "new violations" (1)
    r = _run_cli([str(pkg), "--baseline", str(tmp_path / "no_such.json")])
    assert r.returncode == 2, r.stdout + r.stderr
    assert "cannot read baseline" in r.stderr
    garbled = tmp_path / "garbled.json"
    garbled.write_text("{not json")
    r = _run_cli([str(pkg), "--baseline", str(garbled)])
    assert r.returncode == 2, r.stdout + r.stderr


def test_cli_write_baseline_roundtrip(tmp_path):
    pkg = _fake_repo(tmp_path, bad=True)
    bl = tmp_path / "bl.json"
    r = _run_cli([str(pkg), "--baseline", str(bl), "--write-baseline"])
    assert r.returncode == 0, r.stderr
    r = _run_cli([str(pkg), "--baseline", str(bl)])
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_write_baseline_narrowed_scan_preserves_entries(tmp_path):
    # re-baselining one FILE must not erase grandfathered entries for the
    # rest of the tree; re-baselining with --select must refuse outright
    pkg = _fake_repo(tmp_path, bad=True)
    clean_file = pkg / "kvstore" / "other.py"
    clean_file.write_text("def ok():\n    return 0\n")
    bl = tmp_path / "bl.json"
    r = _run_cli([str(pkg), "--baseline", str(bl), "--write-baseline"])
    assert r.returncode == 0, r.stderr
    full = json.loads(bl.read_text())["entries"]
    assert len(full) == 1     # mod.py's wall-clock hit

    r = _run_cli([str(clean_file), "--baseline", str(bl),
                  "--write-baseline"])
    assert r.returncode == 0, r.stderr
    assert "preserved" in r.stdout
    assert json.loads(bl.read_text())["entries"] == full

    r = _run_cli([str(pkg), "--baseline", str(bl), "--write-baseline",
                  "--select", "jit-purity"])
    assert r.returncode == 2, r.stdout + r.stderr
    assert json.loads(bl.read_text())["entries"] == full


def test_cli_jobs_parallel_matches_serial(tmp_path):
    # --jobs N must produce byte-identical findings to the serial scan
    pkg = _fake_repo(tmp_path, bad=True)
    (pkg / "kvstore" / "waits.py").write_text(src("""
    import threading

    class W:
        def __init__(self):
            self._ev = threading.Event()

        def park(self):
            self._ev.wait()
    """))
    serial = _run_cli([str(pkg), "--no-baseline", "--format", "json"])
    par = _run_cli([str(pkg), "--no-baseline", "--format", "json",
                    "--jobs", "4"])
    assert serial.returncode == par.returncode == 1
    assert json.loads(serial.stdout)["violations"] == \
        json.loads(par.stdout)["violations"]


def test_cli_json_schema_stable(tmp_path):
    pkg = _fake_repo(tmp_path, bad=True)
    r = _run_cli([str(pkg), "--no-baseline", "--format", "json"])
    payload = json.loads(r.stdout)
    assert payload["schema"] == 2
    assert set(payload) >= {"schema", "violations", "baselined",
                            "stale_baseline", "lock_graph"}
    v = payload["violations"][0]
    # the machine contract: rule id, drift-stable fingerprint,
    # file:line, thread roots involved
    assert set(v) >= {"rule", "path", "line", "col", "message",
                      "snippet", "fingerprint", "threads"}
    assert isinstance(v["fingerprint"], str) and len(v["fingerprint"]) == 16
    assert payload["lock_graph"]["acyclic"] in (True, False)


def test_cli_select_accepts_concurrency_rules(tmp_path):
    pkg = _fake_repo(tmp_path, bad=True)
    # selecting ONLY a concurrency rule: the wall-clock hit disappears
    r = _run_cli([str(pkg), "--no-baseline",
                  "--select", "unguarded-shared-write,lock-order-cycle"])
    assert r.returncode == 0, r.stdout + r.stderr
    r = _run_cli(["--list-rules"])
    for rid in ("unguarded-shared-write", "inconsistent-guard",
                "lock-order-cycle", "blocking-wait-unbounded",
                "thread-leak"):
        assert rid in r.stdout


def test_write_baseline_preserves_why(tmp_path):
    # the baseline-justification policy: regenerating the baseline must
    # keep each surviving entry's reviewer-written `why`
    pkg = _fake_repo(tmp_path, bad=True)
    bl = tmp_path / "bl.json"
    r = _run_cli([str(pkg), "--baseline", str(bl), "--write-baseline"])
    assert r.returncode == 0, r.stderr
    data = json.loads(bl.read_text())
    assert len(data["entries"]) == 1
    data["entries"][0]["why"] = "virtual-clock exempt: test fixture"
    bl.write_text(json.dumps(data))
    r = _run_cli([str(pkg), "--baseline", str(bl), "--write-baseline"])
    assert r.returncode == 0, r.stderr
    entries = json.loads(bl.read_text())["entries"]
    assert entries[0]["why"] == "virtual-clock exempt: test fixture"
    assert load_baseline_whys(str(bl))


# ---------------------------------------------------------------------------
# env scanner + gen_env_docs --check
# ---------------------------------------------------------------------------

def test_collect_env_reads(tmp_path):
    f = tmp_path / "m.py"
    f.write_text(src("""
    import os
    from .base import get_env

    a = os.environ.get("MX_ALPHA")
    b = get_env("MXNET_BETA")
    c = os.environ["MX_GAMMA"]
    d = os.environ.get("HOME")        # not MX_*: ignored
    """))
    found = collect_env_reads([str(tmp_path)])
    assert set(found) == {"MX_ALPHA", "MXNET_BETA", "MX_GAMMA"}


@pytest.mark.slow
def test_gen_env_docs_check_passes_on_shipped_tree():
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "gen_env_docs.py"),
                        "--check"], capture_output=True, text=True,
                       cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr


# ---------------------------------------------------------------------------
# the tier-1 gate: shipped tree is clean; reinjected violations trip
# ---------------------------------------------------------------------------

_TREE_SCAN = []     # memo: the full-tree scan feeds three gate tests


def _scan_tree():
    if not _TREE_SCAN:
        _TREE_SCAN.append(lint_paths(RUNTIME_PATHS, root=REPO,
                                     return_project=True))
    return _TREE_SCAN[0]


def _lint_tree():
    diags, _project = _scan_tree()
    return apply_baseline(diags, load_baseline(BASELINE))


def test_shipped_tree_lints_clean():
    # the whole threaded runtime (mxnet_tpu + the supervisor), ALL rules
    # including the concurrency pass
    new, old, stale = _lint_tree()
    assert new == [], "\n".join(map(repr, new))
    assert stale == [], ("baseline entries no longer match the tree — "
                         "run `python -m tools.mxlint --write-baseline`"
                         ": %s" % (stale,))


def test_shipped_lock_graph_is_acyclic():
    # the acceptance criterion verbatim: the runtime's static
    # lock-acquisition graph must stay acyclic, and must actually SEE
    # the lock hierarchy the docs promise
    _diags, project = _scan_tree()
    cycles = project.lock_cycles()
    assert cycles == [], cycles
    edges = set(project.lock_graph())
    assert ("KVStoreServer._barrier_cv",
            "KVStoreServer._seen_lock") in edges
    assert ("KVStoreServer._snapshot_lock",
            "KVStoreServer._global_lock") in edges
    assert ("KVStoreDistAsync._lock",
            "KVStoreDistAsync._seq_lock") in edges


def test_shipped_thread_roots_discovered():
    # the pass must actually SEE the runtime's thread landscape: the
    # kvstore heartbeat, the socketserver handler, the watchdog, and
    # the overlap grad-hook callback
    _diags, project = _scan_tree()
    roots = {r.display for r in project.roots}
    assert any("handler:Handler" in r for r in roots), roots
    assert any("Watchdog._run" in r for r in roots), roots
    assert any("_start_heartbeat" in r and r.startswith("thread:")
               for r in roots), roots
    assert any(r.startswith("hook:") and "_on_grad_ready" in r
               for r in roots), roots
    # ISSUE 13: the async input pipeline's producer thread
    assert "thread:DevicePrefetcher._run" in roots, roots


def test_reinjected_asnumpy_in_trainer_update_trips():
    p = os.path.join(REPO, "mxnet_tpu", "gluon", "trainer.py")
    with open(p) as f:
        code = f.read()
    anchor = 'with _telemetry.phase("optimizer_apply"):'
    assert anchor in code, "Trainer._update moved; update this test"
    bad = code.replace(
        anchor,
        anchor + "\n            _dbg = [g.asnumpy() for g in gs]")
    diags = lint_source(bad, "mxnet_tpu/gluon/trainer.py")
    assert "host-sync-in-hot-path" in rules_of(diags)
    # and it is NOT absorbed by the shipped baseline
    new, _, _ = apply_baseline(diags, load_baseline(BASELINE))
    assert "host-sync-in-hot-path" in rules_of(new)


def test_reinjected_asnumpy_in_compiled_step_body_trips():
    """ISSUE 7: the whole-step compiled trace is a jit-purity target — a
    float(asnumpy()) reintroduced INSIDE the traced step body must trip
    the linter (a host sync under trace either crashes on tracers or
    bakes a constant in; either way the single-program contract dies)."""
    p = os.path.join(REPO, "mxnet_tpu", "step.py")
    with open(p) as f:
        code = f.read()
    anchor = ("            carry = (t_vals, f_vals, opt_states, w32s, "
              "residuals, mstate)")
    assert anchor in code, "_traced_step_window moved; update this test"
    bad = code.replace(
        anchor,
        anchor + "\n            _dbg = float(t_vals[0].asnumpy())", 1)
    diags = lint_source(bad, "mxnet_tpu/step.py")
    assert "jit-purity" in rules_of(diags)
    new, _, _ = apply_baseline(diags, load_baseline(BASELINE))
    assert "jit-purity" in rules_of(new)


def test_reinjected_asnumpy_in_compiled_step_host_path_trips():
    """The compiled lane's HOST side (CompiledStep._run and friends) is a
    hot-path root: a per-dispatch sync there stalls the one-program
    pipeline exactly like a per-op sync used to."""
    p = os.path.join(REPO, "mxnet_tpu", "step.py")
    with open(p) as f:
        code = f.read()
    anchor = "        state = self._gather_state(plan)"
    assert anchor in code, "CompiledStep._run moved; update this test"
    bad = code.replace(
        anchor, anchor + "\n        _dbg = state[0][0].asnumpy()", 1)
    diags = lint_source(bad, "mxnet_tpu/step.py")
    assert "host-sync-in-hot-path" in rules_of(diags)
    new, _, _ = apply_baseline(diags, load_baseline(BASELINE))
    assert "host-sync-in-hot-path" in rules_of(new)


def test_compiled_step_is_hot_path_root():
    """The rule table names the compiled-step entry points (regression
    guard: removing the root entry would silently drop the coverage the
    two reinjection tests above rely on)."""
    from tools.mxlint.rules import HOT_PATH_ROOTS
    roots = dict(HOT_PATH_ROOTS)
    assert "mxnet_tpu/step.py" in roots
    assert any("CompiledStep.step" in q for q in roots["mxnet_tpu/step.py"])
    assert any("CompiledStep._run" in q for q in roots["mxnet_tpu/step.py"])


def test_reinjected_host_sync_in_serve_batcher_trips():
    """ISSUE 9: the serving batcher's dispatch loop is a hot-path root —
    a blocking ``float(...asnumpy())`` reintroduced between dequeue and
    dispatch (debug peeking at the batch output) serializes the whole
    fleet's latency and must trip the rule."""
    p = os.path.join(REPO, "mxnet_tpu", "serve", "batcher.py")
    with open(p) as f:
        code = f.read()
    anchor = "                outs = sv.dispatch(bucket, padded)"
    assert anchor in code, "Batcher._dispatch moved; update this test"
    bad = code.replace(
        anchor,
        anchor + "\n                _dbg = float(outs[0].asnumpy()[0])", 1)
    diags = lint_source(bad, "mxnet_tpu/serve/batcher.py")
    assert "host-sync-in-hot-path" in rules_of(diags)
    new, _, _ = apply_baseline(diags, load_baseline(BASELINE))
    assert "host-sync-in-hot-path" in rules_of(new)


def test_serve_batcher_is_hot_path_root():
    """Regression guard for the root-table entries the reinjection test
    above relies on (batcher loop + the servable dispatch side of the
    cross-file hot edge)."""
    from tools.mxlint.rules import HOT_PATH_ROOTS
    roots = dict(HOT_PATH_ROOTS)
    assert "mxnet_tpu/serve/batcher.py" in roots
    assert any("Batcher._dispatch" in q
               for q in roots["mxnet_tpu/serve/batcher.py"])
    assert any("Batcher._collect" in q
               for q in roots["mxnet_tpu/serve/batcher.py"])
    assert "mxnet_tpu/serve/servable.py" in roots
    assert any("Servable.dispatch" in q
               for q in roots["mxnet_tpu/serve/servable.py"])


def test_serve_batcher_thread_is_a_discovered_root():
    """The concurrency pass must see the batcher's dispatch loop as a
    thread root (its shared state is then race-checked) — and the
    serving socket handler as a multi-instance root, like the kvstore
    server's.  Reuses the memoized full-tree scan."""
    _diags, proj = _scan_tree()
    displays = {r.display for r in proj.roots}
    assert "thread:Batcher._loop" in displays
    assert any("mxnet_tpu/serve/server.py" in e
               for r in proj.roots for e in r.entries
               if r.kind == "handler")


def test_reinjected_host_sync_in_decode_pump_trips():
    """ISSUE 15: the decode pump is a hot-path root — a blocking host
    read reintroduced between decode dispatches (debug peeking at the
    step's emitted tokens) stalls EVERY active generation's token
    cadence; the device→host read belongs only to the harvester
    thread."""
    p = os.path.join(REPO, "mxnet_tpu", "serve", "decode.py")
    with open(p) as f:
        code = f.read()
    anchor = "            out = self._sv.dispatch_step(ids)"
    assert anchor in code, "DecodeBatcher._step moved; update this test"
    bad = code.replace(
        anchor,
        anchor + "\n            _dbg = float(out.asnumpy()[0])", 1)
    diags = lint_source(bad, "mxnet_tpu/serve/decode.py")
    assert "host-sync-in-hot-path" in rules_of(diags)
    new, _, _ = apply_baseline(diags, load_baseline(BASELINE))
    assert "host-sync-in-hot-path" in rules_of(new)


def test_decode_pump_is_hot_path_root():
    """Root-table regression guard for the decode engine (ISSUE 15):
    the pump loop, the slot allocator and the servable dispatch path
    must stay rooted so the reinjection test above keeps meaning
    something."""
    from tools.mxlint.rules import HOT_PATH_ROOTS
    roots = dict(HOT_PATH_ROOTS)
    assert "mxnet_tpu/serve/decode.py" in roots
    entries = roots["mxnet_tpu/serve/decode.py"]
    for qual in ("DecodeBatcher._tick", "DecodeBatcher._admit",
                 "DecodeBatcher._step",
                 "DecodeServable.dispatch_step"):
        assert any(qual in q for q in entries), (qual, entries)
    # the harvester is deliberately NOT rooted: it is the one place the
    # device→host token read is allowed to live
    assert not any("_harvest" in q for q in entries), entries


def test_decode_pump_threads_are_discovered_roots():
    """The concurrency pass must see BOTH decode threads — the dispatch
    pump and the token harvester — as thread roots so their shared
    state is race-checked.  Reuses the memoized full-tree scan."""
    _diags, proj = _scan_tree()
    displays = {r.display for r in proj.roots}
    assert "thread:DecodeBatcher._loop" in displays
    assert "thread:DecodeBatcher._harvest_loop" in displays


def test_reinjected_host_sync_in_page_allocator_trips():
    """ISSUE 18: the page allocator runs inside the pump's admission
    path every tick — a device sync smuggled into ``alloc()`` (debug
    peeking at the heap while handing out pages) stalls admission AND
    decode, since the pump alternates both on one thread."""
    p = os.path.join(REPO, "mxnet_tpu", "serve", "paging.py")
    with open(p) as f:
        code = f.read()
    anchor = "                self._refs[page] = 1"
    assert anchor in code, "PageAllocator.alloc moved; update this test"
    bad = code.replace(
        anchor,
        anchor + "\n                _dbg = float(heap.asnumpy()[page])",
        1)
    diags = lint_source(bad, "mxnet_tpu/serve/paging.py")
    assert "host-sync-in-hot-path" in rules_of(diags)
    new, _, _ = apply_baseline(diags, load_baseline(BASELINE))
    assert "host-sync-in-hot-path" in rules_of(new)


def test_reinjected_host_sync_in_chunk_scheduler_trips():
    """The chunked-prefill scheduler is a hot-path root: a blocking
    read of the chunk's emitted token inside the pump (instead of the
    harvester) re-serializes every interleaved generation."""
    p = os.path.join(REPO, "mxnet_tpu", "serve", "decode.py")
    with open(p) as f:
        code = f.read()
    anchor = "        self._c_chunks.inc()"
    assert anchor in code, \
        "PagedDecodeBatcher._dispatch_chunk_for moved; update this test"
    bad = code.replace(
        anchor, anchor + "\n        _dbg = float(t0.asnumpy())", 1)
    diags = lint_source(bad, "mxnet_tpu/serve/decode.py")
    assert "host-sync-in-hot-path" in rules_of(diags)
    new, _, _ = apply_baseline(diags, load_baseline(BASELINE))
    assert "host-sync-in-hot-path" in rules_of(new)


def test_paged_engine_is_hot_path_root():
    """Root-table regression guard for the paged engine (ISSUE 18):
    the chunk scheduler, the page planner, the allocator and the
    prefix-hash helpers must stay rooted so the reinjection tests
    above keep meaning something."""
    from tools.mxlint.rules import HOT_PATH_ROOTS
    roots = dict(HOT_PATH_ROOTS)
    entries = roots["mxnet_tpu/serve/decode.py"]
    for qual in ("PagedDecodeBatcher._tick", "PagedDecodeBatcher._plan",
                 "PagedDecodeBatcher._dispatch_chunk_for",
                 "PagedDecodeServable.dispatch_chunk",
                 "PagedDecodeServable.dispatch_step"):
        assert any(qual in q for q in entries), (qual, entries)
    assert "mxnet_tpu/serve/paging.py" in roots
    palloc = roots["mxnet_tpu/serve/paging.py"]
    for qual in ("PageAllocator.alloc", "PageAllocator.release",
                 "chain_hash", "page_hashes"):
        assert any(qual in q for q in palloc), (qual, palloc)


def test_reinjected_wall_clock_in_kvstore_retry_trips():
    p = os.path.join(REPO, "mxnet_tpu", "kvstore", "kvstore.py")
    with open(p) as f:
        code = f.read()
    anchor = "if deadline.expired():"
    assert anchor in code, "connect-retry loop moved; update this test"
    bad = code.replace(
        anchor,
        "import time\n                    "
        "if time.time() > _connect_t0 + 60:", 1)
    diags = lint_source(bad, "mxnet_tpu/kvstore/kvstore.py")
    assert "wall-clock-in-fault-path" in rules_of(diags)
    new, _, _ = apply_baseline(diags, load_baseline(BASELINE))
    assert "wall-clock-in-fault-path" in rules_of(new)


def test_reinjected_unguarded_write_in_server_trips():
    # acceptance criterion: re-introduce the known-fixed race (the
    # liveness-table write losing its lock) into a test copy of
    # kvstore/server.py and the lint must fail
    p = os.path.join(REPO, "mxnet_tpu", "kvstore", "server.py")
    with open(p) as f:
        code = f.read()
    anchor = ("            with self._seen_lock:\n"
              "                self._last_seen[rank] = _fault.now()\n"
              "                self._seen_regime[rank] = "
              "_fault.is_virtual()")
    assert anchor in code, "touch() moved; update this test"
    bad = code.replace(anchor,
                       "            self._last_seen[rank] = _fault.now()\n"
                       "            self._seen_regime[rank] = "
                       "_fault.is_virtual()")
    diags = lint_source(bad, "mxnet_tpu/kvstore/server.py")
    assert "unguarded-shared-write" in rules_of(diags)
    new, _, _ = apply_baseline(diags, load_baseline(BASELINE))
    assert "unguarded-shared-write" in rules_of(new)


def test_reinjected_unguarded_write_in_server_fails_cli(tmp_path):
    # same reinjection through the CLI exit-code contract, on a copied
    # tree (the shipped tree itself must stay clean)
    pkg = tmp_path / "mxnet_tpu"
    (pkg / "kvstore").mkdir(parents=True)
    (pkg / "base.py").write_text("ENV_CATALOG = {}\n")
    p = os.path.join(REPO, "mxnet_tpu", "kvstore", "server.py")
    with open(p) as f:
        code = f.read()
    bad = code.replace("            with self._seen_lock:\n"
                       "                self._last_seen[rank]",
                       "            if True:\n"
                       "                self._last_seen[rank]")
    assert bad != code
    (pkg / "kvstore" / "server.py").write_text(bad)
    r = _run_cli([str(pkg), "--select", "unguarded-shared-write"])
    assert r.returncode == 1, r.stdout + r.stderr
    assert "unguarded-shared-write" in r.stdout


def test_reinjected_hook_race_in_trainer_trips():
    # the overlap-session handoff (ISSUE 5) is lock-protected; dropping
    # the guard on the hook-side read must trip the concurrency pass
    p = os.path.join(REPO, "mxnet_tpu", "gluon", "trainer.py")
    with open(p) as f:
        code = f.read()
    anchor = ("    def _on_grad_ready(self, i, d):\n"
              "        with self._hook_lock:\n"
              "            sess = self._exchange_session")
    assert anchor in code, "Trainer._on_grad_ready moved; update this test"
    bad = code.replace(anchor,
                       "    def _on_grad_ready(self, i, d):\n"
                       "        if True:\n"
                       "            sess = self._exchange_session")
    diags = lint_source(bad, "mxnet_tpu/gluon/trainer.py")
    assert "inconsistent-guard" in rules_of(diags) or \
        "unguarded-shared-write" in rules_of(diags)
    new, _, _ = apply_baseline(diags, load_baseline(BASELINE))
    assert new != []


def test_rule_set_is_complete():
    assert {"host-sync-in-hot-path", "jit-purity",
            "wall-clock-in-fault-path", "env-var-registry",
            "donation-after-use",
            # ISSUE 6: the whole-program concurrency pass
            "unguarded-shared-write", "inconsistent-guard",
            "lock-order-cycle", "blocking-wait-unbounded",
            "thread-leak",
            # ISSUE 11: the program-contract PR's AST rules
            "retrace-hazard", "wire-verb-exhaustive"} <= set(RULES)


# ---------------------------------------------------------------------------
# retrace-hazard (ISSUE 11)
# ---------------------------------------------------------------------------

STEP_PATH = "mxnet_tpu/step.py"


def test_retrace_hazard_shape_branch_in_jitted_body():
    code = src("""
    import jax

    def body(x, k):
        if x.shape[0] > 4:
            return x * k
        return x

    f = jax.jit(body)
    """)
    diags = lint_source(code, STEP_PATH, select={"retrace-hazard"})
    assert rules_of(diags) == ["retrace-hazard"]
    assert "x.shape" in diags[0].message and "body" in diags[0].message


def test_retrace_hazard_scalar_literal_at_hot_call_site():
    code = src("""
    import jax

    def body(x, k):
        return x * k

    _F = jax.jit(body)

    class CompiledStep:
        def _run(self, x):
            return _F(x, 3.0)
    """)
    diags = lint_source(code, STEP_PATH, select={"retrace-hazard"})
    assert rules_of(diags) == ["retrace-hazard"]
    assert "3.0" in diags[0].message and "VALUE" in diags[0].message


def test_retrace_hazard_negative_and_keyword_scalars():
    # -1.0 parses as UnaryOp(USub, Constant) and k=3.0 arrives via
    # node.keywords — both are value-keyed retrace amplifiers; a
    # static_argnames-covered keyword is exempt
    code = src("""
    import jax

    def body(x, c, k=None, mode=None):
        return x * c + k

    _F = jax.jit(body, static_argnames=("mode",))

    class CompiledStep:
        def _run(self, x):
            return _F(x, -1.0, k=3.0, mode=2)
    """)
    diags = lint_source(code, STEP_PATH, select={"retrace-hazard"})
    assert rules_of(diags) == ["retrace-hazard"] * 2
    msgs = "\n".join(d.message for d in diags)
    assert "-1.0" in msgs and "3.0" in msgs and "2" not in msgs.split()


def test_retrace_hazard_register_program_site_and_static_exempt():
    # static_argnums covers both halves: the branch argument and the
    # scalar position are trace-static, so neither is a hazard
    code = src("""
    import jax
    from mxnet_tpu.programs import register_program

    def body(x, n):
        if x.shape[0] > n:
            return x
        return x + n

    _F = register_program("p", body, static_argnums=(1,))

    class CompiledStep:
        def _run(self, x):
            return _F(x, 3)
    """)
    diags = lint_source(code, STEP_PATH, select={"retrace-hazard"})
    # the shape branch still flags (x is traced); the scalar does not
    assert rules_of(diags) == ["retrace-hazard"]
    assert "x.shape" in diags[0].message

    clean = src("""
    import jax
    from mxnet_tpu.programs import register_program

    def body(x, n):
        if x.shape[0] > n:
            return x
        return x + n

    _F = register_program("p", body, static_argnums=(0, 1))
    """)
    assert lint_source(clean, STEP_PATH,
                       select={"retrace-hazard"}) == []


def test_retrace_hazard_suppressed_and_ops_exempt():
    code = src("""
    import jax

    def body(x):
        if x.shape[0] > 4:  # mxlint: disable=retrace-hazard
            return x
        return x

    f = jax.jit(body)
    """)
    assert lint_source(code, STEP_PATH, select={"retrace-hazard"}) == []
    # per-op eager kernels specialize by rank/shape by design — the
    # rule's path scope exempts mxnet_tpu/ops entirely
    unsuppressed = code.replace("  # mxlint: disable=retrace-hazard", "")
    assert lint_source(unsuppressed, "mxnet_tpu/ops/matrix.py",
                       select={"retrace-hazard"}) == []


def test_reinjected_shape_branch_in_step_body_trips():
    """ISSUE 11 reinjection: a per-shape python branch reintroduced into
    the traced step body must trip retrace-hazard (and not be absorbed
    by the shipped baseline)."""
    p = os.path.join(REPO, "mxnet_tpu", "step.py")
    with open(p) as f:
        code = f.read()
    anchor = ("            carry = (t_vals, f_vals, opt_states, w32s, "
              "residuals, mstate)")
    assert anchor in code, "_traced_step_window moved; update this test"
    bad = code.replace(
        anchor,
        "            if xs[0].shape[0] > 4:\n"
        "                pass\n" + anchor, 1)
    diags = lint_source(bad, "mxnet_tpu/step.py")
    assert "retrace-hazard" in rules_of(diags)
    new, _, _ = apply_baseline(diags, load_baseline(BASELINE))
    assert "retrace-hazard" in rules_of(new)


# ---------------------------------------------------------------------------
# wire-verb-exhaustive (ISSUE 11)
# ---------------------------------------------------------------------------

WIRE_SERVER = "mxnet_tpu/serve/xserver.py"
WIRE_CLIENT = "mxnet_tpu/serve/xclient.py"

CLEAN_SERVER = src("""
WIRE_VERBS = {
    "ROUTE": {"semantics": "replayable", "codec": "blob"},
    "DRAIN": {"semantics": "idempotent", "codec": None},
}
_CACHED = ("ROUTE",)

def encode_blob(x):
    return x

def decode_blob(x):
    return x

def handle(msg):
    cmd = msg[0]
    if cmd == "ROUTE":
        return True, "ok"
    if cmd == "DRAIN":
        return True, "ok"
    return False, "unknown"
""")

CLEAN_CLIENT = src("""
class C:
    def route(self, x):
        return self._rpc("ROUTE", x)

    def drain(self):
        return self._rpc("DRAIN")
""")


def test_wire_verbs_clean_pair():
    diags = lint_sources({WIRE_SERVER: CLEAN_SERVER,
                          WIRE_CLIENT: CLEAN_CLIENT},
                         select={"wire-verb-exhaustive"})
    assert diags == []


def test_wire_verb_undeclared_emission():
    client = CLEAN_CLIENT + src("""
    class D:
        def leave(self):
            return self._rpc("LEAVE", 0)
    """)
    diags = lint_sources({WIRE_SERVER: CLEAN_SERVER, WIRE_CLIENT: client},
                         select={"wire-verb-exhaustive"})
    assert rules_of(diags) == ["wire-verb-exhaustive"]
    assert "'LEAVE'" in diags[0].message and diags[0].path == WIRE_CLIENT


def test_wire_verb_unhandled_bad_semantics_replay_and_codec():
    server = src("""
    WIRE_VERBS = {
        "JOIN": {"semantics": "replayable", "codec": None},
        "ROUTE": {"semantics": "maybe", "codec": "blob"},
    }
    _CACHED = ("PREDICT",)

    def handle(msg):
        cmd = msg[0]
        if cmd == "ROUTE":
            return True, "ok"
    """)
    diags = lint_sources({WIRE_SERVER: server},
                         select={"wire-verb-exhaustive"})
    msgs = "\n".join(d.message for d in diags)
    assert "no handler comparison" in msgs          # JOIN unhandled
    assert "missing from this file's replay-cache" in msgs
    assert "semantics 'maybe'" in msgs              # ROUTE semantics
    assert "encode_blob" in msgs                    # codec pair absent


def test_wire_verb_handled_but_undeclared_and_idempotent_in_cache():
    server = src("""
    WIRE_VERBS = {
        "ROUTE": {"semantics": "idempotent", "codec": None},
    }
    _CACHED = ("ROUTE",)

    def handle(msg):
        cmd = msg[0]
        if cmd == "ROUTE":
            return True, "ok"
        if cmd == "EVICT":
            return True, "ok"
    """)
    diags = lint_sources({WIRE_SERVER: server},
                         select={"wire-verb-exhaustive"})
    msgs = "\n".join(d.message for d in diags)
    assert "does not declare it" in msgs            # EVICT handled only
    assert "declared idempotent but sits" in msgs   # ROUTE in _CACHED


def test_wire_verb_cross_protocol_declaration_does_not_mask():
    """A verb declared only by ANOTHER protocol's manifest (kvstore's
    STOP) must not satisfy a serve-client emission: declaration is
    scoped to the client's own package directory when it has a
    manifest."""
    kv_server = src("""
    WIRE_VERBS = {
        "STOP": {"semantics": "idempotent", "codec": None},
    }

    def handle(msg):
        cmd = msg[0]
        if cmd == "STOP":
            return True, "ok"
    """)
    # serve server manifest exists but does NOT declare STOP
    serve_server = CLEAN_SERVER
    serve_client = CLEAN_CLIENT + src("""
    class S:
        def stop(self):
            return self._rpc("STOP")
    """)
    diags = lint_sources({"mxnet_tpu/kvstore/xserver.py": kv_server,
                          WIRE_SERVER: serve_server,
                          WIRE_CLIENT: serve_client},
                         select={"wire-verb-exhaustive"})
    assert any("'STOP'" in d.message and d.path == WIRE_CLIENT
               for d in diags), "\n".join(map(repr, diags))
    assert any("this protocol's server module" in d.message
               for d in diags)
    # a manifest-less directory still falls back to any manifest
    tool_client = src("""
    def shutdown(sock):
        send_msg(sock, ("STOP", "rank0"))
    """)
    diags = lint_sources({"mxnet_tpu/kvstore/xserver.py": kv_server,
                          "tools/xlaunch.py": tool_client},
                         select={"wire-verb-exhaustive"})
    assert diags == [], "\n".join(map(repr, diags))


def test_wire_verb_suppressed_on_manifest_line():
    server = CLEAN_SERVER.replace(
        "WIRE_VERBS = {",
        "WIRE_VERBS = {  # mxlint: disable=wire-verb-exhaustive")
    server = server.replace(
        '    "DRAIN": {"semantics": "idempotent", "codec": None},\n', "")
    # DRAIN handled-but-undeclared anchors on the handler line; the
    # manifest-line suppression covers manifest-side findings only
    diags = lint_sources({WIRE_SERVER: server, WIRE_CLIENT: CLEAN_CLIENT},
                         select={"wire-verb-exhaustive"})
    assert {d.rule for d in diags} <= {"wire-verb-exhaustive"}
    assert all("DRAIN" in d.message for d in diags), \
        "\n".join(d.message for d in diags)


def test_reinjected_unpaired_route_verb_trips():
    """ISSUE 11 reinjection (acceptance criterion): a ROUTE verb added
    to the serve client without completing the server's WIRE_VERBS row
    ships half-wired and must fail lint."""
    p = os.path.join(REPO, "mxnet_tpu", "serve", "client.py")
    with open(p) as f:
        code = f.read()
    anchor = "    def stop(self) -> None:"
    assert anchor in code, "ServeClient moved; update this test"
    bad = code.replace(
        anchor,
        "    def route(self, payload):\n"
        "        return self._rpc(\"ROUTE\", payload)\n\n" + anchor, 1)
    sources = {"mxnet_tpu/serve/client.py": bad}
    for rel in ("mxnet_tpu/serve/server.py",
                "mxnet_tpu/kvstore/server.py",
                "mxnet_tpu/kvstore/wire_codec.py"):
        with open(os.path.join(REPO, rel)) as f:
            sources[rel] = f.read()
    diags = lint_sources(sources, select={"wire-verb-exhaustive"})
    assert any("'ROUTE'" in d.message for d in diags), \
        "\n".join(map(repr, diags))
    new, _, _ = apply_baseline(diags, load_baseline(BASELINE))
    assert any("'ROUTE'" in d.message for d in new)


def test_shipped_wire_surface_is_declared():
    """The shipped protocol surface: both server manifests parse, every
    client verb is declared, and the replay sets agree with semantics
    (the tree-level gate is test_shipped_tree_lints_clean; this pins
    the extraction actually SEEING the manifests)."""
    _diags, project = _scan_tree()
    manifests = {p: s.wire.manifest for p, s in project.summaries.items()
                 if getattr(s, "wire", None) is not None
                 and s.wire.manifest is not None}
    assert "mxnet_tpu/serve/server.py" in manifests
    assert "mxnet_tpu/kvstore/server.py" in manifests
    serve = manifests["mxnet_tpu/serve/server.py"]
    # ISSUE 17: DRAIN retires a replica (re-asserting keeps the FIRST
    # deadline, so a retried DRAIN is a no-op = idempotent)
    assert set(serve) == {"PREDICT", "GENERATE", "STREAM", "HEALTH",
                          "METRICS", "SWAP", "STOP", "DRAIN"}
    assert serve["PREDICT"]["semantics"] == "replayable"
    # ISSUE 15: a replayed COMPLETED generation answers from the cache;
    # STREAM is the server->client chunk frame (handled with an explicit
    # error if a client ever emits it as a request)
    assert serve["GENERATE"]["semantics"] == "replayable"
    assert serve["STREAM"]["semantics"] == "idempotent"
    assert serve["DRAIN"]["semantics"] == "idempotent"
    # ISSUE 17: the router speaks the same surface plus its own DRAIN;
    # forwarded verbs keep the replica's replay semantics (the envelope
    # crosses unmodified, so exactly-once stays with the replica cache)
    assert "mxnet_tpu/serve/router.py" in manifests
    rt = manifests["mxnet_tpu/serve/router.py"]
    assert set(rt) == {"PREDICT", "GENERATE", "STREAM", "HEALTH",
                       "METRICS", "SWAP", "STOP", "DRAIN"}
    assert rt["PREDICT"]["semantics"] == "replayable"
    assert rt["GENERATE"]["semantics"] == "replayable"
    assert rt["DRAIN"]["semantics"] == "idempotent"
    kv = manifests["mxnet_tpu/kvstore/server.py"]
    # ISSUE 16: PULLQ (quantized pull — a read, idempotent like PULL)
    # and the elastic membership verbs JOIN/LEAVE/MEMBERS (no-op
    # mutations never bump the epoch, so replays are safe = idempotent)
    assert {"INIT", "PUSH", "PULL", "PULLQ", "SET_OPT", "BARRIER",
            "PING", "METRICS", "JOIN", "LEAVE", "MEMBERS",
            "STOP"} == set(kv)
    assert kv["METRICS"]["semantics"] == "idempotent"
    assert kv["PULLQ"]["semantics"] == "idempotent"
    assert kv["JOIN"]["semantics"] == "idempotent"
    assert kv["LEAVE"]["semantics"] == "idempotent"
    # the fleet plane's surface (ISSUE 12)
    assert "mxnet_tpu/fleet.py" in manifests
    fl = manifests["mxnet_tpu/fleet.py"]
    assert set(fl) == {"FLEET", "METRICS"}
    assert fl["FLEET"]["codec"] == "json"
