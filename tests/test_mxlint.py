"""mxlint (ISSUE 4): the TPU-invariant static analyzer.

Three layers, bottom-up:

  * fixture snippets per rule — positive hit (right rule id, right
    line), suppressed hit (`# mxlint: disable=`), baselined hit, clean
    code — all through ``lint_source`` with no filesystem;
  * the CLI contract (`python -m tools.mxlint`): exit 0 clean / 1 new
    violations / 2 usage error, ``--format json``, ``--write-baseline``
    round-trip, plus ``tools/gen_env_docs.py --check`` consistency;
  * the tier-1 gate: the SHIPPED tree lints clean against the checked-in
    baseline, and intentionally reintroducing the historical violations
    (an ``asnumpy()`` in ``Trainer._update``, a raw ``time.time()`` in
    the kvstore connect-retry loop) trips the right rule id — the
    acceptance criteria of the issue, verbatim.

Pure stdlib + pytest: no jax import, so this file costs milliseconds.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.mxlint import (lint_source, lint_paths, load_baseline,   # noqa: E402
                          write_baseline, collect_env_reads, RULES)
from tools.mxlint.core import apply_baseline                        # noqa: E402

BASELINE = os.path.join(REPO, "tools", "mxlint", "baseline.json")


def rules_of(diags):
    return [d.rule for d in diags]


def src(text):
    return textwrap.dedent(text).lstrip("\n")


# ---------------------------------------------------------------------------
# host-sync-in-hot-path
# ---------------------------------------------------------------------------

HOT_PATH = "mxnet_tpu/gluon/trainer.py"

def test_host_sync_positive_direct_and_via_helper():
    code = src("""
    class Trainer:
        def step(self, batch_size):
            self._update()

        def _update(self):
            for p in self.params:
                self._drain(p)

        def _drain(self, p):
            return float(p.grad.asnumpy()[0])
    """)
    diags = lint_source(code, HOT_PATH)
    assert rules_of(diags) == ["host-sync-in-hot-path"]
    assert diags[0].line == 10
    # message names the reachable root, not just the containing helper
    assert "Trainer" in diags[0].message and "_drain" in diags[0].message


def test_host_sync_suppressed():
    code = src("""
    class Trainer:
        def _update(self):
            return self.g.asnumpy()  # mxlint: disable=host-sync-in-hot-path
    """)
    assert lint_source(code, HOT_PATH) == []


def test_host_sync_clean_and_out_of_hot_path():
    clean = src("""
    class Trainer:
        def _update(self):
            self.w = self.w - self.lr * self.g

    def offline_report(arrs):
        return [a.asnumpy() for a in arrs]
    """)
    assert lint_source(clean, HOT_PATH) == []
    # same sync outside any hot-path file: no rule applies
    sync = "def f(a):\n    return a.asnumpy()\n"
    assert lint_source(sync, "mxnet_tpu/visualization.py") == []


def test_host_sync_metric_update_root():
    code = src("""
    class Accuracy:
        def update(self, labels, preds):
            import numpy as np
            self.sum_metric += float(np.asarray(preds).sum())
    """)
    diags = lint_source(code, "mxnet_tpu/metric.py")
    assert rules_of(diags) == ["host-sync-in-hot-path"]


# ---------------------------------------------------------------------------
# jit-purity
# ---------------------------------------------------------------------------

def test_jit_purity_decorated():
    code = src("""
    import time
    import jax

    @jax.jit
    def kernel(x):
        print("tracing")
        t = time.time()
        if x > 0:
            return x
        return -x
    """)
    diags = lint_source(code, "mxnet_tpu/ops/extra.py")
    kinds = rules_of(diags)
    assert kinds == ["jit-purity"] * 3
    msgs = " | ".join(d.message for d in diags)
    assert "print()" in msgs and "wall-clock" in msgs and \
        "data-dependent" in msgs


def test_jit_purity_static_args_and_shape_branches_ok():
    code = src("""
    import jax
    from functools import partial

    @partial(jax.jit, static_argnames=("mode",))
    def kernel(x, mode, axis=0):
        if mode == "fast":      # static_argnames: fine
            return x
        if axis:                # defaulted param: static by contract
            return x.sum(axis)
        if x.ndim > 2:          # shape attr: static under trace
            return x.reshape(-1)
        if x is None:           # sentinel: fine
            return x
        return x
    """)
    assert lint_source(code, "mxnet_tpu/ops/extra.py") == []


def test_jit_purity_registered_op_and_env_read():
    code = src("""
    import os
    from .registry import register

    @register("myop")
    def _k(x):
        if os.environ.get("MX_DEBUG_FLAG"):
            return x
        return x + 1

    @register("dynop", no_jit=True)
    def _d(x):
        print(x)   # eager op: prints are legal
        return x
    """)
    diags = lint_source(code, "mxnet_tpu/ops/extra.py",
                        catalog={"MX_DEBUG_FLAG"})
    # the same read trips BOTH rules: ad-hoc env read (env-var-registry)
    # and trace-time env read (jit-purity)
    assert sorted(set(rules_of(diags))) == ["env-var-registry", "jit-purity"]
    jp = [d for d in diags if d.rule == "jit-purity"]
    assert "os.environ" in jp[0].message


def test_jit_purity_by_name_jit_call():
    code = src("""
    import jax
    import random

    def make(fn):
        def step(x):
            return x * random.random()
        return jax.jit(step)
    """)
    diags = lint_source(code, "mxnet_tpu/parallel/foo.py")
    assert rules_of(diags) == ["jit-purity"]
    assert "RNG" in diags[0].message


# ---------------------------------------------------------------------------
# wall-clock-in-fault-path
# ---------------------------------------------------------------------------

def test_wall_clock_positive_alias_and_from_import():
    code = src("""
    import time as _time
    from time import monotonic

    def retry_loop():
        deadline = _time.time() + 60
        while monotonic() < deadline:
            _time.sleep(0.2)
    """)
    diags = lint_source(code, "mxnet_tpu/kvstore/kvstore.py")
    assert rules_of(diags) == ["wall-clock-in-fault-path"] * 3
    assert "fault.now()" in diags[0].message
    assert "fault.sleep()" in diags[-1].message


def test_wall_clock_suppressed_and_clean_and_scoped():
    sup = src("""
    import time as _time

    class _RealClock:
        now = staticmethod(_time.monotonic)  # mxlint: disable=wall-clock-in-fault-path
    """)
    assert lint_source(sup, "mxnet_tpu/fault.py") == []
    clean = src("""
    from .. import fault as _fault

    def retry_loop():
        deadline = _fault.now() + 60
        _fault.sleep(0.2)
    """)
    assert lint_source(clean, "mxnet_tpu/kvstore/kvstore.py") == []
    # time.time is legal outside the fault-path files
    other = "import time\ndef f():\n    return time.time()\n"
    assert lint_source(other, "mxnet_tpu/callback.py") == []


# ---------------------------------------------------------------------------
# env-var-registry
# ---------------------------------------------------------------------------

def test_env_registry_adhoc_read_flagged():
    code = src("""
    import os

    def f():
        a = os.environ.get("MX_SOME_FLAG")
        b = os.getenv("MX_OTHER")
        c = os.environ["MX_THIRD"]
        return a, b, c
    """)
    diags = lint_source(code, "mxnet_tpu/foo.py",
                        catalog={"MX_SOME_FLAG", "MX_OTHER", "MX_THIRD"})
    assert rules_of(diags) == ["env-var-registry"] * 3
    assert all("get_env" in d.message for d in diags)


def test_env_registry_submodule_import_does_not_blind():
    # `import os.path` binds the name `os`; the alias map must not remap
    # it to "os.path" or every os.environ detector goes blind
    code = src("""
    import os.path

    def f():
        return os.environ.get("MX_SOME_FLAG")
    """)
    diags = lint_source(code, "mxnet_tpu/foo.py", catalog={"MX_SOME_FLAG"})
    assert rules_of(diags) == ["env-var-registry"]


def test_env_registry_unregistered_and_clean_and_writes_ok():
    code = src("""
    from .base import get_env

    def f():
        return get_env("MX_NOT_IN_CATALOG")
    """)
    diags = lint_source(code, "mxnet_tpu/foo.py", catalog={"MX_KNOWN"})
    assert rules_of(diags) == ["env-var-registry"]
    assert "ENV_CATALOG" in diags[0].message
    clean = src("""
    import os
    from .base import get_env

    def f():
        os.environ["MX_FORCE_CPU"] = "1"   # writes are fine
        return get_env("MX_KNOWN"), os.environ.get("PATH")
    """)
    assert lint_source(clean, "mxnet_tpu/foo.py", catalog={"MX_KNOWN",
                                                           "MX_FORCE_CPU"}) \
        == []
    # base.py itself is the accessor: exempt
    accessor = 'import os\nv = os.environ.get("MX_FORCE_CPU")\n'
    assert lint_source(accessor, "mxnet_tpu/base.py") == []


# ---------------------------------------------------------------------------
# donation-after-use
# ---------------------------------------------------------------------------

def test_donation_after_use_positive():
    code = src("""
    import jax

    def f(g, a, b):
        fn = jax.jit(g, donate_argnums=(0,))
        out = fn(a, b)
        return a + out
    """)
    diags = lint_source(code, "mxnet_tpu/parallel/foo.py")
    assert rules_of(diags) == ["donation-after-use"]
    assert "'a'" in diags[0].message


def test_donation_after_use_rebind_and_nondonated_ok():
    code = src("""
    import jax

    def f(g, a, b):
        fn = jax.jit(g, donate_argnums=(0,))
        a = fn(a, b)      # rebound: old buffer unreachable
        return a + b      # b was not donated
    """)
    assert lint_source(code, "mxnet_tpu/parallel/foo.py") == []


def test_donation_after_use_self_attr_and_conditional_donate():
    code = src("""
    import jax

    class Step:
        def __init__(self, fn, donate):
            self._step = jax.jit(fn, donate_argnums=(0, 1) if donate else ())

        def run(self, params, opt, batch):
            new_p, new_o = self._step(params, opt, batch)
            self.stale = params.copy()
            return new_p, new_o
    """)
    diags = lint_source(code, "mxnet_tpu/parallel/foo.py")
    assert rules_of(diags) == ["donation-after-use"]
    assert "'params'" in diags[0].message


# ---------------------------------------------------------------------------
# baseline mechanics
# ---------------------------------------------------------------------------

def test_baseline_roundtrip(tmp_path):
    code = src("""
    class Trainer:
        def _update(self):
            return self.g.asnumpy()
    """)
    diags = lint_source(code, HOT_PATH)
    assert len(diags) == 1
    bl = tmp_path / "baseline.json"
    write_baseline(str(bl), diags)
    new, old, stale = apply_baseline(lint_source(code, HOT_PATH),
                                     load_baseline(str(bl)))
    assert new == [] and len(old) == 1 and stale == []
    # a SECOND violation with a different line text is NOT absorbed
    code2 = code + "\n    def update(self):\n        return self.w.asnumpy()\n"
    new2, old2, _ = apply_baseline(lint_source(code2, HOT_PATH),
                                   load_baseline(str(bl)))
    assert len(new2) == 1 and len(old2) == 1
    # fixing the violation leaves the entry stale (reported, not fatal)
    fixed = "class Trainer:\n    def _update(self):\n        return 0\n"
    new3, old3, stale3 = apply_baseline(lint_source(fixed, HOT_PATH),
                                        load_baseline(str(bl)))
    assert new3 == [] and old3 == [] and len(stale3) == 1


def test_parse_error_is_a_diagnostic():
    diags = lint_source("def broken(:\n", "mxnet_tpu/foo.py")
    assert rules_of(diags) == ["mxlint-parse"]


# ---------------------------------------------------------------------------
# CLI contract
# ---------------------------------------------------------------------------

def _fake_repo(tmp_path, bad=True):
    pkg = tmp_path / "mxnet_tpu"
    (pkg / "kvstore").mkdir(parents=True)
    (pkg / "base.py").write_text("ENV_CATALOG = {'MX_KNOWN': ('', 'd')}\n")
    body = "import time as _time\n\ndef retry():\n    return _time.time()\n" \
        if bad else "def retry():\n    return 0\n"
    (pkg / "kvstore" / "mod.py").write_text(body)
    return pkg


def _run_cli(args, cwd=REPO):
    return subprocess.run([sys.executable, "-m", "tools.mxlint"] + args,
                          cwd=cwd, capture_output=True, text=True)


def test_cli_exit_codes_and_json(tmp_path):
    pkg = _fake_repo(tmp_path, bad=True)
    r = _run_cli([str(pkg), "--no-baseline", "--format", "json"])
    assert r.returncode == 1, r.stderr
    payload = json.loads(r.stdout)
    assert [v["rule"] for v in payload["violations"]] == \
        ["wall-clock-in-fault-path"]
    assert payload["violations"][0]["path"] == "mxnet_tpu/kvstore/mod.py"

    clean = _fake_repo(tmp_path / "c", bad=False)
    r = _run_cli([str(clean), "--no-baseline"])
    assert r.returncode == 0, r.stdout + r.stderr

    assert _run_cli(["/nonexistent/path"]).returncode == 2
    assert _run_cli([str(pkg), "--select", "no-such-rule"]).returncode == 2
    assert _run_cli(["--list-rules"]).returncode == 0

    # a typo'd --baseline is a usage error (2), NOT "new violations" (1)
    r = _run_cli([str(pkg), "--baseline", str(tmp_path / "no_such.json")])
    assert r.returncode == 2, r.stdout + r.stderr
    assert "cannot read baseline" in r.stderr
    garbled = tmp_path / "garbled.json"
    garbled.write_text("{not json")
    r = _run_cli([str(pkg), "--baseline", str(garbled)])
    assert r.returncode == 2, r.stdout + r.stderr


def test_cli_write_baseline_roundtrip(tmp_path):
    pkg = _fake_repo(tmp_path, bad=True)
    bl = tmp_path / "bl.json"
    r = _run_cli([str(pkg), "--baseline", str(bl), "--write-baseline"])
    assert r.returncode == 0, r.stderr
    r = _run_cli([str(pkg), "--baseline", str(bl)])
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_write_baseline_narrowed_scan_preserves_entries(tmp_path):
    # re-baselining one FILE must not erase grandfathered entries for the
    # rest of the tree; re-baselining with --select must refuse outright
    pkg = _fake_repo(tmp_path, bad=True)
    clean_file = pkg / "kvstore" / "other.py"
    clean_file.write_text("def ok():\n    return 0\n")
    bl = tmp_path / "bl.json"
    r = _run_cli([str(pkg), "--baseline", str(bl), "--write-baseline"])
    assert r.returncode == 0, r.stderr
    full = json.loads(bl.read_text())["entries"]
    assert len(full) == 1     # mod.py's wall-clock hit

    r = _run_cli([str(clean_file), "--baseline", str(bl),
                  "--write-baseline"])
    assert r.returncode == 0, r.stderr
    assert "preserved" in r.stdout
    assert json.loads(bl.read_text())["entries"] == full

    r = _run_cli([str(pkg), "--baseline", str(bl), "--write-baseline",
                  "--select", "jit-purity"])
    assert r.returncode == 2, r.stdout + r.stderr
    assert json.loads(bl.read_text())["entries"] == full


# ---------------------------------------------------------------------------
# env scanner + gen_env_docs --check
# ---------------------------------------------------------------------------

def test_collect_env_reads(tmp_path):
    f = tmp_path / "m.py"
    f.write_text(src("""
    import os
    from .base import get_env

    a = os.environ.get("MX_ALPHA")
    b = get_env("MXNET_BETA")
    c = os.environ["MX_GAMMA"]
    d = os.environ.get("HOME")        # not MX_*: ignored
    """))
    found = collect_env_reads([str(tmp_path)])
    assert set(found) == {"MX_ALPHA", "MXNET_BETA", "MX_GAMMA"}


@pytest.mark.slow
def test_gen_env_docs_check_passes_on_shipped_tree():
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "gen_env_docs.py"),
                        "--check"], capture_output=True, text=True,
                       cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr


# ---------------------------------------------------------------------------
# the tier-1 gate: shipped tree is clean; reinjected violations trip
# ---------------------------------------------------------------------------

def _lint_tree():
    diags = lint_paths([os.path.join(REPO, "mxnet_tpu")], root=REPO)
    return apply_baseline(diags, load_baseline(BASELINE))


def test_shipped_tree_lints_clean():
    new, old, stale = _lint_tree()
    assert new == [], "\n".join(map(repr, new))
    assert stale == [], ("baseline entries no longer match the tree — "
                         "run `python -m tools.mxlint --write-baseline "
                         "mxnet_tpu/`: %s" % (stale,))


def test_reinjected_asnumpy_in_trainer_update_trips():
    p = os.path.join(REPO, "mxnet_tpu", "gluon", "trainer.py")
    with open(p) as f:
        code = f.read()
    anchor = 'with _profiler.annotate("trainer.update"):'
    assert anchor in code, "Trainer._update moved; update this test"
    bad = code.replace(
        anchor,
        anchor + "\n            _dbg = [g.asnumpy() for g in gs]")
    diags = lint_source(bad, "mxnet_tpu/gluon/trainer.py")
    assert "host-sync-in-hot-path" in rules_of(diags)
    # and it is NOT absorbed by the shipped baseline
    new, _, _ = apply_baseline(diags, load_baseline(BASELINE))
    assert "host-sync-in-hot-path" in rules_of(new)


def test_reinjected_wall_clock_in_kvstore_retry_trips():
    p = os.path.join(REPO, "mxnet_tpu", "kvstore", "kvstore.py")
    with open(p) as f:
        code = f.read()
    anchor = "if deadline.expired():"
    assert anchor in code, "connect-retry loop moved; update this test"
    bad = code.replace(
        anchor,
        "import time\n                    "
        "if time.time() > _connect_t0 + 60:", 1)
    diags = lint_source(bad, "mxnet_tpu/kvstore/kvstore.py")
    assert "wall-clock-in-fault-path" in rules_of(diags)
    new, _, _ = apply_baseline(diags, load_baseline(BASELINE))
    assert "wall-clock-in-fault-path" in rules_of(new)


def test_rule_set_is_complete():
    assert {"host-sync-in-hot-path", "jit-purity",
            "wall-clock-in-fault-path", "env-var-registry",
            "donation-after-use"} <= set(RULES)
