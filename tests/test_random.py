"""Random-op distribution battery (reference:
tests/python/unittest/test_random.py — per-distribution moment checks,
chi-square uniformity, seed determinism).

The op battery exempts samplers from numpy refs (stochastic); this file
is their correctness gate: with N=40k draws the sample mean/var must land
within ~5 sigma of the closed-form moments, uniform draws must pass a
chi-square bucket test, and mx.random.seed must reproduce streams.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ndarray.ndarray import invoke

N = 40_000


def draws(op, **params):
    mx.random.seed(7)
    if "size" in params or "shape" in params:
        out = invoke(op, **params)
    else:
        out = invoke(op, shape=(N,), **params)
    return out.asnumpy().astype(np.float64)


# (op, params, mean, var) — closed-form moments
MOMENTS = [
    ("_random_uniform", {"low": -1.0, "high": 3.0}, 1.0, 16.0 / 12.0),
    ("_random_normal", {"loc": 2.0, "scale": 3.0}, 2.0, 9.0),
    ("_random_gamma", {"alpha": 4.0, "beta": 0.5}, 2.0, 1.0),
    ("_random_exponential", {"lam": 2.0}, 0.5, 0.25),
    ("_random_poisson", {"lam": 6.0}, 6.0, 6.0),
    ("_random_negative_binomial", {"k": 5, "p": 0.5}, 5.0, 10.0),
    ("_random_generalized_negative_binomial", {"mu": 4.0, "alpha": 0.25},
     4.0, 4.0 + 0.25 * 16.0),
    ("_random_logistic", {"loc": 1.0, "scale": 0.5},
     1.0, (np.pi ** 2) * 0.25 / 3.0),
    ("_random_gumbel", {"loc": 0.0, "scale": 1.0},
     np.euler_gamma, np.pi ** 2 / 6.0),
    ("_random_rayleigh", {"scale": 2.0},
     2.0 * np.sqrt(np.pi / 2.0), (4.0 - np.pi) / 2.0 * 4.0),
    ("_random_weibull", {"a": 1.0}, 1.0, 1.0),   # == Exp(1)
    ("_random_pareto", {"a": 5.0}, 0.25, 5.0 / 48.0),  # numpy-style Lomax
    ("_npi_laplace", {"loc": -1.0, "scale": 0.5, "size": (N,)},
     -1.0, 2.0 * 0.25),
    ("_npi_beta", {"a": 2.0, "b": 6.0, "size": (N,)},
     0.25, 2.0 * 6.0 / (64.0 * 9.0)),
    ("_npi_chisquare", {"df": 5.0, "size": (N,)}, 5.0, 10.0),
    ("_npi_standard_t", {"df": 10.0, "size": (N,)}, 0.0, 10.0 / 8.0),
    ("_npi_lognormal", {"mean": 0.0, "sigma": 0.5, "size": (N,)},
     np.exp(0.125), (np.exp(0.25) - 1) * np.exp(0.25)),
    ("_npi_triangular", {"left": 0.0, "mode": 1.0, "right": 2.0,
                         "size": (N,)}, 1.0, 4.0 / 24.0 - 0.0),
]


@pytest.mark.parametrize("op,params,mean,var",
                         MOMENTS, ids=[m[0] for m in MOMENTS])
def test_distribution_moments(op, params, mean, var):
    x = draws(op, **params)
    assert np.isfinite(x).all()
    # standard error bounds: 5-sigma on the mean, generous on the var
    se_mean = np.sqrt(var / N)
    assert abs(x.mean() - mean) < 5 * se_mean + 1e-3, \
        (op, x.mean(), mean)
    assert abs(x.var() - var) < 0.15 * var + 5e-3, (op, x.var(), var)


def test_uniform_chi_square():
    """Bucketed chi-square against Uniform(0,1) (reference test_random
    chi-square helper): 20 buckets, dof=19, crit(0.999) ≈ 43.8."""
    x = draws("_random_uniform", low=0.0, high=1.0)
    counts, _ = np.histogram(x, bins=20, range=(0.0, 1.0))
    expect = N / 20.0
    chi2 = float(((counts - expect) ** 2 / expect).sum())
    assert chi2 < 43.8, chi2


def test_randint_bounds_and_coverage():
    x = draws("_random_randint", low=3, high=11)
    assert x.min() >= 3 and x.max() <= 10
    assert set(np.unique(x).astype(int)) == set(range(3, 11))


def test_bernoulli_rate():
    x = draws("_random_bernoulli", prob=0.3)
    assert set(np.unique(x)) <= {0.0, 1.0}
    assert abs(x.mean() - 0.3) < 5 * np.sqrt(0.21 / N)


def test_seed_determinism_and_divergence():
    mx.random.seed(42)
    a = invoke("_random_normal", shape=(64,)).asnumpy()
    mx.random.seed(42)
    b = invoke("_random_normal", shape=(64,)).asnumpy()
    np.testing.assert_array_equal(a, b)
    c = invoke("_random_normal", shape=(64,)).asnumpy()  # stream advanced
    assert not np.array_equal(a, c)
    mx.random.seed(43)
    d = invoke("_random_normal", shape=(64,)).asnumpy()
    assert not np.array_equal(a, d)


def test_sample_ops_parameter_broadcast():
    """_sample_* draw per-row with row-specific parameters (reference
    sample_op row semantics)."""
    mx.random.seed(0)
    mu = nd.array(np.array([0.0, 100.0], np.float32))
    sd = nd.array(np.array([1.0, 1.0], np.float32))
    out = invoke("_sample_normal", mu, sd, shape=(4000,)).asnumpy()
    assert out.shape == (2, 4000)
    assert abs(out[0].mean() - 0.0) < 0.2
    assert abs(out[1].mean() - 100.0) < 0.2


def test_shuffle_is_permutation():
    mx.random.seed(1)
    x = nd.array(np.arange(512, dtype=np.float32))
    y = invoke("shuffle", x).asnumpy()
    assert sorted(y.tolist()) == list(range(512))
    assert not np.array_equal(y, np.arange(512))


def test_f_geometric_power_moments():
    """np.random.f / geometric / power / negative_binomial moment gates
    (reference: np_random tests' moment-check pattern)."""
    mx.random.seed(0)
    f = mx.np.random.f(5.0, 8.0, 40000).asnumpy()
    assert abs(f.mean() - 8 / 6) < 0.05
    g = mx.np.random.geometric(0.3, 40000).asnumpy()
    assert abs(g.mean() - 1 / 0.3) < 0.1 and g.min() >= 1
    p = mx.np.random.power(3.0, 40000).asnumpy()
    assert abs(p.mean() - 0.75) < 0.01 and p.max() <= 1.0
    nb = mx.np.random.negative_binomial(4, 0.4, 40000).asnumpy()
    assert abs(nb.mean() - 6.0) < 0.15
