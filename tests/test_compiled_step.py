"""ISSUE 7: whole-program compiled train step (one donated jit per step).

Pins the tentpole contracts:
  * compiled == eager parity — identical loss trajectories and final
    params through the ICI store for {plain, int8, 2bit, bf16, overlap
    armed, adam} exchange/optimizer modes (the compiled trace inlines
    the SAME bucket layout, error-feedback kernels and fused tree-apply
    bodies the eager pipeline dispatches separately);
  * the lax.scan multi-step window (MX_STEP_SCAN role): N steps in ONE
    dispatch match N per-step dispatches bit-for-bit, and gradient
    accumulation folded into the scanned body (accum=k) matches the
    equivalent concatenated-batch steps;
  * hybridize-style cache semantics — shape change retraces (both entries
    stay live), invalidate() clears, external param mutation between
    steps is picked up (NDArray chunks stay the source of truth);
  * donation safety — params/optimizer state/EF residuals are donated
    into every dispatch, yet NDArray handles held across steps read the
    CURRENT values and save_states round-trips;
  * eager<->compiled mode switches mid-run continue one trajectory
    (optimizer slot state AND int8 error-feedback residuals are shared
    stores, not device-side captures);
  * PS/dist_async transport falls back to the eager pipeline (its
    exchange crosses a socket mid-step) — and still trains;
  * the dispatch budget: 1-2 dispatches per N-step window
    (tools/dispatch_count.py --compiled);
  * Module.fit under MX_STEP_COMPILE=1 — one dispatch per batch, exact
    param parity with the eager fit, metric folded into the jit.
"""
import socket
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.engine import engine
from mxnet_tpu.gluon import nn

CTXS = [mx.cpu(0), mx.cpu(1)]
RNG = np.random.RandomState(7)
X = RNG.randn(16, 8).astype(np.float32)
Y = RNG.randn(16, 4).astype(np.float32)


def _build(compress=None, opt="sgd", optp=None, kvstore="ici", ctxs=CTXS,
           seed=0):
    mx.random.seed(seed)
    net = nn.Sequential()
    net.add(nn.Dense(16, in_units=8, activation="relu"))
    net.add(nn.Dense(4, in_units=16))
    net.initialize(mx.init.Xavier(), ctx=ctxs)
    tr = gluon.Trainer(net.collect_params(), opt,
                       dict(optp or {"learning_rate": 0.05,
                                     "momentum": 0.9}),
                       kvstore=kvstore, compression_params=compress)
    return net, tr


LOSS = gluon.loss.L2Loss()


def _eager_steps(net, tr, steps, data=None, labels=None, ctxs=CTXS):
    """Classic DP eager loop: split batch across device copies, per-copy
    backward, Trainer exchange+update."""
    data = X if data is None else data
    labels = Y if labels is None else labels
    losses = []
    n = len(data)
    per = n // len(ctxs)
    for _ in range(steps):
        tot = 0.0
        with autograd.record():
            for d, ctx in enumerate(ctxs):
                sl = slice(d * per, (d + 1) * per if d < len(ctxs) - 1
                           else n)
                loss = LOSS(net(nd.array(data[sl], ctx=ctx)),
                            nd.array(labels[sl], ctx=ctx))
                loss.backward()
                tot += float(loss.sum().asnumpy())
        tr.step(batch_size=n)
        losses.append(tot / n)
    return losses


def _compiled_steps(step, steps, data=None, labels=None):
    data = X if data is None else data
    labels = Y if labels is None else labels
    out = []
    for _ in range(steps):
        loss = step.step(nd.array(data, ctx=CTXS[0]),
                         nd.array(labels, ctx=CTXS[0]),
                         batch_size=len(data))
        out.append(float(loss.mean().asnumpy()))
    return out


def _params(net):
    return {k: v.data(CTXS[0]).asnumpy()
            for k, v in net.collect_params().items()}


# ---------------------------------------------------------------------------
# compiled == eager parity (the tentpole acceptance)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("compress", [
    None,
    {"type": "int8"},
    {"type": "2bit", "threshold": 0.05},
    {"type": "bf16"},
])
def test_compiled_matches_eager_all_exchange_modes(compress):
    """6-step 2-device fit through the ICI store: the compiled lane's
    loss trajectory and final params match the eager pipeline for every
    wire mode — the traced exchange body IS the eager exchange."""
    net_e, tr_e = _build(compress)
    e_losses = _eager_steps(net_e, tr_e, 6)
    net_c, tr_c = _build(compress)
    step = tr_c.make_compiled_step(net_c, LOSS)
    c_losses = _compiled_steps(step, 6)
    assert step.compiled, step.fallback_reason
    assert c_losses[-1] < c_losses[0]           # it trains
    np.testing.assert_allclose(c_losses, e_losses, rtol=1e-3, atol=1e-5)
    pe, pc = _params(net_e), _params(net_c)
    # bf16 is the one mode where the wire math differs by construction:
    # eager casts each device copy before summing, the compiled trace
    # casts the full-batch sum — one bf16 rounding apart per step
    rtol = 2e-2 if (compress or {}).get("type") == "bf16" else 1e-4
    for k in pe:
        np.testing.assert_allclose(pc[k], pe[k], rtol=rtol, atol=1e-4,
                                   err_msg=k)


def test_compiled_matches_eager_with_overlap_armed(monkeypatch):
    """MX_EXCHANGE_OVERLAP=1 on the eager side is a pure scheduling
    change, so the compiled lane (which has nothing to overlap — the
    whole step is one program) must still match it exactly."""
    monkeypatch.setenv("MX_EXCHANGE_OVERLAP", "1")
    net_e, tr_e = _build({"type": "int8"})
    e_losses = _eager_steps(net_e, tr_e, 5)
    net_c, tr_c = _build({"type": "int8"})
    step = tr_c.make_compiled_step(net_c, LOSS)
    c_losses = _compiled_steps(step, 5)
    np.testing.assert_allclose(c_losses, e_losses, rtol=1e-3, atol=1e-5)
    pe, pc = _params(net_e), _params(net_c)
    for k in pe:
        np.testing.assert_allclose(pc[k], pe[k], rtol=1e-4, atol=1e-5)


def test_compiled_adam_matches_eager():
    """Adam's bias correction rides the traced lr vector (host-folded per
    step); num_update bookkeeping advances once per step per replica."""
    optp = {"learning_rate": 0.01}
    net_e, tr_e = _build({"type": "int8"}, opt="adam", optp=optp)
    e_losses = _eager_steps(net_e, tr_e, 6)
    net_c, tr_c = _build({"type": "int8"}, opt="adam", optp=optp)
    step = tr_c.make_compiled_step(net_c, LOSS)
    c_losses = _compiled_steps(step, 6)
    np.testing.assert_allclose(c_losses, e_losses, rtol=1e-3, atol=1e-5)
    pe, pc = _params(net_e), _params(net_c)
    for k in pe:
        np.testing.assert_allclose(pc[k], pe[k], rtol=1e-4, atol=1e-5)


def test_single_device_compiled_matches_eager_exactly():
    """One context, no kvstore: the compiled step is the pure fused
    pipeline and matches eager bit-for-bit."""
    net_e, tr_e = _build(ctxs=[mx.cpu(0)])
    e_losses = []
    for _ in range(5):
        with autograd.record():
            loss = LOSS(net_e(nd.array(X)), nd.array(Y))
        loss.backward()
        tr_e.step(batch_size=16)
        e_losses.append(float(loss.mean().asnumpy()))
    net_c, tr_c = _build(ctxs=[mx.cpu(0)])
    step = tr_c.make_compiled_step(net_c, LOSS)
    c_losses = _compiled_steps(step, 5)
    np.testing.assert_allclose(c_losses, e_losses, rtol=0, atol=0)
    pe, pc = _params(net_e), _params(net_c)
    for k in pe:
        np.testing.assert_array_equal(pc[k], pe[k])


# ---------------------------------------------------------------------------
# lax.scan windows + gradient accumulation
# ---------------------------------------------------------------------------

def test_scan_window_matches_per_step_exactly():
    """N=4 steps under ONE lax.scan dispatch == 4 per-step dispatches:
    same traced body, so params agree bit-for-bit."""
    rng = np.random.RandomState(3)
    Xw = rng.randn(4, 16, 8).astype(np.float32)
    Yw = rng.randn(4, 16, 4).astype(np.float32)
    net_a, tr_a = _build({"type": "int8"})
    step_a = tr_a.make_compiled_step(net_a, LOSS)
    per_step = [float(step_a.step(nd.array(Xw[t], ctx=CTXS[0]),
                                  nd.array(Yw[t], ctx=CTXS[0]),
                                  batch_size=16).mean().asnumpy())
                for t in range(4)]
    net_b, tr_b = _build({"type": "int8"})
    step_b = tr_b.make_compiled_step(net_b, LOSS)
    losses = step_b.run_window(nd.array(Xw, ctx=CTXS[0]),
                               nd.array(Yw, ctx=CTXS[0]), batch_size=16)
    scanned = list(np.asarray(losses._jax).reshape(4, -1).mean(axis=1))
    np.testing.assert_allclose(scanned, per_step, rtol=1e-6, atol=1e-7)
    pa, pb = _params(net_a), _params(net_b)
    for k in pa:
        np.testing.assert_allclose(pb[k], pa[k], rtol=1e-6, atol=1e-7)


def test_scan_grad_accumulation_matches_concat_batches():
    """accum=2 inside the scanned body: each optimizer step consumes two
    micro-batches whose summed gradient equals the concatenated batch's
    gradient — so a window of 4 micro-batches with accum=2 matches 2
    full-batch steps on the concatenations."""
    rng = np.random.RandomState(5)
    micro = rng.randn(4, 8, 8).astype(np.float32)
    lab = rng.randn(4, 8, 4).astype(np.float32)
    net_a, tr_a = _build()
    step_a = tr_a.make_compiled_step(net_a, LOSS)
    for t in (0, 1):
        step_a.step(nd.array(np.concatenate(micro[2 * t:2 * t + 2]),
                             ctx=CTXS[0]),
                    nd.array(np.concatenate(lab[2 * t:2 * t + 2]),
                             ctx=CTXS[0]),
                    batch_size=16)
    net_b, tr_b = _build()
    step_b = tr_b.make_compiled_step(net_b, LOSS)
    step_b.run_window(nd.array(micro, ctx=CTXS[0]),
                      nd.array(lab, ctx=CTXS[0]),
                      batch_size=16, accum=2)
    pa, pb = _params(net_a), _params(net_b)
    for k in pa:
        np.testing.assert_allclose(pb[k], pa[k], rtol=1e-5, atol=1e-6,
                                   err_msg=k)


def test_window_dispatch_budget():
    """The ISSUE 7 dispatch contract, via the same harness the CLI smoke
    runs: 1-2 dispatches per N-step window, one per single step, and the
    engine attributes N optimizer steps to the one window."""
    import tools.dispatch_count as dc
    report = dc.run_compiled(n_steps=4)
    assert report["ok"], report


# ---------------------------------------------------------------------------
# cache semantics (hybridize parity)
# ---------------------------------------------------------------------------

def test_retrace_on_shape_change_and_invalidate():
    net, tr = _build(ctxs=[mx.cpu(0)])
    step = tr.make_compiled_step(net, LOSS)
    step.step(nd.array(X), nd.array(Y))
    assert len(step._cache) == 1
    # new batch shape: retrace, both executables stay cached
    step.step(nd.array(X[:8]), nd.array(Y[:8]))
    assert len(step._cache) == 2
    # same shapes again: cache hit, no growth
    step.step(nd.array(X), nd.array(Y))
    step.step(nd.array(X[:8]), nd.array(Y[:8]))
    assert len(step._cache) == 2
    step.invalidate()
    assert len(step._cache) == 0
    step.step(nd.array(X), nd.array(Y))
    assert len(step._cache) == 1


def test_external_param_mutation_is_picked_up():
    """set_data between compiled steps must take effect (the NDArray
    chunks, not device captures, are the source of truth) — the
    _clear_cached_op-style invalidation contract."""
    net_c, tr_c = _build(ctxs=[mx.cpu(0)])
    step = tr_c.make_compiled_step(net_c, LOSS)
    step.step(nd.array(X), nd.array(Y))
    for p in net_c.collect_params().values():
        p.set_data(nd.zeros(p.shape))
    loss = step.step(nd.array(X), nd.array(Y))
    # from zero weights the first layer's output is 0 -> loss == mean of
    # 0.5*|y|^2 per example; params moved off zero afterwards
    expect = 0.5 * (Y ** 2).sum(axis=1).mean() / Y.shape[1]
    assert abs(float(loss.mean().asnumpy()) - expect) < 1e-4
    w = net_c.collect_params()[list(net_c.collect_params())[-1]]
    assert float(np.abs(w.data().asnumpy()).sum()) > 0


# ---------------------------------------------------------------------------
# donation safety + state round-trips
# ---------------------------------------------------------------------------

def test_donation_safe_handles_and_save_states(tmp_path):
    """Params, optimizer slot state and EF residuals are donated into
    every dispatch; NDArray handles held across steps must still read
    the CURRENT value (chunk swap, never a dead buffer), and
    save_states/load_states round-trips the donated momentum."""
    net, tr = _build({"type": "int8"})
    params = list(net.collect_params().values())
    held_w = params[0].data(CTXS[0])
    step = tr.make_compiled_step(net, LOSS)
    _compiled_steps(step, 3)
    # the held handle tracks the post-step value of the SAME parameter
    np.testing.assert_array_equal(held_w.asnumpy(),
                                  params[0].data(CTXS[0]).asnumpy())
    assert np.all(np.isfinite(held_w.asnumpy()))
    # momentum state was created in the shared updater store and is live
    st = tr._updaters[0].states
    assert st and all(s is not None for s in st.values())
    f = str(tmp_path / "trainer.states")
    tr.save_states(f)
    # a fresh identical trainer resumes from the saved slot state and
    # matches continued training exactly
    net2, tr2 = _build({"type": "int8"})
    for p2, p in zip(net2.collect_params().values(), params):
        p2.set_data(p.data(CTXS[0]))
    step2 = tr2.make_compiled_step(net2, LOSS)
    step2.step(nd.array(X, ctx=CTXS[0]), nd.array(Y, ctx=CTXS[0]))  # init kv
    tr2.load_states(f)
    # residuals continue from the live store on tr; COPY the arrays over
    # (tr keeps training below and donates its own residuals) so the
    # comparison isolates the optimizer-state round-trip
    import jax.numpy as jnp
    gc1 = tr._kvstore._gc
    tr2._kvstore._gc._residuals = {k: jnp.array(v, copy=True)
                                   for k, v in gc1._residuals.items()}
    for p2, p in zip(net2.collect_params().values(), params):
        p2.set_data(p.data(CTXS[0]))
    a = _compiled_steps(step, 2)
    b = _compiled_steps(step2, 2)
    np.testing.assert_allclose(b, a, rtol=1e-4, atol=1e-6)


def test_mode_switch_continues_trajectory():
    """compiled -> eager mid-run continues ONE trajectory: slot state and
    int8 error-feedback residuals live in shared stores, so 3 compiled +
    3 eager steps equal 6 eager steps."""
    net_e, tr_e = _build({"type": "int8"})
    e_losses = _eager_steps(net_e, tr_e, 6)
    net_m, tr_m = _build({"type": "int8"})
    step = tr_m.make_compiled_step(net_m, LOSS)
    m_losses = _compiled_steps(step, 3)
    m_losses += _eager_steps(net_m, tr_m, 3)
    np.testing.assert_allclose(m_losses, e_losses, rtol=1e-3, atol=1e-5)
    pe, pm = _params(net_e), _params(net_m)
    for k in pe:
        np.testing.assert_allclose(pm[k], pe[k], rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# metric folding
# ---------------------------------------------------------------------------

def test_metric_folds_into_step_dispatch():
    """A device-kernel metric accumulates INSIDE the step's one dispatch
    and get() drains the same value the eager update would produce."""
    net_c, tr_c = _build(ctxs=[mx.cpu(0)])
    metric = mx.metric.MSE()
    step = tr_c.make_compiled_step(net_c, LOSS, metric=metric)
    step.step(nd.array(X), nd.array(Y))        # warm: trace+compile
    c0 = engine.dispatch_count
    step.step(nd.array(X), nd.array(Y))
    assert engine.dispatch_count - c0 == 1     # metric cost no extra dispatch
    name, val = metric.get()
    # eager reference on the SAME outputs
    net_e, tr_e = _build(ctxs=[mx.cpu(0)])
    ref = mx.metric.MSE()
    for _ in range(2):
        with autograd.record():
            out = net_e(nd.array(X))
            loss = LOSS(out, nd.array(Y))
        loss.backward()
        tr_e.step(batch_size=16)
        ref.update([nd.array(Y)], [out])
    _, ref_val = ref.get()
    np.testing.assert_allclose(val, ref_val, rtol=1e-5)


# ---------------------------------------------------------------------------
# PS-transport fallback
# ---------------------------------------------------------------------------

def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_ps_transport_falls_back_to_eager(monkeypatch):
    """dist_async's exchange crosses a socket mid-step — untraceable.
    The compiled step must fall back to the eager pipeline (with the
    documented warning) and still train through the real server."""
    from mxnet_tpu.kvstore.server import serve_forever
    monkeypatch.setenv("MX_KVSTORE_HEARTBEAT", "0")
    monkeypatch.delenv("MX_PS_ROOTS", raising=False)
    port = _free_port()
    t = threading.Thread(target=serve_forever,
                         kwargs=dict(port=port, num_workers=1), daemon=True)
    t.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port),
                                     timeout=0.2).close()
            break
        except OSError:
            time.sleep(0.05)
    monkeypatch.setenv("MX_PS_ROOT", "127.0.0.1:%d" % port)
    net, tr = _build(kvstore="dist_async")
    step = tr.make_compiled_step(net, LOSS)
    with pytest.warns(UserWarning, match="falling back to the eager"):
        losses = _compiled_steps(step, 4)
    assert not step.compiled
    assert "dist_async" in step.fallback_reason
    assert losses[-1] < losses[0]
    assert all(np.isfinite(l) for l in losses)
    tr._kvstore.stop_server()


def test_unsupported_optimizer_falls_back():
    net, tr = _build(opt="rmsprop", optp={"learning_rate": 0.01})
    step = tr.make_compiled_step(net, LOSS)
    with pytest.warns(UserWarning, match="no pure tree kernel"):
        losses = _compiled_steps(step, 3)
    assert not step.compiled
    assert losses[-1] < losses[0]


# ---------------------------------------------------------------------------
# Module.fit wiring (MX_STEP_COMPILE=1)
# ---------------------------------------------------------------------------

def _mlp_symbol():
    from mxnet_tpu import symbol as sym
    data = sym.Variable("data")
    h = sym.FullyConnected(data, sym.Variable("fc1_weight"),
                           sym.Variable("fc1_bias"), num_hidden=16)
    h = sym.Activation(h, act_type="relu")
    out = sym.FullyConnected(h, sym.Variable("fc2_weight"),
                             sym.Variable("fc2_bias"), num_hidden=3)
    return sym.SoftmaxOutput(out, sym.Variable("softmax_label"),
                             normalization="batch", name="softmax")


def _module_fit(compile_flag, monkeypatch):
    from mxnet_tpu import io as mio
    from mxnet_tpu.module import Module
    monkeypatch.setenv("MX_STEP_COMPILE", compile_flag)
    rng = np.random.RandomState(0)
    Xm = rng.randn(96, 8).astype(np.float32)
    Ym = Xm[:, :3].argmax(axis=1).astype(np.float32)
    mx.random.seed(42)
    mod = Module(_mlp_symbol(), context=mx.cpu())
    mod.fit(mio.NDArrayIter(Xm, Ym, batch_size=24), optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            num_epoch=2)
    arg, _aux = mod.get_params()
    return {k: v.asnumpy() for k, v in arg.items()}


def test_module_fit_compiled_matches_eager(monkeypatch):
    eager = _module_fit("0", monkeypatch)
    w0 = engine.compiled_step_windows
    compiled = _module_fit("1", monkeypatch)
    assert engine.compiled_step_windows - w0 == 8    # 4 batches x 2 epochs
    for k in eager:
        np.testing.assert_allclose(compiled[k], eager[k], rtol=1e-5,
                                   atol=1e-6, err_msg=k)
