"""Data-IO tests: recordio container, mx.io iterators, mx.image, im2rec.

Reference pattern: tests/python/unittest/test_recordio.py, test_io.py,
test_image.py — format roundtrips, iterator epoch semantics (shuffle/pad/
discard), ImageRecordIter over an im2rec-built pack.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio, image, io as mio
from mxnet_tpu.gluon.data import RecordFileDataset
from mxnet_tpu.gluon.data.vision import ImageRecordDataset

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- recordio -----------------------------------------------------------------

def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "a.rec")
    w = recordio.MXRecordIO(path, "w")
    payloads = [b"x", b"hello world", b"", b"z" * 4097]
    for p in payloads:
        w.write(p)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    got = []
    while True:
        x = r.read()
        if x is None:
            break
        got.append(x)
    assert got == payloads
    r.reset()
    assert r.read() == payloads[0]
    r.close()


def test_recordio_embedded_magic(tmp_path):
    """Payloads containing the magic pattern must roundtrip (multi-chunk)."""
    path = str(tmp_path / "m.rec")
    magic = (0xced7230a).to_bytes(4, "little")
    payloads = [magic, b"ab" + magic + b"cd", magic + magic, b"tail" + magic]
    w = recordio.MXRecordIO(path, "w")
    for p in payloads:
        w.write(p)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    for p in payloads:
        assert r.read() == p
    assert r.read() is None


def test_indexed_recordio(tmp_path):
    path = str(tmp_path / "b.rec")
    idxp = str(tmp_path / "b.idx")
    w = recordio.MXIndexedRecordIO(idxp, path, "w")
    for i in range(10):
        w.write_idx(i, b"rec%03d" % i)
    w.close()
    assert os.path.isfile(idxp)
    r = recordio.MXIndexedRecordIO(idxp, path, "r")
    assert r.keys == list(range(10))
    for i in (7, 0, 9, 3):
        assert r.read_idx(i) == b"rec%03d" % i


def test_native_and_python_writers_interop(tmp_path):
    """The ctypes-C++ and pure-Python paths produce identical bytes."""
    if recordio._get_lib() is None:
        pytest.skip("native lib unavailable")
    pn = str(tmp_path / "n.rec")
    pp = str(tmp_path / "p.rec")
    payloads = [b"abc", b"x" * 33, (0xced7230a).to_bytes(4, "little") * 2]
    w = recordio.MXRecordIO(pn, "w")
    for x in payloads:
        w.write(x)
    w.close()
    wp = recordio.MXRecordIO(pp, "w")
    wp._handle = None  # force python fallback path
    wp._pyfile = open(pp, "wb")
    for x in payloads:
        wp.write(x)
    wp._pyfile.close()
    wp.is_open = False
    with open(pn, "rb") as f1, open(pp, "rb") as f2:
        assert f1.read() == f2.read()


def test_pack_unpack_img():
    img = (np.random.rand(24, 16, 3) * 255).astype(np.uint8)
    s = recordio.pack_img(recordio.IRHeader(0, 2.0, 5, 0), img,
                          img_fmt=".png")
    header, out = recordio.unpack_img(s)
    assert header.label == 2.0 and header.id == 5
    np.testing.assert_array_equal(out, img)
    # jpeg is lossy but close on smooth content
    grad = np.tile(np.arange(16, dtype=np.uint8)[None, :, None] * 8,
                   (24, 1, 3))
    s = recordio.pack_img(recordio.IRHeader(0, 1.0, 0, 0), grad, quality=95)
    _h, outj = recordio.unpack_img(s)
    assert outj.shape == grad.shape
    assert np.abs(outj.astype(int) - grad.astype(int)).mean() < 4


# -- mx.io --------------------------------------------------------------------

def test_ndarray_iter_basic():
    X = np.arange(40, dtype=np.float32).reshape(10, 4)
    Y = np.arange(10, dtype=np.float32)
    it = mio.NDArrayIter(X, Y, batch_size=3, last_batch_handle="pad")
    descs = it.provide_data
    assert descs[0].name == "data" and descs[0].shape == (3, 4)
    batches = list(it)
    assert len(batches) == 4
    assert batches[-1].pad == 2
    # pad wraps to head samples
    np.testing.assert_array_equal(batches[-1].data[0].asnumpy()[1:],
                                  X[[0, 1]])
    it.reset()
    assert len(list(it)) == 4


def test_ndarray_iter_discard_and_shuffle():
    X = np.arange(10, dtype=np.float32).reshape(10, 1)
    it = mio.NDArrayIter(X, batch_size=4, shuffle=True,
                         last_batch_handle="discard")
    seen = np.concatenate([b.data[0].asnumpy().ravel() for b in it])
    assert len(seen) == 8 and len(np.unique(seen)) == 8
    it.reset()
    seen2 = np.concatenate([b.data[0].asnumpy().ravel() for b in it])
    assert len(seen2) == 8


def test_ndarray_iter_dict_inputs():
    it = mio.NDArrayIter({"a": np.zeros((4, 2)), "b": np.ones((4, 3))},
                         batch_size=2)
    names = [d.name for d in it.provide_data]
    assert names == ["a", "b"]
    b = next(it)
    assert b.data[0].shape == (2, 2) and b.data[1].shape == (2, 3)


def test_resize_and_prefetch_iter():
    X = np.arange(12, dtype=np.float32).reshape(6, 2)
    base = mio.NDArrayIter(X, batch_size=2)
    rs = mio.ResizeIter(base, size=5)  # longer than one epoch: rewinds
    assert len(list(rs)) == 5
    base.reset()
    pf = mio.PrefetchingIter(mio.NDArrayIter(X, batch_size=2))
    batches = list(pf)
    assert len(batches) == 3
    np.testing.assert_array_equal(batches[0].data[0].asnumpy(), X[:2])


def test_csv_iter(tmp_path):
    data = np.random.rand(7, 3).astype(np.float32)
    labels = np.arange(7, dtype=np.float32)
    dcsv = str(tmp_path / "d.csv")
    lcsv = str(tmp_path / "l.csv")
    np.savetxt(dcsv, data, delimiter=",")
    np.savetxt(lcsv, labels, delimiter=",")
    it = mio.CSVIter(data_csv=dcsv, data_shape=(3,), label_csv=lcsv,
                     batch_size=2)
    b = next(it)
    np.testing.assert_allclose(b.data[0].asnumpy(), data[:2], rtol=1e-6)


# -- mx.image -----------------------------------------------------------------

def test_image_decode_resize_crop():
    img = (np.random.rand(40, 30, 3) * 255).astype(np.uint8)
    s = recordio.pack_img(recordio.IRHeader(0, 0.0, 0, 0), img,
                          img_fmt=".png")
    _h, payload = recordio.unpack(s)
    dec = image.imdecode(payload)
    assert dec.shape == (40, 30, 3)
    np.testing.assert_array_equal(dec.asnumpy(), img)
    r = image.imresize(dec, 15, 20)
    assert r.shape == (20, 15, 3)
    rs = image.resize_short(dec, 16)
    assert min(rs.shape[:2]) == 16
    c, rect = image.center_crop(dec, (8, 8))
    assert c.shape == (8, 8, 3) and rect[2:] == (8, 8)
    rc, _ = image.random_crop(dec, (8, 8))
    assert rc.shape == (8, 8, 3)
    n = image.color_normalize(dec, mean=np.array([1.0, 2.0, 3.0]),
                              std=np.array([2.0, 2.0, 2.0]))
    assert str(n.dtype) == "float32"


def test_augmenter_chain():
    augs = image.CreateAugmenter(data_shape=(3, 12, 12), resize=16,
                                 rand_crop=True, rand_mirror=True,
                                 mean=True, std=True)
    img = mx.nd.array((np.random.rand(40, 30, 3) * 255).astype(np.uint8))
    out = img
    for a in augs:
        out = a(out)
    assert out.shape == (12, 12, 3)
    assert str(out.dtype) == "float32"


def _build_pack(tmp_path, n=12, classes=3):
    """im2rec over a generated image folder, via the CLI."""
    from PIL import Image
    root = tmp_path / "imgs"
    for c in range(classes):
        d = root / ("class%d" % c)
        d.mkdir(parents=True)
        for i in range(n // classes):
            arr = np.full((32, 32, 3), 40 * c + i, np.uint8)
            Image.fromarray(arr).save(d / ("img%d.jpg" % i))
    prefix = str(tmp_path / "pack")
    subprocess.run([sys.executable,
                    os.path.join(REPO, "tools", "im2rec.py"),
                    prefix, str(root)], check=True, capture_output=True)
    return prefix


def test_im2rec_and_image_record_iter(tmp_path):
    prefix = _build_pack(tmp_path)
    assert os.path.isfile(prefix + ".rec") and os.path.isfile(prefix + ".idx")
    it = mio.ImageRecordIter(path_imgrec=prefix + ".rec",
                             data_shape=(3, 28, 28), batch_size=4,
                             shuffle=True, preprocess_threads=2)
    labels = []
    nb = 0
    for batch in it:
        assert batch.data[0].shape == (4, 3, 28, 28)
        labels.extend(batch.label[0].asnumpy().tolist())
        nb += 1
    assert nb == 3
    assert set(labels) == {0.0, 1.0, 2.0}
    it.reset()
    assert sum(1 for _ in it) == 3


def test_record_file_and_image_record_dataset(tmp_path):
    prefix = _build_pack(tmp_path)
    ds = RecordFileDataset(prefix + ".rec")
    assert len(ds) == 12
    header, img = recordio.unpack_img(ds[0])
    assert img.shape == (32, 32, 3)
    ids = ImageRecordDataset(prefix + ".rec")
    img, label = ids[5]
    assert img.shape == (32, 32, 3)
    assert isinstance(label, float)
    # DataLoader over the dataset matches direct reads
    from mxnet_tpu.gluon.data import DataLoader
    loader = DataLoader(ids.transform_first(
        lambda im: im.astype(np.float32).transpose(2, 0, 1)),
        batch_size=6)
    batch, lab = next(iter(loader))
    assert batch.shape == (6, 3, 32, 32)


def test_image_record_iter_sharding(tmp_path):
    prefix = _build_pack(tmp_path)
    parts = []
    for pi in range(2):
        it = mio.ImageRecordIter(path_imgrec=prefix + ".rec",
                                 data_shape=(3, 32, 32), batch_size=2,
                                 num_parts=2, part_index=pi)
        ids = []
        for b in it:
            ids.extend(b.label[0].asnumpy().tolist())
        parts.append(len(ids))
    assert sum(parts) == 12  # disjoint shards cover the set


# -- multiprocess DataLoader workers (reference: _MultiWorkerIter) ----------

class _SquareDataset:
    """Top-level (picklable) dataset: sample i -> (i^2 row, i)."""

    def __init__(self, n, width=8):
        self.n = n
        self.width = width

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        row = np.full((self.width,), float(i * i), np.float32)
        return row, np.float32(i)


def test_dataloader_process_workers_order_and_values():
    """num_workers>0 (default process pool): batches arrive IN ORDER with
    the same values as the serial path, across two epochs (pool reuse)."""
    from mxnet_tpu.gluon.data import DataLoader

    ds = _SquareDataset(37)
    serial = DataLoader(ds, batch_size=8, num_workers=0)
    workers = DataLoader(ds, batch_size=8, num_workers=2)
    try:
        for _epoch in range(2):
            got = list(workers)
            want = list(serial)
            assert len(got) == len(want) == 5
            for (gd, gl), (wd, wl) in zip(got, want):
                np.testing.assert_allclose(gd.asnumpy(), wd.asnumpy())
                np.testing.assert_allclose(gl.asnumpy(), wl.asnumpy())
    finally:
        workers._shutdown_pool()


def test_dataloader_thread_pool_optin():
    from mxnet_tpu.gluon.data import DataLoader

    ds = _SquareDataset(20)
    dl = DataLoader(ds, batch_size=5, num_workers=2, thread_pool=True)
    got = list(dl)
    assert len(got) == 4
    np.testing.assert_allclose(got[1][0].asnumpy()[0, 0], 25.0)


def test_dataloader_unpicklable_dataset_raises_helpfully():
    from mxnet_tpu.gluon.data import DataLoader, ArrayDataset

    base = ArrayDataset(mx.nd.array(np.arange(8, dtype=np.float32)))
    ds = base.transform(lambda x: x * 2)      # lambda: not picklable
    dl = DataLoader(ds, batch_size=4, num_workers=2)
    with pytest.raises(RuntimeError, match="picklable"):
        list(dl)
    # thread_pool path still works for the same dataset
    dl2 = DataLoader(ds, batch_size=4, num_workers=2, thread_pool=True)
    out = list(dl2)
    np.testing.assert_allclose(out[0].asnumpy(), [0.0, 2.0, 4.0, 6.0])


# -- LibSVMIter (reference: src/io/iter_libsvm.cc; test_io.py pattern) ------

def test_libsvm_iter_csr_batches(tmp_path):
    path = str(tmp_path / "data.libsvm")
    with open(path, "w") as f:
        f.write("1 0:1.5 3:2.0\n")
        f.write("0 1:3.5\n")
        f.write("2 0:0.5 2:1.0 4:4.0\n")
        f.write("1 4:2.5\n")
    it = mio.LibSVMIter(data_libsvm=path, data_shape=(5,), batch_size=2)
    batches = list(it)
    assert len(batches) == 2
    b0 = batches[0]
    assert b0.data[0].stype == "csr"
    dense = b0.data[0].tostype("default").asnumpy()
    np.testing.assert_allclose(dense, [[1.5, 0, 0, 2.0, 0],
                                       [0, 3.5, 0, 0, 0]])
    np.testing.assert_allclose(b0.label[0].asnumpy().ravel(), [1.0, 0.0])
    b1 = batches[1]
    dense1 = b1.data[0].tostype("default").asnumpy()
    np.testing.assert_allclose(dense1, [[0.5, 0, 1.0, 0, 4.0],
                                        [0, 0, 0, 0, 2.5]])
    # reset re-iterates identically
    it.reset()
    again = next(it).data[0].tostype("default").asnumpy()
    np.testing.assert_allclose(again, dense)


def test_libsvm_iter_round_batch_pad(tmp_path):
    path = str(tmp_path / "d.libsvm")
    with open(path, "w") as f:
        for i in range(3):
            f.write("%d 0:%d\n" % (i, i + 1))
    it = mio.LibSVMIter(data_libsvm=path, data_shape=(2,), batch_size=2)
    b0, b1 = list(it)
    assert b0.pad == 0 and b1.pad == 1          # wrapped one sample
    np.testing.assert_allclose(
        b1.data[0].tostype("default").asnumpy(), [[3, 0], [1, 0]])


def test_libsvm_iter_separate_label_file(tmp_path):
    dpath, lpath = str(tmp_path / "d.libsvm"), str(tmp_path / "l.libsvm")
    with open(dpath, "w") as f:
        f.write("0 0:1.0\n0 1:2.0\n")
    with open(lpath, "w") as f:
        f.write("0:0.5 1:0.7\n")
        f.write("1:0.9\n")
    it = mio.LibSVMIter(data_libsvm=dpath, data_shape=(2,), batch_size=2,
                        label_libsvm=lpath, label_shape=(2,))
    b = next(it)
    np.testing.assert_allclose(b.label[0].asnumpy(), [[0.5, 0.7],
                                                      [0.0, 0.9]])


def test_libsvm_feeds_sparse_dot():
    """The CSR batch plugs straight into sparse compute (dot(csr, dense))."""
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "x.libsvm")
        with open(path, "w") as f:
            f.write("1 0:2.0 2:1.0\n0 1:1.0\n")
        it = mio.LibSVMIter(data_libsvm=path, data_shape=(3,), batch_size=2)
        csr = next(it).data[0]
        w = mx.nd.array(np.arange(6, dtype=np.float32).reshape(3, 2))
        out = mx.nd.sparse.dot(csr, w)
        np.testing.assert_allclose(out.asnumpy(),
                                   csr.tostype("default").asnumpy()
                                   @ w.asnumpy())


# -- MNISTIter (reference: src/io/iter_mnist.cc) ----------------------------

def _write_idx(tmp_path, images, labels):
    img_path = str(tmp_path / "imgs-idx3-ubyte")
    lab_path = str(tmp_path / "labs-idx1-ubyte")
    n, h, w = images.shape
    with open(img_path, "wb") as f:
        f.write((0x803).to_bytes(4, "big"))
        for dim in (n, h, w):
            f.write(dim.to_bytes(4, "big"))
        f.write(images.astype(np.uint8).tobytes())
    with open(lab_path, "wb") as f:
        f.write((0x801).to_bytes(4, "big"))
        f.write(n.to_bytes(4, "big"))
        f.write(labels.astype(np.uint8).tobytes())
    return img_path, lab_path


def test_mnist_iter_shapes_and_values(tmp_path):
    rng = np.random.RandomState(0)
    images = rng.randint(0, 256, (10, 28, 28)).astype(np.uint8)
    labels = (np.arange(10) % 10).astype(np.uint8)
    img_path, lab_path = _write_idx(tmp_path, images, labels)

    it = mio.MNISTIter(image=img_path, label=lab_path, batch_size=4,
                       flat=False)
    b = next(it)
    assert b.data[0].shape == (4, 1, 28, 28)
    np.testing.assert_allclose(b.data[0].asnumpy()[0, 0],
                               images[0] / 255.0, atol=1e-6)
    np.testing.assert_allclose(b.label[0].asnumpy(), labels[:4])

    flat = mio.MNISTIter(image=img_path, label=lab_path, batch_size=5,
                         flat=True)
    fb = next(flat)
    assert fb.data[0].shape == (5, 784)


def test_mnist_iter_sharding(tmp_path):
    images = np.zeros((8, 28, 28), np.uint8)
    labels = np.arange(8).astype(np.uint8)
    img_path, lab_path = _write_idx(tmp_path, images, labels)
    part = mio.MNISTIter(image=img_path, label=lab_path, batch_size=4,
                         num_parts=2, part_index=1)
    b = next(part)
    np.testing.assert_allclose(b.label[0].asnumpy(), [1, 3, 5, 7])


def test_parse_log_tool():
    """tools/parse_log.py scrapes Speedometer/fit logs (reference
    tools/parse_log.py role)."""
    import tempfile
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import parse_log
    lines = [
        "INFO:root:Epoch[0] Batch [100]\tSpeed: 1000.0 samples/sec\t"
        "accuracy=0.61",
        "INFO:root:Epoch[0] Batch [200]\tSpeed: 1200.0 samples/sec\t"
        "accuracy=0.64",
        "INFO:root:Epoch[0] Time cost=10.5",
        "INFO:root:Epoch[0] Validation-accuracy=0.70",
        "INFO:root:Epoch[1] Batch [100]\tSpeed: 1500.0 samples/sec\t"
        "accuracy=0.72",
    ]
    out = parse_log.parse(lines)
    assert out[0]["val-accuracy"] == 0.70
    assert out[0]["time"] == 10.5
    assert out[0]["speeds"] == [1000.0, 1200.0]
    assert out[1]["train-accuracy"] == 0.72


def test_bandwidth_tool_runs():
    """tools/bandwidth.py (reference tools/bandwidth/measure.py role)
    reports a JSON bandwidth line for the local store."""
    import json
    import subprocess
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "bandwidth.py"),
                        "--cpu", "--mb", "2", "--iters", "3"],
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-500:]
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "kvstore_pushpull_bandwidth_gb_per_sec"
    assert rec["value"] > 0


def test_native_jpeg_decoder_matches_pil():
    """src/imdecode.cc (reference ImageRecordIOParser2 decode role):
    bit-exact with PIL on the same libjpeg, clean fallback on corrupt
    bytes and non-JPEG formats."""
    import io as _io
    from PIL import Image as PILImage
    from mxnet_tpu import image as mimg

    if mimg._native_jpeg() is None:
        pytest.skip("no native toolchain")
    rng = np.random.RandomState(0)
    raw = rng.randint(0, 255, (32, 48, 3)).astype(np.uint8)
    buf = _io.BytesIO()
    PILImage.fromarray(raw).save(buf, format="JPEG", quality=92)
    jpeg = buf.getvalue()
    nat = mimg._imdecode_native(jpeg, 1)
    assert nat is not None
    pil = np.asarray(PILImage.open(_io.BytesIO(jpeg)).convert("RGB"))
    np.testing.assert_array_equal(nat, pil)       # same libjpeg: bit-exact
    # grayscale request
    g = mimg._imdecode_native(jpeg, 0)
    assert g.shape[2] in (1, 3)
    # corrupt JPEG -> None (PIL path decides), never a crash
    assert mimg._imdecode_native(b"\xff\xd8not-a-real-jpeg" * 3, 1) is None
    # PNG is not claimed by the native path
    buf2 = _io.BytesIO()
    PILImage.fromarray(raw).save(buf2, format="PNG")
    assert mimg._imdecode_native(buf2.getvalue(), 1) is None
    # the public imdecode composes both paths
    np.testing.assert_array_equal(mimg.imdecode(jpeg).asnumpy(), pil)
    assert mimg.imdecode(buf2.getvalue()).shape == (32, 48, 3)


def test_vision_transforms_hue_gray_rotate():
    """RandomHue/RandomGray/Rotate/RandomRotation (reference:
    gluon/data/vision/transforms.py) — Rotate pinned against np.rot90."""
    import numpy as onp
    from mxnet_tpu.gluon.data.vision import transforms as T
    img = mx.nd.array(onp.random.RandomState(0).rand(8, 8, 3)
                      .astype(onp.float32))
    r = T.Rotate(90)(img).asnumpy()
    onp.testing.assert_allclose(
        r, onp.rot90(img.asnumpy(), 1, axes=(0, 1)), atol=1e-5)
    g = T.RandomGray(1.0)(img).asnumpy()
    onp.testing.assert_allclose(g[..., 0], g[..., 2])
    h = T.RandomHue(0.3)(img)
    assert h.shape == img.shape
    rr = T.RandomRotation((-45, 45))(img)
    assert rr.shape == img.shape
    # p=0 variants are identity
    onp.testing.assert_allclose(
        T.RandomRotation((-45, 45), rotate_with_proba=0.0)(img).asnumpy(),
        img.asnumpy())


def test_image_scale_down():
    """Reference docstring examples (src_size and size both (w, h))."""
    assert mx.image.scale_down((640, 480), (720, 120)) == (640, 106)
    assert mx.image.scale_down((360, 1000), (480, 500)) == (360, 375)
    assert mx.image.scale_down((100, 100), (50, 50)) == (50, 50)


def test_image_record_uint8_iter(tmp_path):
    """io.ImageRecordUInt8Iter: raw uint8 batches, normalization args
    refused (reference: the INT8 pipeline's input iterator)."""
    from mxnet_tpu import recordio
    from mxnet_tpu.io import ImageRecordUInt8Iter
    from mxnet_tpu.base import MXNetError
    rng = np.random.RandomState(0)
    prefix = str(tmp_path / "u8")
    w = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(8):
        img = (rng.rand(32, 32, 3) * 255).astype(np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i % 3), i, 0), img, quality=95))
    w.close()
    it = ImageRecordUInt8Iter(path_imgrec=prefix + ".rec",
                              data_shape=(3, 28, 28), batch_size=4)
    b = next(it)
    assert str(b.data[0].dtype) == "uint8"
    assert b.data[0].shape == (4, 3, 28, 28)
    assert int(b.data[0].asnumpy().max()) > 1    # raw pixels, not scaled
    with pytest.raises(MXNetError):
        ImageRecordUInt8Iter(path_imgrec=prefix + ".rec",
                             data_shape=(3, 28, 28), batch_size=4,
                             mean_r=123.0)


# -- recordio corruption policy (ISSUE 2 satellite) ---------------------------

@pytest.fixture
def _py_recordio(monkeypatch):
    """Pin the pure-python reader: corruption-policy tests must not
    depend on how the native parser classifies a torn tail."""
    monkeypatch.setattr(recordio, "_LIB", None)
    monkeypatch.setattr(recordio, "_LIB_TRIED", True)


def _write_rec(path, payloads):
    w = recordio.MXRecordIO(path, "w")
    for p in payloads:
        w.write(p)
    w.close()


def test_recordio_truncated_tail_names_uri_and_offset(_py_recordio,
                                                      tmp_path):
    """A tail torn by a mid-write crash raises OSError naming the file
    and the damaged record's byte offset; intact records still read."""
    path = str(tmp_path / "torn.rec")
    _write_rec(path, [b"alpha", b"beta", b"gamma-payload"])
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size - 6)               # tear into the last payload
    r = recordio.MXRecordIO(path, "r")
    assert r.read() == b"alpha"
    assert r.read() == b"beta"
    tail_offset = r.tell()
    with pytest.raises(OSError) as ei:
        r.read()
    msg = str(ei.value)
    assert path in msg and "byte offset %d" % tail_offset in msg
    assert "truncated" in msg
    r.close()


def test_recordio_corrupt_header_detected(_py_recordio, tmp_path):
    path = str(tmp_path / "bad.rec")
    _write_rec(path, [b"first", b"second"])
    with open(path, "r+b") as f:
        # last record = magic(4) + len(4) + b"second"(6) + pad(2)
        f.seek(-16, os.SEEK_END)
        f.write(b"\xde\xad\xbe\xef")       # stomp the record's magic
    r = recordio.MXRecordIO(path, "r")
    assert r.read() == b"first"
    with pytest.raises(OSError) as ei:
        r.read()
    assert "byte offset" in str(ei.value)
    r.close()


def test_recordio_tolerate_corrupt_skips_and_counts(_py_recordio, tmp_path,
                                                    monkeypatch):
    """MX_RECORDIO_TOLERATE_CORRUPT=1: the damaged tail reads as EOF,
    the skip is counted, and every intact record before it survives —
    the resume-over-a-damaged-file posture."""
    monkeypatch.setenv("MX_RECORDIO_TOLERATE_CORRUPT", "1")
    path = str(tmp_path / "tolerant.rec")
    _write_rec(path, [b"keep-1", b"keep-2", b"doomed-payload"])
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 5)
    r = recordio.MXRecordIO(path, "r")
    with pytest.warns(UserWarning, match="skipping"):
        got = []
        while True:
            x = r.read()
            if x is None:
                break
            got.append(x)
    assert got == [b"keep-1", b"keep-2"]
    assert r.corrupt_skipped == 1
    assert r.read() is None                # stays EOF, count stays 1
    assert r.corrupt_skipped == 1
    r.reset()                              # new pass: latch cleared,
    assert r.read() == b"keep-1"           # damage re-detected once
    assert r.read() == b"keep-2"
    with pytest.warns(UserWarning, match="skipping"):
        assert r.read() is None
    assert r.corrupt_skipped == 2
    r.close()


def test_indexed_recordio_tolerate_survives_one_bad_record(
        _py_recordio, tmp_path, monkeypatch):
    """Random access: one tolerated bad record must not latch the
    reader into EOF for every other (intact) key — seek clears it."""
    monkeypatch.setenv("MX_RECORDIO_TOLERATE_CORRUPT", "1")
    rec, idx = str(tmp_path / "i.rec"), str(tmp_path / "i.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(3):
        w.write_idx(i, b"payload-%d" % i)
    w.close()
    r = recordio.MXIndexedRecordIO(idx, rec, "r")
    with open(rec, "r+b") as f:
        f.seek(r.idx[1])
        f.write(b"\xde\xad\xbe\xef")       # stomp record 1's magic
    assert r.read_idx(0) == b"payload-0"
    with pytest.warns(UserWarning, match="skipping"):
        assert r.read_idx(1) is None       # the bad record: skipped
    assert r.corrupt_skipped == 1
    assert r.read_idx(2) == b"payload-2"   # intact keys still readable
    assert r.read_idx(0) == b"payload-0"
    r.close()


# -- PrefetchingIter lifecycle (ISSUE 2 satellite) ----------------------------

def _tiny_iter(n=8, batch=4):
    return mio.NDArrayIter(np.zeros((n, 2), np.float32),
                           np.zeros(n, np.float32), batch_size=batch)


def test_prefetching_iter_close_is_idempotent_and_final():
    p = mio.PrefetchingIter(_tiny_iter())
    assert p.next() is not None
    p.close()
    p.close()                              # idempotent
    assert p._pool._shutdown               # threads released, not leaked
    with pytest.raises(mx.MXNetError):
        p.next()
    with pytest.raises(mx.MXNetError):
        p.reset()


def test_prefetching_iter_context_manager():
    with mio.PrefetchingIter(_tiny_iter()) as p:
        n = sum(1 for _ in p)
    assert n == 2
    assert p._pool._shutdown


def test_prefetching_iter_names_failing_inner_iterator():
    class Boom(mio.DataIter):
        def __init__(self):
            super().__init__(batch_size=4)

        def next(self):
            raise ValueError("kaput")

    p = mio.PrefetchingIter([_tiny_iter(), Boom()])
    try:
        with pytest.raises(mx.MXNetError) as ei:
            p.next()
        assert "inner iterator 1" in str(ei.value)
        assert "Boom" in str(ei.value)
        assert isinstance(ei.value.__cause__, ValueError)  # chained
    finally:
        p.close()
