"""Runtime features, memory info, launcher, multi-process init.

Reference pattern: tests/python/unittest/test_runtime.py (feature_list/
is_enabled) and the §4.5 trick of exercising distributed wiring with local
processes (tests/nightly/dist_sync_kvstore.py's launcher pattern).
"""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import runtime

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_feature_list_and_is_enabled():
    feats = runtime.feature_list()
    names = {f.name for f in feats}
    assert {"XLA", "BF16", "RECORDIO", "PROFILER", "DIST_KVSTORE"} <= names
    fs = runtime.features()
    assert fs.is_enabled("XLA") is True
    assert fs.is_enabled("CUDA") is False          # TPU build
    assert fs.is_enabled("xla") is True            # case-insensitive
    with pytest.raises(RuntimeError):
        fs.is_enabled("NO_SUCH_FEATURE")
    assert "✔" in repr(fs["XLA"])


def test_native_recordio_feature_reflects_build():
    fs = runtime.features()
    from mxnet_tpu import recordio
    assert fs.is_enabled("NATIVE_RECORDIO") == \
        (recordio._get_lib() is not None)


def test_memory_info_soft_zero_on_cpu():
    free, total = mx.tpu_memory_info(0)
    assert free >= 0 and total >= 0      # CPU backend: no stats -> (0, 0)
    assert mx.gpu_memory_info(0) == (free, total)


def test_launch_local_sets_env_contract(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent("""
        import os, sys
        rank = os.environ["MX_PROCESS_ID"]
        n = os.environ["MX_NUM_PROCESSES"]
        coord = os.environ["MX_COORDINATOR"]
        assert os.environ["DMLC_ROLE"] == "worker"
        assert os.environ["DMLC_NUM_WORKER"] == n
        print("rank %s/%s at %s" % (rank, n, coord), flush=True)
    """))
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "launch.py"),
                        "-n", "2", "--launcher", "local", "--",
                        sys.executable, str(script)],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    out = r.stdout
    assert "rank 0/2" in out and "rank 1/2" in out


def test_launch_manual_prints_plan():
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "launch.py"),
                        "-n", "3", "--launcher", "manual", "--",
                        "python", "train.py"],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0
    assert r.stdout.count("MX_PROCESS_ID") == 3


def test_init_process_group_two_processes(tmp_path):
    """SURVEY §4.5: real 2-process jax.distributed init on localhost —
    the multi-host wiring the reference tests with local PS processes."""
    script = tmp_path / "dist_worker.py"
    script.write_text(textwrap.dedent("""
        import os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["MX_FORCE_CPU"] = "1"
        sys.path.insert(0, %r)
        import mxnet_tpu as mx           # pins cpu before backend init
        from mxnet_tpu.parallel import init_process_group
        init_process_group()             # reads MX_* env from launch.py
        import jax
        assert jax.process_count() == 2, jax.process_count()
        assert len(jax.devices()) == 2   # one cpu device per process
        from jax.experimental import multihost_utils
        import numpy as np
        mine = np.array([float(jax.process_index())], np.float32)
        every = multihost_utils.process_allgather(mine)
        assert sorted(every.ravel().tolist()) == [0.0, 1.0], every
        print("dist ok rank", jax.process_index(), flush=True)
    """) % REPO)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)           # forced 8-dev count breaks pairing
    # Fail the handshake fast (60s) so a raced port retries with a fresh
    # one instead of hanging out the whole test budget; 3 attempts.
    env["MX_INIT_TIMEOUT"] = "60"
    r = None
    for attempt in range(3):   # retry: the free-port pick can race
        try:
            r = subprocess.run([sys.executable,
                                os.path.join(REPO, "tools", "launch.py"),
                                "-n", "2", "--launcher", "local", "--",
                                sys.executable, str(script)],
                               capture_output=True, text=True, timeout=240,
                               env=env)
        except subprocess.TimeoutExpired:
            continue           # hung handshake: fresh port next attempt
        if r.returncode == 0 and "dist ok rank 0" in r.stdout \
                and "dist ok rank 1" in r.stdout:
            break
    assert r is not None, "every attempt hung out its timeout"
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "dist ok rank 0" in r.stdout and "dist ok rank 1" in r.stdout


def test_launch_preserves_inner_separator(tmp_path):
    script = tmp_path / "echoargs.py"
    script.write_text("import sys; print('ARGS:' + '|'.join(sys.argv[1:]))")
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "launch.py"),
                        "-n", "1", "--launcher", "local", "--",
                        sys.executable, str(script), "--", "--data", "x"],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0
    assert "ARGS:--|--data|x" in r.stdout


def test_util_and_context_modules():
    """mx.util + mx.context compatibility surface (reference:
    python/mxnet/util.py, python/mxnet/context.py)."""
    import mxnet_tpu as mx
    assert mx.context.Context is mx.Context
    assert mx.context.cpu(0) == mx.cpu(0)
    assert mx.util.getenv("MXNET_ENGINE_TYPE") is not None
    mx.util.setenv("MX_UTIL_TEST", "1")
    assert mx.util.getenv("MX_UTIL_TEST") == "1"
    mx.util.setenv("MX_UTIL_TEST", None)

    @mx.util.use_np
    def f(x):
        return mx.np.sqrt(x)
    out = f(mx.np.array([9.0]))
    assert out.asnumpy().tolist() == [3.0]
    assert not mx.util.is_np_array()   # flag restored by the scope
    with mx.util.np_shape():
        assert mx.util.is_np_shape()
    # deactivating scope + exact restore of both flags
    from mxnet_tpu import npx
    npx.set_np(shape=True, array=False)
    with mx.util.np_array(False):
        assert not mx.util.is_np_array()
    assert mx.util.is_np_shape() and not mx.util.is_np_array()
    npx.reset_np()
    from mxnet_tpu.context import Context as CtxImport
    assert CtxImport is mx.Context
    assert mx.util.get_gpu_count() >= 0


def test_standing_tools_exit_clean():
    """The reference-mount verifier and the op-inventory audit must stay
    runnable (they activate for real when /root/reference materializes)."""
    import json
    for tool in ("verify_against_reference.py", "op_inventory.py"):
        r = subprocess.run([sys.executable,
                            os.path.join(REPO, "tools", tool)],
                           capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, (tool, r.stderr[-500:])
    rec = json.loads(subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "op_inventory.py")],
        capture_output=True, text=True, timeout=300).stdout)
    assert rec["ours"]["unique_impls"] >= 700


def test_env_docs_in_sync():
    """docs/ENV_VARS.md is generated from ENV_CATALOG; adding a flag
    without regenerating (tools/gen_env_docs.py) fails here."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import gen_env_docs
    with open(os.path.join(REPO, "docs", "ENV_VARS.md")) as f:
        assert f.read() == gen_env_docs.render()
