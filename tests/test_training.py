"""End-to-end convergence tests (reference: tests/python/train/test_mlp.py —
'does SGD still converge' safety net; BASELINE config 0 gate: Gluon MLP
imperative + hybridized)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.data import DataLoader
from mxnet_tpu.gluon.data.vision import SyntheticImageDataset
from mxnet_tpu.gluon.data.vision.transforms import ToTensor, Compose


def _train_mlp(hybridize: bool, epochs=3):
    np.random.seed(7)
    mx.random.seed(7)
    train_set = SyntheticImageDataset(num_samples=512, shape=(8, 8, 1),
                                      num_classes=10, noise=0.25)
    test_set = SyntheticImageDataset(num_samples=256, shape=(8, 8, 1),
                                     num_classes=10, seed=99, noise=0.25)
    to_tensor = ToTensor()
    train_data = DataLoader(train_set.transform_first(to_tensor),
                            batch_size=64, shuffle=True)
    test_data = DataLoader(test_set.transform_first(to_tensor), batch_size=64)

    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"), nn.Dense(10))
    net.initialize(mx.init.Xavier())
    if hybridize:
        net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for _ in range(epochs):
        for data, label in train_data:
            with autograd.record():
                out = net(data)
                loss = loss_fn(out, label)
            loss.backward()
            trainer.step(data.shape[0])

    metric = mx.metric.Accuracy()
    for data, label in test_data:
        metric.update([label], [net(data)])
    return metric.get()[1]


def test_mlp_converges_imperative():
    acc = _train_mlp(hybridize=False)
    assert acc > 0.95, "imperative MLP failed to converge: acc=%s" % acc


def test_mlp_converges_hybridized():
    acc = _train_mlp(hybridize=True)
    assert acc > 0.95, "hybridized MLP failed to converge: acc=%s" % acc


def test_conv_net_trains():
    """Small CNN loss decreases (reference: tests/python/train/test_conv.py)."""
    np.random.seed(3)
    mx.random.seed(3)
    ds = SyntheticImageDataset(num_samples=128, shape=(8, 8, 1),
                               num_classes=4, noise=0.2)
    data = DataLoader(ds.transform_first(ToTensor()), batch_size=32,
                      shuffle=True)
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1, activation="relu"),
            nn.MaxPool2D(),
            nn.Flatten(),
            nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    first_loss = last_loss = None
    for _ in range(4):
        for x, y in data:
            with autograd.record():
                loss = loss_fn(net(x), y).mean()
            loss.backward()
            trainer.step(1)
            val = float(loss.asscalar())
            if first_loss is None:
                first_loss = val
            last_loss = val
    assert last_loss < first_loss * 0.5, (first_loss, last_loss)


def test_dataloader_shapes_and_shuffle():
    ds = SyntheticImageDataset(num_samples=100, shape=(4, 4, 1))
    dl = DataLoader(ds, batch_size=32, shuffle=True, last_batch="keep")
    batches = list(dl)
    assert len(batches) == 4
    assert batches[0][0].shape == (32, 4, 4, 1)
    assert batches[-1][0].shape == (4, 4, 4, 1)
    dl2 = DataLoader(ds, batch_size=32, last_batch="discard")
    assert len(list(dl2)) == 3


def test_dataloader_workers():
    ds = SyntheticImageDataset(num_samples=64, shape=(4, 4, 1))
    dl = DataLoader(ds, batch_size=16, num_workers=2)
    seen = 0
    for x, y in dl:
        seen += x.shape[0]
    assert seen == 64


def test_datasets_transform_chain():
    ds = SyntheticImageDataset(num_samples=10, shape=(8, 8, 1))
    tf = Compose([ToTensor()])
    out = ds.transform_first(tf)[0]
    x, y = out
    assert x.shape == (1, 8, 8)
    assert float(x.asnumpy().max()) <= 1.0
