"""ISSUE 5: quantized, overlap-scheduled gradient exchange.

Pins the tentpole layers:
  * int8 per-block quantize→dequantize error bounds (error <= scale/2
    per element, scale = max|block|/127) across block sizes;
  * error-feedback accumulation identity — over K steps the sum of
    dequantized payloads + the final residual equals the sum of true
    gradients (gradient mass is delayed, never lost) for int8 AND 2bit;
  * device/host packed-2bit wire-format bit parity;
  * the EQuARX-style dequant-sum-requant collective merge body;
  * the compact dist_async wire codec (QGRAD tuples) end-to-end over a
    real TCP server, server-side dequantize before the accumulator;
  * overlap scheduling — readiness planner unit closing, reverse-packed
    bucket order, hook firing order (late layers first), overlap ==
    serialized parity through a real 2-device Trainer fit, and the
    relaunch-on-rewrite guard;
  * loss-trajectory parity: int8/2bit-compressed DP training tracks the
    fp32 trajectory within documented tolerance.
"""
import socket
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.engine import engine
from mxnet_tpu.gluon import nn
from mxnet_tpu.ops import quantization as qops

import jax.numpy as jnp


# ---------------------------------------------------------------------------
# int8 kernels
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("block", [16, 64, 256])
@pytest.mark.parametrize("n", [16, 100, 1000])
def test_int8_roundtrip_error_bound_per_block(block, n):
    """|x - dequant(quant(x))| <= scale/2 per element, where scale is the
    per-block max|x|/127 — the symmetric-quantization bound."""
    rng = np.random.RandomState(block * 1000 + n)
    x = (rng.randn(n) * rng.uniform(0.1, 10)).astype(np.float32)
    q, scales, res = qops.quantize_int8_blocks(
        jnp.asarray(x), jnp.zeros((n,)), block)
    deq = np.asarray(qops.dequantize_int8_blocks(q, scales, n))
    nb = -(-n // block)
    assert np.asarray(q).shape == (nb * block,)
    assert np.asarray(scales).shape == (nb,)
    pad = np.zeros(nb * block, np.float32)
    pad[:n] = np.abs(x)
    per_block_scale = pad.reshape(nb, block).max(axis=1) / 127.0
    bound = np.repeat(per_block_scale, block)[:n] / 2 + 1e-7
    assert np.all(np.abs(deq - x) <= bound), np.abs(deq - x).max()
    # the residual is exactly the error (error feedback's carry)
    np.testing.assert_allclose(np.asarray(res), x - deq, atol=1e-6)


def test_int8_wire_bytes_accounting():
    # 1000 elems, block 256 -> 4 blocks: 1024 padded codes + 4 f32 scales
    assert qops.int8_wire_bytes(1000, 256) == 1024 + 16
    assert qops.two_bit_wire_bytes(50) == 4 * 4 + 4   # 4 words + threshold
    # the acceptance ratio: >= 3.5x fewer bytes than fp32 at default block
    n = 1 << 20
    assert 4 * n / qops.int8_wire_bytes(n, 256) > 3.5


@pytest.mark.parametrize("mode", ["int8", "2bit"])
def test_error_feedback_accumulation_identity(mode):
    """sum(dequantized payloads) + final residual == sum(true grads):
    quantization error is carried, never lost."""
    from mxnet_tpu.kvstore.gradient_compression import GradientCompression
    gc = GradientCompression(type=mode, threshold=0.5, block=16)
    rng = np.random.RandomState(7)
    n = 100
    grads = [(rng.randn(n) * 0.2).astype(np.float32) for _ in range(12)]
    emitted = np.zeros(n, np.float32)
    for g in grads:
        emitted += np.asarray(gc.quantize("k", jnp.asarray(g)))
    residual = np.asarray(gc._residuals["k"])
    np.testing.assert_allclose(emitted + residual, np.sum(grads, axis=0),
                               rtol=1e-4, atol=1e-4)


def test_residual_rolls_on_shape_change():
    from mxnet_tpu.kvstore.gradient_compression import GradientCompression
    gc = GradientCompression(type="int8", block=16)
    gc.quantize("k", jnp.ones((32,)))
    assert gc._residuals["k"].shape == (32,)
    gc.quantize("k", jnp.ones((16,)))    # layout change: fresh residual
    assert gc._residuals["k"].shape == (16,)


def test_dequant_sum_requant_merge():
    """The collective merge body: dequantize each worker's payload at its
    own scales, sum, requantize — result tracks the true sum within the
    merged scale's quantization step."""
    rng = np.random.RandomState(3)
    block, nb, w = 32, 4, 3
    xs = [(rng.randn(nb * block) * (i + 1)).astype(np.float32)
          for i in range(w)]
    qs, ss = [], []
    for x in xs:
        q, s, _ = qops.quantize_int8_blocks(jnp.asarray(x), jnp.zeros_like(
            jnp.asarray(x)), block)
        qs.append(np.asarray(q))
        ss.append(np.asarray(s))
    qo, so = qops.dequant_sum_requant_int8(
        jnp.asarray(np.stack(qs)), jnp.asarray(np.stack(ss)))
    merged = np.asarray(qops.dequantize_int8_blocks(qo, so, nb * block))
    true = np.sum(xs, axis=0)
    # two quantizations deep: per-worker error + requant error
    per_in = np.stack([np.repeat(s, block) for s in ss]).sum(axis=0) / 2
    bound = per_in + np.repeat(np.asarray(so), block) / 2 + 1e-6
    assert np.all(np.abs(merged - true) <= bound)


def test_pack_2bit_device_host_bit_parity():
    """ops.quantization.pack_2bit_words must emit the exact words the
    host-side pack_2bit does (the PS wire is decoded host-side)."""
    from mxnet_tpu.kvstore.gradient_compression import pack_2bit, unpack_2bit
    t = 0.25
    rng = np.random.RandomState(1)
    levels = rng.choice([-t, 0.0, t], size=53).astype(np.float32)
    dev = np.asarray(qops.pack_2bit_words(jnp.asarray(levels)))
    host = pack_2bit(levels, t)
    np.testing.assert_array_equal(dev, host)
    back_dev = np.asarray(qops.unpack_2bit_words(jnp.asarray(dev), t, 53))
    np.testing.assert_allclose(back_dev, levels)
    np.testing.assert_allclose(unpack_2bit(dev, 53, t), levels)


# ---------------------------------------------------------------------------
# compression config + wire codec
# ---------------------------------------------------------------------------

def test_set_gradient_compression_contract():
    from mxnet_tpu import kvstore
    kv = kvstore.create("local")
    with pytest.raises(ValueError, match="1bit"):
        kv.set_gradient_compression({"type": "1bit"})
    kv.set_gradient_compression({"type": "int8", "block": 64})
    assert kv._gc.type == "int8" and kv._gc.block == 64
    assert kv._gc.get_params()["block"] == 64
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.25})
    assert kv._gc.type == "2bit" and kv._gc.threshold == 0.25
    kv.set_gradient_compression({"type": "bf16"})
    assert kv._gc is None and kv._compress_bf16
    with pytest.raises(ValueError):
        from mxnet_tpu.kvstore.gradient_compression import \
            GradientCompression
        GradientCompression(type="bf16")     # cast path, not GC state


@pytest.mark.parametrize("mode", ["int8", "2bit"])
def test_wire_codec_roundtrip(mode):
    from mxnet_tpu.kvstore import gradient_compression as gcomp
    gc = gcomp.GradientCompression(type=mode, threshold=0.5, block=16)
    rng = np.random.RandomState(11)
    x = rng.randn(5, 7).astype(np.float32)
    wire = gc.encode("k", jnp.asarray(x))
    assert gcomp.is_wire_payload(wire)
    assert not gcomp.is_wire_payload(x)
    deq = gcomp.decode_wire(wire)
    assert deq.shape == (5, 7) and deq.dtype == np.float32
    # the decoded payload is the quantized view of x (error in residual)
    residual = np.asarray(gc._residuals["k"]).reshape(5, 7)
    np.testing.assert_allclose(deq + residual, x, rtol=1e-4, atol=1e-4)
    # compact: int8 ~1B/elem + scales; 2bit ~2 bits/elem
    payload = wire[5]
    nbytes = len(payload) if isinstance(payload, bytes) else payload.nbytes
    assert nbytes < x.size * 4


# ---------------------------------------------------------------------------
# collective (ici) quantized exchange
# ---------------------------------------------------------------------------

def test_ici_int8_bucketed_exchange_tracks_true_sum():
    """Single-process ici store, int8: the batched push/pull quantizes
    per bucket (one residual per bucket name) and the pulled values track
    the true per-key gradients within the block quantization error."""
    from mxnet_tpu import kvstore
    kv = kvstore.create("ici")
    kv.set_gradient_compression({"type": "int8", "block": 64})
    keys = list(range(6))
    shapes = [(16,), (8, 8), (32,), (4, 4), (64,), (2,)]
    for k, s in zip(keys, shapes):
        kv.init(k, nd.zeros(s))
    rng = np.random.RandomState(0)
    grads = [nd.array(rng.randn(*s).astype(np.float32)) for s in shapes]
    w0 = engine.wire_bytes
    kv.push(keys, [[g] for g in grads])
    outs = [nd.zeros(s) for s in shapes]
    kv.pull(keys, outs)
    wire = engine.wire_bytes - w0
    total = sum(int(np.prod(s)) for s in shapes)
    assert wire < total * 4, (wire, total * 4)     # compressed on the wire
    for g, o in zip(grads, outs):
        g = g.asnumpy()
        err = np.abs(o.asnumpy() - g)
        assert err.max() <= np.abs(g).max() / 127 + 1e-6, err.max()


def test_ici_2bit_exchange_emits_levels():
    from mxnet_tpu import kvstore
    kv = kvstore.create("ici")
    t = 0.5
    kv.set_gradient_compression({"type": "2bit", "threshold": t})
    kv.init("k", nd.zeros((8,)))
    g = nd.array(np.array([0.7, -0.7, 0.1, -0.1, 0.0, 2.0, -2.0, 0.4],
                          np.float32))
    kv.push("k", g)
    out = nd.zeros((8,))
    kv.pull("k", out=out)
    assert set(np.round(np.unique(out.asnumpy()), 5)) <= {-t, 0.0, t}


# ---------------------------------------------------------------------------
# dist_async compact wire over a real server
# ---------------------------------------------------------------------------

def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _start_server(port):
    from mxnet_tpu.kvstore.server import serve_forever
    t = threading.Thread(target=serve_forever,
                         kwargs=dict(port=port, num_workers=1), daemon=True)
    t.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port), timeout=0.2).close()
            return t
        except OSError:
            time.sleep(0.05)
    raise RuntimeError("server did not come up on %d" % port)


@pytest.fixture
def _dist_async_client(monkeypatch):
    from mxnet_tpu.kvstore.kvstore import KVStoreDistAsync
    monkeypatch.setenv("MX_KVSTORE_HEARTBEAT", "0")
    monkeypatch.delenv("MX_PS_ROOTS", raising=False)
    port = _free_port()
    _start_server(port)
    monkeypatch.setenv("MX_PS_ROOT", "127.0.0.1:%d" % port)
    kv = KVStoreDistAsync()
    yield kv
    kv.stop_server()


@pytest.mark.parametrize("mode", ["int8", "2bit"])
def test_dist_async_compressed_wire_roundtrip(_dist_async_client, mode):
    """PUSH ships the compact QGRAD tuple; the server dequantizes before
    its accumulator, so PULL returns full-width values tracking the true
    gradient within the mode's quantization error."""
    kv = _dist_async_client
    kv.set_gradient_compression({"type": mode, "threshold": 0.5,
                                 "block": 16})
    rng = np.random.RandomState(5)
    # 2bit emits at most +-threshold per push: keep |g| under the
    # threshold (the reference's tuning contract) so error feedback can
    # keep the cumulative sum in its +-(t + |g|max) band
    g = (rng.randn(6, 6) * 0.15).astype(np.float32)
    kv.init("w", nd.zeros((6, 6)))
    w0 = engine.wire_bytes
    kv.push("w", nd.array(g))
    wire = engine.wire_bytes - w0
    assert 0 < wire < g.nbytes                      # compact on the wire
    out = nd.zeros((6, 6))
    kv.pull("w", out=out)
    got = out.asnumpy()
    if mode == "int8":
        assert np.abs(got - g).max() <= np.abs(g).max() / 127 + 1e-6
    else:
        assert set(np.round(np.unique(got), 5)) <= {-0.5, 0.0, 0.5}
    # error feedback across pushes: the cumulative pulled sum stays in
    # the +-(threshold + |g|max) band of the true sum (2bit) / within
    # the accumulated block-quantization error (int8)
    for _ in range(10):
        kv.push("w", nd.array(g))
    kv.pull("w", out=out)
    total = out.asnumpy()
    atol = (0.5 + np.abs(g).max() if mode == "2bit"
            else np.abs(g).max() / 127 * 11) + 1e-5
    np.testing.assert_allclose(total, 11 * g, atol=atol)


def test_dist_async_bucketed_compressed_push(_dist_async_client,
                                             monkeypatch):
    """Fusion buckets + compression: ONE compact wire tuple per bucket."""
    monkeypatch.setenv("MX_KVSTORE_BUCKET_KB", "1")
    kv = _dist_async_client
    kv.set_gradient_compression({"type": "int8", "block": 16})
    keys = [0, 1, 2]
    shapes = [(8, 8), (16,), (8, 8)]
    for k, s in zip(keys, shapes):
        kv.init(k, nd.zeros(s))
    rng = np.random.RandomState(2)
    grads = [nd.array(rng.randn(*s).astype(np.float32)) for s in shapes]
    kv.push(keys, grads)
    assert kv._bucket_inited                        # buckets went out
    outs = [nd.zeros(s) for s in shapes]
    kv.pull(keys, outs)
    for g, o in zip(grads, outs):
        g = g.asnumpy()
        assert np.abs(o.asnumpy() - g).max() <= np.abs(g).max() / 127 + 1e-6


# ---------------------------------------------------------------------------
# overlap scheduling
# ---------------------------------------------------------------------------

def test_readiness_planner_reverse_buckets_close_in_production_order():
    from mxnet_tpu.kvstore.bucketing import ReadinessPlanner, plan_buckets
    keys = list(range(6))
    shapes = [(8,)] * 6
    buckets, solo = plan_buckets(keys, shapes, ["float32"] * 6, [4] * 6,
                                 ["default"] * 6, max_bytes=64,
                                 reverse=True)
    # reverse packing: bucket 0 holds the LAST params (backward's first)
    assert [sorted(b.positions) for b in buckets] == [[4, 5], [2, 3],
                                                      [0, 1]]
    planner = ReadinessPlanner(buckets, solo)
    closed = []
    for pos in reversed(keys):          # backward production order
        closed.extend(planner.note(pos))
    assert closed == [0, 1, 2]          # units close in launch order
    assert planner.pending() == []
    assert not planner.stale


def test_readiness_planner_copies_and_stale():
    from mxnet_tpu.kvstore.bucketing import Bucket, ReadinessPlanner
    b = Bucket(0, [0, 1], ["a", "b"], [4, 4], [(4,), (4,)], "float32")
    p = ReadinessPlanner([b], [2], copies=2)
    assert p.note(0, 0) == [] and p.note(0, 1) == []   # 1 of 2 members
    assert p.note(1, 0) == []
    assert p.note(1, 1) == [0]                         # bucket closes
    assert p.note(2, 0) == [] and p.note(2, 1) == [1]  # solo unit
    assert not p.stale
    assert p.note(0, 0) == [] and p.stale              # double event
    # unknown positions are ignored (params outside the exchange set)
    assert p.note(99) == []


def test_backward_fires_grad_hooks_late_layers_first():
    """Incremental leaf finalization: each grad hook fires exactly once,
    the grad is FINAL at hook time, and layers closer to the head
    finalize first — the order reverse-packed buckets rely on."""
    mx.random.seed(0)
    net = nn.Sequential()
    net.add(nn.Dense(8, in_units=4, activation="relu"))
    net.add(nn.Dense(8, in_units=8, activation="relu"))
    net.add(nn.Dense(2, in_units=8))
    net.initialize(mx.init.Xavier())
    params = list(net.collect_params().values())
    x = nd.array(np.random.RandomState(0).randn(4, 4).astype(np.float32))
    with autograd.record():
        loss = net(x).sum()
    fired = []
    for i, p in enumerate(params):
        g = p.list_grad()[0]
        g._grad_hook = (lambda i=i, g=g:
                        fired.append((i, np.asarray(g._jax).copy())))
    try:
        loss.backward()
    finally:
        for p in params:
            p.list_grad()[0]._grad_hook = None
    assert sorted(i for i, _ in fired) == list(range(len(params)))
    # grad value at hook time == final grad (finality)
    for i, snap in fired:
        np.testing.assert_array_equal(
            snap, np.asarray(params[i].list_grad()[0]._jax))
    # the LAST layer's params finalize before the first layer's
    order = [i for i, _ in fired]
    assert order.index(len(params) - 1) < order.index(0)


def _fit_two_device(compress=None, steps=4, rewrite_grads=False):
    mx.random.seed(0)
    ctxs = [mx.cpu(0), mx.cpu(1)]
    net = nn.Sequential()
    net.add(nn.Dense(16, in_units=8, activation="relu"))
    net.add(nn.Dense(4, in_units=16))
    net.initialize(mx.init.Xavier(), ctx=ctxs)
    params = list(net.collect_params().values())
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.05},
                       kvstore="device", compression_params=compress)
    loss_fn = gluon.loss.L2Loss()
    rng = np.random.RandomState(0)
    X = rng.randn(8, 8).astype(np.float32)
    Y = rng.randn(8, 4).astype(np.float32)
    losses = []
    for _ in range(steps):
        tot = 0.0
        with autograd.record():
            for ctx, sl in zip(ctxs, (slice(0, 4), slice(4, None))):
                loss = loss_fn(net(nd.array(X[sl], ctx=ctx)),
                               nd.array(Y[sl], ctx=ctx))
                loss.backward()
                tot += float(loss.mean().asnumpy())
        if rewrite_grads:
            # out-of-band mutation AFTER backward (and after any armed
            # overlap launches): halve every gradient
            for p in params:
                for g in p.list_grad():
                    g._set_jax(g._jax * 0.5)
        tr.step(batch_size=8)
        losses.append(tot)
    return losses, {k: v.data(ctxs[0]).asnumpy()
                    for k, v in net.collect_params().items()}


@pytest.mark.parametrize("compress", [None, {"type": "int8"}])
def test_overlap_matches_serialized_exchange(monkeypatch, compress):
    """MX_EXCHANGE_OVERLAP=1 is a pure scheduling change: params after a
    multi-step 2-device fit equal the serialized exchange bit-for-bit
    modulo fp accumulation order (same dispatches, earlier)."""
    monkeypatch.setenv("MX_EXCHANGE_OVERLAP", "0")
    _, base = _fit_two_device(compress=compress)
    monkeypatch.setenv("MX_EXCHANGE_OVERLAP", "1")
    _, overlapped = _fit_two_device(compress=compress)
    assert set(base) == set(overlapped)
    for k in base:
        np.testing.assert_allclose(overlapped[k], base[k],
                                   rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("compress", [None, {"type": "int8"},
                                      {"type": "2bit", "threshold": 0.05}])
def test_overlap_relaunches_on_grad_rewrite(monkeypatch, compress):
    """A gradient rewritten between backward and step() (manual grad
    scaling) invalidates the launched exchange: the snapshot guard
    relaunches the unit — and with compression on, the relaunch first
    ROLLS BACK the discarded launch's error-feedback step — so overlap
    matches the serialized result exactly."""
    monkeypatch.setenv("MX_EXCHANGE_OVERLAP", "0")
    _, base = _fit_two_device(compress=compress, rewrite_grads=True)
    monkeypatch.setenv("MX_EXCHANGE_OVERLAP", "1")
    _, overlapped = _fit_two_device(compress=compress, rewrite_grads=True)
    for k in base:
        np.testing.assert_allclose(overlapped[k], base[k],
                                   rtol=1e-5, atol=1e-6)


def test_session_relaunch_rolls_back_error_feedback():
    """Session-level EF rollback: launch a unit, rewrite its input,
    drain.  The relaunch must quantize the NEW value against the
    PRE-launch residual — the discarded payload's EF step un-happens, so
    the pulled value + residual account for exactly the committed
    gradient (no mass lost, no double-stepped residual)."""
    from mxnet_tpu import kvstore
    kv = kvstore.create("ici")
    kv.set_gradient_compression({"type": "int8", "block": 16})
    kv.init("k", nd.zeros((32,)))
    rng = np.random.RandomState(0)
    g = nd.array(rng.randn(32).astype(np.float32))
    sess = kv.begin_exchange(["k"], [[g]])
    sess.notify_key("k")                       # launches (consumes EF)
    true_committed = 0.5 * g.asnumpy()
    g._set_jax(g._jax * 0.5)                   # rewrite after launch
    sess.drain()                               # must rollback + relaunch
    out = nd.zeros((32,))
    kv.pull("k", out=out)
    residual = np.asarray(kv._gc._residuals["k"])
    np.testing.assert_allclose(out.asnumpy() + residual, true_committed,
                               rtol=1e-5, atol=1e-6)
    # donation resumes after commit (no pins left behind)
    assert not kv._gc._pinned


def test_overlap_residual_wire_keys_stable_across_steps(monkeypatch):
    """With overlap enabled, the first step's serialized fallback runs
    through the session machinery too, so every step quantizes under the
    SAME reverse-packed bucket names — no orphaned error-feedback
    residual (and no silently dropped compression error) at the
    serialized→overlapped transition."""
    from mxnet_tpu.kvstore import create as kv_create
    monkeypatch.setenv("MX_EXCHANGE_OVERLAP", "1")
    mx.random.seed(0)
    ctxs = [mx.cpu(0), mx.cpu(1)]
    net = nn.Sequential()
    net.add(nn.Dense(8, in_units=4, activation="relu"))
    net.add(nn.Dense(2, in_units=8))
    net.initialize(mx.init.Xavier(), ctx=ctxs)
    kv = kv_create("ici")
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=kv,
                       compression_params={"type": "int8"})
    loss_fn = gluon.loss.L2Loss()
    rng = np.random.RandomState(0)
    X = rng.randn(8, 4).astype(np.float32)
    Y = rng.randn(8, 2).astype(np.float32)
    key_sets = []
    for _ in range(3):
        with autograd.record():
            for ctx, sl in zip(ctxs, (slice(0, 4), slice(4, None))):
                loss_fn(net(nd.array(X[sl], ctx=ctx)),
                        nd.array(Y[sl], ctx=ctx)).backward()
        tr.step(batch_size=8)
        key_sets.append(frozenset(kv._gc._residuals))
    assert key_sets[0] == key_sets[1] == key_sets[2], key_sets
    # the keys are bucket names (per-bucket residuals, not per-param)
    assert all(str(k).startswith("__fusedb")
               for k in key_sets[0]), key_sets[0]


def test_ici_sparse_push_survives_wire_accounting():
    """row_sparse payloads (no _jax, nnz-keyed) must pass through the
    ici store's wire accounting and int8 gates untouched — with and
    without compression installed (the supported sparse flow: a
    store-side updater applies the sparse gradient)."""
    from mxnet_tpu import kvstore
    from mxnet_tpu import optimizer as opt
    for compress in (None, {"type": "int8"}):
        kv = kvstore.create("ici")
        if compress:
            kv.set_gradient_compression(compress)
        kv.set_optimizer(opt.create("sgd", learning_rate=1.0))
        dense = nd.array(np.eye(4, 3, dtype=np.float32))
        w0 = np.ones((4, 3), np.float32)
        kv.init(0, nd.array(w0))
        r = dense.tostype("row_sparse")
        kv.push([0], [[r]])                  # must not crash
        out = nd.zeros((4, 3))
        kv.pull([0], [out])
        # sgd lr=1: w = w0 - grad
        np.testing.assert_allclose(out.asnumpy(),
                                   w0 - dense.asnumpy(), atol=1e-5)


def test_overlap_grad_req_flip_between_steps(monkeypatch):
    """Unfreezing a param between steps changes the exchange key set: the
    armed session no longer covers it, must be discarded (EF state rolled
    back), and the newly trainable param's gradients still exchange —
    params match the serialized path exactly."""
    def run(overlap):
        monkeypatch.setenv("MX_EXCHANGE_OVERLAP", overlap)
        mx.random.seed(0)
        ctxs = [mx.cpu(0), mx.cpu(1)]
        net = nn.Sequential()
        net.add(nn.Dense(8, in_units=4, activation="relu"))
        net.add(nn.Dense(2, in_units=8))
        net.initialize(mx.init.Xavier(), ctx=ctxs)
        params = list(net.collect_params().values())
        frozen = params[:2]                  # first layer starts frozen
        for p in frozen:
            p.grad_req = "null"
        tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.05},
                           kvstore="device",
                           compression_params={"type": "int8"})
        loss_fn = gluon.loss.L2Loss()
        rng = np.random.RandomState(0)
        X = rng.randn(8, 4).astype(np.float32)
        Y = rng.randn(8, 2).astype(np.float32)
        for step in range(4):
            if step == 2:                    # unfreeze mid-training
                for p in frozen:
                    p.grad_req = "write"
            with autograd.record():
                for ctx, sl in zip(ctxs, (slice(0, 4), slice(4, None))):
                    loss_fn(net(nd.array(X[sl], ctx=ctx)),
                            nd.array(Y[sl], ctx=ctx)).backward()
            tr.step(batch_size=8)
        # every device copy identical (the unfrozen layer exchanged too)
        for p in params:
            ds = [d.asnumpy() for d in p.list_data()]
            for d in ds[1:]:
                np.testing.assert_array_equal(ds[0], d)
        return {k: v.data(ctxs[0]).asnumpy()
                for k, v in net.collect_params().items()}

    base = run("0")
    overlapped = run("1")
    for k in base:
        np.testing.assert_allclose(overlapped[k], base[k],
                                   rtol=1e-5, atol=1e-6)


def test_trainer_picks_up_env_default_compression(monkeypatch):
    monkeypatch.setenv("MX_GRAD_COMPRESS", "int8")
    net = nn.Dense(2, in_units=4)
    net.initialize(mx.init.Xavier(), ctx=[mx.cpu(0), mx.cpu(1)])
    tr = gluon.Trainer(net.collect_params(), "sgd", kvstore="device")
    assert tr._compression_params == {"type": "int8"}
    # explicit params always win over the env default
    tr2 = gluon.Trainer(net.collect_params(), "sgd", kvstore="device",
                        compression_params={"type": "bf16"})
    assert tr2._compression_params == {"type": "bf16"}


# ---------------------------------------------------------------------------
# loss-trajectory parity (dryrun_multichip-style)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("compress,tol", [
    ({"type": "int8"}, 0.02),
    ({"type": "2bit", "threshold": 0.05}, 0.25),
    ({"type": "bf16"}, 0.02),
])
def test_compressed_training_loss_parity(monkeypatch, compress, tol):
    """2-device DP training under compression tracks the fp32 loss
    trajectory: per-step relative divergence stays within the documented
    tolerance (int8/bf16 tight; 2bit coarser — its error feedback pays
    back over steps, not within one)."""
    monkeypatch.setenv("MX_EXCHANGE_OVERLAP", "1")
    base, _ = _fit_two_device(compress=None, steps=6)
    got, _ = _fit_two_device(compress=compress, steps=6)
    assert got[-1] < got[0]                     # it trains
    rel = [abs(a - b) / max(1e-6, abs(b)) for a, b in zip(got, base)]
    assert max(rel) <= tol, (rel, base, got)
