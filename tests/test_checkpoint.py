"""Sharded checkpoint/resume tests (SURVEY §5.4) on the fake 8-device mesh.

Reference pattern: checkpoint-resume bitwise-continuation tests — save mid
training, restore into a FRESH training step, and require the loss
trajectory to continue identically; plus elastic restore onto a different
mesh layout.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.checkpoint import (save_sharded, restore_sharded,
                                  CheckpointManager)
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel import make_mesh, TrainStep


def _devices(n=8):
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip("needs %d devices" % n)
    return devs[:n]


def _net():
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net(mx.nd.zeros((1, 8)))
    return net


def _loss_fn(logits, labels):
    logp = jax.nn.log_softmax(logits, axis=-1)
    onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=logp.dtype)
    return -jnp.mean(jnp.sum(logp * onehot, axis=-1))


def _batch(seed=0, n=16):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randn(n, 8), jnp.float32),
            jnp.asarray(rng.randint(0, 4, n), jnp.int32))


def test_sharded_save_restore_roundtrip(tmp_path):
    mesh = make_mesh(axes=("dp", "tp"), shape=(4, 2), devices=_devices())
    step = TrainStep(_net(), _loss_fn, mesh, learning_rate=0.1)
    x, y = _batch()
    for _ in range(3):
        step(x, y)
    step.save(str(tmp_path / "ck"))

    step2 = TrainStep(_net(), _loss_fn, mesh, learning_rate=0.1)
    tmpl_shardings = {n: v.sharding for n, v in step2.params.items()}
    step2.restore(str(tmp_path / "ck"))
    for name in step.params:
        np.testing.assert_array_equal(np.asarray(step.params[name]),
                                      np.asarray(step2.params[name]))
        # restore lays out onto the TEMPLATE step's shardings (the new
        # job's layout), not whatever the saving compiler chose
        assert step2.params[name].sharding == tmpl_shardings[name]
    # training CONTINUES identically (opt state restored too)
    l1 = float(step(x, y))
    l2 = float(step2(x, y))
    assert l1 == pytest.approx(l2, rel=1e-6)


def test_elastic_restore_onto_different_mesh(tmp_path):
    mesh_a = make_mesh(axes=("dp", "tp"), shape=(4, 2), devices=_devices())
    step_a = TrainStep(_net(), _loss_fn, mesh_a, learning_rate=0.1)
    x, y = _batch(1)
    step_a(x, y)
    step_a.save(str(tmp_path / "ck"))

    # new job, new topology: dp=2 x tp=4
    mesh_b = make_mesh(axes=("dp", "tp"), shape=(2, 4), devices=_devices())
    step_b = TrainStep(_net(), _loss_fn, mesh_b, learning_rate=0.1)
    step_b.restore(str(tmp_path / "ck"))
    for name in step_a.params:
        np.testing.assert_array_equal(np.asarray(step_a.params[name]),
                                      np.asarray(step_b.params[name]))
    l = float(step_b(x, y))
    assert np.isfinite(l)


def test_checkpoint_manager_retention_and_resume(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "run"), max_to_keep=2)
    state = {"w": jnp.arange(8, dtype=jnp.float32)}
    for s in (1, 2, 3):
        mgr.save(s, {"w": state["w"] * s})
    assert mgr.latest_step() == 3
    assert mgr.all_steps() == [2, 3]      # retention dropped step 1
    out = mgr.restore(template=state)
    np.testing.assert_array_equal(np.asarray(out["w"]),
                                  np.arange(8, dtype=np.float32) * 3)
    out2 = mgr.restore(step=2, template=state)
    np.testing.assert_array_equal(np.asarray(out2["w"]),
                                  np.arange(8, dtype=np.float32) * 2)
    mgr.close()


def test_restore_without_template(tmp_path):
    save_sharded(str(tmp_path / "raw"), {"a": jnp.ones((3,)),
                                         "b": {"c": jnp.zeros((2, 2))}})
    out = restore_sharded(str(tmp_path / "raw"))
    np.testing.assert_array_equal(np.asarray(out["a"]), np.ones(3))
    assert out["b"]["c"].shape == (2, 2)
