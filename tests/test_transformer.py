"""Attention + BERT tests (reference pattern: GluonNLP bert tests +
src/operator/contrib/transformer.cc op tests in test_operator.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.model_zoo import bert as bert_mod


def _np_attention(q, k, v, scale, causal=False, mask=None):
    logits = np.einsum("bhqd,bhkd->bhqk", q, k).astype(np.float64) * scale
    if causal:
        Tq, Tk = q.shape[2], k.shape[2]
        cm = np.tril(np.ones((Tq, Tk), bool), Tk - Tq)
        logits = np.where(cm, logits, -np.inf)
    if mask is not None:
        logits = np.where(mask, logits, -1e30)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, v)


def test_attention_core_matches_numpy():
    from mxnet_tpu.ops.attention import attention_core
    np.random.seed(0)
    B, H, T, D = 2, 3, 8, 4
    q = np.random.randn(B, H, T, D).astype(np.float32)
    k = np.random.randn(B, H, T, D).astype(np.float32)
    v = np.random.randn(B, H, T, D).astype(np.float32)
    scale = 1.0 / np.sqrt(D)
    out = np.asarray(attention_core(q, k, v, scale=scale))
    ref = _np_attention(q, k, v, scale)
    assert np.allclose(out, ref, atol=1e-5)
    out_c = np.asarray(attention_core(q, k, v, scale=scale, causal=True))
    ref_c = _np_attention(q, k, v, scale, causal=True)
    assert np.allclose(out_c, ref_c, atol=1e-5)


def test_flash_kernel_matches_reference_cpu_interpret():
    """Run the Pallas kernel in interpreter mode on CPU against the jnp
    path (the TPU run is covered by bench/verify)."""
    import jax
    import jax.experimental.pallas as pl
    from mxnet_tpu.ops import attention as att
    np.random.seed(0)
    B, H, T, D = 1, 2, 512, 128
    q = np.random.randn(B, H, T, D).astype(np.float32)
    k = np.random.randn(B, H, T, D).astype(np.float32)
    v = np.random.randn(B, H, T, D).astype(np.float32)
    scale = 1.0 / np.sqrt(D)

    # _flash_fwd auto-interprets off-TPU — no monkeypatching needed
    out, lse = att._flash_fwd(q, k, v, scale, False)
    out, lse = np.asarray(out), np.asarray(lse)
    out_causal = np.asarray(att._flash_fwd(q, k, v, scale, True)[0])
    ref = _np_attention(q, k, v, scale)
    ref_causal = _np_attention(q, k, v, scale, causal=True)
    assert np.allclose(out, ref, atol=2e-4), np.abs(out - ref).max()
    assert np.allclose(out_causal, ref_causal, atol=2e-4)
    # lse residual: logsumexp of the scaled scores
    s = np.einsum("bhqd,bhkd->bhqk", q, k) * scale
    lse_ref = np.log(np.exp(s - s.max(-1, keepdims=True)).sum(-1)) \
        + s.max(-1)
    assert np.allclose(lse, lse_ref, atol=1e-4), np.abs(lse - lse_ref).max()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_matches_jnp_cpu_interpret(causal):
    """The blockwise Pallas backward (recompute-from-LSE, O(L) memory) must
    produce the same dq/dk/dv as differentiating the jnp composition."""
    import jax
    import jax.numpy as jnp
    import jax.experimental.pallas as pl
    from mxnet_tpu.ops import attention as att
    np.random.seed(1)
    B, H, T, D = 1, 2, 512, 128
    q = np.random.randn(B, H, T, D).astype(np.float32)
    k = np.random.randn(B, H, T, D).astype(np.float32)
    v = np.random.randn(B, H, T, D).astype(np.float32)
    g = np.random.randn(B, H, T, D).astype(np.float32)
    scale = 1.0 / np.sqrt(D)

    _, vjp = jax.vjp(
        lambda q, k, v: att.flash_attention(q, k, v, scale, causal),
        q, k, v)
    dq, dk, dv = vjp(jnp.asarray(g))

    _, vjp_ref = jax.vjp(
        lambda q, k, v: att._attention_jnp(q, k, v, scale, causal), q, k, v)
    dq_r, dk_r, dv_r = vjp_ref(jnp.asarray(g))
    for got, want, name in ((dq, dq_r, "dq"), (dk, dk_r, "dk"),
                            (dv, dv_r, "dv")):
        err = np.abs(np.asarray(got) - np.asarray(want)).max()
        rel = err / max(np.abs(np.asarray(want)).max(), 1e-6)
        assert rel < 2e-4, (name, err, rel)


def test_flash_backward_bf16_cpu_interpret():
    """bf16 inputs (the MXU-native training dtype) flow through the flash
    backward; grads come back bf16 and near the fp32 reference."""
    import jax
    import jax.numpy as jnp
    import jax.experimental.pallas as pl
    from mxnet_tpu.ops import attention as att
    np.random.seed(2)
    B, H, T, D = 1, 1, 256, 128
    q = jnp.asarray(np.random.randn(B, H, T, D), jnp.bfloat16)
    k = jnp.asarray(np.random.randn(B, H, T, D), jnp.bfloat16)
    v = jnp.asarray(np.random.randn(B, H, T, D), jnp.bfloat16)
    scale = 1.0 / np.sqrt(D)

    def loss(q, k, v):
        return jnp.sum(att.flash_attention(q, k, v, scale, False)
                       .astype(jnp.float32))
    dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    assert dq.dtype == jnp.bfloat16
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    def loss_ref(q, k, v):
        return jnp.sum(att._attention_jnp(q, k, v, scale, False))
    rq, rk, rv = jax.grad(loss_ref, argnums=(0, 1, 2))(qf, kf, vf)
    for got, want in ((dq, rq), (dk, rk), (dv, rv)):
        rel = (np.abs(np.asarray(got, np.float32) - np.asarray(want)).max()
               / max(np.abs(np.asarray(want)).max(), 1e-6))
        assert rel < 0.05, rel


def test_interleaved_selfatt_ops():
    """interleaved_matmul_selfatt_qk + valatt == plain attention."""
    np.random.seed(0)
    T, N, H, D = 6, 2, 2, 4
    qkv = np.random.randn(T, N, H * 3 * D).astype(np.float32)
    s = mx.nd.invoke("_contrib_interleaved_matmul_selfatt_qk",
                     mx.nd.array(qkv), heads=H)
    att = s.softmax(axis=-1)
    out = mx.nd.invoke("_contrib_interleaved_matmul_selfatt_valatt",
                       mx.nd.array(qkv), att, heads=H)
    assert out.shape == (T, N, H * D)
    # reference: deinterleave manually
    x = qkv.reshape(T, N, H, 3, D)
    q = x[:, :, :, 0].transpose(1, 2, 0, 3)
    k = x[:, :, :, 1].transpose(1, 2, 0, 3)
    v = x[:, :, :, 2].transpose(1, 2, 0, 3)
    ref = _np_attention(q, k, v, 1.0 / np.sqrt(D))
    ref = ref.transpose(2, 0, 1, 3).reshape(T, N, H * D)
    assert np.allclose(out.asnumpy(), ref, atol=1e-4)


def test_mha_block():
    np.random.seed(0)
    blk = bert_mod.MultiHeadAttention(units=16, num_heads=4)
    blk.initialize(mx.init.Xavier())
    x = mx.nd.array(np.random.randn(2, 5, 16).astype(np.float32))
    out = blk(x)
    assert out.shape == (2, 5, 16)


def test_bert_tiny_forward_and_heads():
    net = bert_mod.get_bert(num_layers=2, units=32, num_heads=4,
                            vocab_size=100, max_length=16, dropout=0.0)
    net.initialize(mx.init.Normal(0.02))
    tokens = mx.nd.array(np.random.randint(0, 100, (3, 10)).astype(np.float32))
    segments = mx.nd.array(np.zeros((3, 10), np.float32))
    seq, pooled, nsp, mlm = net(tokens, segments)
    assert seq.shape == (3, 10, 32)
    assert pooled.shape == (3, 32)
    assert nsp.shape == (3, 2)
    assert mlm.shape == (3, 10, 100)


def test_bert_valid_length_masks_padding():
    net = bert_mod.get_bert(num_layers=1, units=16, num_heads=2,
                            vocab_size=50, max_length=8, dropout=0.0,
                            use_decoder=False, use_classifier=False)
    net.initialize(mx.init.Normal(0.02))
    tok = np.random.randint(1, 50, (1, 6)).astype(np.float32)
    vl = mx.nd.array([4.0])
    seq1, _ = net(mx.nd.array(tok), None, vl)
    # changing a padded token must not change valid positions' output
    tok2 = tok.copy()
    tok2[0, 5] = (tok2[0, 5] + 7) % 50
    seq2, _ = net(mx.nd.array(tok2), None, vl)
    assert np.allclose(seq1.asnumpy()[:, :4], seq2.asnumpy()[:, :4],
                       atol=1e-5)


def test_bert_mlm_training_descends():
    np.random.seed(0)
    mx.random.seed(0)
    V = 30
    net = bert_mod.get_bert(num_layers=1, units=16, num_heads=2,
                            vocab_size=V, max_length=8, dropout=0.0,
                            use_pooler=False, use_classifier=False)
    net.initialize(mx.init.Normal(0.05))
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tokens = np.random.randint(0, V, (8, 8)).astype(np.float32)
    first = last = None
    for _ in range(15):
        x = mx.nd.array(tokens)
        with autograd.record():
            seq, mlm = net(x)
            loss = loss_fn(mlm.reshape((-1, V)),
                           mx.nd.array(tokens.reshape(-1))).mean()
        loss.backward()
        trainer.step(1)
        v = float(loss.asscalar())
        first = first if first is not None else v
        last = v
    assert last < first * 0.5, (first, last)


def test_optimize_for_selects_attention_lowering():
    """optimize_for(backend) must actually change the attention dispatch
    (VERDICT: previously a recorded string with no effect)."""
    import warnings
    from mxnet_tpu.ops import attention as att
    np.random.seed(0)
    B, H, T, D = 1, 1, 256, 128
    q = np.random.randn(B, H, T, D).astype(np.float32)
    k = np.random.randn(B, H, T, D).astype(np.float32)
    v = np.random.randn(B, H, T, D).astype(np.float32)

    calls = {"flash": 0}
    orig_flash = att.flash_attention

    def spy(*a, **kw):
        calls["flash"] += 1
        return orig_flash(*a, **kw)

    att.flash_attention = spy
    try:
        att.set_attention_impl("xla")
        att.attention_core(q, k, v)
        assert calls["flash"] == 0          # forced OFF even when aligned
        att.set_attention_impl("pallas")
        out_p = np.asarray(att.attention_core(q, k, v))
        assert calls["flash"] == 1          # forced ON even on CPU
    finally:
        att.flash_attention = orig_flash
        att.set_attention_impl(None)
    out_x = np.asarray(att.attention_core(q, k, v))
    assert np.allclose(out_p, out_x, atol=2e-4)

    # the Block surface stamps a PER-BLOCK property (never the global);
    # unknown backends warn
    net = nn.Dense(4, in_units=8)
    net.initialize()
    x = mx.nd.ones((2, 8))
    net.optimize_for(x, backend="pallas")
    assert att._FORCED_IMPL is None          # global untouched
    assert net._backend == "pallas"
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        net.optimize_for(x, backend="tensorrt")
    assert any("unknown subgraph backend" in str(x.message) for x in w)
    assert net._backend is None
