"""INT8 quantization: op semantics + the quantize_net calibration/rewrite
flow (reference: tests/python/quantization/test_quantization.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.contrib.quantization import (quantize_net,
                                            _get_optimal_threshold)


def test_quantize_dequantize_roundtrip():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 16).astype(np.float32) * 3
    mn, mx_ = float(x.min()), float(x.max())
    q, qmn, qmx = nd.invoke("_contrib_quantize", nd.array(x),
                            nd.array([mn]), nd.array([mx_]), out_type="int8")
    assert q.dtype == np.int8
    back = nd.invoke("_contrib_dequantize", q, qmn, qmx)
    amax = max(abs(mn), abs(mx_))
    np.testing.assert_allclose(back.asnumpy(), x, atol=amax / 127 + 1e-6)


def test_quantize_v2_auto_range():
    x = np.array([[-1.0, 0.5, 2.0]], np.float32)
    q, mn, mx_ = nd.invoke("_contrib_quantize_v2", nd.array(x),
                           out_type="int8")
    assert float(mx_.asnumpy()[0]) == pytest.approx(2.0, rel=1e-5)
    assert q.asnumpy()[0, 2] == 127


def test_quantize_uint8():
    x = np.array([0.0, 1.0, 2.0], np.float32)
    q, mn, mx_ = nd.invoke("_contrib_quantize", nd.array(x),
                           nd.array([0.0]), nd.array([2.0]),
                           out_type="uint8")
    assert q.dtype == np.uint8
    np.testing.assert_array_equal(q.asnumpy(), [0, 128, 255])


def test_quantized_fully_connected_matches_float():
    rng = np.random.RandomState(1)
    x = rng.randn(8, 32).astype(np.float32)
    w = rng.randn(16, 32).astype(np.float32)
    b = rng.randn(16).astype(np.float32)

    def qr(a):
        return nd.array([float(a.min())]), nd.array([float(a.max())])

    xmn, xmx = qr(x); wmn, wmx = qr(w); bmn, bmx = qr(b)
    qx, qxmn, qxmx = nd.invoke("_contrib_quantize", nd.array(x), xmn, xmx,
                               out_type="int8")
    qw, _, _ = nd.invoke("_contrib_quantize", nd.array(w), wmn, wmx,
                         out_type="int8")
    qb, _, _ = nd.invoke("_contrib_quantize", nd.array(b), bmn, bmx,
                         out_type="int8")
    acc, omn, omx = nd.invoke("_contrib_quantized_fully_connected",
                              qx, qw, qb, qxmn, qxmx, wmn, wmx, bmn, bmx,
                              num_hidden=16)
    assert acc.dtype == np.int32
    out = nd.invoke("_contrib_dequantize", acc, omn, omx)
    expect = x @ w.T + b
    # int8 GEMM tolerance: ~1% of the output scale
    err = np.abs(out.asnumpy() - expect).max()
    assert err < 0.05 * np.abs(expect).max()


def test_quantized_conv_matches_float():
    rng = np.random.RandomState(2)
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    w = rng.randn(4, 3, 3, 3).astype(np.float32)

    def qz(a):
        mn, mx_ = nd.array([float(a.min())]), nd.array([float(a.max())])
        q, qmn, qmx = nd.invoke("_contrib_quantize", nd.array(a), mn, mx_,
                                out_type="int8")
        return q, mn, mx_, qmn, qmx

    qx, xmn, xmx, qxmn, qxmx = qz(x)
    qw, wmn, wmx, _, _ = qz(w)
    acc, omn, omx = nd.invoke("_contrib_quantized_conv", qx, qw, None,
                              qxmn, qxmx, wmn, wmx, kernel=(3, 3),
                              pad=(1, 1), num_filter=4, no_bias=True)
    out = nd.invoke("_contrib_dequantize", acc, omn, omx).asnumpy()
    expect = nd.invoke("Convolution", nd.array(x), nd.array(w), None,
                       kernel=(3, 3), pad=(1, 1), num_filter=4,
                       no_bias=True).asnumpy()
    assert np.abs(out - expect).max() < 0.05 * np.abs(expect).max()


def test_quantized_pooling_and_flatten_pass_range():
    x = (np.arange(16).reshape(1, 1, 4, 4) - 8).astype(np.int8)
    out, mn, mx_ = nd.invoke("_contrib_quantized_pooling", nd.array(x),
                             nd.array([-1.0]), nd.array([1.0]),
                             kernel=(2, 2), stride=(2, 2), pool_type="max")
    assert out.dtype == np.int8
    assert float(mx_.asnumpy()[0]) == 1.0
    f, _, _ = nd.invoke("_contrib_quantized_flatten", out,
                        nd.array([-1.0]), nd.array([1.0]))
    assert f.shape == (1, 4)


def test_requantize_int32_to_int8():
    rng = np.random.RandomState(7)
    x = rng.randn(4, 8).astype(np.float32)
    mn, mx_ = float(x.min()), float(x.max())
    q, qmn, qmx = nd.invoke("_contrib_quantize", nd.array(x),
                            nd.array([mn]), nd.array([mx_]), out_type="int8")
    # fake an int32 accumulator carrying the same values: acc = q * 2^16,
    # so full-scale 2^31 corresponds to amax_range/127 * 2^15 in float
    acc = q.asnumpy().astype(np.int32) * (1 << 16)
    amax = max(abs(mn), abs(mx_)) * (2.0 ** 31) / (127.0 * (1 << 16))
    r, rmn, rmx = nd.invoke("_contrib_requantize", nd.array(acc),
                            nd.array([-amax]), nd.array([amax]))
    assert r.dtype == np.int8
    back = nd.invoke("_contrib_dequantize", r, rmn, rmx).asnumpy()
    np.testing.assert_allclose(back, x, atol=2 * max(abs(mn), abs(mx_)) / 127)


def test_quantized_act_relu():
    x = np.array([-5, -1, 0, 3, 7], np.int8)
    out, mn, mx_ = nd.invoke("_contrib_quantized_act", nd.array(x),
                             nd.array([-1.0]), nd.array([2.0]),
                             act_type="relu")
    np.testing.assert_array_equal(out.asnumpy(), [0, 0, 0, 3, 7])
    assert float(mn.asnumpy()[0]) == 0.0
    assert float(mx_.asnumpy()[0]) == 2.0


def test_quantized_fc_uint8_data():
    # uint8 activations must not wrap modulo 256 in the GEMM
    rng = np.random.RandomState(8)
    x = rng.rand(4, 16).astype(np.float32) * 3  # non-negative -> uint8 range
    w = rng.randn(6, 16).astype(np.float32)
    qx, qxmn, qxmx = nd.invoke("_contrib_quantize", nd.array(x),
                               nd.array([0.0]), nd.array([3.0]),
                               out_type="uint8")
    assert qx.asnumpy().max() > 127  # the wrap-prone regime
    wmn, wmx = nd.array([float(w.min())]), nd.array([float(w.max())])
    qw, _, _ = nd.invoke("_contrib_quantize", nd.array(w), wmn, wmx,
                         out_type="int8")
    acc, omn, omx = nd.invoke("_contrib_quantized_fully_connected",
                              qx, qw, None, qxmn, qxmx, wmn, wmx,
                              num_hidden=6, no_bias=True)
    out = nd.invoke("_contrib_dequantize", acc, omn, omx).asnumpy()
    expect = x @ w.T
    assert np.abs(out - expect).max() < 0.05 * np.abs(expect).max()


def test_optimal_threshold_sane():
    rng = np.random.RandomState(3)
    x = rng.randn(20000).astype(np.float32)
    x[0] = 40.0  # one huge outlier the KL calibration should clip away
    t = _get_optimal_threshold(x)
    assert 2.0 < t < 40.0


def test_quantize_net_mlp():
    mx.random.seed(4)     # initializers draw from the mx stream: pin it so
    rng = np.random.RandomState(4)  # accuracy tolerance is deterministic
    net = mx.gluon.nn.HybridSequential()
    net.add(mx.gluon.nn.Dense(32, activation="relu"),
            mx.gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    x = nd.array(rng.randn(64, 20).astype(np.float32))
    float_out = net(x).asnumpy()

    quantize_net(net, calib_data=[x], calib_mode="naive")
    q_out = net(x).asnumpy()
    # int8 accuracy: close to float on a 2-layer MLP
    scale = np.abs(float_out).max()
    assert np.abs(q_out - float_out).max() < 0.1 * scale


def test_quantize_net_conv_entropy():
    mx.random.seed(5)
    rng = np.random.RandomState(5)
    net = mx.gluon.nn.HybridSequential()
    net.add(mx.gluon.nn.Conv2D(8, 3, padding=1, activation="relu"),
            mx.gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    batches = [nd.array(rng.randn(4, 3, 8, 8).astype(np.float32))
               for _ in range(3)]
    float_out = net(batches[0]).asnumpy()
    quantize_net(net, calib_data=batches, calib_mode="entropy")
    q_out = net(batches[0]).asnumpy()
    scale = np.abs(float_out).max()
    assert np.abs(q_out - float_out).max() < 0.15 * scale


def test_quantize_net_excludes():
    # exclude_layers names are structural child paths: HybridSequential's
    # direct children are "0", "1", ... (nested blocks dot-join: "0.body.2")
    net = mx.gluon.nn.HybridSequential()
    net.add(mx.gluon.nn.Dense(8), mx.gluon.nn.Dense(4))
    net.initialize()
    x = nd.ones((2, 6))
    quantize_net(net, calib_data=[x], exclude_layers=["0"])
    kids = list(net._children.values())
    assert not getattr(kids[0], "_quantized", False)
    assert getattr(kids[1], "_quantized", False)
