"""DGL graph-sampling contrib ops (reference:
src/operator/contrib/dgl_graph.cc; tests/python/unittest/test_dgl_graph.py
pattern — structural invariants over small CSR graphs)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def ring(n=6):
    """Directed ring + chord graph as CSR."""
    indptr = np.arange(0, 2 * n + 1, 2, dtype=np.int64)
    indices = np.empty(2 * n, np.int64)
    for v in range(n):
        indices[2 * v] = (v + 1) % n
        indices[2 * v + 1] = (v + 2) % n
    data = np.arange(1, 2 * n + 1, dtype=np.float32)
    return nd.sparse.csr_matrix((data, indices, indptr), shape=(n, n))


def to_dense(csr):
    return csr.tostype("default").asnumpy()


def test_dgl_adjacency():
    g = ring()
    adj = mx.nd.contrib.dgl_adjacency(g)
    assert adj.stype == "csr"
    d = to_dense(adj)
    assert set(np.unique(d)) <= {0.0, 1.0}
    assert (d != 0).sum() == 12          # same structure as parent
    assert ((to_dense(g) != 0) == (d != 0)).all()


def test_dgl_subgraph_induced():
    g = ring()
    vids = nd.array(np.array([0, 1, 2], np.int64))
    sub, mapping = mx.nd.contrib.dgl_subgraph(g, vids, return_mapping=True)
    assert sub.shape == (3, 3) and mapping.shape == (3, 3)
    parent = to_dense(g)
    subd = to_dense(sub)
    md = to_dense(mapping)
    v = [0, 1, 2]
    for i in range(3):
        for j in range(3):
            # edge present in subgraph iff present between parent vertices
            assert (subd[i, j] != 0) == (parent[v[i], v[j]] != 0)
            if md[i, j] != 0:
                # mapping data = parent edge id = index into g.data
                eid = int(md[i, j])
                lo, hi = int(g.indptr.asnumpy()[v[i]]), \
                    int(g.indptr.asnumpy()[v[i] + 1])
                assert lo <= eid < hi
                assert int(g.indices.asnumpy()[eid]) == v[j]


def test_dgl_uniform_sample_invariants():
    g = ring(8)
    mx.random.seed(3)
    out = mx.nd.contrib.dgl_csr_neighbor_uniform_sample(
        g, nd.array(np.array([0], np.int64)),
        num_hops=2, num_neighbor=2, max_num_vertices=8)
    verts, sub, layer = out
    v = verts.asnumpy()
    n = int(v[-1])
    assert 1 <= n <= 8
    assert v[0] == 0                       # seed first
    lay = layer.asnumpy()
    assert lay[0] == 0
    assert (lay[:n] >= 0).all() and (lay[:n] <= 2).all()
    # every sampled edge exists in the parent graph
    parent = to_dense(g)
    subd = to_dense(sub)
    for i in range(n):
        for j in range(n):
            if subd[i, j] != 0:
                assert parent[int(v[i]), int(v[j])] != 0


def test_dgl_non_uniform_sample_respects_zero_prob():
    g = ring(6)
    # forbid vertex 1 entirely: its sampling probability is 0
    prob = np.ones(6, np.float32)
    prob[1] = 0.0
    mx.random.seed(0)
    out = mx.nd.contrib.dgl_csr_neighbor_non_uniform_sample(
        g, nd.array(prob), nd.array(np.array([0], np.int64)),
        num_hops=3, num_neighbor=1, max_num_vertices=6)
    verts, pv, sub, layer = out
    v = verts.asnumpy()
    n = int(v[-1])
    assert 1 not in v[:n].tolist()
    # returned probabilities align with the sampled vertices
    assert np.allclose(pv.asnumpy()[:n], prob[v[:n]])


def test_dgl_graph_compact():
    g = ring(8)
    mx.random.seed(1)
    verts, sub, _layer = mx.nd.contrib.dgl_csr_neighbor_uniform_sample(
        g, nd.array(np.array([2], np.int64)),
        num_hops=1, num_neighbor=2, max_num_vertices=8)
    n = int(verts.asnumpy()[-1])
    compact = mx.nd.contrib.dgl_graph_compact(sub, graph_sizes=(n,))
    c = compact[0] if isinstance(compact, (list, tuple)) else compact
    assert c.shape == (n, n)
    # compaction preserves the live block
    assert (to_dense(sub)[:n, :n] != 0).sum() == (to_dense(c) != 0).sum()


def test_sparse_storage_fallback_warns():
    """Dense-only ops densify sparse inputs with a one-time warning
    (reference storage-fallback semantics)."""
    g = ring(4)
    with pytest.warns(UserWarning, match="storage-fallback|no sparse"):
        out = nd.sum(g)
    got = float(out.asnumpy()) if hasattr(out, "asnumpy") else float(out)
    assert np.isclose(got, g.data.asnumpy().sum())
