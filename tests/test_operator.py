"""Per-operator battery: numpy-reference forward + numeric-gradient check
for EVERY registered op.

Reference: tests/python/unittest/test_operator.py (~10k lines of per-op
numpy-reference + check_numeric_gradient tests) — rebuilt as a spec table
(`SPECS`) driving three parametrized tests:

  test_forward   — invoke the op, compare against a NumPy reference (when
                   given) or assert shape/finiteness sanity,
  test_grad      — central-difference gradient check via
                   test_utils.check_numeric_gradient for differentiable ops,
  test_coverage  — every unique registry op must appear in SPECS or in
                   TESTED_ELSEWHERE (pointing at the suite that covers it);
                   adding an op without a test fails CI.

Reference coverage: ~85% of SPECS carry a `ref=` numpy re-implementation.
The ~99 specs WITHOUT refs are exactly these classes, exempt by nature:
  * stochastic samplers (_random_* / _sample_* / _npi_<dist> / shuffle /
    *_like / _image_random_*) — no deterministic reference exists;
    shape+finiteness here, moment checks in their dedicated tests;
  * _npi_partition/_npi_argpartition — within-segment order is
    UNSPECIFIED; pinned by test_npi_partition_semantics instead;
  * _npi_empty_like — values are undefined by contract;
  * decode/IO ops (_cvimread/_cvimdecode/_image_imdecode) and resamplers
    (_cvimresize/_image_resize/BilinearResize2D/BilinearSampler/
    GridGenerator/SpatialTransformer/Correlation/Deconvolution/ROIPooling
    /PSROIPooling family) — pinned by exactness-anchor tests further down
    this file (test_deformable_matches_convolution, PSROI/box anchors) and
    tests/test_ssd.py end-to-end parity rather than elementwise refs;
  * detection pipeline ops (MultiBox*/Proposal*/box_nms/box_encode/
    mrcnn_mask_target) — protocol-level checks live in test_ssd.py and the
    box-anchor tests here;
  * quantized/intgemm kernels — numeric contracts pinned in
    tests/test_quantization.py;
  * linalg factorizations (linalg_syevd/gelqf/maketrian) — eigenvector/
    factor sign+order ambiguity; validated by reconstruction identities in
    their grad specs and tests/test_ndarray.py linalg checks;
  * im2col/col2im, count_sketch, hawkesll, calibrate_entropy,
    sldwin_atten_* — pinned by dedicated reference tests in this file
    (sliding-window attention vs dense mask, hawkesll vs slow loop,
    KL-calibration behaviour) rather than one-liner refs.
"""
import numpy as np
import pytest
import scipy.special as _sp

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ndarray.ndarray import invoke
from mxnet_tpu.ops import registry
from mxnet_tpu.test_utils import assert_almost_equal, check_numeric_gradient

R = np.random.RandomState(7)


def f(*shape):
    """Well-conditioned float input away from singular points."""
    return (R.uniform(0.3, 0.9, shape) * R.choice([-1.0, 1.0], shape)
            ).astype(np.float32)


def fpos(*shape):
    return R.uniform(0.3, 0.9, shape).astype(np.float32)


def funit(*shape):
    return R.uniform(-0.7, 0.7, shape).astype(np.float32)


def ints(*shape, lo=0, hi=8):
    return R.randint(lo, hi, shape).astype(np.int32)


def sep(*shape):
    """Well-separated values: numeric grad safe at order statistics."""
    flat = np.argsort(R.rand(int(np.prod(shape))))
    return (flat.reshape(shape).astype(np.float32)
            + R.uniform(0.1, 0.3, shape).astype(np.float32))


class Spec:
    def __init__(self, inputs, params=None, ref=None, grad=None, rtol=1e-4,
                 atol=1e-4, grad_rtol=1e-2, grad_atol=1e-2):
        self.inputs = inputs          # callable -> list[np.ndarray]
        self.params = params or {}
        self.ref = ref                # callable(*np_inputs) -> np / tuple
        self.grad = grad              # None = infer from registry
        self.rtol, self.atol = rtol, atol
        self.grad_rtol, self.grad_atol = grad_rtol, grad_atol


def S(inputs, params=None, ref=None, **kw):
    return Spec(inputs, params, ref, **kw)


def _masked_softmax_ref(x, m):
    b = m.astype(bool)
    xm = np.where(b, x, -1e30)
    e = np.exp(xm - xm.max(-1, keepdims=True))
    out = e / e.sum(-1, keepdims=True)
    return np.where(b, out, 0.0).astype(np.float32)


def _masked_log_softmax_ref(x, m):
    b = m.astype(bool)
    xm = np.where(b, x, -1e30)
    out = xm - xm.max(-1, keepdims=True) - np.log(
        np.exp(xm - xm.max(-1, keepdims=True)).sum(-1, keepdims=True))
    return np.where(b, out, -np.inf).astype(np.float32)


def _scatter_nd_ref(data, idx, shape):
    out = np.zeros(shape, data.dtype)
    out[tuple(idx[i] for i in range(idx.shape[0]))] = data
    return out


def _index_add_ref(data, index, value):
    out = data.copy()
    np.add.at(out, index, value)
    return out


def _index_set_ref(data, index, value):
    out = data.copy()
    out[index] = value
    return out


def _seq_mask_ref(x, lens, value=0.0):
    out = x.copy()
    for b, L in enumerate(lens.astype(int)):
        out[L:, b] = value
    return out


def _pool_max_ref(x, k, s, ceil=False):
    N, C, H, W = x.shape
    if ceil:
        Ho = -((H - k) // -s) + 1
        Wo = -((W - k) // -s) + 1
    else:
        Ho, Wo = (H - k) // s + 1, (W - k) // s + 1
    out = np.zeros((N, C, Ho, Wo), x.dtype)
    for i in range(Ho):
        for j in range(Wo):
            out[:, :, i, j] = x[:, :, i * s:min(i * s + k, H),
                                j * s:min(j * s + k, W)].max((2, 3))
    return out


def _lrn_ref(x, nsize=3, alpha=1e-4, beta=0.75, k=2.0):
    sq = np.square(x)
    half = nsize // 2
    acc = np.zeros_like(sq)
    C = x.shape[1]
    for c in range(C):
        lo, hi = max(0, c - half), min(C, c + half + 1)
        acc[:, c] = sq[:, lo:hi].sum(1)
    return x / np.power(k + (alpha / nsize) * acc, beta)


def _boxes(n):
    """(n, 4) corner boxes with x1<x2, y1<y2."""
    lo = R.uniform(0.0, 0.5, (n, 2)).astype(np.float32)
    hi = lo + R.uniform(0.1, 0.5, (n, 2)).astype(np.float32)
    return np.concatenate([lo, hi], 1)


def _iou_ref(a, b):
    out = np.zeros((a.shape[0], b.shape[0]), np.float32)
    for i in range(a.shape[0]):
        for j in range(b.shape[0]):
            ix = max(0.0, min(a[i, 2], b[j, 2]) - max(a[i, 0], b[j, 0]))
            iy = max(0.0, min(a[i, 3], b[j, 3]) - max(a[i, 1], b[j, 1]))
            inter = ix * iy
            ua = ((a[i, 2] - a[i, 0]) * (a[i, 3] - a[i, 1])
                  + (b[j, 2] - b[j, 0]) * (b[j, 3] - b[j, 1]) - inter)
            out[i, j] = inter / ua if ua > 0 else 0.0
    return out


def _conv2d_ref(x, w, b, stride=1, pad=0):
    N, C, H, W = x.shape
    O, _C, kh, kw = w.shape
    if pad:
        x = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
        H, W = H + 2 * pad, W + 2 * pad
    Ho, Wo = (H - kh) // stride + 1, (W - kw) // stride + 1
    out = np.zeros((N, O, Ho, Wo), np.float32)
    for i in range(Ho):
        for j in range(Wo):
            patch = x[:, :, i * stride:i * stride + kh,
                      j * stride:j * stride + kw]          # N,C,kh,kw
            out[:, :, i, j] = np.einsum("nchw,ochw->no", patch, w)
    return out + b.reshape(1, -1, 1, 1)


# --- unary elementwise with direct numpy refs ------------------------------
_UNARY = {
    "abs": (np.abs, f), "negative": (np.negative, f),
    "exp": (np.exp, f), "expm1": (np.expm1, f),
    "log": (np.log, fpos), "log10": (np.log10, fpos),
    "log1p": (np.log1p, fpos), "log2": (np.log2, fpos),
    "sqrt": (np.sqrt, fpos), "rsqrt": (lambda x: 1 / np.sqrt(x), fpos),
    "cbrt": (np.cbrt, fpos), "rcbrt": (lambda x: 1 / np.cbrt(x), fpos),
    "square": (np.square, f), "reciprocal": (np.reciprocal, f),
    "sin": (np.sin, f), "cos": (np.cos, f), "tan": (np.tan, funit),
    "arcsin": (np.arcsin, funit), "arccos": (np.arccos, funit),
    "arctan": (np.arctan, f),
    "sinh": (np.sinh, f), "cosh": (np.cosh, f), "tanh": (np.tanh, f),
    "arcsinh": (np.arcsinh, f), "arccosh": (lambda x: np.arccosh(1 + x), fpos),
    "arctanh": (np.arctanh, funit),
    "sign": (np.sign, f), "ceil": (np.ceil, f), "floor": (np.floor, f),
    "trunc": (np.trunc, f), "rint": (np.rint, f), "round": (np.round, f),
    "fix": (np.fix, f),
    "sigmoid": (lambda x: 1 / (1 + np.exp(-x)), f),
    "relu": (lambda x: np.maximum(x, 0), f),
    "softsign": (lambda x: x / (1 + np.abs(x)), f),
    "identity": (lambda x: x, f),
    "erf": (lambda x: _sp.erf(x), f), "erfc": (lambda x: _sp.erfc(x), f),
    "erfinv": (lambda x: _sp.erfinv(x), funit),
    "gamma": (lambda x: _sp.gamma(x), fpos),
    "gammaln": (lambda x: _sp.gammaln(x), fpos),
    "digamma": (lambda x: _sp.digamma(x), fpos),
    "radians": (np.radians, f), "degrees": (np.degrees, f),
    "sinc": (np.sinc, f), "i0": (lambda x: _sp.i0(x), fpos),
    "selu": (lambda x: 1.0507009873554805 * np.where(
        x > 0, x, 1.6732632423543772 * (np.exp(x) - 1)), f),
    "gelu": (lambda x: 0.5 * x * (1 + _sp.erf(x / np.sqrt(2.0))), f),
    "silu": (lambda x: x / (1 + np.exp(-x)), f),
    "mish": (lambda x: x * np.tanh(np.log1p(np.exp(x))), f),
    "elu": (lambda x: np.where(x > 0, x, np.exp(x) - 1), f),
    "softrelu": (lambda x: np.log1p(np.exp(x)), f),
    "log_sigmoid": (lambda x: -np.log1p(np.exp(-x)), f),
    "hard_sigmoid": (lambda x: np.clip(0.2 * x + 0.5, 0, 1), f),
    "hard_swish": (lambda x: x * np.clip(x + 3, 0, 6) / 6.0, f),
    "isnan": (np.isnan, f), "isinf": (np.isinf, f),
    "isfinite": (np.isfinite, f),
    "logical_not": (lambda x: np.logical_not(x).astype(np.float32), f),
    "zeros_like_op": (np.zeros_like, f), "ones_like_op": (np.ones_like, f),
    "atleast_1d": (np.atleast_1d, f), "atleast_2d": (np.atleast_2d, f),
    "atleast_3d": (np.atleast_3d, f),
    "nan_to_num": (np.nan_to_num, f),
}

# --- binary broadcast with numpy refs --------------------------------------
_BINARY = {
    "broadcast_add": np.add, "broadcast_sub": np.subtract,
    "broadcast_mul": np.multiply, "broadcast_div": np.divide,
    "broadcast_maximum": np.maximum, "broadcast_minimum": np.minimum,
    "broadcast_hypot": np.hypot, "hypot": np.hypot,


    "broadcast_equal": lambda a, b: (a == b).astype(np.float32),
    "broadcast_not_equal": lambda a, b: (a != b).astype(np.float32),
    "broadcast_greater": lambda a, b: (a > b).astype(np.float32),
    "broadcast_greater_equal": lambda a, b: (a >= b).astype(np.float32),
    "broadcast_lesser": lambda a, b: (a < b).astype(np.float32),
    "broadcast_lesser_equal": lambda a, b: (a <= b).astype(np.float32),
    "broadcast_logical_and": lambda a, b: np.logical_and(a, b).astype(np.float32),
    "broadcast_logical_or": lambda a, b: np.logical_or(a, b).astype(np.float32),
    "broadcast_logical_xor": lambda a, b: np.logical_xor(a, b).astype(np.float32),
    "arctan2": np.arctan2, "copysign": np.copysign,
    "logaddexp": np.logaddexp, "fmod": np.fmod, "nextafter": np.nextafter,
    "heaviside": np.heaviside, "ldexp": lambda a, b: a * np.exp2(b),
}

SPECS = {}
for _name, (_ref, _gen) in _UNARY.items():
    SPECS[_name] = S(lambda g=_gen: [g(3, 4)], ref=_ref)
for _name, _ref in _BINARY.items():
    SPECS[_name] = S(lambda: [f(3, 4), fpos(3, 4)], ref=_ref)

SPECS.update({
    "arccosh": S(lambda: [1.0 + fpos(3, 4)], ref=np.arccosh),
    "broadcast_mod": S(lambda: [f(3, 4), fpos(3, 4)], ref=np.mod,
                       grad=False),
    "broadcast_power": S(lambda: [fpos(3, 4), f(3, 4)], ref=np.power),
    "nextafter": S(lambda: [f(3, 4), fpos(3, 4)], ref=np.nextafter,
                   grad=False),
    "lerp": S(lambda: [f(3, 4), f(3, 4), fpos(3, 4)],
              ref=lambda a, b, w: a + w * (b - a)),
    # reductions
    "sum": S(lambda: [f(2, 3, 4)], {"axis": (0, 2)},
             ref=lambda x: x.sum(axis=(0, 2))),
    "mean": S(lambda: [f(2, 3, 4)], {"axis": 1}, ref=lambda x: x.mean(1)),
    "max": S(lambda: [f(3, 4)], {"axis": 1}, ref=lambda x: x.max(1)),
    "min": S(lambda: [f(3, 4)], {"axis": 0}, ref=lambda x: x.min(0)),
    "prod": S(lambda: [fpos(3, 4)], {"axis": 1}, ref=lambda x: x.prod(1)),
    "nansum": S(lambda: [f(3, 4)], ref=np.nansum),
    "nanprod": S(lambda: [fpos(3, 4)], ref=np.nanprod),
    "norm": S(lambda: [f(3, 4)], {"ord": 2},
              ref=lambda x: np.sqrt((x * x).sum())),
    "std": S(lambda: [f(3, 4)], {"axis": 1}, ref=lambda x: x.std(1)),
    "var": S(lambda: [f(3, 4)], {"axis": 1}, ref=lambda x: x.var(1)),
    # well-separated values: numeric grad is undefined at tied extrema
    "ptp": S(lambda: [np.argsort(R.rand(3, 4), 1).astype(np.float32)
                      + f(3, 4) * 0.1],
             {"axis": 1}, ref=lambda x: np.ptp(x, 1)),
    "median": S(lambda: [f(3, 5)], {"axis": 1},
                ref=lambda x: np.median(x, 1), grad=False),
    "quantile": S(lambda: [f(3, 5)], {"q": 0.5, "axis": 1},
                  ref=lambda x: np.quantile(x, 0.5, 1), grad=False),
    "percentile": S(lambda: [f(3, 5)], {"q": 30.0, "axis": 1},
                    ref=lambda x: np.percentile(x, 30.0, 1), grad=False),
    "average": S(lambda: [f(3, 4)], {"axis": 1}, ref=lambda x: x.mean(1)),
    "logsumexp": S(lambda: [f(3, 4)], {"axis": 1},
                   ref=lambda x: np.log(np.exp(x).sum(1))),
    "moments": S(lambda: [f(3, 4)], {"axes": (0, 1)},
                 ref=lambda x: (x.mean(), x.var())),
    "argmax": S(lambda: [f(3, 4)], {"axis": 1},
                ref=lambda x: x.argmax(1).astype(np.float32)),
    "argmin": S(lambda: [f(3, 4)], {"axis": 1},
                ref=lambda x: x.argmin(1).astype(np.float32)),
    "argmax_channel": S(lambda: [f(3, 4)],
                        ref=lambda x: x.argmax(1).astype(np.float32)),
    # softmax family
    "softmax": S(lambda: [f(3, 4)], {"axis": -1},
                 ref=lambda x: np.exp(x) / np.exp(x).sum(-1, keepdims=True)),
    "softmin": S(lambda: [f(3, 4)], {"axis": -1},
                 ref=lambda x: np.exp(-x) / np.exp(-x).sum(-1, keepdims=True)),
    "log_softmax": S(lambda: [f(3, 4)], {"axis": -1},
                     ref=lambda x: x - x.max(-1, keepdims=True) - np.log(
                         np.exp(x - x.max(-1, keepdims=True)).sum(
                             -1, keepdims=True))),
    "masked_softmax": S(
        # mask keeps column 0 live so no row is fully masked
        lambda: [f(3, 4),
                 np.concatenate([np.ones((3, 1), np.int32),
                                 ints(3, 3, lo=0, hi=2)], 1)],
        {"axis": -1}, grad=False, ref=_masked_softmax_ref),
    # all-ones mask here (battery finiteness gate rejects the -inf the op
    # yields at masked slots); partial-mask path pinned by
    # test_masked_log_softmax_partial
    "masked_log_softmax": S(lambda: [f(3, 4), np.ones((3, 4), np.int32)],
                            {"axis": -1}, grad=False,
                            ref=lambda x, m: x - x.max(-1, keepdims=True)
                            - np.log(np.exp(x - x.max(-1, keepdims=True))
                                     .sum(-1, keepdims=True))),
    "softmax_cross_entropy": S(
        lambda: [f(3, 4), ints(3, lo=0, hi=4)], grad=False,
        ref=lambda x, y: np.asarray(-(
            (x - x.max(-1, keepdims=True)
             - np.log(np.exp(x - x.max(-1, keepdims=True)).sum(
                 -1, keepdims=True)))[np.arange(3), y]).sum(),
            np.float32)),
    "smooth_l1": S(lambda: [f(3, 4)], {"scalar": 1.0},
                   ref=lambda x: np.where(np.abs(x) < 1, 0.5 * x * x,
                                          np.abs(x) - 0.5)),
    # shape ops
    "reshape": S(lambda: [f(3, 4)], {"shape": (4, 3)},
                 ref=lambda x: x.reshape(4, 3)),
    "flatten": S(lambda: [f(2, 3, 4)], ref=lambda x: x.reshape(2, 12)),
    "transpose": S(lambda: [f(3, 4)], ref=lambda x: x.T),
    "swapaxes": S(lambda: [f(2, 3, 4)], {"dim1": 0, "dim2": 2},
                  ref=lambda x: x.swapaxes(0, 2)),
    "expand_dims": S(lambda: [f(3, 4)], {"axis": 1},
                     ref=lambda x: x[:, None, :]),
    "squeeze": S(lambda: [f(3, 1, 4)], {"axis": 1},
                 ref=lambda x: x.squeeze(1)),
    "broadcast_to": S(lambda: [f(1, 4)], {"shape": (3, 4)},
                      ref=lambda x: np.broadcast_to(x, (3, 4))),
    "broadcast_axis": S(lambda: [f(1, 4)], {"axis": 0, "size": 3},
                        ref=lambda x: np.broadcast_to(x, (3, 4))),
    "concat": S(lambda: [f(2, 3), f(2, 3)], {"dim": 1},
                ref=lambda a, b: np.concatenate([a, b], 1)),
    "stack": S(lambda: [f(2, 3), f(2, 3)], {"axis": 0},
               ref=lambda a, b: np.stack([a, b], 0)),
    "split": S(lambda: [f(4, 6)], {"num_outputs": 2, "axis": 1},
               ref=lambda x: tuple(np.split(x, 2, 1))),
    "split_v2": S(lambda: [f(4, 6)], {"indices": (2, 4), "axis": 1},
                  ref=lambda x: tuple(np.split(x, [2, 4], 1))),
    "slice": S(lambda: [f(4, 5)], {"begin": (1, 0), "end": (3, 4)},
               ref=lambda x: x[1:3, 0:4]),
    "slice_axis": S(lambda: [f(4, 5)], {"axis": 1, "begin": 1, "end": 4},
                    ref=lambda x: x[:, 1:4]),
    "slice_like": S(lambda: [f(4, 5), f(2, 3)],
                    ref=lambda a, b: a[:2, :3]),
    "tile": S(lambda: [f(2, 3)], {"reps": (2, 2)},
              ref=lambda x: np.tile(x, (2, 2))),
    "repeat": S(lambda: [f(2, 3)], {"repeats": 2, "axis": 1},
                ref=lambda x: np.repeat(x, 2, 1)),
    "pad": S(lambda: [f(1, 1, 3, 3)],
             {"mode": "constant", "pad_width": (0, 0, 0, 0, 1, 1, 1, 1)},
             ref=lambda x: np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))),
    "flip": S(lambda: [f(3, 4)], {"axis": 1}, ref=lambda x: x[:, ::-1]),
    "roll": S(lambda: [f(3, 4)], {"shift": 1, "axis": 1},
              ref=lambda x: np.roll(x, 1, 1)),
    "rot90": S(lambda: [f(3, 4)], {"k": 1, "axes": (0, 1)},
               ref=lambda x: np.rot90(x)),
    "diag": S(lambda: [f(4, 4)], ref=np.diag),
    "diagonal": S(lambda: [f(3, 3)], ref=np.diagonal),
    "tril": S(lambda: [f(4, 4)], ref=np.tril),
    "triu": S(lambda: [f(4, 4)], ref=np.triu),
    "trace_op": S(lambda: [f(4, 4)], ref=np.trace),
    "space_to_depth": S(lambda: [f(1, 1, 4, 4)], {"block_size": 2},
                        grad=False,
                        ref=lambda x: x.reshape(1, 1, 2, 2, 2, 2)
                        .transpose(0, 3, 5, 1, 2, 4).reshape(1, 4, 2, 2)),
    "depth_to_space": S(lambda: [f(1, 4, 2, 2)], {"block_size": 2},
                        grad=False,
                        ref=lambda x: x.reshape(1, 2, 2, 1, 2, 2)
                        .transpose(0, 3, 4, 1, 5, 2).reshape(1, 1, 4, 4)),
    "reverse": S(lambda: [f(3, 4)], {"axis": (0, 1)},
                 ref=lambda x: x[::-1, ::-1]),
    "shape_array": S(lambda: [f(3, 4)],
                     ref=lambda x: np.array([3, 4], np.int64), grad=False),
    "size_array": S(lambda: [f(3, 4)],
                    ref=lambda x: np.array([12], np.int64), grad=False),
    "cast": S(lambda: [f(3, 4)], {"dtype": "float32"}, ref=lambda x: x),
    "amp_cast": S(lambda: [f(3, 4)], {"dtype": "float32"}, ref=lambda x: x),
    "clip": S(lambda: [f(3, 4)], {"a_min": -0.5, "a_max": 0.5},
              ref=lambda x: np.clip(x, -0.5, 0.5)),
    # matmul
    "dot": S(lambda: [f(3, 4), f(4, 5)], ref=np.dot),
    "batch_dot": S(lambda: [f(2, 3, 4), f(2, 4, 5)], ref=np.matmul),
    "kron": S(lambda: [f(2, 2), f(2, 2)], ref=np.kron),
    "cross": S(lambda: [f(3, 3), f(3, 3)], ref=np.cross),
    "einsum": S(lambda: [f(2, 3), f(3, 4)], {"subscripts": "ij,jk->ik"},
                ref=lambda a, b: np.einsum("ij,jk->ik", a, b)),
    "khatri_rao": S(lambda: [f(2, 3), f(4, 3)],
                    ref=lambda a, b: np.vstack(
                        [np.kron(a[:, k], b[:, k]) for k in range(3)]).T),
    # linalg
    "linalg_gemm": S(lambda: [f(3, 4), f(4, 5), f(3, 5)],
                     ref=lambda a, b, c: a @ b + c),
    "linalg_gemm2": S(lambda: [f(3, 4), f(4, 5)], ref=lambda a, b: a @ b),
    "linalg_syrk": S(lambda: [f(3, 4)], ref=lambda a: a @ a.T),
    "linalg_trmm": S(lambda: [f(3, 3), f(3, 4)],
                     ref=lambda a, b: np.tril(a) @ b),
    "linalg_potrf": S(lambda: [_spd(3)], ref=np.linalg.cholesky,
                      grad=False),
    "linalg_potri": S(lambda: [np.linalg.cholesky(_spd(3))],
                      ref=lambda l: np.linalg.inv(l @ l.T), grad=False,
                      rtol=1e-3, atol=1e-3),
    "linalg_trsm": S(lambda: [np.tril(fpos(3, 3)) + 2 * np.eye(3, dtype=np.float32), f(3, 4)],
                     ref=lambda a, b: np.linalg.solve(np.tril(a), b),
                     grad=False),
    "linalg_det": S(lambda: [_spd(3)], ref=np.linalg.det),
    "linalg_slogdet": S(lambda: [_spd(3)], ref=np.linalg.slogdet,
                        grad=False),
    "linalg_inverse": S(lambda: [_spd(3)], ref=np.linalg.inv,
                        rtol=1e-3, atol=1e-3),
    "linalg_sumlogdiag": S(lambda: [_spd(3)],
                           ref=lambda a: np.log(np.diag(a)).sum()),
    "linalg_makediag": S(lambda: [f(4)], ref=np.diag),
    "linalg_extractdiag": S(lambda: [f(4, 4)], ref=np.diag),
    "linalg_maketrian": S(lambda: [f(6)], grad=False),
    "linalg_extracttrian": S(lambda: [f(3, 3)],
                             ref=lambda a: a[np.tril_indices(3)],
                             grad=False),
    "linalg_gelqf": S(lambda: [f(3, 4)], grad=False),
    "linalg_syevd": S(lambda: [_spd(3)], grad=False),
    # indexing
    "take": S(lambda: [f(5, 3), ints(4, hi=5)],
              ref=lambda a, i: a[i], grad=False),
    "batch_take": S(lambda: [f(3, 4), ints(3, hi=4)],
                    ref=lambda a, i: a[np.arange(3), i], grad=False),
    "pick": S(lambda: [f(3, 4), ints(3, hi=4)], {"axis": 1},
              ref=lambda a, i: a[np.arange(3), i], grad=False),
    "one_hot": S(lambda: [ints(4, hi=5)], {"depth": 5},
                 ref=lambda i: np.eye(5, dtype=np.float32)[i], grad=False),
    "gather_nd": S(lambda: [f(4, 5), np.array([[0, 1], [2, 3]], np.int32)],
                   ref=lambda a, i: a[i[0], i[1]], grad=False),
    "scatter_nd": S(lambda: [f(2), np.array([[0, 1], [2, 3]], np.int32)],
                    {"shape": (4, 5)}, grad=False,
                    ref=lambda d, i: _scatter_nd_ref(d, i, (4, 5))),
    "where_op": S(lambda: [ints(3, 4, lo=0, hi=2), f(3, 4), f(3, 4)],
                  ref=lambda c, a, b: np.where(c, a, b), grad=False),
    "where": S(lambda: [ints(3, 4, lo=0, hi=2), f(3, 4), f(3, 4)],
               ref=lambda c, a, b: np.where(c, a, b), grad=False),
    "boolean_mask": S(lambda: [f(4, 3), np.array([1, 0, 1, 1], np.int32)],
                      grad=False,
                      ref=lambda d, m: d[m.astype(bool)]),
    "index_add": S(lambda: [f(5, 3), np.array([1, 3], np.int32), f(2, 3)],
                   grad=False, ref=_index_add_ref),
    "index_copy": S(lambda: [f(5, 3), np.array([1, 3], np.int32), f(2, 3)],
                    grad=False, ref=_index_set_ref),
    "index_update": S(lambda: [f(5, 3), np.array([1, 3], np.int32),
                               f(2, 3)], grad=False, ref=_index_set_ref),
    "ravel_multi_index": S(
        lambda: [np.array([[1, 2], [0, 3]], np.int64)], {"shape": (3, 4)},
        ref=lambda d: np.ravel_multi_index((d[0], d[1]), (3, 4)),
        grad=False),
    "unravel_index": S(
        lambda: [np.array([5, 11], np.int64)], {"shape": (3, 4)},
        ref=lambda d: np.stack(np.unravel_index(d, (3, 4))), grad=False),
    "searchsorted": S(lambda: [np.sort(f(8)), f(3)], grad=False,
                      ref=np.searchsorted),
    "bincount": S(lambda: [ints(10, hi=5)], {"minlength": 5},
                  ref=lambda d: np.bincount(d, minlength=5), grad=False),
    "digitize": S(lambda: [f(5), np.sort(f(4))], grad=False,
                  ref=np.digitize),
    "histogram": S(lambda: [fpos(20)], {"bin_cnt": 5, "range": (0.0, 1.0)},
                   grad=False,
                   ref=lambda x: np.histogram(x, 5, (0.0, 1.0))),
    "interp": S(lambda: [f(4), np.sort(fpos(5)), fpos(5)], grad=False,
                ref=np.interp),
    # sorting
    "sort": S(lambda: [f(3, 6)], {"axis": -1}, ref=lambda x: np.sort(x, -1),
              grad=False),
    "argsort": S(lambda: [f(3, 6)], {"axis": -1},
                 ref=lambda x: np.argsort(x, -1).astype(np.float32),
                 grad=False),
    "topk": S(lambda: [sep(3, 6)], {"k": 2, "ret_typ": "value"}, grad=False,
              ref=lambda x: np.sort(x, -1)[:, :-3:-1]),
    "cumsum": S(lambda: [f(3, 4)], {"axis": 1},
                ref=lambda x: np.cumsum(x, 1)),
    "cumprod": S(lambda: [fpos(3, 4)], {"axis": 1},
                 ref=lambda x: np.cumprod(x, 1)),
    "cummax": S(lambda: [f(3, 4)], {"axis": 1},
                ref=lambda x: np.maximum.accumulate(x, 1), grad=False),
    "cummin": S(lambda: [f(3, 4)], {"axis": 1},
                ref=lambda x: np.minimum.accumulate(x, 1), grad=False),
    # bitwise / int
    "bitwise_and": S(lambda: [ints(3, 4), ints(3, 4)],
                     ref=np.bitwise_and, grad=False),
    "bitwise_or": S(lambda: [ints(3, 4), ints(3, 4)],
                    ref=np.bitwise_or, grad=False),
    "bitwise_xor": S(lambda: [ints(3, 4), ints(3, 4)],
                     ref=np.bitwise_xor, grad=False),
    "bitwise_not": S(lambda: [ints(3, 4)], ref=np.bitwise_not, grad=False),
    "bitwise_left_shift": S(lambda: [ints(3, 4), ints(3, 4, hi=3)],
                            ref=np.left_shift, grad=False),
    "bitwise_right_shift": S(lambda: [ints(3, 4, lo=4, hi=64),
                                      ints(3, 4, hi=3)],
                             ref=np.right_shift, grad=False),
    # special binary
    "prelu": S(lambda: [f(3, 4), fpos(1)],
               ref=lambda x, g: np.where(x >= 0, x, g * x)),
    "polygamma": S(lambda: [fpos(3)], {"n": 1}, grad=False,
                   ref=lambda x: _sp.polygamma(1, x).astype(np.float32)),
    "gammainc": S(lambda: [fpos(3), fpos(3)], grad=False,
                  ref=lambda a, x: _sp.gammainc(a, x)),
    "gammaincc": S(lambda: [fpos(3), fpos(3)], grad=False,
                   ref=lambda a, x: _sp.gammaincc(a, x)),
    # windows / creation
    "hanning": S(lambda: [], {"M": 8}, ref=lambda: np.hanning(8),
                 grad=False, rtol=1e-5, atol=1e-6),
    "hamming": S(lambda: [], {"M": 8}, ref=lambda: np.hamming(8),
                 grad=False, rtol=1e-5, atol=1e-6),
    "blackman": S(lambda: [], {"M": 8}, ref=lambda: np.blackman(8),
                  grad=False, rtol=1e-5, atol=1e-5),
    # sequence ops
    "sequence_mask": S(
        lambda: [f(4, 2, 3), np.array([2, 4], np.int32)],
        {"use_sequence_length": True}, grad=False,
        ref=lambda x, lens: _seq_mask_ref(x, lens)),
    "SequenceLast": S(
        lambda: [f(4, 2, 3), np.array([2, 4], np.int32)],
        {"use_sequence_length": True}, grad=False,
        ref=lambda x, lens: x[lens.astype(int) - 1,
                              np.arange(x.shape[1])]),
    "SequenceReverse": S(
        lambda: [f(4, 2, 3), np.array([2, 4], np.int32)],
        {"use_sequence_length": True}, grad=False,
        ref=lambda x, lens: np.stack(
            [np.concatenate([x[:L, b][::-1], x[L:, b]])
             for b, L in enumerate(lens.astype(int))], 1)),
    # NN layers (layer semantics tested in test_gluon; battery = sanity+grad)
    "FullyConnected": S(lambda: [f(3, 4), f(5, 4), f(5)],
                        {"num_hidden": 5},
                        ref=lambda x, w, b: x @ w.T + b),
    "Convolution": S(lambda: [f(1, 2, 5, 5), f(3, 2, 3, 3), f(3)],
                     {"kernel": (3, 3), "num_filter": 3}, grad=False,
                     ref=lambda x, w, b: _conv2d_ref(x, w, b)),
    "Deconvolution": S(lambda: [f(1, 2, 4, 4), f(2, 3, 3, 3), f(3)],
                       {"kernel": (3, 3), "num_filter": 3}, grad=False),
    "Pooling": S(lambda: [f(1, 2, 4, 4)],
                 {"kernel": (2, 2), "pool_type": "max", "stride": (2, 2)},
                 grad=False, ref=lambda x: _pool_max_ref(x, 2, 2)),
    "Activation": S(lambda: [f(3, 4)], {"act_type": "relu"},
                    ref=lambda x: np.maximum(x, 0)),
    "LeakyReLU": S(lambda: [f(3, 4)], {"act_type": "leaky", "slope": 0.1},
                   ref=lambda x: np.where(x > 0, x, 0.1 * x)),
    "BatchNorm": S(lambda: [f(2, 3, 4, 4), np.ones(3, np.float32),
                            np.zeros(3, np.float32),
                            np.zeros(3, np.float32),
                            np.ones(3, np.float32)], grad=False,
                   ref=lambda x, g, b, mm, mv:
                   (x - x.mean((0, 2, 3), keepdims=True))
                   / np.sqrt(x.var((0, 2, 3), keepdims=True) + 1e-5)),
    "LayerNorm": S(lambda: [f(3, 4), np.ones(4, np.float32),
                            np.zeros(4, np.float32)], grad=False,
                   rtol=1e-3, atol=1e-3,
                   ref=lambda x, g, b: (x - x.mean(-1, keepdims=True))
                   / np.sqrt(x.var(-1, keepdims=True) + 1e-5)),
    "GroupNorm": S(lambda: [f(2, 4, 3), np.ones(4, np.float32),
                            np.zeros(4, np.float32)], {"num_groups": 2},
                   grad=False, rtol=1e-3, atol=1e-3,
                   ref=lambda x, g, b:
                   ((x.reshape(2, 2, 2, 3)
                     - x.reshape(2, 2, 2, 3).mean((2, 3), keepdims=True))
                    / np.sqrt(x.reshape(2, 2, 2, 3).var((2, 3),
                                                        keepdims=True)
                              + 1e-5)).reshape(2, 4, 3)),
    "InstanceNorm": S(lambda: [f(2, 3, 4), np.ones(3, np.float32),
                               np.zeros(3, np.float32)], grad=False,
                      rtol=1e-3, atol=1e-3,
                      ref=lambda x, g, b: (x - x.mean(-1, keepdims=True))
                      / np.sqrt(x.var(-1, keepdims=True) + 1e-3)),
    "RMSNorm": S(lambda: [f(3, 4), np.ones(4, np.float32)], grad=False,
                 rtol=1e-3, atol=1e-3,
                 ref=lambda x, g: x / np.sqrt(
                     (x * x).mean(-1, keepdims=True) + 1e-6)),
    "L2Normalization": S(lambda: [f(3, 4)],
                         ref=lambda x: x / np.sqrt(
                             (x * x).sum(1, keepdims=True) + 1e-10)),
    "Embedding": S(lambda: [ints(5, hi=7), f(7, 4)],
                   {"input_dim": 7, "output_dim": 4},
                   ref=lambda i, w: w[i], grad=False),
    "Dropout": S(lambda: [f(3, 4)], {"p": 0.0}, ref=lambda x: x,
                 grad=False),
    "SoftmaxOutput": S(lambda: [f(3, 4), ints(3, hi=4)], grad=False,
                       ref=lambda x, y: np.exp(x - x.max(-1, keepdims=True))
                       / np.exp(x - x.max(-1, keepdims=True)).sum(
                           -1, keepdims=True)),
    "UpSampling": S(lambda: [f(1, 2, 3, 3)],
                    {"scale": 2, "sample_type": "nearest"}, grad=False,
                    ref=lambda x: x.repeat(2, 2).repeat(2, 3)),
    "AdaptiveAvgPooling2D": S(lambda: [f(1, 2, 4, 4)],
                              {"output_size": (2, 2)}, grad=False,
                              ref=lambda x: x.reshape(1, 2, 2, 2, 2, 2)
                              .mean((3, 5))),
    "BilinearResize2D": S(lambda: [f(1, 2, 4, 4)],
                          {"height": 8, "width": 8}, grad=False),
    "Cast": S(lambda: [f(3, 4)], {"dtype": "float32"}, ref=lambda x: x),
    "im2col": S(lambda: [f(1, 2, 4, 4)],
                {"kernel": (3, 3), "stride": (1, 1)}, grad=False),
    # spatial
    "GridGenerator": S(lambda: [np.array([[1, 0, 0, 0, 1, 0]], np.float32)],
                       {"transform_type": "affine", "target_shape": (4, 4)},
                       grad=False),
    "BilinearSampler": S(
        lambda: [f(1, 2, 4, 4),
                 np.stack(np.meshgrid(np.linspace(-1, 1, 4),
                                      np.linspace(-1, 1, 4)))[None].astype(
                     np.float32)], grad=False),
    "SpatialTransformer": S(
        lambda: [f(1, 2, 4, 4), np.array([[1, 0, 0, 0, 1, 0]], np.float32)],
        {"target_shape": (4, 4)}, grad=False),
    "ROIPooling": S(lambda: [f(1, 2, 6, 6),
                             np.array([[0, 0, 0, 4, 4]], np.float32)],
                    {"pooled_size": (2, 2), "spatial_scale": 1.0},
                    grad=False),
    "_contrib_ROIAlign": S(lambda: [f(1, 2, 6, 6),
                                    np.array([[0, 0, 0, 4, 4]], np.float32)],
                           {"pooled_size": (2, 2), "spatial_scale": 1.0},
                           grad=False),
    "Correlation": S(lambda: [f(1, 2, 4, 4), f(1, 2, 4, 4)],
                     {"max_displacement": 1}, grad=False),
    # random (moment checks happen in test_forward sanity)
    "_random_uniform": S(lambda: [], {"shape": (500,)}, grad=False),
    "_random_normal": S(lambda: [], {"shape": (500,)}, grad=False),
    "_random_gamma": S(lambda: [], {"alpha": 2.0, "beta": 1.0,
                                    "shape": (64,)}, grad=False),
    "_random_exponential": S(lambda: [], {"lam": 1.0, "shape": (64,)},
                             grad=False),
    "_random_f": S(lambda: [], {"dfnum": 5.0, "dfden": 8.0,
                                "shape": (64,)}, grad=False),
    "_random_geometric": S(lambda: [], {"p": 0.4, "shape": (64,)},
                           grad=False),
    "_random_power": S(lambda: [], {"a": 2.0, "shape": (64,)},
                       grad=False),
    "_random_poisson": S(lambda: [], {"lam": 2.0, "shape": (64,)},
                         grad=False),
    "_random_randint": S(lambda: [], {"low": 0, "high": 5, "shape": (64,)},
                         grad=False),
    "_random_bernoulli": S(lambda: [], {"prob": 0.4, "shape": (64,)},
                           grad=False),
    "_sample_multinomial": S(
        lambda: [np.full((3, 4), 0.25, np.float32)], {"shape": 2},
        grad=False),
    "sample_normal_like": S(lambda: [f(8)], grad=False),
    "shuffle": S(lambda: [f(8, 2)], grad=False),
    # detection
    "MultiBoxPrior": S(lambda: [f(1, 2, 3, 3)],
                       {"sizes": (0.5,), "ratios": (1.0,)}, grad=False),
    "MultiBoxTarget": S(
        lambda: [_anchors(), np.array([[[0, .1, .1, .4, .4]]], np.float32),
                 np.zeros((1, 3, 9), np.float32)], grad=False),
    "MultiBoxDetection": S(
        lambda: [np.full((1, 3, 9), 1 / 3, np.float32),
                 np.zeros((1, 36), np.float32), _anchors()], grad=False),
    "_contrib_box_nms": S(
        lambda: [np.array([[[0, .9, 0, 0, 1, 1], [0, .8, 0, 0, 1, 1]]],
                          np.float32)], grad=False),
    "_contrib_box_iou": S(lambda: [_boxes(3), _boxes(2)], grad=False,
                          ref=lambda a, b: _iou_ref(a, b)),
})


# --- scalar-operand family (reference: elemwise_binary_scalar_op*) --------
_SCALAR_REFS = {
    "_plus_scalar": lambda x, s: x + s,
    "_minus_scalar": lambda x, s: x - s,
    "_rminus_scalar": lambda x, s: s - x,
    "_mul_scalar": lambda x, s: x * s,
    "_div_scalar": lambda x, s: x / s,
    "_rdiv_scalar": lambda x, s: s / x,
    "_mod_scalar": lambda x, s: np.mod(x, s),
    "_power_scalar": lambda x, s: np.power(np.abs(x) + 0.5, s),
    "_rpower_scalar": lambda x, s: np.power(s, x),
    "_maximum_scalar": lambda x, s: np.maximum(x, s),
    "_minimum_scalar": lambda x, s: np.minimum(x, s),
    "_hypot_scalar": lambda x, s: np.hypot(x, s),
    "_equal_scalar": lambda x, s: (x == s).astype(np.float32),
    "_not_equal_scalar": lambda x, s: (x != s).astype(np.float32),
    "_greater_scalar": lambda x, s: (x > s).astype(np.float32),
    "_greater_equal_scalar": lambda x, s: (x >= s).astype(np.float32),
    "_lesser_scalar": lambda x, s: (x < s).astype(np.float32),
    "_lesser_equal_scalar": lambda x, s: (x <= s).astype(np.float32),
    "_logical_and_scalar":
        lambda x, s: np.logical_and(x, s).astype(np.float32),
    "_logical_or_scalar":
        lambda x, s: np.logical_or(x, s).astype(np.float32),
    "_logical_xor_scalar":
        lambda x, s: np.logical_xor(x, s).astype(np.float32),
}
for _name, _sref in _SCALAR_REFS.items():
    SPECS[_name] = S(lambda: [f(3, 4)], {"scalar": 0.7},
                     ref=(lambda r=_sref: lambda x: r(x, 0.7))())
SPECS["_power_scalar"] = S(lambda: [fpos(3, 4)], {"scalar": 1.3},
                           ref=lambda x: np.power(x, 1.3))
# numeric gradient is undefined at the min/max kink: keep the scalar
# OUTSIDE the f() value range (±[0.3, 0.9])
SPECS["_maximum_scalar"] = S(lambda: [f(3, 4)], {"scalar": 1.5},
                             ref=lambda x: np.maximum(x, 1.5))
SPECS["_minimum_scalar"] = S(lambda: [f(3, 4)], {"scalar": 1.5},
                             ref=lambda x: np.minimum(x, 1.5))
SPECS["_rmod_scalar"] = S(lambda: [fpos(3, 4)], {"scalar": 0.7},
                          ref=lambda x: np.mod(0.7, x))
SPECS["smooth_l1_scalar"] = S(
    lambda: [f(3, 4)], {"scalar": 1.0},
    ref=lambda x: np.where(np.abs(x) < 1, 0.5 * x * x, np.abs(x) - 0.5))

SPECS.update({
    # creation (init_op.cc)
    "_zeros": S(lambda: [], {"shape": (3, 4)},
                ref=lambda: np.zeros((3, 4), np.float32)),
    "_ones": S(lambda: [], {"shape": (3, 4)},
               ref=lambda: np.ones((3, 4), np.float32)),
    "_full": S(lambda: [], {"shape": (2, 3), "value": 2.5},
               ref=lambda: np.full((2, 3), 2.5, np.float32)),
    "_arange": S(lambda: [], {"start": 1.0, "stop": 7.0, "step": 2.0},
                 ref=lambda: np.arange(1.0, 7.0, 2.0, np.float32)),
    "_linspace": S(lambda: [], {"start": 0.0, "stop": 1.0, "num": 5},
                   ref=lambda: np.linspace(0, 1, 5, dtype=np.float32)),
    "_eye": S(lambda: [], {"N": 3, "M": 4, "k": 1},
              ref=lambda: np.eye(3, 4, 1, dtype=np.float32)),
    # misc tail
    "add_n": S(lambda: [f(3, 4), f(3, 4), f(3, 4)],
               ref=lambda a, b, c: a + b + c),
    "all_finite": S(lambda: [f(3, 4)],
                    ref=lambda x: np.float32([np.isfinite(x).all()])),
    "multi_all_finite": S(lambda: [f(3), f(3)], {"num_arrays": 2},
                          ref=lambda a, b: np.float32([1.0])),
    "amp_multicast": S(lambda: [f(3, 4), f(3, 4)], {"num_outputs": 2},
                       ref=lambda a, b: (a, b)),
    "cast_storage": S(lambda: [f(3, 4)], {"stype": "default"},
                      ref=lambda x: x),
    "_copyto": S(lambda: [f(3, 4)], ref=lambda x: x),
    "choose_element_0index": S(
        lambda: [f(4, 5), ints(4, hi=5).astype(np.float32)], grad=False,
        ref=lambda x, i: x[np.arange(4), i.astype(np.int64)]),
    "fill_element_0index": S(
        lambda: [f(4, 5), f(4), ints(4, hi=5).astype(np.float32)],
        grad=False,
        ref=lambda x, v, i: _fill_ref(x, v, i)),
    "reshape_like": S(lambda: [f(2, 6), f(3, 4)], ref=lambda a, b: a.reshape(3, 4)),
    "broadcast_like": S(lambda: [f(1, 4), f(3, 4)],
                        ref=lambda a, b: np.broadcast_to(a, (3, 4))),
    "diff": S(lambda: [f(3, 6)], {"n": 1, "axis": -1},
              ref=lambda x: np.diff(x, axis=-1)),
    "_onehot_encode": S(lambda: [ints(4, hi=5).astype(np.float32), f(4, 5)],
                        grad=False,
                        ref=lambda i, o: np.eye(5, dtype=np.float32)[
                            i.astype(np.int64)]),
    "_sparse_retain": S(
        lambda: [f(5, 3), np.array([0, 2], np.int32)], grad=False,
        ref=lambda x, i: np.where(
            np.isin(np.arange(5), i)[:, None], x, 0).astype(np.float32)),
    "softmax_with_length": S(
        lambda: [f(2, 5), np.array([3, 5], np.int32)], grad=False,
        ref=lambda x, ln: np.stack([
            np.concatenate([
                np.exp(x[b, :ln[b]]) / np.exp(x[b, :ln[b]]).sum(),
                np.zeros(5 - ln[b], np.float32)])
            for b in range(2)])),
    "_scatter_set_nd": S(
        lambda: [f(4, 5), f(2), np.array([[0, 2], [1, 3]], np.int32)],
        grad=False,
        ref=lambda l, r, i: _index_set_ref(l, (i[0], i[1]), r)),
    "IdentityAttachKLSparseReg": S(lambda: [fpos(4, 3)], grad=False,
                                   ref=lambda x: x),
    "_contrib_arange_like": S(lambda: [f(2, 3)], {"axis": 1}, grad=False,
                              ref=lambda x: np.arange(3, dtype=np.float32)),
    "_contrib_div_sqrt_dim": S(lambda: [f(3, 4)],
                               ref=lambda x: x / np.sqrt(4)),
    "_contrib_gradientmultiplier": S(lambda: [f(3, 4)], {"scalar": 1.0},
                                     ref=lambda x: x),
    "_contrib_index_array": S(lambda: [f(2, 3)], grad=False,
                              ref=lambda x: np.stack(
                                  np.indices(x.shape), -1)),
    "_contrib_allclose": S(lambda: [f(3, 4), f(3, 4)], grad=False,
                           ref=lambda a, b: np.asarray(
                               np.allclose(a, b), np.float32)),
    "_contrib_quadratic": S(lambda: [f(3, 4)],
                            {"a": 1.0, "b": 2.0, "c": 3.0},
                            ref=lambda x: x * x + 2 * x + 3),
    "_contrib_fft": S(
        lambda: [f(2, 8)], grad=False,
        ref=lambda x: np.stack([np.fft.fft(x, axis=-1).real,
                                np.fft.fft(x, axis=-1).imag],
                               axis=-1).reshape(2, 16).astype(np.float32)),
    "_contrib_ifft": S(
        lambda: [f(2, 16)], grad=False,
        ref=lambda x: np.fft.ifft(
            x.reshape(2, 8, 2)[..., 0] + 1j * x.reshape(2, 8, 2)[..., 1],
            axis=-1).real.astype(np.float32)),
    "_contrib_bipartite_matching": S(
        lambda: [np.array([[0.9, 0.1], [0.8, 0.7]], np.float32)],
        grad=False,
        ref=lambda x: (np.array([0., 1.], np.float32),
                       np.array([0., 1.], np.float32))),
    "_contrib_getnnz": S(lambda: [f(3, 4)], grad=False,
                         ref=lambda x: np.asarray(
                             (x != 0).sum(), np.int64)),
    "_contrib_dynamic_reshape": S(
        lambda: [f(2, 6), np.array([3, 4], np.int32)], grad=False,
        ref=lambda x, s: x.reshape(3, 4)),
    "_contrib_count_sketch": S(
        lambda: [f(3, 6), ints(6, hi=4).astype(np.float32),
                 R.choice([-1.0, 1.0], 6).astype(np.float32)],
        {"out_dim": 4}, grad=False, ref=None),
    "_contrib_hawkesll": S(
        lambda: [fpos(2, 3), fpos(3), fpos(3), fpos(2, 3),
                 fpos(2, 4), ints(2, 4, hi=3).astype(np.float32),
                 np.array([4, 3], np.float32),
                 np.array([10.0, 10.0], np.float32)],
        grad=False, ref=None),
    "_rnn_param_concat": S(lambda: [f(6), f(4)], {"dim": 0},
                           ref=lambda a, b: np.concatenate([a, b])),
    "col2im": S(
        lambda: [_im2col_np(f(1, 2, 4, 4))],
        {"output_size": (4, 4), "kernel": (2, 2), "stride": (2, 2)},
        grad=False, ref=None),
    # optimizer tail (update semantics pinned in test_optimizer for the
    # single-weight rows; here forward sanity for the fused fleets)
    # update-rule refs re-derived from the published formulas (FTML paper,
    # NAG, LAMB paper, decoupled AdamW) — independent of the op impls
    "ftml_update": S(lambda: [f(4), f(4), fpos(4), fpos(4), f(4)],
                     {"lr": 0.01, "t": 1}, grad=False,
                     ref=lambda w, g, d, v, z, b1=0.6, b2=0.999, e=1e-8:
                     -(b1 * z + (1 - b1) * g
                       - ((1 - b1) / 0.01 * (np.sqrt(
                           (b2 * v + (1 - b2) * g * g) / (1 - b2)) + e)
                          - b1 * d) * w)
                     / ((1 - b1) / 0.01 * (np.sqrt(
                         (b2 * v + (1 - b2) * g * g) / (1 - b2)) + e))),
    "mp_nag_mom_update": S(
        lambda: [f(4), f(4), f(4), f(4)], {"lr": 0.01, "momentum": 0.9},
        grad=False,
        ref=lambda w, g, m, w32: w32 - 0.01 * (g + 0.9 * (0.9 * m + g))),
    "mp_lamb_update_phase1": S(
        lambda: [f(4), f(4), f(4), fpos(4)], {"t": 1}, grad=False,
        ref=lambda g, w32, m, v, b1=0.9, b2=0.999, e=1e-6:
        ((b1 * m + (1 - b1) * g) / (1 - b1))
        / (np.sqrt((b2 * v + (1 - b2) * g * g) / (1 - b2)) + e)),
    "mp_lamb_update_phase2": S(
        lambda: [f(4), f(4), np.array(1.0, np.float32),
                 np.array(1.0, np.float32), f(4)],
        {"lr": 0.01}, grad=False,
        ref=lambda w, gu, r1, r2, w32: w32 - 0.01 * (r1 / r2) * gu),
    "mp_adamw_update": S(
        lambda: [f(4), f(4), f(4), fpos(4), f(4),
                 np.array(1.0, np.float32)],
        {"lr": 0.01}, grad=False,
        ref=lambda w, g, m, v, w32, rs, b1=0.9, b2=0.999, e=1e-8:
        w32 - 0.01 * (b1 * m + (1 - b1) * g)
        / (np.sqrt(b2 * v + (1 - b2) * g * g) + e)),
    "_contrib_group_adagrad_update": S(
        lambda: [f(4, 3), f(4, 3), fpos(4, 1)], {"lr": 0.01}, grad=False,
        ref=lambda w, g, h: w - 0.01 * g / (np.sqrt(
            h + (g * g).mean(1, keepdims=True)) + 1e-5)),
    "multi_sgd_update": S(
        lambda: [f(4), f(4), f(3), f(3)],
        {"lrs": (0.1, 0.1), "wds": (0.0, 0.0), "num_weights": 2},
        grad=False, ref=lambda w0, g0, w1, g1: (w0 - 0.1 * g0,
                                                w1 - 0.1 * g1)),
    "multi_sgd_mom_update": S(
        lambda: [f(4), f(4), np.zeros(4, np.float32),
                 f(3), f(3), np.zeros(3, np.float32)],
        {"lrs": (0.1, 0.1), "wds": (0.0, 0.0), "num_weights": 2},
        # all outputs are written back in place -> invisible to
        # test_forward; pinned by test_fleet_update_writeback
        grad=False),
    "multi_mp_sgd_update": S(
        lambda: [f(4), f(4), f(4), f(3), f(3), f(3)],
        {"lrs": (0.1, 0.1), "wds": (0.0, 0.0), "num_weights": 2},
        grad=False),
    "multi_mp_sgd_mom_update": S(
        lambda: [f(4), f(4), np.zeros(4, np.float32), f(4),
                 f(3), f(3), np.zeros(3, np.float32), f(3)],
        {"lrs": (0.1, 0.1), "wds": (0.0, 0.0), "num_weights": 2},
        grad=False),
    "multi_sum_sq": S(lambda: [f(4), f(3)], {"num_arrays": 2}, grad=False,
                      ref=lambda a, b: np.array([np.sum(a * a),
                                                 np.sum(b * b)],
                                                np.float32)),
    "multi_lars": S(
        lambda: [fpos(3), fpos(3), fpos(3), np.zeros(3, np.float32)],
        {"eta": 0.001}, grad=False,
        ref=lambda lrs, wsq, gsq, wds: lrs * np.where(
            (np.sqrt(wsq) > 0) & (np.sqrt(gsq) > 0),
            0.001 * np.sqrt(wsq) / (np.sqrt(gsq) + wds * np.sqrt(wsq)
                                    + 1e-8), 1.0)),
    "preloaded_multi_sgd_update": S(
        lambda: [f(4), f(4), f(3), f(3),
                 np.array([0.1, 0.1], np.float32),
                 np.zeros(2, np.float32)],
        {"num_weights": 2}, grad=False,
        ref=lambda w0, g0, w1, g1, lrs, wds: (w0 - 0.1 * g0,
                                              w1 - 0.1 * g1)),
    "preloaded_multi_sgd_mom_update": S(
        lambda: [f(4), f(4), np.zeros(4, np.float32),
                 f(3), f(3), np.zeros(3, np.float32),
                 np.array([0.1, 0.1], np.float32),
                 np.zeros(2, np.float32)],
        {"num_weights": 2}, grad=False),
    "preloaded_multi_mp_sgd_update": S(
        lambda: [f(4), f(4), f(4), f(3), f(3), f(3),
                 np.array([0.1, 0.1], np.float32),
                 np.zeros(2, np.float32)],
        {"num_weights": 2}, grad=False),
    "preloaded_multi_mp_sgd_mom_update": S(
        lambda: [f(4), f(4), np.zeros(4, np.float32), f(4),
                 f(3), f(3), np.zeros(3, np.float32), f(3),
                 np.array([0.1, 0.1], np.float32),
                 np.zeros(2, np.float32)],
        {"num_weights": 2}, grad=False),
    "reset_arrays": S(lambda: [f(3), f(4)], {"num_arrays": 2}, grad=False,
                      ref=lambda a, b: (np.zeros_like(a),
                                        np.zeros_like(b))),
    # nn tail
    "LRN": S(lambda: [f(2, 6, 4, 4)], {"nsize": 3}, grad=False,
             ref=_lrn_ref),
    "BlockGrad": S(lambda: [f(3, 4)], grad=False, ref=lambda x: x),
    "MakeLoss": S(lambda: [fpos(3, 4)], grad=False, ref=lambda x: x),
    "SVMOutput": S(lambda: [f(4, 5), ints(4, hi=5).astype(np.float32)],
                   grad=False, ref=lambda x, y: x),
    "SoftmaxActivation": S(
        lambda: [f(3, 4)], grad=False,
        ref=lambda x: np.exp(x - x.max(-1, keepdims=True))
        / np.exp(x - x.max(-1, keepdims=True)).sum(-1, keepdims=True)),
    "Crop": S(lambda: [f(1, 2, 6, 6)],
              {"offset": (1, 1), "h_w": (4, 4), "num_args": 1},
              grad=False, ref=lambda x: x[:, :, 1:5, 1:5]),
    "_contrib_BatchNormWithReLU": S(
        lambda: [f(2, 3, 4, 4), np.ones(3, np.float32),
                 np.zeros(3, np.float32), np.zeros(3, np.float32),
                 np.ones(3, np.float32)], grad=False, ref=None),
    "_contrib_SyncBatchNorm": S(
        lambda: [f(2, 3, 4, 4), np.ones(3, np.float32),
                 np.zeros(3, np.float32), np.zeros(3, np.float32),
                 np.ones(3, np.float32)], grad=False, ref=None),
    # image ops
    "_image_to_tensor": S(
        lambda: [ints(4, 5, 3, hi=255).astype(np.uint8)], grad=False,
        ref=lambda x: (x.astype(np.float32) / 255).transpose(2, 0, 1)),
    "_image_normalize": S(
        lambda: [fpos(3, 4, 5)],
        {"mean": (0.5, 0.5, 0.5), "std": (0.2, 0.2, 0.2)}, grad=False,
        ref=lambda x: (x - 0.5) / 0.2),
    "_image_resize": S(lambda: [ints(6, 8, 3, hi=255).astype(np.uint8)],
                       {"size": (4, 3)}, grad=False, ref=None),
    "_image_crop": S(lambda: [ints(6, 8, 3, hi=255).astype(np.uint8)],
                     {"x": 1, "y": 2, "width": 4, "height": 3}, grad=False,
                     ref=lambda x: x[2:5, 1:5, :]),
    "_image_flip_left_right": S(
        lambda: [fpos(4, 5, 3)], grad=False, ref=lambda x: x[:, ::-1, :]),
    "_image_flip_top_bottom": S(
        lambda: [fpos(4, 5, 3)], grad=False, ref=lambda x: x[::-1, :, :]),
    "_image_adjust_lighting": S(
        lambda: [fpos(4, 5, 3)], {"alpha": (0.0, 0.0, 0.0)}, grad=False,
        ref=lambda x: x),
    "_image_random_brightness": S(
        lambda: [fpos(4, 5, 3)], {"min_factor": 0.5, "max_factor": 1.5},
        grad=False),
    "_image_random_contrast": S(
        lambda: [fpos(4, 5, 3)], {"min_factor": 0.5, "max_factor": 1.5},
        grad=False),
    "_image_random_saturation": S(
        lambda: [fpos(4, 5, 3)], {"min_factor": 0.5, "max_factor": 1.5},
        grad=False),
    "_image_random_hue": S(
        lambda: [fpos(4, 5, 3)], {"min_factor": -0.1, "max_factor": 0.1},
        grad=False),
    "_image_random_color_jitter": S(
        lambda: [fpos(4, 5, 3)],
        {"brightness": 0.2, "contrast": 0.2, "saturation": 0.2,
         "hue": 0.05}, grad=False),
    "_image_random_lighting": S(lambda: [fpos(4, 5, 3)],
                                {"alpha_std": 0.05}, grad=False),
    "_image_random_flip_left_right": S(lambda: [fpos(4, 5, 3)], grad=False),
    "_image_random_flip_top_bottom": S(lambda: [fpos(4, 5, 3)], grad=False),
    "_image_imdecode": S(lambda: [_jpeg_bytes()], grad=False, ref=None),
    # random tail
    "_random_negative_binomial": S(
        lambda: [], {"k": 3, "p": 0.5, "shape": (64,)}, grad=False),
    "_random_generalized_negative_binomial": S(
        lambda: [], {"mu": 2.0, "alpha": 0.3, "shape": (64,)}, grad=False),
    "_random_pareto": S(lambda: [], {"a": 2.0, "shape": (64,)}, grad=False),
    "_random_rayleigh": S(lambda: [], {"scale": 1.5, "shape": (64,)},
                          grad=False),
    "_random_weibull": S(lambda: [], {"a": 1.5, "shape": (64,)}, grad=False),
    "_random_logistic": S(lambda: [], {"loc": 0.0, "scale": 1.0,
                                       "shape": (64,)}, grad=False),
    "_random_gumbel": S(lambda: [], {"loc": 0.0, "scale": 1.0,
                                     "shape": (64,)}, grad=False),
    "_sample_uniform": S(lambda: [np.zeros(3, np.float32),
                                  np.ones(3, np.float32)],
                         {"shape": (5,)}, grad=False),
    "_sample_normal": S(lambda: [f(3), fpos(3)], {"shape": (5,)},
                        grad=False),
    "_sample_gamma": S(lambda: [fpos(3) + 1, fpos(3)], {"shape": (5,)},
                       grad=False),
    "_sample_exponential": S(lambda: [fpos(3)], {"shape": (5,)},
                             grad=False),
    "_sample_poisson": S(lambda: [fpos(3) * 3], {"shape": (5,)},
                         grad=False),
    "_sample_negative_binomial": S(
        lambda: [np.array([2., 3., 4.], np.float32), fpos(3)],
        {"shape": (5,)}, grad=False),
    "_sample_generalized_negative_binomial": S(
        lambda: [fpos(3) * 2, fpos(3)], {"shape": (5,)}, grad=False),
    "_sample_unique_zipfian": S(lambda: [], {"range_max": 100,
                                             "shape": (8,)}, grad=False),
    # detection tail
    "_contrib_box_encode": S(
        lambda: [np.ones((1, 2), np.float32),
                 np.zeros((1, 2), np.float32),
                 np.array([[[0., 0., 1., 1.], [1., 1., 2., 2.]]],
                          np.float32),
                 np.array([[[0., 0., 1., 1.]]], np.float32)],
        grad=False, ref=None),
    "_contrib_box_decode": S(
        lambda: [np.zeros((1, 2, 4), np.float32),
                 np.array([[[0., 0., 1., 1.], [1., 1., 2., 2.]]],
                          np.float32)],
        grad=False,
        ref=lambda d, a: a),
    "_contrib_PSROIPooling": S(
        lambda: [fpos(1, 8, 6, 6),
                 np.array([[0, 0, 0, 4, 4]], np.float32)],
        {"spatial_scale": 1.0, "output_dim": 2, "pooled_size": 2},
        grad=False, ref=None),
    "Proposal": S(
        lambda: [fpos(1, 6, 4, 4), f(1, 12, 4, 4) * 0.1,
                 np.array([64., 64., 1.], np.float32)],
        {"scales": (8,), "ratios": (0.5, 1, 2), "rpn_pre_nms_top_n": 12,
         "rpn_post_nms_top_n": 4, "feature_stride": 16},
        grad=False, ref=None),
    "MultiProposal": S(
        lambda: [fpos(2, 6, 4, 4), f(2, 12, 4, 4) * 0.1,
                 np.array([64., 64., 1.], np.float32)],
        {"scales": (8,), "ratios": (0.5, 1, 2), "rpn_pre_nms_top_n": 12,
         "rpn_post_nms_top_n": 4, "feature_stride": 16},
        grad=False, ref=None),
    "_contrib_DeformableConvolution": S(
        lambda: [fpos(1, 2, 5, 5), np.zeros((1, 18, 5, 5), np.float32),
                 f(3, 2, 3, 3)],
        {"kernel": (3, 3), "pad": (1, 1), "num_filter": 3, "no_bias": True},
        grad=False,
        # zero offsets make deformable conv == plain convolution
        ref=lambda x, off, w: _conv2d_ref(x, w, np.zeros(3, np.float32),
                                          pad=1)),
    # quantized tail (numeric contracts pinned in test_quantization)
    "_contrib_quantized_batch_norm": S(
        lambda: [ints(2, 3, 4, 4, lo=-100, hi=100).astype(np.int8),
                 np.ones(3, np.float32), np.zeros(3, np.float32),
                 np.zeros(3, np.float32), np.ones(3, np.float32),
                 np.array([-1.0], np.float32), np.array([1.0], np.float32)],
        grad=False, ref=None),
    "_contrib_quantized_elemwise_add": S(
        lambda: [ints(3, 4, lo=-100, hi=100).astype(np.int8),
                 ints(3, 4, lo=-100, hi=100).astype(np.int8),
                 np.array([-1.], np.float32), np.array([1.], np.float32),
                 np.array([-1.], np.float32), np.array([1.], np.float32)],
        grad=False, ref=None),
    "_contrib_quantized_elemwise_mul": S(
        lambda: [ints(3, 4, lo=-100, hi=100).astype(np.int8),
                 ints(3, 4, lo=-100, hi=100).astype(np.int8),
                 np.array([-1.], np.float32), np.array([1.], np.float32),
                 np.array([-1.], np.float32), np.array([1.], np.float32)],
        grad=False, ref=None),
    "_contrib_quantized_embedding": S(
        lambda: [ints(5, hi=4).astype(np.float32),
                 ints(4, 6, lo=-100, hi=100).astype(np.int8),
                 np.array([-1.], np.float32), np.array([1.], np.float32)],
        grad=False, ref=None),
    "_contrib_quantized_concat": S(
        lambda: [ints(2, 3, lo=-100, hi=100).astype(np.int8),
                 ints(2, 3, lo=-100, hi=100).astype(np.int8),
                 np.array([-1.], np.float32), np.array([1.], np.float32),
                 np.array([-2.], np.float32), np.array([2.], np.float32)],
        {"num_args": 2, "dim": 0}, grad=False, ref=None),
    "_contrib_calibrate_entropy": S(
        lambda: [np.histogram(np.abs(R.randn(5000)), bins=64,
                              range=(0, 4))[0].astype(np.float32),
                 np.histogram(np.abs(R.randn(5000)), bins=64,
                              range=(0, 4))[1].astype(np.float32)],
        {"num_quantized_bins": 15}, grad=False, ref=None),
    "_contrib_intgemm_maxabsolute": S(
        lambda: [f(3, 4)], grad=False,
        ref=lambda x: np.array([np.abs(x).max()], np.float32)),
    "_contrib_intgemm_prepare_data": S(
        lambda: [f(3, 4), np.array([1.0], np.float32)], grad=False,
        ref=None),
    "_contrib_intgemm_prepare_weight": S(
        lambda: [f(3, 4), np.array([1.0], np.float32)], grad=False,
        ref=None),
    "_contrib_intgemm_take_weight": S(
        lambda: [ints(4, 6, lo=-100, hi=100).astype(np.int8),
                 ints(2, hi=4).astype(np.float32)], grad=False, ref=None),
    "_contrib_intgemm_fully_connected": S(
        lambda: [ints(2, 8, lo=-30, hi=30).astype(np.int8),
                 ints(4, 8, lo=-30, hi=30).astype(np.int8),
                 np.array([0.01], np.float32)],
        {"num_hidden": 4, "no_bias": True}, grad=False,
        ref=lambda x, w, s: (x.astype(np.int32)
                             @ w.astype(np.int32).T).astype(np.float32)
        * 0.01),
})




_MPLANS_W = f(4)


def _lans_ref(w, g, m, v, lr, wd, beta1=0.9, beta2=0.999, eps=1e-6, t=1):
    """NumPy LANS single step (the paper's Algorithm: normalized grad,
    trust ratio on momentum AND gradient terms, each incl. weight decay)."""
    g = g / max(np.sqrt(np.sum(g * g)), 1e-12)
    m1 = beta1 * m + (1 - beta1) * g
    v1 = beta2 * v + (1 - beta2) * g * g
    mh = m1 / (1 - beta1 ** t)
    vh = v1 / (1 - beta2 ** t)
    wn = np.sqrt(np.sum(w * w))

    def trust(u):
        un = np.sqrt(np.sum(u * u))
        return (wn / un if wn > 0 and un > 0 else 1.0) * u
    d = np.sqrt(vh) + eps
    upd = beta1 * trust(mh / d + wd * w) + \
        (1 - beta1) * trust(g / d + wd * w)
    return (w - lr * upd, m1, v1)


_JPEG_FILE = None


def _jpeg_file():
    """One temp jpeg per process, removed at exit (the spec table needs a
    concrete path at build time)."""
    global _JPEG_FILE
    if _JPEG_FILE is None:
        import atexit
        import os as _os
        import tempfile
        from PIL import Image
        fd, path = tempfile.mkstemp(suffix=".jpg")
        _os.close(fd)
        Image.fromarray(ints(8, 8, 3, hi=255).astype(np.uint8)).save(path)
        atexit.register(lambda: _os.path.exists(path) and _os.unlink(path))
        _JPEG_FILE = path
    return _JPEG_FILE


SPECS.update({
    # sliding-window attention (GluonNLP longformer ops)
    "_contrib_sldwin_atten_score": S(
        lambda: [f(1, 8, 2, 4), f(1, 8, 2, 4),
                 np.ones(2, np.float32)], {"w": 2, "symmetric": True},
        grad=False, ref=None),
    "_contrib_sldwin_atten_mask_like": S(
        lambda: [f(1, 8, 2, 5), np.ones(2, np.float32),
                 np.array([8.0], np.float32)], {"w": 2, "symmetric": True},
        grad=False, ref=None),
    "_contrib_sldwin_atten_context": S(
        lambda: [f(1, 8, 2, 5), f(1, 8, 2, 4),
                 np.ones(2, np.float32)], {"w": 2, "symmetric": True},
        grad=False, ref=None),
    # straight-through estimators
    # numeric-vs-autodiff comparison is wrong BY DESIGN for STEs (the
    # straight-through gradient is identity while the true one is 0 a.e.)
    # -> forward ref here, gradient pinned in test_ste_identity_gradient
    "_contrib_round_ste": S(lambda: [f(3, 4)], ref=np.rint, grad=False),
    "_contrib_sign_ste": S(lambda: [f(3, 4)], ref=np.sign, grad=False),
    # opencv-plugin parity
    "_cvimdecode": S(lambda: [_jpeg_bytes()], grad=False, ref=None),
    "_cvimread": S(lambda: [], {"filename": _jpeg_file()}, grad=False,
                   ref=None),
    "_cvimresize": S(lambda: [ints(6, 8, 3, hi=255).astype(np.uint8)],
                     {"w": 4, "h": 3}, grad=False, ref=None),
    "_cvcopyMakeBorder": S(
        lambda: [fpos(3, 4, 3)], {"top": 1, "bot": 1, "left": 2,
                                  "right": 2},
        grad=False,
        ref=lambda x: np.pad(x, ((1, 1), (2, 2), (0, 0))).astype(
            np.float32)),
    # fused adamw fleets
    "multi_lans_update": S(
        lambda: [f(4), f(4), np.zeros(4, np.float32),
                 np.zeros(4, np.float32)],
        {"learning_rates": (0.1,), "wds": (0.01,), "t": 1,
         "num_weights": 1}, grad=False,
        ref=lambda w, g, m, v: _lans_ref(w, g, m, v, 0.1, 0.01)),
    "multi_mp_lans_update": S(
        lambda: [_MPLANS_W.copy(), f(4), np.zeros(4, np.float32),
                 np.zeros(4, np.float32), _MPLANS_W.astype(np.float32)],
        {"learning_rates": (0.1,), "wds": (0.01,), "t": 1,
         "num_weights": 1}, grad=False,
        ref=lambda w, g, m, v, w32: _lans_ref(w32, g, m, v, 0.1, 0.01)),
    "multi_adamw_update": S(
        lambda: [f(4), f(4), f(4), fpos(4), f(3), f(3), f(3), fpos(3),
                 np.array(1.0, np.float32)],
        {"lrs": (0.01, 0.01), "wds": (0.0, 0.0), "num_weights": 2},
        grad=False),
    "multi_mp_adamw_update": S(
        lambda: [f(4), f(4), f(4), fpos(4), f(4),
                 f(3), f(3), f(3), fpos(3), f(3),
                 np.array(1.0, np.float32)],
        {"lrs": (0.01, 0.01), "wds": (0.0, 0.0), "num_weights": 2},
        grad=False),
    # detection tail 2
    "_contrib_edge_id": S(
        lambda: [np.array([0, 2, 3], np.float32),
                 np.array([1, 2, 0], np.float32),
                 np.array([0, 0, 1, 1], np.float32),
                 np.array([2, 0, 0, 2], np.float32)],
        grad=False,
        ref=lambda ip, ix, u, v: np.array([1.0, -1.0, 2.0, -1.0],
                                          np.float32)),
    "_contrib_DeformablePSROIPooling": S(
        lambda: [fpos(1, 8, 6, 6), np.array([[0, 0, 0, 4, 4]], np.float32),
                 np.full((1, 2, 2, 2), 0.5, np.float32)],  # (R, 2, p, p)
        {"spatial_scale": 1.0, "output_dim": 2, "group_size": 2,
         "pooled_size": 2, "part_size": 2, "trans_std": 0.1},
        grad=False, ref=None),
    "Convolution_v1": S(
        lambda: [fpos(1, 2, 5, 5), f(3, 2, 3, 3)],
        {"kernel": (3, 3), "pad": (1, 1), "num_filter": 3, "no_bias": True},
        grad=False,
        ref=lambda x, w: _conv2d_ref(x, w, np.zeros(3, np.float32),
                                     pad=1)),
    "Pooling_v1": S(
        lambda: [fpos(1, 2, 5, 5)],
        {"kernel": (2, 2), "stride": (2, 2), "pool_type": "max"},
        # v1 pooling uses the CEIL output convention (windows clipped at
        # the edge) — that is the v1/v2 behavioural difference
        grad=False, ref=lambda x: _pool_max_ref(x, 2, 2, ceil=True)),
    "_contrib_mrcnn_mask_target": S(
        lambda: [np.array([[[1., 1., 5., 5.]]], np.float32),
                 fpos(1, 2, 8, 8), np.zeros((1, 1), np.float32),
                 np.ones((1, 1), np.float32)],
        {"num_classes": 2, "mask_size": (4, 4)}, grad=False, ref=None),
    "_contrib_ModulatedDeformableConvolution": S(
        lambda: [fpos(1, 2, 5, 5), np.zeros((1, 18, 5, 5), np.float32),
                 np.ones((1, 9, 5, 5), np.float32), f(3, 2, 3, 3)],
        {"kernel": (3, 3), "pad": (1, 1), "num_filter": 3,
         "no_bias": True}, grad=False, ref=None),
})



def _fill_ref(x, v, i):
    y = x.copy()
    np.put_along_axis(y, i.astype(np.int64)[:, None], v[:, None], axis=-1)
    return y


def _im2col_np(x):
    """2x2/stride-2 im2col in the (C, kh, kw)-flattened layout."""
    B, C, H, W = x.shape
    Ho, Wo = H // 2, W // 2
    out = np.zeros((B, C * 4, Ho * Wo), np.float32)
    for c in range(C):
        for i in range(2):
            for j in range(2):
                for l in range(Ho * Wo):
                    out[:, c * 4 + i * 2 + j, l] = \
                        x[:, c, 2 * (l // Wo) + i, 2 * (l % Wo) + j]
    return out


def _jpeg_bytes():
    import io as _io
    from PIL import Image
    img = Image.fromarray(ints(8, 8, 3, hi=255).astype(np.uint8))
    buf = _io.BytesIO()
    img.save(buf, format="JPEG")
    return np.frombuffer(buf.getvalue(), np.uint8).copy()


def _spd(n):
    a = fpos(n, n)
    return (a @ a.T + n * np.eye(n, dtype=np.float32))


def _anchors():
    from mxnet_tpu.ndarray.ndarray import invoke as _inv
    return _inv("MultiBoxPrior", nd.zeros((1, 2, 3, 3)),
                sizes=(0.5,), ratios=(1.0,)).asnumpy()


# --- _npi_* numpy-semantics layer (ops/numpy_ops.py) -----------------------
# Each op mirrors one numpy function, so the reference IS that function.

_NPI_UNARY_GEN = {
    "log": fpos, "log2": fpos, "log10": fpos, "log1p": fpos, "sqrt": fpos,
    "cbrt": fpos, "arccosh": lambda *s: 1.0 + fpos(*s), "arcsin": funit,
    "arccos": funit, "arctanh": funit, "i0": fpos,
}
_NPI_UNARY = [
    "absolute", "fabs", "negative", "positive", "conjugate", "exp", "exp2",
    "expm1", "log", "log2", "log10", "log1p", "sqrt", "cbrt", "square",
    "reciprocal", "sin", "cos", "tan", "arcsin", "arccos", "arctan", "sinh",
    "cosh", "tanh", "arcsinh", "arccosh", "arctanh", "degrees", "radians",
    "deg2rad", "rad2deg", "sinc", "i0", "sign", "signbit", "floor", "ceil",
    "trunc", "rint", "fix", "isnan", "isinf", "isfinite", "isneginf",
    "isposinf", "logical_not", "real", "imag",
]
for _n in _NPI_UNARY:
    _gen = _NPI_UNARY_GEN.get(_n, f)
    SPECS["_npi_" + _n] = S(lambda g=_gen: [g(3, 4)], ref=getattr(np, _n))
SPECS["_npi_bitwise_not"] = S(lambda: [ints(3, 4)], ref=np.bitwise_not)
SPECS["_npi_invert"] = S(lambda: [ints(3, 4)], ref=np.invert)
SPECS["_npi_around"] = S(lambda: [f(3, 4)], {"decimals": 1},
                         ref=lambda x: np.around(x, 1))
SPECS["_npi_nan_to_num"] = S(lambda: [f(3, 4)], ref=np.nan_to_num)

_NPI_BINARY = [
    "add", "subtract", "multiply", "true_divide", "power", "float_power",
    "arctan2", "hypot", "logaddexp", "logaddexp2", "maximum", "minimum",
    "fmax", "fmin", "copysign", "floor_divide", "remainder", "fmod",
    "nextafter", "ldexp", "heaviside", "equal", "not_equal", "less",
    "less_equal", "greater", "greater_equal", "logical_and", "logical_or",
    "logical_xor",
]
for _n in _NPI_BINARY:
    _r = getattr(np, _n)
    if _n in ("power", "float_power"):
        SPECS["_npi_" + _n] = S(lambda: [fpos(3, 4), f(3, 4)], ref=_r)
    else:
        SPECS["_npi_" + _n] = S(lambda: [f(3, 4), fpos(3, 4)], ref=_r,
                                rtol=1e-4, atol=1e-4)
for _n in ("gcd", "lcm", "bitwise_and", "bitwise_or", "bitwise_xor"):
    SPECS["_npi_" + _n] = S(lambda: [ints(2, 5, lo=1), ints(2, 5, lo=1)],
                            ref=getattr(np, _n))
SPECS["_npi_ldexp"] = S(lambda: [f(3, 4), ints(3, 4, hi=4)], ref=np.ldexp)
SPECS["_npi_left_shift"] = S(lambda: [ints(3, 4), ints(3, 4, hi=4)],
                             ref=np.left_shift)
SPECS["_npi_right_shift"] = S(lambda: [ints(3, 4, lo=8, hi=64),
                                       ints(3, 4, hi=3)], ref=np.right_shift)
SPECS["_npi_divmod"] = S(lambda: [f(3, 4), fpos(3, 4)],
                         ref=lambda a, b: np.divmod(a, b))
SPECS["_npi_modf"] = S(lambda: [f(3, 4)], ref=lambda a: np.modf(a))
SPECS["_npi_frexp"] = S(lambda: [fpos(3, 4)], ref=lambda a: np.frexp(a))
SPECS["_npi_isclose"] = S(lambda: [f(3, 4), f(3, 4)], ref=np.isclose)
SPECS["_npi_allclose"] = S(lambda: [f(3, 4), f(3, 4)],
                           ref=lambda a, b: np.asarray(np.allclose(a, b)))
SPECS["_npi_array_equal"] = S(
    lambda: [f(3, 4), f(3, 4)],
    ref=lambda a, b: np.asarray(np.array_equal(a, b)))
SPECS["_npi_array_equiv"] = S(
    lambda: [f(3, 4), f(3, 4)],
    ref=lambda a, b: np.asarray(np.array_equiv(a, b)))

# reductions
for _n in ("sum", "prod", "mean", "nansum", "nanprod", "nanmean", "std",
           "var", "nanstd", "nanvar"):
    SPECS["_npi_" + _n] = S(lambda: [fpos(2, 3, 4)], {"axis": 1},
                            ref=(lambda r: lambda x: r(x, axis=1))(
                                getattr(np, _n)))
for _n, _r in (("amax", np.max), ("amin", np.min), ("nanmax", np.nanmax),
               ("nanmin", np.nanmin), ("ptp", np.ptp)):
    SPECS["_npi_" + _n] = S(lambda: [sep(3, 4)], {"axis": 1},
                            ref=(lambda r: lambda x: r(x, axis=1))(_r))
for _n in ("all", "any"):
    SPECS["_npi_" + _n] = S(lambda: [ints(3, 4, hi=2)], {"axis": 1},
                            ref=(lambda r: lambda x: r(x, axis=1))(
                                getattr(np, _n)))
SPECS["_npi_count_nonzero"] = S(lambda: [ints(3, 4, hi=2)], {"axis": 1},
                                ref=lambda x: np.count_nonzero(x, axis=1))
for _n in ("argmax", "argmin", "nanargmax", "nanargmin"):
    SPECS["_npi_" + _n] = S(lambda: [sep(3, 4)], {"axis": 1},
                            ref=(lambda r: lambda x: r(x, axis=1))(
                                getattr(np, _n)))
for _n in ("cumsum", "cumprod", "nancumsum", "nancumprod"):
    SPECS["_npi_" + _n] = S(lambda: [fpos(3, 4)], {"axis": 1},
                            ref=(lambda r: lambda x: r(x, axis=1))(
                                getattr(np, _n)))
SPECS["_npi_median"] = S(lambda: [sep(3, 5)], {"axis": 1},
                         ref=lambda x: np.median(x, axis=1))
SPECS["_npi_nanmedian"] = S(lambda: [sep(3, 5)], {"axis": 1},
                            ref=lambda x: np.nanmedian(x, axis=1))
SPECS["_npi_percentile"] = S(lambda: [sep(20)], {"q": 30.0},
                             ref=lambda x: np.percentile(x, 30.0),
                             grad=False)
SPECS["_npi_nanpercentile"] = S(lambda: [sep(20)], {"q": 30.0},
                                ref=lambda x: np.nanpercentile(x, 30.0),
                                grad=False)
SPECS["_npi_quantile"] = S(lambda: [sep(20)], {"q": 0.3},
                           ref=lambda x: np.quantile(x, 0.3), grad=False)
SPECS["_npi_nanquantile"] = S(lambda: [sep(20)], {"q": 0.3},
                              ref=lambda x: np.nanquantile(x, 0.3),
                              grad=False)
SPECS["_npi_average"] = S(lambda: [f(3, 4), fpos(3, 4)],
                          ref=lambda a, w: np.average(a, weights=w))
SPECS["_npi_trapz"] = S(lambda: [f(8)],
                        ref=lambda y: np.trapezoid(y)
                        if hasattr(np, "trapezoid") else np.trapz(y))

# shape manipulation
SPECS["_npi_reshape"] = S(lambda: [f(3, 4)], {"newshape": (4, 3)},
                          ref=lambda x: x.reshape(4, 3))
SPECS["_npi_ravel"] = S(lambda: [f(3, 4)], ref=np.ravel)
SPECS["_npi_transpose"] = S(lambda: [f(3, 4, 2)], {"axes": (2, 0, 1)},
                            ref=lambda x: x.transpose(2, 0, 1))
SPECS["_npi_swapaxes"] = S(lambda: [f(3, 4, 2)], {"axis1": 0, "axis2": 2},
                           ref=lambda x: np.swapaxes(x, 0, 2))
SPECS["_npi_moveaxis"] = S(lambda: [f(3, 4, 2)],
                           {"source": 0, "destination": 2},
                           ref=lambda x: np.moveaxis(x, 0, 2))
SPECS["_npi_rollaxis"] = S(lambda: [f(3, 4, 2)], {"axis": 2},
                           ref=lambda x: np.rollaxis(x, 2))
SPECS["_npi_expand_dims"] = S(lambda: [f(3, 4)], {"axis": 1},
                              ref=lambda x: np.expand_dims(x, 1))
SPECS["_npi_squeeze"] = S(lambda: [f(3, 1, 4)], {"axis": 1},
                          ref=lambda x: np.squeeze(x, 1))
SPECS["_npi_broadcast_to"] = S(lambda: [f(1, 4)], {"shape": (3, 4)},
                               ref=lambda x: np.broadcast_to(x, (3, 4)))
SPECS["_npi_flip"] = S(lambda: [f(3, 4)], {"axis": 1},
                       ref=lambda x: np.flip(x, 1))
SPECS["_npi_fliplr"] = S(lambda: [f(3, 4)], ref=np.fliplr)
SPECS["_npi_flipud"] = S(lambda: [f(3, 4)], ref=np.flipud)
SPECS["_npi_roll"] = S(lambda: [f(3, 4)], {"shift": 2, "axis": 1},
                       ref=lambda x: np.roll(x, 2, 1))
SPECS["_npi_rot90"] = S(lambda: [f(3, 4)], {"k": 1},
                        ref=lambda x: np.rot90(x, 1))
SPECS["_npi_concatenate"] = S(lambda: [f(3, 4), f(2, 4)], {"axis": 0},
                              ref=lambda a, b: np.concatenate([a, b], 0))
SPECS["_npi_stack"] = S(lambda: [f(3, 4), f(3, 4)], {"axis": 1},
                        ref=lambda a, b: np.stack([a, b], 1))
SPECS["_npi_column_stack"] = S(lambda: [f(4), f(4)],
                               ref=lambda a, b: np.column_stack([a, b]))
SPECS["_npi_hstack"] = S(lambda: [f(3, 4), f(3, 2)],
                         ref=lambda a, b: np.hstack([a, b]))
SPECS["_npi_vstack"] = S(lambda: [f(3, 4), f(2, 4)],
                         ref=lambda a, b: np.vstack([a, b]))
SPECS["_npi_dstack"] = S(lambda: [f(3, 4), f(3, 4)],
                         ref=lambda a, b: np.dstack([a, b]))
SPECS["_npi_split"] = S(lambda: [f(4, 6)],
                        {"indices_or_sections": 2, "axis": 1},
                        ref=lambda x: tuple(np.split(x, 2, 1)))
SPECS["_npi_array_split"] = S(lambda: [f(5, 4)],
                              {"indices_or_sections": 2, "axis": 0},
                              ref=lambda x: tuple(np.array_split(x, 2, 0)))
SPECS["_npi_hsplit"] = S(lambda: [f(4, 6)], {"indices_or_sections": 3},
                         ref=lambda x: tuple(np.hsplit(x, 3)))
SPECS["_npi_vsplit"] = S(lambda: [f(4, 6)], {"indices_or_sections": 2},
                         ref=lambda x: tuple(np.vsplit(x, 2)))
SPECS["_npi_dsplit"] = S(lambda: [f(2, 3, 4)], {"indices_or_sections": 2},
                         ref=lambda x: tuple(np.dsplit(x, 2)))
SPECS["_npi_repeat"] = S(lambda: [f(3, 4)], {"repeats": 2, "axis": 1},
                         ref=lambda x: np.repeat(x, 2, 1))
SPECS["_npi_tile"] = S(lambda: [f(3, 4)], {"reps": (2, 1)},
                       ref=lambda x: np.tile(x, (2, 1)))
SPECS["_npi_append"] = S(lambda: [f(3, 4), f(2, 4)], {"axis": 0},
                         ref=lambda a, b: np.append(a, b, 0))
SPECS["_npi_pad"] = S(lambda: [f(3, 4)], {"pad_width": ((1, 1), (2, 0))},
                      ref=lambda x: np.pad(x, ((1, 1), (2, 0))))
SPECS["_npi_delete"] = S(lambda: [f(5, 4)], {"obj": 2, "axis": 0},
                         ref=lambda x: np.delete(x, 2, 0))
SPECS["_npi_insert"] = S(lambda: [f(5, 4), f(1, 4)], {"obj": 2, "axis": 0},
                         ref=lambda x, v: np.insert(x, 2, v, 0))
SPECS["_npi_trim_zeros"] = S(
    lambda: [np.concatenate([[0.0, 0.0], fpos(4), [0.0]]).astype(np.float32)],
    ref=np.trim_zeros)

# indexing / selection
SPECS["_npi_take"] = S(lambda: [f(5, 4), ints(3, hi=5)], {"axis": 0},
                       ref=lambda x, i: np.take(x, i, 0))
SPECS["_npi_take_along_axis"] = S(
    lambda: [f(3, 4), np.argsort(R.rand(3, 4), 1).astype(np.int64)],
    {"axis": 1}, ref=lambda x, i: np.take_along_axis(x, i, 1))
SPECS["_npi_compress"] = S(lambda: [ints(4, hi=2), f(4, 3)], {"axis": 0},
                           ref=lambda c, x: np.compress(c.astype(bool), x, 0),
                           grad=False)
SPECS["_npi_extract"] = S(lambda: [ints(3, 4, hi=2), f(3, 4)],
                          ref=lambda c, x: np.extract(c, x), grad=False)
SPECS["_npi_choose"] = S(lambda: [ints(4, hi=3), f(4), f(4), f(4)],
                         ref=lambda i, a, b, c: np.choose(i, [a, b, c]))
SPECS["_npi_select"] = S(
    lambda: [ints(3, 4, hi=2), ints(3, 4, hi=2), f(3, 4), f(3, 4)],
    ref=lambda c1, c2, x1, x2: np.select([c1.astype(bool), c2.astype(bool)],
                                         [x1, x2]))
SPECS["_npi_where"] = S(lambda: [ints(3, 4, hi=2), f(3, 4), f(3, 4)],
                        ref=lambda c, x, y: np.where(c.astype(bool), x, y))
SPECS["_npi_nonzero"] = S(lambda: [ints(3, 4, hi=2)],
                          ref=lambda x: tuple(np.nonzero(x)), grad=False)
SPECS["_npi_flatnonzero"] = S(lambda: [ints(3, 4, hi=2)],
                              ref=np.flatnonzero, grad=False)
SPECS["_npi_argwhere"] = S(lambda: [ints(3, 4, hi=2)], ref=np.argwhere,
                           grad=False)
SPECS["_npi_searchsorted"] = S(lambda: [np.sort(f(8)), f(5)],
                               ref=np.searchsorted)
SPECS["_npi_unravel_index"] = S(lambda: [ints(5, hi=12)], {"shape": (3, 4)},
                                ref=lambda i: np.unravel_index(i, (3, 4)))
SPECS["_npi_ravel_multi_index"] = S(
    lambda: [ints(5, hi=3), ints(5, hi=4)], {"dims": (3, 4)},
    ref=lambda a, b: np.ravel_multi_index((a, b), (3, 4)))
SPECS["_npi_diag_indices_from"] = S(
    lambda: [f(4, 4)], ref=lambda x: tuple(np.diag_indices_from(x)),
    grad=False)
SPECS["_npi_tril_indices"] = S(lambda: [], {"n": 4, "k": 0},
                               ref=lambda: tuple(np.tril_indices(4)))
SPECS["_npi_triu_indices"] = S(lambda: [], {"n": 4, "k": 0},
                               ref=lambda: tuple(np.triu_indices(4)))
SPECS["_npi_indices"] = S(lambda: [], {"dimensions": (2, 3)},
                          ref=lambda: np.indices((2, 3)).astype(np.int32))

# linalg
SPECS["_npi_dot"] = S(lambda: [f(3, 4), f(4, 2)], ref=np.dot)
SPECS["_npi_vdot"] = S(lambda: [f(8), f(8)], ref=np.vdot)
SPECS["_npi_inner"] = S(lambda: [f(3, 4), f(2, 4)], ref=np.inner)
SPECS["_npi_outer"] = S(lambda: [f(3), f(4)], ref=np.outer)
SPECS["_npi_matmul"] = S(lambda: [f(2, 3, 4), f(2, 4, 5)], ref=np.matmul)
SPECS["_npi_tensordot"] = S(lambda: [f(3, 4, 5), f(4, 5, 2)],
                            {"axes": 2}, ref=lambda a, b: np.tensordot(a, b))
SPECS["_npi_trace"] = S(lambda: [f(4, 4)], ref=np.trace)

# set ops
SPECS["_npi_unique"] = S(lambda: [ints(12, hi=5)], ref=np.unique, grad=False)
SPECS["_npi_isin"] = S(lambda: [ints(3, 4), ints(5)], ref=np.isin)
SPECS["_npi_in1d"] = S(lambda: [ints(8), ints(5)],
                       ref=lambda a, b: np.isin(a.ravel(), b))
SPECS["_npi_intersect1d"] = S(lambda: [ints(8), ints(8)], ref=np.intersect1d,
                              grad=False)
SPECS["_npi_union1d"] = S(lambda: [ints(8), ints(8)], ref=np.union1d,
                          grad=False)
SPECS["_npi_setdiff1d"] = S(lambda: [ints(8), ints(8)], ref=np.setdiff1d,
                            grad=False)
SPECS["_npi_setxor1d"] = S(lambda: [ints(8), ints(8)], ref=np.setxor1d,
                           grad=False)

# sorting
SPECS["_npi_sort"] = S(lambda: [sep(3, 4)], {"axis": 1},
                       ref=lambda x: np.sort(x, 1))
SPECS["_npi_argsort"] = S(lambda: [sep(3, 4)], {"axis": 1},
                          ref=lambda x: np.argsort(x, 1))
SPECS["_npi_lexsort"] = S(lambda: [sep(6), sep(6)],
                          ref=lambda a, b: np.lexsort((a, b)))
# partition order within segments is UNSPECIFIED -> semantic test below,
# not an elementwise ref
SPECS["_npi_partition"] = S(lambda: [sep(8)], {"kth": 3}, grad=False)
SPECS["_npi_argpartition"] = S(lambda: [sep(8)], {"kth": 3}, grad=False)


def test_masked_log_softmax_partial():
    """Masked slots must be -inf and kept slots must renormalize over the
    kept set only (the battery spec uses an all-ones mask because its
    finiteness gate rejects -inf)."""
    x = f(3, 4)
    m = np.concatenate([np.ones((3, 1), np.int32),
                        ints(3, 3, lo=0, hi=2)], 1)
    got = invoke("masked_log_softmax", nd.array(x), nd.array(m),
                 axis=-1).asnumpy()
    want = _masked_log_softmax_ref(x, m)
    b = m.astype(bool)
    assert np.isneginf(got[~b]).all()
    assert_almost_equal(got[b], want[b], rtol=1e-4, atol=1e-4,
                        names=("masked_log_softmax", "ref"))


def test_fleet_update_writeback():
    """multi_* / preloaded_multi_* optimizer fleets write every output back
    in place (aux_writeback covers them all), so test_forward sees an empty
    visible return and compares nothing.  Pin the written-back weights
    against the update formulas here."""
    def arrs(*xs):
        return [nd.array(x) for x in xs]

    w1, g1, w2, g2 = f(4), f(4), f(3), f(3)
    ws = arrs(w1, w2)
    invoke("multi_sgd_update", ws[0], nd.array(g1), ws[1], nd.array(g2),
           lrs=(0.1, 0.2), wds=(0.0, 0.0), num_weights=2)
    assert_almost_equal(ws[0].asnumpy(), w1 - 0.1 * g1, 1e-5, 1e-5,
                        names=("multi_sgd w1", "ref"))
    assert_almost_equal(ws[1].asnumpy(), w2 - 0.2 * g2, 1e-5, 1e-5,
                        names=("multi_sgd w2", "ref"))

    # momentum variant, one step from zero state == plain sgd step
    ws = arrs(w1, w2)
    moms = arrs(np.zeros(4, np.float32), np.zeros(3, np.float32))
    invoke("multi_sgd_mom_update", ws[0], nd.array(g1), moms[0],
           ws[1], nd.array(g2), moms[1],
           lrs=(0.1, 0.1), wds=(0.0, 0.0), momentum=0.9, num_weights=2)
    assert_almost_equal(ws[0].asnumpy(), w1 - 0.1 * g1, 1e-5, 1e-5,
                        names=("multi_sgd_mom w1", "ref"))

    # mp variant: fp32 master weights drive the update
    ws = arrs(w1, w2)
    w32s = arrs(w1.copy(), w2.copy())
    invoke("multi_mp_sgd_update", ws[0], nd.array(g1), w32s[0],
           ws[1], nd.array(g2), w32s[1],
           lrs=(0.1, 0.1), wds=(0.0, 0.0), num_weights=2)
    assert_almost_equal(ws[0].asnumpy(), w1 - 0.1 * g1, 1e-5, 1e-5,
                        names=("multi_mp_sgd w1", "ref"))
    assert_almost_equal(w32s[1].asnumpy(), w2 - 0.1 * g2, 1e-5, 1e-5,
                        names=("multi_mp_sgd w32", "ref"))

    # preloaded variant: lrs/wds arrive as tensors
    ws = arrs(w1, w2)
    invoke("preloaded_multi_sgd_update", ws[0], nd.array(g1),
           ws[1], nd.array(g2),
           nd.array(np.array([0.1, 0.3], np.float32)),
           nd.array(np.zeros(2, np.float32)), num_weights=2)
    assert_almost_equal(ws[1].asnumpy(), w2 - 0.3 * g2, 1e-5, 1e-5,
                        names=("preloaded_multi_sgd w2", "ref"))

    # adamw fleet: one step from zero states vs the decoupled-AdamW formula
    m1, v1 = np.zeros(4, np.float32), np.zeros(4, np.float32)
    m2, v2 = np.zeros(3, np.float32), np.zeros(3, np.float32)
    ws = arrs(w1, w2)
    ms, vs = arrs(m1, m2), arrs(v1, v2)
    invoke("multi_adamw_update", ws[0], nd.array(g1), ms[0], vs[0],
           ws[1], nd.array(g2), ms[1], vs[1],
           nd.array(np.array(1.0, np.float32)),
           lrs=(0.01, 0.01), wds=(0.0, 0.0), num_weights=2)
    b1, b2, e = 0.9, 0.999, 1e-8
    nm, nv = (1 - b1) * g1, (1 - b2) * g1 * g1
    assert_almost_equal(ws[0].asnumpy(),
                        w1 - 0.01 * nm / (np.sqrt(nv) + e), 1e-5, 1e-5,
                        names=("multi_adamw w1", "ref"))


def test_npi_partition_semantics():
    x = sep(9)
    part = invoke("_npi_partition", nd.array(x), kth=4).asnumpy()
    api = invoke("_npi_argpartition", nd.array(x), kth=4).asnumpy()
    for out in (part, x[api]):
        assert out[4] == np.sort(x)[4]
        assert (out[:4] <= out[4]).all() and (out[5:] >= out[4]).all()
        assert sorted(out.tolist()) == sorted(x.tolist())
SPECS["_npi_msort"] = S(lambda: [sep(5, 3)], ref=lambda x: np.sort(x, 0))

# math misc
SPECS["_npi_clip"] = S(lambda: [f(3, 4)], {"a_min": -0.5, "a_max": 0.5},
                       ref=lambda x: np.clip(x, -0.5, 0.5))
SPECS["_npi_interp"] = S(lambda: [f(5), np.sort(f(8)), f(8)],
                         ref=np.interp, grad=False)
SPECS["_npi_ediff1d"] = S(lambda: [f(8)], ref=np.ediff1d)
SPECS["_npi_diff"] = S(lambda: [f(3, 6)], {"n": 1, "axis": 1},
                       ref=lambda x: np.diff(x, 1, 1))
SPECS["_npi_gradient"] = S(lambda: [f(4, 5)],
                           ref=lambda x: tuple(np.gradient(x)))
SPECS["_npi_convolve"] = S(lambda: [f(6), f(3)], {"mode": "full"},
                           ref=lambda a, v: np.convolve(a, v, "full"))
SPECS["_npi_correlate"] = S(lambda: [f(6), f(3)], {"mode": "valid"},
                            ref=lambda a, v: np.correlate(a, v, "valid"))
SPECS["_npi_polyval"] = S(lambda: [f(4), f(5)], ref=np.polyval)
SPECS["_npi_corrcoef"] = S(lambda: [f(3, 8)], ref=np.corrcoef, grad=False)
SPECS["_npi_cov"] = S(lambda: [f(3, 8)], ref=lambda m: np.cov(m),
                      grad=False)
SPECS["_npi_histogram"] = S(lambda: [f(20)], {"bins": 5, "range": (-1., 1.)},
                            ref=lambda x: np.histogram(x, 5, (-1., 1.)),
                            grad=False)
SPECS["_npi_bincount"] = S(lambda: [ints(12, hi=5)], ref=np.bincount,
                           grad=False)
SPECS["_npi_digitize"] = S(lambda: [f(8), np.sort(f(4))], ref=np.digitize)

# windows + creation
SPECS["_npi_bartlett"] = S(lambda: [], {"M": 8},
                           ref=lambda: np.bartlett(8), grad=False)
SPECS["_npi_kaiser"] = S(lambda: [], {"M": 8, "beta": 2.0},
                         ref=lambda: np.kaiser(8, 2.0), grad=False)
SPECS["_npi_blackman_np"] = S(lambda: [], {"M": 8},
                              ref=lambda: np.blackman(8), grad=False)
SPECS["_npi_hamming_np"] = S(lambda: [], {"M": 8},
                             ref=lambda: np.hamming(8), grad=False)
SPECS["_npi_hanning_np"] = S(lambda: [], {"M": 8},
                             ref=lambda: np.hanning(8), grad=False)
SPECS["_npi_full_like"] = S(lambda: [f(3, 4)], {"fill_value": 2.5},
                            ref=lambda x: np.full_like(x, 2.5))
SPECS["_npi_empty_like"] = S(lambda: [f(3, 4)], grad=False)  # values undef
SPECS["_npi_identity"] = S(lambda: [], {"n": 4},
                           ref=lambda: np.identity(4, np.float32))
SPECS["_npi_tri"] = S(lambda: [], {"N": 4, "k": 0},
                      ref=lambda: np.tri(4, dtype=np.float32))
SPECS["_npi_diagflat"] = S(lambda: [f(4)], {"k": 1},
                           ref=lambda x: np.diagflat(x, 1))
SPECS["_npi_vander"] = S(lambda: [f(4)], {"N": 3},
                         ref=lambda x: np.vander(x, 3))
SPECS["_npi_meshgrid"] = S(lambda: [f(3), f(4)],
                           ref=lambda a, b: tuple(np.meshgrid(a, b)))
SPECS["_npi_broadcast_arrays"] = S(
    lambda: [f(1, 4), f(3, 1)],
    ref=lambda a, b: tuple(np.broadcast_arrays(a, b)))
SPECS["_npi_logspace"] = S(lambda: [], {"start": 0.0, "stop": 2.0, "num": 5},
                           ref=lambda: np.logspace(0.0, 2.0, 5), grad=False)
SPECS["_npi_geomspace"] = S(lambda: [], {"start": 1.0, "stop": 16.0,
                                         "num": 5},
                            ref=lambda: np.geomspace(1.0, 16.0, 5),
                            grad=False)

# numpy linalg (_npi_*): deterministic factorizations get direct refs;
# sign/order-ambiguous ones (svd/qr/eigh/lstsq) are pinned by the
# reconstruction-identity test below
SPECS["_npi_solve"] = S(lambda: [_spd(4), f(4, 2)],
                        ref=np.linalg.solve, rtol=1e-3, atol=1e-3)
SPECS["_npi_pinv"] = S(lambda: [f(4, 3)], ref=np.linalg.pinv,
                       rtol=1e-3, atol=1e-3)
SPECS["_npi_cholesky"] = S(lambda: [_spd(4)], ref=np.linalg.cholesky,
                           rtol=1e-3, atol=1e-3)
SPECS["_npi_eigvalsh"] = S(lambda: [_spd(4)], ref=np.linalg.eigvalsh,
                           rtol=1e-3, atol=1e-3)
SPECS["_npi_matrix_rank"] = S(lambda: [_spd(4)], grad=False,
                              ref=lambda a: np.asarray(
                                  np.linalg.matrix_rank(a)))
SPECS["_npi_matrix_power"] = S(lambda: [_spd(3)], {"n": 3},
                               ref=lambda a: np.linalg.matrix_power(a, 3),
                               rtol=1e-3, atol=1e-3)
SPECS["_npi_multi_dot"] = S(lambda: [f(3, 4), f(4, 5), f(5, 2)],
                            ref=lambda *ms: np.linalg.multi_dot(ms))
SPECS["_npi_tensorsolve"] = S(
    lambda: [_spd(4).reshape(2, 2, 2, 2), f(2, 2)],
    ref=np.linalg.tensorsolve, rtol=1e-3, atol=1e-3, grad=False)
SPECS["_npi_tensorinv"] = S(lambda: [_spd(4).reshape(2, 2, 2, 2)],
                            ref=np.linalg.tensorinv,
                            rtol=1e-3, atol=1e-3, grad=False)
SPECS["_npi_cond"] = S(lambda: [_spd(4)], grad=False,
                       ref=lambda a: np.asarray(np.linalg.cond(a),
                                                np.float32),
                       rtol=1e-3, atol=1e-3)
SPECS["_npi_svd"] = S(lambda: [f(4, 3)], grad=False)     # sign-ambiguous
SPECS["_npi_qr"] = S(lambda: [f(4, 3)], grad=False)      # sign-ambiguous
SPECS["_npi_eigh"] = S(lambda: [_spd(4)], grad=False)    # sign-ambiguous
SPECS["_npi_lstsq"] = S(lambda: [f(5, 3), f(5, 2)], grad=False)


def test_npi_linalg_reconstruction_identities():
    """svd/qr/eigh/lstsq are unique only up to signs/order: pin them by
    the identities they must satisfy instead of elementwise refs."""
    a = f(5, 3)
    u, s, vh = (x.asnumpy() for x in invoke("_npi_svd", nd.array(a)))
    np.testing.assert_allclose((u * s) @ vh, a, rtol=1e-4, atol=1e-4)
    q, r = (x.asnumpy() for x in invoke("_npi_qr", nd.array(a)))
    np.testing.assert_allclose(q @ r, a, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(q.T @ q, np.eye(3), rtol=1e-4, atol=1e-4)
    spd = _spd(4)
    w, v = (x.asnumpy() for x in invoke("_npi_eigh", nd.array(spd)))
    np.testing.assert_allclose(v @ np.diag(w) @ v.T, spd,
                               rtol=1e-3, atol=1e-3)
    A, b = f(6, 3), f(6, 2)
    x = invoke("_npi_lstsq", nd.array(A), nd.array(b))[0].asnumpy()
    want = np.linalg.lstsq(A, b, rcond=None)[0]
    np.testing.assert_allclose(x, want, rtol=1e-3, atol=1e-3)


# numpy-era + *_like samplers: stochastic -> shape/finiteness + moments
for _n, _p in [
        ("_random_uniform_like", {}), ("_random_normal_like", {}),
        ("_random_exponential_like", {}), ("_random_gamma_like", {}),
        ("_random_poisson_like", {}), ("_random_negative_binomial_like", {}),
        ("_random_generalized_negative_binomial_like", {})]:
    SPECS[_n] = S(lambda: [fpos(64)], _p, grad=False)
for _n, _p in [
        ("_npi_uniform", {"size": (64,)}), ("_npi_normal", {"size": (64,)}),
        ("_npi_laplace", {"size": (64,)}), ("_npi_beta", {"size": (64,)}),
        ("_npi_chisquare", {"size": (64,)}), ("_npi_f", {"size": (64,)}),
        ("_npi_standard_t", {"df": 4.0, "size": (64,)}),
        ("_npi_lognormal", {"size": (64,)}),
        ("_npi_triangular", {"size": (64,)})]:
    SPECS[_n] = S(lambda: [], _p, grad=False)
SPECS["_npi_choice"] = S(lambda: [fpos(16)], {"size": (8,)}, grad=False)
SPECS["_npi_permutation"] = S(lambda: [f(8)], grad=False)


# Ops exercised by dedicated suites rather than the battery:
# _npi scalar-variant family (generated, mirroring the kernel table):
# every entry carries an independent numpy ref.  Int-domain ops use int
# inputs + is_int=True.
def _ints34():
    return ints(3, 4, hi=7) + 1


_SCALAR_FAM = {
    # name: (inputs, scalar, np forward, int_domain)
    "add": (lambda: [f(3, 4)], 1.7, np.add, False),
    "subtract": (lambda: [f(3, 4)], 1.7, np.subtract, False),
    "multiply": (lambda: [f(3, 4)], 1.7, np.multiply, False),
    "true_divide": (lambda: [f(3, 4)], 1.7, np.true_divide, False),
    "power": (lambda: [fpos(3, 4)], 1.3, np.power, False),
    "float_power": (lambda: [fpos(3, 4)], 1.3, np.float_power, False),
    "arctan2": (lambda: [f(3, 4)], 0.7, np.arctan2, False),
    "hypot": (lambda: [f(3, 4)], 0.7, np.hypot, False),
    "logaddexp": (lambda: [f(3, 4)], 0.7, np.logaddexp, False),
    "logaddexp2": (lambda: [f(3, 4)], 0.7, np.logaddexp2, False),
    "maximum": (lambda: [f(3, 4)], 0.3, np.maximum, False),
    "minimum": (lambda: [f(3, 4)], 0.3, np.minimum, False),
    "fmax": (lambda: [f(3, 4)], 0.3, np.fmax, False),
    "fmin": (lambda: [f(3, 4)], 0.3, np.fmin, False),
    "copysign": (lambda: [f(3, 4)], -1.0, np.copysign, False),
    "floor_divide": (lambda: [fpos(3, 4)], 0.7, np.floor_divide, False),
    "mod": (lambda: [fpos(3, 4)], 0.7, np.mod, False),
    "fmod": (lambda: [fpos(3, 4)], 0.7, np.fmod, False),
    "nextafter": (lambda: [f(3, 4)], 1.0, np.nextafter, False),
    "ldexp": (lambda: [f(3, 4)], 2.0,
              lambda x, s: np.ldexp(x, int(s)), True),
    "heaviside": (lambda: [f(3, 4)], 0.5, np.heaviside, False),
    "gcd": (lambda: [_ints34()], 6.0,
            lambda x, s: np.gcd(x, int(s)), True),
    "lcm": (lambda: [_ints34()], 6.0,
            lambda x, s: np.lcm(x, int(s)), True),
    "bitwise_and": (lambda: [_ints34()], 6.0,
                    lambda x, s: np.bitwise_and(x, int(s)), True),
    "bitwise_or": (lambda: [_ints34()], 6.0,
                   lambda x, s: np.bitwise_or(x, int(s)), True),
    "bitwise_xor": (lambda: [_ints34()], 6.0,
                    lambda x, s: np.bitwise_xor(x, int(s)), True),
    "left_shift": (lambda: [_ints34()], 2.0,
                   lambda x, s: np.left_shift(x, int(s)), True),
    "right_shift": (lambda: [_ints34()], 1.0,
                    lambda x, s: np.right_shift(x, int(s)), True),
    "equal": (lambda: [ints(3, 4, hi=3).astype(np.float32)], 1.0,
              np.equal, False),
    "not_equal": (lambda: [ints(3, 4, hi=3).astype(np.float32)], 1.0,
                  np.not_equal, False),
    "less": (lambda: [f(3, 4)], 0.0, np.less, False),
    "less_equal": (lambda: [f(3, 4)], 0.0, np.less_equal, False),
    "greater": (lambda: [f(3, 4)], 0.0, np.greater, False),
    "greater_equal": (lambda: [f(3, 4)], 0.0, np.greater_equal, False),
    "logical_and": (lambda: [ints(3, 4, hi=2).astype(np.float32)], 1.0,
                    np.logical_and, False),
    "logical_or": (lambda: [ints(3, 4, hi=2).astype(np.float32)], 0.0,
                   np.logical_or, False),
    "logical_xor": (lambda: [ints(3, 4, hi=2).astype(np.float32)], 1.0,
                    np.logical_xor, False),
}

_R_SCALAR = ("subtract", "true_divide", "power", "mod", "floor_divide",
             "arctan2", "copysign", "ldexp")


def _mk_scalar_spec(np_fn, scalar, refl, int_dom):
    if refl:
        ref = lambda x: np.asarray(np_fn(  # noqa: E731
            (int(scalar) if int_dom else scalar), x))
    else:
        ref = lambda x: np.asarray(np_fn(  # noqa: E731
            x, (int(scalar) if int_dom else scalar)))
    return ref


# the differentiable subset gets the numeric-gradient battery too
# (random float inputs stay clear of the max/min/copysign kinks)
_SCALAR_DIFF = {"add", "subtract", "multiply", "true_divide", "power",
                "float_power", "arctan2", "hypot", "logaddexp",
                "logaddexp2", "maximum", "minimum", "fmax", "fmin",
                "copysign"}

for _n, (_inp, _s, _np_fn, _intd) in _SCALAR_FAM.items():
    _params = {"scalar": _s}
    if _intd:
        _params["is_int"] = True
    _g = _n in _SCALAR_DIFF
    SPECS["_npi_%s_scalar" % _n] = S(
        _inp, dict(_params), grad=_g,
        ref=_mk_scalar_spec(_np_fn, _s, False, _intd))
    if _n in _R_SCALAR and _n != "ldexp":
        SPECS["_npi_r%s_scalar" % _n] = S(
            _inp, dict(_params), grad=_g,
            ref=_mk_scalar_spec(_np_fn, _s, True, _intd))

# reflected ldexp: scalar * 2**data, float exponents allowed
SPECS["_npi_rldexp_scalar"] = S(
    lambda: [f(3, 4)], {"scalar": 2.0},
    ref=lambda x: np.asarray(2.0 * np.exp2(x)))
SPECS["_npi_rnextafter_scalar"] = S(
    lambda: [f(3, 4)], {"scalar": 1.0}, grad=False,
    ref=lambda x: np.nextafter(np.float32(1.0), x))

SPECS.update({
    "_npi_mod": S(lambda: [fpos(3, 4), fpos(3, 4) + 0.5], grad=False,
                  ref=np.mod),
    "_npi_rarctan2": S(lambda: [f(3, 4), f(3, 4)],
                       ref=lambda a, b: np.arctan2(b, a)),
    "_npi_rcopysign": S(lambda: [f(3, 4), f(3, 4)],
                        ref=lambda a, b: np.copysign(b, a)),
    "_npi_rldexp": S(lambda: [f(3, 4), f(3, 4)],
                     ref=lambda a, b: np.asarray(b * np.exp2(a))),
    "_npi_spacing": S(lambda: [f(3, 4)], grad=False, ref=np.spacing),
    "_npx_nonzero": S(lambda: [ints(3, 4, hi=2).astype(np.float32)],
                      grad=False,
                      ref=lambda x: np.stack(np.nonzero(x), axis=-1)),
})


def _lamb_ref(w, g, m, v, lr, wd, beta1=0.9, beta2=0.999, eps=1e-6, t=1):
    """NumPy LAMB single step: adam moments, one trust ratio on the whole
    update (incl. weight decay)."""
    m1 = beta1 * m + (1 - beta1) * g
    v1 = beta2 * v + (1 - beta2) * g * g
    mh = m1 / (1 - beta1 ** t)
    vh = v1 / (1 - beta2 ** t)
    upd = mh / (np.sqrt(vh) + eps) + wd * w
    wn = np.sqrt(np.sum(w * w))
    un = np.sqrt(np.sum(upd * upd))
    ratio = wn / un if wn > 0 and un > 0 else 1.0
    return (w - lr * ratio * upd, m1, v1)


def _rroi_ref(data, rois, PH=2, PW=2, S=2):
    """NumPy rotated-roi-align (angle=0 case exercises the full bilinear
    sampling path)."""
    N = rois.shape[0]
    C = data.shape[1]
    out = np.zeros((N, C, PH, PW), np.float32)
    H, W = data.shape[2], data.shape[3]
    for n in range(N):
        b, cx, cy, rw, rh, ang = rois[n]
        rw, rh = max(rw, 1.0), max(rh, 1.0)
        th = ang * np.pi / 180.0
        ix = (np.arange(S) + 0.5) / S
        lx = (((np.arange(PW)[:, None] + ix) / PW) - 0.5).reshape(-1) * rw
        ly = (((np.arange(PH)[:, None] + ix) / PH) - 0.5).reshape(-1) * rh
        gx, gy = np.meshgrid(lx, ly, indexing="xy")
        sx = cx + gx * np.cos(th) - gy * np.sin(th)
        sy = cy + gx * np.sin(th) + gy * np.cos(th)
        x0 = np.clip(np.floor(sx).astype(int), 0, W - 1)
        y0 = np.clip(np.floor(sy).astype(int), 0, H - 1)
        x1 = np.clip(x0 + 1, 0, W - 1)
        y1 = np.clip(y0 + 1, 0, H - 1)
        fx = np.clip(sx, 0, W - 1) - x0
        fy = np.clip(sy, 0, H - 1) - y0
        img = data[int(b)]
        vals = (img[:, y0, x0] * (1 - fx) * (1 - fy)
                + img[:, y0, x1] * fx * (1 - fy)
                + img[:, y1, x0] * (1 - fx) * fy
                + img[:, y1, x1] * fx * fy)
        out[n] = vals.reshape(C, PH, S, PW, S).mean(axis=(2, 4))
    return out


def _slice_assign_ref(lhs, rhs, begin, end):
    out = lhs.copy()
    out[tuple(slice(b, e) for b, e in zip(begin, end))] = rhs
    return out


def _index_copy_ref(old, idx, new):
    out = old.copy()
    out[idx.astype(int)] = new
    return out


SPECS.update({
    "adagrad_update": S(
        lambda: [f(4), f(4), fpos(4)], {"lr": 0.01, "wd": 0.01},
        grad=False,
        ref=lambda w, g, h: w - 0.01 * (
            g / np.sqrt(h + g * g + 1e-7) + 0.01 * w)),
    "multi_lamb_update": S(
        lambda: [f(4), f(4), np.zeros(4, np.float32),
                 np.zeros(4, np.float32)],
        {"learning_rates": (0.1,), "wds": (0.01,), "t": 1,
         "num_weights": 1}, grad=False,
        ref=lambda w, g, m, v: _lamb_ref(w, g, m, v, 0.1, 0.01)),
    "multi_mp_lamb_update": S(
        lambda: [_MPLANS_W.copy(), f(4), np.zeros(4, np.float32),
                 np.zeros(4, np.float32), _MPLANS_W.astype(np.float32)],
        {"learning_rates": (0.1,), "wds": (0.01,), "t": 1,
         "num_weights": 1}, grad=False,
        ref=lambda w, g, m, v, w32: _lamb_ref(w32, g, m, v, 0.1, 0.01)),
    "_contrib_boolean_mask": S(
        lambda: [f(4, 3), np.array([1, 0, 1, 1], np.float32)], {},
        grad=False,
        ref=lambda d, i: d[i != 0]),
    "_contrib_index_copy": S(
        lambda: [f(5, 3), np.array([0, 2], np.int32), f(2, 3)], {},
        ref=lambda o, i, n: _index_copy_ref(o, i, n)),
    "_identity_with_attr_like_rhs": S(
        lambda: [f(3, 4), f(3, 4)], {}, ref=lambda a, b: a),
    "_slice_assign": S(
        lambda: [f(4, 5), f(2, 4)], {"begin": (1, 0), "end": (3, 4)},
        ref=lambda l, r: _slice_assign_ref(l, r, (1, 0), (3, 4))),
    "_slice_assign_scalar": S(
        lambda: [f(4, 5)], {"scalar": 2.5, "begin": (1, 0), "end": (3, 4)},
        ref=lambda l: _slice_assign_ref(
            l, np.float32(2.5), (1, 0), (3, 4))),
    "_contrib_RROIAlign": S(
        lambda: [f(1, 2, 8, 8),
                 np.array([[0, 4.0, 4.0, 4.0, 4.0, 30.0]], np.float32)],
        {"pooled_size": (2, 2), "spatial_scale": 1.0, "sampling_ratio": 2},
        grad=False,
        ref=lambda d, r: _rroi_ref(d, r)),
})


TESTED_ELSEWHERE = {
    # round-5 numpy-surface families: oracled in tests/test_numpy_extras.py
    **{op: "tests/test_numpy_extras.py" for op in (
        "_npi_fft", "_npi_ifft", "_npi_rfft", "_npi_irfft", "_npi_hfft",
        "_npi_ihfft", "_npi_fft2", "_npi_ifft2", "_npi_rfft2",
        "_npi_irfft2", "_npi_fftn", "_npi_ifftn", "_npi_rfftn",
        "_npi_irfftn", "_npi_fftfreq", "_npi_rfftfreq", "_npi_fftshift",
        "_npi_ifftshift",
        "_npi_polyadd", "_npi_polysub", "_npi_polymul", "_npi_polydiv",
        "_npi_polyder", "_npi_polyint", "_npi_polyfit", "_npi_roots",
        "_npi_poly", "_npi_kaiser", "_npi_unwrap", "_npi_spacing",
        "_npi_histogram_bin_edges", "_npi_real_if_close",
        "_npi_matrix_transpose", "_npi_place_impl", "_npi_putmask_impl",
        "_npi_dirichlet", "_npi_standard_cauchy", "_npi_standard_gamma",
        "_npi_noncentral_chisquare", "_npi_wald", "_npi_logseries",
        "_npi_vonmises", "_npi_zipf",
        "_npx_betainc", "_npx_zeta", "_npx_ndtr", "_npx_ndtri",
        "_npx_log_ndtr", "_npx_logit", "_npx_expit", "_npx_xlogy",
        "_npx_xlog1py", "_npx_entr", "_npx_rel_entr", "_npx_kl_div",
        "_npx_i0e", "_npx_i1", "_npx_i1e", "_npx_betaln",
        "_npx_bernoulli", "_npx_expi", "_npx_expn", "_npx_exp1",
        "_npx_factorial", "_npx_gammasgn", "_npx_hyp1f1",
        "_npx_multigammaln", "_npx_poch", "_npx_spence",
        "_npx_stats_norm_pdf", "_npx_stats_norm_logpdf",
        "_npx_stats_norm_cdf", "_npx_stats_norm_logcdf",
        "_npx_stats_expon_logpdf", "_npx_stats_gamma_logpdf",
        "_npx_stats_beta_logpdf", "_npx_stats_t_logpdf",
        "_npx_stats_cauchy_logpdf", "_npx_stats_laplace_logpdf",
        "_npx_stats_uniform_logpdf", "_npx_stats_poisson_pmf",
        "_npx_stats_poisson_logpmf", "_npx_stats_bernoulli_logpmf",
    )},
    "_contrib_quantize": "tests/test_quantization.py",
    "_contrib_quantize_v2": "tests/test_quantization.py",
    "_contrib_dequantize": "tests/test_quantization.py",
    "_contrib_requantize": "tests/test_quantization.py",
    "_contrib_quantized_fully_connected": "tests/test_quantization.py",
    "_contrib_quantized_conv": "tests/test_quantization.py",
    "_contrib_quantized_pooling": "tests/test_quantization.py",
    "_contrib_quantized_flatten": "tests/test_quantization.py",
    "_contrib_quantized_act": "tests/test_quantization.py",
    "LinearRegressionOutput": "tests/test_module.py",
    "MAERegressionOutput": "tests/test_module.py",
    "LogisticRegressionOutput": "tests/test_module.py",
    "_sparse_sgd_update": "tests/test_sparse.py",
    "_sparse_sgd_mom_update": "tests/test_sparse.py",
    "_sparse_adam_update": "tests/test_sparse.py",
    "RNN": "tests/test_rnn.py",
    "CTCLoss": "tests/test_loss.py",
    "multi_head_attention": "tests/test_transformer.py",
    "_contrib_interleaved_matmul_selfatt_qk": "tests/test_transformer.py",
    "_contrib_interleaved_matmul_selfatt_valatt": "tests/test_transformer.py",
    "_contrib_interleaved_matmul_encdec_qk": "tests/test_transformer.py",
    "_contrib_interleaved_matmul_encdec_valatt": "tests/test_transformer.py",
    "sgd_update": "tests/test_optimizer.py",
    "sgd_mom_update": "tests/test_optimizer.py",
    "mp_sgd_update": "tests/test_optimizer.py",
    "mp_sgd_mom_update": "tests/test_optimizer.py",
    "adam_update": "tests/test_optimizer.py",
    "adamw_update": "tests/test_optimizer.py",
    "nag_mom_update": "tests/test_optimizer.py",
    "rmsprop_update": "tests/test_optimizer.py",
    "rmspropalex_update": "tests/test_optimizer.py",
    "ftrl_update": "tests/test_optimizer.py",
    "signsgd_update": "tests/test_optimizer.py",
    "signum_update": "tests/test_optimizer.py",
    "lamb_update_phase1": "tests/test_optimizer.py",
    "lamb_update_phase2": "tests/test_optimizer.py",
    "rrelu": "stochastic activation (forward sanity only via LeakyReLU)",
    "_internal_getitem": "tests/test_ndarray.py (indexing suite)",
    "_contrib_dgl_adjacency": "tests/test_graph.py",
    "_contrib_dgl_subgraph": "tests/test_graph.py",
    "_contrib_dgl_csr_neighbor_uniform_sample": "tests/test_graph.py",
    "_contrib_dgl_csr_neighbor_non_uniform_sample": "tests/test_graph.py",
    "_contrib_dgl_graph_compact": "tests/test_graph.py",
}


def _unique_ops():
    seen = {}
    for name in registry.list_ops():
        op = registry.get_op(name)
        seen.setdefault(id(op), op.name)
    return sorted(seen.values())


def test_coverage():
    missing = [op for op in _unique_ops()
               if op not in SPECS and op not in TESTED_ELSEWHERE]
    assert not missing, ("ops without battery spec or TESTED_ELSEWHERE "
                         "entry: %s" % missing)


@pytest.mark.parametrize("opname", sorted(SPECS))
def test_forward(opname):
    spec = SPECS[opname]
    np_inputs = spec.inputs()
    nd_inputs = [nd.array(x) for x in np_inputs]
    out = invoke(opname, *nd_inputs, **spec.params)
    outs = out if isinstance(out, (list, tuple)) else [out]
    for o in outs:
        a = o.asnumpy()
        assert a.shape is not None
        if np.issubdtype(a.dtype, np.floating):
            assert np.isfinite(a).all(), "%s produced non-finite" % opname
    if spec.ref is not None:
        expect = spec.ref(*np_inputs)
        expects = expect if isinstance(expect, tuple) else (expect,)
        for o, e in zip(outs, expects):
            assert_almost_equal(o.asnumpy(), np.asarray(e),
                                rtol=spec.rtol, atol=spec.atol,
                                names=(opname, opname + "_ref"))


def _grad_specs():
    out = []
    for opname in sorted(SPECS):
        spec = SPECS[opname]
        op = registry.get_op(opname)
        do_grad = spec.grad if spec.grad is not None else op.differentiable
        if not do_grad:
            continue
        np_inputs = spec.inputs()
        if not np_inputs or any(not np.issubdtype(x.dtype, np.floating)
                                for x in np_inputs):
            continue
        out.append(opname)
    return out


@pytest.mark.parametrize("opname", _grad_specs())
def test_grad(opname):
    spec = SPECS[opname]
    np_inputs = spec.inputs()
    nd_inputs = [nd.array(x) for x in np_inputs]

    def fn(*args):
        out = invoke(opname, *args, **spec.params)
        if isinstance(out, (list, tuple)):
            out = out[0]
        return out

    check_numeric_gradient(fn, nd_inputs, rtol=spec.grad_rtol,
                           atol=spec.grad_atol)


def test_ste_identity_gradient():
    """round_ste/sign_ste must pass the incoming gradient straight through
    (reference: stes_op.cc)."""
    from mxnet_tpu import autograd
    for op in ("_contrib_round_ste", "_contrib_sign_ste"):
        x = nd.array(f(3, 4))
        x.attach_grad()
        with autograd.record():
            y = invoke(op, x)
        y.backward(nd.array(np.full((3, 4), 2.5, np.float32)))
        np.testing.assert_allclose(x.grad.asnumpy(),
                                   np.full((3, 4), 2.5), rtol=1e-6)


def test_multi_lans_matches_reference():
    """Fleet outputs are written back in place (visible return is empty),
    so the in-place results must be compared explicitly against the numpy
    LANS step — including a NONZERO weight decay inside both trust terms."""
    w_np, g_np = f(4), f(4)
    w = nd.array(w_np)
    g = nd.array(g_np)
    m = nd.array(np.zeros(4, np.float32))
    v = nd.array(np.zeros(4, np.float32))
    invoke("multi_lans_update", w, g, m, v,
           learning_rates=(0.1,), wds=(0.01,), t=1, num_weights=1)
    w_ref, m_ref, v_ref = _lans_ref(w_np, g_np, np.zeros(4, np.float32),
                                    np.zeros(4, np.float32), 0.1, 0.01)
    np.testing.assert_allclose(w.asnumpy(), w_ref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(m.asnumpy(), m_ref, rtol=1e-5)
    np.testing.assert_allclose(v.asnumpy(), v_ref, rtol=1e-5, atol=1e-9)

    # mixed-precision variant: master weights drive the math
    w2_np = f(4)
    w2 = nd.array(w2_np.astype(np.float32))
    g2_np = f(4)
    g2 = nd.array(g2_np)
    m2 = nd.array(np.zeros(4, np.float32))
    v2 = nd.array(np.zeros(4, np.float32))
    w32 = nd.array(w2_np.astype(np.float32))
    invoke("multi_mp_lans_update", w2, g2, m2, v2, w32,
           learning_rates=(0.1,), wds=(0.01,), t=1, num_weights=1)
    wr, mr, vr = _lans_ref(w2_np, g2_np, np.zeros(4, np.float32),
                           np.zeros(4, np.float32), 0.1, 0.01)
    np.testing.assert_allclose(w32.asnumpy(), wr, rtol=1e-5, atol=1e-6)


def test_multi_lamb_matches_reference():
    """LAMB fleet outputs are in-place (visible return empty) — compare the
    written-back arrays against the numpy LAMB step, nonzero weight decay.
    (The SPECS refs for these two ops never execute for the same reason;
    this test is the real comparison.)"""
    w_np, g_np = f(4), f(4)
    w, g = nd.array(w_np), nd.array(g_np)
    m = nd.array(np.zeros(4, np.float32))
    v = nd.array(np.zeros(4, np.float32))
    invoke("multi_lamb_update", w, g, m, v,
           learning_rates=(0.1,), wds=(0.01,), t=1, num_weights=1)
    w_ref, m_ref, v_ref = _lamb_ref(w_np, g_np, np.zeros(4, np.float32),
                                    np.zeros(4, np.float32), 0.1, 0.01)
    np.testing.assert_allclose(w.asnumpy(), w_ref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(m.asnumpy(), m_ref, rtol=1e-5)
    np.testing.assert_allclose(v.asnumpy(), v_ref, rtol=1e-5, atol=1e-9)

    w2_np, g2_np = f(4), f(4)
    w2 = nd.array(w2_np)
    g2 = nd.array(g2_np)
    m2 = nd.array(np.zeros(4, np.float32))
    v2 = nd.array(np.zeros(4, np.float32))
    w32 = nd.array(w2_np.astype(np.float32))
    invoke("multi_mp_lamb_update", w2, g2, m2, v2, w32,
           learning_rates=(0.1,), wds=(0.01,), t=1, num_weights=1)
    wr, mr, vr = _lamb_ref(w2_np, g2_np, np.zeros(4, np.float32),
                           np.zeros(4, np.float32), 0.1, 0.01)
    np.testing.assert_allclose(w32.asnumpy(), wr, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(m2.asnumpy(), mr, rtol=1e-5)


def test_sldwin_attention_matches_banded_reference():
    """Sliding-window attention ops vs a dense numpy banded reference
    (score gather, mask, context contraction; symmetric and causal-left
    windows, dilation > 1)."""
    rng = np.random.RandomState(0)
    B, L, H, D, w = 1, 10, 2, 4, 2
    q = rng.randn(B, L, H, D).astype(np.float32)
    k = rng.randn(B, L, H, D).astype(np.float32)
    v = rng.randn(B, L, H, D).astype(np.float32)
    for symmetric, dil in ((True, 1), (False, 1), (True, 2),
                           (False, 2)):
        dilation = np.full(H, dil, np.float32)
        offs = list(range(-w, (w if symmetric else 0) + 1))
        J = len(offs)
        score = invoke("_contrib_sldwin_atten_score", nd.array(q),
                       nd.array(k), nd.array(dilation), w=w,
                       symmetric=symmetric).asnumpy()
        assert score.shape == (B, L, H, J)
        ref = np.zeros((B, L, H, J), np.float32)
        for i in range(L):
            for jj, o in enumerate(offs):
                t = i + o * dil
                if 0 <= t < L:
                    for h in range(H):
                        ref[0, i, h, jj] = q[0, i, h] @ k[0, t, h]
        np.testing.assert_allclose(score, ref, rtol=1e-5, atol=1e-5)

        mask = invoke("_contrib_sldwin_atten_mask_like", nd.array(score),
                      nd.array(dilation), nd.array([float(L)]), w=w,
                      symmetric=symmetric).asnumpy()
        valid = np.zeros((B, L, H, J), np.float32)
        for i in range(L):
            for jj, o in enumerate(offs):
                t = i + o * dil
                valid[0, i, :, jj] = 1.0 if 0 <= t < L else 0.0
        np.testing.assert_array_equal(mask, valid)

        ctxo = invoke("_contrib_sldwin_atten_context", nd.array(score),
                      nd.array(v), nd.array(dilation), w=w,
                      symmetric=symmetric).asnumpy()
        refc = np.zeros((B, L, H, D), np.float32)
        for i in range(L):
            for jj, o in enumerate(offs):
                t = i + o * dil
                if 0 <= t < L:
                    for h in range(H):
                        refc[0, i, h] += ref[0, i, h, jj] * v[0, t, h]
        np.testing.assert_allclose(ctxo, refc, rtol=1e-4, atol=1e-4)


def test_psroi_pooling_reference():
    """PSROIPooling vs a direct numpy computation on a tiny grid."""
    data = np.arange(1 * 4 * 4 * 4, dtype=np.float32).reshape(1, 4, 4, 4)
    rois = np.array([[0, 0, 0, 3, 3]], np.float32)
    out = invoke("_contrib_PSROIPooling", nd.array(data), nd.array(rois),
                 spatial_scale=1.0, output_dim=1, pooled_size=2,
                 group_size=2).asnumpy()
    assert out.shape == (1, 1, 2, 2)
    # bin (ph, pw) averages channel ph*2+pw over its spatial window
    # roi [0,3]x[0,3] -> bins cover rows/cols [0,1.5) and [1.5,3)
    def avg(c, ys, ye, xs, xe):
        mask = np.zeros((4, 4), np.float32)
        for yy in range(4):
            for xx in range(4):
                if yy + 1 > ys and yy < ye and xx + 1 > xs and xx < xe:
                    mask[yy, xx] = 1
        return (data[0, c] * mask).sum() / max(mask.sum(), 1)
    expect = np.array([[avg(0, 0, 1.5, 0, 1.5), avg(1, 0, 1.5, 1.5, 3)],
                       [avg(2, 1.5, 3, 0, 1.5), avg(3, 1.5, 3, 1.5, 3)]],
                      np.float32)
    np.testing.assert_allclose(out[0, 0], expect, rtol=1e-5)


def test_box_encode_decode_roundtrip():
    """box_encode targets decoded against the same anchors must recover
    the matched ground-truth boxes (the SSD/R-CNN regression contract)."""
    anchors = np.array([[[0.1, 0.1, 0.4, 0.5], [0.5, 0.4, 0.9, 0.8]]],
                       np.float32)
    refs = np.array([[[0.15, 0.12, 0.45, 0.55], [0.48, 0.42, 0.88, 0.82]]],
                    np.float32)
    samples = np.ones((1, 2), np.float32)
    matches = np.array([[0, 1]], np.float32)
    targets, masks = invoke("_contrib_box_encode", nd.array(samples),
                            nd.array(matches), nd.array(anchors),
                            nd.array(refs))
    assert masks.asnumpy().min() == 1.0     # both rois positive
    # decode with matching stds recovers the refs
    decoded = invoke("_contrib_box_decode",
                     targets * nd.array(np.array([0.1, 0.1, 0.2, 0.2],
                                                 np.float32)),
                     nd.array(anchors), std0=1.0, std1=1.0, std2=1.0,
                     std3=1.0).asnumpy()
    np.testing.assert_allclose(decoded, refs, rtol=1e-4, atol=1e-5)


def test_deformable_conv_zero_offsets_equals_convolution():
    """With all offsets zero (and all-ones modulation), deformable conv
    must equal standard Convolution — the exactness anchor for the
    bilinear-sampling path."""
    rng = np.random.RandomState(0)
    x = rng.randn(1, 2, 6, 6).astype(np.float32)
    w = rng.randn(3, 2, 3, 3).astype(np.float32)
    want = invoke("Convolution", nd.array(x), nd.array(w), None,
                  kernel=(3, 3), pad=(1, 1), num_filter=3,
                  no_bias=True).asnumpy()
    got = invoke("_contrib_DeformableConvolution", nd.array(x),
                 nd.array(np.zeros((1, 18, 6, 6), np.float32)),
                 nd.array(w), kernel=(3, 3), pad=(1, 1), num_filter=3,
                 no_bias=True).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    got_v2 = invoke("_contrib_ModulatedDeformableConvolution", nd.array(x),
                    nd.array(np.zeros((1, 18, 6, 6), np.float32)),
                    nd.array(np.ones((1, 9, 6, 6), np.float32)),
                    nd.array(w), kernel=(3, 3), pad=(1, 1), num_filter=3,
                    no_bias=True).asnumpy()
    np.testing.assert_allclose(got_v2, want, rtol=1e-4, atol=1e-5)
    # half-modulation scales the output linearly
    got_half = invoke("_contrib_ModulatedDeformableConvolution",
                      nd.array(x),
                      nd.array(np.zeros((1, 18, 6, 6), np.float32)),
                      nd.array(np.full((1, 9, 6, 6), 0.5, np.float32)),
                      nd.array(w), kernel=(3, 3), pad=(1, 1), num_filter=3,
                      no_bias=True).asnumpy()
    np.testing.assert_allclose(got_half, 0.5 * want, rtol=1e-4, atol=1e-5)


def test_hawkesll_matches_slow_reference():
    """Hawkes log-likelihood vs a direct O(T²)-style numpy evaluation of
    intensity terms and the exponential-kernel compensator."""
    rng = np.random.RandomState(0)
    B, T, K = 1, 5, 2
    lda = np.full((B, K), 0.5, np.float32)
    alpha = np.array([0.2, 0.3], np.float32)
    beta = np.array([1.0, 2.0], np.float32)
    state = np.zeros((B, K), np.float32)
    lags = rng.rand(B, T).astype(np.float32)
    marks = rng.randint(0, K, (B, T)).astype(np.float32)
    valid = np.array([T], np.float32)
    tmax = np.array([float(lags.sum() + 1.0)], np.float32)
    ll, _ = invoke("_contrib_hawkesll", nd.array(lda), nd.array(alpha),
                   nd.array(beta), nd.array(state), nd.array(lags),
                   nd.array(marks), nd.array(valid), nd.array(tmax))
    # slow reference
    times = np.cumsum(lags[0])
    ll_ref = 0.0
    for i in range(T):
        k = int(marks[0, i])
        exc = 0.0
        for j in range(i):
            if int(marks[0, j]) == k:
                exc += np.exp(-beta[k] * (times[i] - times[j]))
        lam = lda[0, k] + alpha[k] * beta[k] * exc
        ll_ref += np.log(lam)
    comp = lda[0].sum() * tmax[0]
    for i in range(T):
        k = int(marks[0, i])
        comp += alpha[k] * (1 - np.exp(-beta[k] * (tmax[0] - times[i])))
    ll_ref -= comp
    np.testing.assert_allclose(float(ll.asnumpy()[0]), ll_ref, rtol=1e-4)


def test_npi_symbol_json_name_parity():
    """A 2.x-era symbol.json whose nodes use _npi_/_npx_ op names loads
    and executes through the registry aliases (numpy-era graph compat)."""
    import json as _json
    sym_json = _json.dumps({
        "nodes": [
            {"op": "null", "name": "data", "inputs": []},
            {"op": "null", "name": "w", "inputs": []},
            {"op": "_npx_fully_connected", "name": "fc",
             "attrs": {"num_hidden": "3", "no_bias": "True"},
             "inputs": [[0, 0, 0], [1, 0, 0]]},
            {"op": "_npx_relu", "name": "act", "inputs": [[2, 0, 0]]},
            {"op": "_npi_add", "name": "out",
             "inputs": [[3, 0, 0], [3, 0, 0]]},
        ],
        "arg_nodes": [0, 1],
        "node_row_ptr": [0, 1, 2, 3, 4, 5],
        "heads": [[4, 0, 0]],
        "attrs": {"mxnet_version": ["int", 20000]},
    })
    import mxnet_tpu as mx
    s = mx.sym.loads(sym_json)
    x = f(2, 4)
    w = f(3, 4)
    exe = s.bind(mx.cpu(), {"data": nd.array(x), "w": nd.array(w)})
    got = exe.forward()[0].asnumpy()
    want = 2 * np.maximum(x @ w.T, 0)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
