"""Per-operator battery: numpy-reference forward + numeric-gradient check
for EVERY registered op.

Reference: tests/python/unittest/test_operator.py (~10k lines of per-op
numpy-reference + check_numeric_gradient tests) — rebuilt as a spec table
(`SPECS`) driving three parametrized tests:

  test_forward   — invoke the op, compare against a NumPy reference (when
                   given) or assert shape/finiteness sanity,
  test_grad      — central-difference gradient check via
                   test_utils.check_numeric_gradient for differentiable ops,
  test_coverage  — every unique registry op must appear in SPECS or in
                   TESTED_ELSEWHERE (pointing at the suite that covers it);
                   adding an op without a test fails CI.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ndarray.ndarray import invoke
from mxnet_tpu.ops import registry
from mxnet_tpu.test_utils import assert_almost_equal, check_numeric_gradient

R = np.random.RandomState(7)


def f(*shape):
    """Well-conditioned float input away from singular points."""
    return (R.uniform(0.3, 0.9, shape) * R.choice([-1.0, 1.0], shape)
            ).astype(np.float32)


def fpos(*shape):
    return R.uniform(0.3, 0.9, shape).astype(np.float32)


def funit(*shape):
    return R.uniform(-0.7, 0.7, shape).astype(np.float32)


def ints(*shape, lo=0, hi=8):
    return R.randint(lo, hi, shape).astype(np.int32)


class Spec:
    def __init__(self, inputs, params=None, ref=None, grad=None, rtol=1e-4,
                 atol=1e-4, grad_rtol=1e-2, grad_atol=1e-2):
        self.inputs = inputs          # callable -> list[np.ndarray]
        self.params = params or {}
        self.ref = ref                # callable(*np_inputs) -> np / tuple
        self.grad = grad              # None = infer from registry
        self.rtol, self.atol = rtol, atol
        self.grad_rtol, self.grad_atol = grad_rtol, grad_atol


def S(inputs, params=None, ref=None, **kw):
    return Spec(inputs, params, ref, **kw)


# --- unary elementwise with direct numpy refs ------------------------------
_UNARY = {
    "abs": (np.abs, f), "negative": (np.negative, f),
    "exp": (np.exp, f), "expm1": (np.expm1, f),
    "log": (np.log, fpos), "log10": (np.log10, fpos),
    "log1p": (np.log1p, fpos), "log2": (np.log2, fpos),
    "sqrt": (np.sqrt, fpos), "rsqrt": (lambda x: 1 / np.sqrt(x), fpos),
    "cbrt": (np.cbrt, fpos), "rcbrt": (lambda x: 1 / np.cbrt(x), fpos),
    "square": (np.square, f), "reciprocal": (np.reciprocal, f),
    "sin": (np.sin, f), "cos": (np.cos, f), "tan": (np.tan, funit),
    "arcsin": (np.arcsin, funit), "arccos": (np.arccos, funit),
    "arctan": (np.arctan, f),
    "sinh": (np.sinh, f), "cosh": (np.cosh, f), "tanh": (np.tanh, f),
    "arcsinh": (np.arcsinh, f), "arccosh": (lambda x: np.arccosh(1 + x), fpos),
    "arctanh": (np.arctanh, funit),
    "sign": (np.sign, f), "ceil": (np.ceil, f), "floor": (np.floor, f),
    "trunc": (np.trunc, f), "rint": (np.rint, f), "round": (np.round, f),
    "fix": (np.fix, f),
    "sigmoid": (lambda x: 1 / (1 + np.exp(-x)), f),
    "relu": (lambda x: np.maximum(x, 0), f),
    "softsign": (lambda x: x / (1 + np.abs(x)), f),
    "identity": (lambda x: x, f),
    "erf": (None, f), "erfc": (None, f), "erfinv": (None, funit),
    "gamma": (None, fpos), "gammaln": (None, fpos), "digamma": (None, fpos),
    "radians": (np.radians, f), "degrees": (np.degrees, f),
    "sinc": (np.sinc, f), "i0": (None, fpos),
    "selu": (None, f), "gelu": (None, f), "silu": (None, f),
    "mish": (None, f), "elu": (None, f), "softrelu": (None, f),
    "log_sigmoid": (None, f),
    "hard_sigmoid": (None, f), "hard_swish": (None, f),
    "isnan": (np.isnan, f), "isinf": (np.isinf, f),
    "isfinite": (np.isfinite, f),
    "logical_not": (lambda x: np.logical_not(x).astype(np.float32), f),
    "zeros_like_op": (np.zeros_like, f), "ones_like_op": (np.ones_like, f),
    "atleast_1d": (np.atleast_1d, f), "atleast_2d": (np.atleast_2d, f),
    "atleast_3d": (np.atleast_3d, f),
    "nan_to_num": (np.nan_to_num, f),
}

# --- binary broadcast with numpy refs --------------------------------------
_BINARY = {
    "broadcast_add": np.add, "broadcast_sub": np.subtract,
    "broadcast_mul": np.multiply, "broadcast_div": np.divide,
    "broadcast_maximum": np.maximum, "broadcast_minimum": np.minimum,
    "broadcast_hypot": np.hypot, "hypot": np.hypot,


    "broadcast_equal": lambda a, b: (a == b).astype(np.float32),
    "broadcast_not_equal": lambda a, b: (a != b).astype(np.float32),
    "broadcast_greater": lambda a, b: (a > b).astype(np.float32),
    "broadcast_greater_equal": lambda a, b: (a >= b).astype(np.float32),
    "broadcast_lesser": lambda a, b: (a < b).astype(np.float32),
    "broadcast_lesser_equal": lambda a, b: (a <= b).astype(np.float32),
    "broadcast_logical_and": lambda a, b: np.logical_and(a, b).astype(np.float32),
    "broadcast_logical_or": lambda a, b: np.logical_or(a, b).astype(np.float32),
    "broadcast_logical_xor": lambda a, b: np.logical_xor(a, b).astype(np.float32),
    "arctan2": np.arctan2, "copysign": np.copysign,
    "logaddexp": np.logaddexp, "fmod": None, "nextafter": np.nextafter,
    "heaviside": np.heaviside, "ldexp": None,
}

SPECS = {}
for _name, (_ref, _gen) in _UNARY.items():
    SPECS[_name] = S(lambda g=_gen: [g(3, 4)], ref=_ref)
for _name, _ref in _BINARY.items():
    SPECS[_name] = S(lambda: [f(3, 4), fpos(3, 4)], ref=_ref)

SPECS.update({
    "arccosh": S(lambda: [1.0 + fpos(3, 4)], ref=np.arccosh),
    "broadcast_mod": S(lambda: [f(3, 4), fpos(3, 4)], grad=False),
    "broadcast_power": S(lambda: [fpos(3, 4), f(3, 4)], ref=np.power),
    "nextafter": S(lambda: [f(3, 4), fpos(3, 4)], ref=np.nextafter,
                   grad=False),
    "lerp": S(lambda: [f(3, 4), f(3, 4), fpos(3, 4)],
              ref=lambda a, b, w: a + w * (b - a)),
    # reductions
    "sum": S(lambda: [f(2, 3, 4)], {"axis": (0, 2)},
             ref=lambda x: x.sum(axis=(0, 2))),
    "mean": S(lambda: [f(2, 3, 4)], {"axis": 1}, ref=lambda x: x.mean(1)),
    "max": S(lambda: [f(3, 4)], {"axis": 1}, ref=lambda x: x.max(1)),
    "min": S(lambda: [f(3, 4)], {"axis": 0}, ref=lambda x: x.min(0)),
    "prod": S(lambda: [fpos(3, 4)], {"axis": 1}, ref=lambda x: x.prod(1)),
    "nansum": S(lambda: [f(3, 4)], ref=np.nansum),
    "nanprod": S(lambda: [fpos(3, 4)], ref=np.nanprod),
    "norm": S(lambda: [f(3, 4)], {"ord": 2},
              ref=lambda x: np.sqrt((x * x).sum())),
    "std": S(lambda: [f(3, 4)], {"axis": 1}, ref=lambda x: x.std(1)),
    "var": S(lambda: [f(3, 4)], {"axis": 1}, ref=lambda x: x.var(1)),
    "ptp": S(lambda: [f(3, 4)], {"axis": 1}, ref=lambda x: np.ptp(x, 1)),
    "median": S(lambda: [f(3, 5)], {"axis": 1},
                ref=lambda x: np.median(x, 1), grad=False),
    "quantile": S(lambda: [f(3, 5)], {"q": 0.5, "axis": 1},
                  ref=lambda x: np.quantile(x, 0.5, 1), grad=False),
    "percentile": S(lambda: [f(3, 5)], {"q": 30.0, "axis": 1},
                    ref=lambda x: np.percentile(x, 30.0, 1), grad=False),
    "average": S(lambda: [f(3, 4)], {"axis": 1}, ref=lambda x: x.mean(1)),
    "logsumexp": S(lambda: [f(3, 4)], {"axis": 1},
                   ref=lambda x: np.log(np.exp(x).sum(1))),
    "moments": S(lambda: [f(3, 4)], {"axes": (0, 1)},
                 ref=lambda x: (x.mean(), x.var())),
    "argmax": S(lambda: [f(3, 4)], {"axis": 1},
                ref=lambda x: x.argmax(1).astype(np.float32)),
    "argmin": S(lambda: [f(3, 4)], {"axis": 1},
                ref=lambda x: x.argmin(1).astype(np.float32)),
    "argmax_channel": S(lambda: [f(3, 4)],
                        ref=lambda x: x.argmax(1).astype(np.float32)),
    # softmax family
    "softmax": S(lambda: [f(3, 4)], {"axis": -1},
                 ref=lambda x: np.exp(x) / np.exp(x).sum(-1, keepdims=True)),
    "softmin": S(lambda: [f(3, 4)], {"axis": -1},
                 ref=lambda x: np.exp(-x) / np.exp(-x).sum(-1, keepdims=True)),
    "log_softmax": S(lambda: [f(3, 4)], {"axis": -1},
                     ref=lambda x: x - x.max(-1, keepdims=True) - np.log(
                         np.exp(x - x.max(-1, keepdims=True)).sum(
                             -1, keepdims=True))),
    "masked_softmax": S(lambda: [f(3, 4), ints(3, 4, lo=0, hi=2)],
                        {"axis": -1}, grad=False),
    "masked_log_softmax": S(lambda: [f(3, 4), np.ones((3, 4), np.int32)],
                            {"axis": -1}, grad=False),
    "softmax_cross_entropy": S(
        lambda: [f(3, 4), ints(3, lo=0, hi=4)], grad=False),
    "smooth_l1": S(lambda: [f(3, 4)], {"scalar": 1.0},
                   ref=lambda x: np.where(np.abs(x) < 1, 0.5 * x * x,
                                          np.abs(x) - 0.5)),
    # shape ops
    "reshape": S(lambda: [f(3, 4)], {"shape": (4, 3)},
                 ref=lambda x: x.reshape(4, 3)),
    "flatten": S(lambda: [f(2, 3, 4)], ref=lambda x: x.reshape(2, 12)),
    "transpose": S(lambda: [f(3, 4)], ref=lambda x: x.T),
    "swapaxes": S(lambda: [f(2, 3, 4)], {"dim1": 0, "dim2": 2},
                  ref=lambda x: x.swapaxes(0, 2)),
    "expand_dims": S(lambda: [f(3, 4)], {"axis": 1},
                     ref=lambda x: x[:, None, :]),
    "squeeze": S(lambda: [f(3, 1, 4)], {"axis": 1},
                 ref=lambda x: x.squeeze(1)),
    "broadcast_to": S(lambda: [f(1, 4)], {"shape": (3, 4)},
                      ref=lambda x: np.broadcast_to(x, (3, 4))),
    "broadcast_axis": S(lambda: [f(1, 4)], {"axis": 0, "size": 3},
                        ref=lambda x: np.broadcast_to(x, (3, 4))),
    "concat": S(lambda: [f(2, 3), f(2, 3)], {"dim": 1},
                ref=lambda a, b: np.concatenate([a, b], 1)),
    "stack": S(lambda: [f(2, 3), f(2, 3)], {"axis": 0},
               ref=lambda a, b: np.stack([a, b], 0)),
    "split": S(lambda: [f(4, 6)], {"num_outputs": 2, "axis": 1},
               ref=lambda x: tuple(np.split(x, 2, 1))),
    "split_v2": S(lambda: [f(4, 6)], {"indices": (2, 4), "axis": 1},
                  ref=lambda x: tuple(np.split(x, [2, 4], 1))),
    "slice": S(lambda: [f(4, 5)], {"begin": (1, 0), "end": (3, 4)},
               ref=lambda x: x[1:3, 0:4]),
    "slice_axis": S(lambda: [f(4, 5)], {"axis": 1, "begin": 1, "end": 4},
                    ref=lambda x: x[:, 1:4]),
    "slice_like": S(lambda: [f(4, 5), f(2, 3)],
                    ref=lambda a, b: a[:2, :3]),
    "tile": S(lambda: [f(2, 3)], {"reps": (2, 2)},
              ref=lambda x: np.tile(x, (2, 2))),
    "repeat": S(lambda: [f(2, 3)], {"repeats": 2, "axis": 1},
                ref=lambda x: np.repeat(x, 2, 1)),
    "pad": S(lambda: [f(1, 1, 3, 3)],
             {"mode": "constant", "pad_width": (0, 0, 0, 0, 1, 1, 1, 1)},
             ref=lambda x: np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))),
    "flip": S(lambda: [f(3, 4)], {"axis": 1}, ref=lambda x: x[:, ::-1]),
    "roll": S(lambda: [f(3, 4)], {"shift": 1, "axis": 1},
              ref=lambda x: np.roll(x, 1, 1)),
    "rot90": S(lambda: [f(3, 4)], {"k": 1, "axes": (0, 1)},
               ref=lambda x: np.rot90(x)),
    "diag": S(lambda: [f(4, 4)], ref=np.diag),
    "diagonal": S(lambda: [f(3, 3)], ref=np.diagonal),
    "tril": S(lambda: [f(4, 4)], ref=np.tril),
    "triu": S(lambda: [f(4, 4)], ref=np.triu),
    "trace_op": S(lambda: [f(4, 4)], ref=np.trace),
    "space_to_depth": S(lambda: [f(1, 1, 4, 4)], {"block_size": 2},
                        grad=False),
    "depth_to_space": S(lambda: [f(1, 4, 2, 2)], {"block_size": 2},
                        grad=False),
    "reverse": S(lambda: [f(3, 4)], {"axis": 0}, ref=lambda x: x[::-1]),
    "shape_array": S(lambda: [f(3, 4)],
                     ref=lambda x: np.array([3, 4], np.int64), grad=False),
    "size_array": S(lambda: [f(3, 4)],
                    ref=lambda x: np.array([12], np.int64), grad=False),
    "cast": S(lambda: [f(3, 4)], {"dtype": "float32"}, ref=lambda x: x),
    "amp_cast": S(lambda: [f(3, 4)], {"dtype": "float32"}, ref=lambda x: x),
    "clip": S(lambda: [f(3, 4)], {"a_min": -0.5, "a_max": 0.5},
              ref=lambda x: np.clip(x, -0.5, 0.5)),
    # matmul
    "dot": S(lambda: [f(3, 4), f(4, 5)], ref=np.dot),
    "batch_dot": S(lambda: [f(2, 3, 4), f(2, 4, 5)], ref=np.matmul),
    "kron": S(lambda: [f(2, 2), f(2, 2)], ref=np.kron),
    "cross": S(lambda: [f(3, 3), f(3, 3)], ref=np.cross),
    "einsum": S(lambda: [f(2, 3), f(3, 4)], {"subscripts": "ij,jk->ik"},
                ref=lambda a, b: np.einsum("ij,jk->ik", a, b)),
    "khatri_rao": S(lambda: [f(2, 3), f(4, 3)],
                    ref=lambda a, b: np.vstack(
                        [np.kron(a[:, k], b[:, k]) for k in range(3)]).T),
    # linalg
    "linalg_gemm": S(lambda: [f(3, 4), f(4, 5), f(3, 5)],
                     ref=lambda a, b, c: a @ b + c),
    "linalg_gemm2": S(lambda: [f(3, 4), f(4, 5)], ref=lambda a, b: a @ b),
    "linalg_syrk": S(lambda: [f(3, 4)], ref=lambda a: a @ a.T),
    "linalg_trmm": S(lambda: [f(3, 3), f(3, 4)],
                     ref=lambda a, b: np.tril(a) @ b),
    "linalg_potrf": S(lambda: [_spd(3)], ref=np.linalg.cholesky,
                      grad=False),
    "linalg_potri": S(lambda: [np.linalg.cholesky(_spd(3))],
                      ref=lambda l: np.linalg.inv(l @ l.T), grad=False,
                      rtol=1e-3, atol=1e-3),
    "linalg_trsm": S(lambda: [np.tril(fpos(3, 3)) + 2 * np.eye(3, dtype=np.float32), f(3, 4)],
                     ref=lambda a, b: np.linalg.solve(np.tril(a), b),
                     grad=False),
    "linalg_det": S(lambda: [_spd(3)], ref=np.linalg.det),
    "linalg_slogdet": S(lambda: [_spd(3)], ref=np.linalg.slogdet,
                        grad=False),
    "linalg_inverse": S(lambda: [_spd(3)], ref=np.linalg.inv,
                        rtol=1e-3, atol=1e-3),
    "linalg_sumlogdiag": S(lambda: [_spd(3)],
                           ref=lambda a: np.log(np.diag(a)).sum()),
    "linalg_makediag": S(lambda: [f(4)], ref=np.diag),
    "linalg_extractdiag": S(lambda: [f(4, 4)], ref=np.diag),
    "linalg_maketrian": S(lambda: [f(6)], grad=False),
    "linalg_extracttrian": S(lambda: [f(3, 3)],
                             ref=lambda a: a[np.tril_indices(3)],
                             grad=False),
    "linalg_gelqf": S(lambda: [f(3, 4)], grad=False),
    "linalg_syevd": S(lambda: [_spd(3)], grad=False),
    # indexing
    "take": S(lambda: [f(5, 3), ints(4, hi=5)],
              ref=lambda a, i: a[i], grad=False),
    "batch_take": S(lambda: [f(3, 4), ints(3, hi=4)],
                    ref=lambda a, i: a[np.arange(3), i], grad=False),
    "pick": S(lambda: [f(3, 4), ints(3, hi=4)], {"axis": 1},
              ref=lambda a, i: a[np.arange(3), i], grad=False),
    "one_hot": S(lambda: [ints(4, hi=5)], {"depth": 5},
                 ref=lambda i: np.eye(5, dtype=np.float32)[i], grad=False),
    "gather_nd": S(lambda: [f(4, 5), np.array([[0, 1], [2, 3]], np.int32)],
                   ref=lambda a, i: a[i[0], i[1]], grad=False),
    "scatter_nd": S(lambda: [f(2), np.array([[0, 1], [2, 3]], np.int32)],
                    {"shape": (4, 5)}, grad=False),
    "where_op": S(lambda: [ints(3, 4, lo=0, hi=2), f(3, 4), f(3, 4)],
                  ref=lambda c, a, b: np.where(c, a, b), grad=False),
    "where": S(lambda: [ints(3, 4, lo=0, hi=2), f(3, 4), f(3, 4)],
               ref=lambda c, a, b: np.where(c, a, b), grad=False),
    "boolean_mask": S(lambda: [f(4, 3), np.array([1, 0, 1, 1], np.int32)],
                      grad=False),
    "index_add": S(lambda: [f(5, 3), ints(2, hi=5), f(2, 3)], grad=False),
    "index_copy": S(lambda: [f(5, 3), ints(2, hi=5), f(2, 3)], grad=False),
    "index_update": S(lambda: [f(5, 3), ints(2, hi=5), f(2, 3)],
                      grad=False),
    "ravel_multi_index": S(
        lambda: [np.array([[1, 2], [0, 3]], np.int64)], {"shape": (3, 4)},
        ref=lambda d: np.ravel_multi_index((d[0], d[1]), (3, 4)),
        grad=False),
    "unravel_index": S(
        lambda: [np.array([5, 11], np.int64)], {"shape": (3, 4)},
        ref=lambda d: np.stack(np.unravel_index(d, (3, 4))), grad=False),
    "searchsorted": S(lambda: [np.sort(f(8)), f(3)], grad=False),
    "bincount": S(lambda: [ints(10, hi=5)], {"minlength": 5},
                  ref=lambda d: np.bincount(d, minlength=5), grad=False),
    "digitize": S(lambda: [f(5), np.sort(f(4))], grad=False),
    "histogram": S(lambda: [fpos(20)], {"bin_cnt": 5, "range": (0.0, 1.0)},
                   grad=False),
    "interp": S(lambda: [f(4), np.sort(fpos(5)), fpos(5)], grad=False),
    # sorting
    "sort": S(lambda: [f(3, 6)], {"axis": -1}, ref=lambda x: np.sort(x, -1),
              grad=False),
    "argsort": S(lambda: [f(3, 6)], {"axis": -1},
                 ref=lambda x: np.argsort(x, -1).astype(np.float32),
                 grad=False),
    "topk": S(lambda: [f(3, 6)], {"k": 2, "ret_typ": "value"}, grad=False),
    "cumsum": S(lambda: [f(3, 4)], {"axis": 1},
                ref=lambda x: np.cumsum(x, 1)),
    "cumprod": S(lambda: [fpos(3, 4)], {"axis": 1},
                 ref=lambda x: np.cumprod(x, 1)),
    "cummax": S(lambda: [f(3, 4)], {"axis": 1},
                ref=lambda x: np.maximum.accumulate(x, 1), grad=False),
    "cummin": S(lambda: [f(3, 4)], {"axis": 1},
                ref=lambda x: np.minimum.accumulate(x, 1), grad=False),
    # bitwise / int
    "bitwise_and": S(lambda: [ints(3, 4), ints(3, 4)],
                     ref=np.bitwise_and, grad=False),
    "bitwise_or": S(lambda: [ints(3, 4), ints(3, 4)],
                    ref=np.bitwise_or, grad=False),
    "bitwise_xor": S(lambda: [ints(3, 4), ints(3, 4)],
                     ref=np.bitwise_xor, grad=False),
    "bitwise_not": S(lambda: [ints(3, 4)], ref=np.bitwise_not, grad=False),
    "bitwise_left_shift": S(lambda: [ints(3, 4), ints(3, 4, hi=3)],
                            ref=np.left_shift, grad=False),
    "bitwise_right_shift": S(lambda: [ints(3, 4, lo=4, hi=64),
                                      ints(3, 4, hi=3)],
                             ref=np.right_shift, grad=False),
    # special binary
    "prelu": S(lambda: [f(3, 4), fpos(1)],
               ref=lambda x, g: np.where(x >= 0, x, g * x)),
    "polygamma": S(lambda: [fpos(3)], {"n": 1}, grad=False),
    "gammainc": S(lambda: [fpos(3), fpos(3)], grad=False),
    "gammaincc": S(lambda: [fpos(3), fpos(3)], grad=False),
    # windows / creation
    "hanning": S(lambda: [], {"M": 8}, ref=lambda: np.hanning(8),
                 grad=False, rtol=1e-5, atol=1e-6),
    "hamming": S(lambda: [], {"M": 8}, ref=lambda: np.hamming(8),
                 grad=False, rtol=1e-5, atol=1e-6),
    "blackman": S(lambda: [], {"M": 8}, ref=lambda: np.blackman(8),
                  grad=False, rtol=1e-5, atol=1e-5),
    # sequence ops
    "sequence_mask": S(
        lambda: [f(4, 2, 3), np.array([2, 4], np.int32)],
        {"use_sequence_length": True}, grad=False),
    "SequenceLast": S(
        lambda: [f(4, 2, 3), np.array([2, 4], np.int32)],
        {"use_sequence_length": True}, grad=False),
    "SequenceReverse": S(
        lambda: [f(4, 2, 3), np.array([2, 4], np.int32)],
        {"use_sequence_length": True}, grad=False),
    # NN layers (layer semantics tested in test_gluon; battery = sanity+grad)
    "FullyConnected": S(lambda: [f(3, 4), f(5, 4), f(5)],
                        {"num_hidden": 5},
                        ref=lambda x, w, b: x @ w.T + b),
    "Convolution": S(lambda: [f(1, 2, 5, 5), f(3, 2, 3, 3), f(3)],
                     {"kernel": (3, 3), "num_filter": 3}, grad=False),
    "Deconvolution": S(lambda: [f(1, 2, 4, 4), f(2, 3, 3, 3), f(3)],
                       {"kernel": (3, 3), "num_filter": 3}, grad=False),
    "Pooling": S(lambda: [f(1, 2, 4, 4)],
                 {"kernel": (2, 2), "pool_type": "max", "stride": (2, 2)},
                 grad=False),
    "Activation": S(lambda: [f(3, 4)], {"act_type": "relu"},
                    ref=lambda x: np.maximum(x, 0)),
    "LeakyReLU": S(lambda: [f(3, 4)], {"act_type": "leaky", "slope": 0.1},
                   ref=lambda x: np.where(x > 0, x, 0.1 * x)),
    "BatchNorm": S(lambda: [f(2, 3, 4, 4), np.ones(3, np.float32),
                            np.zeros(3, np.float32),
                            np.zeros(3, np.float32),
                            np.ones(3, np.float32)], grad=False),
    "LayerNorm": S(lambda: [f(3, 4), np.ones(4, np.float32),
                            np.zeros(4, np.float32)], grad=False),
    "GroupNorm": S(lambda: [f(2, 4, 3), np.ones(4, np.float32),
                            np.zeros(4, np.float32)], {"num_groups": 2},
                   grad=False),
    "InstanceNorm": S(lambda: [f(2, 3, 4), np.ones(3, np.float32),
                               np.zeros(3, np.float32)], grad=False),
    "RMSNorm": S(lambda: [f(3, 4), np.ones(4, np.float32)], grad=False),
    "L2Normalization": S(lambda: [f(3, 4)],
                         ref=lambda x: x / np.sqrt(
                             (x * x).sum(1, keepdims=True) + 1e-10)),
    "Embedding": S(lambda: [ints(5, hi=7), f(7, 4)],
                   {"input_dim": 7, "output_dim": 4},
                   ref=lambda i, w: w[i], grad=False),
    "Dropout": S(lambda: [f(3, 4)], {"p": 0.0}, ref=lambda x: x,
                 grad=False),
    "SoftmaxOutput": S(lambda: [f(3, 4), ints(3, hi=4)], grad=False),
    "UpSampling": S(lambda: [f(1, 2, 3, 3)],
                    {"scale": 2, "sample_type": "nearest"}, grad=False),
    "AdaptiveAvgPooling2D": S(lambda: [f(1, 2, 4, 4)],
                              {"output_size": (2, 2)}, grad=False),
    "BilinearResize2D": S(lambda: [f(1, 2, 4, 4)],
                          {"height": 8, "width": 8}, grad=False),
    "Cast": S(lambda: [f(3, 4)], {"dtype": "float32"}, ref=lambda x: x),
    "im2col": S(lambda: [f(1, 2, 4, 4)],
                {"kernel": (3, 3), "stride": (1, 1)}, grad=False),
    # spatial
    "GridGenerator": S(lambda: [np.array([[1, 0, 0, 0, 1, 0]], np.float32)],
                       {"transform_type": "affine", "target_shape": (4, 4)},
                       grad=False),
    "BilinearSampler": S(
        lambda: [f(1, 2, 4, 4),
                 np.stack(np.meshgrid(np.linspace(-1, 1, 4),
                                      np.linspace(-1, 1, 4)))[None].astype(
                     np.float32)], grad=False),
    "SpatialTransformer": S(
        lambda: [f(1, 2, 4, 4), np.array([[1, 0, 0, 0, 1, 0]], np.float32)],
        {"target_shape": (4, 4)}, grad=False),
    "ROIPooling": S(lambda: [f(1, 2, 6, 6),
                             np.array([[0, 0, 0, 4, 4]], np.float32)],
                    {"pooled_size": (2, 2), "spatial_scale": 1.0},
                    grad=False),
    "_contrib_ROIAlign": S(lambda: [f(1, 2, 6, 6),
                                    np.array([[0, 0, 0, 4, 4]], np.float32)],
                           {"pooled_size": (2, 2), "spatial_scale": 1.0},
                           grad=False),
    "Correlation": S(lambda: [f(1, 2, 4, 4), f(1, 2, 4, 4)],
                     {"max_displacement": 1}, grad=False),
    # random (moment checks happen in test_forward sanity)
    "_random_uniform": S(lambda: [], {"shape": (500,)}, grad=False),
    "_random_normal": S(lambda: [], {"shape": (500,)}, grad=False),
    "_random_gamma": S(lambda: [], {"alpha": 2.0, "beta": 1.0,
                                    "shape": (64,)}, grad=False),
    "_random_exponential": S(lambda: [], {"lam": 1.0, "shape": (64,)},
                             grad=False),
    "_random_poisson": S(lambda: [], {"lam": 2.0, "shape": (64,)},
                         grad=False),
    "_random_randint": S(lambda: [], {"low": 0, "high": 5, "shape": (64,)},
                         grad=False),
    "_random_bernoulli": S(lambda: [], {"prob": 0.4, "shape": (64,)},
                           grad=False),
    "_sample_multinomial": S(
        lambda: [np.full((3, 4), 0.25, np.float32)], {"shape": 2},
        grad=False),
    "sample_normal_like": S(lambda: [f(8)], grad=False),
    "shuffle": S(lambda: [f(8, 2)], grad=False),
    # detection
    "MultiBoxPrior": S(lambda: [f(1, 2, 3, 3)],
                       {"sizes": (0.5,), "ratios": (1.0,)}, grad=False),
    "MultiBoxTarget": S(
        lambda: [_anchors(), np.array([[[0, .1, .1, .4, .4]]], np.float32),
                 np.zeros((1, 3, 9), np.float32)], grad=False),
    "MultiBoxDetection": S(
        lambda: [np.full((1, 3, 9), 1 / 3, np.float32),
                 np.zeros((1, 36), np.float32), _anchors()], grad=False),
    "_contrib_box_nms": S(
        lambda: [np.array([[[0, .9, 0, 0, 1, 1], [0, .8, 0, 0, 1, 1]]],
                          np.float32)], grad=False),
    "_contrib_box_iou": S(lambda: [fpos(3, 4), fpos(2, 4)], grad=False),
})


def _spd(n):
    a = fpos(n, n)
    return (a @ a.T + n * np.eye(n, dtype=np.float32))


def _anchors():
    from mxnet_tpu.ndarray.ndarray import invoke as _inv
    return _inv("MultiBoxPrior", nd.zeros((1, 2, 3, 3)),
                sizes=(0.5,), ratios=(1.0,)).asnumpy()


# Ops exercised by dedicated suites rather than the battery:
TESTED_ELSEWHERE = {
    "_contrib_quantize": "tests/test_quantization.py",
    "_contrib_quantize_v2": "tests/test_quantization.py",
    "_contrib_dequantize": "tests/test_quantization.py",
    "_contrib_requantize": "tests/test_quantization.py",
    "_contrib_quantized_fully_connected": "tests/test_quantization.py",
    "_contrib_quantized_conv": "tests/test_quantization.py",
    "_contrib_quantized_pooling": "tests/test_quantization.py",
    "_contrib_quantized_flatten": "tests/test_quantization.py",
    "_contrib_quantized_act": "tests/test_quantization.py",
    "LinearRegressionOutput": "tests/test_module.py",
    "MAERegressionOutput": "tests/test_module.py",
    "LogisticRegressionOutput": "tests/test_module.py",
    "_sparse_sgd_update": "tests/test_sparse.py",
    "_sparse_sgd_mom_update": "tests/test_sparse.py",
    "_sparse_adam_update": "tests/test_sparse.py",
    "RNN": "tests/test_rnn.py",
    "CTCLoss": "tests/test_loss.py",
    "multi_head_attention": "tests/test_transformer.py",
    "_contrib_interleaved_matmul_selfatt_qk": "tests/test_transformer.py",
    "_contrib_interleaved_matmul_selfatt_valatt": "tests/test_transformer.py",
    "_contrib_interleaved_matmul_encdec_qk": "tests/test_transformer.py",
    "_contrib_interleaved_matmul_encdec_valatt": "tests/test_transformer.py",
    "sgd_update": "tests/test_optimizer.py",
    "sgd_mom_update": "tests/test_optimizer.py",
    "mp_sgd_update": "tests/test_optimizer.py",
    "mp_sgd_mom_update": "tests/test_optimizer.py",
    "adam_update": "tests/test_optimizer.py",
    "adamw_update": "tests/test_optimizer.py",
    "nag_mom_update": "tests/test_optimizer.py",
    "rmsprop_update": "tests/test_optimizer.py",
    "rmspropalex_update": "tests/test_optimizer.py",
    "ftrl_update": "tests/test_optimizer.py",
    "signsgd_update": "tests/test_optimizer.py",
    "signum_update": "tests/test_optimizer.py",
    "lamb_update_phase1": "tests/test_optimizer.py",
    "lamb_update_phase2": "tests/test_optimizer.py",
    "rrelu": "stochastic activation (forward sanity only via LeakyReLU)",
    "_internal_getitem": "tests/test_ndarray.py (indexing suite)",
}


def _unique_ops():
    seen = {}
    for name in registry.list_ops():
        op = registry.get_op(name)
        seen.setdefault(id(op), op.name)
    return sorted(seen.values())


def test_coverage():
    missing = [op for op in _unique_ops()
               if op not in SPECS and op not in TESTED_ELSEWHERE]
    assert not missing, ("ops without battery spec or TESTED_ELSEWHERE "
                         "entry: %s" % missing)


@pytest.mark.parametrize("opname", sorted(SPECS))
def test_forward(opname):
    spec = SPECS[opname]
    np_inputs = spec.inputs()
    nd_inputs = [nd.array(x) for x in np_inputs]
    out = invoke(opname, *nd_inputs, **spec.params)
    outs = out if isinstance(out, (list, tuple)) else [out]
    for o in outs:
        a = o.asnumpy()
        assert a.shape is not None
        if np.issubdtype(a.dtype, np.floating):
            assert np.isfinite(a).all(), "%s produced non-finite" % opname
    if spec.ref is not None:
        expect = spec.ref(*np_inputs)
        expects = expect if isinstance(expect, tuple) else (expect,)
        for o, e in zip(outs, expects):
            assert_almost_equal(o.asnumpy(), np.asarray(e),
                                rtol=spec.rtol, atol=spec.atol,
                                names=(opname, opname + "_ref"))


def _grad_specs():
    out = []
    for opname in sorted(SPECS):
        spec = SPECS[opname]
        op = registry.get_op(opname)
        do_grad = spec.grad if spec.grad is not None else op.differentiable
        if not do_grad:
            continue
        np_inputs = spec.inputs()
        if not np_inputs or any(not np.issubdtype(x.dtype, np.floating)
                                for x in np_inputs):
            continue
        out.append(opname)
    return out


@pytest.mark.parametrize("opname", _grad_specs())
def test_grad(opname):
    spec = SPECS[opname]
    np_inputs = spec.inputs()
    nd_inputs = [nd.array(x) for x in np_inputs]

    def fn(*args):
        out = invoke(opname, *args, **spec.params)
        if isinstance(out, (list, tuple)):
            out = out[0]
        return out

    check_numeric_gradient(fn, nd_inputs, rtol=spec.grad_rtol,
                           atol=spec.grad_atol)
