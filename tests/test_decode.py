"""Decode-serving tests (ISSUE 15): cached attention semantics, the
prefill/decode program split vs a full-recompute reference, slot-bucket
packing invariance, continuous batching (long generations never block
short ones; scheduling never changes tokens), donated KV-pool flatness
+ census attribution, dispatch/retrace budgets, the GENERATE wire verb
(round trip, streaming, exactly-once replay, mid-generation failover),
and the engine's telemetry/env/contract surface.
"""
import socket
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu import programs, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.engine import engine
from mxnet_tpu.ops.attention import cached_attention
from mxnet_tpu.serve import (Overloaded, ServeClient, ServeServer,
                             serve_forever)
from mxnet_tpu.serve.decode import (DecodeBatcher, DecodeConfig,
                                    DecodeServable, demo_lm_params,
                                    reference_generate)
from mxnet_tpu.telemetry import registry

# one small shared geometry: 5 programs to warm (2 prefill + 3 slot
# buckets), reused by every sync-engine test below
CFG = dict(dim=16, heads=2, layers=2, slots=4, max_tokens=12,
           prompt_buckets=(4, 8))


@pytest.fixture(scope="module")
def shared_sv():
    """One warmed servable; tests build their own (cheap) sync engines
    on it sequentially — KV state is donated-chained, slot bookkeeping
    is per-engine, and a fresh prefill resets any slot it reuses."""
    cfg = DecodeConfig(**CFG)
    return DecodeServable(config=cfg), cfg


def _sync_engine(sv, **kw):
    return DecodeBatcher(sv, autostart=False, **kw)


# ---------------------------------------------------------------------------
# kernel + model semantics
# ---------------------------------------------------------------------------


def test_cached_attention_matches_reference():
    rng = np.random.RandomState(0)
    B, P, H, D = 3, 16, 2, 8
    q = jnp.asarray(rng.randn(B, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, P, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, P, H, D).astype(np.float32))
    lens = jnp.asarray([1, 7, 16], jnp.int32)
    out = np.asarray(cached_attention(q, k, v, lens))
    scale = 1.0 / np.sqrt(D)
    for b in range(B):
        n = int(lens[b])
        for h in range(H):
            logits = np.asarray(k)[b, :n, h] @ np.asarray(q)[b, h] * scale
            p = np.exp(logits - logits.max())
            p /= p.sum()
            want = p @ np.asarray(v)[b, :n, h]
            np.testing.assert_allclose(out[b, h], want, rtol=1e-5,
                                       atol=1e-5)


def test_cached_attention_ignores_stale_pages():
    """Entries at positions >= cur_len must not influence the output —
    the whole eviction story (retire = bookkeeping, stale KV masked)
    rests on this."""
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 2, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 8, 2, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(1, 8, 2, 8).astype(np.float32))
    lens = jnp.asarray([3], jnp.int32)
    base = np.asarray(cached_attention(q, k, v, lens))
    k2 = k.at[0, 3:].set(99.0)          # poison the stale region
    v2 = v.at[0, 3:].set(-99.0)
    out = np.asarray(cached_attention(q, k2, v2, lens))
    np.testing.assert_array_equal(base, out)


def test_config_geometry():
    cfg = DecodeConfig(slots=8, max_tokens=32, page=16,
                       prompt_buckets=(4, 8, 16))
    assert cfg.slot_buckets == (1, 2, 4, 8)
    assert cfg.slot_bucket_for(3) == 4
    assert cfg.prompt_bucket_for(5) == 8
    assert cfg.prompt_bucket_for(17) is None
    assert cfg.max_len % cfg.page == 0
    assert cfg.max_len >= cfg.prompt_buckets[-1] + cfg.max_tokens
    with pytest.raises(MXNetError):
        DecodeConfig(dim=30, heads=4)


def test_decode_matches_full_recompute_reference(shared_sv):
    sv, cfg = shared_sv
    eng = _sync_engine(sv)
    prompts = [[2, 3, 5], [7, 7], [11, 4, 9, 1, 6]]
    gens = [eng.submit(p, max_new=8) for p in prompts]
    eng.drain_sync()
    for p, g in zip(prompts, gens):
        ref = reference_generate(p, 8, params=sv.params, config=cfg)
        assert g.tokens_so_far() == ref, (p, g.tokens_so_far(), ref)
        assert g.done()


def test_bucket_packing_invariance(shared_sv):
    """A sequence's tokens must not depend on which slot bucket it was
    packed into — the 4-packed decode must equal the 1-alone decode
    (and the cross-process reference the chaos driver uses)."""
    sv, cfg = shared_sv
    ref = reference_generate([9, 2, 13], 10, params=sv.params,
                             config=cfg)
    eng = _sync_engine(sv)
    g_alone = eng.submit([9, 2, 13], max_new=10)
    eng.drain_sync()
    assert g_alone.tokens_so_far() == ref
    eng2 = _sync_engine(sv)
    packed = [eng2.submit([9, 2, 13], max_new=10)] + \
        [eng2.submit([int(i) + 3, 8], max_new=10) for i in range(3)]
    eng2.drain_sync()
    assert packed[0].tokens_so_far() == ref


def test_scheduling_never_changes_tokens(shared_sv):
    """Continuous vs request-level batching is a THROUGHPUT knob, not a
    semantics knob: identical workloads produce identical sequences."""
    sv, cfg = shared_sv
    prompts = [[3, 1, 4], [1, 5], [9, 2, 6, 5], [3, 5, 8], [9, 7],
               [9, 3, 2]]
    news = [2, 9, 4, 2, 7, 3]

    def run(mode):
        eng = _sync_engine(sv, mode=mode)
        gens = [eng.submit(p, max_new=n) for p, n in zip(prompts, news)]
        eng.drain_sync()
        return [g.tokens_so_far() for g in gens]

    assert run("continuous") == run("request")


# ---------------------------------------------------------------------------
# continuous batching + slots
# ---------------------------------------------------------------------------


def test_long_generation_never_blocks_short(shared_sv):
    sv, cfg = shared_sv
    eng = _sync_engine(sv)
    long_g = eng.submit([2], max_new=12)
    shorts = [eng.submit([3], max_new=2) for _ in range(3)]
    for _ in range(5):
        eng.step_sync()
    assert all(g.done() for g in shorts)
    assert not long_g.done()
    # freed slots admit NEW work while the long one still runs
    late = eng.submit([4], max_new=2)
    for _ in range(4):
        eng.step_sync()
    assert late.done() and not long_g.done()
    eng.drain_sync()
    assert long_g.done() and len(long_g.tokens_so_far()) == 12


def test_request_mode_holds_admissions(shared_sv):
    sv, cfg = shared_sv
    eng = _sync_engine(sv, mode="request")
    wave1 = [eng.submit([5], max_new=6) for _ in range(cfg.slots)]
    late = eng.submit([6], max_new=2)
    eng.step_sync()                     # admits wave 1 only
    assert eng.active_count() == cfg.slots
    for _ in range(3):
        eng.step_sync()
    # wave 1 not all done -> the strawman refuses to admit `late`
    assert not late.done() and eng.queue_depth() == 1
    eng.drain_sync()
    assert late.done() and all(g.done() for g in wave1)


def test_slot_reuse_after_retire_is_clean(shared_sv):
    """A retired slot's stale KV must never leak into the next tenant:
    prefill resets the slot's length and overwrites from position 0."""
    sv, cfg = shared_sv
    eng = _sync_engine(sv)
    first = [eng.submit([7, 3], max_new=6) for _ in range(cfg.slots)]
    eng.drain_sync()
    second = eng.submit([2, 8, 4], max_new=8)      # reuses a dirty slot
    eng.drain_sync()
    ref = reference_generate([2, 8, 4], 8, params=sv.params, config=cfg)
    assert second.tokens_so_far() == ref
    assert all(g.done() for g in first)


def test_admission_refusals(shared_sv):
    sv, cfg = shared_sv
    eng = _sync_engine(sv)
    with pytest.raises(MXNetError):
        eng.submit([])                              # empty prompt
    with pytest.raises(MXNetError):
        eng.submit([1] * (cfg.prompt_buckets[-1] + 1))   # over-bucket
    with pytest.raises(MXNetError):
        eng.submit([cfg.vocab + 5])                 # out of vocab
    with pytest.raises(MXNetError):
        eng.submit(["nope"])                        # not token ids
    r0 = registry.value("serve.decode.rejected")
    assert r0 >= 4


def test_queue_cap_sheds_overload(shared_sv):
    sv, cfg = shared_sv
    eng = _sync_engine(sv, queue_cap=2)
    eng.submit([1], max_new=2)
    eng.submit([1], max_new=2)
    with pytest.raises(Overloaded):
        eng.submit([1], max_new=2)
    eng.drain_sync()


def test_max_tokens_clamps_to_config(shared_sv):
    sv, cfg = shared_sv
    eng = _sync_engine(sv)
    g = eng.submit([5, 5], max_new=cfg.max_tokens + 50)
    eng.drain_sync()
    assert len(g.tokens_so_far()) == cfg.max_tokens


def test_eos_stops_generation(shared_sv):
    """Per-request stop tokens (submit(eos_id=...), the wire's
    opts["eos"]): generation ends ON the eos token, reference oracle
    agrees."""
    sv, cfg = shared_sv
    ref = reference_generate([3, 9], 8, params=sv.params, config=cfg)
    eos = ref[2]                       # third emitted token
    eng = _sync_engine(sv)
    g = eng.submit([3, 9], max_new=8, eos_id=eos)
    plain = eng.submit([3, 9], max_new=8)      # no stop token: full run
    eng.drain_sync()
    assert g.tokens_so_far() == ref[:3]        # stops ON the eos token
    assert plain.tokens_so_far() == ref
    assert reference_generate([3, 9], 8, params=sv.params, config=cfg,
                              eos_id=eos) == ref[:3]


# ---------------------------------------------------------------------------
# budgets: dispatches, retraces, KV-pool flatness, donation
# ---------------------------------------------------------------------------


def test_dispatch_budget_exact(shared_sv):
    """1 dispatch per decode step regardless of the active count, 1 per
    prefill, every dispatch accounted, zero retraces after warm."""
    sv, cfg = shared_sv
    eng = _sync_engine(sv)
    retr0 = sv.retraces
    pre0 = registry.value("serve.decode.prefills")
    st0 = registry.value("serve.decode.steps")
    c0 = engine.snapshot()["dispatches"]
    gens = [eng.submit([2, 4, 6], max_new=5) for _ in range(4)]
    eng.drain_sync()
    dispatches = engine.snapshot()["dispatches"] - c0
    prefills = registry.value("serve.decode.prefills") - pre0
    steps = registry.value("serve.decode.steps") - st0
    assert prefills == 4
    assert steps == 4                   # token 1 comes from the prefill
    assert dispatches == prefills + steps
    assert sv.retraces == retr0
    assert all(len(g.tokens_so_far()) == 5 for g in gens)


def test_kv_pool_flat_and_census_owner(shared_sv):
    sv, cfg = shared_sv
    eng = _sync_engine(sv)
    census = programs.buffer_census()
    assert "kv_cache" in census
    assert census["kv_cache"]["bytes"] >= sv.kv_state_bytes()
    b0 = sv.kv_state_bytes()
    for _ in range(3):
        gens = [eng.submit([3, 3], max_new=7) for _ in range(6)]
        eng.drain_sync()
        assert all(g.done() for g in gens)
    assert sv.kv_state_bytes() == b0
    after = programs.buffer_census()["kv_cache"]["bytes"]
    assert after == census["kv_cache"]["bytes"]


def test_state_donated_and_rebound(shared_sv):
    """Every dispatch rebinds ``_state`` to the program outputs; the
    consumed buffers are donated (deleted), so the pool never holds two
    copies — the device-side face of 'HBM stays flat'."""
    sv, cfg = shared_sv
    eng = _sync_engine(sv)
    eng.submit([4, 2], max_new=4)
    old = dict(sv._state)
    eng.drain_sync()
    assert sv._state["k"] is not old["k"]
    assert old["k"].is_deleted()        # donated into the dispatch
    assert old["len"].is_deleted()


def test_decode_contracts_declared():
    names = {c.name for c in programs.contracts()}
    assert "serve.decode" in names and "serve.prefill" in names
    by_name = {c.name: c for c in programs.contracts()}
    assert by_name["serve.decode"].donate_argnums == (1, 2, 3, 4)
    assert by_name["serve.prefill"].donate_argnums == (1, 2, 3, 4)


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------


def test_phases_and_token_histogram(shared_sv):
    sv, cfg = shared_sv
    snap0 = telemetry.phase_snapshot()
    tok_h = registry.find("serve.decode.token_seconds")
    t0 = tok_h.snapshot()["count"] if tok_h is not None else 0
    eng = _sync_engine(sv)
    gens = [eng.submit([6, 1], max_new=4) for _ in range(5)]
    eng.drain_sync()
    eng.step_sync()                     # boundary after harvest: retire
    snap = telemetry.phase_snapshot()

    def count(name):
        now = snap.get(name, {}).get("count", 0)
        return now - snap0.get(name, {}).get("count", 0)

    assert count("prefill") >= 5
    assert count("decode_step") >= 3
    assert count("kv_evict") >= 1
    tok_h = registry.find("serve.decode.token_seconds")
    assert tok_h is not None
    assert tok_h.snapshot()["count"] - t0 == sum(
        len(g.tokens_so_far()) for g in gens)


def test_streaming_wait_new(shared_sv):
    sv, cfg = shared_sv
    eng = _sync_engine(sv)
    g = eng.submit([8, 8], max_new=6)
    chunk, done = g.wait_new(0, timeout=0.01)      # nothing yet
    assert chunk == [] and not done
    eng.drain_sync()
    chunk, done = g.wait_new(0, timeout=1.0)
    assert done and chunk == g.tokens_so_far() and len(chunk) == 6
    tail, done = g.wait_new(4, timeout=1.0)
    assert done and tail == g.tokens_so_far()[4:]


# ---------------------------------------------------------------------------
# the GENERATE wire verb
# ---------------------------------------------------------------------------


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _start_decode_replica(port, cfg=None, params=None, abort_event=None,
                          on_tick=None):
    sv = DecodeServable(params=params,
                        config=cfg or DecodeConfig(**CFG))
    state = ServeServer(decode=DecodeBatcher(sv, on_tick=on_tick))
    stop_ev = threading.Event()
    t = threading.Thread(
        target=serve_forever,
        kwargs=dict(port=port, state=state, stop_event=stop_ev,
                    abort_event=abort_event),
        daemon=True)
    t.start()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port),
                                     timeout=0.2).close()
            return state, sv, stop_ev
        except OSError:
            time.sleep(0.05)
    raise RuntimeError("decode replica did not come up on %d" % port)


@pytest.fixture(scope="module")
def wire_replica():
    port = _free_port()
    state, sv, stop_ev = _start_decode_replica(port)
    yield "127.0.0.1:%d" % port, state, sv
    stop_ev.set()
    state.close()


def test_wire_generate_round_trip(wire_replica):
    addr, state, sv = wire_replica
    with ServeClient([addr], timeout=30) as cli:
        ref = reference_generate([3, 1, 4], 9, params=sv.params,
                                 config=sv.config)
        version, toks = cli.generate([3, 1, 4], max_tokens=9)
        assert version == sv.version and toks == ref
        # refusals come back as normal errors, not severed connections
        with pytest.raises(MXNetError):
            cli.generate([1] * 99)


def test_wire_generate_streaming(wire_replica):
    addr, state, sv = wire_replica
    got = []
    with ServeClient([addr], timeout=30) as cli:
        _v, toks = cli.generate([2, 9, 5], max_tokens=8,
                                on_token=got.extend)
    assert toks == got
    assert toks == reference_generate([2, 9, 5], 8, params=sv.params,
                                      config=sv.config)


def test_generate_replay_exactly_once(wire_replica):
    """A replayed COMPLETED generation answers from the exactly-once
    cache: identical reply, no second prefill, replay counted."""
    addr, state, sv = wire_replica
    pre0 = registry.value("serve.decode.prefills")
    rep0 = registry.value("serve.server_replays")
    msg = ("SEQ", "decode-replay-test", 7,
           ("GENERATE", [4, 4, 4], {"max_tokens": 5}))
    r1 = state.handle_request(msg)
    assert r1[0] is True
    pre1 = registry.value("serve.decode.prefills")
    r2 = state.handle_request(msg)
    assert r2 == r1
    assert registry.value("serve.decode.prefills") == pre1
    assert pre1 - pre0 == 1
    assert registry.value("serve.server_replays") - rep0 == 1


def test_health_reports_decode(wire_replica):
    addr, state, sv = wire_replica
    with ServeClient([addr], timeout=30) as cli:
        h = cli.health()
    assert h["status"] == "serving"
    assert h["decode"]["slots"] == sv.config.slots
    assert h["decode"]["model"] == sv.name
    assert h["decode"]["retraces"] == sv.retraces


def test_failover_mid_generation(wire_replica):
    """Kill a replica while a generation is IN FLIGHT: the client
    fails over, the survivor (the module's wire replica) re-prefills,
    and the caller still gets the exact deterministic sequence — no
    lost or corrupted generations."""
    addr2, _state2, sv2 = wire_replica
    p1 = _free_port()
    ab1 = threading.Event()
    # throttle replica 1's pump (~25ms/step) so the generation
    # comfortably outlives the abort's ~100ms detection latency — the
    # kill must land MID-generation, not between request and reply
    state1, sv1, _st1 = _start_decode_replica(
        p1, params=sv2.params, abort_event=ab1,
        on_tick=lambda: time.sleep(0.025))
    addrs = ["127.0.0.1:%d" % p1, addr2]
    ref = reference_generate([6, 2, 8], 12, params=sv2.params,
                             config=sv2.config)
    fo0 = registry.value("serve.client_failovers")
    result = {}

    def call():
        with ServeClient(addrs, timeout=30) as cli:
            result["out"] = cli.generate([6, 2, 8], max_tokens=12)

    t = threading.Thread(target=call, daemon=True)
    t.start()
    # sever replica 1 the moment the generation is live on it
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if state1.decode.active_count() > 0:
            break
        time.sleep(0.001)
    ab1.set()
    t.join(timeout=60)
    assert "out" in result, "generation lost in failover"
    _version, toks = result["out"]
    assert toks == ref
    assert registry.value("serve.client_failovers") > fo0
    state1.close()


# ---------------------------------------------------------------------------
# env + threaded smoke
# ---------------------------------------------------------------------------


def test_decode_env_catalog():
    from mxnet_tpu.base import ENV_CATALOG
    for name in ("MX_SERVE_DECODE_SLOTS", "MX_SERVE_DECODE_MAX_TOKENS",
                 "MX_SERVE_DECODE_PAGE",
                 "MX_SERVE_DECODE_PROMPT_BUCKETS"):
        assert name in ENV_CATALOG, name
        default, doc = ENV_CATALOG[name]
        assert default and doc


def test_threaded_engine_smoke(shared_sv):
    """The real (pump + harvester) threads: a burst of mixed-length
    generations all complete correctly and the engine closes clean."""
    sv, cfg = shared_sv
    eng = DecodeBatcher(sv)
    try:
        prompts = [[5, 6, 7], [2, 2], [9, 1, 3, 8]]
        refs = [reference_generate(p, n, params=sv.params, config=cfg)
                for p, n in zip(prompts, (8, 2, 5))]
        gens = [eng.submit(p, max_new=n)
                for p, n in zip(prompts, (8, 2, 5))] * 1
        gens += [eng.submit(prompts[0], max_new=8) for _ in range(5)]
        outs = [g.result(timeout=60) for g in gens]
        assert outs[0] == refs[0] and outs[1] == refs[1] \
            and outs[2] == refs[2]
        assert all(o == refs[0] for o in outs[3:])
    finally:
        eng.close()
    # close() is idempotent and the threads are gone
    eng.close()
    assert not eng._pump.is_alive() and not eng._harvester.is_alive()
