"""RNN tests (reference: tests/python/unittest/test_gluon_rnn.py — cell vs
fused-layer consistency, shapes, bidirectional, unroll)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn, rnn


def _copy_cell_params_to_layer(cell, layer, layer_idx=0, prefix="l"):
    """Map cell params (i2h_weight, ...) onto layer params (l0_i2h_weight)."""
    for name in ("i2h_weight", "h2h_weight", "i2h_bias", "h2h_bias"):
        src = getattr(cell, name).data()
        getattr(layer, "%s%d_%s" % (prefix, layer_idx, name)).set_data(src)


@pytest.mark.parametrize("mode", ["lstm", "gru", "rnn_tanh"])
def test_fused_layer_matches_cell_unroll(mode):
    """The fused lax.scan op and the explicit cell unroll must agree."""
    np.random.seed(0)
    T, N, I, H = 5, 3, 4, 6
    x_tnc = mx.nd.array(np.random.randn(T, N, I).astype(np.float32))

    if mode == "lstm":
        cell = rnn.LSTMCell(H)
        layer = rnn.LSTM(H)
    elif mode == "gru":
        cell = rnn.GRUCell(H)
        layer = rnn.GRU(H)
    else:
        cell = rnn.RNNCell(H, activation="tanh")
        layer = rnn.RNN(H, activation="tanh")
    cell.initialize(mx.init.Xavier())
    # build cell params with a fwd pass
    cell(x_tnc[0], cell.begin_state(N))
    layer.initialize()
    layer(x_tnc)  # trigger deferred init
    _copy_cell_params_to_layer(cell, layer)

    out_fused = layer(x_tnc).asnumpy()  # (T, N, H)
    outs, _ = cell.unroll(T, [x_tnc[t] for t in range(T)],
                          merge_outputs=False)
    out_cell = np.stack([o.asnumpy() for o in outs])
    assert np.allclose(out_fused, out_cell, atol=1e-5), \
        np.abs(out_fused - out_cell).max()


def test_lstm_shapes_and_states():
    T, N, I, H, L = 7, 2, 5, 8, 2
    layer = rnn.LSTM(H, num_layers=L)
    layer.initialize()
    x = mx.nd.ones((T, N, I))
    out = layer(x)
    assert out.shape == (T, N, H)
    states = layer.begin_state(N)
    out, new_states = layer(x, states)
    assert out.shape == (T, N, H)
    assert new_states[0].shape == (L, N, H)
    assert new_states[1].shape == (L, N, H)


def test_bidirectional_lstm_shape():
    T, N, I, H = 4, 3, 5, 6
    layer = rnn.LSTM(H, bidirectional=True)
    layer.initialize()
    out = layer(mx.nd.ones((T, N, I)))
    assert out.shape == (T, N, 2 * H)


def test_ntc_layout():
    N, T, I, H = 3, 4, 5, 6
    layer = rnn.GRU(H, layout="NTC")
    layer.initialize()
    out = layer(mx.nd.ones((N, T, I)))
    assert out.shape == (N, T, H)


def test_rnn_gradient_flows():
    layer = rnn.LSTM(4, num_layers=2)
    layer.initialize()
    x = mx.nd.ones((3, 2, 5))
    with autograd.record():
        out = layer(x)
        loss = (out ** 2).sum()
    loss.backward()
    g = layer.l0_i2h_weight.grad().asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_rnn_hybridize_consistency():
    layer = rnn.LSTM(6)
    layer.initialize()
    x = mx.nd.array(np.random.randn(4, 2, 3).astype(np.float32))
    imp = layer(x).asnumpy()
    layer.hybridize()
    hyb = layer(x).asnumpy()
    assert np.allclose(imp, hyb, atol=1e-5)


def test_sequential_cell_stack():
    stack = rnn.SequentialRNNCell()
    stack.add(rnn.LSTMCell(6))
    stack.add(rnn.DropoutCell(0.0))
    stack.add(rnn.LSTMCell(4))
    stack.initialize()
    x = mx.nd.ones((2, 5))
    states = stack.begin_state(2)
    out, new_states = stack(x, states)
    assert out.shape == (2, 4)
    assert len(new_states) == 4


def test_residual_cell():
    cell = rnn.ResidualCell(rnn.GRUCell(5, input_size=5))
    cell.initialize()
    x = mx.nd.ones((3, 5))
    out, _ = cell(x, cell.begin_state(3))
    assert out.shape == (3, 5)


def test_bidirectional_cell_unroll():
    bi = rnn.BidirectionalCell(rnn.LSTMCell(4), rnn.LSTMCell(4))
    bi.initialize()
    x = mx.nd.ones((5, 2, 3))  # TNC
    seq = [x[t] for t in range(5)]
    outs, states = bi.unroll(5, seq, layout="TNC", merge_outputs=False)
    assert len(outs) == 5
    assert outs[0].shape == (2, 8)


def test_word_lm_converges():
    """Tiny PTB-style LM: embedding → LSTM → dense, perplexity drops
    (BASELINE config 3 pattern; reference example/rnn/word_lm)."""
    np.random.seed(0)
    mx.random.seed(0)
    V, E, H, T, N = 20, 8, 16, 6, 8

    class WordLM(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.embed = nn.Embedding(V, E)
            self.lstm = rnn.LSTM(H)
            self.decoder = nn.Dense(V, flatten=False)

        def forward(self, x, states):
            emb = self.embed(x)              # (T, N, E)
            out, states = self.lstm(emb, states)
            return self.decoder(out), states

    # deterministic cyclic sequence data: next = (cur + 1) % V
    data = np.arange(T * N * 8).reshape(8, T, N) % V
    model = WordLM()
    model.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(model.collect_params(), "adam",
                            {"learning_rate": 0.05})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    first = last = None
    for epoch in range(6):
        states = model.lstm.begin_state(N)
        for batch in data:
            x = mx.nd.array(batch.astype(np.float32))
            y = mx.nd.array(((batch + 1) % V).astype(np.float32))
            # truncated BPTT: detach carried states (reference pattern)
            states = [s.detach() for s in states]
            with autograd.record():
                out, states = model(x, states)
                loss = loss_fn(out.reshape((-1, V)), y.reshape(-1)).mean()
            loss.backward()
            trainer.step(1)
            val = float(loss.asscalar())
            if first is None:
                first = val
            last = val
    assert last < first * 0.5, (first, last)


def test_lstm_sequence_length():
    """use_sequence_length: final states come from each sample's last valid
    step; padded outputs are zeroed (reference RNN op [1.7+] semantics)."""
    np.random.seed(0)
    T, N, I, H = 6, 2, 3, 4
    layer = rnn.LSTM(H)
    layer.initialize(mx.init.Xavier())
    x = mx.nd.array(np.random.randn(T, N, I).astype(np.float32))
    states = layer.begin_state(N)
    seq_len = mx.nd.array(np.array([4, 6], np.float32))
    out, new_states = layer(x, states, sequence_length=seq_len)
    out_np = out.asnumpy()
    # sample 0: outputs at t >= 4 are zero
    assert np.allclose(out_np[4:, 0], 0.0)
    assert not np.allclose(out_np[3, 0], 0.0)
    # sample 0 final state equals a 4-step run's final state
    out4, states4 = layer(x[:4], layer.begin_state(N))
    assert np.allclose(new_states[0].asnumpy()[0, 0],
                       states4[0].asnumpy()[0, 0], atol=1e-5)
    assert np.allclose(new_states[1].asnumpy()[0, 0],
                       states4[1].asnumpy()[0, 0], atol=1e-5)


def test_bilstm_sequence_length_consistency():
    """Bidirectional + valid_length: reverse direction must start at each
    sample's last valid step — check against a truncated run."""
    np.random.seed(0)
    T, N, I, H = 5, 2, 3, 4
    layer = rnn.LSTM(H, bidirectional=True)
    layer.initialize(mx.init.Xavier())
    x = mx.nd.array(np.random.randn(T, N, I).astype(np.float32))
    seq_len = mx.nd.array(np.array([3, 5], np.float32))
    out, _ = layer(x, layer.begin_state(N), sequence_length=seq_len)
    # sample 0 truncated to its valid 3 steps must match a plain 3-step run
    out3 = layer(x[:3, 0:1])
    assert np.allclose(out.asnumpy()[:3, 0], out3.asnumpy()[:, 0], atol=1e-5)


def test_bidirectional_cell_valid_length():
    np.random.seed(0)
    bi = rnn.BidirectionalCell(rnn.LSTMCell(4), rnn.LSTMCell(4))
    bi.initialize()
    x = mx.nd.array(np.random.randn(5, 2, 3).astype(np.float32))
    seq = [x[t] for t in range(5)]
    vl = mx.nd.array(np.array([3, 5], np.float32))
    outs, _ = bi.unroll(5, seq, layout="TNC", merge_outputs=False,
                        valid_length=vl)
    # outputs past valid_length are masked to zero for sample 0
    assert np.allclose(outs[4].asnumpy()[0], 0.0)
    # sample 0's valid region must equal a standalone 3-step bi-unroll
    bi2_outs, _ = bi.unroll(3, [s[0:1] for s in seq[:3]], layout="TNC",
                            merge_outputs=False)
    for t in range(3):
        assert np.allclose(outs[t].asnumpy()[0], bi2_outs[t].asnumpy()[0],
                           atol=1e-5)
