"""Wire-protocol verifier (ISSUE 19): ``python -m tools.mxlint
--protocol`` — per-verb effect summaries + exhaustive bounded
fault-schedule model checking of the exactly-once layer.

Layers, bottom-up:

  * extraction units — synthetic machines through ``check_sources``
    prove the effect-category tables, invalidating-guard analysis and
    SEQ facts on code small enough to eyeball;
  * codec robustness (satellite) — deterministic fuzz of the
    NPX/TXT/JSN/QGRAD codecs: truncated / bit-flipped / wrong-verb
    payloads raise :class:`WireCodecError`, never hang, and a corrupt
    PUSH never partially applies server state;
  * the shipped tree certifies — zero findings, the deterministic
    schedule count pinned at 737 (drift = reviewed machine change),
    byte-identical across runs;
  * the four reinjection quads — each classic protocol fault tripped
    by its designated rule and cleared by a targeted line suppression;
  * the CLI contract — exit 0/1/2, ``--format json`` with stable
    fingerprints, and ``tools/gen_wire_docs.py --check`` in sync.

Pure stdlib + numpy + pytest: no jax import, milliseconds per test.
"""
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.mxlint import protocol                            # noqa: E402
from tools.mxlint import lint_source                         # noqa: E402

# the deterministic fault-schedule count over the shipped machines —
# pinned here AND in tools/lint.sh: a drift means a machine/verb/SEQ
# shape change that must be reviewed, then repinned in both places
PINNED_SCHEDULES = 737

MACHINE_PATHS = ("mxnet_tpu/kvstore/server.py",
                 "mxnet_tpu/serve/server.py",
                 "mxnet_tpu/serve/router.py",
                 "mxnet_tpu/fleet.py")


def shipped_sources():
    out = {}
    for fp in protocol.iter_py_files([os.path.join(REPO, "mxnet_tpu")]):
        rel = os.path.relpath(fp, REPO).replace(os.sep, "/")
        with open(fp, encoding="utf-8") as f:
            out[rel] = f.read()
    return out


def rules_of(diags):
    return sorted({d.rule for d in diags})


def src(text):
    return textwrap.dedent(text).lstrip("\n")


# ---------------------------------------------------------------------------
# extraction units: synthetic machines
# ---------------------------------------------------------------------------

MINI = """
from mxnet_tpu.kvstore.wire_verbs import declare_verbs

WIRE_VERBS = declare_verbs("mini", {
    "SET": {"semantics": "replayable", "replay": "cached",
            "codec": None, "mutates": ("kv",)},
    "GET": {"semantics": "idempotent", "replay": "bypass",
            "codec": None, "mutates": ()},
}, role="server")


class Mini:
    _CACHED = ("SET",)

    def _handle_seq(self, env):
        _, cid, seq, inner = env
        if inner[0] not in self._CACHED:
            return self.handle(inner)
        ent = self._replay.get(cid)
        if ent is not None and seq == ent[0]:
            return ent[2]
        if ent is not None and seq < ent[0]:
            return False, "stale"
        ent = [seq, _Evt(), None]
        self._replay[cid] = ent
        resp = self.handle(inner)
        ent[2] = resp
        ent[1].set()
        return resp

    def handle(self, msg):
        if msg[0] == "SET":
            key, value = msg[1], msg[2]
            self._store[key] = value
            return True, None
        if msg[0] == "GET":
            return True, self._store.get(msg[1])
"""


def check_mini(body=MINI):
    return protocol.check_sources({"mxnet_tpu/mini.py": body})


def test_extraction_mini_machine_clean():
    diags, stats = check_mini()
    assert diags == [] or rules_of(diags) == [], rules_of(diags)
    assert len(stats["machines"]) == 1
    m = stats["machines"][0]
    assert m["protocol"] == "mini" and m["verbs"] == 2
    assert stats["schedules"] > 0


def test_extraction_guarded_vs_unguarded_effects():
    # the KV write is an unguarded set; wrap it in an invalidating
    # `not in` guard and the extractor must mark it guarded (the
    # retry/no-op path skips it)
    guarded = MINI.replace(
        "            self._store[key] = value\n",
        "            if key not in self._store:\n"
        "                self._store[key] = value\n")
    for body in (MINI, guarded):
        diags, _ = check_mini(body)
        assert not [d for d in diags if d.rule != "protocol-model"], \
            rules_of(diags)


def test_extraction_missing_dispatch_branch_is_lane_error():
    body = MINI.replace('if msg[0] == "GET":', 'if msg[0] == "GETX":')
    diags, _ = check_mini(body)
    assert "protocol-error" in rules_of(diags)
    msgs = " ".join(d.message for d in diags)
    assert "GET" in msgs and "no dispatch branch" in msgs


def test_replay_class_mutating_verb_outside_cache():
    # q1 in miniature: SET declared cached but dropped from _CACHED —
    # a retried SET re-executes instead of replaying
    body = MINI.replace('_CACHED = ("SET",)', '_CACHED = ()')
    diags, _ = check_mini(body)
    assert "protocol-replay-class" in rules_of(diags)


def test_model_checker_catches_unguarded_reexecution():
    # SET declared *idempotent* + bypass with an ACCUMULATING handler
    # (+=, not =): the duplicate schedule applies it twice and the
    # model checker must object — note a plain assignment in the same
    # position is genuinely idempotent and stays clean (previous test)
    body = MINI.replace(
        '"SET": {"semantics": "replayable", "replay": "cached",',
        '"SET": {"semantics": "idempotent", "replay": "bypass",')
    body = body.replace('_CACHED = ("SET",)', '_CACHED = ()')
    body = body.replace("            self._store[key] = value\n",
                        "            self._store[key] += value\n")
    diags, _ = check_mini(body)
    assert "protocol-model" in rules_of(diags), \
        "model stayed silent on a re-executing accumulate"


# ---------------------------------------------------------------------------
# codec robustness (satellite): typed errors, no partial application
# ---------------------------------------------------------------------------

from mxnet_tpu.kvstore.wire_codec import (            # noqa: E402
    WireCodecError, encode_array, decode_array, encode_text,
    decode_text, encode_json, decode_json, encode_wire, decode_wire,
    quantize_int8_np, pack_2bit)


def _payload_zoo():
    a = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    q, s = quantize_int8_np(a.ravel(), block=8)
    words = pack_2bit(np.sign(a.ravel() - 11.0), 0.25)
    return [
        (encode_array(a), decode_array),
        (encode_text("héllo wire"), decode_text),
        (encode_json({"k": [1, 2, {"n": None}]}), decode_json),
        (encode_wire("int8", a.shape, a.dtype, (q, s)), decode_wire),
        (encode_wire("2bit", a.shape, a.dtype, (words, 0.25)),
         decode_wire),
    ]


def test_codec_roundtrips_still_hold():
    a = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    np.testing.assert_array_equal(decode_array(encode_array(a)), a)
    assert decode_text(encode_text("x")) == "x"
    assert decode_json(encode_json({"a": 1})) == {"a": 1}
    q, s = quantize_int8_np(a.ravel(), block=8)
    out = decode_wire(encode_wire("int8", a.shape, a.dtype, (q, s)))
    assert out.shape == a.shape and out.dtype == a.dtype


def test_codec_fuzz_truncate_bitflip_wrongverb():
    """Property-style fuzz, deterministically seeded: every corrupted
    payload either decodes to a value or raises WireCodecError —
    nothing else escapes, nothing hangs."""
    rng = np.random.RandomState(20190807)
    decoders = (decode_array, decode_text, decode_json, decode_wire)
    for payload, its_decoder in _payload_zoo():
        # wrong verb: every OTHER decoder must refuse with the typed
        # error (tag mismatch), not garbage or an arbitrary exception
        for dec in decoders:
            if dec is its_decoder:
                continue
            with pytest.raises(WireCodecError):
                dec(payload)
        for trial in range(60):
            corrupt = list(payload)
            what = rng.randint(3)
            idx = rng.randint(1, len(corrupt))
            field = corrupt[idx]
            if what == 0 and isinstance(field, bytes) and field:
                cut = rng.randint(len(field))
                corrupt[idx] = field[:cut]              # truncate
            elif what == 1 and isinstance(field, bytes) and field:
                pos = rng.randint(len(field))
                flipped = bytearray(field)
                flipped[pos] ^= 1 << rng.randint(8)     # bit flip
                corrupt[idx] = bytes(flipped)
            else:
                junk = [None, "junk", -1, b"\x00", (), 3.5]
                corrupt[idx] = junk[rng.randint(len(junk))]
            try:
                its_decoder(tuple(corrupt))
            except WireCodecError:
                pass        # the contract: clean typed failure
            # a decode that still succeeds is fine (the corruption may
            # have hit a semantically-dead byte, e.g. a flipped bit
            # inside a float payload) — what must never happen is any
            # OTHER exception type, which pytest would surface here


def test_codec_error_is_valueerror_subclass():
    # pre-existing `except ValueError` call sites keep working
    assert issubclass(WireCodecError, ValueError)
    with pytest.raises(ValueError):
        decode_array(("NPX", (2,), "float32", b"\x00"))


def test_corrupt_push_never_partially_applies():
    from mxnet_tpu.kvstore.server import KVStoreServer
    srv = KVStoreServer(num_workers=1)
    init = np.zeros(8, np.float32)
    assert srv.handle(("INIT", "w", init)) == (True, None)
    # truncated QGRAD frame: decode raises BEFORE any store/optimizer
    # state is touched — the stored value must be bit-identical after
    q, s = quantize_int8_np(np.ones(8, np.float32), block=8)
    frame = encode_wire("int8", (8,), "float32", (q, s))
    bad = frame[:5] + (frame[5][:3], frame[6])
    with pytest.raises(WireCodecError):
        srv.handle(("PUSH", "w", bad))
    ok, out = srv.handle(("PULL", "w"))
    assert ok and (out == init).all()
    # and a well-formed retry of the same logical push still lands
    assert srv.handle(("PUSH", "w", frame)) == (True, None)


# ---------------------------------------------------------------------------
# the shipped tree certifies
# ---------------------------------------------------------------------------

def test_shipped_tree_zero_findings_and_pinned_schedules():
    diags, stats = protocol.check_sources(shipped_sources())
    assert diags == [], [(d.rule, "%s:%d" % (d.path, d.line), d.message)
                         for d in diags]
    assert len(stats["machines"]) == 4
    assert {m["protocol"] for m in stats["machines"]} == \
        {"kvstore", "serve", "router", "fleet"}
    assert stats["verbs"] == 30
    assert stats["schedules"] == PINNED_SCHEDULES


def test_every_manifest_verb_is_covered():
    sources = shipped_sources()
    covered = set()
    for path in MACHINE_PATHS:
        m = protocol._extract_machine(path, sources[path])
        assert m is not None, path
        for verb in m.manifest:
            assert verb in m.verbs, (path, verb)
            covered.add((m.protocol, verb))
    assert len(covered) == 30


def test_model_checker_is_deterministic():
    sources = shipped_sources()
    runs = [protocol.check_sources(sources) for _ in range(2)]
    assert runs[0][1] == runs[1][1]
    assert [(d.rule, d.path, d.line, d.message) for d in runs[0][0]] == \
        [(d.rule, d.path, d.line, d.message) for d in runs[1][0]]


# ---------------------------------------------------------------------------
# the reinjection quads: trip, then clear under targeted suppression
# ---------------------------------------------------------------------------

QUADS = [
    # (path, old, new, rule that must fire)
    ("mxnet_tpu/serve/server.py",
     '_CACHED = ("PREDICT", "SWAP", "GENERATE")',
     '_CACHED = ("PREDICT", "SWAP")',
     "protocol-replay-class"),
    ("mxnet_tpu/kvstore/server.py",
     "            self.touch(who)\n"
     "            if changed:\n",
     "            self.touch(who)\n"
     "            self._membership_epoch += 1\n"
     "            if changed:\n",
     "protocol-idempotent-epoch"),
    ("mxnet_tpu/kvstore/server.py",
     "        ent[2] = resp\n"
     "        ent[1].set()\n"
     "        if cmd in self._MUTATING:\n"
     "            self._note_mutation()\n"
     "        return resp",
     "        if cmd in self._MUTATING:\n"
     "            self._note_mutation()\n"
     "        ent[2] = resp\n"
     "        ent[1].set()\n"
     "        return resp",
     "protocol-reply-order"),
    ("mxnet_tpu/serve/router.py",
     "                send_msg(up, env)\n"
     "                while True:",
     '                send_msg(up, ("SEQ", cid, attempt, env))\n'
     "                while True:",
     "protocol-router-verbatim"),
]


@pytest.mark.parametrize("path,old,new,rule",
                         QUADS, ids=[q[3] for q in QUADS])
def test_reinjection_quad_trips_and_suppresses(path, old, new, rule):
    sources = shipped_sources()
    assert old in sources[path], "quad anchor drifted: %s" % rule
    sources[path] = sources[path].replace(old, new)
    diags, _ = protocol.check_sources(sources)
    fired = rules_of(diags)
    assert rule in fired, (rule, fired)
    # the static finding corroborated by the model checker replaying
    # the fault schedule that exploits it (except the pure-contract
    # replay-class case on a machine whose model sees the same hole)
    assert all(d.path in MACHINE_PATHS for d in diags)
    # targeted suppression at each finding's line clears the lane —
    # the documented fix-or-suppress-with-why escape hatch (two rules
    # anchored on one line ride one comma-joined disable comment)
    by_line = {}
    for d in diags:
        by_line.setdefault((d.path, d.line), set()).add(d.rule)
    for (path2, line), rset in by_line.items():
        lines = sources[path2].split("\n")
        lines[line - 1] += "  # mxlint: disable=%s" % ",".join(
            sorted(rset))
        sources[path2] = "\n".join(lines)
    diags2, _ = protocol.check_sources(sources)
    assert diags2 == [], [(d.rule, d.line) for d in diags2]


# ---------------------------------------------------------------------------
# stream-dedupe: the one rule anchored client-side
# ---------------------------------------------------------------------------

STREAM_CLIENT = """
def request(verb, payload, on_stream=None):
    pass
"""

STREAM_MACHINE = """
from mxnet_tpu.kvstore.wire_verbs import declare_verbs

WIRE_VERBS = declare_verbs("minis", {
    "GENERATE": {"semantics": "replayable", "replay": "cached",
                 "codec": None, "mutates": ("engine",), "stream": True},
}, role="server")


class S:
    _CACHED = ("GENERATE",)

    def _handle_seq(self, env):
        _, cid, seq, inner = env
        if inner[0] not in self._CACHED:
            return self.handle(inner)
        ent = self._replay.get(cid)
        if ent is not None and seq == ent[0]:
            return ent[2]
        if ent is not None and seq < ent[0]:
            return False, "stale"
        ent = [seq, _Evt(), None]
        self._replay[cid] = ent
        resp = self.handle(inner)
        ent[2] = resp
        ent[1].set()
        return resp

    def handle(self, msg):
        if msg[0] == "GENERATE":
            self.batcher.submit(msg[1])
            return True, None
"""


def test_stream_dedupe_offset_blind_callback_fires():
    blind = STREAM_CLIENT + src("""
    def run():
        request("GENERATE", "req",
                on_stream=lambda off, tok: print(tok))
    """)
    diags, _ = protocol.check_sources({
        "mxnet_tpu/minis.py": STREAM_MACHINE,
        "mxnet_tpu/minic.py": blind})
    assert "protocol-stream-dedupe" in rules_of(diags)
    d = [x for x in diags if x.rule == "protocol-stream-dedupe"][0]
    assert d.path == "mxnet_tpu/minic.py"


def test_stream_dedupe_offset_consulting_callback_clean():
    dedup = STREAM_CLIENT + src("""
    def run(state):
        def on_frame(off, tok):
            if off <= state["seen"]:
                return
            state["seen"] = off
            state["out"].append(tok)
        request("GENERATE", "req", on_stream=on_frame)
    """)
    diags, _ = protocol.check_sources({
        "mxnet_tpu/minis.py": STREAM_MACHINE,
        "mxnet_tpu/minic.py": dedup})
    assert "protocol-stream-dedupe" not in rules_of(diags)


def test_shipped_stream_client_dedupes():
    # the real serve client's on_stream plumbing consults the frame
    # offset — the rule stays quiet over the whole shipped tree (the
    # zero-findings test above covers it; this pins the client file
    # specifically so a refactor that drops the dedupe can't hide)
    sources = shipped_sources()
    assert "mxnet_tpu/serve/client.py" in sources
    diags, _ = protocol.check_sources(sources)
    assert "protocol-stream-dedupe" not in rules_of(diags)


# ---------------------------------------------------------------------------
# wire-manifest-schema (file rule riding the normal pass)
# ---------------------------------------------------------------------------

def test_wire_manifest_schema_bare_dict_fires():
    code = src("""
    WIRE_VERBS = {
        "PING": {"semantics": "idempotent", "codec": None},
    }
    """)
    diags = lint_source(code, path="mxnet_tpu/fleet.py",
                        select={"wire-manifest-schema"})
    assert [d.rule for d in diags] == ["wire-manifest-schema"]


def test_wire_manifest_schema_declared_clean_and_scoped():
    code = src("""
    from mxnet_tpu.kvstore.wire_verbs import declare_verbs
    WIRE_VERBS = declare_verbs("fleet", {
        "PING": {"semantics": "idempotent", "codec": None},
    }, role="collector")
    """)
    assert lint_source(code, path="mxnet_tpu/fleet.py",
                       select={"wire-manifest-schema"}) == []
    # out of the four machine files, a bare dict is none of this
    # rule's business (tests build toy manifests all the time)
    bare = 'WIRE_VERBS = {"X": {"semantics": "idempotent"}}\n'
    assert lint_source(bare, path="mxnet_tpu/other.py",
                       select={"wire-manifest-schema"}) == []


# ---------------------------------------------------------------------------
# CLI contract + docs gate
# ---------------------------------------------------------------------------

def run_cli(*argv):
    return subprocess.run(
        [sys.executable, "-m", "tools.mxlint"] + list(argv),
        cwd=REPO, capture_output=True, text=True)


def test_cli_protocol_clean_tree_exit_zero():
    p = run_cli("--protocol")
    assert p.returncode == 0, p.stdout + p.stderr
    assert "%d fault schedule(s) checked" % PINNED_SCHEDULES in p.stdout
    assert "0 violation(s)" in p.stdout


def test_cli_protocol_json_schema_and_fingerprints(tmp_path):
    p = run_cli("--protocol", "--format", "json")
    assert p.returncode == 0, p.stdout + p.stderr
    payload = json.loads(p.stdout)
    assert payload["protocol_schema"] == 1
    assert payload["schedules"] == PINNED_SCHEDULES
    assert payload["verbs"] == 30 and len(payload["machines"]) == 4
    assert payload["violations"] == []
    # findings DO carry fingerprints: run against a mutated copy
    mut = tmp_path / "mxnet_tpu"
    import shutil
    shutil.copytree(os.path.join(REPO, "mxnet_tpu"), mut,
                    ignore=shutil.ignore_patterns("__pycache__"))
    sp = mut / "serve" / "server.py"
    sp.write_text(sp.read_text().replace(
        '_CACHED = ("PREDICT", "SWAP", "GENERATE")',
        '_CACHED = ("PREDICT", "SWAP")'))
    p = subprocess.run(
        [sys.executable, "-m", "tools.mxlint", "--protocol",
         "--format", "json", str(mut)],
        cwd=REPO, capture_output=True, text=True)
    assert p.returncode == 1, p.stdout + p.stderr
    payload = json.loads(p.stdout)
    assert payload["violations"], "mutated tree must yield findings"
    for v in payload["violations"]:
        assert v["rule"].startswith("protocol-")
        assert len(v["fingerprint"]) == 16


def test_cli_protocol_select_and_usage_errors():
    p = run_cli("--protocol", "--select", "protocol-model")
    assert p.returncode == 0, p.stdout + p.stderr
    p = run_cli("--protocol", "--select", "no-such-rule")
    assert p.returncode == 2
    p = run_cli("--protocol", "does/not/exist")
    assert p.returncode == 2


def test_protocol_rules_listed():
    p = run_cli("--list-rules")
    assert p.returncode == 0
    for rule in ("protocol-replay-class", "protocol-idempotent-epoch",
                 "protocol-reply-order", "protocol-stream-dedupe",
                 "protocol-router-verbatim", "protocol-effects-drift",
                 "protocol-model", "protocol-error",
                 "wire-manifest-schema"):
        assert rule in p.stdout, rule


def test_gen_wire_docs_in_sync():
    p = subprocess.run(
        [sys.executable, os.path.join("tools", "gen_wire_docs.py"),
         "--check"], cwd=REPO, capture_output=True, text=True)
    assert p.returncode == 0, p.stdout + p.stderr


def test_wire_doc_mentions_every_verb():
    doc = open(os.path.join(REPO, "docs", "WIRE_PROTOCOL.md")).read()
    sources = shipped_sources()
    for path in MACHINE_PATHS:
        m = protocol._extract_machine(path, sources[path])
        for verb in m.manifest:
            assert "`%s`" % verb in doc, (path, verb)
    assert str(PINNED_SCHEDULES) in doc
