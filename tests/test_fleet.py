"""Fleet telemetry plane (ISSUE 12): merge algebra exactness (counter
restart rebasing, gauge rollups, bucket-wise histogram merge + quantile
reproduction, mismatched-boundary rejection), wire scraping of a real
kvstore server and a real serve replica (merged p99 == per-replica p99
within one bucket boundary), absent-member marking within one scrape,
straggler naming within two windows, SLO burn + latched breach on a
rejection spike, the FLEET verb + federation faces, fleet_top rendering,
the supervisor embed, and the mxlint hot-path reinjection."""
import importlib.util
import json
import os
import socket
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mxnet_tpu import fault, fleet, telemetry  # noqa: E402
from mxnet_tpu.base import ENV_CATALOG, MXNetError  # noqa: E402
from mxnet_tpu.fleet import (FleetCollector, FleetMember,  # noqa: E402
                             FleetMergeError, SLOTracker,
                             StragglerDetector, merge_bucket_maps,
                             merge_snapshots, quantile_from_buckets)
from mxnet_tpu.telemetry import Registry  # noqa: E402


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        "mx_%s_fleet_test" % name,
        os.path.join(REPO, "tools", "%s.py" % name))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _beat(path, payload, head="0 0"):
    with open(path, "w") as f:
        f.write("%f %s\n" % (time.time(), head))
        if payload is not None:
            f.write(payload if isinstance(payload, str)
                    else json.dumps(payload))
            f.write("\n")


# ---------------------------------------------------------------------------
# merge algebra
# ---------------------------------------------------------------------------

def test_counters_sum_and_gauges_roll_up():
    r1, r2 = Registry(), Registry()
    r1.counter("serve.requests").inc(5)
    r2.counter("serve.requests").inc(7)
    r1.gauge("serve.queue_rows").set(4)
    r2.gauge("serve.queue_rows").set(10)
    m = merge_snapshots({"serve:0": r1.snapshot(),
                         "serve:1": r2.snapshot()})
    c = m["counters"]["serve.requests"]
    assert c["total"] == 12
    assert c["per_member"] == {"serve:0": 5, "serve:1": 7}
    g = m["gauges"]["serve.queue_rows"]
    assert g["min"] == 4 and g["max"] == 10 and g["mean"] == 7.0


def test_histogram_merge_is_exact_vs_union():
    """merged(p50/p99) == quantiles recomputed from the union of
    observations on identical bucket boundaries."""
    buckets = (0.001, 0.01, 0.1, 1.0)
    obs_a = [0.0005, 0.005, 0.05, 0.05]
    obs_b = [0.005, 0.5, 0.5, 0.5, 0.05]
    ra, rb, runion = Registry(), Registry(), Registry()
    ha = ra.histogram("lat", buckets=buckets)
    hb = rb.histogram("lat", buckets=buckets)
    hu = runion.histogram("lat", buckets=buckets)
    for v in obs_a:
        ha.observe(v)
        hu.observe(v)
    for v in obs_b:
        hb.observe(v)
        hu.observe(v)
    merged = merge_snapshots({"a": ra.snapshot(), "b": rb.snapshot()})
    mh = merged["histograms"]["lat"]
    union = hu.snapshot()
    assert mh["buckets"] == union["buckets"]
    assert mh["count"] == len(obs_a) + len(obs_b)
    for q in (0.5, 0.9, 0.99):
        assert quantile_from_buckets(mh["buckets"], q) == \
            quantile_from_buckets(union["buckets"], q)


def test_mismatched_boundaries_rejected():
    ra, rb = Registry(), Registry()
    ra.histogram("lat", buckets=(0.01, 0.1)).observe(0.05)
    rb.histogram("lat", buckets=(0.02, 0.2)).observe(0.05)
    with pytest.raises(FleetMergeError) as ei:
        merge_snapshots({"a": ra.snapshot(), "b": rb.snapshot()})
    assert "lat" in str(ei.value) and "boundaries" in str(ei.value)


def test_quantile_upper_bound_convention():
    assert quantile_from_buckets({}, 0.99) == 0.0
    b = {"0.01": 1, "0.1": 3, "1": 4, "+Inf": 4}
    assert quantile_from_buckets(b, 0.25) == 0.01
    assert quantile_from_buckets(b, 0.5) == 0.1
    assert quantile_from_buckets(b, 1.0) == 1.0
    # mass above the top bound reports the largest FINITE boundary
    # (Prometheus histogram_quantile convention) — an inf here would
    # serialize as the non-RFC 'Infinity' token on the JSON faces
    b_inf = {"0.01": 0, "+Inf": 2}
    assert quantile_from_buckets(b_inf, 0.99) == 0.01
    assert json.loads(json.dumps(quantile_from_buckets(b_inf, 0.99)))


def test_merge_bucket_maps_sums_and_checks():
    a = {"0.1": 1, "+Inf": 2}
    b = {"0.1": 3, "+Inf": 4}
    assert merge_bucket_maps([a, b]) == {"0.1": 4, "+Inf": 6}
    assert merge_bucket_maps([a, {}]) == a      # empties drop out
    with pytest.raises(FleetMergeError):
        merge_bucket_maps([a, {"0.2": 1, "+Inf": 1}], name="x")


def test_counter_restart_rebased_not_double_counted(tmp_path):
    """A member restart resets its process counters; the fleet total
    must neither jump backwards nor double-count the pre-restart work."""
    hb = str(tmp_path / "rank_0")
    c = FleetCollector([FleetMember("worker", 0, heartbeat=hb)],
                       interval=0.01, stale_after=60)
    _beat(hb, {"schema": 1, "step": 100, "steps_per_sec": 10.0})
    m1 = c.scrape_once()
    assert m1["counters"]["worker.steps"]["total"] == 100
    _beat(hb, {"schema": 1, "step": 130, "steps_per_sec": 10.0})
    m2 = c.scrape_once()
    assert m2["counters"]["worker.steps"]["total"] == 130
    # restart: the rank's step counter resets and climbs to 20
    _beat(hb, {"schema": 1, "step": 20, "steps_per_sec": 10.0})
    m3 = c.scrape_once()
    assert m3["counters"]["worker.steps"]["total"] == 150   # 130 + 20
    _beat(hb, {"schema": 1, "step": 25, "steps_per_sec": 10.0})
    m4 = c.scrape_once()
    assert m4["counters"]["worker.steps"]["total"] == 155
    totals = [m["counters"]["worker.steps"]["total"]
              for m in (m1, m2, m3, m4)]
    assert totals == sorted(totals)             # monotone


# ---------------------------------------------------------------------------
# detectors
# ---------------------------------------------------------------------------

def test_straggler_named_within_two_windows():
    det = StragglerDetector(factor=2.0, window=5)
    fast = {"step_seconds": 0.1,
            "phases": {"forward": 0.06, "data_wait": 0.02}}
    slow = {"step_seconds": 0.3,
            "phases": {"forward": 0.08, "data_wait": 0.2}}
    found = []
    for _round in range(2):
        found = det.update({"worker:0": fast, "worker:1": slow})
    assert len(found) == 1
    f = found[0]
    assert f["member"] == "worker:1"
    assert f["ratio"] >= 3.0 - 1e-6
    assert f["dominant_phase"] == "data_wait"
    assert f["dominant_share"] > 0.5


def test_no_straggler_when_uniform():
    det = StragglerDetector(factor=2.0, window=3)
    s = {"step_seconds": 0.1, "phases": {"forward": 0.1}}
    for _ in range(3):
        assert det.update({"worker:0": dict(s), "worker:1": dict(s)}) \
            == []


def test_slo_latch_on_rejection_spike():
    tr = SLOTracker(window=4, targets={"rejection_rate": 0.05})
    out = tr.update({}, rejected_delta=0, offered_delta=100,
                    queue_depth=0)
    assert out["burn"]["rejection_rate"] == 0.0
    assert out["breached"] == {}
    out = tr.update({}, rejected_delta=40, offered_delta=100,
                    queue_depth=0)
    assert out["burn"]["rejection_rate"] > 1.0
    assert "rejection_rate" in out["breached"]
    # latched: a healthy round later, the breach stays raised
    out = tr.update({}, rejected_delta=0, offered_delta=100,
                    queue_depth=0)
    assert "rejection_rate" in out["breached"]
    # latched: healthy rounds (even past the window) keep it raised
    for _ in range(5):
        out = tr.update({}, rejected_delta=0, offered_delta=100,
                        queue_depth=0)
    assert out["burn"]["rejection_rate"] == 0.0
    assert "rejection_rate" in out["breached"]
    # only an explicit operator reset un-latches — and with the spike
    # aged out of the window it stays quiet
    tr.reset()
    out = tr.update({}, rejected_delta=0, offered_delta=100,
                    queue_depth=0)
    assert out["breached"] == {}


def test_slo_latency_burn_from_bucket_deltas():
    tr = SLOTracker(window=4, targets={"p99_latency": 50.0})
    fast = {"0.01": 10, "0.1": 10, "+Inf": 10}       # all <= 10ms
    out = tr.update(fast, 0, 10, 0)
    assert out["p99_ms"] == 10.0 and out["breached"] == {}
    slow = {"0.01": 0, "0.1": 20, "+Inf": 20}        # all <= 100ms
    out = tr.update(slow, 0, 20, 0)
    assert out["p99_ms"] == 100.0
    assert out["burn"]["p99_latency"] == 2.0
    assert "p99_latency" in out["breached"]


def test_slo_latency_window_ages_out_when_idle():
    """Idle rounds roll the window too: a spike must not keep burn hot
    forever on a fleet serving zero traffic (review finding)."""
    tr = SLOTracker(window=3, targets={"p99_latency": 50.0})
    spike = {"0.01": 0, "0.1": 10, "+Inf": 10}       # p99 = 100ms
    out = tr.update(spike, 0, 10, 0)
    assert out["burn"]["p99_latency"] == 2.0
    for _ in range(3):                               # 3 idle rounds
        out = tr.update({}, 0, 0, 0)
    assert out["p99_ms"] == 0.0
    assert out["burn"]["p99_latency"] == 0.0
    # the breach stays LATCHED by design; only the live burn decays
    assert "p99_latency" in out["breached"]


def test_straggler_history_survives_one_missed_round():
    det = StragglerDetector(factor=2.0, window=5)
    fast = {"step_seconds": 0.1, "phases": {"forward": 0.1}}
    slow = {"step_seconds": 0.3, "phases": {"data_wait": 0.3}}
    for _ in range(3):
        det.update({"worker:0": fast, "worker:1": slow})
    # worker:1 misses ONE round (transient scrape failure): its window
    # must survive, and it is named again the moment it reports
    det.update({"worker:0": fast})
    found = det.update({"worker:0": fast, "worker:1": slow})
    assert [f["member"] for f in found] == ["worker:1"]
    # a full window of misses DOES retire the history
    for _ in range(6):
        det.update({"worker:0": fast})
    assert det.update({"worker:0": fast}) == []


def test_straggler_ages_out_present_but_durationless_worker():
    """A worker that stays PRESENT but stops reporting a usable step
    duration (e.g. its payload is dropped by the schema gate) must age
    out of detection like an absent one — not stay flagged forever on
    a frozen pre-silence mean (review finding)."""
    det = StragglerDetector(factor=2.0, window=3)
    fast = {"step_seconds": 0.1, "phases": {"forward": 0.1}}
    slow = {"step_seconds": 0.3, "phases": {"data_wait": 0.3}}
    for _ in range(3):
        det.update({"worker:0": fast, "worker:1": slow})
    mute = {"step_seconds": None, "phases": {}}
    out = []
    for _ in range(5):      # present every round, never a duration
        out = det.update({"worker:0": fast, "worker:1": mute})
    assert out == []        # frozen history retired, flag dropped


def test_first_scrape_lifetime_totals_do_not_latch_slo(tmp_path):
    """Attaching a collector to an already-running fleet must not
    compute burn over lifetime history (review finding)."""
    r = Registry()
    r.counter("serve.requests").inc(100)
    r.counter("serve.rejected").inc(1000)    # ancient startup burst
    snap = r.snapshot()
    c = FleetCollector([FleetMember("serve", 0, addr="127.0.0.1:1")],
                       interval=0.05,
                       slo_targets={"rejection_rate": 0.05})
    st = c._state["serve:0"]
    c._rebase_counters(st, snap)
    merged = c._fold(c.members(), {"serve:0": (snap, "wire", None, 0)})
    assert merged["slo"]["burn"].get("rejection_rate", 0.0) == 0.0
    assert merged["slo"]["breached"] == {}


def test_model_of_prefers_highest_version():
    r = Registry()
    r.gauge("serve.active_version", labels={"model": "mlp-a"}).set(1)
    r.gauge("serve.active_version", labels={"model": "mlp-b"}).set(2)
    assert FleetCollector._model_of(r.snapshot()) == "mlp-b"


def test_breached_gauge_clears_after_reset(tmp_path):
    hb = str(tmp_path / "rank_0")
    _beat(hb, {"schema": 1, "step": 1, "steps_per_sec": 5.0})
    c = FleetCollector([FleetMember("worker", 0, heartbeat=hb)],
                       interval=0.05, stale_after=60,
                       slo_targets={"queue_depth": 1.0})
    c.scrape_once()
    # force a queue breach: feed the tracker directly, then publish
    c.slo.update({}, 0, 0, queue_depth=5.0)
    m = c.scrape_once()
    gauge = telemetry.registry.find("fleet.slo_breached",
                                    {"slo": "queue_depth"})
    # the latch itself is sticky across healthy rounds...
    assert gauge is not None
    if "queue_depth" in m["slo"]["breached"]:
        assert gauge.value == 1
    c.slo.reset()
    c.scrape_once()
    # ...but an operator reset clears the EXPORTED gauge too
    assert gauge.value == 0


def test_hung_member_does_not_stall_the_round(tmp_path):
    """Members scrape concurrently: one peer that accepts and never
    replies costs ITS slot the scrape_timeout, not the whole round
    (review finding — the absent-within-one-scrape promise is per
    member)."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(2)
    addr = "127.0.0.1:%d" % srv.getsockname()[1]
    hb = str(tmp_path / "rank_0")
    _beat(hb, {"schema": 1, "step": 3, "steps_per_sec": 5.0})
    c = FleetCollector([FleetMember("serve", 0, addr=addr),
                        FleetMember("worker", 0, heartbeat=hb)],
                       interval=0.05, stale_after=60,
                       scrape_timeout=0.5)
    t0 = time.monotonic()
    m = c.scrape_once()
    assert time.monotonic() - t0 < 3.0
    assert m["members"]["worker:0"]["present"]
    assert not m["members"]["serve:0"]["present"]
    srv.close()


def test_collector_restartable_after_stop(tmp_path):
    hb = str(tmp_path / "rank_0")
    _beat(hb, {"schema": 1, "step": 1, "steps_per_sec": 5.0})
    c = FleetCollector([FleetMember("worker", 0, heartbeat=hb)],
                       interval=0.05, stale_after=60)
    c.start()
    c.stop()
    n0 = c.snapshot()["scrape"] if c.snapshot() else 0
    c.start()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        s = c.snapshot()
        if s and s["scrape"] > n0:
            break
        time.sleep(0.02)
    c.stop()
    assert c.snapshot()["scrape"] > n0      # the restarted thread scrapes


# ---------------------------------------------------------------------------
# collector over real wires
# ---------------------------------------------------------------------------

@pytest.fixture
def kv_server():
    from mxnet_tpu.kvstore import server as kvs
    port = _free_port()
    t = threading.Thread(target=kvs.serve_forever,
                         kwargs=dict(port=port, num_workers=1),
                         daemon=True)
    t.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port),
                                     timeout=0.2).close()
            break
        except OSError:
            time.sleep(0.02)
    yield "127.0.0.1:%d" % port
    try:
        from tools.launch import _send_stop
        _send_stop("127.0.0.1:%d" % port)
    except Exception:
        pass


def test_kvstore_metrics_verb_scrape(kv_server):
    snap = fleet.fetch_metrics(kv_server, fmt="json")
    assert any(k.startswith("engine.") for k in snap)
    text = fleet.fetch_metrics(kv_server, fmt="prometheus")
    assert "# TYPE" in text
    c = FleetCollector([FleetMember("server", 0, addr=kv_server)],
                       interval=0.05)
    merged = c.scrape_once()
    meta = merged["members"]["server:0"]
    assert meta["present"] and meta["source"] == "wire"


def test_absent_marked_within_one_scrape(tmp_path):
    dead_addr = "127.0.0.1:%d" % _free_port()        # nothing listening
    hb = str(tmp_path / "rank_0")
    _beat(hb, {"schema": 1, "step": 3, "steps_per_sec": 5.0})
    c = FleetCollector([FleetMember("serve", 0, addr=dead_addr),
                        FleetMember("worker", 0, heartbeat=hb)],
                       interval=0.05, stale_after=0.2,
                       scrape_timeout=0.5)
    m = c.scrape_once()
    assert not m["members"]["serve:0"]["present"]
    assert m["members"]["serve:0"]["absent_scrapes"] == 1
    assert m["members"]["worker:0"]["present"]
    # worker goes silent: stale past the bound -> absent next scrape
    time.sleep(0.3)
    m = c.scrape_once()
    assert not m["members"]["worker:0"]["present"]
    assert telemetry.registry.value("fleet.members_absent") == 2


def test_malformed_heartbeat_line_tolerated_and_counted(tmp_path):
    hb = str(tmp_path / "rank_0")
    # both malformed classes: broken JSON, and VALID JSON that is not
    # an object (a torn write can leave a bare number — review finding:
    # this must count as malformed, not kill the scraper thread)
    for bad in ("{not json", "42", "null"):
        _beat(hb, bad, head="1 2")
        c = FleetCollector([FleetMember("worker", 0, heartbeat=hb)],
                           interval=0.05, stale_after=60)
        n0 = telemetry.registry.value("fleet.malformed_beats")
        m = c.scrape_once()
        # the beat still proves liveness; the bad payload is counted
        assert m["members"]["worker:0"]["present"], bad
        assert m["malformed_beats"] == 1, bad
        assert telemetry.registry.value("fleet.malformed_beats") == n0 + 1


def test_parse_heartbeat_shared_helper():
    head, payload, bad = telemetry.parse_heartbeat(
        ["123.4 1 2", '{"schema": 1, "step": 7}'])
    assert head == "123.4 1 2" and payload["step"] == 7 and bad == 0
    for line2 in ("{broken", "7", "null", "[1]"):
        _h, payload, bad = telemetry.parse_heartbeat(["t 0 0", line2])
        assert payload == {} and bad == 1, line2
    assert telemetry.parse_heartbeat([]) == ("", {}, 0)
    # a beat stamped by a NEWER framework is ignored, not mis-rendered
    _h, payload, bad = telemetry.parse_heartbeat(
        ["t 0 0", '{"schema": %d, "step": 9}'
         % (telemetry.HEARTBEAT_SCHEMA + 1)])
    assert payload == {} and bad == 0


def test_survivor_rollups_keep_advancing_past_a_death(tmp_path):
    hb0, hb1 = str(tmp_path / "rank_0"), str(tmp_path / "rank_1")
    _beat(hb0, {"schema": 1, "step": 10, "steps_per_sec": 5.0})
    _beat(hb1, {"schema": 1, "step": 10, "steps_per_sec": 5.0})
    c = FleetCollector([FleetMember("worker", 0, heartbeat=hb0),
                        FleetMember("worker", 1, heartbeat=hb1)],
                       interval=0.05, stale_after=0.25)
    m1 = c.scrape_once()
    assert m1["counters"]["worker.steps"]["total"] == 20
    os.remove(hb1)                                  # rank 1 dies
    _beat(hb0, {"schema": 1, "step": 15, "steps_per_sec": 5.0})
    m2 = c.scrape_once()
    assert not m2["members"]["worker:1"]["present"]
    # the dead rank's counted work is retained, the survivor advances
    assert m2["counters"]["worker.steps"]["total"] == 25


# ---------------------------------------------------------------------------
# serve-replica scrape: merged p99 within one bucket boundary
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def serve_replica():
    from mxnet_tpu.serve import (BucketTable, ServeClient, ServeServer,
                                 Servable, serve_forever)
    from mxnet_tpu.serve.demo import DEMO_IN, demo_block, demo_example
    port = _free_port()
    state = ServeServer()
    # two buckets, not the default five: the scrape contract under test
    # is bucket-count-independent and each bucket costs a trace+compile
    state.host.deploy(Servable(demo_block(), name="demo-mlp", version=1,
                               buckets=BucketTable((1, 2))),
                      example=demo_example())
    stop_ev = threading.Event()
    t = threading.Thread(target=serve_forever,
                         kwargs=dict(port=port, state=state,
                                     stop_event=stop_ev), daemon=True)
    t.start()
    addr = "127.0.0.1:%d" % port
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port),
                                     timeout=0.2).close()
            break
        except OSError:
            time.sleep(0.05)
    cli = ServeClient([addr], timeout=30)
    x = np.zeros((1, DEMO_IN), np.float32)
    for _ in range(4):
        cli.predict([x])
    cli.close()
    yield addr
    stop_ev.set()


def test_fleet_p99_matches_replica_p99(serve_replica):
    c = FleetCollector([FleetMember("serve", 0, addr=serve_replica)],
                       interval=0.05)
    merged = c.scrape_once()
    key = "step_phase_seconds{phase=serve_dispatch}"
    mh = merged["histograms"].get(key)
    assert mh is not None and mh["count"] >= 1
    per_replica = fleet.fetch_metrics(serve_replica, fmt="json")[key]
    expect = quantile_from_buckets(per_replica["buckets"], 0.99)
    # single member: exact; the convention makes multi-member merges
    # land within one bucket boundary by construction
    assert mh["p99"] == expect
    # the member self-describes its model via the version gauge
    assert merged["members"]["serve:0"]["model"] == "demo-mlp"


def test_fleet_verb_and_federation(serve_replica):
    c = FleetCollector([FleetMember("serve", 0, addr=serve_replica)],
                       interval=0.05)
    c.scrape_once()
    srv = fleet.serve_fleet(c, 0)
    try:
        addr = "127.0.0.1:%d" % srv.server_address[1]
        snap = fleet.fetch_fleet(addr)
        assert snap["schema"] == fleet.SCHEMA
        assert snap["members"]["serve:0"]["present"]
        fed = fleet.fetch_metrics(addr, fmt="prometheus")
        assert 'role="serve"' in fed and 'rank="0"' in fed
        assert 'model="demo-mlp"' in fed
        assert "mx_fleet_members" in fed        # local rollups ride too
    finally:
        srv.shutdown()
        srv.server_close()


def test_federation_http_endpoint(kv_server):
    import urllib.request
    c = FleetCollector([FleetMember("server", 0, addr=kv_server)],
                       interval=0.05)
    c.scrape_once()
    srv = fleet._serve_federation(c, 0)
    try:
        hp = srv.server_address[1]
        txt = urllib.request.urlopen(
            "http://127.0.0.1:%d/metrics" % hp, timeout=5).read().decode()
        assert 'role="server"' in txt and "mx_fleet_members" in txt
        snap = json.loads(urllib.request.urlopen(
            "http://127.0.0.1:%d/fleet.json" % hp, timeout=5).read())
        assert snap["schema"] == fleet.SCHEMA
        assert snap["members"]["server:0"]["present"]
    finally:
        srv.shutdown()
        srv.server_close()


def test_fleet_top_renders_once(serve_replica, tmp_path, capsys):
    c = FleetCollector([FleetMember("serve", 0, addr=serve_replica)],
                       interval=0.05)
    c.scrape_once()
    srv = fleet.serve_fleet(c, 0)
    try:
        addr = "127.0.0.1:%d" % srv.server_address[1]
        ft = _load_tool("fleet_top")
        rc = ft.main(["--fleet", addr, "--once"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "serve:0" in out and "slo:" in out
    finally:
        srv.shutdown()
        srv.server_close()


def test_fleet_top_table_flags_stragglers_and_absent():
    ft = _load_tool("fleet_top")
    snap = {
        "schema": 1, "scrape": 7,
        "members": {
            "worker:0": {"role": "worker", "present": True,
                         "source": "heartbeat", "model": None},
            "worker:1": {"role": "worker", "present": False,
                         "absent_scrapes": 3, "source": "heartbeat",
                         "model": None},
        },
        "counters": {"worker.steps": {"per_member": {"worker:0": 12}}},
        "gauges": {"worker.steps_per_sec":
                   {"per_member": {"worker:0": 4.0}}},
        "histograms": {},
        "stragglers": [{"member": "worker:0", "ratio": 3.1,
                        "dominant_phase": "data_wait"}],
        "slo": {"p50_ms": 1, "p99_ms": 2, "rejection_rate": 0.0,
                "queue_depth": 0, "burn": {"p99_latency": 1.5},
                "breached": {"p99_latency": {}}},
    }
    out = ft.render(snap)
    assert "STRAGGLER(3.1x data_wait)" in out
    assert "ABSENT(3)" in out
    assert "BREACH" in out


# ---------------------------------------------------------------------------
# supervisor embed
# ---------------------------------------------------------------------------

def _load_launch():
    spec = importlib.util.spec_from_file_location(
        "mx_launch_fleet_test", os.path.join(REPO, "tools", "launch.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_supervisor_embeds_collector_and_flags(tmp_path, monkeypatch):
    monkeypatch.setenv("MX_FLEET_STALE", "60")
    launch = _load_launch()
    sup = launch.Supervisor(status_interval=1)
    hb0, hb1 = str(tmp_path / "rank_0"), str(tmp_path / "rank_1")
    _beat(hb0, {"schema": 1, "step": 10, "steps_per_sec": 10.0,
                "phases": {"forward": 0.09}})
    _beat(hb1, {"schema": 1, "step": 4, "steps_per_sec": 2.0,
                "phases": {"forward": 0.1, "data_wait": 0.39}})
    sup.add("rank 0", ["true"], {"MX_PROCESS_ID": "0"}, heartbeat=hb0)
    sup.add("rank 1", ["true"], {"MX_PROCESS_ID": "1"}, heartbeat=hb1)
    sup._start_collector()
    try:
        assert sup.fleet is not None
        for _ in range(2):
            sup.fleet.scrape_once()
        table = sup.status_table()
        assert "flags" in table
        assert "STRAGGLER" in table and "data_wait" in table
        # crash dumps carry the fleet section
        monkeypatch.setenv("MX_CRASH_DIR", str(tmp_path / "crash"))
        path = sup._crash_dump(sup.procs[1], 1, "exit 1")
        blob = json.load(open(path))
        assert blob["fleet"]["schema"] == fleet.SCHEMA
        assert "worker:1" in blob["fleet"]["members"]
    finally:
        sup._stop_collector()


def test_supervisor_read_beat_counts_malformed(tmp_path):
    launch = _load_launch()
    hb = str(tmp_path / "hb")
    _beat(hb, "{broken", head="2 5")
    sp = launch.SupervisedProc("rank 0", ["true"], {}, heartbeat=hb)
    n0 = launch.Supervisor.malformed_beats
    age, head, payload = launch.Supervisor._read_beat(sp)
    assert age is not None and payload == {}
    assert head.split()[1:] == ["2", "5"]           # beat NOT dropped
    assert launch.Supervisor.malformed_beats == n0 + 1


def test_supervisor_read_beat_virtual_clock_age(tmp_path):
    launch = _load_launch()
    hb = str(tmp_path / "hb")
    sp = launch.SupervisedProc("rank 0", ["true"], {}, heartbeat=hb)
    with fault.use_virtual_time() as clk:
        _beat(hb, {"schema": 1, "step": 1, "ts": fault.now()})
        clk.advance(42.0)
        age, _head, payload = launch.Supervisor._read_beat(sp)
    assert payload.get("schema") == telemetry.HEARTBEAT_SCHEMA
    # the age came off the injectable clock, not wall-vs-mtime
    assert abs(age - 42.0) < 1e-6


def test_heartbeat_payload_has_schema_ts_and_phases(tmp_path):
    telemetry.flight_recorder.clear()
    with telemetry.phase("forward"):
        pass
    telemetry.note_step(epoch=0, batch=1)
    p = telemetry.heartbeat_payload()
    try:
        assert p["schema"] == telemetry.HEARTBEAT_SCHEMA
        assert isinstance(p["ts"], (int, float))
        assert "forward" in p.get("phases", {})
    finally:
        telemetry.flight_recorder.clear()


# ---------------------------------------------------------------------------
# env catalog + mxlint wiring
# ---------------------------------------------------------------------------

def test_fleet_env_knobs_cataloged():
    for name in ("MX_FLEET_INTERVAL", "MX_FLEET_RING", "MX_FLEET_WINDOW",
                 "MX_FLEET_STRAGGLER_FACTOR", "MX_FLEET_STALE",
                 "MX_FLEET_SLO_P50_MS", "MX_FLEET_SLO_P99_MS",
                 "MX_FLEET_SLO_REJECT_RATE", "MX_FLEET_SLO_QUEUE",
                 "MX_FLEET_SLO_PHASES", "MX_FLEET_PORT",
                 "MX_FLEET_HTTP_PORT"):
        assert name in ENV_CATALOG, name


def test_fleet_is_hot_path_root():
    from tools.mxlint.rules import HOT_PATH_ROOTS
    roots = dict(HOT_PATH_ROOTS)
    assert "mxnet_tpu/fleet.py" in roots
    quals = roots["mxnet_tpu/fleet.py"]
    assert "FleetCollector.scrape_once" in quals
    assert "merge_snapshots" in quals


def test_reinjected_sync_in_merge_loop_trips_hot_path_rule():
    from tools.mxlint import lint_source
    p = os.path.join(REPO, "mxnet_tpu", "fleet.py")
    with open(p) as f:
        code = f.read()
    anchor = "        merged = self._fold(members, snap_results)"
    assert anchor in code, "scrape_once moved; update this test"
    bad = code.replace(
        anchor, "        _dbg = snap_results and "
                "list(snap_results.values())[0][0].asnumpy()\n" + anchor,
        1)
    diags = lint_source(bad, "mxnet_tpu/fleet.py")
    assert "host-sync-in-hot-path" in {d.rule for d in diags}, \
        {d.rule for d in diags}


def test_shipped_fleet_lints_clean():
    from tools.mxlint import lint_paths
    # wire_codec rides along: the wire-verb rule is project-scope and
    # resolves the json/text codec pairs from the scanned set
    diags = lint_paths(
        [os.path.join(REPO, "mxnet_tpu", "fleet.py"),
         os.path.join(REPO, "tools", "fleet_top.py"),
         os.path.join(REPO, "mxnet_tpu", "kvstore", "wire_codec.py")],
        root=REPO)
    assert [d for d in diags] == [], diags


def test_wire_verbs_declared():
    from mxnet_tpu.fleet import WIRE_VERBS as FLEET_VERBS
    from mxnet_tpu.kvstore.server import WIRE_VERBS as KV_VERBS
    assert FLEET_VERBS["FLEET"]["semantics"] == "idempotent"
    assert FLEET_VERBS["METRICS"]["codec"] == "text"
    assert KV_VERBS["METRICS"]["semantics"] == "idempotent"


# ---------------------------------------------------------------------------
# telemetry_dump graceful-partial behavior + fleet row
# ---------------------------------------------------------------------------

def test_telemetry_dump_partial_dir_exits_zero(tmp_path, capsys):
    td = _load_tool("telemetry_dump")
    d = str(tmp_path / "traces")
    os.makedirs(d)
    out = str(tmp_path / "merged.json")
    rc = td.main(["--out", out, "--dir", d,
                  "--expect-roles", "worker,server,fleet"])
    captured = capsys.readouterr()
    assert rc == 0 and os.path.exists(out)
    summary = json.loads(captured.out)
    assert summary["absent_roles"] == ["fleet", "server", "worker"]
    assert "no input traces" in captured.err


def test_telemetry_dump_skips_unreadable_and_merges_fleet_row(
        tmp_path, capsys):
    td = _load_tool("telemetry_dump")
    good = str(tmp_path / "trace-worker-r0-p1.trace.json")
    json.dump({"traceEvents": [{"name": "phase.forward", "ph": "X",
                                "ts": 1.0, "dur": 2.0, "pid": 1,
                                "tid": 1, "args": {"trace_id": "t1"}}],
               "metadata": {"role": "worker", "rank": "0", "pid": 1}},
              open(good, "w"))
    fleet_tr = str(tmp_path / "trace-fleet-r0-p2.trace.json")
    json.dump({"traceEvents": [{"name": "fleet.scrape.METRICS",
                                "ph": "X", "ts": 2.0, "dur": 1.0,
                                "pid": 2, "tid": 2, "args": {}}],
               "metadata": {"role": "fleet", "rank": "0", "pid": 2}},
              open(fleet_tr, "w"))
    bad = str(tmp_path / "trace-server-r0-p3.trace.json")
    with open(bad, "w") as f:
        f.write("{corrupt")
    out = str(tmp_path / "merged.json")
    rc = td.main(["--out", out, good, fleet_tr, bad,
                  "--expect-roles", "worker,server,fleet"])
    captured = capsys.readouterr()
    assert rc == 0
    summary = json.loads(captured.out)
    assert set(summary["roles"]) == {"worker", "fleet"}
    assert list(summary["skipped"]) == [os.path.basename(bad)]
    assert summary["absent_roles"] == ["server"]
    merged = json.load(open(out))
    names = {e.get("args", {}).get("name") for e in merged["traceEvents"]
             if e.get("ph") == "M"}
    assert any(n and n.startswith("fleet ") for n in names)


def test_collector_flushes_fleet_trace_row(tmp_path, monkeypatch):
    monkeypatch.setenv("MX_TELEMETRY_TRACE", str(tmp_path))
    telemetry.clear_trace()
    c = FleetCollector([], interval=0.05)
    telemetry.start_tracing()
    try:
        with telemetry.rpc_span("fleet.scrape.METRICS"):
            pass
    finally:
        telemetry.stop_tracing()
    c.stop()
    files = [f for f in os.listdir(str(tmp_path))
             if f.startswith("trace-fleet-")]
    assert files, os.listdir(str(tmp_path))
    blob = json.load(open(str(tmp_path / files[0])))
    assert blob["metadata"]["role"] == "fleet"
    telemetry.clear_trace()


# ---------------------------------------------------------------------------
# prometheus escaping round-trip (ISSUE 12 satellite)
# ---------------------------------------------------------------------------

def _parse_prom_labels(raw):
    """Minimal exposition-format label parser (the round-trip half)."""
    out = {}
    i = 0
    while i < len(raw):
        eq = raw.index("=", i)
        key = raw[i:eq]
        assert raw[eq + 1] == '"'
        j = eq + 2
        val = []
        while raw[j] != '"':
            if raw[j] == "\\":
                nxt = raw[j + 1]
                val.append({"\\": "\\", '"': '"', "n": "\n"}[nxt])
                j += 2
            else:
                val.append(raw[j])
                j += 1
        out[key] = "".join(val)
        i = j + 1
        if i < len(raw) and raw[i] == ",":
            i += 1
    return out


def test_prometheus_label_escaping_roundtrip():
    nasty = 'mo"del\\path\nwith newline'
    r = Registry()
    r.counter("serve.requests", labels={"model": nasty}).inc(3)
    text = r.to_prometheus()
    line = next(ln for ln in text.splitlines()
                if ln.startswith("mx_serve_requests{"))
    raw = line[line.index("{") + 1:line.rindex("}")]
    assert _parse_prom_labels(raw)["model"] == nasty
    # exactly one sample line — the raw newline did not split it
    samples = [ln for ln in text.splitlines()
               if ln.startswith("mx_serve_requests{")]
    assert len(samples) == 1 and samples[0].endswith(" 3")
