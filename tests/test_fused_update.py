"""ISSUE 3: single-dispatch training step.

Pins the three tentpole layers:
  * fused multi-tensor optimizer apply == per-param loop to fp32 tolerance
    (SGD / SGD-momentum / NAG / Adam / AdamW, incl. multi-precision bf16
    weights + fp32 master, and lr_mult / wd_mult overrides);
  * dispatch-count regression: Trainer.step and metric.update issue O(1)
    device dispatches, not O(#params) (tools/dispatch_count.py harness);
  * bucketed gradient exchange: deterministic key→bucket layout, dist_async
    roundtrip over real sockets, retry-layer composition;
  * device-side metric accumulation parity with the host-numpy path.
"""
import os
import socket
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu import optimizer as opt
from mxnet_tpu.engine import engine
from mxnet_tpu.gluon import nn

SHAPES = [(4, 3), (7,), (2, 3, 2), (5, 5), (1,)]


def _run_updater(name, kwargs, aggregate, steps=4, mp=False, mults=False):
    """Drive an Updater over SHAPES params; returns final fp32 weights."""
    np.random.seed(0)
    dtype = "bfloat16" if mp else "float32"
    o = opt.create(name, multi_precision=mp,
                   param_idx2name={i: "p%d_weight" % i
                                   for i in range(len(SHAPES))}, **kwargs)
    if not aggregate:
        o.aggregate_num = 0
    assert (o.aggregate_num > 0) == aggregate
    if mults:
        o.set_lr_mult({"p1_weight": 0.5, "p3_weight": 2.0})
        o.set_wd_mult({"p2_weight": 3.0})
    upd = opt.get_updater(o)
    ws = [nd.array(np.random.randn(*s).astype(np.float32)).astype(dtype)
          for s in SHAPES]
    for step in range(steps):
        gs = [nd.array((np.random.randn(*s) * (step + 1)).astype(np.float32)
                       ).astype(dtype) for s in SHAPES]
        upd(list(range(len(SHAPES))), gs, ws)
    return [w.asnumpy().astype(np.float32) for w in ws]


@pytest.mark.parametrize("mults", [False, True])
@pytest.mark.parametrize("mp", [False, True])
@pytest.mark.parametrize("name,kwargs", [
    ("sgd", {"learning_rate": 0.1}),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 0.01}),
    ("sgd", {"learning_rate": 0.5, "momentum": 0.9, "clip_gradient": 0.2}),
    ("nag", {"learning_rate": 0.1, "momentum": 0.9, "wd": 0.01}),
    ("adam", {"learning_rate": 0.01, "wd": 0.01}),
    ("adamw", {"learning_rate": 0.01, "wd": 0.05}),
])
def test_fused_matches_per_param_loop(name, kwargs, mp, mults):
    fused = _run_updater(name, kwargs, True, mp=mp, mults=mults)
    loop = _run_updater(name, kwargs, False, mp=mp, mults=mults)
    for f, l in zip(fused, loop):
        np.testing.assert_allclose(f, l, rtol=2e-5, atol=1e-6)


def test_fused_respects_lr_scheduler():
    from mxnet_tpu.lr_scheduler import FactorScheduler

    def run(aggregate):
        np.random.seed(1)
        o = opt.SGD(momentum=0.9,
                    lr_scheduler=FactorScheduler(step=2, factor=0.5,
                                                 base_lr=0.2))
        if not aggregate:
            o.aggregate_num = 0
        upd = opt.get_updater(o)
        ws = [nd.array(np.random.randn(*s).astype(np.float32))
              for s in SHAPES]
        for _ in range(5):
            gs = [nd.array(np.random.randn(*s).astype(np.float32))
                  for s in SHAPES]
            upd(list(range(len(SHAPES))), gs, ws)
        return [w.asnumpy() for w in ws]

    for f, l in zip(run(True), run(False)):
        np.testing.assert_allclose(f, l, rtol=2e-5, atol=1e-6)


def test_aggregate_env_opt_out(monkeypatch):
    monkeypatch.setenv("MX_OPTIMIZER_AGGREGATE", "0")
    assert opt.create("sgd").aggregate_num == 0
    assert opt.create("adam").aggregate_num == 0
    monkeypatch.setenv("MX_OPTIMIZER_AGGREGATE", "8")
    assert opt.create("sgd").aggregate_num == 8
    monkeypatch.delenv("MX_OPTIMIZER_AGGREGATE")
    assert opt.create("sgd").aggregate_num > 0     # fused by default
    assert opt.create("adamw").aggregate_num > 0
    # explicit constructor arg wins over the default
    assert opt.create("sgd", aggregate_num=3).aggregate_num == 3


def test_aggregate_num_chunks_dispatches():
    o = opt.SGD(learning_rate=0.1, momentum=0.9, aggregate_num=2)
    upd = opt.get_updater(o)
    ws = [nd.ones((3, 3)) for _ in range(5)]
    gs = [nd.ones((3, 3)) for _ in range(5)]
    upd(list(range(5)), gs, ws)          # warmup (state creation)
    c0 = engine.dispatch_count
    upd(list(range(5)), gs, ws)
    assert engine.dispatch_count - c0 == 3   # ceil(5 / 2)


def test_fused_updater_state_roundtrip():
    """Pickled updater states from the fused path load back and keep the
    trajectory identical (momentum buffers survive)."""
    o = opt.SGD(learning_rate=0.1, momentum=0.9)
    upd = opt.get_updater(o)
    ws = [nd.ones((3, 3)) for _ in range(3)]
    gs = [nd.ones((3, 3)) * 0.5 for _ in range(3)]
    upd(list(range(3)), gs, ws)
    blob = upd.get_states()
    upd2 = opt.get_updater(opt.SGD(learning_rate=0.1, momentum=0.9))
    upd2.set_states(blob)
    assert set(upd2.states) == {0, 1, 2}
    ws2 = [w.copy() for w in ws]
    upd(list(range(3)), gs, ws)
    upd2(list(range(3)), gs, ws2)
    for a, b in zip(ws, ws2):
        np.testing.assert_allclose(a.asnumpy(), b.asnumpy(), rtol=1e-6)


# ---------------------------------------------------------------------------
# dispatch budget: O(1) per step, not O(#params)
# ---------------------------------------------------------------------------

def test_trainer_step_dispatch_budget():
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import dispatch_count
    report = dispatch_count.run(steps=3)
    assert report["ok"], report
    assert report["params"] >= 10
    assert report["trainer_step_dispatches"] <= report["step_budget"]
    assert report["trainer_step_dispatches"] < report["params"]
    assert report["metric_update_dispatches"] <= report["metric_budget"]


def test_trainer_fused_step_matches_loop_trajectory():
    """End-to-end Gluon: training with the fused step reproduces the
    per-param-loop trajectory."""

    def train(aggregate):
        mx.random.seed(0)
        np.random.seed(0)
        net = nn.Sequential()
        net.add(nn.Dense(8, in_units=6, activation="relu"),
                nn.Dense(3, in_units=8))
        net.initialize(mx.init.Xavier())
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1, "momentum": 0.9})
        if not aggregate:
            trainer.optimizer.aggregate_num = 0
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        x = nd.array(np.random.randn(12, 6).astype(np.float32))
        y = nd.array(np.random.randint(0, 3, 12).astype(np.float32))
        for _ in range(4):
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(batch_size=12)
        return [p.data().asnumpy() for p in net.collect_params().values()]

    for a, b in zip(train(True), train(False)):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# bucketed gradient exchange
# ---------------------------------------------------------------------------

def test_bucket_plan_layout():
    from mxnet_tpu.kvstore.bucketing import plan_buckets
    keys = [0, 1, 2, 3, 4, 5]
    shapes = [(8, 8), (16,), (100, 100), (8, 8), (4,), (2,)]
    dtypes = ["float32"] * 5 + ["int32"]
    stypes = ["default"] * 6
    buckets, solo = plan_buckets(keys, shapes, dtypes, [4] * 6, stypes,
                                 max_bytes=1024)
    # (100,100) fp32 = 40 KB > cap -> solo; the lone int32 key -> solo
    assert solo == [2, 5]
    assert len(buckets) == 1
    b = buckets[0]
    assert b.positions == [0, 1, 3, 4]
    assert b.total == 64 + 16 + 64 + 4
    assert b.offsets == [0, 64, 80, 144]
    # deterministic, content-addressed name
    again, _ = plan_buckets(keys, shapes, dtypes, [4] * 6, stypes, 1024)
    assert again[0].name == b.name
    # layout change changes the name (stale-server safety)
    changed, _ = plan_buckets(keys, [(9, 8)] + shapes[1:], dtypes, [4] * 6,
                              stypes, 1024)
    assert changed[0].name != b.name


def test_bucket_plan_excludes_sparse_and_respects_cap():
    from mxnet_tpu.kvstore.bucketing import plan_buckets
    keys = list(range(4))
    shapes = [(8,), (8,), (8,), (8,)]
    buckets, solo = plan_buckets(keys, shapes, ["float32"] * 4, [4] * 4,
                                 ["default", "row_sparse", "default",
                                  "default"], max_bytes=1024)
    assert 1 in solo                     # sparse never bucketed
    assert buckets[0].positions == [0, 2, 3]
    # cap forces multiple buckets: 2 x 32B per 64B bucket
    buckets, solo = plan_buckets(keys, shapes, ["float32"] * 4, [4] * 4,
                                 ["default"] * 4, max_bytes=64)
    assert len(buckets) == 2
    assert [b.positions for b in buckets] == [[0, 1], [2, 3]]
    # 0 disables
    buckets, solo = plan_buckets(keys, shapes, ["float32"] * 4, [4] * 4,
                                 ["default"] * 4, max_bytes=0)
    assert not buckets and solo == [0, 1, 2, 3]


def test_bucket_kb_zero_disables_bucketing_at_store(monkeypatch):
    """ISSUE 5 satellite: MX_KVSTORE_BUCKET_KB=0 cleanly disables
    bucketing (everything takes the per-key path — no degenerate 0-byte
    buckets), the exchange stays correct, and flipping the knob
    mid-process re-plans instead of serving a stale cached layout."""
    from mxnet_tpu import kvstore
    kv = kvstore.create("ici")
    keys = [0, 1, 2]
    arrays = [nd.array(np.arange(4, dtype=np.float32) + k) for k in keys]
    kv.init(keys, [nd.zeros((4,)) for _ in keys])

    monkeypatch.setenv("MX_KVSTORE_BUCKET_KB", "0")
    buckets, solo = kv._bucket_plans(keys, arrays)
    assert buckets == [] and list(solo) == keys
    kv.push(keys, [[a] for a in arrays])
    outs = [nd.zeros((4,)) for _ in keys]
    kv.pull(keys, outs)
    for k, o in zip(keys, outs):
        np.testing.assert_allclose(o.asnumpy(),
                                   np.arange(4, dtype=np.float32) + k)

    # same store, knob back on: the plan cache keys on the capacity, so
    # the bucketed layout comes back without a new store
    monkeypatch.setenv("MX_KVSTORE_BUCKET_KB", "4096")
    buckets, solo = kv._bucket_plans(keys, arrays)
    assert len(buckets) == 1 and buckets[0].positions == keys
    assert list(solo) == []
    kv.push(keys, [[a] for a in arrays])
    kv.pull(keys, outs)
    for k, o in zip(keys, outs):
        np.testing.assert_allclose(o.asnumpy(),
                                   np.arange(4, dtype=np.float32) + k)


def test_bucket_kb_zero_trainer_step(monkeypatch):
    """A 2-device Trainer step with bucketing disabled still trains
    (per-key exchange path) and matches the bucketed result."""
    def run():
        mx.random.seed(0)
        ctxs = [mx.cpu(0), mx.cpu(1)]
        net = nn.Dense(2, in_units=4)
        net.initialize(mx.init.Xavier(), ctx=ctxs)
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1}, kvstore="device")
        rng = np.random.RandomState(0)
        X = rng.randn(8, 4).astype(np.float32)
        Y = rng.randn(8, 2).astype(np.float32)
        loss_fn = gluon.loss.L2Loss()
        for _ in range(2):
            with autograd.record():
                for ctx, sl in zip(ctxs, (slice(0, 4), slice(4, None))):
                    loss_fn(net(nd.array(X[sl], ctx=ctx)),
                            nd.array(Y[sl], ctx=ctx)).backward()
            tr.step(batch_size=8)
        return {k: v.data(ctxs[0]).asnumpy()
                for k, v in net.collect_params().items()}

    monkeypatch.setenv("MX_KVSTORE_BUCKET_KB", "0")
    unbucketed = run()
    monkeypatch.setenv("MX_KVSTORE_BUCKET_KB", "4096")
    bucketed = run()
    assert set(unbucketed) == set(bucketed)
    for k in unbucketed:
        np.testing.assert_allclose(unbucketed[k], bucketed[k],
                                   rtol=1e-6, atol=1e-6)


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _start_server(port):
    from mxnet_tpu.kvstore.server import serve_forever
    t = threading.Thread(target=serve_forever,
                         kwargs=dict(port=port, num_workers=1), daemon=True)
    t.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port), timeout=0.2).close()
            return t
        except OSError:
            time.sleep(0.05)
    raise RuntimeError("server did not come up on %d" % port)


@pytest.fixture
def _dist_async_client(monkeypatch):
    from mxnet_tpu.kvstore.kvstore import KVStoreDistAsync
    monkeypatch.setenv("MX_KVSTORE_HEARTBEAT", "0")
    monkeypatch.setenv("MX_KVSTORE_BUCKET_KB", "1")   # force small buckets
    monkeypatch.delenv("MX_PS_ROOTS", raising=False)
    port = _free_port()
    _start_server(port)
    monkeypatch.setenv("MX_PS_ROOT", "127.0.0.1:%d" % port)
    kv = KVStoreDistAsync()
    yield kv
    kv.stop_server()


def test_dist_async_bucketed_roundtrip(_dist_async_client):
    kv = _dist_async_client
    keys = list(range(5))
    shapes = [(8, 8), (16,), (8, 8), (64, 64), (4,)]   # 16 KB one stays solo
    for k, s in zip(keys, shapes):
        kv.init(k, nd.zeros(s))
    grads = [nd.array(np.random.RandomState(k).randn(*s).astype(np.float32))
             for k, s in zip(keys, shapes)]
    kv.push(keys, grads)
    assert kv._bucket_inited                   # fusion buckets went out
    outs = [nd.zeros(s) for s in shapes]
    kv.pull(keys, outs)
    for g, o in zip(grads, outs):
        np.testing.assert_allclose(o.asnumpy(), g.asnumpy(), rtol=1e-6)
    # the server accumulates bucket payloads exactly like per-key pushes
    kv.push(keys, grads)
    kv.pull(keys, outs)
    for g, o in zip(grads, outs):
        np.testing.assert_allclose(o.asnumpy(), 2 * g.asnumpy(), rtol=1e-6)


def test_dist_async_bucket_pull_from_other_worker(_dist_async_client,
                                                  monkeypatch):
    """A worker that never pushed derives the same deterministic layout
    and reads the bucket another client wrote — no silent per-key
    staleness (code-review regression)."""
    from mxnet_tpu.kvstore.kvstore import KVStoreDistAsync
    kv = _dist_async_client
    keys = [0, 1, 2]
    shapes = [(8, 8), (16,), (8, 8)]
    for k, s in zip(keys, shapes):
        kv.init(k, nd.zeros(s))
    grads = [nd.array(np.random.RandomState(k).randn(*s).astype(np.float32))
             for k, s in zip(keys, shapes)]
    kv.push(keys, grads)
    other = KVStoreDistAsync()          # fresh client, empty _bucket_inited
    try:
        for k, s in zip(keys, shapes):
            other.init(k, nd.zeros(s))  # mirrors only; bucket already live
        outs = [nd.zeros(s) for s in shapes]
        other.pull(keys, outs)
        for g, o in zip(grads, outs):
            np.testing.assert_allclose(o.asnumpy(), g.asnumpy(), rtol=1e-6)
    finally:
        other.close()


def test_dist_async_bucket_pull_falls_back_before_any_push(
        _dist_async_client):
    """Batched pull BEFORE any bucket push: the bucket is absent server-
    side, so the pull must fall back to per-key reads (broadcast-weights
    pattern), not fail and not return garbage."""
    kv = _dist_async_client
    keys = [0, 1]
    vals = [nd.array(np.full((4,), 7.0, np.float32)),
            nd.array(np.full((6,), 9.0, np.float32))]
    for k, v in zip(keys, vals):
        kv.init(k, v)
    outs = [nd.zeros((4,)), nd.zeros((6,))]
    kv.pull(keys, outs)
    np.testing.assert_allclose(outs[0].asnumpy(), 7.0)
    np.testing.assert_allclose(outs[1].asnumpy(), 9.0)


def test_dist_async_bucketing_off_with_server_optimizer(_dist_async_client):
    """With a server-side optimizer the server must see each key
    individually: buckets stay off and per-key semantics hold."""
    kv = _dist_async_client
    kv.init("w", nd.ones((4,)))
    kv.init("v", nd.ones((3,)))
    kv.set_optimizer(opt.SGD(learning_rate=0.5))
    kv.push(["w", "v"], [nd.ones((4,)), nd.ones((3,))])
    assert not kv._bucket_inited
    out_w, out_v = nd.zeros((4,)), nd.zeros((3,))
    kv.pull(["w", "v"], [out_w, out_v])
    np.testing.assert_allclose(out_w.asnumpy(), 0.5)
    np.testing.assert_allclose(out_v.asnumpy(), 0.5)


def test_ici_store_batched_push_pull_single_process():
    """The Trainer's batched push/pull path through the collective store:
    local device-copy reduce still works keyed per param."""
    from mxnet_tpu import kvstore
    kv = kvstore.create("ici")
    keys = [0, 1]
    kv.init(keys, [nd.zeros((3,)), nd.zeros((2, 2))])
    g0 = [nd.array(np.full(3, r + 1.0, np.float32), ctx=mx.cpu(r))
          for r in range(2)]
    g1 = [nd.array(np.full((2, 2), 10.0 * (r + 1), np.float32),
                   ctx=mx.cpu(r)) for r in range(2)]
    kv.push(keys, [g0, g1])
    o0, o1 = nd.zeros((3,)), nd.zeros((2, 2))
    kv.pull(keys, [o0, o1])
    np.testing.assert_allclose(o0.asnumpy(), 3.0)    # 1 + 2
    np.testing.assert_allclose(o1.asnumpy(), 30.0)   # 10 + 20


# ---------------------------------------------------------------------------
# device-side metric accumulation
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("cls,kwargs", [
    ("Accuracy", {}),
    ("MSE", {}),
    ("MAE", {}),
    ("RMSE", {}),
    ("CrossEntropy", {}),
    ("Perplexity", {"ignore_label": 2}),
])
def test_device_metric_matches_host(cls, kwargs):
    from mxnet_tpu import metric as M
    rng = np.random.RandomState(0)
    lab = rng.randint(0, 5, (8,)).astype(np.float32)
    if cls in ("MSE", "MAE", "RMSE"):
        pred = rng.rand(8).astype(np.float32)
    else:
        pred = rng.rand(8, 5).astype(np.float32)
        pred /= pred.sum(axis=1, keepdims=True)
    dev = getattr(M, cls)(**kwargs)
    host = getattr(M, cls)(**kwargs)
    for _ in range(3):
        dev.update([nd.array(lab)], [nd.array(pred)])
        host.update([lab], [pred])
    # update() stayed device-side: accumulators live, no host sync yet
    assert dev._dev_sum is not None
    assert np.allclose(dev.get()[1], host.get()[1], rtol=1e-5, atol=1e-7)
    # drained after get()
    assert dev._dev_sum is None


def test_device_metric_single_dispatch_per_update():
    from mxnet_tpu import metric as M
    m = M.Accuracy()
    lab, pred = nd.array(np.zeros(8)), nd.array(np.random.rand(8, 4))
    m.update([lab], [pred])          # warm
    c0 = engine.dispatch_count
    m.update([lab], [pred])
    assert engine.dispatch_count - c0 == 1


def test_device_metric_mixed_paths_and_reset():
    from mxnet_tpu import metric as M
    rng = np.random.RandomState(3)
    lab = rng.randint(0, 4, (6,)).astype(np.float32)
    pred = rng.rand(6, 4).astype(np.float32)
    m = M.Accuracy()
    m.update([nd.array(lab)], [nd.array(pred)])   # device
    m.update([lab], [pred])                       # host numpy
    h = M.Accuracy()
    h.update([lab], [pred])
    h.update([lab], [pred])
    assert np.allclose(m.get()[1], h.get()[1])
    m.reset()
    assert m.num_inst == 0 and m._dev_sum is None
    name, val = m.get()
    assert np.isnan(val)


def test_loss_metric_device_path():
    from mxnet_tpu import metric as M
    x = np.random.RandomState(1).rand(4, 3).astype(np.float32)
    m, h = M.Loss(), M.Loss()
    m.update(None, [nd.array(x)])
    h.update(None, [x])
    assert m._dev_sum is not None
    assert np.allclose(m.get()[1], h.get()[1], rtol=1e-6)


def test_module_fit_epoch_metric_still_correct():
    """Module fit path end-to-end with the device-accumulated Accuracy."""
    import mxnet_tpu.symbol as sym
    from mxnet_tpu.io import NDArrayIter
    from mxnet_tpu.module import Module

    np.random.seed(0)
    x = np.random.randn(64, 10).astype(np.float32)
    w = np.random.randn(10, 3).astype(np.float32)
    y = np.argmax(x @ w, axis=1).astype(np.float32)
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=3, name="fc")
    out = sym.SoftmaxOutput(net, sym.Variable("softmax_label"),
                            name="softmax")
    mod = Module(out, context=mx.cpu())
    it = NDArrayIter(x, y, batch_size=16)
    mod.fit(it, num_epoch=6, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5})
    metric = mx.metric.Accuracy()
    score = mod.score(it, metric)
    assert dict(score)["accuracy"] > 0.8
