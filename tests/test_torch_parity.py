"""Independent-oracle parity: heavyweight kernels vs torch (CPU).

The operator battery checks ops against hand-written numpy references;
torch is a fully independent implementation of the same math (reference
pattern: tests/python/unittest/test_operator.py uses scipy/your-own-loop
oracles for conv/rnn).  Forward AND backward are compared — both
frameworks get the same cotangent.
"""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import mxnet_tpu as mx               # noqa: E402
from mxnet_tpu import autograd, gluon, nd  # noqa: E402
from mxnet_tpu.ndarray import invoke  # noqa: E402

RTOL, ATOL = 1e-4, 1e-4


def _t(x, grad=False):
    t = torch.tensor(x)
    if grad:
        t.requires_grad_(True)
    return t


def _close(ours, theirs, rtol=RTOL, atol=ATOL, what=""):
    a = ours.asnumpy() if hasattr(ours, "asnumpy") else np.asarray(ours)
    b = theirs.detach().numpy() if hasattr(theirs, "detach") \
        else np.asarray(theirs)
    np.testing.assert_allclose(a, b, rtol=rtol, atol=atol, err_msg=what)


@pytest.mark.parametrize("cin,cout,k,s,p,d,g", [
    (3, 8, 3, 1, 1, 1, 1),
    (4, 8, 3, 2, 1, 2, 1),
    (4, 4, 3, 1, 0, 1, 2),
    (2, 6, 5, 2, 2, 1, 2),
])
def test_convolution_vs_torch(cin, cout, k, s, p, d, g):
    rng = np.random.RandomState(0)
    x = rng.randn(2, cin, 12, 12).astype(np.float32)
    w = rng.randn(cout, cin // g, k, k).astype(np.float32)
    b = rng.randn(cout).astype(np.float32)

    tx, tw, tb = _t(x, True), _t(w, True), _t(b, True)
    to = torch.nn.functional.conv2d(tx, tw, tb, stride=s, padding=p,
                                    dilation=d, groups=g)
    go = rng.randn(*to.shape).astype(np.float32)
    to.backward(_t(go))

    xx, ww, bb = nd.array(x), nd.array(w), nd.array(b)
    for v in (xx, ww, bb):
        v.attach_grad()
    with autograd.record():
        o = invoke("Convolution", xx, ww, bb, kernel=(k, k),
                   num_filter=cout, stride=(s, s), pad=(p, p),
                   dilate=(d, d), num_group=g)
    o.backward(nd.array(go))

    _close(o, to, what="conv fwd")
    _close(xx.grad, tx.grad, what="conv dx")
    _close(ww.grad, tw.grad, what="conv dw")
    _close(bb.grad, tb.grad, what="conv db")


@pytest.mark.parametrize("cin,cout,k,s,p,adj,g", [
    (4, 6, 3, 2, 1, 1, 1),
    (4, 4, 4, 2, 1, 0, 2),
    (3, 5, 3, 1, 0, 0, 1),
])
def test_deconvolution_vs_torch(cin, cout, k, s, p, adj, g):
    rng = np.random.RandomState(1)
    x = rng.randn(2, cin, 7, 7).astype(np.float32)
    w = rng.randn(cin, cout // g, k, k).astype(np.float32)

    tx, tw = _t(x, True), _t(w, True)
    to = torch.nn.functional.conv_transpose2d(
        tx, tw, stride=s, padding=p, output_padding=adj, groups=g)
    go = rng.randn(*to.shape).astype(np.float32)
    to.backward(_t(go))

    xx, ww = nd.array(x), nd.array(w)
    xx.attach_grad()
    ww.attach_grad()
    with autograd.record():
        o = invoke("Deconvolution", xx, ww, None, kernel=(k, k),
                   num_filter=cout, stride=(s, s), pad=(p, p),
                   adj=(adj, adj), num_group=g, no_bias=True)
    o.backward(nd.array(go))

    _close(o, to, what="deconv fwd")
    _close(xx.grad, tx.grad, what="deconv dx")
    _close(ww.grad, tw.grad, what="deconv dw")


def test_pooling_vs_torch():
    rng = np.random.RandomState(2)
    x = rng.randn(2, 3, 10, 10).astype(np.float32)
    # max pool with stride+pad
    tx = _t(x, True)
    to = torch.nn.functional.max_pool2d(tx, 3, stride=2, padding=1)
    go = rng.randn(*to.shape).astype(np.float32)
    to.backward(_t(go))
    xx = nd.array(x)
    xx.attach_grad()
    with autograd.record():
        o = invoke("Pooling", xx, kernel=(3, 3), pool_type="max",
                   stride=(2, 2), pad=(1, 1))
    o.backward(nd.array(go))
    _close(o, to, what="maxpool fwd")
    _close(xx.grad, tx.grad, what="maxpool dx")

    # avg pool, no padding (sidesteps count_include_pad conventions)
    to2 = torch.nn.functional.avg_pool2d(torch.tensor(x), 2, stride=2)
    o2 = invoke("Pooling", nd.array(x), kernel=(2, 2), pool_type="avg",
                stride=(2, 2))
    _close(o2, to2, what="avgpool fwd")


def test_batchnorm_train_vs_torch():
    rng = np.random.RandomState(3)
    C = 5
    x = rng.randn(4, C, 6, 6).astype(np.float32)
    gamma = rng.rand(C).astype(np.float32) + 0.5
    beta = rng.randn(C).astype(np.float32)
    rm = rng.randn(C).astype(np.float32)
    rv = rng.rand(C).astype(np.float32) + 0.5
    mom = 0.9   # MXNet: moving = mom*moving + (1-mom)*batch

    trm, trv = _t(rm.copy()), _t(rv.copy())
    tx = _t(x, True)
    tg, tb = _t(gamma, True), _t(beta, True)
    to = torch.nn.functional.batch_norm(
        tx, trm, trv, tg, tb, training=True, momentum=1.0 - mom, eps=1e-5)
    go = rng.randn(*to.shape).astype(np.float32)
    to.backward(_t(go))

    xx = nd.array(x)
    gg, bb = nd.array(gamma), nd.array(beta)
    mmean, mvar = nd.array(rm.copy()), nd.array(rv.copy())
    xx.attach_grad()
    gg.attach_grad()
    bb.attach_grad()
    with autograd.record():
        o = invoke("BatchNorm", xx, gg, bb, mmean, mvar, eps=1e-5,
                   momentum=mom, fix_gamma=False, training=True)
    o.backward(nd.array(go))

    _close(o, to, what="bn fwd")
    _close(xx.grad, tx.grad, rtol=1e-3, atol=1e-4, what="bn dx")
    _close(gg.grad, tg.grad, rtol=1e-3, atol=1e-4, what="bn dgamma")
    _close(bb.grad, tb.grad, what="bn dbeta")
    # running-stat update (torch uses unbiased var for the running stat;
    # MXNet uses biased — rescale before comparing)
    n = x.size // C
    _close(mmean, trm, what="bn running mean")
    rv_ours = mvar.asnumpy()
    rv_theirs = trv.numpy()
    batch_biased = x.transpose(1, 0, 2, 3).reshape(C, -1).var(axis=1)
    expect_ours = mom * rv + (1 - mom) * batch_biased
    np.testing.assert_allclose(rv_ours, expect_ours, rtol=1e-4,
                               err_msg="bn running var (mxnet semantics)")
    expect_theirs = mom * rv + (1 - mom) * batch_biased * n / (n - 1)
    np.testing.assert_allclose(rv_theirs, expect_theirs, rtol=1e-4,
                               err_msg="torch unbiased-var sanity")


def test_layernorm_vs_torch():
    rng = np.random.RandomState(4)
    x = rng.randn(3, 7, 16).astype(np.float32)
    gamma = rng.rand(16).astype(np.float32) + 0.5
    beta = rng.randn(16).astype(np.float32)
    tx, tg, tb = _t(x, True), _t(gamma, True), _t(beta, True)
    to = torch.nn.functional.layer_norm(tx, (16,), tg, tb, eps=1e-5)
    go = rng.randn(*to.shape).astype(np.float32)
    to.backward(_t(go))

    xx, gg, bb = nd.array(x), nd.array(gamma), nd.array(beta)
    for v in (xx, gg, bb):
        v.attach_grad()
    with autograd.record():
        o = invoke("LayerNorm", xx, gg, bb, axis=-1, eps=1e-5)
    o.backward(nd.array(go))
    _close(o, to, what="ln fwd")
    _close(xx.grad, tx.grad, rtol=1e-3, atol=1e-4, what="ln dx")
    _close(gg.grad, tg.grad, rtol=1e-3, atol=1e-4, what="ln dgamma")
    _close(bb.grad, tb.grad, what="ln dbeta")


def _copy_rnn_params(gluon_net, torch_net, num_layers, bidirectional):
    """gluon l{k}_/r{k}_ params <- torch weight_*_l{k}[_reverse] (same
    (G*H, in) layouts and gate orders for LSTM i,f,g,o / GRU r,z,n)."""
    params = gluon_net.collect_params()
    for layer in range(num_layers):
        for direction, prefix in ((0, "l"), (1, "r")):
            if direction == 1 and not bidirectional:
                continue
            sfx = "_reverse" if direction else ""
            pairs = [
                ("%s%d_i2h_weight" % (prefix, layer),
                 "weight_ih_l%d%s" % (layer, sfx)),
                ("%s%d_h2h_weight" % (prefix, layer),
                 "weight_hh_l%d%s" % (layer, sfx)),
                ("%s%d_i2h_bias" % (prefix, layer),
                 "bias_ih_l%d%s" % (layer, sfx)),
                ("%s%d_h2h_bias" % (prefix, layer),
                 "bias_hh_l%d%s" % (layer, sfx)),
            ]
            for gname, tname in pairs:
                t = getattr(torch_net, tname).detach().numpy()
                params[gname].set_data(nd.array(t))


@pytest.mark.parametrize("mode,bidirectional,layers", [
    ("lstm", False, 1), ("lstm", True, 2), ("gru", False, 2),
    ("gru", True, 1),
])
def test_rnn_vs_torch(mode, bidirectional, layers):
    T, N, I, H = 7, 3, 5, 6
    rng = np.random.RandomState(5)
    x = rng.randn(T, N, I).astype(np.float32)

    tnet = (torch.nn.LSTM if mode == "lstm" else torch.nn.GRU)(
        I, H, num_layers=layers, bidirectional=bidirectional)
    gnet = (gluon.rnn.LSTM if mode == "lstm" else gluon.rnn.GRU)(
        H, num_layers=layers, bidirectional=bidirectional)
    gnet.initialize()
    gnet(nd.zeros((T, N, I)))     # shape inference
    _copy_rnn_params(gnet, tnet, layers, bidirectional)

    tx = _t(x, True)
    to, _ = tnet(tx)
    go = rng.randn(*to.shape).astype(np.float32)
    to.backward(_t(go))

    xx = nd.array(x)
    xx.attach_grad()
    with autograd.record():
        o = gnet(xx)
    o.backward(nd.array(go))

    _close(o, to, rtol=1e-3, atol=1e-4, what="rnn fwd")
    _close(xx.grad, tx.grad, rtol=1e-3, atol=1e-4, what="rnn dx")


def test_embedding_grad_vs_torch():
    rng = np.random.RandomState(6)
    V, D = 11, 4
    w = rng.randn(V, D).astype(np.float32)
    idx = rng.randint(0, V, (3, 5)).astype(np.int32)

    tw = _t(w, True)
    to = torch.nn.functional.embedding(torch.tensor(idx).long(), tw)
    go = rng.randn(*to.shape).astype(np.float32)
    to.backward(_t(go))

    ww = nd.array(w)
    ww.attach_grad()
    with autograd.record():
        o = invoke("Embedding", nd.array(idx), ww, input_dim=V,
                   output_dim=D)
    o.backward(nd.array(go))
    _close(o, to, what="embedding fwd")
    _close(ww.grad, tw.grad, what="embedding dweight")


def test_ctc_loss_vs_torch():
    rng = np.random.RandomState(7)
    T, N, C, L = 12, 3, 6, 4
    pred = rng.randn(T, N, C).astype(np.float32)
    # labels in 1..C-1 (blank=0), variable lengths, 0-padded
    lab_lens = np.array([4, 2, 3], np.int32)
    label = np.zeros((N, L), np.int32)
    for i, ln in enumerate(lab_lens):
        label[i, :ln] = rng.randint(1, C, ln)
    in_lens = np.array([12, 10, 11], np.int32)

    tp = _t(pred, True)
    tlogp = torch.nn.functional.log_softmax(tp, dim=-1)
    targets = torch.tensor(
        np.concatenate([label[i, :lab_lens[i]] for i in range(N)]).astype(
            np.int64))
    tloss = torch.nn.functional.ctc_loss(
        tlogp, targets, torch.tensor(in_lens.astype(np.int64)),
        torch.tensor(lab_lens.astype(np.int64)), blank=0,
        reduction="none", zero_infinity=False)
    tloss.sum().backward()

    xx = nd.array(pred)
    xx.attach_grad()
    with autograd.record():
        o = invoke("CTCLoss", xx, nd.array(label),
                   nd.array(in_lens), nd.array(lab_lens))
    o.backward(nd.array(np.ones(N, np.float32)))
    _close(o, tloss, rtol=1e-3, atol=1e-4, what="ctc loss")
    _close(xx.grad, tp.grad, rtol=1e-3, atol=1e-4, what="ctc dpred")


def test_softmax_axis_vs_torch():
    rng = np.random.RandomState(8)
    x = rng.randn(2, 5, 3, 4).astype(np.float32)
    for ax in (1, -1):
        tx = _t(x, True)
        to = torch.nn.functional.softmax(tx, dim=ax)
        go = rng.randn(*to.shape).astype(np.float32)
        to.backward(_t(go))
        xx = nd.array(x)
        xx.attach_grad()
        with autograd.record():
            o = invoke("softmax", xx, axis=ax)
        o.backward(nd.array(go))
        _close(o, to, what="softmax fwd ax=%d" % ax)
        _close(xx.grad, tx.grad, rtol=1e-3, atol=1e-5,
               what="softmax dx ax=%d" % ax)


def test_group_instance_norm_vs_torch():
    rng = np.random.RandomState(9)
    x = rng.randn(2, 6, 5, 5).astype(np.float32)
    g = rng.rand(6).astype(np.float32) + 0.5
    b = rng.randn(6).astype(np.float32)

    tx, tg, tb = _t(x, True), _t(g, True), _t(b, True)
    to = torch.nn.functional.group_norm(tx, 3, tg, tb, eps=1e-5)
    go = rng.randn(*to.shape).astype(np.float32)
    to.backward(_t(go))
    xx, gg, bb = nd.array(x), nd.array(g), nd.array(b)
    for v in (xx, gg, bb):
        v.attach_grad()
    with autograd.record():
        o = invoke("GroupNorm", xx, gg, bb, num_groups=3, eps=1e-5)
    o.backward(nd.array(go))
    _close(o, to, what="groupnorm fwd")
    _close(xx.grad, tx.grad, rtol=1e-3, atol=1e-4, what="groupnorm dx")
    _close(gg.grad, tg.grad, rtol=1e-3, atol=1e-4, what="gn dgamma")
    _close(bb.grad, tb.grad, what="gn dbeta")

    to2 = torch.nn.functional.instance_norm(
        torch.tensor(x), weight=torch.tensor(g), bias=torch.tensor(b),
        eps=1e-3)
    o2 = invoke("InstanceNorm", nd.array(x), nd.array(g), nd.array(b),
                eps=1e-3)
    _close(o2, to2, rtol=1e-3, atol=1e-5, what="instancenorm fwd")


@pytest.mark.parametrize("act", ["relu", "tanh"])
def test_vanilla_rnn_vs_torch(act):
    T, N, I, H = 6, 2, 4, 5
    rng = np.random.RandomState(10)
    x = rng.randn(T, N, I).astype(np.float32)
    tnet = torch.nn.RNN(I, H, nonlinearity=act)
    gnet = gluon.rnn.RNN(H, activation=act)
    gnet.initialize()
    gnet(nd.zeros((T, N, I)))
    _copy_rnn_params(gnet, tnet, 1, False)
    to, _ = tnet(_t(x))
    o = gnet(nd.array(x))
    _close(o, to, rtol=1e-4, atol=1e-5, what="vanilla rnn fwd")


def test_attention_vs_torch_sdpa():
    from mxnet_tpu.ops.attention import attention_core, attention_impl_scope
    import jax
    rng = np.random.RandomState(11)
    B, H, S, D = 2, 4, 256, 128     # aligned so pallas path engages
    q = rng.randn(B, H, S, D).astype(np.float32)
    k = rng.randn(B, H, S, D).astype(np.float32)
    v = rng.randn(B, H, S, D).astype(np.float32)
    for causal in (False, True):
        tq, tk, tv = _t(q, True), _t(k, True), _t(v, True)
        to = torch.nn.functional.scaled_dot_product_attention(
            tq, tk, tv, is_causal=causal)
        go = rng.randn(*to.shape).astype(np.float32)
        to.backward(_t(go))
        for impl in ("pallas", "xla"):
            with attention_impl_scope(impl):
                o, vjp = jax.vjp(
                    lambda q_, k_, v_: attention_core(q_, k_, v_,
                                                      causal=causal),
                    q, k, v)
                dq, dk, dv = vjp(go)
            _close(o, to, rtol=2e-3, atol=2e-3,
                   what="sdpa fwd %s causal=%s" % (impl, causal))
            for ours, theirs, nm in ((dq, tq.grad, "dq"),
                                     (dk, tk.grad, "dk"),
                                     (dv, tv.grad, "dv")):
                _close(ours, theirs, rtol=2e-3, atol=2e-3,
                       what="sdpa %s %s causal=%s" % (nm, impl, causal))


def test_bilinear_sampler_vs_torch_grid_sample():
    rng = np.random.RandomState(12)
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    # strictly in-range grid: convention parity on the interpolation math
    grid = (rng.rand(2, 2, 5, 5).astype(np.float32) * 1.8 - 0.9)
    tg = torch.tensor(np.moveaxis(grid, 1, -1))     # (N, Ho, Wo, 2)
    to = torch.nn.functional.grid_sample(
        torch.tensor(x), tg, mode="bilinear", align_corners=True)
    o = invoke("BilinearSampler", nd.array(x), nd.array(grid))
    _close(o, to, rtol=1e-4, atol=1e-5, what="bilinear sampler")

    # out-of-range grid: zero padding outside the image (reference
    # bilinear_sampler.cc semantics)
    grid2 = (rng.rand(2, 2, 5, 5).astype(np.float32) * 3.0 - 1.5)
    tg2 = torch.tensor(np.moveaxis(grid2, 1, -1))
    to2 = torch.nn.functional.grid_sample(
        torch.tensor(x), tg2, mode="bilinear", padding_mode="zeros",
        align_corners=True)
    o2 = invoke("BilinearSampler", nd.array(x), nd.array(grid2))
    _close(o2, to2, rtol=1e-4, atol=1e-5, what="bilinear sampler OOB")


def test_trainer_sgd_adam_vs_torch_optim():
    """3 full steps of Dense + Trainer vs torch Linear + optim — wires
    gluon Trainer, optimizer update ops, and autograd into one oracle."""
    rng = np.random.RandomState(13)
    w0 = rng.randn(3, 5).astype(np.float32)
    b0 = rng.randn(3).astype(np.float32)
    xs = rng.randn(4, 5).astype(np.float32)
    ys = rng.randn(4, 3).astype(np.float32)

    for opt_name, opt_kw, topt_cls, topt_kw in [
        ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 0.01},
         torch.optim.SGD, {"lr": 0.1, "momentum": 0.9,
                           "weight_decay": 0.01}),
        ("adam", {"learning_rate": 0.05},
         torch.optim.Adam, {"lr": 0.05}),
        ("adamw", {"learning_rate": 0.05, "wd": 0.02},
         torch.optim.AdamW, {"lr": 0.05, "weight_decay": 0.02}),
        ("nag", {"learning_rate": 0.1, "momentum": 0.9},
         torch.optim.SGD, {"lr": 0.1, "momentum": 0.9,
                           "nesterov": True}),
        ("adamax", {"learning_rate": 0.05},
         torch.optim.Adamax, {"lr": 0.05}),
    ]:
        net = gluon.nn.Dense(3, in_units=5)
        net.initialize()
        net.weight.set_data(nd.array(w0))
        net.bias.set_data(nd.array(b0))
        trainer = gluon.Trainer(net.collect_params(), opt_name, opt_kw)

        tnet = torch.nn.Linear(5, 3)
        with torch.no_grad():
            tnet.weight.copy_(torch.tensor(w0))
            tnet.bias.copy_(torch.tensor(b0))
        topt = topt_cls(tnet.parameters(), **topt_kw)

        for _ in range(3):
            with autograd.record():
                loss = ((net(nd.array(xs)) - nd.array(ys)) ** 2).mean()
            loss.backward()
            trainer.step(1, ignore_stale_grad=True)

            topt.zero_grad()
            tl = ((tnet(torch.tensor(xs)) - torch.tensor(ys)) ** 2).mean()
            tl.backward()
            topt.step()

        tol = 2e-3 if opt_name == "adamw" else 1e-4
        _close(net.weight.data(), tnet.weight, rtol=tol, atol=tol / 10,
               what="%s weight after 3 steps" % opt_name)
        _close(net.bias.data(), tnet.bias, rtol=tol, atol=tol / 10,
               what="%s bias after 3 steps" % opt_name)


def test_pooling_conventions_vs_torch():
    """MXNet pooling_convention='full' == torch ceil_mode=True;
    count_include_pad both ways on padded avg pool."""
    rng = np.random.RandomState(14)
    # 10x10: (10-3) % 2 != 0, so ceil gives 5 outputs vs floor's 4 —
    # the 'full' padding path actually engages
    x = rng.randn(2, 3, 10, 10).astype(np.float32)

    to = torch.nn.functional.max_pool2d(torch.tensor(x), 3, stride=2,
                                        ceil_mode=True)
    o = invoke("Pooling", nd.array(x), kernel=(3, 3), pool_type="max",
               stride=(2, 2), pooling_convention="full")
    _close(o, to, what="maxpool full/ceil")

    for cip in (True, False):
        to2 = torch.nn.functional.avg_pool2d(
            torch.tensor(x), 3, stride=2, padding=1,
            count_include_pad=cip)
        o2 = invoke("Pooling", nd.array(x), kernel=(3, 3),
                    pool_type="avg", stride=(2, 2), pad=(1, 1),
                    count_include_pad=cip)
        _close(o2, to2, what="avgpool count_include_pad=%s" % cip)


def test_lrn_vs_torch():
    rng = np.random.RandomState(15)
    x = rng.randn(2, 8, 6, 6).astype(np.float32)
    alpha, beta, k, n = 1e-3, 0.75, 2.0, 5
    to = torch.nn.functional.local_response_norm(
        torch.tensor(x), n, alpha=alpha, beta=beta, k=k)
    o = invoke("LRN", nd.array(x), alpha=alpha, beta=beta, knorm=k,
               nsize=n)
    _close(o, to, rtol=1e-4, atol=1e-5, what="lrn fwd")


def test_spatial_transformer_vs_torch():
    """GridGenerator(affine) + BilinearSampler == affine_grid +
    grid_sample(align_corners=True) (reference: spatial_transformer.cc)."""
    rng = np.random.RandomState(16)
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    # mild affine transforms around identity
    theta = (np.tile(np.array([1, 0, 0, 0, 1, 0], np.float32), (2, 1))
             + rng.uniform(-0.2, 0.2, (2, 6)).astype(np.float32))

    tg = torch.nn.functional.affine_grid(
        torch.tensor(theta.reshape(2, 2, 3)), (2, 3, 6, 6),
        align_corners=True)
    to = torch.nn.functional.grid_sample(
        torch.tensor(x), tg, mode="bilinear", padding_mode="zeros",
        align_corners=True)

    o = invoke("SpatialTransformer", nd.array(x), nd.array(theta),
               target_shape=(6, 6), transform_type="affine",
               sampler_type="bilinear")
    _close(o, to, rtol=1e-4, atol=1e-5, what="spatial transformer")


def test_conv1d_conv3d_vs_torch():
    """The 1-D and 3-D Convolution layouts (NCW/NCDHW) — only the 2-D
    path gets regular exercise elsewhere."""
    rng = np.random.RandomState(17)
    # 1-D
    x1 = rng.randn(2, 3, 12).astype(np.float32)
    w1 = rng.randn(5, 3, 3).astype(np.float32)
    tx, tw = _t(x1, True), _t(w1, True)
    to = torch.nn.functional.conv1d(tx, tw, stride=2, padding=1)
    go = rng.randn(*to.shape).astype(np.float32)
    to.backward(_t(go))
    xx, ww = nd.array(x1), nd.array(w1)
    xx.attach_grad()
    ww.attach_grad()
    with autograd.record():
        o = invoke("Convolution", xx, ww, None, kernel=(3,),
                   num_filter=5, stride=(2,), pad=(1,), no_bias=True)
    o.backward(nd.array(go))
    _close(o, to, what="conv1d fwd")
    _close(xx.grad, tx.grad, what="conv1d dx")
    _close(ww.grad, tw.grad, what="conv1d dw")

    # 3-D
    x3 = rng.randn(1, 2, 6, 6, 6).astype(np.float32)
    w3 = rng.randn(4, 2, 3, 3, 3).astype(np.float32)
    tx3, tw3 = _t(x3, True), _t(w3, True)
    to3 = torch.nn.functional.conv3d(tx3, tw3, stride=1, padding=1)
    go3 = rng.randn(*to3.shape).astype(np.float32)
    to3.backward(_t(go3))
    xx3, ww3 = nd.array(x3), nd.array(w3)
    xx3.attach_grad()
    ww3.attach_grad()
    with autograd.record():
        o3 = invoke("Convolution", xx3, ww3, None, kernel=(3, 3, 3),
                    num_filter=4, stride=(1, 1, 1), pad=(1, 1, 1),
                    no_bias=True)
    o3.backward(nd.array(go3))
    _close(o3, to3, rtol=2e-4, atol=2e-4, what="conv3d fwd")
    _close(xx3.grad, tx3.grad, rtol=2e-4, atol=2e-4, what="conv3d dx")
    _close(ww3.grad, tw3.grad, rtol=2e-4, atol=2e-4, what="conv3d dw")


def test_pool1d_pool3d_vs_torch():
    rng = np.random.RandomState(18)
    x1 = rng.randn(2, 3, 11).astype(np.float32)
    tx = _t(x1, True)
    to = torch.nn.functional.max_pool1d(tx, 3, stride=2)
    go = rng.randn(*to.shape).astype(np.float32)
    to.backward(_t(go))
    xx = nd.array(x1)
    xx.attach_grad()
    with autograd.record():
        o = invoke("Pooling", xx, kernel=(3,), pool_type="max",
                   stride=(2,))
    o.backward(nd.array(go))
    _close(o, to, what="maxpool1d fwd")
    _close(xx.grad, tx.grad, what="maxpool1d dx")

    x3 = rng.randn(1, 2, 6, 6, 6).astype(np.float32)
    tx3 = _t(x3, True)
    to3 = torch.nn.functional.avg_pool3d(tx3, 2, stride=2)
    go3 = rng.randn(*to3.shape).astype(np.float32)
    to3.backward(_t(go3))
    xx3 = nd.array(x3)
    xx3.attach_grad()
    with autograd.record():
        o3 = invoke("Pooling", xx3, kernel=(2, 2, 2),
                    pool_type="avg", stride=(2, 2, 2))
    o3.backward(nd.array(go3))
    _close(o3, to3, what="avgpool3d fwd")
    _close(xx3.grad, tx3.grad, what="avgpool3d dx")


def test_deconv1d_vs_torch():
    rng = np.random.RandomState(19)
    x = rng.randn(2, 4, 9).astype(np.float32)
    w = rng.randn(4, 6, 3).astype(np.float32)
    tx, tw = _t(x, True), _t(w, True)
    to = torch.nn.functional.conv_transpose1d(tx, tw, stride=2, padding=1)
    go = rng.randn(*to.shape).astype(np.float32)
    to.backward(_t(go))
    xx, ww = nd.array(x), nd.array(w)
    xx.attach_grad()
    ww.attach_grad()
    with autograd.record():
        o = invoke("Deconvolution", xx, ww, None, kernel=(3,),
                   num_filter=6, stride=(2,), pad=(1,), no_bias=True)
    o.backward(nd.array(go))
    _close(o, to, what="deconv1d fwd")
    _close(xx.grad, tx.grad, what="deconv1d dx")
    _close(ww.grad, tw.grad, what="deconv1d dw")


def test_softmax_temperature_and_bn_global_stats():
    rng = np.random.RandomState(20)
    x = rng.randn(3, 7).astype(np.float32)
    T = 2.5
    to = torch.nn.functional.softmax(torch.tensor(x) / T, dim=-1)
    o = invoke("softmax", nd.array(x), axis=-1, temperature=T)
    _close(o, to, what="softmax temperature")

    # use_global_stats=True in TRAINING still normalizes by the moving
    # stats (the reference's frozen-BN fine-tuning mode)
    C = 4
    xb = rng.randn(2, C, 5, 5).astype(np.float32)
    g = rng.rand(C).astype(np.float32) + 0.5
    b = rng.randn(C).astype(np.float32)
    rm = rng.randn(C).astype(np.float32)
    rv = rng.rand(C).astype(np.float32) + 0.5
    to2 = torch.nn.functional.batch_norm(
        torch.tensor(xb), torch.tensor(rm), torch.tensor(rv),
        torch.tensor(g), torch.tensor(b), training=False, eps=1e-5)
    o2 = invoke("BatchNorm", nd.array(xb), nd.array(g), nd.array(b),
                nd.array(rm.copy()), nd.array(rv.copy()), eps=1e-5,
                fix_gamma=False, use_global_stats=True, training=True)
    _close(o2, to2, rtol=1e-4, atol=1e-5, what="bn use_global_stats")


def test_gluon_losses_vs_torch():
    """Gluon loss blocks vs torch.nn.functional equivalents (mean over
    the batch axis matches gluon's per-sample means)."""
    from mxnet_tpu import gluon
    rng = np.random.RandomState(21)
    p = rng.randn(4, 5).astype(np.float32)
    t = rng.randn(4, 5).astype(np.float32)

    l1 = gluon.loss.L1Loss()(nd.array(p), nd.array(t)).asnumpy()
    tl1 = torch.nn.functional.l1_loss(torch.tensor(p), torch.tensor(t),
                                      reduction="none").mean(1).numpy()
    np.testing.assert_allclose(l1, tl1, rtol=1e-5)

    l2 = gluon.loss.L2Loss()(nd.array(p), nd.array(t)).asnumpy()
    tl2 = torch.nn.functional.mse_loss(torch.tensor(p), torch.tensor(t),
                                       reduction="none").mean(1).numpy()
    np.testing.assert_allclose(l2, tl2 / 2.0, rtol=1e-5)  # gluon halves

    lab = rng.randint(0, 5, 4)
    ce = gluon.loss.SoftmaxCrossEntropyLoss()(
        nd.array(p), nd.array(lab.astype(np.float32))).asnumpy()
    tce = torch.nn.functional.cross_entropy(
        torch.tensor(p), torch.tensor(lab).long(),
        reduction="none").numpy()
    np.testing.assert_allclose(ce, tce, rtol=1e-5)

    logits = rng.randn(4, 5).astype(np.float32)
    bin_t = (rng.rand(4, 5) > 0.5).astype(np.float32)
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()(
        nd.array(logits), nd.array(bin_t)).asnumpy()
    tbce = torch.nn.functional.binary_cross_entropy_with_logits(
        torch.tensor(logits), torch.tensor(bin_t),
        reduction="none").mean(1).numpy()
    np.testing.assert_allclose(bce, tbce, rtol=1e-4)

    # Huber: gluon HuberLoss(rho) == torch huber_loss(delta=rho)/rho?
    # MXNet: 0.5*err^2/rho for |err|<=rho else |err|-0.5*rho; torch
    # huber: 0.5*err^2 for |err|<=d else d*(|err|-0.5*d) — gluon = torch/d
    rho = 1.3
    h = gluon.loss.HuberLoss(rho=rho)(nd.array(p), nd.array(t)).asnumpy()
    th = torch.nn.functional.huber_loss(
        torch.tensor(p), torch.tensor(t), delta=rho,
        reduction="none").mean(1).numpy()
    np.testing.assert_allclose(h, th / rho, rtol=1e-5)


def test_nadam_single_param_vs_torch():
    """Nadam vs torch.optim.NAdam on ONE parameter: the reference keeps
    m_schedule as an optimizer-global scalar advanced per update() call,
    so multi-parameter trajectories deliberately follow the reference
    (not torch); with a single parameter the two definitions coincide
    and must match numerically."""
    rng = np.random.RandomState(21)
    w0 = rng.randn(3, 5).astype(np.float32)
    xs = rng.randn(4, 5).astype(np.float32)
    ys = rng.randn(4, 3).astype(np.float32)
    net = gluon.nn.Dense(3, in_units=5, use_bias=False)
    net.initialize()
    net.weight.set_data(nd.array(w0))
    trainer = gluon.Trainer(net.collect_params(), "nadam",
                            {"learning_rate": 0.05})
    tw = torch.nn.Parameter(torch.tensor(w0.copy()))
    topt = torch.optim.NAdam([tw], lr=0.05)
    for _ in range(4):
        with autograd.record():
            loss = ((net(nd.array(xs)) - nd.array(ys)) ** 2).mean()
        loss.backward()
        trainer.step(1, ignore_stale_grad=True)
        topt.zero_grad()
        tl = ((torch.tensor(xs) @ tw.T - torch.tensor(ys)) ** 2).mean()
        tl.backward()
        topt.step()
    _close(net.weight.data(), tw, rtol=2e-4, atol=2e-5,
           what="nadam weight after 4 steps")
