"""The data-drop activation contract, proven end-to-end OFFLINE.

tests/test_real_data.py's gates have never run because no drop exists.
This meta-test synthesizes a learnable MNIST-shaped idx drop, a PTB-shaped
corpus and a VOC2007-shaped detection set, lays them out with
tools/prepare_data.py, and then RUNS the real-data gates against the
result in a subprocess — so the entire activation path (layout
validation -> gz idx readers -> corpus reader -> VOC XML parse ->
det-rec pack -> gates) is exercised every round, and a real drop only
changes the numbers, not the code path.
"""
import gzip
import os
import struct
import subprocess
import sys

import numpy as np
import pytest
from PIL import Image

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_idx_images(path, imgs):
    n, h, w = imgs.shape
    with gzip.open(path, "wb") as f:
        f.write(struct.pack(">IIII", 0x803, n, h, w))
        f.write(imgs.astype(np.uint8).tobytes())


def _write_idx_labels(path, labels):
    with gzip.open(path, "wb") as f:
        f.write(struct.pack(">II", 0x801, len(labels)))
        f.write(labels.astype(np.uint8).tobytes())


def _make_mnist(dirpath, n_train=4096, n_test=512):
    """Learnable MNIST stand-in: each digit a fixed 28x28 prototype plus
    noise, so the config-0 accuracy gate can actually reach its bar."""
    os.makedirs(dirpath, exist_ok=True)
    protos = (np.random.RandomState(42).rand(10, 28, 28) * 255)

    def make(n, seed):
        rng = np.random.RandomState(seed)
        y = rng.randint(0, 10, n)
        x = protos[y] * 0.6 + rng.rand(n, 28, 28) * 255 * 0.4
        return np.clip(x, 0, 255), y

    xtr, ytr = make(n_train, 0)
    xte, yte = make(n_test, 1)
    _write_idx_images(os.path.join(dirpath,
                                   "train-images-idx3-ubyte.gz"), xtr)
    _write_idx_labels(os.path.join(dirpath,
                                   "train-labels-idx1-ubyte.gz"), ytr)
    _write_idx_images(os.path.join(dirpath,
                                   "t10k-images-idx3-ubyte.gz"), xte)
    _write_idx_labels(os.path.join(dirpath,
                                   "t10k-labels-idx1-ubyte.gz"), yte)


def _make_ptb(dirpath):
    """Highly regular corpus: the perplexity gate's bar (<300) is easy
    for structured text, which is the point — the gate must RUN."""
    os.makedirs(dirpath, exist_ok=True)
    rng = np.random.RandomState(0)
    words = ["the", "cat", "dog", "sat", "ran", "on", "mat", "log",
             "a", "and"]
    def corpus(n):
        toks = []
        for _ in range(n):
            s = rng.randint(0, len(words) - 1)
            toks += [words[s], words[(s + 1) % len(words)],
                     words[(s + 2) % len(words)]]
        return " ".join(toks)
    with open(os.path.join(dirpath, "ptb.train.txt"), "w") as f:
        f.write(corpus(40000))
    with open(os.path.join(dirpath, "ptb.valid.txt"), "w") as f:
        f.write(corpus(2000))


def _make_voc(dirpath, n=24, edge=200):
    """VOC2007-shaped drop: JPEGs with one bright box each + matching
    annotation XMLs and trainval split."""
    ann = os.path.join(dirpath, "Annotations")
    jpg = os.path.join(dirpath, "JPEGImages")
    split = os.path.join(dirpath, "ImageSets", "Main")
    for d in (ann, jpg, split):
        os.makedirs(d, exist_ok=True)
    rng = np.random.RandomState(3)
    ids = []
    for i in range(n):
        img_id = "%06d" % i
        ids.append(img_id)
        img = np.full((edge, edge, 3), 40, np.uint8)
        bw = rng.randint(edge // 4, edge // 2)
        x0 = rng.randint(0, edge - bw)
        y0 = rng.randint(0, edge - bw)
        img[y0:y0 + bw, x0:x0 + bw] = 230
        Image.fromarray(img).save(os.path.join(jpg, img_id + ".jpg"),
                                  quality=90)
        cls = ["cat", "dog"][i % 2]
        xml = ("<annotation><size><width>%d</width><height>%d</height>"
               "<depth>3</depth></size><object><name>%s</name><bndbox>"
               "<xmin>%d</xmin><ymin>%d</ymin><xmax>%d</xmax>"
               "<ymax>%d</ymax></bndbox></object></annotation>"
               % (edge, edge, cls, x0 + 1, y0 + 1, x0 + bw, y0 + bw))
        with open(os.path.join(ann, img_id + ".xml"), "w") as f:
            f.write(xml)
    with open(os.path.join(split, "trainval.txt"), "w") as f:
        f.write("\n".join(ids) + "\n")
    with open(os.path.join(split, "test.txt"), "w") as f:
        f.write("\n".join(ids[: n // 4]) + "\n")


def _make_image_tree(dirpath, classes=3, per_class=4, edge=48):
    """class-subdirectory image layout: the im2rec packing input."""
    rng = np.random.RandomState(5)
    for c in range(classes):
        d = os.path.join(dirpath, "class%d" % c)
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            img = (rng.rand(edge, edge, 3) * 255).astype(np.uint8)
            Image.fromarray(img).save(
                os.path.join(d, "img%d.jpg" % i), quality=85)


@pytest.mark.slow
def test_prepare_data_layout_and_gates_run(tmp_path):
    # slow lane: ~3 minutes of subprocess training gates — over 20% of
    # the tier-1 870s wall budget for ONE meta-test, and it currently
    # sits in the environmental-failure set on CPU boxes.  The data-drop
    # activation contract still runs under the slow selection
    # (`pytest -m slow tests/test_prepare_data.py`).
    # 1. scatter a synthetic "downloads" directory
    src = tmp_path / "downloads"
    _make_mnist(str(src / "somewhere" / "deep"))
    _make_ptb(str(src / "simple-examples" / "data"))
    _make_voc(str(src / "VOCdevkit" / "VOC2007"))
    _make_image_tree(str(src / "raw_images"))

    # 2. prepare_data converts it into the documented layout
    target = tmp_path / "data"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "prepare_data.py"),
         str(src), str(target)],
        capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "mnist: OK" in r.stdout
    assert "ptb: OK" in r.stdout
    assert "voc: OK" in r.stdout
    # the image tree was packed through im2rec into train.rec
    assert "imagenet: train.rec present" in r.stdout, r.stdout
    from mxnet_tpu.io import ImageRecordIter
    it = ImageRecordIter(
        path_imgrec=str(target / "imagenet" / "train.rec"),
        data_shape=(3, 32, 32), batch_size=4)
    b = next(it)
    assert b.data[0].shape == (4, 3, 32, 32)

    # 3. --check agrees
    r2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "prepare_data.py"),
         "--check", str(target)], capture_output=True, text=True, cwd=REPO)
    assert r2.returncode == 0, r2.stdout + r2.stderr

    # 4. the real-data gates RUN against the drop (no skips)
    env = dict(os.environ, MX_DATA_DIR=str(target),
               JAX_PLATFORMS="cpu", MX_FORCE_CPU="1")
    r3 = subprocess.run(
        [sys.executable, "-m", "pytest", "-q", "--no-header",
         "-p", "no:cacheprovider", "tests/test_real_data.py"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=1500)
    tail = r3.stdout.strip().splitlines()[-1] if r3.stdout.strip() else ""
    assert r3.returncode == 0, r3.stdout[-3000:] + r3.stderr[-2000:]
    assert "skipped" not in tail, tail
    assert "3 passed" in tail, tail
