"""Runtime telemetry (ISSUE 8): instrument registry semantics (incl.
under threads), JSON/Prometheus exposition, step-phase spans feeding the
profiler and the flight recorder, client<->server trace-ID propagation
over a real socket (retry + replay child events), crash dumps on a
virtual-clock watchdog trip and the NaN raise policy, heartbeat JSON
round-trip into the supervisor's fleet status table, and the mxlint
reinjection proving a host sync inside a span helper trips the hot-path
rule."""
import importlib.util
import json
import os
import socket
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mxnet_tpu import fault, health, telemetry  # noqa: E402
from mxnet_tpu.telemetry import (Counter, Gauge, Histogram,  # noqa: E402
                                 Registry, registry)


def _load_launch():
    spec = importlib.util.spec_from_file_location(
        "mx_launch_telemetry_test", os.path.join(REPO, "tools", "launch.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def clean_telemetry(monkeypatch):
    """Isolated ring + trace buffer; MX_TELEMETRY forced on."""
    monkeypatch.setenv("MX_TELEMETRY", "1")
    telemetry.flight_recorder.clear()
    telemetry.clear_trace()
    yield
    telemetry.flight_recorder.clear()
    telemetry.clear_trace()


# ---------------------------------------------------------------------------
# instruments
# ---------------------------------------------------------------------------

def test_counter_gauge_semantics():
    r = Registry()
    c = r.counter("c", doc="d")
    c.inc()
    c.inc(4)
    assert c.value == 5
    assert r.counter("c") is c          # get-or-create
    c.set(0)
    assert c.value == 0
    g = r.gauge("g")
    g.set(7)
    g.dec(3)
    assert g.value == 4
    with pytest.raises(ValueError):
        r.gauge("c")                    # type mismatch on same name


def test_histogram_buckets_and_stats():
    r = Registry()
    h = r.histogram("lat", buckets=(0.001, 0.01, 0.1))
    for v in (0.0005, 0.005, 0.05, 5.0):
        h.observe(v)
    s = h.snapshot()
    assert s["count"] == 4
    assert s["buckets"] == {"0.001": 1, "0.01": 2, "0.1": 3, "+Inf": 4}
    assert s["min"] == 0.0005 and s["max"] == 5.0
    assert abs(s["avg"] - (0.0005 + 0.005 + 0.05 + 5.0) / 4) < 1e-9


def test_labeled_instruments_are_distinct():
    r = Registry()
    a = r.counter("reqs", labels={"cmd": "PUSH"})
    b = r.counter("reqs", labels={"cmd": "PULL"})
    assert a is not b
    a.inc(2)
    b.inc(3)
    snap = r.snapshot()
    assert snap["reqs{cmd=PUSH}"]["value"] == 2
    assert snap["reqs{cmd=PULL}"]["value"] == 3


def test_instruments_exact_under_threads():
    r = Registry()
    c = r.counter("n")
    h = r.histogram("h", buckets=(0.5,))

    def work():
        for _ in range(2000):
            c.inc()
            h.observe(0.25)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 16000
    assert h.snapshot()["count"] == 16000
    assert h.snapshot()["buckets"]["0.5"] == 16000


def test_prometheus_exposition_format():
    r = Registry()
    r.counter("engine.dispatch_count", doc="dispatches").inc(3)
    h = r.histogram("step_phase_seconds", labels={"phase": "forward"},
                    buckets=(0.01, 1.0))
    h.observe(0.005)
    h.observe(2.0)
    text = r.to_prometheus()
    assert "# TYPE mx_engine_dispatch_count counter" in text
    assert "mx_engine_dispatch_count 3" in text
    assert "# TYPE mx_step_phase_seconds histogram" in text
    assert 'mx_step_phase_seconds_bucket{phase="forward",le="0.01"} 1' \
        in text
    assert 'mx_step_phase_seconds_bucket{phase="forward",le="+Inf"} 2' \
        in text
    assert 'mx_step_phase_seconds_count{phase="forward"} 2' in text


def test_json_exposition_roundtrips():
    r = Registry()
    r.counter("a").inc(1)
    r.histogram("b").observe(0.2)
    blob = json.loads(r.to_json())
    assert blob["a"]["value"] == 1
    assert blob["b"]["count"] == 1


# ---------------------------------------------------------------------------
# engine counter fold-in (satellite: aliases keep working)
# ---------------------------------------------------------------------------

def test_engine_counters_are_registry_backed():
    from mxnet_tpu.engine import engine
    base = registry.value("engine.dispatch_count")
    assert engine.dispatch_count == base     # alias reads the registry
    engine.count_dispatch(2)
    assert engine.dispatch_count == base + 2
    assert registry.value("engine.dispatch_count") == base + 2
    # the tools' reset idiom writes through too
    w0 = engine.wire_bytes
    engine.count_wire_bytes(128)
    assert engine.wire_bytes == w0 + 128
    engine.wire_bytes = 0
    assert registry.value("engine.wire_bytes") == 0
    s0 = engine.compiled_steps
    engine.count_step_window(4, dispatches=2)
    assert engine.compiled_steps == s0 + 4


# ---------------------------------------------------------------------------
# phase spans + flight recorder
# ---------------------------------------------------------------------------

def test_phase_spans_accumulate_into_step_record(clean_telemetry):
    with telemetry.phase("forward"):
        pass
    with telemetry.phase("exchange"):
        pass
    rec = telemetry.note_step(steps=1, epoch=2, batch=5, batch_size=32)
    assert rec["epoch"] == 2 and rec["batch"] == 5
    assert set(rec["phases"]) >= {"forward", "exchange"}
    assert "dispatches" in rec and "wire_bytes" in rec
    ps = telemetry.phase_snapshot()
    assert ps["forward"]["count"] >= 1


def test_nested_same_phase_counts_once(clean_telemetry):
    h0 = telemetry.phase_snapshot().get("backward", {}).get("count", 0)
    with telemetry.phase("backward"):
        with telemetry.phase("backward"):      # Module->autograd nesting
            pass
    assert telemetry.phase_snapshot()["backward"]["count"] == h0 + 1
    rec = telemetry.note_step()
    assert rec["phases"]["backward"] > 0


def test_phase_disabled_is_noop(clean_telemetry, monkeypatch):
    monkeypatch.setenv("MX_TELEMETRY", "0")
    span = telemetry.phase("forward")
    with span:
        pass
    assert telemetry.note_step() is None
    assert telemetry.flight_recorder.records() == []


def test_ring_capacity_honors_env(clean_telemetry, monkeypatch):
    monkeypatch.setenv("MX_TELEMETRY_RING", "3")
    telemetry.flight_recorder.clear()        # re-size on next record
    for i in range(7):
        telemetry.note_step(batch=i)
    recs = telemetry.flight_recorder.records()
    assert len(recs) == 3
    assert [r["batch"] for r in recs] == [4, 5, 6]
    assert recs[-1]["step"] == 7             # total steps keep counting


def test_throughput_computed_between_steps(clean_telemetry):
    telemetry.note_step(batch_size=8)
    time.sleep(0.01)
    rec = telemetry.note_step(batch_size=8)
    assert rec["steps_per_sec"] > 0
    assert rec["throughput"] == pytest.approx(8 * rec["steps_per_sec"],
                                              rel=1e-3)


def test_trainer_step_records_flight_data(clean_telemetry):
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    net = gluon.nn.Dense(4, in_units=8)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    loss_fn = gluon.loss.L2Loss()
    x = nd.array(np.random.RandomState(0).randn(4, 8).astype(np.float32))
    y = nd.array(np.zeros((4, 4), np.float32))
    for _ in range(2):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(batch_size=4)
    recs = telemetry.flight_recorder.records()
    assert len(recs) == 2
    assert "backward" in recs[-1]["phases"]
    assert "optimizer_apply" in recs[-1]["phases"]
    assert recs[-1]["dispatches"] > 0


# ---------------------------------------------------------------------------
# profiler integration (satellite: compiled-step blind spot)
# ---------------------------------------------------------------------------

def test_phase_spans_land_in_profiler_dumps(clean_telemetry):
    from mxnet_tpu import profiler
    profiler.reset()
    profiler.set_state("run")
    try:
        with telemetry.phase("exchange"):
            pass
    finally:
        profiler.set_state("stop")
    agg = json.loads(profiler.dumps(format="json", reset=True))
    assert "phase.exchange" in agg


def test_compiled_step_dispatches_visible_in_profiler(clean_telemetry):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd, profiler

    mx.random.seed(0)
    net = gluon.nn.Dense(3, in_units=6)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05})
    step = trainer.make_compiled_step(net, gluon.loss.L2Loss())
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(4, 6).astype(np.float32))
    y = nd.array(rng.randn(4, 3).astype(np.float32))
    step.step(x, y)                      # deferred init + trace
    step.step(x, y)
    profiler.reset()
    profiler.set_state("run")
    try:
        step.step(x, y)
        Xw = nd.array(np.broadcast_to(np.asarray(x._jax),
                                      (4,) + tuple(x.shape)).copy())
        Yw = nd.array(np.broadcast_to(np.asarray(y._jax),
                                      (4,) + tuple(y.shape)).copy())
        step.run_window(Xw, Yw)
    finally:
        profiler.set_state("stop")
    assert step.compiled, step.fallback_reason
    agg = json.loads(profiler.dumps(format="json", reset=True))
    # single compiled steps and scan windows aggregate separately
    assert "phase.compiled_step" in agg
    assert "phase.compiled_window" in agg
    # and the window's flight record attributes every scanned step
    rec = telemetry.flight_recorder.last()
    assert rec["steps"] == 4 and rec.get("compiled") is True


# ---------------------------------------------------------------------------
# distributed trace propagation over a real socket
# ---------------------------------------------------------------------------

def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _start_server(port, num_workers=1):
    from mxnet_tpu.kvstore.server import serve_forever
    t = threading.Thread(target=serve_forever,
                         kwargs=dict(port=port, num_workers=num_workers),
                         daemon=True)
    t.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port),
                                     timeout=0.2).close()
            return t
        except OSError:
            time.sleep(0.05)
    raise RuntimeError("server did not come up on %d" % port)


def _stop_server(port, thread):
    from mxnet_tpu.kvstore.server import send_msg, recv_msg
    raw = socket.create_connection(("127.0.0.1", port), timeout=5)
    send_msg(raw, ("STOP", None))
    recv_msg(raw, timeout=5)
    raw.close()
    thread.join(timeout=10)


@pytest.fixture
def traced_client(clean_telemetry, monkeypatch):
    from mxnet_tpu.kvstore.kvstore import KVStoreDistAsync
    monkeypatch.setenv("MX_KVSTORE_RETRY_DEADLINE", "20")
    monkeypatch.setenv("MX_KVSTORE_RETRY_BASE", "0.05")
    monkeypatch.setenv("MX_KVSTORE_RETRY_MAX", "0.25")
    monkeypatch.setenv("MX_KVSTORE_HEARTBEAT", "0")
    monkeypatch.delenv("MX_PS_ROOTS", raising=False)
    port = _free_port()
    thread = _start_server(port)
    monkeypatch.setenv("MX_PS_ROOT", "127.0.0.1:%d" % port)
    telemetry.start_tracing()
    kv = KVStoreDistAsync()
    yield kv
    telemetry.stop_tracing()
    kv.close()
    _stop_server(port, thread)
    fault.clear()


def _spans(name):
    return [e for e in telemetry.trace_events()
            if e["name"] == name and e["ph"] == "X"]


def test_client_server_spans_share_trace_id(traced_client):
    from mxnet_tpu import nd
    kv = traced_client
    kv.init("w", nd.array(np.zeros(4, np.float32)))
    telemetry.clear_trace()
    kv.push("w", nd.array(np.ones(4, np.float32)))
    out = nd.zeros((4,))
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), np.ones(4))
    for cmd in ("PUSH", "PULL"):
        cli = _spans("kv.client.%s" % cmd)
        srv = _spans("kv.server.%s" % cmd)
        assert cli and srv, (cmd, [e["name"]
                                   for e in telemetry.trace_events()])
        assert srv[0]["args"]["trace_id"] == cli[0]["args"]["trace_id"]
        assert srv[0]["args"]["parent_id"] == cli[0]["args"]["span_id"]


def test_retry_and_replay_child_events(traced_client):
    """A reply lost after the server applied the PUSH: the client span
    gains a ``retry`` child event, the server's second handling answers
    from the exactly-once replay cache and gains a ``replay`` event —
    all under ONE trace id (the acceptance-criteria scenario)."""
    from mxnet_tpu import nd
    kv = traced_client
    kv.init("k", nd.array(np.zeros(2, np.float32)))
    telemetry.clear_trace()
    r0 = registry.value("kvstore.client_retries")
    p0 = registry.value("kvstore.server_replays")
    # drop the connection between send and recv: the PUSH is applied
    # server-side but the reply never lands -> reconnect + replay
    fault.inject("kvstore.recv", action="close", after=0, count=1)
    kv.push("k", nd.array(np.ones(2, np.float32)))
    out = nd.zeros((2,))
    kv.pull("k", out=out)
    np.testing.assert_allclose(out.asnumpy(), np.ones(2))  # exactly once
    assert registry.value("kvstore.client_retries") == r0 + 1
    assert registry.value("kvstore.server_replays") == p0 + 1
    cli = _spans("kv.client.PUSH")
    assert len(cli) == 1
    trace_id = cli[0]["args"]["trace_id"]
    retries = [e for e in telemetry.trace_events()
               if e["name"] == "retry" and e["ph"] == "i"]
    assert retries and retries[0]["args"]["trace_id"] == trace_id
    srv = _spans("kv.server.PUSH")
    assert len(srv) == 2                     # original + replayed handling
    assert all(s["args"]["trace_id"] == trace_id for s in srv)
    replays = [e for e in telemetry.trace_events()
               if e["name"] == "replay" and e["ph"] == "i"]
    assert replays and replays[0]["args"]["trace_id"] == trace_id


def test_plain_seq_envelope_still_handled():
    """4-tuple SEQ envelopes (no trace context) keep working — older
    tools and tests construct them directly."""
    from mxnet_tpu.kvstore.server import KVStoreServer
    srv = KVStoreServer(num_workers=1)
    ok, _ = srv.handle_request(
        ("SEQ", "r0:x", 1, ("INIT", "a", np.zeros(2))))
    assert ok
    ok, _ = srv.handle_request(
        ("SEQ", "r0:x", 2, ("PUSH", "a", np.ones(2))))
    assert ok
    ok, val = srv.handle_request(("SEQ", "r0:x", 3, ("PULL", "a")))
    assert ok and np.allclose(val, np.ones(2))


def test_trace_dump_and_merge(clean_telemetry, tmp_path):
    telemetry.start_tracing()
    try:
        with telemetry.Span("kv.client.PUSH", cat="rpc") as sp:
            ctx = sp.wire_context()
            with telemetry.rpc_span("kv.server.PUSH", trace_id=ctx[0],
                                    parent_id=ctx[1]):
                pass
    finally:
        telemetry.stop_tracing()
    p1 = telemetry.dump_trace(str(tmp_path / "a.trace.json"))
    blob = json.load(open(p1))
    assert blob["traceEvents"] and "metadata" in blob
    # second "process": same events, different file
    p2 = str(tmp_path / "b.trace.json")
    json.dump({"traceEvents": blob["traceEvents"],
               "metadata": {"pid": 999, "rank": "1", "role": "server"}},
              open(p2, "w"))
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import telemetry_dump
    merged, summary = telemetry_dump.merge([p1, p2])
    assert summary["distinct_trace_ids"] == 1      # one causal chain
    pids = {e["pid"] for e in merged["traceEvents"]}
    assert len(pids) == 2                          # one row per process
    names = {e["name"] for e in merged["traceEvents"]}
    assert "process_name" in names
    # CLI end-to-end
    out = str(tmp_path / "merged.json")
    rc = telemetry_dump.main(["--out", out, p1, p2])
    assert rc == 0 and os.path.exists(out)


# ---------------------------------------------------------------------------
# crash dumps: watchdog trip (virtual clock), NaN raise, fit death
# ---------------------------------------------------------------------------

def test_watchdog_trip_dumps_flight_recorder(clean_telemetry, monkeypatch,
                                             tmp_path, capsys):
    monkeypatch.setenv("MX_CRASH_DIR", str(tmp_path / "crash"))
    telemetry.note_step(epoch=0, batch=3)
    fired = []
    with fault.use_virtual_time() as clk:
        wd = health.Watchdog(timeout=5.0, on_timeout=lambda: fired.append(1))
        wd.pet()
        clk.advance(6.0)
        assert wd.check()
    assert fired == [1]
    dumps = os.listdir(str(tmp_path / "crash"))
    assert len(dumps) == 1, dumps
    blob = json.load(open(str(tmp_path / "crash" / dumps[0])))
    assert "watchdog" in blob["reason"]
    assert len(blob["records"]) >= 1
    assert blob["records"][-1]["batch"] == 3
    assert "engine.dispatch_count" in blob["counters"]


def test_nan_raise_policy_dumps_and_counts(clean_telemetry, monkeypatch,
                                           tmp_path):
    from mxnet_tpu import nd
    monkeypatch.setenv("MX_CRASH_DIR", str(tmp_path / "crash"))
    n0 = registry.value("health.nan_events")
    guard = health.GradientGuard("raise")
    poisoned = [("w", nd.array(np.array([1.0, np.nan], np.float32)))]
    from mxnet_tpu.base import MXNetError
    with pytest.raises(MXNetError):
        guard.allow_update(poisoned)
    assert registry.value("health.nan_events") == n0 + 1
    dumps = os.listdir(str(tmp_path / "crash"))
    assert dumps and "nan_policy_raise" in \
        json.load(open(str(tmp_path / "crash" / dumps[0])))["reason"]


def test_dump_crash_without_dir_is_none(clean_telemetry, monkeypatch):
    monkeypatch.delenv("MX_CRASH_DIR", raising=False)
    assert telemetry.dump_crash("whatever") is None


# ---------------------------------------------------------------------------
# heartbeat JSON round-trip -> supervisor fleet status table
# ---------------------------------------------------------------------------

def test_heartbeat_payload_roundtrip(clean_telemetry, tmp_path):
    telemetry.note_step(epoch=1, batch=2, batch_size=16)
    time.sleep(0.005)
    telemetry.note_step(epoch=1, batch=3, batch_size=16)
    hb = health.Heartbeat(str(tmp_path / "hb"))
    hb.beat(epoch=1, nbatch=3)
    launch = _load_launch()
    sp = launch.SupervisedProc("rank 0", ["true"], {},
                               heartbeat=str(tmp_path / "hb"))
    age, head, payload = launch.Supervisor._read_beat(sp)
    assert age is not None and age < 60
    assert head.split()[1:] == ["1", "3"]
    rec = telemetry.flight_recorder.last()
    assert payload["step"] == rec["step"]
    assert payload["throughput"] == rec["throughput"]
    assert payload["wire_bytes"] == rec["wire_bytes"]


def test_supervisor_status_table_renders(clean_telemetry, tmp_path):
    telemetry.note_step(epoch=0, batch=1, batch_size=8)
    time.sleep(0.005)
    telemetry.note_step(epoch=0, batch=2, batch_size=8)
    hb = health.Heartbeat(str(tmp_path / "hb"))
    hb.beat(epoch=0, nbatch=2)
    launch = _load_launch()
    sup = launch.Supervisor()
    sup.add("rank 0", ["true"], {}, heartbeat=str(tmp_path / "hb"))
    sup.add("server 0", ["true"], {}, role="server")
    table = sup.status_table()
    assert "fleet status:" in table
    assert "rank 0" in table and "server 0" in table
    rec = telemetry.flight_recorder.last()
    assert str(rec["step"]) in table          # step column populated
    assert "img/s" in table


def test_supervisor_crash_dump_written(clean_telemetry, monkeypatch,
                                       tmp_path):
    telemetry.note_step(epoch=0, batch=1)
    hb = health.Heartbeat(str(tmp_path / "hb"))
    hb.beat(epoch=0, nbatch=1)
    monkeypatch.setenv("MX_CRASH_DIR", str(tmp_path / "crash"))
    launch = _load_launch()
    sup = launch.Supervisor()
    sp = sup.add("rank 0", ["true"], {}, heartbeat=str(tmp_path / "hb"))
    path = sup._crash_dump(sp, 86, "exit 86 (watchdog)")
    blob = json.load(open(path))
    assert blob["rc"] == 86 and blob["proc"] == "rank 0"
    assert blob["heartbeat"].get("step") == \
        telemetry.flight_recorder.last()["step"]


# ---------------------------------------------------------------------------
# mxlint reinjection: spans must stay sync-free (hot-path rule roots)
# ---------------------------------------------------------------------------

def test_telemetry_is_hot_path_root():
    from tools.mxlint.rules import HOT_PATH_ROOTS
    roots = dict(HOT_PATH_ROOTS)
    assert "mxnet_tpu/telemetry.py" in roots
    quals = roots["mxnet_tpu/telemetry.py"]
    assert "phase" in quals and "note_step" in quals


def test_reinjected_sync_in_phase_span_trips_hot_path_rule():
    from tools.mxlint import lint_source
    from tools.mxlint.core import apply_baseline, load_baseline
    p = os.path.join(REPO, "mxnet_tpu", "telemetry.py")
    with open(p) as f:
        code = f.read()
    anchor = "        if enabled() and not any(isinstance(s, _PhaseSpan) and"
    assert anchor in code, "_PhaseSpan.__exit__ moved; update this test"
    bad = code.replace(
        anchor, "        _dbg = exc[0].asnumpy()\n" + anchor, 1)
    diags = lint_source(bad, "mxnet_tpu/telemetry.py")
    rules = {d.rule for d in diags}
    assert "host-sync-in-hot-path" in rules, rules
    baseline = load_baseline(os.path.join(REPO, "tools", "mxlint",
                                          "baseline.json"))
    new, _, _ = apply_baseline(diags, baseline)
    assert "host-sync-in-hot-path" in {d.rule for d in new}


def test_shipped_telemetry_lints_clean():
    from tools.mxlint import lint_paths
    diags = lint_paths([os.path.join(REPO, "mxnet_tpu", "telemetry.py"),
                        os.path.join(REPO, "tools", "telemetry_dump.py")],
                       root=REPO)
    assert [d for d in diags] == [], diags
