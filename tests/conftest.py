"""Test harness config.

Default lane (per SURVEY.md §4.5): unit tests run on a *fake 8-device CPU
mesh* (xla_force_host_platform_device_count) so multi-device/kvstore/
shard_map logic is exercised without TPU hardware; `mx.tpu(i)` resolves to
the i-th host device.  Must run before jax is imported anywhere.

TPU lane (SURVEY.md §4.2 — "the rebuild's most important pattern"):
``MX_TEST_CTX=tpu python -m pytest tests/test_operator.py tests/test_gluon.py``
re-runs the suite with the REAL chip as the default context (mx.tpu(0) →
axon device 0).  The tunnel is probed first in a subprocess; if it is
wedged every test is skipped cleanly instead of hanging.  Multi-device
mesh tests are not part of this lane (one real chip) — point it at the op
battery and gluon files, the ctx-sensitive surface.
"""
import os
import sys

TPU_LANE = os.environ.get("MX_TEST_CTX", "").lower() == "tpu"

if not TPU_LANE:
    # force: tests must not touch the (flaky) TPU tunnel
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["MX_FORCE_CPU"] = "1"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_tpu_reachable = False
if TPU_LANE:
    # probe in a SUBPROCESS (a wedged tunnel hangs in-process jax init);
    # budget env-tunable (MX_TPU_PROBE_TIMEOUT, default 120s) so the
    # skip-cleanliness test can prove the path without burning two
    # minutes of tier-1 wall time on a wedged tunnel
    from mxnet_tpu.base import probe_accelerator, probe_timeout

    _tpu_reachable = probe_accelerator(probe_timeout())
else:
    # The axon TPU plugin's sitecustomize force-overrides the platform list
    # with jax.config.update("jax_platforms", "axon,cpu"), IGNORING the
    # JAX_PLATFORMS env var — and any jax.devices() call then hangs forever
    # on a wedged TPU tunnel. Re-override the config back to cpu-only
    # before anything touches a backend.
    from mxnet_tpu.base import pin_cpu

    pin_cpu()

import numpy as np
import pytest


def pytest_configure(config):
    # chaos lane: fault-injection tests (tests/test_fault.py).  They run
    # inside tier-1's `not slow` selection — the FaultInjector's virtual
    # clock keeps retry/backoff schedules sleep-free, so determinism
    # comes from exact call ordinals, not wall-clock races.
    config.addinivalue_line(
        "markers",
        "chaos: deterministic fault-injection tests (virtual delays, "
        "no real sleeps; kept fast enough for tier-1)")
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 `not slow` selection")


def pytest_collection_modifyitems(config, items):
    if TPU_LANE and not _tpu_reachable:
        skip = pytest.mark.skip(
            reason="MX_TEST_CTX=tpu but the accelerator probe failed "
                   "(tunnel wedged/absent)")
        for item in items:
            item.add_marker(skip)


@pytest.fixture(autouse=True)
def _seeded():
    """Reference: @with_seed() — fixed seeds, logged for reproducibility;
    in the TPU lane every test additionally runs under a tpu(0) default
    context (the reference's ctx-parametrized GPU rerun)."""
    np.random.seed(1234)
    import mxnet_tpu as mx
    mx.random.seed(1234)
    if TPU_LANE and _tpu_reachable:
        with mx.Context("tpu", 0):
            yield
    else:
        yield
