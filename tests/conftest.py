"""Test harness config.

Per SURVEY.md §4.5: unit tests run on a *fake 8-device CPU mesh*
(xla_force_host_platform_device_count) so multi-device/kvstore/shard_map
logic is exercised without TPU hardware; `mx.tpu(i)` resolves to the i-th
host device.  Must run before jax is imported anywhere.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # force: tests must not touch the (flaky) TPU tunnel
os.environ["MX_FORCE_CPU"] = "1"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The axon TPU plugin's sitecustomize force-overrides the platform list with
# jax.config.update("jax_platforms", "axon,cpu"), IGNORING the JAX_PLATFORMS
# env var — and any jax.devices() call then hangs forever on a wedged TPU
# tunnel. Re-override the config back to cpu-only before anything touches a
# backend.
from mxnet_tpu.base import pin_cpu

pin_cpu()

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seeded():
    """Reference: @with_seed() — fixed seeds, logged for reproducibility."""
    np.random.seed(1234)
    import mxnet_tpu as mx
    mx.random.seed(1234)
    yield
