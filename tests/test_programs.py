"""Program census (ISSUE 10): registry exactness on CPU (memory/cost
metadata matching jax's own AOT analysis, graceful None in light mode),
retrace-explainer diff correctness for shape/dtype/tree-structure
changes, the device-buffer census with owner attribution + leak
detector, crash-dump/flight-recorder wiring, the serve METRICS verb
over a real socket, engine.snapshot() consistency, the bench_compare
regression sentinel, and the mxlint reinjection proving a host sync in
the census hot path trips the rule."""
import json
import os
import socket
import subprocess
import sys
import threading
import time
import uuid

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax                                              # noqa: E402
import jax.numpy as jnp                                 # noqa: E402

import mxnet_tpu as mx                                  # noqa: E402
from mxnet_tpu import programs, telemetry               # noqa: E402


def _name(tag):
    """Unique program name per test run (records are process-global)."""
    return "test.%s.%s" % (tag, uuid.uuid4().hex[:8])


# ---------------------------------------------------------------------------
# registry exactness
# ---------------------------------------------------------------------------

def test_aot_program_records_compile_time_memory_and_cost():
    name = _name("aot")

    def fn(x, y):
        return x @ y + 1.0

    prog = programs.register_program(name, fn)
    a = jnp.ones((8, 16), jnp.float32)
    b = jnp.ones((16, 4), jnp.float32)
    out = prog(a, b)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(jax.jit(fn)(a, b)))
    rec = programs.find_record(name)
    assert rec is not None
    snap = rec.snapshot()
    assert snap["compiles"] == 1
    assert snap["retraces"] == 0
    assert snap["compile_seconds"]["total"] > 0
    # exactness vs jax's own AOT analysis of the identical program
    ref = jax.jit(fn).lower(a, b).compile()
    ref_mem = ref.memory_analysis()
    if ref_mem is None:
        assert snap["memory"] is None       # graceful None
    else:
        assert snap["memory"]["argument_bytes"] == \
            int(ref_mem.argument_size_in_bytes)
        assert snap["memory"]["output_bytes"] == \
            int(ref_mem.output_size_in_bytes)
        assert snap["memory"]["temp_bytes"] == \
            int(ref_mem.temp_size_in_bytes)
    ca = ref.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    if isinstance(ca, dict) and "flops" in ca:
        assert snap["cost"]["flops"] == pytest.approx(float(ca["flops"]))
    # second identical call: cached executable, no new compile
    prog(a, b)
    assert programs.find_record(name).compiles == 1


def test_light_program_counts_traces_memory_explicitly_none():
    name = _name("light")
    prog = programs.register_program(name, lambda x: x * 2, mode="light")
    a = jnp.ones((4,), jnp.float32)
    prog(a)
    prog(a)                                 # cache hit: no new compile
    rec = programs.find_record(name)
    assert rec.compiles == 1
    assert rec.snapshot()["compile_seconds"]["total"] > 0
    assert rec.memory is None               # explicitly None in light mode
    assert rec.cost is None
    prog(jnp.ones((7,), jnp.float32))       # retrace
    assert rec.compiles == 2
    assert rec.retraces == 1


def test_register_but_never_dispatch_creates_no_record():
    name = _name("idle")
    programs.register_program(name, lambda x: x)
    assert programs.find_record(name) is None
    assert name not in programs.program_table()


def test_census_disabled_returns_plain_jit(monkeypatch):
    monkeypatch.setenv("MX_PROGRAM_CENSUS", "0")
    name = _name("off")
    prog = programs.register_program(name, lambda x: x + 1)
    out = prog(jnp.ones((2,), jnp.float32))
    np.testing.assert_allclose(np.asarray(out), [2.0, 2.0])
    assert not isinstance(prog, programs.Program)
    assert programs.find_record(name) is None


def test_donated_aot_program_dispatches():
    name = _name("donate")
    prog = programs.register_program(name, lambda x: x + 1,
                                     donate_argnums=(0,))
    out = prog(jnp.ones((4,), jnp.float32))
    np.testing.assert_allclose(np.asarray(out), 2.0 * np.ones(4))
    out2 = prog(jnp.asarray(np.asarray(out)))
    np.testing.assert_allclose(np.asarray(out2), 3.0 * np.ones(4))
    assert programs.find_record(name).compiles == 1


def test_aot_fallback_on_unlowerable_site_degrades_to_light():
    name = _name("fallback")
    calls = []

    def fn(x):
        calls.append(1)
        return x + 1

    prog = programs.register_program(name, fn)
    prog._aot = False                       # simulate a failed lowering
    out = prog(jnp.ones((3,), jnp.float32))
    np.testing.assert_allclose(np.asarray(out), 2.0 * np.ones(3))
    rec = programs.find_record(name)
    assert rec.compiles == 1                # probe-counted
    assert rec.memory is None


def test_aot_fallback_after_successful_compiles_counts_exactly():
    # AOT lowers bump the light-mode trace probe too; a later fallback
    # must not re-record those probe bumps as phantom compiles
    name = _name("fb2")
    prog = programs.register_program(name, lambda x: x + 1)
    prog(jnp.ones((2,), jnp.float32))           # real AOT compile
    rec = programs.find_record(name)
    assert rec.compiles == 1
    orig_jit = prog._jit

    class BoomLower:
        def lower(self, *a, **k):
            raise RuntimeError("boom")

        def __call__(self, *a, **k):
            return orig_jit(*a, **k)

    prog._jit = BoomLower()
    out = prog(jnp.ones((3,), jnp.float32))     # degrade to light
    np.testing.assert_allclose(np.asarray(out), 2.0 * np.ones(3))
    assert not prog._aot
    assert rec.compiles == 2, rec.compiles      # one light trace, no phantoms


# ---------------------------------------------------------------------------
# retrace explainer
# ---------------------------------------------------------------------------

def test_explainer_shape_change():
    name = _name("shape")
    prog = programs.register_program(name, lambda x: x.sum())
    prog(jnp.ones((4, 4), jnp.float32))
    prog(jnp.ones((8, 4), jnp.float32))
    rec = programs.find_record(name)
    assert rec.retraces == 1
    diff = rec.last_retrace["diff"]
    assert diff["kind"] == "leaves"
    (chg,) = diff["changed"]
    assert chg["change"] == "shape"
    assert chg["before"]["shape"] == (4, 4)
    assert chg["after"]["shape"] == (8, 4)


def test_explainer_dtype_change():
    name = _name("dtype")
    prog = programs.register_program(name, lambda x: x.sum())
    prog(jnp.ones((4,), jnp.float32))
    prog(jnp.ones((4,), jnp.bfloat16))
    diff = programs.find_record(name).last_retrace["diff"]
    (chg,) = diff["changed"]
    assert chg["change"] == "dtype"
    assert chg["before"]["dtype"] == "float32"
    assert chg["after"]["dtype"] == "bfloat16"


def test_explainer_tree_structure_change():
    name = _name("tree")
    prog = programs.register_program(
        name, lambda t: sum(jax.tree_util.tree_leaves(t)))
    a = jnp.ones((2,), jnp.float32)
    prog((a, a))
    prog({"x": a, "y": a})
    diff = programs.find_record(name).last_retrace["diff"]
    assert diff["kind"] == "tree_structure"
    assert diff["before"] != diff["after"]


def test_explainer_names_the_changed_arg_in_light_mode():
    name = _name("lightdiff")
    prog = programs.register_program(
        name, lambda x, y: x + y.sum(), mode="light")
    a = jnp.ones((2,), jnp.float32)
    prog(a, jnp.ones((3,), jnp.float32))
    prog(a, jnp.ones((5,), jnp.float32))
    diff = programs.find_record(name).last_retrace["diff"]
    (chg,) = diff["changed"]
    assert "[1]" in chg["arg"]              # second positional arg
    assert chg["change"] == "shape"


def test_explainer_sharding_change_same_shape_dtype():
    """ISSUE 11 satellite: a resharded argument — same shape, same
    dtype, different PartitionSpec — must diff as a 'sharding' change,
    not a generic leaf change.  This is the first explainer path FSDP
    (ROADMAP item 1) will exercise: flipping a parameter from
    replicated to fsdp-sharded retraces every program it feeds."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(np.array(jax.devices("cpu")[:1]), ("fsdp",))
    x = jnp.ones((8, 4), jnp.float32)
    repl = jax.device_put(x, NamedSharding(mesh, P()))
    shard = jax.device_put(x, NamedSharding(mesh, P("fsdp")))
    old = programs.signature_of((repl,))
    new = programs.signature_of((shard,))
    diff = programs.diff_signatures(old, new)
    assert diff is not None and diff["kind"] == "leaves"
    (chg,) = diff["changed"]
    assert chg["change"] == "sharding"
    assert chg["before"]["shape"] == chg["after"]["shape"] == (8, 4)
    assert chg["before"]["dtype"] == chg["after"]["dtype"] == "float32"
    assert chg["before"]["device"] != chg["after"]["device"]
    # identical shardings stay cache hits (no spurious diff)
    assert programs.diff_signatures(
        old, programs.signature_of(
            (jax.device_put(x, NamedSharding(mesh, P())),))) is None


def test_explainer_sharding_change_through_dispatch():
    """End-to-end: dispatching an AOT program with a resharded
    (shape/dtype-identical) argument builds a second executable — the
    AOT cache keys on sharding, since an AOT executable rejects inputs
    laid out differently — and the record's explainer diff names the
    arg and the sharding change.  (Light mode defers to jax.jit's own
    cache, which may normalize single-device shardings; the AOT lane is
    the one serving/step programs use, so it is the one FSDP will
    retrace through.)"""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    name = _name("reshard")
    prog = programs.register_program(name, lambda x: x.sum())
    mesh = Mesh(np.array(jax.devices("cpu")[:1]), ("fsdp",))
    x = jnp.ones((8, 4), jnp.float32)
    prog(jax.device_put(x, NamedSharding(mesh, P())))
    prog(jax.device_put(x, NamedSharding(mesh, P("fsdp"))))
    rec = programs.find_record(name)
    assert rec.compiles == 2 and rec.retraces == 1
    (chg,) = rec.last_retrace["diff"]["changed"]
    assert chg["change"] == "sharding"
    assert "[0]" in chg["arg"]


def test_program_retrace_counter_in_telemetry():
    name = _name("metric")
    prog = programs.register_program(name, lambda x: x + 1)
    prog(jnp.ones((2,), jnp.float32))
    prog(jnp.ones((3,), jnp.float32))
    c = telemetry.registry.find("program_retraces", {"program": name})
    assert c is not None and c.value == 1
    prom = telemetry.registry.to_prometheus()
    assert "mx_program_compile_seconds" in prom
    assert "mx_program_retraces" in prom


# ---------------------------------------------------------------------------
# device-buffer census + leak detector
# ---------------------------------------------------------------------------

def test_census_attributes_params_and_optimizer_state():
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn
    net = nn.Dense(4, in_units=8)
    net.initialize(mx.init.Xavier())
    tr = gluon.Trainer(list(net.collect_params().values()), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    loss_fn = gluon.loss.L2Loss()
    x = nd.array(np.random.RandomState(0).randn(4, 8).astype(np.float32))
    y = nd.array(np.random.RandomState(1).randn(4, 4).astype(np.float32))
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    tr.step(batch_size=4)
    census = programs.buffer_census()
    assert census["params"]["count"] >= 2           # weight+bias (+grads)
    assert census["params"]["bytes"] > 0
    assert census["optimizer_state"]["count"] >= 2  # momentum buffers
    assert census["total_bytes"] >= sum(
        v["bytes"] for k, v in census.items() if isinstance(v, dict))
    # the arrays stay counted once: total is consistent with the walk
    assert census["n_arrays"] >= census["params"]["count"]


def test_leak_detector_trips_on_retained_buffers(monkeypatch):
    monkeypatch.setenv("MX_LEAK_WARN_BYTES", "4096")
    det = programs.LeakDetector()
    det.check()                              # baseline
    retained = [jnp.ones((4096,), jnp.float32) for _ in range(3)]
    chk = det.check()
    assert chk["tripped"]
    assert chk["growth_bytes"] >= 4096
    g = telemetry.registry.find("census_leak_bytes")
    assert g is not None and g.value >= 4096
    # releasing the buffers shrinks the total: the streak resets
    del retained
    chk2 = det.check()
    assert not chk2["tripped"]
    assert chk2["growth_bytes"] == 0


def test_leak_detector_plateau_keeps_streak(monkeypatch):
    # a flat check between growth steps (allocator reuse) must NOT
    # reset the streak — only a shrink does
    monkeypatch.setenv("MX_LEAK_WARN_BYTES", str(450 * 1024))
    det = programs.LeakDetector()
    det.check()
    keep1 = [jnp.ones((64 * 1024,), jnp.float32)]      # +256KB
    assert not det.check()["tripped"]
    det.check()                                         # plateau
    keep2 = [jnp.ones((64 * 1024,), jnp.float32)]      # +256KB more
    chk = det.check()
    assert chk["tripped"], chk
    del keep1, keep2


def test_leak_detector_zero_threshold_never_trips(monkeypatch):
    monkeypatch.setenv("MX_LEAK_WARN_BYTES", "0")
    det = programs.LeakDetector()
    det.check()
    retained = [jnp.ones((1 << 16,), jnp.float32)]
    assert not det.check()["tripped"]
    del retained


def test_flight_recorder_step_records_carry_census(monkeypatch):
    monkeypatch.setenv("MX_TELEMETRY", "1")
    telemetry.flight_recorder.clear()
    for _ in range(17):                      # census rides every 16th
        telemetry.note_step(steps=1)
    recs = telemetry.flight_recorder.records()
    assert any("live_bytes" in r for r in recs), recs[-1]
    telemetry.flight_recorder.clear()


def test_crash_dump_carries_buffer_census_and_programs(tmp_path):
    name = _name("crash")
    prog = programs.register_program(name, lambda x: x * 3)
    prog(jnp.ones((2,), jnp.float32))
    path = telemetry.dump_crash("test", directory=str(tmp_path))
    blob = json.load(open(path))
    assert blob["buffer_census"]["total_bytes"] > 0
    assert name in blob["programs"]
    assert blob["programs"][name]["compile_seconds"]["total"] > 0


# ---------------------------------------------------------------------------
# serve: bucket table attribution + METRICS verb
# ---------------------------------------------------------------------------

@pytest.fixture
def serve_replica():
    from mxnet_tpu.serve import ServeServer, serve_forever, Servable
    from mxnet_tpu.serve.demo import demo_block, demo_example
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    state = ServeServer()
    sv = Servable(demo_block(), name="census-demo", version=1)
    state.host.deploy(sv, example=demo_example())
    stop = threading.Event()
    t = threading.Thread(target=serve_forever,
                         kwargs=dict(port=port, state=state,
                                     stop_event=stop), daemon=True)
    t.start()
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port),
                                     timeout=0.2).close()
            break
        except OSError:
            time.sleep(0.05)
    yield "127.0.0.1:%d" % port, sv
    stop.set()
    t.join(timeout=10)


def test_serve_bucket_table_fully_attributed(serve_replica):
    from mxnet_tpu.serve import ServeClient
    from mxnet_tpu.serve.demo import DEMO_IN
    addr, sv = serve_replica
    table = programs.program_table()
    for bucket in sv.buckets:
        key = "serve.census-demo.b%d" % bucket
        assert key in table, sorted(table)
        assert table[key]["compiles"] >= 1
        assert table[key]["compile_seconds"]["total"] > 0
        assert table[key]["retraces"] == 0
    # dispatching again stays retrace-free and the version's buffers
    # are attributed to the "serve" owner bucket
    cli = ServeClient([addr], timeout=30)
    cli.predict([np.zeros((2, DEMO_IN), np.float32)])
    after = programs.program_table()
    assert all(after["serve.census-demo.b%d" % b]["retraces"] == 0
               for b in sv.buckets)
    census = programs.buffer_census()
    assert census["serve"]["count"] >= 1
    assert census["serve"]["bytes"] > 0
    cli.close()


def test_metrics_verb_returns_prometheus_snapshot(serve_replica):
    from mxnet_tpu.serve import ServeClient
    addr, _sv = serve_replica
    cli = ServeClient([addr], timeout=30)
    text = cli.metrics()
    assert "# TYPE" in text
    assert "mx_serve_batches" in text or "mx_serve_requests" in text
    assert "mx_program_compile_seconds" in text
    blob = cli.metrics(fmt="json")
    parsed = json.loads(blob)
    assert any(k.startswith("program_compile_seconds") for k in parsed)
    cli.close()


def test_text_wire_codec_roundtrip():
    from mxnet_tpu.kvstore.wire_codec import (decode_text, encode_text,
                                              is_text_payload)
    payload = encode_text("mx_metric 1\n# ünïcode")
    assert is_text_payload(payload)
    assert decode_text(payload) == "mx_metric 1\n# ünïcode"
    with pytest.raises(ValueError):
        decode_text(("NOPE", b""))


def test_serve_load_cli_metrics_flag(serve_replica):
    addr, _sv = serve_replica
    env = dict(os.environ, JAX_PLATFORMS="cpu", MX_FORCE_CPU="1")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_load.py"),
         "--addrs", addr, "--requests", "2", "--metrics"],
        capture_output=True, text=True, timeout=240, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "SERVE_LOAD_OK" in r.stdout
    assert "==== metrics: replica 0" in r.stdout
    assert "mx_program_compile_seconds" in r.stdout


# ---------------------------------------------------------------------------
# whole-step lane
# ---------------------------------------------------------------------------

def test_compiled_step_registers_program_and_explains_invalidation():
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.gluon import nn
    net = nn.Dense(4, in_units=8)
    net.initialize(mx.init.Xavier())
    tr = gluon.Trainer(list(net.collect_params().values()), "sgd",
                       {"learning_rate": 0.1})
    cstep = tr.make_compiled_step(net, gluon.loss.L2Loss())
    rng = np.random.RandomState(0)
    x = nd.array(rng.randn(4, 8).astype(np.float32))
    y = nd.array(rng.randn(4, 4).astype(np.float32))
    cstep.step(x, y)
    cstep.step(x, y)
    rec = programs.find_record("step.step")
    assert rec is not None
    assert rec.snapshot()["compile_seconds"]["total"] > 0
    before = rec.compiles
    # a batch-shape change is a CompiledStep invalidation: the census
    # explains it as a step.step retrace naming the data arg
    x2 = nd.array(rng.randn(6, 8).astype(np.float32))
    y2 = nd.array(rng.randn(6, 4).astype(np.float32))
    cstep.step(x2, y2)
    assert rec.compiles == before + 1
    assert rec.last_retrace is not None
    diff = rec.last_retrace["diff"]
    assert diff["kind"] == "leaves"
    assert any(c["change"] == "shape" for c in diff["changed"])


# ---------------------------------------------------------------------------
# engine snapshot + bench sentinel
# ---------------------------------------------------------------------------

def test_engine_snapshot_consistent_group():
    from mxnet_tpu.engine import engine
    s0 = engine.snapshot()
    for key in ("dispatches", "wire_bytes", "compiled_steps",
                "compiled_step_windows", "programs"):
        assert key in s0
    engine.count_step_window(5, dispatches=2)
    engine.count_wire_bytes(123)
    s1 = engine.snapshot()
    assert s1["dispatches"] - s0["dispatches"] == 2
    assert s1["compiled_steps"] - s0["compiled_steps"] == 5
    assert s1["compiled_step_windows"] - s0["compiled_step_windows"] == 1
    assert s1["wire_bytes"] - s0["wire_bytes"] == 123
    assert s1["programs"] >= 0


def _run_compare(history, report, *extra):
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_compare.py"),
         "-", "--history", history] + list(extra),
        input=json.dumps(report), capture_output=True, text=True,
        timeout=120)
    return r.returncode, r.stdout


def test_bench_compare_seeds_passes_and_gates(tmp_path):
    history = str(tmp_path / "hist.jsonl")
    report = {"metric": "m", "value": 50.0, "unit": "img/s",
              "device": "cpu",
              "census": {"summary": {"compile_seconds_total": 1.0,
                                     "peak_temp_bytes": 1 << 20,
                                     "retraces": 0, "programs": 3}}}
    rc, out = _run_compare(history, report)
    assert rc == 0, out
    rc, out = _run_compare(history, report)          # same run: passes
    assert rc == 0, out
    assert len(open(history).read().splitlines()) == 2
    # the synthetic 2x step-time regression MUST gate non-zero
    rc, out = _run_compare(history, report, "--inject-slowdown", "2.0")
    assert rc == 1, out
    assert "THROUGHPUT REGRESSION" in out
    # injected runs never pollute the history
    assert len(open(history).read().splitlines()) == 2
    # a small wobble within tolerance passes
    ok = dict(report, value=47.0)
    rc, _ = _run_compare(history, ok)
    assert rc == 0
    # >15% peak-temp-bytes growth gates
    fat = dict(report)
    fat["census"] = {"summary": {"compile_seconds_total": 1.0,
                                 "peak_temp_bytes": int(1.3 * (1 << 20)),
                                 "retraces": 0, "programs": 3}}
    rc, out = _run_compare(history, fat)
    assert rc == 1
    assert "MEMORY REGRESSION" in out


def test_bench_compare_check_schema(tmp_path):
    history = str(tmp_path / "hist.jsonl")
    report = {"metric": "m", "value": 1.0, "unit": "x"}
    rc, _ = _run_compare(history, report)
    assert rc == 0
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_compare.py"),
         "--check-schema", "--history", history],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    with open(history, "a") as f:
        f.write("{broken\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_compare.py"),
         "--check-schema", "--history", history],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 1
    assert "unparseable" in r.stderr


def test_env_catalog_covers_new_flags():
    from mxnet_tpu.base import ENV_CATALOG
    for var in ("MX_PROGRAM_CENSUS", "MX_LEAK_WARN_BYTES",
                "MX_BENCH_HISTORY"):
        assert var in ENV_CATALOG


# ---------------------------------------------------------------------------
# mxlint reinjection: census helpers must stay sync-free
# ---------------------------------------------------------------------------

def test_reinjected_sync_in_census_call_path_trips_hot_path_rule():
    from tools.mxlint import lint_source
    from tools.mxlint.core import apply_baseline, load_baseline
    p = os.path.join(REPO, "mxnet_tpu", "programs.py")
    with open(p) as f:
        code = f.read()
    anchor = "        seq = self._seq\n"
    assert anchor in code, "Program.__call__ moved; update this test"
    bad = code.replace(
        anchor, "        _dbg = args[0].asnumpy()\n" + anchor, 1)
    diags = lint_source(bad, "mxnet_tpu/programs.py")
    rules = {d.rule for d in diags}
    assert "host-sync-in-hot-path" in rules, rules
    baseline = load_baseline(os.path.join(REPO, "tools", "mxlint",
                                          "baseline.json"))
    new, _, _ = apply_baseline(diags, baseline)
    assert "host-sync-in-hot-path" in {d.rule for d in new}


def test_shipped_programs_lints_clean():
    from tools.mxlint import lint_paths
    diags = lint_paths([os.path.join(REPO, "mxnet_tpu", "programs.py"),
                        os.path.join(REPO, "tools", "bench_compare.py")],
                       root=REPO)
    assert [d for d in diags] == [], diags
