"""Autograd tests (reference pattern: tests/python/unittest/test_autograd.py:
record/pause scopes, backward, grad_req modes, autograd.grad, Function)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def test_simple_backward():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [2.0, 4.0, 6.0])


def test_chain_rule_through_ops():
    x = nd.array([[0.5, -1.0], [2.0, 0.0]])
    x.attach_grad()
    with autograd.record():
        y = nd.relu(x)
        z = (y * 3.0).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [[3.0, 0.0], [3.0, 0.0]])


def test_backward_nonscalar_default_head_grad():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2.0
    y.backward()  # implicit ones head grad
    np.testing.assert_allclose(x.grad.asnumpy(), [2.0, 2.0])


def test_explicit_head_grad():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward(nd.array([10.0, 100.0]))
    np.testing.assert_allclose(x.grad.asnumpy(), [20.0, 400.0])


def test_grad_req_add_and_null():
    x = nd.array([1.0, 2.0])
    x.attach_grad(grad_req="add")
    for _ in range(2):
        with autograd.record():
            y = (x * x).sum()
        y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [4.0, 8.0])

    z = nd.array([1.0])
    z.attach_grad(grad_req="null")
    with autograd.record():
        w = z * 2
    w.backward()
    np.testing.assert_allclose(z.grad.asnumpy(), [0.0])


def test_pause_scope():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        with autograd.pause():
            c = x * 10.0   # not recorded
        z = y + c.detach()
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [4.0])


def test_training_flags():
    assert not autograd.is_training()
    assert not autograd.is_recording()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
        with autograd.predict_mode():
            assert not autograd.is_training()
    with autograd.train_mode():
        assert autograd.is_training()


def test_autograd_grad_api():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x * x
    (g,) = autograd.grad(y, [x])
    np.testing.assert_allclose(g.asnumpy(), [27.0])
    # .grad untouched by grad()
    np.testing.assert_allclose(x.grad.asnumpy(), [0.0])


def test_shared_subexpression():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x          # y used twice
        z = y + y
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [8.0])


def test_multi_input_op():
    a = nd.array([1.0, 2.0])
    b = nd.array([3.0, 4.0])
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        c = (a * b).sum()
    c.backward()
    np.testing.assert_allclose(a.grad.asnumpy(), [3.0, 4.0])
    np.testing.assert_allclose(b.grad.asnumpy(), [1.0, 2.0])


def test_matmul_grads():
    a = nd.array(np.random.rand(3, 4).astype(np.float32))
    w = nd.array(np.random.rand(4, 2).astype(np.float32))
    w.attach_grad()
    with autograd.record():
        out = nd.dot(a, w).sum()
    out.backward()
    expected = a.asnumpy().T @ np.ones((3, 2), np.float32)
    np.testing.assert_allclose(w.grad.asnumpy(), expected, rtol=1e-5)


def test_dropout_under_record():
    x = nd.ones((100, 100))
    x.attach_grad()
    with autograd.record():
        y = nd.Dropout(x, p=0.5, training=True)
        s = y.sum()
    s.backward()
    g = x.grad.asnumpy()
    # grads are 0 or 2 (1/keep_prob)
    vals = np.unique(g)
    assert set(np.round(vals, 3)).issubset({0.0, 2.0})


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = nd.sigmoid(x)
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self.saved_tensors
            return dy * y * (1 - y)

    f = Sigmoid()
    x = nd.array([0.0, 1.0])
    x.attach_grad()
    with autograd.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    np.testing.assert_allclose(x.grad.asnumpy(), s * (1 - s), rtol=1e-5)


def test_deep_chain_no_recursion_error():
    x = nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = x
        for _ in range(300):
            y = y + 0.01
        z = y * 1.0
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [1.0])


def test_numeric_gradient_checker():
    from mxnet_tpu.test_utils import check_numeric_gradient
    check_numeric_gradient(lambda x: nd.tanh(x), [nd.array([0.1, -0.3, 0.7])])
    check_numeric_gradient(lambda a, b: a * b + nd.exp(a),
                           [nd.array([0.5, 1.0]), nd.array([2.0, -1.0])])


# ---------------------------------------------------------------------------
# higher-order autograd (reference: Imperative::Backward create_graph)
# ---------------------------------------------------------------------------


def test_second_order_grad():
    x = mx.nd.array(np.array([2.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = (x ** 3).sum()
        gx = autograd.grad(y, [x], create_graph=True)[0]   # 3x^2
        assert abs(float(gx.asnumpy()[0]) - 12.0) < 1e-5
        z = (gx ** 2).sum()                                # 9x^4
    z.backward()
    assert abs(float(x.grad.asnumpy()[0]) - 288.0) < 1e-3  # 36x^3


def test_third_order_grad():
    x = mx.nd.array(np.array([1.5], np.float32))
    x.attach_grad()
    with autograd.record():
        f = (x ** 4).sum()
        g1 = autograd.grad(f, [x], create_graph=True)[0]
        g2 = autograd.grad(g1.sum(), [x], create_graph=True)[0]
        g3 = autograd.grad(g2.sum(), [x])[0]
    assert abs(float(g3.asnumpy()[0]) - 36.0) < 1e-3       # 24x


def test_gradient_norm_penalty():
    """The WGAN-GP / sharpness-aware pattern: differentiate a gradient's
    norm back to the weights."""
    w = mx.nd.array(np.array([[0.5, -0.3]], np.float32))
    w.attach_grad()
    x = mx.nd.array(np.array([[1.0, 2.0]], np.float32))
    with autograd.record():
        out = (mx.nd.dot(w, x.T) ** 2).sum()
        gw = autograd.grad(out, [w], create_graph=True)[0]
        gnorm = (gw ** 2).sum()
    gnorm.backward()
    # out=(w.x)^2, gw=2(w.x)x, |gw|^2=4(w.x)^2|x|^2, d/dw=8(w.x)|x|^2 x
    expect = 8 * (-0.1) * 5 * np.array([1.0, 2.0])
    np.testing.assert_allclose(w.grad.asnumpy()[0], expect, rtol=1e-4)


def test_second_order_mixed_ops():
    """exp/sin chain: d2/dx2 exp(sin x) at x0 vs closed form."""
    x0 = 0.7
    x = mx.nd.array(np.array([x0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = mx.nd.exp(mx.nd.sin(x)).sum()
        g1 = autograd.grad(y, [x], create_graph=True)[0]
    g1.backward()
    expect = np.exp(np.sin(x0)) * (np.cos(x0) ** 2 - np.sin(x0))
    np.testing.assert_allclose(x.grad.asnumpy()[0], expect, rtol=1e-4)


def test_create_graph_outside_record_scope():
    """grad(create_graph=True) called after exiting record() must keep
    fan-out cotangent accumulation differentiable (the backward forces its
    own recording scope)."""
    x = mx.nd.array(np.array([2.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = (x * x + x * x).sum()          # fan-out: x feeds two products
    g1 = autograd.grad(y, [x], create_graph=True)[0]   # outside record
    assert abs(float(g1.asnumpy()[0]) - 8.0) < 1e-5    # 4x
    with autograd.record():
        s = g1.sum()
    gg = autograd.grad(s, [x])[0]
    assert abs(float(gg.asnumpy()[0]) - 4.0) < 1e-5    # d/dx 4x
