"""NDArray core tests (reference pattern: tests/python/unittest/test_ndarray.py:
indexing, aliasing views, save/load roundtrip, async/sync surface)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_creation_basics():
    a = nd.zeros((2, 3))
    assert a.shape == (2, 3)
    assert a.dtype == np.float32
    assert a.context == mx.cpu()
    b = nd.ones((4,), dtype="int32")
    assert b.dtype == np.int32
    c = nd.array([[1, 2], [3, 4]])
    assert c.dtype == np.float32  # python lists default to f32 like reference
    np.testing.assert_array_equal(c.asnumpy(), [[1, 2], [3, 4]])
    d = nd.full((2, 2), 7.5)
    assert d.asnumpy().ravel().tolist() == [7.5] * 4
    e = nd.arange(0, 10, 2)
    np.testing.assert_array_equal(e.asnumpy(), [0, 2, 4, 6, 8])


def test_context_placement():
    t = nd.zeros((2, 2), ctx=mx.tpu(0))
    assert t.context == mx.tpu(0)
    h = t.as_in_context(mx.cpu())
    assert h.context == mx.cpu()
    np.testing.assert_array_equal(h.asnumpy(), t.asnumpy())
    # gpu aliases the accelerator
    g = nd.ones((2,), ctx=mx.gpu(0))
    assert g.context == mx.tpu(0)


def test_arithmetic_and_broadcast():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([10.0, 20.0])
    np.testing.assert_allclose((a + b).asnumpy(), [[11, 22], [13, 24]])
    np.testing.assert_allclose((a * 2).asnumpy(), [[2, 4], [6, 8]])
    np.testing.assert_allclose((2 * a).asnumpy(), [[2, 4], [6, 8]])
    np.testing.assert_allclose((1.0 / a).asnumpy(), 1.0 / a.asnumpy())
    np.testing.assert_allclose((a - b).asnumpy(), [[-9, -18], [-7, -16]])
    np.testing.assert_allclose((a ** 2).asnumpy(), [[1, 4], [9, 16]])
    np.testing.assert_allclose((-a).asnumpy(), -a.asnumpy())
    assert float((a == a).asnumpy().sum()) == 4.0


def test_inplace_ops():
    a = nd.ones((3,))
    a += 2
    np.testing.assert_array_equal(a.asnumpy(), [3, 3, 3])
    a *= 2
    np.testing.assert_array_equal(a.asnumpy(), [6, 6, 6])
    a /= 3
    np.testing.assert_array_equal(a.asnumpy(), [2, 2, 2])


def test_setitem_full_and_partial():
    a = nd.zeros((3, 4))
    a[:] = 5
    assert (a.asnumpy() == 5).all()
    a[1] = 7
    np.testing.assert_array_equal(a.asnumpy()[1], [7, 7, 7, 7])
    a[0, 2] = -1
    assert a.asnumpy()[0, 2] == -1
    a[:, 1] = nd.array([9.0, 9.0, 9.0])
    np.testing.assert_array_equal(a.asnumpy()[:, 1], [9, 9, 9])


def test_slice_is_view():
    """MXNet slices are views: writes go through to the base."""
    a = nd.zeros((4, 4))
    v = a[1:3]
    v[:] = 3.0
    expected = np.zeros((4, 4))
    expected[1:3] = 3.0
    np.testing.assert_array_equal(a.asnumpy(), expected)
    # chained views compose
    v2 = v[0]
    v2[:] = 5.0
    expected[1] = 5.0
    np.testing.assert_array_equal(a.asnumpy(), expected)
    # view reads see base updates
    a[:] = 1.0
    np.testing.assert_array_equal(v.asnumpy(), np.ones((2, 4)))


def test_reshape_view_writes_through():
    a = nd.zeros((2, 6))
    r = a.reshape((3, 4))
    r[:] = 2.0
    np.testing.assert_array_equal(a.asnumpy(), np.full((2, 6), 2.0))
    r2 = a.reshape((-1,))
    assert r2.shape == (12,)
    r3 = a.reshape((0, 3, 2))
    assert r3.shape == (2, 3, 2)


def test_advanced_indexing_is_copy():
    a = nd.array(np.arange(12).reshape(3, 4))
    idx = nd.array([0, 2], dtype="int32")
    picked = a[idx]
    np.testing.assert_array_equal(picked.asnumpy(), a.asnumpy()[[0, 2]])
    picked[:] = -1
    assert (a.asnumpy() >= 0).all()  # base untouched


def test_negative_strides_and_steps():
    a = nd.array(np.arange(10, dtype=np.float32))
    np.testing.assert_array_equal(a[::2].asnumpy(), np.arange(0, 10, 2))
    np.testing.assert_array_equal(a[8:2:-2].asnumpy(), [8, 6, 4])


def test_scalar_conversions():
    a = nd.array([3.5])
    assert float(a) == 3.5
    assert a.asscalar() == 3.5
    b = nd.array([[2]], dtype="int32")
    assert int(b) == 2
    with pytest.raises(ValueError):
        nd.zeros((2, 2)).asscalar()


def test_copy_semantics():
    a = nd.ones((2, 2))
    b = a.copy()
    b[:] = 0
    assert (a.asnumpy() == 1).all()
    c = nd.zeros((2, 2))
    a.copyto(c)
    assert (c.asnumpy() == 1).all()


def test_astype():
    a = nd.array([1.5, 2.5])
    b = a.astype("int32")
    assert b.dtype == np.int32
    c = a.astype("bfloat16")
    assert str(c.dtype) == "bfloat16"
    d = c.astype("float32")
    np.testing.assert_allclose(d.asnumpy(), [1.5, 2.5])


def test_save_load_roundtrip(tmp_path):
    f = str(tmp_path / "x.params")
    a = nd.array(np.random.rand(3, 4).astype(np.float32))
    b = nd.array(np.arange(5), dtype="int64")
    nd.save(f, {"a": a, "b": b})
    loaded = nd.load(f)
    assert set(loaded.keys()) == {"a", "b"}
    np.testing.assert_array_equal(loaded["a"].asnumpy(), a.asnumpy())
    np.testing.assert_array_equal(loaded["b"].asnumpy(), b.asnumpy())
    # int64 narrows to int32 on the no-x64 TPU path (like the reference's
    # default 32-bit index build); dtype must round-trip consistently
    assert loaded["b"].dtype == b.dtype
    # list format
    nd.save(f, [a, b])
    lst = nd.load(f)
    assert isinstance(lst, list) and len(lst) == 2
    np.testing.assert_array_equal(lst[0].asnumpy(), a.asnumpy())


def test_save_load_bfloat16(tmp_path):
    f = str(tmp_path / "bf.params")
    a = nd.array([1.0, 2.0, 3.0]).astype("bfloat16")
    nd.save(f, {"w": a})
    back = nd.load(f)["w"]
    assert str(back.dtype) == "bfloat16"
    np.testing.assert_allclose(back.astype("float32").asnumpy(), [1, 2, 3])


def test_wait_and_sync():
    a = nd.ones((16, 16), ctx=mx.tpu(0))
    b = nd.dot(a, a)
    b.wait_to_read()
    nd.waitall()
    assert (b.asnumpy() == 16).all()


def test_naive_engine_mode():
    with mx.environment("MXNET_ENGINE_TYPE", "NaiveEngine"):
        assert mx.engine.is_naive()
        a = nd.ones((4,)) * 3
        np.testing.assert_array_equal(a.asnumpy(), [3, 3, 3, 3])
    assert not mx.engine.is_naive()


def test_method_forms():
    a = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    assert a.sum().asscalar() == 15
    np.testing.assert_allclose(a.mean(axis=1).asnumpy(), [1.0, 4.0])
    assert a.max().asscalar() == 5
    assert a.T.shape == (3, 2)
    assert a.flatten().shape == (2, 3)
    assert a.expand_dims(0).shape == (1, 2, 3)
    np.testing.assert_allclose(a.clip(1, 4).asnumpy(),
                               np.clip(a.asnumpy(), 1, 4))


def test_dlpack_interop():
    import jax.numpy as jnp
    a = nd.array([1.0, 2.0])
    j = jnp.asarray(np.from_dlpack(a))
    np.testing.assert_array_equal(np.asarray(j), [1, 2])


def test_positional_attr_convention():
    """Classic-API positional attrs: a plain value in a defaulted kernel
    slot is an attr (nd.expand_dims(x, 0), nd.one_hot(i, depth),
    nd.reshape(x, shape)); defaultless slots keep scalars as array
    operands (broadcast_add(x, 1.5))."""
    x = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    assert nd.reshape(x, (3, 2)).shape == (3, 2)
    assert nd.tile(x, (2, 1)).shape == (4, 3)
    assert nd.repeat(x, 2).shape == (12,)
    assert nd.expand_dims(x, 0).shape == (1, 2, 3)
    assert nd.one_hot(nd.array(np.array([0, 2], np.float32)), 3) \
        .shape == (2, 3)
    np.testing.assert_allclose(nd.flip(x, 1).asnumpy()[0], [2, 1, 0])
    from mxnet_tpu.ndarray.ndarray import invoke
    np.testing.assert_allclose(
        invoke("broadcast_add", x, 1.5).asnumpy()[0], [1.5, 2.5, 3.5])
    # symbol side follows the same convention
    s = mx.sym.Variable("x")
    e = mx.sym.reshape(mx.sym.expand_dims(s, 0), (3, 2))
    exe = e.simple_bind(mx.cpu(), x=(2, 3))
    exe.arg_dict["x"][:] = x
    assert exe.forward()[0].shape == (3, 2)


def test_classic_idiom_battery():
    """The positional idioms every v1.x codebase uses, in one net."""
    x = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    assert nd.transpose(x, (1, 0)).shape == (3, 2)
    assert nd.swapaxes(x, 0, 1).shape == (3, 2)
    assert (nd.clip(x, 1, 4).asnumpy() <= 4).all()
    assert len(nd.split(x, 3)) == 3
    assert nd.concat(x, x, dim=0).shape == (4, 3)
    assert nd.dot(x, x, True).shape == (3, 3)
    assert nd.sum(x, 1).shape == (2,)
    assert nd.argmax(x, 1).shape == (2,)
    assert nd.slice_axis(x, 1, 0, 2).shape == (2, 2)
    assert nd.squeeze(nd.expand_dims(x, 0), 0).shape == (2, 3)
    assert nd.stack(x, x, axis=0).shape == (2, 2, 3)
    assert nd.broadcast_axis(nd.expand_dims(x, 0), 0, 4).shape \
        == (4, 2, 3)
    assert nd.cast(x, "int32").dtype == np.int32
    np.testing.assert_allclose(
        nd.one_hot(nd.array(np.array([0, 2], np.float32)), 3,
                   on_value=5, off_value=-1).asnumpy()[0], [5, -1, -1])
    np.testing.assert_allclose(
        nd.SequenceMask(nd.ones((3, 2)),
                        nd.array(np.array([1, 2], np.float32)), True,
                        value=-9).asnumpy()[:, 0], [1, -9, -9])
    for rt, want in (("indices", (2, 2)), ("value", (2, 2)),
                     ("mask", (2, 3))):
        assert tuple(nd.topk(x, k=2, ret_typ=rt).shape) == want
