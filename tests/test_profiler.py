"""mx.profiler tests.

Reference pattern: tests/python/unittest/test_profiler.py — set_config,
run ops under state 'run', dump a trace file with named operator events,
check the aggregate stats surface.
"""
import json
import os

import mxnet_tpu as mx
from mxnet_tpu import profiler


def teardown_function(_fn):
    profiler.set_state("stop")
    profiler.reset()


def test_profile_ops_dump_and_stats(tmp_path):
    trace = str(tmp_path / "profile.json")
    profiler.set_config(filename=trace, profile_imperative=True,
                        aggregate_stats=True)
    profiler.set_state("run")
    a = mx.nd.ones((8, 8))
    b = mx.nd.ones((8, 8))
    for _ in range(3):
        c = mx.nd.dot(a, b)
    c.wait_to_read()
    profiler.set_state("stop")
    profiler.dump()

    with open(trace) as f:
        payload = json.load(f)
    names = {e["name"] for e in payload["traceEvents"]}
    assert "dot" in names
    dot_events = [e for e in payload["traceEvents"] if e["name"] == "dot"]
    assert len(dot_events) == 3
    assert all(e["ph"] == "X" and e["cat"] == "operator" for e in dot_events)

    table = profiler.dumps()
    assert "Profile Statistics" in table and "dot" in table
    stats = json.loads(profiler.dumps(format="json"))
    assert stats["dot"]["count"] == 3
    assert stats["dot"]["total_us"] > 0


def test_profiler_off_collects_nothing(tmp_path):
    profiler.set_config(filename=str(tmp_path / "p.json"),
                        profile_imperative=True)
    x = mx.nd.ones((4,)) + 1  # profiler stopped
    x.wait_to_read()
    assert profiler.dumps(format="json") == "{}"


def test_pause_resume(tmp_path):
    profiler.set_config(filename=str(tmp_path / "p.json"),
                        profile_imperative=True, aggregate_stats=True)
    profiler.set_state("run")
    mx.nd.ones((4,)).wait_to_read()
    n_running = json.loads(profiler.dumps(format="json"))
    profiler.pause()
    _ = mx.nd.ones((4,)) * 2
    mx.nd.waitall()
    n_paused = json.loads(profiler.dumps(format="json"))
    assert n_paused.keys() == n_running.keys()  # nothing new while paused
    profiler.resume()
    _ = mx.nd.ones((4,)) * 2
    mx.nd.waitall()
    assert "broadcast_mul" in json.loads(profiler.dumps(format="json"))
    profiler.set_state("stop")


def test_task_event_counter_marker(tmp_path):
    trace = str(tmp_path / "instr.json")
    profiler.set_config(filename=trace)
    profiler.set_state("run")
    with profiler.Task(name="epoch0"):
        pass
    ev = profiler.Event("fwd")
    ev.start()
    ev.stop()
    ctr = profiler.Counter(name="samples", value=0)
    ctr += 5
    ctr.decrement(2)
    profiler.Marker(name="tick").mark()
    profiler.set_state("stop")
    profiler.dump()
    with open(trace) as f:
        events = json.load(f)["traceEvents"]
    names = {e["name"] for e in events}
    assert {"epoch0", "fwd", "samples", "tick"} <= names
    counter_vals = [e["args"]["value"] for e in events
                    if e["name"] == "samples"]
    assert counter_vals == [0, 5, 3]


def test_scope_in_jit_and_eager():
    # eager: the scope span is recorded; in-jit: jax.named_scope must not crash
    profiler.set_config(aggregate_stats=True)
    profiler.set_state("run")
    with profiler.scope("my_phase"):
        y = mx.nd.ones((4,)) + 1
    y.wait_to_read()
    profiler.set_state("stop")
    assert "my_phase" in json.loads(profiler.dumps(format="json"))
