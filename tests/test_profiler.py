"""mx.profiler tests.

Reference pattern: tests/python/unittest/test_profiler.py — set_config,
run ops under state 'run', dump a trace file with named operator events,
check the aggregate stats surface.
"""
import json
import os

import mxnet_tpu as mx
from mxnet_tpu import profiler


def teardown_function(_fn):
    profiler.set_state("stop")
    profiler.reset()


def test_profile_ops_dump_and_stats(tmp_path):
    trace = str(tmp_path / "profile.json")
    profiler.set_config(filename=trace, profile_imperative=True,
                        aggregate_stats=True)
    profiler.set_state("run")
    a = mx.nd.ones((8, 8))
    b = mx.nd.ones((8, 8))
    for _ in range(3):
        c = mx.nd.dot(a, b)
    c.wait_to_read()
    profiler.set_state("stop")
    profiler.dump()

    with open(trace) as f:
        payload = json.load(f)
    names = {e["name"] for e in payload["traceEvents"]}
    assert "dot" in names
    dot_events = [e for e in payload["traceEvents"] if e["name"] == "dot"]
    assert len(dot_events) == 3
    assert all(e["ph"] == "X" and e["cat"] == "operator" for e in dot_events)

    table = profiler.dumps()
    assert "Profile Statistics" in table and "dot" in table
    stats = json.loads(profiler.dumps(format="json"))
    assert stats["dot"]["count"] == 3
    assert stats["dot"]["total_us"] > 0


def test_profiler_off_collects_nothing(tmp_path):
    profiler.set_config(filename=str(tmp_path / "p.json"),
                        profile_imperative=True)
    x = mx.nd.ones((4,)) + 1  # profiler stopped
    x.wait_to_read()
    assert profiler.dumps(format="json") == "{}"


def test_pause_resume(tmp_path):
    profiler.set_config(filename=str(tmp_path / "p.json"),
                        profile_imperative=True, aggregate_stats=True)
    profiler.set_state("run")
    mx.nd.ones((4,)).wait_to_read()
    n_running = json.loads(profiler.dumps(format="json"))
    profiler.pause()
    _ = mx.nd.ones((4,)) * 2
    mx.nd.waitall()
    n_paused = json.loads(profiler.dumps(format="json"))
    assert n_paused.keys() == n_running.keys()  # nothing new while paused
    profiler.resume()
    _ = mx.nd.ones((4,)) * 2
    mx.nd.waitall()
    assert "broadcast_mul" in json.loads(profiler.dumps(format="json"))
    profiler.set_state("stop")


def test_task_event_counter_marker(tmp_path):
    trace = str(tmp_path / "instr.json")
    profiler.set_config(filename=trace)
    profiler.set_state("run")
    with profiler.Task(name="epoch0"):
        pass
    ev = profiler.Event("fwd")
    ev.start()
    ev.stop()
    ctr = profiler.Counter(name="samples", value=0)
    ctr += 5
    ctr.decrement(2)
    profiler.Marker(name="tick").mark()
    profiler.set_state("stop")
    profiler.dump()
    with open(trace) as f:
        events = json.load(f)["traceEvents"]
    names = {e["name"] for e in events}
    assert {"epoch0", "fwd", "samples", "tick"} <= names
    counter_vals = [e["args"]["value"] for e in events
                    if e["name"] == "samples"]
    assert counter_vals == [0, 5, 3]


def test_scope_in_jit_and_eager():
    # eager: the scope span is recorded; in-jit: jax.named_scope must not crash
    profiler.set_config(aggregate_stats=True)
    profiler.set_state("run")
    with profiler.scope("my_phase"):
        y = mx.nd.ones((4,)) + 1
    y.wait_to_read()
    profiler.set_state("stop")
    assert "my_phase" in json.loads(profiler.dumps(format="json"))


# -- TB SummaryWriter (mxboard role; SURVEY §5.5) ----------------------------

def test_summary_writer_roundtrip(tmp_path):
    """Scalars/histograms/text written in real TFRecord+Event wire format
    (masked crc32c verified on read-back)."""
    import numpy as np
    from mxnet_tpu.contrib.summary import SummaryWriter, read_events
    import mxnet_tpu as mx

    logdir = str(tmp_path / "logs")
    with SummaryWriter(logdir) as sw:
        sw.add_scalar("loss", 0.75, 1)
        sw.add_scalar("loss", mx.nd.array([0.5]).reshape(()), 2)
        sw.add_histogram("w", np.random.RandomState(0).randn(256), 2)
        sw.add_text("note", "round-4", 3)
        path = sw._path
    events = read_events(path)
    by_tag = {}
    for step, tag, payload in events:
        by_tag.setdefault(tag, []).append((step, payload))
    assert by_tag["loss"][0] == (1, ("scalar", 0.75))
    assert by_tag["loss"][1][0] == 2
    assert abs(by_tag["loss"][1][1][1] - 0.5) < 1e-6
    assert by_tag["w"][0][1][0] == "histo"
    assert by_tag["note"][0][1][0] == "text"


def test_summary_writer_crc_detects_corruption(tmp_path):
    from mxnet_tpu.contrib.summary import SummaryWriter, read_events
    with SummaryWriter(str(tmp_path)) as sw:
        sw.add_scalar("x", 1.0, 0)
        path = sw._path
    data = bytearray(open(path, "rb").read())
    data[-3] ^= 0xFF                      # flip a payload byte
    open(path, "wb").write(bytes(data))
    import pytest
    with pytest.raises(ValueError, match="crc"):
        read_events(path)
