"""mx.model.FeedForward — the deprecated v1.x estimator veneer
(reference: python/mxnet/model.py class FeedForward; test pattern:
tests/python/unittest/test_model* and the classic MNIST mlp script)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _mlp():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data=data, num_hidden=32, name="fc1")
    h = mx.sym.Activation(data=h, act_type="relu", name="relu1")
    h = mx.sym.FullyConnected(data=h, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(data=h, name="softmax")


def _toy(n=256, d=16, k=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    W = rng.randn(d, k).astype(np.float32)
    Y = (X @ W).argmax(1).astype(np.float32)
    return X, Y


def test_feedforward_classic_script_runs_unmodified():
    """The exact v1.x idiom: construct with optimizer kwargs, fit on numpy
    arrays, predict returns numpy, score returns a scalar."""
    X, Y = _toy()
    with pytest.warns(DeprecationWarning):
        model = mx.model.FeedForward(
            symbol=_mlp(), num_epoch=8, learning_rate=0.2, momentum=0.9,
            numpy_batch_size=64)
    model.fit(X=X, y=Y)
    preds = model.predict(X)
    assert isinstance(preds, np.ndarray) and preds.shape == (256, 4)
    # both classic idioms: score(X, y) on arrays and score(val_iter)
    acc = model.score(X, Y, eval_metric="acc")
    assert acc > 0.9, acc
    val = mx.io.NDArrayIter(X, Y, batch_size=64,
                            label_name="softmax_label")
    assert abs(model.score(val) - acc) < 1e-6
    assert float((preds.argmax(1) == Y).mean()) > 0.9


def test_feedforward_eval_data_and_dataiter_input():
    X, Y = _toy()
    it = mx.io.NDArrayIter(X, Y, batch_size=64, shuffle=True,
                           label_name="softmax_label")
    val = mx.io.NDArrayIter(X, Y, batch_size=64,
                            label_name="softmax_label")
    model = mx.model.FeedForward(symbol=_mlp(), num_epoch=10,
                                 learning_rate=0.2, momentum=0.9)
    model.fit(X=it, eval_data=val, eval_metric="acc")
    assert model.score(val) > 0.85


def test_feedforward_save_load_roundtrip(tmp_path):
    X, Y = _toy()
    model = mx.model.FeedForward(symbol=_mlp(), num_epoch=5,
                                 learning_rate=0.2)
    model.fit(X=X, y=Y)
    prefix = str(tmp_path / "ff")
    model.save(prefix)                      # -> ff-symbol.json, ff-0005.params
    loaded = mx.model.FeedForward.load(prefix, 5)
    np.testing.assert_allclose(loaded.predict(X), model.predict(X),
                               rtol=1e-5, atol=1e-6)
    # and the artifact interchanges with the Module checkpoint reader
    sym2, args2, aux2 = mx.model.load_checkpoint(prefix, 5)
    assert "fc1_weight" in args2


def test_feedforward_create_and_predict_with_return_data():
    X, Y = _toy()
    model = mx.model.FeedForward.create(
        symbol=_mlp(), X=X, y=Y, num_epoch=5, learning_rate=0.2)
    preds, data_np, label_np = model.predict(X, return_data=True)
    assert data_np.shape == X.shape
    assert preds.shape[0] == X.shape[0]


def test_feedforward_predict_before_fit_requires_params():
    model = mx.model.FeedForward(symbol=_mlp())
    with pytest.raises(AssertionError):
        model.predict(np.zeros((4, 16), np.float32))


def test_feedforward_epoch_size_and_eval_callbacks():
    """epoch_size bounds batches/epoch (streaming-iter contract) and
    eval_end fires ONCE per evaluation while eval_batch_end fires per
    eval batch (reference BaseModule.fit contract)."""
    X, Y = _toy(n=256)
    seen_batches, eval_ends, eval_batches = [], [], []
    model = mx.model.FeedForward(symbol=_mlp(), num_epoch=3,
                                 learning_rate=0.1, epoch_size=2,
                                 numpy_batch_size=32)
    model.fit(
        X=X, y=Y, eval_data=(X[:64], Y[:64]),
        batch_end_callback=lambda p: seen_batches.append(p.nbatch),
        eval_end_callback=lambda p: eval_ends.append(p.epoch),
        eval_batch_end_callback=lambda p: eval_batches.append(p.nbatch))
    # 3 epochs x epoch_size=2 batches
    assert len(seen_batches) == 6, seen_batches
    assert eval_ends == [0, 1, 2], eval_ends
    # eval set: 64 rows / 32 batch = 2 eval batches per epoch
    assert len(eval_batches) == 6, eval_batches
