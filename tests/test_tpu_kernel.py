"""mx.tpu_kernel: user Pallas kernels — launch, decorator, op registration
with autograd (reference: tests/python/gpu/test_rtc.py pattern, rebuilt for
the Pallas RTC equivalent). Runs in interpret mode on the CPU test mesh."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def test_kernel_launch():
    def axpy(a_ref, x_ref, y_ref, o_ref):
        o_ref[...] = a_ref[...] * x_ref[...] + y_ref[...]

    k = mx.tpu_kernel.Kernel(axpy)
    a = nd.full((8, 128), 2.0)
    x = nd.array(np.arange(8 * 128, dtype=np.float32).reshape(8, 128))
    y = nd.ones((8, 128))
    out = k.launch([a, x, y], out_shape=(8, 128))
    np.testing.assert_allclose(out.asnumpy(),
                               2.0 * x.asnumpy() + 1.0, rtol=1e-6)


def test_kernel_decorator_and_call():
    @mx.tpu_kernel.kernel()
    def double(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0

    x = nd.array(np.random.RandomState(0).randn(4, 128).astype(np.float32))
    out = double(x, out_shape=(4, 128))
    np.testing.assert_allclose(out.asnumpy(), 2 * x.asnumpy(), rtol=1e-6)


def test_kernel_gridded():
    import jax.experimental.pallas as pl

    @mx.tpu_kernel.kernel(grid=(2,),
                          in_specs=[pl.BlockSpec((4, 128), lambda i: (i, 0))],
                          out_specs=pl.BlockSpec((4, 128), lambda i: (i, 0)))
    def relu_blocked(x_ref, o_ref):
        o_ref[...] = np.maximum(x_ref[...], 0.0) if isinstance(
            x_ref[...], np.ndarray) else x_ref[...].clip(0.0)

    x = nd.array(np.random.RandomState(1).randn(8, 128).astype(np.float32))
    out = relu_blocked(x, out_shape=(8, 128))
    np.testing.assert_allclose(out.asnumpy(), np.maximum(x.asnumpy(), 0),
                               rtol=1e-6)


def test_registered_op_with_grad():
    @mx.tpu_kernel.register(
        "pallas_square",
        out_shape_fn=lambda x: x,
        grad=lambda cts, x: (cts[0] * 2.0 * x,))
    def square_kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] * x_ref[...]

    xv = np.array([1.0, -2.0, 3.0], np.float32)
    x = nd.array(xv)
    out = nd.pallas_square(x)
    np.testing.assert_allclose(out.asnumpy(), xv * xv, rtol=1e-6)

    x.attach_grad()
    with autograd.record():
        y = nd.pallas_square(x)
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 2 * xv, rtol=1e-6)


def test_registered_op_in_hybridize():
    @mx.tpu_kernel.register(
        "pallas_scale3", out_shape_fn=lambda x: x,
        grad=lambda cts, x: (cts[0] * 3.0,))
    def scale3(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 3.0

    class Net(mx.gluon.HybridBlock):
        def forward(self, x):
            return nd.pallas_scale3(x)

    net = Net()
    net.hybridize()
    xv = np.random.RandomState(2).randn(2, 5).astype(np.float32)
    x = nd.array(xv)
    x.attach_grad()
    with autograd.record():
        y = net(x)
    y.backward()
    np.testing.assert_allclose(y.asnumpy(), 3 * xv, rtol=1e-6)
    np.testing.assert_allclose(x.grad.asnumpy(), np.full_like(xv, 3.0))


def test_reregistration_evicts_jit_cache():
    def make(mult):
        @mx.tpu_kernel.register("pallas_mul_iter", out_shape_fn=lambda x: x)
        def mul_kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...] * mult
        return mul_kernel

    x = nd.array(np.array([1.0, 2.0], np.float32))
    make(2.0)
    np.testing.assert_allclose(nd.pallas_mul_iter(x).asnumpy(), [2.0, 4.0])
    make(5.0)  # notebook iteration: same name, new body
    np.testing.assert_allclose(nd.pallas_mul_iter(x).asnumpy(), [5.0, 10.0])


def test_nondiff_registered_op_refuses_grad():
    @mx.tpu_kernel.register("pallas_sign_nd", out_shape_fn=lambda x: x)
    def sign_kernel(x_ref, o_ref):
        o_ref[...] = (x_ref[...] > 0).astype(x_ref[...].dtype)

    x = nd.array(np.array([1.0, -1.0], np.float32))
    out = nd.pallas_sign_nd(x)
    np.testing.assert_allclose(out.asnumpy(), [1.0, 0.0])
