"""Symbol API tests.

Reference patterns: tests/python/unittest/test_symbol.py (compose, json
roundtrip, infer_shape), test_gluon.py export/imports roundtrips, and the
Executor surface of python/mxnet/executor.py.
"""
import json

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.block import SymbolBlock


def test_compose_eval():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = 2.0 * a + b / 2.0
    out = c.eval(a=nd.array([1.0, 2.0]), b=nd.array([10.0, 20.0]))
    np.testing.assert_allclose(out[0].asnumpy(), [7.0, 14.0])


def test_op_namespace_mirrors_nd():
    x = sym.Variable("x")
    y = sym.relu(sym.dot(x, x))
    v = nd.array([[1.0, -2.0], [3.0, 4.0]])
    out = y.eval(x=v)[0]
    expect = np.maximum(v.asnumpy() @ v.asnumpy(), 0)
    np.testing.assert_allclose(out.asnumpy(), expect, rtol=1e-6)


def test_json_roundtrip():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = sym.broadcast_mul(sym.broadcast_add(a, b), a, name="prod")
    js = c.tojson()
    g = json.loads(js)
    assert {n["op"] for n in g["nodes"]} == {"null", "broadcast_add",
                                            "broadcast_mul"}
    assert g["heads"] and g["arg_nodes"] == [0, 1]
    assert g["node_row_ptr"][-1] == len(g["nodes"])
    c2 = sym.loads(js)
    assert c2.list_arguments() == ["a", "b"]
    va, vb = nd.array([2.0]), nd.array([3.0])
    np.testing.assert_allclose(c2.eval(a=va, b=vb)[0].asnumpy(), [10.0])


def test_save_load_file(tmp_path):
    a = sym.Variable("a")
    s = sym.exp(a)
    f = str(tmp_path / "s.json")
    s.save(f)
    s2 = mx.symbol.load(f)
    np.testing.assert_allclose(
        s2.eval(a=nd.array([0.0, 1.0]))[0].asnumpy(),
        np.exp([0.0, 1.0]), rtol=1e-6)


def test_infer_shape_and_type():
    d = sym.Variable("data")
    w = sym.Variable("w")
    o = sym.dot(d, w)
    arg_shapes, out_shapes, aux_shapes = o.infer_shape(data=(4, 3), w=(3, 7))
    assert arg_shapes == [(4, 3), (3, 7)]
    assert out_shapes == [(4, 7)]
    assert aux_shapes == []


def test_group_and_internals():
    a = sym.Variable("a")
    b = sym.sigmoid(a)
    c = sym.tanh(a)
    g = sym.Group([b, c])
    assert len(g) == 2
    outs = g.eval(a=nd.array([0.0]))
    assert len(outs) == 2
    np.testing.assert_allclose(outs[0].asnumpy(), [0.5])
    internals = b.get_internals()
    assert "a" in internals.list_outputs()[0] or \
        "a" in [s.name for s in internals]


def test_scalar_const_nodes():
    a = sym.Variable("a")
    c = (a + 1.5) * 2.0
    js = c.tojson()
    assert "_const" in js
    out = sym.loads(js).eval(a=nd.array([1.0]))[0]
    np.testing.assert_allclose(out.asnumpy(), [5.0])


def test_export_imports_dense(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    x = nd.random.normal(shape=(3, 8))
    y0 = net(x)
    prefix = str(tmp_path / "dense")
    sf, pf = net.export(prefix)
    sb = SymbolBlock.imports(sf, ["data"], pf)
    y1 = sb(x)
    np.testing.assert_allclose(y0.asnumpy(), y1.asnumpy(),
                               rtol=1e-5, atol=1e-5)


def test_export_imports_conv_bn(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1), nn.BatchNorm(),
            nn.Activation("relu"), nn.MaxPool2D(), nn.Flatten(),
            nn.Dense(10))
    net.initialize(mx.init.Xavier())
    x = nd.random.normal(shape=(2, 3, 16, 16))
    y0 = net(x)
    prefix = str(tmp_path / "conv")
    sf, pf = net.export(prefix)
    loaded = mx.symbol.load(sf)
    assert loaded.list_auxiliary_states() == ["1.running_mean",
                                              "1.running_var"]
    assert "data" in loaded.list_arguments()
    sb = SymbolBlock.imports(sf, ["data"], pf)
    y1 = sb(x)
    np.testing.assert_allclose(y0.asnumpy(), y1.asnumpy(),
                               rtol=1e-5, atol=1e-5)


def test_export_requires_forward(tmp_path):
    net = nn.Dense(4)
    net.initialize()
    with pytest.raises(mx.MXNetError):
        net.export(str(tmp_path / "nofwd"))


def test_executor_forward_backward():
    d = sym.Variable("data")
    w = sym.Variable("w")
    o = sym.sum(sym.dot(d, w))
    exe = o.simple_bind(mx.cpu(), data=(4, 3), w=(3, 2))
    dv = np.random.randn(4, 3).astype(np.float32)
    wv = np.random.randn(3, 2).astype(np.float32)
    exe.copy_params_from({"data": nd.array(dv), "w": nd.array(wv)})
    outs = exe.forward(is_train=True)
    np.testing.assert_allclose(outs[0].asnumpy(), (dv @ wv).sum(),
                               rtol=1e-5)
    exe.backward()
    # d sum(d@w)/dw = d^T @ ones
    np.testing.assert_allclose(exe.grad_dict["w"].asnumpy(),
                               dv.T @ np.ones((4, 2), np.float32),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(exe.grad_dict["data"].asnumpy(),
                               np.ones((4, 2), np.float32) @ wv.T,
                               rtol=1e-5, atol=1e-5)


def test_symbolblock_forward_is_hybridizable(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="tanh"), nn.Dense(2))
    net.initialize()
    x = nd.random.normal(shape=(2, 4))
    y0 = net(x)
    prefix = str(tmp_path / "hyb")
    sf, pf = net.export(prefix)
    sb = SymbolBlock.imports(sf, ["data"], pf)
    y1 = sb(x)
    y2 = sb(x)  # second call: cached path
    np.testing.assert_allclose(y0.asnumpy(), y1.asnumpy(), rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(y1.asnumpy(), y2.asnumpy(), rtol=1e-6)


def test_attr_scope_group2ctx_model_parallel():
    """Manual model parallelism (reference: AttrScope(ctx_group=...) +
    bind(group2ctx=...)): layers land on their mapped devices, cross-group
    edges become transfers, and the math matches single-device."""
    import numpy as np
    with mx.AttrScope(ctx_group="dev1"):
        data = sym.Variable("data")
        w1 = sym.Variable("w1")
        h = sym.FullyConnected(data, w1, num_hidden=8, no_bias=True,
                               flatten=False, name="fc1")
        h = sym.Activation(h, act_type="relu", name="act1")
    with mx.AttrScope(ctx_group="dev2"):
        w2 = sym.Variable("w2")
        out = sym.FullyConnected(h, w2, num_hidden=3, no_bias=True,
                                 flatten=False, name="fc2")
    assert out._heads[0][0].attrs.get("__ctx_group__") == "dev2"

    rng = np.random.RandomState(0)
    vals = {"data": mx.nd.array(rng.randn(2, 4).astype(np.float32)),
            "w1": mx.nd.array(rng.randn(8, 4).astype(np.float32)),
            "w2": mx.nd.array(rng.randn(3, 8).astype(np.float32))}
    # single-device reference
    want = out.bind(mx.cpu(0), dict(vals)).forward()[0].asnumpy()
    # split across two (fake-mesh) devices
    g2c = {"dev1": mx.cpu(0), "dev2": mx.cpu(1)}
    exe = out.bind(mx.cpu(0), dict(vals), group2ctx=g2c)
    got = exe.forward()[0]
    assert got.context == mx.cpu(1)          # fc2 ran on its group device
    np.testing.assert_allclose(got.asnumpy(), want, rtol=1e-5)
    # attrs survive symbol.json round-trip
    reloaded = sym.loads(out.tojson())
    node_attrs = reloaded.attr_dict()
    assert node_attrs["fc1"]["__ctx_group__"] == "dev1"
    assert node_attrs["fc2"]["__ctx_group__"] == "dev2"


def test_name_manager_and_prefix():
    """mx.name.NameManager / Prefix scope auto-generated symbol names
    (reference: python/mxnet/name.py; test_symbol name-scoping pattern)."""
    import mxnet_tpu as mx
    from mxnet_tpu import sym

    with mx.name.NameManager():              # fresh counter scope
        a = sym.relu(sym.Variable("x"))
        assert a.name == "relu0"
        b = sym.relu(sym.Variable("y"))
        assert b.name == "relu1"
        with mx.name.Prefix("stage1_"):
            c = sym.relu(sym.Variable("z"))
            assert c.name.startswith("stage1_relu")
        d = sym.relu(sym.Variable("w"))      # prefix scope popped
        assert d.name == "relu2"
    # explicit names pass through untouched
    e = sym.relu(sym.Variable("x"), name="myrelu")
    assert e.name == "myrelu"


def test_symbolblock_imports_classic_autovar_net():
    """A classic symbol built with keyword inputs + auto-created params
    round-trips through symbol.json into gluon.SymbolBlock and matches
    the executor numerics."""
    import os
    import tempfile

    import numpy as onp

    from mxnet_tpu import gluon

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data=data, num_hidden=8, name="fc1")
    net = mx.sym.Activation(data=net, act_type="relu")
    net = mx.sym.FullyConnected(data=net, num_hidden=3, name="fc2")
    with tempfile.TemporaryDirectory() as d:
        sym_path = os.path.join(d, "net-symbol.json")
        with open(sym_path, "w") as f:
            f.write(net.tojson())
        exe = net.simple_bind(mx.cpu(), data=(2, 5))
        for k in exe.arg_dict:
            if k != "data":
                exe.arg_dict[k][:] = nd.random.normal(
                    shape=exe.arg_dict[k].shape)
        params_path = os.path.join(d, "net-0000.params")
        nd.save(params_path, {"arg:%s" % k: v
                              for k, v in exe.arg_dict.items()
                              if k != "data"})
        sb = gluon.SymbolBlock.imports(sym_path, ["data"], params_path)
        x = nd.random.normal(shape=(2, 5))
        onp.testing.assert_allclose(sb(x).asnumpy(),
                                    exe.forward(data=x)[0].asnumpy(),
                                    atol=1e-5)


def test_infer_shape_partial_and_get_children():
    """Reference Symbol.infer_shape_partial: unreached args/outputs come
    back as () instead of raising; get_children returns the head op's
    direct inputs (None for leaves)."""
    d = sym.Variable("data")
    o = sym.Activation(sym.FullyConnected(d, num_hidden=3, name="pfc"),
                       act_type="relu", name="pact")
    args, outs, _ = o.infer_shape_partial()
    assert args == [(), (), ()] and outs == [()]
    args, outs, _ = o.infer_shape_partial(data=(2, 4))
    assert args == [(2, 4), (3, 4), (3,)] and outs == [(2, 3)]
    # full inference still raises on unknowns
    import pytest
    from mxnet_tpu.base import MXNetError
    with pytest.raises(MXNetError):
        o.infer_shape()
    kids = o.get_children()
    assert kids.list_outputs() == ["pfc_output"]
    assert sym.Variable("x").get_children() is None
    # grandparents: children of children reach the leaf variables
    gk = kids.get_children()
    assert set(gk.list_outputs()) == {"data", "pfc_weight", "pfc_bias"}
