"""SSD-300 end-to-end slice (BASELINE config 4): ImageDetIter, SSD model,
MultiBox loss training descent, VOC mAP metric.

Reference pattern: example/ssd/train.py + tests around
python/mxnet/image/detection.py (ImageDetIter) and GluonCV's VOCMApMetric.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, recordio
from mxnet_tpu.image.detection import (CreateDetAugmenter,
                                       DetHorizontalFlipAug, ImageDetIter)
from mxnet_tpu.gluon.model_zoo.ssd import (SSDMultiBoxLoss, ssd_300_vgg16_voc,
                                           ssd_toy)
from mxnet_tpu.metric import VOC07MApMetric, VOCMApMetric


def _make_det_rec(tmp_path, n=8, edge=64):
    """Synthetic detection .rec: one bright square per image, det-format
    label [header_width=2, obj_width=5, cls, x1, y1, x2, y2]."""
    rng = np.random.RandomState(0)
    prefix = str(tmp_path / "det")
    w = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    boxes = []
    for i in range(n):
        img = np.full((edge, edge, 3), 30, np.uint8)
        bw = rng.randint(edge // 4, edge // 2)
        x0 = rng.randint(0, edge - bw)
        y0 = rng.randint(0, edge - bw)
        img[y0:y0 + bw, x0:x0 + bw] = 220
        box = np.array([x0 / edge, y0 / edge, (x0 + bw) / edge,
                        (y0 + bw) / edge], np.float32)
        boxes.append(box)
        label = np.concatenate([[2, 5, 0], box]).astype(np.float32)
        header = recordio.IRHeader(0, label, i, 0)
        w.write_idx(i, recordio.pack_img(header, img, quality=95))
    w.close()
    return prefix + ".rec", boxes


def test_image_det_iter_shapes_and_labels(tmp_path):
    rec, boxes = _make_det_rec(tmp_path)
    it = ImageDetIter(path_imgrec=rec, data_shape=(3, 32, 32), batch_size=4,
                      aug_list=CreateDetAugmenter((3, 32, 32)))
    descs = it.provide_label
    assert descs[0].shape == (4, 1, 5)
    batch = next(it)
    data = batch.data[0].asnumpy()
    label = batch.label[0].asnumpy()
    assert data.shape == (4, 3, 32, 32)
    assert label.shape == (4, 1, 5)
    # labels survived the resize untouched (normalized coords)
    np.testing.assert_allclose(label[0, 0, 1:5], boxes[0], atol=1e-6)
    assert label[0, 0, 0] == 0.0
    n_batches = 1 + sum(1 for _ in it)
    assert n_batches == 2  # 8 images / 4


def test_det_hflip_moves_boxes():
    aug = DetHorizontalFlipAug(p=1.0)
    img = nd.array(np.arange(4 * 6 * 3).reshape(4, 6, 3).astype(np.uint8))
    label = np.array([[0, 0.1, 0.2, 0.4, 0.6]], np.float32)
    out_img, out_label = aug(img, label)
    np.testing.assert_allclose(out_label[0],
                               [0, 0.6, 0.2, 0.9, 0.6], atol=1e-6)
    np.testing.assert_array_equal(out_img.asnumpy(),
                                  img.asnumpy()[:, ::-1, :])


def test_det_random_crop_keeps_normalized_boxes(tmp_path):
    rec, _ = _make_det_rec(tmp_path)
    it = ImageDetIter(path_imgrec=rec, data_shape=(3, 32, 32), batch_size=8,
                      rand_crop=1.0, rand_pad=1.0, rand_mirror=True, seed=3)
    batch = next(it)
    label = batch.label[0].asnumpy()
    valid = label[label[:, :, 0] >= 0]
    assert valid.size  # augmentation should keep at least some objects
    assert (valid[:, 1:] >= -1e-6).all() and (valid[:, 1:] <= 1 + 1e-6).all()


def test_ssd_toy_trains_on_synthetic_boxes():
    """Config-4 smoke: the joint MultiBox loss must descend on a synthetic
    one-box detection task."""
    mx.random.seed(0)
    rng = np.random.RandomState(0)
    net = ssd_toy(classes=1)
    net.initialize(mx.init.Xavier())
    loss_fn = SSDMultiBoxLoss()
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1, "momentum": 0.9})

    bs, edge = 8, 32
    imgs = np.full((bs, 3, edge, edge), 0.1, np.float32)
    labels = np.full((bs, 1, 5), -1.0, np.float32)
    for b in range(bs):
        bw = rng.randint(edge // 4, edge // 2)
        x0 = rng.randint(0, edge - bw)
        y0 = rng.randint(0, edge - bw)
        imgs[b, :, y0:y0 + bw, x0:x0 + bw] = 1.0
        labels[b, 0] = [0, x0 / edge, y0 / edge, (x0 + bw) / edge,
                        (y0 + bw) / edge]
    x, y = nd.array(imgs), nd.array(labels)

    losses = []
    for step in range(30):
        with autograd.record():
            anchors, cls_preds, box_preds = net(x)
            loc_t, loc_m, cls_t = net.targets(anchors, cls_preds, y)
            L = loss_fn(cls_preds, box_preds, cls_t, loc_t, loc_m)
        L.backward()
        trainer.step(bs)
        losses.append(float(L.asnumpy().item()))
    assert losses[-1] < 0.72 * losses[0], losses


def test_ssd_300_builds_and_runs():
    """The full SSD-300 VGG16 architecture compiles a forward pass and its
    anchor count matches the reference layout (8732 boxes)."""
    mx.random.seed(0)
    net = ssd_300_vgg16_voc(classes=20)
    net.initialize(mx.init.Xavier())
    x = nd.zeros((1, 3, 300, 300))
    anchors, cls_preds, box_preds = net(x)
    assert anchors.shape == (1, 8732, 4), anchors.shape
    assert cls_preds.shape == (1, 8732, 21)
    assert box_preds.shape == (1, 8732 * 4)


def test_voc_map_metric():
    labels = nd.array(np.array(
        [[[0, .1, .1, .4, .4], [1, .5, .5, .9, .9]]], np.float32))
    perfect = nd.array(np.array(
        [[[0, .95, .1, .1, .4, .4], [1, .9, .5, .5, .9, .9]]], np.float32))
    m = VOCMApMetric()
    m.update([labels], [perfect])
    assert m.get()[1] == pytest.approx(1.0)
    # wrong classes -> zero AP everywhere
    swapped = nd.array(np.array(
        [[[1, .95, .1, .1, .4, .4], [0, .9, .5, .5, .9, .9]]], np.float32))
    m2 = VOCMApMetric()
    m2.update([labels], [swapped])
    assert m2.get()[1] == pytest.approx(0.0)
    # one hit one miss, VOC07 11-point
    half = nd.array(np.array(
        [[[0, .95, .1, .1, .4, .4], [1, .9, .0, .0, .2, .2]]], np.float32))
    m3 = VOC07MApMetric()
    m3.update([labels], [half])
    name, val = m3.get()
    assert 0.0 < val < 1.0
    assert name == "mAP07"
    # metric.create resolves by name
    from mxnet_tpu import metric as metric_mod
    assert isinstance(metric_mod.create("vocmapmetric"), VOCMApMetric)
