"""Parallel-layer tests on the fake 8-device CPU mesh.

Reference patterns: tests/nightly/dist_sync_kvstore.py (exact-integer
payload reduces), SURVEY.md §4.5 (xla_force_host_platform_device_count
fake-mesh testing of kvstore='ici'/shard_map logic).

Key invariant exercised throughout: sharding annotations NEVER change
semantics — a dp- or dp×tp-sharded TrainStep must produce the same loss
trajectory as the single-device step (XLA inserts collectives to preserve
the math; placement only affects performance).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel import (make_mesh, shard_params_tp, batch_sharded,
                                TrainStep)
from jax.sharding import PartitionSpec as P


def _devices(n=8):
    devs = jax.devices("cpu")
    if len(devs) < n:
        pytest.skip("needs %d fake devices" % n)
    return devs[:n]


def _make_net(seed=0, dense_sizes=(16, 10), conv=False):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = nn.HybridSequential()
    if conv:
        net.add(nn.Conv2D(4, 3, padding=1, activation="relu"),
                nn.MaxPool2D(), nn.Flatten())
    for k in dense_sizes[:-1]:
        net.add(nn.Dense(k, activation="relu"))
    net.add(nn.Dense(dense_sizes[-1]))
    net.initialize(mx.init.Xavier())
    return net


def _loss_fn(logits, labels):
    import jax.numpy as jnp
    logp = jax.nn.log_softmax(logits, axis=-1)
    onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=logp.dtype)
    return -jnp.mean(jnp.sum(logp * onehot, axis=-1))


def _batch(seed=1, n=16, feat=(8,), classes=10):
    rng = np.random.RandomState(seed)
    import jax.numpy as jnp
    x = jnp.asarray(rng.randn(n, *feat).astype(np.float32))
    y = jnp.asarray(rng.randint(0, classes, n).astype(np.int32))
    return x, y


def _run_steps(net_seed, mesh, steps=3, conv=False, tp_rules=None):
    feat = (3, 8, 8) if conv else (8,)
    net = _make_net(net_seed, conv=conv)
    net(nd.zeros((1,) + feat))      # finalize deferred shapes
    step = TrainStep(net, _loss_fn, mesh, learning_rate=0.1,
                     momentum=0.9, tp_rules=tp_rules)
    x, y = _batch(net_seed + 1, feat=feat)
    return [float(step(x, y)) for _ in range(steps)]


def test_make_mesh_shapes():
    devs = _devices()
    m = make_mesh(axes=("dp",), devices=devs)
    assert dict(m.shape) == {"dp": 8}
    m = make_mesh(axes=("dp", "tp"), shape=(-1, 2), devices=devs)
    assert dict(m.shape) == {"dp": 4, "tp": 2}
    m = make_mesh(axes=("dp", "tp"), shape=(2, 4), devices=devs)
    assert dict(m.shape) == {"dp": 2, "tp": 4}


def test_dp_matches_single_device():
    devs = _devices()
    losses_1 = _run_steps(0, make_mesh(axes=("dp",), devices=devs[:1]))
    losses_8 = _run_steps(0, make_mesh(axes=("dp",), devices=devs))
    np.testing.assert_allclose(losses_1, losses_8, rtol=2e-4)
    assert losses_8[-1] < losses_8[0]    # and it actually descends


def test_dp_tp_matches_dp_only():
    devs = _devices()
    losses_dp = _run_steps(0, make_mesh(axes=("dp",), devices=devs))
    losses_tp = _run_steps(0, make_mesh(axes=("dp", "tp"), shape=(-1, 2),
                                        devices=devs))
    np.testing.assert_allclose(losses_dp, losses_tp, rtol=2e-4)


def test_tp_non_alternating_architecture_correct():
    """3 Dense + conv: col/row alternation is a placement heuristic only —
    results must equal the single-device run regardless of layer layout."""
    devs = _devices()
    losses_1 = _run_steps(0, make_mesh(axes=("dp",), devices=devs[:1]),
                          conv=True)
    losses_tp = _run_steps(0, make_mesh(axes=("dp", "tp"), shape=(2, 4),
                                        devices=devs), conv=True)
    np.testing.assert_allclose(losses_1, losses_tp, rtol=2e-4)


def test_shard_params_tp_explicit_rules():
    devs = _devices()
    mesh = make_mesh(axes=("dp", "tp"), shape=(4, 2), devices=devs)
    import jax.numpy as jnp
    params = {"a.weight": jnp.zeros((8, 4)), "a.bias": jnp.zeros((8,)),
              "emb.weight": jnp.zeros((16, 8))}
    out = shard_params_tp(params, mesh, rules={"a.weight": P("tp", None)})
    spec_a = out["a.weight"].sharding.spec
    assert tuple(spec_a) == ("tp", None)
    # un-matched names replicate under explicit rules
    assert tuple(out["emb.weight"].sharding.spec) in ((), (None, None))


def test_shard_params_tp_default_alternation():
    devs = _devices()
    mesh = make_mesh(axes=("dp", "tp"), shape=(4, 2), devices=devs)
    import jax.numpy as jnp
    params = {"0.weight": jnp.zeros((8, 4)), "0.bias": jnp.zeros((8,)),
              "1.weight": jnp.zeros((4, 8))}
    out = shard_params_tp(params, mesh)
    assert tuple(out["0.weight"].sharding.spec) == ("tp", None)   # column
    assert tuple(out["1.weight"].sharding.spec) == (None, "tp")   # row
    assert tuple(out["0.bias"].sharding.spec) in ((), (None,))    # replicated


def test_batch_sharded_placement():
    devs = _devices()
    mesh = make_mesh(axes=("dp",), devices=devs)
    import jax.numpy as jnp
    x = jax.device_put(jnp.zeros((16, 4)), batch_sharded(mesh))
    assert len(x.sharding.device_set) == 8
    assert tuple(x.sharding.spec) == ("dp",)


def test_kvstore_ici_exact_integer_reduce():
    """dist_sync_kvstore pattern: push known integer payloads from every
    'worker' (device), pull the exact sum."""
    kv = mx.kvstore.create("ici")
    shape = (4, 4)
    kv.init("w", nd.zeros(shape))
    n = kv.num_devices if hasattr(kv, "num_devices") else 8
    vals = [nd.array(np.full(shape, i + 1, np.float32)) for i in range(4)]
    kv.push("w", vals)
    out = nd.zeros(shape)
    kv.pull("w", out=out)
    np.testing.assert_array_equal(out.asnumpy(),
                                  np.full(shape, 1 + 2 + 3 + 4, np.float32))


def test_trainstep_write_back():
    devs = _devices()
    mesh = make_mesh(axes=("dp",), devices=devs)
    net = _make_net(3)
    net(nd.zeros((1, 8)))
    before = {k: p.data().asnumpy().copy()
              for k, p in net.collect_params().items()}
    step = TrainStep(net, _loss_fn, mesh, learning_rate=0.1)
    x, y = _batch(4)
    step(x, y)
    step.write_back(net)
    after = {k: p.data().asnumpy() for k, p in net.collect_params().items()}
    changed = [k for k in before if not np.allclose(before[k], after[k])]
    assert changed, "write_back did not update any parameter"


# -- sequence/context parallelism (ring + ulysses) ----------------------------

def _ref_attention(q, k, v, causal):
    scale = q.shape[-1] ** -0.5
    s = np.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        L = q.shape[1]
        mask = np.tril(np.ones((L, L), bool))
        s = np.where(mask[None, None], s, -np.inf)
    s = s - s.max(axis=-1, keepdims=True)
    p = np.exp(s)
    p = p / p.sum(axis=-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("method", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_context_parallel_attention_matches_reference(method, causal):
    from mxnet_tpu.parallel import make_mesh, context_parallel_attention
    np.random.seed(0)
    B, L, H, D = 2, 32, 8, 16   # L split over sp=8 -> 4 per device
    q = np.random.randn(B, L, H, D).astype(np.float32)
    k = np.random.randn(B, L, H, D).astype(np.float32)
    v = np.random.randn(B, L, H, D).astype(np.float32)
    mesh = make_mesh(axes=("sp",))
    out = context_parallel_attention(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v), mesh, causal=causal,
                                     method=method)
    ref = _ref_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)


def test_ring_attention_gradients_match_local():
    """SP must be transparent to training: grads through the ring equal
    grads through plain attention."""
    from mxnet_tpu.parallel import make_mesh, context_parallel_attention
    np.random.seed(1)
    B, L, H, D = 1, 16, 4, 8
    q = jnp.asarray(np.random.randn(B, L, H, D).astype(np.float32))
    k = jnp.asarray(np.random.randn(B, L, H, D).astype(np.float32))
    v = jnp.asarray(np.random.randn(B, L, H, D).astype(np.float32))
    mesh = make_mesh(axes=("sp",))

    def ring_loss(q, k, v):
        return context_parallel_attention(q, k, v, mesh, causal=True,
                                          method="ring").sum()

    def local_loss(q, k, v):
        scale = D ** -0.5
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        mask = jnp.tril(jnp.ones((L, L), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v).sum()

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_loc = jax.grad(local_loss, argnums=(0, 1, 2))(q, k, v)
    for gr, gl in zip(g_ring, g_loc):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gl),
                                   rtol=5e-4, atol=5e-5)


def test_ring_attention_long_sequence_sp2():
    """sp=2 with the remaining devices on dp: mixed-axis mesh works."""
    from mxnet_tpu.parallel import make_mesh, context_parallel_attention
    np.random.seed(2)
    B, L, H, D = 4, 64, 2, 8
    q = np.random.randn(B, L, H, D).astype(np.float32)
    mesh = make_mesh(axes=("dp", "sp"), shape=(4, 2))
    out = context_parallel_attention(jnp.asarray(q), jnp.asarray(q),
                                     jnp.asarray(q), mesh, causal=True)
    ref = _ref_attention(q, q, q, True)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)


def test_ulysses_rejects_indivisible_heads():
    from mxnet_tpu.parallel import make_mesh, context_parallel_attention
    mesh = make_mesh(axes=("sp",))  # sp=8
    q = jnp.zeros((1, 16, 6, 4), jnp.float32)  # 6 heads % 8 != 0
    with pytest.raises(Exception, match="heads"):
        context_parallel_attention(q, q, q, mesh, method="ulysses")


# -- pipeline parallelism ------------------------------------------------------

def _stage_fn(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


def _make_stages(n_stages, d, seed=0):
    rng = np.random.RandomState(seed)
    ws = jnp.asarray(rng.randn(n_stages, d, d).astype(np.float32) * 0.5)
    bs = jnp.asarray(rng.randn(n_stages, d).astype(np.float32) * 0.1)
    return (ws, bs)


def test_pipeline_matches_sequential():
    from mxnet_tpu.parallel import make_mesh, pipeline_parallel
    d, batch, n_stages = 6, 16, 4
    mesh = make_mesh(axes=("pp",), shape=(n_stages,),
                     devices=_devices(n_stages))
    stacked = _make_stages(n_stages, d)
    apply = pipeline_parallel(_stage_fn, mesh, n_microbatches=4)
    x = jnp.asarray(np.random.RandomState(1).randn(batch, d)
                    .astype(np.float32))
    out = apply(stacked, x)
    # sequential reference
    ref = x
    for s in range(n_stages):
        ref = _stage_fn((stacked[0][s], stacked[1][s]), ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_gradients_match_sequential():
    from mxnet_tpu.parallel import make_mesh, pipeline_parallel
    d, batch, n_stages = 4, 8, 4
    mesh = make_mesh(axes=("pp",), shape=(n_stages,),
                     devices=_devices(n_stages))
    stacked = _make_stages(n_stages, d, seed=2)
    apply = pipeline_parallel(_stage_fn, mesh, n_microbatches=2)
    x = jnp.asarray(np.random.RandomState(3).randn(batch, d)
                    .astype(np.float32))

    def pipe_loss(params):
        return (apply(params, x) ** 2).mean()

    def seq_loss(params):
        ws, bs = params
        h = x
        for s in range(n_stages):
            h = _stage_fn((ws[s], bs[s]), h)
        return (h ** 2).mean()

    gp = jax.grad(pipe_loss)(stacked)
    gs = jax.grad(seq_loss)(stacked)
    for a, b in zip(gp, gs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-5)


def test_pipeline_training_step_descends():
    from mxnet_tpu.parallel import make_mesh, pipeline_parallel
    d, batch, n_stages = 4, 16, 4
    mesh = make_mesh(axes=("pp",), shape=(n_stages,),
                     devices=_devices(n_stages))
    params = _make_stages(n_stages, d, seed=4)
    apply = pipeline_parallel(_stage_fn, mesh, n_microbatches=4)
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(batch, d).astype(np.float32))
    y = jnp.asarray(rng.randn(batch, d).astype(np.float32))

    @jax.jit
    def step(params):
        loss, g = jax.value_and_grad(
            lambda p: ((apply(p, x) - y) ** 2).mean())(params)
        return tuple(p - 0.2 * gi for p, gi in zip(params, g)), loss

    params, l0 = step(params)
    params, l1 = step(params)
    assert float(l1) < float(l0)


# -- expert parallelism (MoE) --------------------------------------------------

def _expert_fn(params, x):
    w1, w2 = params
    return jnp.maximum(x @ w1, 0) @ w2


def test_moe_matches_per_token_reference():
    from mxnet_tpu.parallel import make_mesh, moe_parallel
    rng = np.random.RandomState(0)
    d, dh, T = 8, 16, 64
    mesh = make_mesh(axes=("ep",), devices=_devices(8))  # 1 expert/device
    E = 8
    w1 = jnp.asarray(rng.randn(E, d, dh).astype(np.float32) * 0.3)
    w2 = jnp.asarray(rng.randn(E, dh, d).astype(np.float32) * 0.3)
    gate_w = jnp.asarray(rng.randn(d, E).astype(np.float32))
    x = jnp.asarray(rng.randn(T, d).astype(np.float32))

    apply = moe_parallel(_expert_fn, mesh, capacity_factor=8.0)  # no drops
    y, aux = apply(x, gate_w, (w1, w2))

    # dense per-token reference: top-1 expert output scaled by gate prob
    xn = np.asarray(x)
    logits = xn @ np.asarray(gate_w)
    probs = np.exp(logits - logits.max(1, keepdims=True))
    probs /= probs.sum(1, keepdims=True)
    pick = probs.argmax(1)
    ref = np.zeros_like(xn)
    for t in range(T):
        e = pick[t]
        h = np.maximum(xn[t] @ np.asarray(w1[e]), 0) @ np.asarray(w2[e])
        ref[t] = probs[t, e] * h
    np.testing.assert_allclose(np.asarray(y), ref, rtol=2e-4, atol=2e-5)
    assert np.isfinite(float(aux))


def test_moe_capacity_drops_tokens_to_zero():
    from mxnet_tpu.parallel import make_mesh, moe_parallel
    rng = np.random.RandomState(1)
    d, T = 4, 32
    mesh = make_mesh(axes=("ep",), devices=_devices(8))
    E = 8
    w1 = jnp.asarray(rng.randn(E, d, d).astype(np.float32))
    w2 = jnp.asarray(rng.randn(E, d, d).astype(np.float32))
    # force every token to expert 0 -> capacity overflows
    gate_w = jnp.asarray(
        np.concatenate([np.full((d, 1), 5.0),
                        np.zeros((d, E - 1))], axis=1).astype(np.float32))
    x = jnp.asarray(np.abs(rng.randn(T, d)).astype(np.float32))
    apply = moe_parallel(_expert_fn, mesh, capacity_factor=1.0)
    y, _aux = apply(x, gate_w, (w1, w2))
    yn = np.asarray(y)
    zero_rows = (np.abs(yn).sum(axis=1) == 0).sum()
    assert zero_rows > 0            # overflow tokens were dropped
    assert zero_rows < T            # but capacity tokens went through


def test_moe_trains_with_gradients():
    from mxnet_tpu.parallel import make_mesh, moe_parallel
    rng = np.random.RandomState(2)
    d, T, E = 4, 32, 8
    mesh = make_mesh(axes=("ep",), devices=_devices(8))
    params = (jnp.asarray(rng.randn(E, d, d).astype(np.float32) * 0.3),
              jnp.asarray(rng.randn(E, d, d).astype(np.float32) * 0.3))
    gate_w = jnp.asarray(rng.randn(d, E).astype(np.float32) * 0.3)
    x = jnp.asarray(rng.randn(T, d).astype(np.float32))
    tgt = jnp.asarray(rng.randn(T, d).astype(np.float32))
    apply = moe_parallel(_expert_fn, mesh, capacity_factor=4.0)

    @jax.jit
    def step(params, gate_w):
        def loss_fn(p, g):
            y, aux = apply(x, g, p)
            return ((y - tgt) ** 2).mean() + 0.01 * aux
        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(
            params, gate_w)
        p, g = grads
        return (tuple(a - 0.1 * b for a, b in zip(params, p)),
                gate_w - 0.1 * g, loss)

    params, gate_w, l0 = step(params, gate_w)
    params, gate_w, l1 = step(params, gate_w)
    assert float(l1) < float(l0)


def test_pipeline_rejects_stage_count_mismatch():
    from mxnet_tpu.parallel import make_mesh, pipeline_parallel
    mesh = make_mesh(axes=("pp",), shape=(4,), devices=_devices(4))
    stacked = _make_stages(8, 4)      # 8 stages on a 4-device axis
    apply = pipeline_parallel(_stage_fn, mesh, n_microbatches=4)
    with pytest.raises(ValueError, match="stacked stages"):
        apply(stacked, jnp.zeros((8, 4), jnp.float32))


def test_moe_rejects_gate_expert_mismatch():
    from mxnet_tpu.parallel import make_mesh, moe_parallel
    mesh = make_mesh(axes=("ep",), devices=_devices(8))
    params = (jnp.zeros((8, 4, 4), jnp.float32),
              jnp.zeros((8, 4, 4), jnp.float32))
    gate_w = jnp.zeros((4, 16), jnp.float32)   # 16 routes, 8 experts
    apply = moe_parallel(_expert_fn, mesh)
    with pytest.raises(ValueError, match="gate_w"):
        apply(jnp.zeros((16, 4), jnp.float32), gate_w, params)


def test_ring_attention_flash_path_matches_single_device():
    """Block-aligned shards route ring hops through the Pallas flash
    kernel (interpret off-TPU): outputs AND gradients must match the
    single-device attention reference."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from mxnet_tpu.parallel import ring as ring_mod
    from mxnet_tpu.ops.attention import _attention_jnp

    devs = np.array(jax.devices("cpu")[:4])
    mesh = Mesh(devs, ("sp",))
    B, L, H, D = 1, 1024, 2, 128          # 256 per shard: block-aligned
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, L, H, D), jnp.float32) * 0.1
    k = jnp.asarray(rng.randn(B, L, H, D), jnp.float32) * 0.1
    v = jnp.asarray(rng.randn(B, L, H, D), jnp.float32) * 0.1
    g = jnp.asarray(rng.randn(B, L, H, D), jnp.float32) * 0.1
    scale = 1.0 / np.sqrt(D)

    from mxnet_tpu.ops import attention as att
    prev = att.set_attention_impl("pallas")   # engage flash off-TPU
    for causal in (False, True):
        def run(q, k, v):
            return ring_mod.context_parallel_attention(
                q, k, v, mesh, sp_axis="sp", causal=causal, method="ring",
                scale=scale)
        # the flash path must actually engage on these shapes
        assert ring_mod._flash_ok(
            jnp.zeros((B, L // 4, H, D)), jnp.zeros((B, L // 4, H, D)))
        out, vjp = jax.vjp(run, q, k, v)
        dq, dk, dv = vjp(g)

        def ref(q, k, v):
            o = _attention_jnp(q.transpose(0, 2, 1, 3),
                               k.transpose(0, 2, 1, 3),
                               v.transpose(0, 2, 1, 3), scale, causal)
            return o.transpose(0, 2, 1, 3)
        want, vjp_r = jax.vjp(ref, q, k, v)
        dq_r, dk_r, dv_r = vjp_r(g)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=2e-3, atol=2e-4)
        for got, ref_g, nm in ((dq, dq_r, "dq"), (dk, dk_r, "dk"),
                               (dv, dv_r, "dv")):
            err = np.abs(np.asarray(got) - np.asarray(ref_g)).max()
            rel = err / max(np.abs(np.asarray(ref_g)).max(), 1e-6)
            assert rel < 5e-3, (causal, nm, rel)
    att.set_attention_impl(prev)


# -- 2-bit gradient compression (reference: gradient_compression.cc) --------

def test_quantize_2bit_matches_numpy_reference():
    """Multi-step error feedback vs a step-by-step numpy re-implementation
    of Quantize2BitImpl."""
    import numpy as np
    from mxnet_tpu.kvstore.gradient_compression import quantize_2bit
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    t = 0.4
    g_steps = [rng.randn(16).astype(np.float32) * 0.3 for _ in range(6)]
    res_ref = np.zeros(16, np.float32)
    res = jnp.zeros(16)
    for g in g_steps:
        # numpy reference: residual += g; emit level; residual -= level
        res_ref = res_ref + g
        level = np.where(res_ref >= t, t,
                         np.where(res_ref <= -t, -t, 0.0)).astype(np.float32)
        res_ref -= level
        q, res = quantize_2bit(jnp.asarray(g), res, t)
        np.testing.assert_allclose(np.asarray(q), level, atol=1e-6)
        np.testing.assert_allclose(np.asarray(res), res_ref, atol=1e-5)
        lv = np.array([-t, 0.0, t], np.float32)
        assert all(np.isclose(lv, v).any() for v in np.asarray(q))


def test_pack_unpack_2bit_roundtrip():
    import numpy as np
    from mxnet_tpu.kvstore.gradient_compression import pack_2bit, unpack_2bit

    t = 0.25
    rng = np.random.RandomState(1)
    levels = rng.choice([-t, 0.0, t], size=50).astype(np.float32)
    words = pack_2bit(levels, t)
    assert words.dtype == np.uint32 and len(words) == 4  # ceil(50/16)
    back = unpack_2bit(words, 50, t)
    np.testing.assert_allclose(back, levels)
    # 2 bits/element on the wire: 50 elems -> 4 words = 16 bytes vs 200
    assert words.nbytes * 8 >= 2 * 50


def test_kvstore_local_2bit_error_feedback_converges():
    """Single-process: compressed pushes never lose gradient mass — the
    cumulative pulled sum tracks the true sum within the threshold band,
    even for gradients far below the threshold."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import kvstore, nd

    kv = kvstore.create("local")
    t = 1.0
    kv.set_gradient_compression({"type": "2bit", "threshold": t})
    g = np.array([0.09, -0.21, 0.0, 0.35], np.float32)  # all |g| < t
    kv.init(3, nd.zeros((4,)))
    total = np.zeros(4, np.float32)
    for _ in range(40):
        kv.push(3, nd.array(g))
        out = nd.zeros((4,))
        kv.pull(3, out=out)
        levels = out.asnumpy()
        assert set(np.round(np.unique(levels), 5)) <= {-t, 0.0, t}
        total += levels
    true = 40 * g
    assert np.all(np.abs(total - true) <= t + np.abs(g).max() + 1e-5), \
        (total, true)


def test_2bit_compressed_dp_training_converges():
    """2-device DP with {'type': '2bit'}: final loss within a whisker of
    uncompressed training (threshold sits at raw-summed-grad scale — the
    same tuning contract as the reference's PS compression)."""
    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, gluon

    def train(compression):
        mx.random.seed(0)
        ctxs = [mx.cpu(0), mx.cpu(1)]
        net = gluon.nn.Dense(1)
        net.initialize(mx.init.Xavier(), ctx=ctxs)
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1}, kvstore="device",
                           compression_params=compression)
        rng = onp.random.RandomState(0)
        Xn = rng.randn(64, 4).astype("float32")
        w_true = onp.array([[1.0, -2.0, 0.5, 3.0]], "float32")
        yn = Xn @ w_true.T
        halves = [(nd.array(Xn[:32], ctx=ctxs[0]),
                   nd.array(yn[:32], ctx=ctxs[0])),
                  (nd.array(Xn[32:], ctx=ctxs[1]),
                   nd.array(yn[32:], ctx=ctxs[1]))]
        for _ in range(300):
            losses = []
            with autograd.record():
                for X, y in halves:
                    losses.append(((net(X) - y) ** 2).mean())
            for l in losses:
                l.backward()
            tr.step(64)
        return sum(float(l.asnumpy()) for l in losses) / 2

    plain = train(None)
    comp = train({"type": "2bit", "threshold": 5.0})
    # convergence delta bound: compressed within 2x of uncompressed + eps
    assert comp < 2 * plain + 0.1, (plain, comp)


def test_ring_attention_flash_path_aligned_shards():
    """Per-shard shapes aligned to the flash blocks + impl forced to
    'pallas': each ring hop runs the REAL Pallas kernel (interpret on
    CPU, Mosaic on TPU) and must still match the dense reference."""
    from mxnet_tpu.ops.attention import attention_impl_scope
    from mxnet_tpu.parallel import make_mesh, context_parallel_attention
    np.random.seed(3)
    B, L, H, D = 1, 512, 1, 128          # sp=2 -> 256 per shard
    q = np.random.randn(B, L, H, D).astype(np.float32)
    k = np.random.randn(B, L, H, D).astype(np.float32)
    v = np.random.randn(B, L, H, D).astype(np.float32)
    mesh = make_mesh(axes=("dp", "sp"), shape=(4, 2))
    with attention_impl_scope("pallas"):
        out = context_parallel_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh,
            causal=True)
    ref = _ref_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-3,
                               atol=2e-4)
