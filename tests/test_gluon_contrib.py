"""gluon.contrib blocks (reference: python/mxnet/gluon/contrib/ —
tests/python/unittest/test_gluon_contrib.py pattern)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.contrib import nn as cnn
from mxnet_tpu.gluon.contrib import rnn as crnn


def test_concurrent_and_identity():
    assert cnn.Identity is nn.Identity             # aliased, not duplicated
    net = cnn.HybridConcurrent(axis=1)
    net.add(nn.Dense(3), cnn.Identity(), nn.Dense(2))
    net.initialize()
    x = nd.array(np.random.RandomState(0).randn(4, 5).astype("float32"))
    out = net(x)
    assert out.shape == (4, 3 + 5 + 2)
    # identity branch passes x through untouched
    np.testing.assert_allclose(out.asnumpy()[:, 3:8], x.asnumpy(),
                               rtol=1e-6)


def test_pixelshuffle2d_matches_numpy():
    ps = cnn.PixelShuffle2D(2)
    rng = np.random.RandomState(1)
    x = rng.randn(1, 8, 3, 4).astype(np.float32)
    out = ps(nd.array(x)).asnumpy()
    assert out.shape == (1, 2, 6, 8)
    # numpy reference: torch.pixel_shuffle layout
    want = x.reshape(1, 2, 2, 2, 3, 4).transpose(0, 1, 4, 2, 5, 3) \
        .reshape(1, 2, 6, 8)
    np.testing.assert_allclose(out, want, rtol=1e-6)


def test_pixelshuffle1d_and_3d_shapes():
    x1 = nd.array(np.arange(12, dtype=np.float32).reshape(1, 6, 2))
    assert cnn.PixelShuffle1D(3)(x1).shape == (1, 2, 6)
    x3 = nd.array(np.zeros((1, 8, 2, 2, 2), np.float32))
    assert cnn.PixelShuffle3D(2)(x3).shape == (1, 1, 4, 4, 4)


def test_conv2d_lstm_cell_unroll():
    cell = crnn.Conv2DLSTMCell(input_shape=(3, 8, 8), hidden_channels=4,
                               i2h_kernel=3, h2h_kernel=3)
    cell.initialize(mx.init.Xavier())
    rng = np.random.RandomState(2)
    seq = nd.array(rng.randn(2, 5, 3, 8, 8).astype(np.float32))  # NTC...
    outs, states = cell.unroll(5, seq, layout="NTC")
    assert outs.shape == (2, 5, 4, 8, 8)
    assert states[0].shape == (2, 4, 8, 8)
    assert states[1].shape == (2, 4, 8, 8)
    assert np.isfinite(outs.asnumpy()).all()
    # gradient flows end to end
    cell.reset()
    with autograd.record():
        o, _ = cell.unroll(5, seq, layout="NTC")
        loss = (o * o).mean()
    loss.backward()
    g = cell.i2h_weight.grad()
    assert np.abs(g.asnumpy()).sum() > 0


def test_variational_dropout_same_mask_every_step():
    base = mx.gluon.rnn.LSTMCell(6, input_size=4)
    cell = crnn.VariationalDropoutCell(base, drop_inputs=0.5)
    cell.initialize()
    rng = np.random.RandomState(3)
    seq = nd.array(rng.randn(2, 7, 4).astype(np.float32))
    mx.random.seed(0)
    with autograd.record(train_mode=True):
        cell.unroll(7, seq, layout="NTC")
        m_first = cell._mask_i.asnumpy()
    # the mask is drawn once and reused across all 7 steps
    assert set(np.round(np.unique(m_first), 4)) <= {0.0, 2.0}
    # inference: no dropout
    cell.reset()
    outs, _ = cell.unroll(7, seq, layout="NTC")
    assert cell._mask_i is None


def test_lstmp_cell_projection_shapes():
    cell = crnn.LSTMPCell(hidden_size=8, projection_size=3, input_size=5)
    cell.initialize(mx.init.Xavier())
    x = nd.array(np.random.RandomState(4).randn(2, 5).astype("float32"))
    states = cell.begin_state(2)
    assert states[0].shape == (2, 3) and states[1].shape == (2, 8)
    out, next_states = cell(x, states)
    assert out.shape == (2, 3)                  # projected
    assert next_states[1].shape == (2, 8)       # cell state full width
    seq = nd.array(np.random.RandomState(5).randn(2, 4, 5).astype("float32"))
    cell.reset()
    outs, _ = cell.unroll(4, seq, layout="NTC")
    assert outs.shape == (2, 4, 3)


def test_sparse_embedding_forward_grad():
    emb = cnn.SparseEmbedding(10, 4)
    emb.initialize(mx.init.Normal(0.1))
    idx = nd.array(np.array([1, 3, 1], np.float32))
    with autograd.record():
        out = emb(idx)
        loss = (out * out).sum()
    loss.backward()
    assert out.shape == (3, 4)
    g = emb.weight.grad()
    gn = g.asnumpy() if hasattr(g, "asnumpy") else np.asarray(g)
    if gn.ndim == 2 and gn.shape == (10, 4):
        touched = np.abs(gn).sum(1) > 0
        assert touched[1] and touched[3] and not touched[0]


def test_sync_batch_norm_api():
    assert cnn.SyncBatchNorm is nn.SyncBatchNorm   # one class, 2.x move
    bn = cnn.SyncBatchNorm(in_channels=4, num_devices=8)
    bn.initialize()
    x = nd.array(np.random.RandomState(6).randn(2, 4, 3, 3)
                 .astype("float32"))
    with autograd.record(train_mode=True):
        out = bn(x)
    assert out.shape == x.shape
