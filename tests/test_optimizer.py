"""Optimizer tests: each optimizer against a slow NumPy reference updater
(the reference's tests/python/unittest/test_optimizer.py pattern)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import optimizer as opt


def _run(opt_instance, steps=3, shape=(5, 3), seed=0):
    rng = np.random.RandomState(seed)
    w0 = rng.randn(*shape).astype(np.float32)
    grads = [rng.randn(*shape).astype(np.float32) for _ in range(steps)]
    weight = mx.nd.array(w0.copy())
    state = opt_instance.create_state(0, weight)
    for g in grads:
        opt_instance.update(0, weight, mx.nd.array(g), state)
    return w0, grads, weight.asnumpy()


def test_sgd_no_momentum():
    o = opt.SGD(learning_rate=0.1, wd=0.0)
    w0, grads, w = _run(o)
    expect = w0.copy()
    for g in grads:
        expect -= 0.1 * g
    assert np.allclose(w, expect, atol=1e-6)


def test_sgd_momentum_wd():
    lr, mom, wd = 0.1, 0.9, 0.01
    o = opt.SGD(learning_rate=lr, momentum=mom, wd=wd)
    w0, grads, w = _run(o)
    expect = w0.copy()
    m = np.zeros_like(expect)
    for g in grads:
        g = g + wd * expect
        m = mom * m - lr * g
        expect = expect + m
    assert np.allclose(w, expect, atol=1e-5)


def test_sgd_clip_gradient():
    o = opt.SGD(learning_rate=1.0, clip_gradient=0.1)
    w0, grads, w = _run(o)
    expect = w0.copy()
    for g in grads:
        expect -= np.clip(g, -0.1, 0.1)
    assert np.allclose(w, expect, atol=1e-6)


def test_adam():
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
    o = opt.Adam(learning_rate=lr, beta1=b1, beta2=b2, epsilon=eps)
    w0, grads, w = _run(o)
    expect = w0.copy()
    m = np.zeros_like(expect)
    v = np.zeros_like(expect)
    for t, g in enumerate(grads, 1):
        lr_t = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        expect -= lr_t * m / (np.sqrt(v) + eps)
    assert np.allclose(w, expect, atol=1e-5)


def test_nag():
    lr, mom = 0.1, 0.9
    o = opt.NAG(learning_rate=lr, momentum=mom)
    w0, grads, w = _run(o)
    expect = w0.copy()
    m = np.zeros_like(expect)
    for g in grads:
        m = mom * m + g
        expect -= lr * (mom * m + g)
    assert np.allclose(w, expect, atol=1e-5)


def test_rmsprop():
    lr, gamma1, eps = 0.01, 0.9, 1e-8
    o = opt.RMSProp(learning_rate=lr, gamma1=gamma1, epsilon=eps)
    w0, grads, w = _run(o)
    expect = w0.copy()
    n = np.zeros_like(expect)
    for g in grads:
        n = (1 - gamma1) * g * g + gamma1 * n
        expect -= lr * g / np.sqrt(n + eps)
    assert np.allclose(w, expect, atol=1e-5)


def test_adagrad():
    lr, eps, wd = 0.1, 1e-7, 0.01
    o = opt.AdaGrad(learning_rate=lr, eps=eps, wd=wd)
    w0, grads, w = _run(o)
    expect = w0.copy()
    h = np.zeros_like(expect)
    for g in grads:
        h += g * g
        expect -= lr * (g / np.sqrt(h + eps) + wd * expect)
    assert np.allclose(w, expect, atol=1e-5)


def test_adamw_decoupled_wd():
    lr, b1, b2, eps, wd = 0.01, 0.9, 0.999, 1e-8, 0.1
    o = opt.AdamW(learning_rate=lr, beta1=b1, beta2=b2, epsilon=eps, wd=wd)
    w0, grads, w = _run(o)
    expect = w0.copy()
    m = np.zeros_like(expect)
    v = np.zeros_like(expect)
    for t, g in enumerate(grads, 1):
        lr_t = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        # decoupled decay at the RAW lr (huggingface/2.x AdamW; pinned
        # against torch.optim.AdamW in the torch-oracle lane)
        expect -= lr_t * m / (np.sqrt(v) + eps)
        expect -= lr * wd * expect
    assert np.allclose(w, expect, atol=1e-4)


def test_ftrl():
    o = opt.Ftrl(learning_rate=0.1, lamda1=0.01, beta=1.0)
    w0, grads, w = _run(o)
    lr, l1, beta = 0.1, 0.01, 1.0
    expect = w0.copy()
    z = np.zeros_like(expect)
    n = np.zeros_like(expect)
    for g in grads:
        n_new = n + g * g
        sigma = (np.sqrt(n_new) - np.sqrt(n)) / lr
        z = z + g - sigma * expect
        n = n_new
        expect = np.where(np.abs(z) <= l1, 0.0,
                          (np.sign(z) * l1 - z) / ((beta + np.sqrt(n)) / lr))
    assert np.allclose(w, expect, atol=1e-5)


def test_signum():
    lr, mom = 0.01, 0.9
    o = opt.Signum(learning_rate=lr, momentum=mom)
    w0, grads, w = _run(o)
    expect = w0.copy()
    m = np.zeros_like(expect)
    for g in grads:
        m = mom * m - (1 - mom) * g
        expect = expect + lr * np.sign(m)
    assert np.allclose(w, expect, atol=1e-5)


def test_lamb_runs_and_descends():
    o = opt.LAMB(learning_rate=0.01)
    w0, grads, w = _run(o, steps=5)
    assert w.shape == w0.shape
    assert not np.allclose(w, w0)
    assert np.isfinite(w).all()


def test_multi_precision_sgd():
    o = opt.SGD(learning_rate=0.1, momentum=0.9, multi_precision=True)
    weight = mx.nd.array(np.ones((4, 4), np.float16))
    state = o.create_state_multi_precision(0, weight)
    grad = mx.nd.array(np.full((4, 4), 0.5, np.float16))
    o.update_multi_precision(0, weight, grad, state)
    assert weight.dtype == np.float16
    # master copy is fp32
    assert state[1].dtype == np.float32
    assert np.allclose(weight.asnumpy(), 1.0 - 0.05, atol=1e-3)


def test_lr_scheduler_factor():
    from mxnet_tpu.lr_scheduler import FactorScheduler
    sched = FactorScheduler(step=10, factor=0.5, base_lr=1.0)
    o = opt.SGD(learning_rate=1.0, lr_scheduler=sched)
    assert abs(sched(5) - 1.0) < 1e-9
    assert abs(sched(11) - 0.5) < 1e-9


def test_lr_scheduler_warmup():
    from mxnet_tpu.lr_scheduler import CosineScheduler
    sched = CosineScheduler(max_update=100, base_lr=1.0, warmup_steps=10)
    assert sched(0) == 0.0
    assert sched(5) == pytest.approx(0.5)
    assert sched(10) == pytest.approx(1.0)
    assert sched(100) == pytest.approx(0.0, abs=1e-6)


def test_optimizer_registry_create():
    o = opt.create("adam", learning_rate=0.1)
    assert isinstance(o, opt.Adam)
    assert o.lr == 0.1
    with pytest.raises(ValueError):
        opt.create("nonexistent_optimizer")


def test_updater_pickle_states():
    o = opt.SGD(learning_rate=0.1, momentum=0.9)
    upd = opt.get_updater(o)
    w = mx.nd.ones((3, 3))
    g = mx.nd.ones((3, 3))
    upd(0, g, w)
    blob = upd.get_states()
    upd2 = opt.get_updater(opt.SGD(learning_rate=0.1, momentum=0.9))
    upd2.set_states(blob)
    assert 0 in upd2.states


def test_round5_optimizers_descend_and_create():
    """FTML/Adamax/Nadam/SGLD: registry create() resolves them and each
    descends on a quadratic (Adamax additionally trajectory-pinned vs
    torch in test_torch_parity)."""
    import numpy as onp
    for name in ("ftml", "adamax", "nadam", "sgld"):
        mx.random.seed(0)
        o = opt.create(name, learning_rate=0.05 if name != "sgld"
                       else 0.005)
        w = nd.array(onp.array([3.0, -2.0], "float32"))
        state = o.create_state(0, w)
        first = float((w * w).sum().asnumpy().item())
        for _ in range(120):
            o.update(0, w, 2.0 * w, state)
        last = float((w * w).sum().asnumpy().item())
        assert last < first * (0.6 if name != "sgld" else 0.9), \
            (name, first, last)
