"""mx.operator CustomOp bridge: numpy-callback ops with autograd, under
eager and hybridized execution (reference: tests/python/unittest/
test_operator.py test_custom_op)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


@mx.operator.register("sigmoid_custom")
class SigmoidProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return SigmoidOp()


class SigmoidOp(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0]
        self.assign(out_data[0], req[0], 1.0 / (1.0 + np.exp(-x)))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0]
        self.assign(in_grad[0], req[0], out_grad[0] * y * (1.0 - y))


@mx.operator.register("scale2")
class Scale2Prop(mx.operator.CustomOpProp):
    """Two inputs, two outputs: (2a+b, a*b)."""
    def list_arguments(self):
        return ["a", "b"]

    def list_outputs(self):
        return ["s", "p"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0], in_shape[0]], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return Scale2Op()


class Scale2Op(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        a, b = in_data
        self.assign(out_data[0], req[0], 2 * a + b)
        self.assign(out_data[1], req[1], a * b)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        a, b = in_data
        gs, gp = out_grad
        self.assign(in_grad[0], req[0], 2 * gs + gp * b)
        self.assign(in_grad[1], req[1], gs + gp * a)


def test_custom_forward():
    x = nd.array(np.array([-1.0, 0.0, 2.0], np.float32))
    y = nd.Custom(x, op_type="sigmoid_custom")
    np.testing.assert_allclose(y.asnumpy(),
                               1 / (1 + np.exp(-x.asnumpy())), rtol=1e-6)


def test_custom_backward():
    xv = np.array([[-1.0, 0.5], [2.0, -3.0]], np.float32)
    x = nd.array(xv)
    x.attach_grad()
    with autograd.record():
        y = nd.Custom(x, op_type="sigmoid_custom")
        loss = y.sum()
    loss.backward()
    s = 1 / (1 + np.exp(-xv))
    np.testing.assert_allclose(x.grad.asnumpy(), s * (1 - s), rtol=1e-5)


def test_custom_multi_io_backward():
    av, bv = np.array([1.0, 2.0], np.float32), np.array([3.0, -1.0], np.float32)
    a, b = nd.array(av), nd.array(bv)
    a.attach_grad(); b.attach_grad()
    with autograd.record():
        s, p = nd.Custom(a, b, op_type="scale2")
        loss = (s * s).sum() + p.sum()
    loss.backward()
    # d/da [(2a+b)^2 + a*b] = 4(2a+b) + b ; d/db = 2(2a+b) + a
    np.testing.assert_allclose(a.grad.asnumpy(), 4 * (2 * av + bv) + bv, rtol=1e-5)
    np.testing.assert_allclose(b.grad.asnumpy(), 2 * (2 * av + bv) + av, rtol=1e-5)


def test_custom_inside_hybridize():
    class Net(mx.gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.dense = mx.gluon.nn.Dense(4)

        def forward(self, x):
            return nd.Custom(self.dense(x), op_type="sigmoid_custom")

    net = Net()
    net.initialize()
    x = nd.array(np.random.RandomState(0).randn(2, 3).astype(np.float32))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    np.testing.assert_allclose(eager, hybrid, rtol=1e-5, atol=1e-6)


def test_custom_hybridize_grad():
    class Net(mx.gluon.HybridBlock):
        def forward(self, x):
            return nd.Custom(x, op_type="sigmoid_custom")

    net = Net()
    net.hybridize()
    xv = np.array([0.3, -0.7], np.float32)
    x = nd.array(xv)
    x.attach_grad()
    with autograd.record():
        y = net(x)
    y.backward()
    s = 1 / (1 + np.exp(-xv))
    np.testing.assert_allclose(x.grad.asnumpy(), s * (1 - s), rtol=1e-5)


def test_unregistered_raises():
    with pytest.raises(mx.MXNetError):
        nd.Custom(nd.zeros((2,)), op_type="nope_not_here")
