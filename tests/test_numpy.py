"""mx.np / mx.npx tests.

Reference pattern: tests/python/unittest/test_numpy_op.py /
test_numpy_ndarray.py — function-surface parity against real numpy,
npx extensions, interop with autograd/gluon.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np, npx, autograd
from mxnet_tpu.gluon import nn

R = onp.random.RandomState(42)


def test_one_array_type():
    assert np.ndarray is mx.nd.NDArray
    a = np.array([[1.0, 2.0], [3.0, 4.0]])
    assert isinstance(a, mx.nd.NDArray)


def test_creation():
    onp.testing.assert_array_equal(np.zeros((2, 3)).asnumpy(),
                                   onp.zeros((2, 3), onp.float32))
    onp.testing.assert_array_equal(np.ones(4).asnumpy(), onp.ones(4))
    onp.testing.assert_array_equal(np.full((2,), 7.0).asnumpy(),
                                   onp.full((2,), 7.0, onp.float32))
    onp.testing.assert_array_equal(np.arange(5).asnumpy(), onp.arange(5))
    onp.testing.assert_allclose(np.linspace(0, 1, 5).asnumpy(),
                                onp.linspace(0, 1, 5), rtol=1e-6)
    onp.testing.assert_array_equal(np.eye(3).asnumpy(), onp.eye(3))
    a = np.array([1.0, 2.0])
    onp.testing.assert_array_equal(np.zeros_like(a).asnumpy(), [0, 0])
    onp.testing.assert_array_equal(np.ones_like(a).asnumpy(), [1, 1])


UNARY = ["exp", "log1p", "sqrt", "square", "abs", "sign", "floor", "ceil",
         "sin", "cos", "tanh", "arctan", "sinh", "log2", "expm1", "rint",
         "isnan", "isfinite", "negative", "reciprocal", "cbrt", "radians"]


@pytest.mark.parametrize("name", UNARY)
def test_unary_matches_numpy(name):
    x = R.uniform(0.2, 0.9, (3, 4)).astype(onp.float32)
    got = getattr(np, name)(np.array(x)).asnumpy()
    want = getattr(onp, name)(x)
    onp.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)


BINARY = ["add", "subtract", "multiply", "divide", "maximum", "minimum",
          "power", "arctan2", "hypot", "logaddexp", "copysign",
          "greater", "less_equal", "not_equal", "logical_and"]


@pytest.mark.parametrize("name", BINARY)
def test_binary_matches_numpy(name):
    a = R.uniform(0.2, 0.9, (3, 4)).astype(onp.float32)
    b = R.uniform(0.2, 0.9, (4,)).astype(onp.float32)   # broadcast
    got = getattr(np, name)(np.array(a), np.array(b)).asnumpy()
    want = getattr(onp, name)(a, b)
    onp.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)


REDUCE = ["sum", "mean", "std", "var", "min", "max", "prod"]


@pytest.mark.parametrize("name", REDUCE)
@pytest.mark.parametrize("axis", [None, 0, 1])
def test_reductions(name, axis):
    x = R.randn(4, 5).astype(onp.float32)
    got = getattr(np, name)(np.array(x), axis=axis).asnumpy()
    want = getattr(onp, name)(x, axis=axis)
    onp.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_shape_ops():
    x = R.randn(2, 3, 4).astype(onp.float32)
    a = np.array(x)
    onp.testing.assert_array_equal(np.reshape(a, (6, 4)).asnumpy(),
                                   x.reshape(6, 4))
    onp.testing.assert_array_equal(np.transpose(a, (2, 0, 1)).asnumpy(),
                                   x.transpose(2, 0, 1))
    onp.testing.assert_array_equal(np.expand_dims(a, 1).asnumpy(),
                                   onp.expand_dims(x, 1))
    onp.testing.assert_array_equal(np.concatenate([a, a], axis=2).asnumpy(),
                                   onp.concatenate([x, x], axis=2))
    onp.testing.assert_array_equal(np.stack([a, a], axis=0).asnumpy(),
                                   onp.stack([x, x]))
    onp.testing.assert_array_equal(np.flip(a, axis=1).asnumpy(),
                                   onp.flip(x, 1))
    onp.testing.assert_array_equal(np.moveaxis(a, 0, -1).asnumpy(),
                                   onp.moveaxis(x, 0, -1))
    onp.testing.assert_array_equal(np.ravel(a).asnumpy(), x.ravel())
    onp.testing.assert_array_equal(
        np.where(np.array(x > 0), a, -a).asnumpy(), onp.where(x > 0, x, -x))


def test_linalg_and_matmul():
    a = R.randn(3, 4).astype(onp.float32)
    b = R.randn(4, 2).astype(onp.float32)
    onp.testing.assert_allclose(np.dot(np.array(a), np.array(b)).asnumpy(),
                                a @ b, rtol=1e-5)
    onp.testing.assert_allclose(np.matmul(np.array(a), np.array(b)).asnumpy(),
                                a @ b, rtol=1e-5)
    onp.testing.assert_allclose(
        np.einsum("ij,jk->ik", np.array(a), np.array(b)).asnumpy(),
        a @ b, rtol=1e-5)
    sq = a @ a.T + 3 * onp.eye(3, dtype=onp.float32)
    onp.testing.assert_allclose(
        np.linalg.inv(np.array(sq)).asnumpy(), onp.linalg.inv(sq),
        rtol=1e-3, atol=1e-4)
    onp.testing.assert_allclose(
        np.linalg.norm(np.array(a)).asnumpy(), onp.linalg.norm(a),
        rtol=1e-5)
    onp.testing.assert_allclose(
        np.linalg.cholesky(np.array(sq)).asnumpy(), onp.linalg.cholesky(sq),
        rtol=1e-4, atol=1e-5)


def test_random():
    mx.random.seed(7)
    u = np.random.uniform(0, 1, size=(100,))
    assert u.shape == (100,)
    assert 0 <= float(u.asnumpy().min()) and float(u.asnumpy().max()) <= 1
    n = np.random.normal(0, 1, size=(500,))
    assert abs(float(n.asnumpy().mean())) < 0.2
    r = np.random.randint(0, 10, size=(50,))
    assert set(r.asnumpy().tolist()) <= set(range(10))
    assert np.random.randn(2, 3).shape == (2, 3)
    mx.random.seed(7)
    u2 = np.random.uniform(0, 1, size=(100,))
    onp.testing.assert_array_equal(u.asnumpy(), u2.asnumpy())


def test_np_arrays_flow_through_autograd_and_gluon():
    net = nn.Dense(4, in_units=3)
    net.initialize()
    x = np.random.normal(size=(2, 3))
    with autograd.record():
        y = net(x)
        loss = np.sum(y * y)
    loss.backward()
    g = net.weight.grad()
    assert g.shape == (4, 3)
    assert float(np.abs(g).asnumpy().sum()) > 0


def test_npx_set_np_and_ops():
    npx.set_np()
    assert npx.is_np_array() and npx.is_np_shape()
    npx.reset_np()
    assert not npx.is_np_array()
    x = np.array(R.randn(2, 5).astype(onp.float32))
    s = npx.softmax(x)
    onp.testing.assert_allclose(s.asnumpy().sum(axis=1), 1.0, rtol=1e-5)
    onp.testing.assert_allclose(npx.relu(x).asnumpy(),
                                onp.maximum(x.asnumpy(), 0))
    oh = npx.one_hot(np.array([0, 2]), depth=3)
    onp.testing.assert_array_equal(oh.asnumpy(),
                                   [[1, 0, 0], [0, 0, 1]])
    k = npx.topk(x, k=2, axis=-1)
    assert k.shape == (2, 2)


def test_npx_save_load_roundtrip(tmp_path):
    f = str(tmp_path / "arrs")
    npx.save(f, {"a": np.ones((2, 2)), "b": np.arange(3)})
    out = npx.load(f)
    onp.testing.assert_array_equal(out["a"].asnumpy(), onp.ones((2, 2)))
    onp.testing.assert_array_equal(out["b"].asnumpy(), onp.arange(3))


# -- review-finding regressions ----------------------------------------------

def test_pad_all_numpy_forms():
    x = onp.arange(6, dtype=onp.float32).reshape(2, 3)
    a = np.array(x)
    for pw in [1, (1, 2), ((1, 1), (0, 2))]:
        onp.testing.assert_array_equal(np.pad(a, pw).asnumpy(),
                                       onp.pad(x, pw))
    onp.testing.assert_array_equal(
        np.pad(a, 1, constant_values=5.0).asnumpy(),
        onp.pad(x, 1, constant_values=5.0))


def test_histogram_and_bincount():
    x = onp.array([0.1, 0.4, 0.4, 0.9], onp.float32)
    counts, edges = np.histogram(np.array(x), bins=4, range=(0, 1))
    c_ref, e_ref = onp.histogram(x, bins=4, range=(0, 1))
    onp.testing.assert_array_equal(counts.asnumpy(), c_ref)
    onp.testing.assert_allclose(edges.asnumpy(), e_ref, rtol=1e-6)
    counts2, _ = np.histogram(np.array(x))  # range inferred from data
    assert int(counts2.asnumpy().sum()) == 4
    b = onp.array([0, 1, 1, 3], onp.int32)
    onp.testing.assert_array_equal(np.bincount(np.array(b)).asnumpy(),
                                   onp.bincount(b))


def test_concatenate_axis_none_flattens():
    a = np.ones((2, 2))
    b = np.zeros((2, 2))
    out = np.concatenate([a, b], axis=None)
    assert out.shape == (8,)
    onp.testing.assert_array_equal(out.asnumpy(),
                                   onp.concatenate([onp.ones((2, 2)),
                                                    onp.zeros((2, 2))],
                                                   axis=None))


def test_like_ctx_and_randint_dtype():
    a = np.ones((2, 2), ctx=mx.cpu())
    z = np.zeros_like(a, dtype=onp.int32)
    assert z.context == a.context and str(z.dtype) == "int32"
    r = np.random.randint(0, 5, size=(4,), dtype="int64")
    assert str(r.dtype) in ("int64", "int32")  # int32 if x64 disabled


# ---------------------------------------------------------------------------
# numpy-surface tail + array interop protocols
# ---------------------------------------------------------------------------


def test_np_nan_family():
    x = np.array([[1.0, 2.0], [3.0, float("nan")]])
    assert float(np.nanmean(x)) == pytest.approx(2.0)
    assert float(np.nanmax(x)) == 3.0
    assert float(np.nansum(x)) == 6.0
    assert float(np.nanstd(x)) == pytest.approx(onp.nanstd(x.asnumpy()))


def test_np_set_ops_and_stacking():
    a = np.array([3, 1, 3, 2])
    assert np.unique(a).asnumpy().tolist() == [1, 2, 3]
    u = np.union1d(np.array([1, 2]), np.array([2, 3]))
    assert u.asnumpy().tolist() == [1, 2, 3]
    v = np.vstack([np.ones((1, 2)), np.zeros((1, 2))])
    assert v.shape == (2, 2)
    h = np.hstack([np.ones((2, 1)), np.zeros((2, 2))])
    assert h.shape == (2, 3)
    cs = np.column_stack([np.array([1.0, 2.0]), np.array([3.0, 4.0])])
    assert cs.shape == (2, 2)


def test_np_statistics_tail():
    x = np.array([[1.0, 2.0, 3.0], [2.0, 4.0, 6.5]])
    c = np.cov(x)
    onp.testing.assert_allclose(c.asnumpy(), onp.cov(x.asnumpy()),
                                rtol=1e-5)
    cc = np.corrcoef(x)
    onp.testing.assert_allclose(cc.asnumpy(), onp.corrcoef(x.asnumpy()),
                                rtol=1e-5)
    t = np.trapz(np.array([0.0, 1.0, 2.0]))
    assert float(t) == pytest.approx(2.0)
    g = np.gradient(np.array([0.0, 1.0, 4.0]))
    onp.testing.assert_allclose(g.asnumpy(), [1.0, 2.0, 3.0])
    yi = np.interp(np.array([0.5]), np.array([0.0, 1.0]),
                   np.array([10.0, 20.0]))
    assert float(yi.asnumpy()[0]) == pytest.approx(15.0)


def test_np_random_tail_deterministic():
    import mxnet_tpu as mx
    draws = {}
    for name, kwargs in [("beta", dict(a=2.0, b=3.0, size=(4,))),
                         ("laplace", dict(size=(4,))),
                         ("lognormal", dict(size=(4,))),
                         ("chisquare", dict(df=3.0, size=(4,))),
                         ("poisson", dict(lam=2.0, size=(4,)))]:
        mx.random.seed(11)
        a = getattr(np.random, name)(**kwargs).asnumpy()
        mx.random.seed(11)
        b = getattr(np.random, name)(**kwargs).asnumpy()
        onp.testing.assert_array_equal(a, b)
        draws[name] = a
    assert all(onp.isfinite(v).all() for v in draws.values())


def test_numpy_ufunc_protocol_returns_ndarray():
    """np.sqrt(mx_array) must stay device-resident (reference:
    mx.np.ndarray.__array_ufunc__)."""
    from mxnet_tpu.ndarray.ndarray import NDArray
    x = np.array([1.0, 4.0, 9.0])
    r = onp.sqrt(x)
    assert isinstance(r, NDArray)
    onp.testing.assert_allclose(r.asnumpy(), [1.0, 2.0, 3.0])
    r2 = onp.add(x, 1.0)
    assert isinstance(r2, NDArray)


def test_numpy_array_function_protocol():
    from mxnet_tpu.ndarray.ndarray import NDArray
    x = np.array([1.0, 2.0])
    r = onp.concatenate([x, x])
    assert isinstance(r, NDArray) and r.shape == (4,)
    s = onp.stack([x, x])
    assert isinstance(s, NDArray) and s.shape == (2, 2)


def test_numpy_protocol_kwargs_and_fallback_run_on_host():
    """ufunc kwargs (dtype=...) and numpy functions with no device impl
    coerce to host numpy instead of raising."""
    x = np.array([1.0, 4.0])
    r = onp.sqrt(x, dtype=onp.float64)
    assert isinstance(r, onp.ndarray) and r.dtype == onp.float64
    # polyfit grew a device impl in round 5: the protocol now routes it
    # on-device instead of host-coercing
    fit = onp.polyfit(onp.arange(4.0),
                      np.array(onp.arange(4.0, dtype=onp.float32)), 1)
    onp.testing.assert_allclose(onp.asarray(fit), [1.0, 0.0], atol=1e-5)
    # a numpy function with NO device impl still coerces to host numpy
    rq = onp.require(np.array([1.0, 3.0]), requirements=["C"])
    assert isinstance(rq, onp.ndarray)
    assert rq.tolist() == [1.0, 3.0]


def test_numpy_ufunc_records_on_tape():
    x = np.array([4.0])
    x.attach_grad()
    with autograd.record():
        y = onp.sqrt(x)
        y.sum().backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [0.25])


def test_np_random_binomial_array_p():
    import mxnet_tpu as mx
    mx.random.seed(0)
    b = np.random.binomial(10, onp.array([0.0, 1.0], onp.float32),
                           size=(2,))
    assert b.asnumpy().tolist() == [0, 10]


def test_npi_routing_numpy_semantics():
    """mx.np dispatches through the registered _npi_* layer: comparisons
    give bool, mixed dtypes promote numpy-style, results are tape-aware."""
    a = np.array([1.0, 2.0, 3.0])
    b = np.array([2.0, 2.0, 2.0])
    eq = np.equal(a, b)
    assert eq.dtype == onp.bool_, eq.dtype
    assert np.less(a, b).asnumpy().tolist() == [True, False, False]
    # int + float promotes (legacy mx.nd ops would not)
    i = np.array(onp.array([1, 2, 3], onp.int32))
    s = np.add(i, np.array([0.5, 0.5, 0.5]))
    assert "float" in str(s.dtype)
    # divmod / modf multi-output
    q, r = np.divmod(a, b)
    assert q.asnumpy().tolist() == [0.0, 1.0, 1.0]
    assert r.asnumpy().tolist() == [1.0, 0.0, 1.0]


def test_npi_routing_autograd():
    """_npi ops record on the tape like every registry op."""
    import mxnet_tpu as mx
    x = np.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with mx.autograd.record():
        y = np.sum(np.multiply(x, x))
    y.backward()
    assert onp.allclose(x.grad.asnumpy(), [2.0, 4.0, 6.0])


def test_npi_unique_and_sets():
    a = np.array(onp.array([3, 1, 2, 3, 1], onp.int32))
    u = np.unique(a)
    assert u.asnumpy().tolist() == [1, 2, 3]
    u, idx, inv, cnt = np.unique(a, return_index=True, return_inverse=True,
                                  return_counts=True)
    assert cnt.asnumpy().tolist() == [2, 1, 2]
    assert np.setdiff1d(a, np.array(onp.array([1], onp.int32))
                         ).asnumpy().tolist() == [2, 3]
    got = np.isin(a, np.array(onp.array([1, 2], onp.int32)))
    assert got.asnumpy().tolist() == [False, True, True, False, True]


# -- corner semantics battery (VERDICT r3 weak #8) ---------------------------

def test_np_mode_boolean_comparisons_and_masking():
    """Under npx.set_np() comparisons yield BOOL (numpy semantics, the
    reference set_np contract) and boolean mask indexing/assignment work;
    legacy float32 0/1 comparisons return once reset."""
    x = np.array(onp.array([-1.0, 2.0, -3.0, 4.0], "float32"))
    assert str((x > 0).dtype) == "float32"         # legacy default
    npx.set_np()
    try:
        m = x > 0
        assert m.dtype == onp.bool_
        assert x[m].asnumpy().tolist() == [2.0, 4.0]
        y = np.array(onp.array([-1.0, 2.0, -3.0, 4.0], "float32"))
        y[y < 0] = 0.0
        assert y.asnumpy().tolist() == [0.0, 2.0, 0.0, 4.0]
        assert (x == x).dtype == onp.bool_
        assert (x != x).asnumpy().any() == False  # noqa: E712
    finally:
        npx.reset_np()
    assert str((x > 0).dtype) == "float32"


def test_np_zero_d_scalars():
    s = np.sum(np.array(onp.array([1.0, 2.0], "float32")))
    assert s.shape == () and s.ndim == 0
    assert float(s) == 3.0 and s.item() == 3.0
    z = np.array(2.5)
    assert z.shape == () and float(z) == 2.5
    # 0-d participates in arithmetic and broadcasting
    out = np.add(z, np.array(onp.ones(3, "float32")))
    assert out.shape == (3,)
    # argmax of 0-d-producing reduce
    am = np.argmax(np.array(onp.array([3.0, 9.0, 1.0], "float32")))
    assert am.shape == () and int(am.item()) == 1


def test_np_function_promotion_rules():
    """mx.np FUNCTIONS use numpy promotion (via the _npi layer) even
    though legacy operators keep MXNet dtype rules by design."""
    i = np.array(onp.array([1, 2, 3], "int32"))
    assert "float" in str(np.add(i, 0.5).dtype)
    assert "float" in str(np.true_divide(i, np.array(
        onp.array([2, 2, 2], "int32"))).dtype)
    b = np.greater(i, 1)
    assert b.dtype == onp.bool_
    assert str(np.sum(b).dtype).startswith("int")     # bool sums to int


def test_np_block_choose_putalong_ix():
    """The np.block/choose/put_along_axis/ix_/tril_indices_from family
    (2.x mx.np breadth) against numpy."""
    a = np.array([[1.0, 2], [3, 4]])
    assert np.block([[a, a], [a, a]]).shape == (4, 4)
    onp.testing.assert_allclose(
        np.block([a, a]).asnumpy(), onp.block([a.asnumpy(), a.asnumpy()]))
    c = np.choose(np.array([0, 1], dtype="int32"),
                  [np.array([1.0, 2]), np.array([10.0, 20])])
    onp.testing.assert_allclose(c.asnumpy(), [1, 20])
    arr = np.zeros((2, 3))
    np.put_along_axis(arr, np.array([[0], [2]], dtype="int32"),
                      np.array([[5.0], [7.0]]), axis=1)
    onp.testing.assert_allclose(arr.asnumpy(), [[5, 0, 0], [0, 0, 7]])
    r, c2 = np.tril_indices_from(a)
    er, ec = onp.tril_indices_from(a.asnumpy())
    assert onp.array_equal(r.asnumpy(), er)
    assert onp.array_equal(c2.asnumpy(), ec)
    ix = np.ix_(np.array([0, 1], dtype="int32"),
                np.array([1], dtype="int32"))
    assert ix[0].shape == (2, 1) and ix[1].shape == (1, 1)
    r3, c3 = np.mask_indices(3, np.triu, 1)
    er3, ec3 = onp.mask_indices(3, onp.triu, 1)
    assert onp.array_equal(r3.asnumpy(), er3)
    assert onp.array_equal(c3.asnumpy(), ec3)
