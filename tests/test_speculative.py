"""Speculative decoding + multi-model packing tests (ISSUE 20):
draft/verify window bit-identity against the greedy oracle across
prompts x spec_k, forced draft disagreement (full and partial window
rejection) via a monkeypatched draft step — output must stay bit-exact
while the window arithmetic degrades exactly as the acceptance rule
says — the census-driven ModelHost packer refusing a budget-busting
admission with a typed in-band error, two co-hosted models answering
isolated predictions with per-model telemetry labels, and the exact
speculative dispatch plan (tools/dispatch_count.py --speculative).
"""
import numpy as np
import pytest

from mxnet_tpu.base import MXNetError
from mxnet_tpu.kvstore.wire_codec import decode_array, encode_array
from mxnet_tpu.serve import (BucketTable, ModelHost, Servable,
                             ServeServer)
from mxnet_tpu.serve.decode import (DecodeConfig, DraftDecodeServable,
                                    PagedDecodeBatcher,
                                    PagedDecodeServable,
                                    SpeculativeDecodeBatcher,
                                    demo_spec_pair, reference_generate)
from mxnet_tpu.serve.demo import (DEMO_IN, demo_block, demo_example,
                                  demo_expected)
from mxnet_tpu.serve.servable import BudgetExceeded
from mxnet_tpu.telemetry import registry

# tiny paged geometry shared by every engine in this file: 3 slot
# buckets + 1 chunk program on the target, 2 draft prefill buckets,
# 3 verify buckets — cheap enough to warm per test
SCFG = dict(dim=16, heads=2, layers=2, slots=4, max_tokens=24,
            prompt_buckets=(4, 8), kv_page_len=4, prefill_chunk=4,
            kv_pages=30)

PROMPTS = ([3, 1, 4], [2, 7, 1, 8, 2, 8], [5, 5], [9, 3, 9, 8, 1])
NEWS = (6, 11, 13, 8)


def _pair(spec_k, draft_layers=1):
    cfg = DecodeConfig(spec_k=spec_k, **SCFG)
    tparams, dcfg, dparams = demo_spec_pair(cfg,
                                            draft_layers=draft_layers)
    sv = PagedDecodeServable(params=tparams, config=cfg)
    draft = DraftDecodeServable(params=dparams, config=dcfg,
                                name="demo-lm-draft")
    return sv, draft, cfg


# ---------------------------------------------------------------------------
# bit-identity: speculative == greedy oracle == plain paged engine
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("k", (1, 4))
def test_speculative_bit_identity_across_prompts(k):
    """Every emitted token is the target's own argmax: across window
    sizes the speculative engine's output must equal the greedy oracle
    token for token, with zero warm retraces on either model.  At k=4
    the acceptance statement is also checked verbatim: the identical
    workload through the PLAIN paged engine emits identical tokens
    (the draft only changes the dispatch count)."""
    sv, draft, cfg = _pair(k)
    eng = SpeculativeDecodeBatcher(sv, draft, autostart=False)
    try:
        r0 = sv.retraces + draft.retraces
        gens = [eng.submit(list(p), max_new=n)
                for p, n in zip(PROMPTS, NEWS)]
        eng.drain_sync()
        refs = [reference_generate(list(p), n, params=sv.params,
                                   config=cfg)
                for p, n in zip(PROMPTS, NEWS)]
        spec_outs = [g.tokens_so_far() for g in gens]
        assert spec_outs == refs
        assert sv.retraces + draft.retraces == r0
    finally:
        eng.close()
    if k != 4:
        return
    plain_sv = PagedDecodeServable(params=sv.params, config=cfg)
    plain = PagedDecodeBatcher(plain_sv, autostart=False)
    try:
        gens = [plain.submit(list(p), max_new=n)
                for p, n in zip(PROMPTS, NEWS)]
        plain.drain_sync()
        assert [g.tokens_so_far() for g in gens] == spec_outs
    finally:
        plain.close()


# ---------------------------------------------------------------------------
# forced accept/reject: a corrupted draft degrades throughput, never
# correctness
# ---------------------------------------------------------------------------


def test_forced_draft_disagreement(monkeypatch):
    """Corrupt every proposal column >= ``corrupt_from`` AFTER the
    draft step ran (draft_layers == layers, so uncorrupted columns
    agree with the target exactly): each window then commits exactly
    ``min(corrupt_from, k-1) + 1`` tokens, the window count follows,
    and the output still equals the greedy oracle bit for bit.  One
    engine serves every corruption point — the cell flips between
    workloads (full rejection, 1-token and 2-token partial accepts)."""
    k = 4
    orig = DraftDecodeServable.dispatch_step
    cell = {"corrupt_from": k}          # no corruption while warming

    def corrupted(self, slot_ids, col):
        props = orig(self, slot_ids, col)
        if col >= cell["corrupt_from"]:
            st = dict(self._state)
            st["props"] = st["props"].at[:, col].set(
                (st["props"][:, col] + 1) % self.config.vocab)
            self._state = st
            props = st["props"]
        return props

    monkeypatch.setattr(DraftDecodeServable, "dispatch_step",
                        corrupted)
    sv, draft, cfg = _pair(k, draft_layers=SCFG["layers"])
    eng = SpeculativeDecodeBatcher(sv, draft, autostart=False)
    try:
        for corrupt_from in (0, 1, 2):
            cell["corrupt_from"] = corrupt_from
            n_em = min(corrupt_from, k - 1) + 1
            for prompt, max_new in zip(PROMPTS[:2], (9, 12)):
                w0 = registry.value("serve.decode.spec_windows")
                g = eng.submit(list(prompt), max_new=max_new)
                eng.drain_sync()
                ref = reference_generate(list(prompt), max_new,
                                         params=sv.params, config=cfg)
                assert g.tokens_so_far() == ref
                windows = registry.value(
                    "serve.decode.spec_windows") - w0
                assert windows == -(-(len(ref) - 1) // n_em), \
                    "acceptance rule: %d tokens per window" % n_em
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# packer refusal + two-model isolation (the ModelHost side)
# ---------------------------------------------------------------------------


def _demo_sv(name, version=1, scale=None):
    net = demo_block()
    if scale is not None:
        for p in net.collect_params().values():
            p.set_data(p.data() * scale)
    return Servable(net, name=name, version=version,
                    buckets=BucketTable((1, 2))), net


def test_packer_refuses_budget_busting_third_model():
    """Two models fit the HBM budget; the third's censused footprint
    (params + warm temp peak) busts it — deploy must raise the TYPED
    BudgetExceeded (an MXNetError: in-band refusal on the wire, never
    a crashed replica) and leave the two admitted models untouched."""
    probe = ModelHost()
    sv0, _ = _demo_sv("probe")
    probe.deploy(sv0, example=demo_example())
    foot = probe.used_bytes()
    assert foot > 0

    host = ModelHost(hbm_budget=int(2.5 * foot))
    for name in ("m-a", "m-b"):
        sv, _ = _demo_sv(name)
        host.deploy(sv, example=demo_example())
    third, _ = _demo_sv("m-c")
    with pytest.raises(BudgetExceeded) as ei:
        host.deploy(third, example=demo_example())
    assert isinstance(ei.value, MXNetError)
    msg = str(ei.value)
    assert "MX_SERVE_HBM_BUDGET" in msg and "m-c" in msg
    # the refusal names the incumbents and changed nothing
    assert list(host.models()) == ["m-a", "m-b"]
    assert host.version_of("m-a") == 1 and host.version_of("m-b") == 1
    report = host.packing_report()
    assert report["hbm_budget_bytes"] == int(2.5 * foot)
    assert report["used_bytes"] <= report["hbm_budget_bytes"]
    assert set(report["models"]) == {"m-a", "m-b"}


def test_two_model_isolation_and_per_model_metrics():
    """One replica, two co-hosted models with different weights: a
    routed PREDICT answers from the named model's own engine (outputs
    match that model's net, versions don't bleed), an unknown name is
    refused in-band, and the serve counters carry per-model labels."""
    host = ModelHost()
    sv1, net1 = _demo_sv("demo")
    host.deploy(sv1, example=demo_example())
    state = ServeServer(host=host, max_delay_us=0, queue_cap=16)
    try:
        sv2, net2 = _demo_sv("demo-b", version=7, scale=3.0)
        state.add_model(sv2, example=demo_example(), max_delay_us=0)
        x = np.random.RandomState(5).rand(1, DEMO_IN).astype(np.float32)
        c1 = registry.value("serve.requests",
                            labels={"model": "demo"})
        c2 = registry.value("serve.requests",
                            labels={"model": "demo-b"})
        ok, (ver, outs) = state.handle(("PREDICT", [encode_array(x)]))
        assert ok and ver == 1
        np.testing.assert_allclose(decode_array(outs[0]),
                                   demo_expected(x, net=net1),
                                   rtol=1e-4, atol=1e-5)
        ok, (ver, outs) = state.handle(
            ("PREDICT", [encode_array(x)], "demo-b"))
        assert ok and ver == 7
        np.testing.assert_allclose(decode_array(outs[0]),
                                   demo_expected(x, net=net2),
                                   rtol=1e-4, atol=1e-5)
        # isolation: each model's labeled request counter moved by
        # exactly its own traffic
        assert registry.value("serve.requests",
                              labels={"model": "demo"}) == c1 + 1
        assert registry.value("serve.requests",
                              labels={"model": "demo-b"}) == c2 + 1
        ok, reason = state.handle(
            ("PREDICT", [encode_array(x)], "nope"))
        assert ok is False and "unknown model" in reason
        assert "demo" in reason and "demo-b" in reason
        # the packing report rides HEALTH once the host is multi-model
        assert state.health()["packing"]["models"]
    finally:
        state.close()


# ---------------------------------------------------------------------------
# dispatch plan
# ---------------------------------------------------------------------------


def test_speculative_dispatch_plan_pinned():
    """tools/dispatch_count.py --speculative: the sequential lane is
    closed-form exact (chunks + draft prefill + k draft + 1 verify per
    window), the concurrent lane satisfies the accounting identity
    under the <=1-dispatch-per-tick budget, zero retraces."""
    from tools.dispatch_count import run_speculative
    res = run_speculative(n_gens=2, prompt_len=8, max_new=9, slots=4,
                          spec_k=4)
    assert res["ok"], res
    assert res["sequential_dispatches"] == res["expected_sequential"]
    assert res["max_dispatches_per_tick"] <= res["tick_budget"]
    assert res["retraces"] == 0
