"""dist_sync contract for kvstore='ici': cross-process allreduce.

Reference pattern: tests/nightly/dist_sync_kvstore.py — N local worker
processes push rank-distinguishable payloads and assert the pull equals the
num_workers-sum (src/kvstore/kvstore_dist.h KVStoreDist::PushPullImpl
semantics), plus a Trainer.step gradient-equality check across processes.
"""
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _launch(script_path, n=2, xla_flags=None):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # conftest's forced 8-dev count breaks pairing
    if xla_flags:
        env["XLA_FLAGS"] = xla_flags
    for attempt in range(2):   # retry once: the free-port pick can race
        r = subprocess.run([sys.executable,
                            os.path.join(REPO, "tools", "launch.py"),
                            "-n", str(n), "--launcher", "local", "--",
                            sys.executable, str(script_path)],
                           capture_output=True, text=True, timeout=300,
                           env=env)
        if r.returncode == 0:
            return r.stdout
    assert r.returncode == 0, (r.stdout, r.stderr)
    return r.stdout


_PRELUDE = """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["MX_FORCE_CPU"] = "1"
    sys.path.insert(0, %r)
    import mxnet_tpu as mx
    from mxnet_tpu.parallel import init_process_group
    init_process_group()
    import jax
    import numpy as np
    from mxnet_tpu import nd, autograd
""" % REPO


def test_pushpull_is_num_workers_sum(tmp_path):
    script = tmp_path / "w.py"
    script.write_text(textwrap.dedent(_PRELUDE) + textwrap.dedent("""
        from mxnet_tpu import kvstore
        kv = kvstore.create("ici")
        assert kv.num_workers == 2, kv.num_workers
        rank = kv.rank

        # float payload: worker r pushes (r+1)*base; pull must be 3*base
        base = np.array([1., 2., 3., 4.], np.float32)
        kv.init("f", nd.zeros((4,)))
        kv.push("f", nd.array(base * (rank + 1)))
        out = nd.zeros((4,))
        kv.pull("f", out=out)
        np.testing.assert_allclose(out.asnumpy(), base * 3, rtol=1e-6)

        # integer payload must be exact (no averaging artifacts)
        kv.init("i", nd.zeros((3,), dtype="int32"))
        kv.push("i", nd.array(np.full(3, rank + 10, np.int32)))
        oi = nd.zeros((3,), dtype="int32")
        kv.pull("i", out=oi)
        np.testing.assert_array_equal(oi.asnumpy(), np.full(3, 21, np.int32))

        # fused pushpull
        kv.init("g", nd.zeros((2,)))
        o = nd.zeros((2,))
        kv.pushpull("g", nd.array(np.full(2, rank + 1.0, np.float32)), out=o)
        np.testing.assert_allclose(o.asnumpy(), [3., 3.])
        print("PUSHPULL_OK rank", rank, flush=True)
    """))
    out = _launch(script)
    assert out.count("PUSHPULL_OK") == 2


def test_pushpull_multi_local_device(tmp_path):
    """2 processes x 2 local devices: the payload rides local device 0,
    zeros pad the rest — the sum must still be the num_workers-sum."""
    script = tmp_path / "w.py"
    script.write_text(textwrap.dedent(_PRELUDE) + textwrap.dedent("""
        assert len(jax.local_devices()) == 2, jax.local_devices()
        from mxnet_tpu import kvstore
        kv = kvstore.create("ici")
        rank = kv.rank
        kv.init("k", nd.zeros((5,)))
        kv.push("k", nd.array(np.arange(5, dtype=np.float32) + 10 * rank))
        out = nd.zeros((5,))
        kv.pull("k", out=out)
        expect = 2 * np.arange(5, dtype=np.float32) + 10.0  # sum of ranks
        np.testing.assert_allclose(out.asnumpy(), expect, rtol=1e-6)
        print("MULTIDEV_OK rank", rank, flush=True)
    """))
    out = _launch(script,
                  xla_flags="--xla_force_host_platform_device_count=2")
    assert out.count("MULTIDEV_OK") == 2


def test_trainer_step_matches_serial_reference(tmp_path):
    """Each worker trains on its own batch; after Trainer.step the weights
    must (a) be identical across workers and (b) equal the serial update
    computed from BOTH batches — the reference's dist-sync training
    invariant."""
    script = tmp_path / "w.py"
    script.write_text(textwrap.dedent(_PRELUDE) + textwrap.dedent("""
        from jax.experimental import multihost_utils
        rank = jax.process_index()

        def fresh_net(w=None):
            mx.random.seed(7)           # identical init across RANKS (the
            net = mx.gluon.nn.Dense(1, use_bias=False, in_units=3)
            net.initialize(mx.init.Xavier())
            if w is not None:           # draw order advances the stream, so
                net.weight.set_data(nd.array(w))  # clones copy explicitly
            return net

        def batch(r):
            rng = np.random.RandomState(100 + r)
            x = rng.randn(4, 3).astype(np.float32)
            y = rng.randn(4, 1).astype(np.float32)
            return nd.array(x), nd.array(y)

        def grad_of(net, x, y):
            with autograd.record():
                loss = ((net(x) - y) ** 2).mean()
            loss.backward()
            return net.weight.grad().asnumpy().copy()

        # -- distributed: my batch only, Trainer with kvstore='ici' --------
        net = fresh_net()
        w0 = net.weight.data().asnumpy().copy()
        trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                                   {"learning_rate": 0.5, "wd": 0.0},
                                   kvstore="ici")
        x, y = batch(rank)
        with autograd.record():
            loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        trainer.step(2)                  # global batch: 2 workers
        w_dist = net.weight.data().asnumpy()

        # -- serial reference: both batches, num_workers-sum of grads ------
        ref = fresh_net(w0)
        g0 = grad_of(ref, *batch(0))
        g1 = grad_of(ref, *batch(1))
        w_exp = w0 - 0.5 * (g0 + g1) / 2.0

        np.testing.assert_allclose(w_dist, w_exp, rtol=1e-5, atol=1e-6)
        # identical across workers
        allw = multihost_utils.process_allgather(w_dist)
        np.testing.assert_allclose(allw[0], allw[-1], rtol=0, atol=0)
        print("TRAINER_OK rank", rank, flush=True)
    """))
    out = _launch(script)
    assert out.count("TRAINER_OK") == 2


def test_gradient_compression_bf16(tmp_path):
    """set_gradient_compression({'type': 'bf16'}) casts the allreduce
    payload to bfloat16; an unknown type raises (never a silent no-op)."""
    script = tmp_path / "w.py"
    script.write_text(textwrap.dedent(_PRELUDE) + textwrap.dedent("""
        import warnings
        from mxnet_tpu import kvstore
        kv = kvstore.create("ici")
        rank = kv.rank
        kv.set_gradient_compression({"type": "bf16"})
        kv.init("c", nd.zeros((4,)))
        v = np.array([1.0, 2.0, 3.0, 4.5], np.float32)
        kv.push("c", nd.array(v * (rank + 1)))
        out = nd.zeros((4,))
        kv.pull("c", out=out)
        # bf16 has ~3 decimal digits: sum 3*v to bf16 precision
        np.testing.assert_allclose(out.asnumpy(), 3 * v, rtol=2e-2)
        assert out.dtype == np.float32          # decompressed on arrival
        # '2bit'/'int8' are real schemes (no warning); junk RAISES
        # (upstream MXNet contract — never a silent no-op)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
            kv.set_gradient_compression({"type": "int8"})
        assert not w, [str(x.message) for x in w]
        try:
            kv.set_gradient_compression({"type": "1bit"})
        except ValueError as e:
            assert "1bit" in str(e)
        else:
            raise AssertionError("unsupported type must raise ValueError")
        print("COMPRESS_OK rank", rank, flush=True)
    """))
    out = _launch(script)
    assert out.count("COMPRESS_OK") == 2


def test_trainstep_two_process_dp(tmp_path):
    """The north-star path multi-host: parallel.TrainStep whole-step jit
    over a GLOBAL dp mesh spanning both processes — each worker feeds its
    local batch shard, gradients allreduce inside the jitted step, params
    stay replicated and identical."""
    script = tmp_path / "w.py"
    script.write_text(textwrap.dedent(_PRELUDE) + textwrap.dedent("""
        import jax.numpy as jnp
        from jax.experimental import multihost_utils
        from mxnet_tpu.parallel import make_mesh, TrainStep
        rank = jax.process_index()

        mx.random.seed(5)                   # identical init on both ranks
        net = mx.gluon.nn.Dense(2, in_units=4)
        net.initialize(mx.init.Xavier())
        net(nd.zeros((1, 4)))

        def loss_fn(out, labels):
            return jnp.mean((out - labels) ** 2)

        mesh = make_mesh(axes=("dp",), devices=jax.devices())  # GLOBAL
        step = TrainStep(net, loss_fn, mesh, learning_rate=0.1)
        rng = np.random.RandomState(42)     # same on both ranks
        xg = rng.randn(8, 4).astype(np.float32)   # global batch
        yg = rng.randn(8, 2).astype(np.float32)
        # each process feeds ITS half (dp=2 -> rows split in two)
        xl, yl = xg[rank * 4:(rank + 1) * 4], yg[rank * 4:(rank + 1) * 4]
        losses = []
        for _ in range(5):
            loss = step(xl, yl)
            losses.append(float(np.asarray(jax.device_get(
                loss._jax if hasattr(loss, "_jax") else loss))))
        assert losses[-1] < losses[0], losses
        # params are dp-replicated: both processes see identical values
        w = np.asarray(jax.device_get(
            list(step.params.values())[0].addressable_data(0)))
        allw = multihost_utils.process_allgather(w.ravel())
        np.testing.assert_allclose(allw[0], allw[-1], rtol=0, atol=0)
        print("TRAINSTEP_OK rank", rank, "loss", round(losses[-1], 4),
              flush=True)
    """))
    out = _launch(script)
    assert out.count("TRAINSTEP_OK") == 2


def test_trainer_update_on_kvstore_two_process(tmp_path):
    """update_on_kvstore=True multi-process (the reference's server-side
    optimizer): every worker's store applies the SAME summed gradient, so
    weights stay identical and match the serial update."""
    script = tmp_path / "w.py"
    script.write_text(textwrap.dedent(_PRELUDE) + textwrap.dedent("""
        from jax.experimental import multihost_utils
        rank = jax.process_index()
        mx.random.seed(3)
        net = mx.gluon.nn.Dense(2, use_bias=False, in_units=3)
        net.initialize(mx.init.Xavier())
        w0 = net.weight.data().asnumpy().copy()
        trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                                   {"learning_rate": 0.4, "wd": 0.0},
                                   kvstore="ici", update_on_kvstore=True)
        rng = np.random.RandomState(50 + rank)
        x = nd.array(rng.randn(4, 3).astype(np.float32))
        y = nd.array(rng.randn(4, 2).astype(np.float32))
        with autograd.record():
            loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        g_local = net.weight.grad().asnumpy().copy()
        trainer.step(2)
        w1 = net.weight.data().asnumpy()
        # serial: sum of both workers' grads, applied once
        allg = multihost_utils.process_allgather(g_local)
        gsum = allg.reshape(2, *g_local.shape).sum(axis=0)
        # update_on_kvstore: optimizer rescale_grad = 1/batch_size
        w_exp = w0 - 0.4 * gsum / 2.0
        np.testing.assert_allclose(w1, w_exp, rtol=1e-5, atol=1e-6)
        allw = multihost_utils.process_allgather(w1)
        np.testing.assert_allclose(allw[0], allw[-1], rtol=0, atol=0)
        print("UOK_OK rank", rank, flush=True)
    """))
    out = _launch(script)
    assert out.count("UOK_OK") == 2


def test_2bit_compression_two_process_sum_with_residual(tmp_path):
    """VERDICT r3 #5: dist contract of {'type': '2bit'} — each worker
    quantizes its pushed grad to {-t, 0, +t} with per-key error feedback;
    the pull is the num_workers-sum of the quantized levels, and over many
    pushes the accumulated sum tracks the true sum within num_workers *
    threshold per element (residual never exceeds the threshold band)."""
    import textwrap as tw
    script = tmp_path / "w.py"
    script.write_text(tw.dedent(_PRELUDE) + tw.dedent("""
        from mxnet_tpu import kvstore
        kv = kvstore.create("ici")
        rank = kv.rank
        t = 0.5
        kv.set_gradient_compression({"type": "2bit", "threshold": t})

        # per-step |g| must stay under the threshold: 2-bit can emit at
        # most one +-t level per step (the reference has the same tracking
        # condition)
        g = np.array([0.1, -0.2, 0.15, 0.05], np.float32) * (rank + 1)
        kv.init("w", nd.zeros((4,)))
        total = np.zeros(4, np.float32)
        for step in range(8):
            kv.push("w", nd.array(g))
            out = nd.zeros((4,))
            kv.pull("w", out=out)
            got = out.asnumpy()
            # every pulled element is a sum of 2 workers' levels from
            # {-t, 0, +t}
            lv = np.array([-2*t, -t, 0.0, t, 2*t], np.float32)
            assert all(np.isclose(lv, v).any() for v in got), got
            total += got
        # error feedback: per worker the emitted sum differs from the true
        # sum by the final residual, |residual| < t + |g|_max
        base = g / (rank + 1)
        true = 8 * 3 * base                      # g_0 + g_1 = 3 * base
        bound = 2 * (t + np.abs(g).max())
        assert np.all(np.abs(total - true) <= bound), (total, true)
        print("COMPRESS2BIT_OK rank", rank, flush=True)
    """))
    out = _launch(script)
    assert out.count("COMPRESS2BIT_OK") == 2


def test_dist_async_parameter_server(tmp_path):
    """dist_async contract (reference: kvstore_dist_server.h DataHandleEx
    async path + tests/nightly/dist_async_kvstore.py): a real PS process
    applies each worker's push IMMEDIATELY (server-side optimizer), pulls
    return current state, and a worker progresses without the other."""
    import textwrap as tw
    script = tmp_path / "w.py"
    script.write_text(tw.dedent(_PRELUDE) + tw.dedent("""
        from mxnet_tpu import kvstore, optimizer
        kv = kvstore.create("dist_async")
        assert kv.type == "dist_async"
        rank = kv.rank
        assert kv.num_workers == 2

        kv.init("w", nd.ones((4,)))
        kv.set_optimizer(optimizer.SGD(learning_rate=0.5))

        # ASYNC: this worker pushes and pulls alone — no barrier, the
        # other worker's participation is not required for progress
        g = np.full(4, 1.0, np.float32)
        kv.push("w", nd.array(g))
        out = nd.zeros((4,))
        kv.pull("w", out=out)
        v = out.asnumpy()
        # server applied AT LEAST this worker's update; each update is
        # -0.5*g, so value is 1 - 0.5*k for k pushes seen so far
        k = round(float((1.0 - v[0]) / 0.5))
        assert k >= 1 and np.allclose(v, 1.0 - 0.5 * k), v

        # after both workers barrier, exactly 2 pushes are in
        kv._barrier()
        kv.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), 1.0 - 0.5 * 2)

        # row_sparse_pull must see CURRENT server state, not the
        # init-time mirror
        from mxnet_tpu.ndarray.sparse import RowSparseNDArray
        kv.init("emb", nd.ones((6, 2)))
        kv.push("emb", nd.array(np.ones((6, 2), np.float32)))
        kv._barrier()
        tgt = nd.sparse.row_sparse_array(
            (np.zeros((2, 2), np.float32), np.array([1, 4])), shape=(6, 2))
        kv.row_sparse_pull("emb", out=tgt, row_ids=nd.array([1, 4]))
        got = tgt.data.asnumpy()
        assert not np.allclose(got, 1.0), got   # moved off the init value
        print("DIST_ASYNC_OK rank", rank, flush=True)
    """))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "launch.py"),
                        "-n", "2", "-s", "1", "--launcher", "local", "--",
                        sys.executable, str(script)],
                       capture_output=True, text=True, timeout=300,
                       env=env)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert r.stdout.count("DIST_ASYNC_OK") == 2


def test_dist_async_without_server_degrades_loudly(tmp_path):
    import warnings
    from mxnet_tpu import kvstore
    for var in ("MX_PS_ROOT", "DMLC_PS_ROOT_URI"):
        os.environ.pop(var, None)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        kv = kvstore.create("dist_async")
    assert any("parameter server" in str(x.message) for x in w)
    assert kv.type == "ici"


def test_dist_async_two_servers_key_sharding(tmp_path):
    """-s 2: keys hash-shard across two PS processes; every key's
    init/push/pull routes to the same server and values stay correct."""
    import textwrap as tw
    script = tmp_path / "w.py"
    script.write_text(tw.dedent(_PRELUDE) + tw.dedent("""
        from mxnet_tpu import kvstore, optimizer
        kv = kvstore.create("dist_async")
        assert len(kv._socks) == 2, len(kv._socks)
        rank = kv.rank
        kv.set_optimizer(optimizer.SGD(learning_rate=0.5))
        # enough keys to land on both servers
        keys = list(range(8))
        servers = {k: kv._server_of(k) for k in keys}
        assert set(servers.values()) == {0, 1}, servers
        for k in keys:
            kv.init(k, nd.ones((3,)) * (k + 1))
        kv._barrier()
        for k in keys:
            kv.push(k, nd.array(np.full(3, 2.0, np.float32)))
        kv._barrier()
        for k in keys:
            out = nd.zeros((3,))
            kv.pull(k, out=out)
            # init (k+1) minus 0.5*2.0 per push, 2 workers
            np.testing.assert_allclose(out.asnumpy(), (k + 1) - 2.0,
                                       rtol=1e-6)
        print("SHARDED_PS_OK rank", rank, flush=True)
    """))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "launch.py"),
                        "-n", "2", "-s", "2", "--launcher", "local", "--",
                        sys.executable, str(script)],
                       capture_output=True, text=True, timeout=300,
                       env=env)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert r.stdout.count("SHARDED_PS_OK") == 2


def test_dist_async_bigarray_shards_across_servers(tmp_path):
    """MXNET_KVSTORE_BIGARRAY_BOUND (reference kvstore_dist.h): tensors
    over the bound split EVENLY across ALL servers (flat slices) instead
    of hashing whole to one; small tensors keep whole-key routing; the
    server-side optimizer updates each slice correctly."""
    import textwrap as tw
    script = tmp_path / "w.py"
    script.write_text(tw.dedent(_PRELUDE) + tw.dedent("""
        from mxnet_tpu import kvstore, optimizer
        kv = kvstore.create("dist_async")
        assert len(kv._socks) == 2
        assert kv._bigarray_bound == 10        # env reached the store
        rank = kv.rank
        kv.set_optimizer(optimizer.SGD(learning_rate=0.5))

        big = np.arange(24, dtype=np.float32).reshape(4, 6)  # 24 >= 10
        small = np.ones(3, np.float32)
        kv.init("big", nd.array(big))
        kv.init("small", nd.array(small))
        kv._barrier()

        # each server holds ONLY its slice: part keys answer directly
        p0 = np.asarray(kv._rpc_on(0, "PULL", "big::part0")).ravel()
        p1 = np.asarray(kv._rpc_on(1, "PULL", "big::part1")).ravel()
        np.testing.assert_allclose(p0, np.arange(12, dtype=np.float32))
        np.testing.assert_allclose(p1,
                                   np.arange(12, 24, dtype=np.float32))

        kv.push("big", nd.array(np.ones((4, 6), np.float32)))
        kv.push("small", nd.array(np.ones(3, np.float32)))
        kv._barrier()
        out = nd.zeros((4, 6))
        kv.pull("big", out=out)
        # 2 workers pushed grad=1 each at lr 0.5 -> value - 1.0
        np.testing.assert_allclose(out.asnumpy(), big - 1.0, rtol=1e-6)
        outs = nd.zeros((3,))
        kv.pull("small", out=outs)
        np.testing.assert_allclose(outs.asnumpy(), small - 1.0, rtol=1e-6)
        print("BIGARRAY_OK rank", rank, flush=True)
    """))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["MXNET_KVSTORE_BIGARRAY_BOUND"] = "10"
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "launch.py"),
                        "-n", "2", "-s", "2", "--launcher", "local", "--",
                        sys.executable, str(script)],
                       capture_output=True, text=True, timeout=300,
                       env=env)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert r.stdout.count("BIGARRAY_OK") == 2
