"""Gluon Block/Parameter/hybridize tests.

Modeled on the reference's tests/python/unittest/test_gluon.py patterns:
run imperative, hybridize, run again, assert identical outputs; parameter
shape/save/load semantics; trainer updates.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn


def test_parameter_basic():
    p = gluon.Parameter("weight", shape=(10, 10))
    p.initialize(init="xavier", ctx=mx.cpu())
    assert p.name == "weight"
    assert p.data().shape == (10, 10)
    assert p.grad().shape == (10, 10)
    assert len(p.list_data()) == 1
    p.zero_grad()
    assert np.allclose(p.grad().asnumpy(), 0)


def test_parameter_invalid_access():
    p = gluon.Parameter("weight", shape=(10, 10))
    with pytest.raises(RuntimeError):
        p.data()


def test_constant():
    value = np.random.rand(4, 5)
    c = gluon.Constant(value, name="const")
    c.initialize()
    assert c.grad_req == "null"
    assert np.allclose(c.data().asnumpy(), value.astype(np.float32), atol=1e-6)


def test_collect_params_structural_names():
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Dense(2))
    params = net.collect_params()
    names = set(params.keys())
    assert "0.weight" in names and "1.bias" in names


def test_collect_params_select():
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Dense(2))
    only_w = net.collect_params(".*weight")
    assert all(k.endswith("weight") for k in only_w.keys())
    assert len(list(only_w.keys())) == 2


def test_deferred_init_and_infer_shape():
    d = nn.Dense(16)
    d.initialize()
    x = mx.nd.ones((2, 7))
    y = d(x)
    assert y.shape == (2, 16)
    assert d.weight.shape == (16, 7)


def test_uninitialized_raises():
    d = nn.Dense(16)
    x = mx.nd.ones((2, 7))
    with pytest.raises(RuntimeError):
        d(x)


def test_hybridize_consistency():
    """The canonical pattern: imperative output == hybridized output."""
    np.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(16),
            nn.LayerNorm(), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    x = mx.nd.array(np.random.randn(5, 12).astype(np.float32))
    imp = net(x).asnumpy()
    net.hybridize()
    hyb1 = net(x).asnumpy()   # cache-building call
    hyb2 = net(x).asnumpy()   # cached call
    assert np.allclose(imp, hyb1, atol=1e-5)
    assert np.allclose(imp, hyb2, atol=1e-5)


def test_hybridize_backward_matches_imperative():
    np.random.seed(0)

    def build():
        net = nn.HybridSequential()
        net.add(nn.Dense(8, activation="tanh"), nn.Dense(3))
        return net

    x = mx.nd.array(np.random.randn(4, 6).astype(np.float32))
    net1 = build()
    net1.initialize(mx.init.Xavier())
    with autograd.record():
        loss1 = (net1(x) ** 2).sum()
    loss1.backward()
    g1 = net1[0].weight.grad().asnumpy()

    net2 = build()
    net2.load_dict = None
    # copy params
    net2.initialize(mx.init.Xavier())
    for (_, a), (_, b) in zip(net2.collect_params().items(),
                              net1.collect_params().items()):
        a.set_data(b.data())
    net2.hybridize()
    with autograd.record():
        loss2 = (net2(x) ** 2).sum()
    loss2.backward()
    g2 = net2[0].weight.grad().asnumpy()
    assert np.allclose(float(loss1.asscalar()), float(loss2.asscalar()),
                       rtol=1e-5)
    assert np.allclose(g1, g2, atol=1e-5)


def test_hybridize_retrace_on_new_shape():
    net = nn.Dense(4, in_units=3)
    net.initialize()
    net.hybridize()
    y1 = net(mx.nd.ones((2, 3)))
    y2 = net(mx.nd.ones((5, 3)))
    assert y1.shape == (2, 4) and y2.shape == (5, 4)
    assert len(net._cache) == 2  # one executable per input shape


def test_batchnorm_running_stats_update():
    bn = nn.BatchNorm(in_channels=3)
    bn.initialize()
    x = mx.nd.array(np.random.randn(4, 3, 5, 5).astype(np.float32) * 2 + 1)
    before = bn.running_mean.data().asnumpy().copy()
    with autograd.record():
        bn(x)
    after = bn.running_mean.data().asnumpy()
    assert not np.allclose(before, after)
    # eval mode: no update
    before = after.copy()
    bn(x)
    assert np.allclose(before, bn.running_mean.data().asnumpy())


def test_dropout_modes():
    do = nn.Dropout(0.5)
    x = mx.nd.ones((100, 100))
    # eval: identity
    assert np.allclose(do(x).asnumpy(), 1.0)
    with autograd.record():
        y = do(x).asnumpy()
    assert (y == 0).any() and not np.allclose(y, 1.0)


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(3))
    net.initialize(mx.init.Normal(0.1))
    x = mx.nd.ones((2, 5))
    ref = net(x).asnumpy()
    fname = str(tmp_path / "net.params")
    net.save_parameters(fname)

    net2 = nn.HybridSequential()
    net2.add(nn.Dense(8, activation="relu"), nn.Dense(3))
    net2.load_parameters(fname)
    assert np.allclose(net2(x).asnumpy(), ref, atol=1e-6)


def test_load_parameters_errors(tmp_path):
    net = nn.Dense(4, in_units=3)
    net.initialize()
    fname = str(tmp_path / "d.params")
    net.save_parameters(fname)
    other = nn.HybridSequential()
    other.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    other.initialize()
    with pytest.raises(AssertionError):
        other.load_parameters(fname)
    # bare-Dense names ("weight") are both missing-from and extra-to the
    # Sequential's structural names ("0.weight") — need both flags
    other.load_parameters(fname, allow_missing=True, ignore_extra=True)


def test_trainer_sgd_matches_manual():
    np.random.seed(0)
    net = nn.Dense(1, in_units=4, use_bias=False)
    net.initialize(mx.init.Normal(1.0))
    w0 = net.weight.data().asnumpy().copy()
    x = mx.nd.array(np.random.randn(8, 4).astype(np.float32))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    g = net.weight.grad().asnumpy().copy()
    trainer.step(batch_size=8)
    expect = w0 - 0.5 * (g / 8)
    assert np.allclose(net.weight.data().asnumpy(), expect, atol=1e-6)


def test_trainer_deferred_param_does_not_clobber_weights():
    """Re-entering _init_params while a deferred param is pending must not
    re-broadcast already-trained params: their store slot holds the reduced
    GRADIENT after a step (update_on_kvstore=False), not a weight."""
    ctxs = [mx.cpu(0), mx.cpu(1)]
    used = nn.Dense(2, in_units=3, use_bias=False)
    unused = nn.Dense(2)                # frozen branch, never forwarded:
    for p in unused.collect_params().values():
        p.grad_req = "null"             # stays deferred across steps
    used.initialize(mx.init.Normal(1.0), ctx=ctxs)
    unused.initialize(mx.init.Normal(1.0), ctx=ctxs)
    params = list(used.collect_params().values()) + \
        list(unused.collect_params().values())
    trainer = gluon.Trainer(params, "sgd", {"learning_rate": 0.5},
                            kvstore="ici")

    def step(i):
        xs = [mx.nd.array(np.full((2, 3), i + 1 + j, np.float32), ctx=c)
              for j, c in enumerate(ctxs)]
        with autograd.record():
            ls = [(used(x) ** 2).mean() for x in xs]
        for l in ls:
            l.backward()
        w_before = used.weight.data(ctxs[0]).asnumpy().copy()
        gsum = sum(used.weight.grad(c).asnumpy() for c in ctxs)
        trainer.step(4)
        return w_before - 0.5 * gsum / 4

    step(0)
    assert trainer._params_to_init          # unused is still deferred
    expect2 = step(1)                       # re-enters _init_params
    np.testing.assert_allclose(used.weight.data(ctxs[0]).asnumpy(),
                               expect2, rtol=1e-5, atol=1e-7)


def test_trainer_compression_params_reach_kvstore():
    net = nn.Dense(2, in_units=3)
    net.initialize(ctx=[mx.cpu(0), mx.cpu(1)])
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore="ici",
                            compression_params={"type": "bf16"})
    x = mx.nd.ones((4, 3))
    with autograd.record():
        ls = [net(x.as_in_context(c)).sum()
              for c in (mx.cpu(0), mx.cpu(1))]
    for l in ls:
        l.backward()
    trainer.step(8)
    assert trainer._kvstore is not None
    assert getattr(trainer._kvstore, "_compress_bf16", False) is True


def test_trainer_save_load_states(tmp_path):
    net = nn.Dense(2, in_units=3)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    x = mx.nd.ones((4, 3))
    with autograd.record():
        net(x).sum().backward()
    trainer.step(4)
    fname = str(tmp_path / "trainer.states")
    trainer.save_states(fname)
    trainer2 = gluon.Trainer(net.collect_params(), "sgd",
                             {"learning_rate": 0.1, "momentum": 0.9})
    trainer2.load_states(fname)
    s1 = trainer._updaters[0].states
    s2 = trainer2._updaters[0].states
    assert set(s1.keys()) == set(s2.keys())


def test_sequential_getitem_len():
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Dense(3), nn.Dense(2))
    assert len(net) == 3
    assert isinstance(net[1], nn.Dense)
    assert len(net[1:]) == 2


def test_grad_req_null_not_updated():
    net = nn.Dense(2, in_units=3)
    net.initialize()
    net.weight.grad_req = "null"
    with autograd.record():
        loss = net(mx.nd.ones((2, 3))).sum()
    loss.backward()
    assert net.bias.grad() is not None
    with pytest.raises(RuntimeError):
        net.weight.grad()


def test_block_apply_and_cast():
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    net.initialize()
    net.cast("float16")
    assert net[0].weight.dtype == np.float16
    out = net(mx.nd.ones((1, 3)).astype("float16"))
    assert out.dtype == np.float16


def test_v1_style_hybrid_forward():
    """v1.x era: hybrid_forward(F, x, weight) with injected params."""

    class Scale(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.scale = gluon.Parameter("scale", shape=(1,))

        def hybrid_forward(self, F, x, scale):
            return x * scale

    blk = Scale()
    blk.initialize(mx.init.Constant(3.0))
    y = blk(mx.nd.ones((2, 2)))
    assert np.allclose(y.asnumpy(), 3.0)
    blk.hybridize()
    y2 = blk(mx.nd.ones((2, 2)))
    assert np.allclose(y2.asnumpy(), 3.0)


def test_share_parameters():
    a = nn.Dense(4, in_units=3)
    a.initialize()
    b = nn.Dense(4, in_units=3)
    b.share_parameters(a.collect_params())
    b.initialize()
    assert a.weight is b.weight
    x = mx.nd.ones((2, 3))
    assert np.allclose(a(x).asnumpy(), b(x).asnumpy())


def test_trainer_update_on_kvstore():
    """update_on_kvstore=True: server-side optimizer updates weights and
    they are pulled back into the parameters."""
    np.random.seed(0)
    ctxs = [mx.tpu(0), mx.tpu(1)]
    net = nn.Dense(1, in_units=4, use_bias=False)
    net.initialize(mx.init.Normal(1.0), ctx=ctxs)
    w0 = net.weight.data(ctxs[0]).asnumpy().copy()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5}, kvstore="ici",
                            update_on_kvstore=True)
    x = mx.nd.array(np.random.randn(8, 4).astype(np.float32))
    from mxnet_tpu.gluon.utils import split_and_load
    parts = split_and_load(x, ctxs)
    with autograd.record():
        losses = [net(p).sum() for p in parts]
    autograd.backward(losses)
    grads = [net.weight.grad(c).asnumpy() for c in ctxs]
    trainer.step(batch_size=8)
    total_g = sum(grads)
    expect = w0 - 0.5 * (total_g / 8)
    for c in ctxs:
        assert np.allclose(net.weight.data(c).asnumpy(), expect, atol=1e-5)


def test_split_data_uneven_small():
    from mxnet_tpu.gluon.utils import split_data
    x = mx.nd.ones((2, 3))
    parts = split_data(x, 4, even_split=False)
    assert len(parts) == 2
    assert all(p.shape[0] == 1 for p in parts)


def test_pretrained_local_weight_store(tmp_path, monkeypatch):
    """get_model(..., pretrained=True) activates from a local weight drop
    (reference model_store.get_model_file role; VERDICT r3 missing #8 —
    no network, so absent weights raise pointing at the drop path)."""
    import pytest
    import numpy as np
    from mxnet_tpu import nd
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.gluon.model_zoo.model_store import get_model_file

    monkeypatch.setenv("MX_PRETRAINED_DIR", str(tmp_path))
    # absent: clear error naming the expected location
    with pytest.raises(FileNotFoundError, match="MX_PRETRAINED_DIR"):
        vision.get_model("alexnet", pretrained=True, classes=10)
    # drop weights -> pretrained=True loads them
    donor = vision.get_model("alexnet", classes=10)
    donor.initialize(mx.init.Xavier())
    donor(nd.zeros((1, 3, 224, 224)))
    donor.save_parameters(str(tmp_path / "alexnet.params"))
    assert get_model_file("alexnet").endswith("alexnet.params")
    net = vision.get_model("alexnet", pretrained=True, classes=10)
    got = net(nd.ones((1, 3, 224, 224))).asnumpy()
    want = donor(nd.ones((1, 3, 224, 224))).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
