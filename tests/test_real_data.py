"""Real-dataset convergence parity gates (BASELINE configs 0 and 3).

These are the accuracy/perplexity PARITY runs VERDICT round-2 weak #9 asks
to keep ready: they skip cleanly offline (no network in this environment)
and run the moment a data drop appears at ``MX_DATA_DIR``:

    MX_DATA_DIR=/data python -m pytest tests/test_real_data.py

Expected layout (tools/prepare_data.py validates/creates it):
  $MX_DATA_DIR/mnist/train-images-idx3-ubyte(.gz) + the other 3 idx files
  $MX_DATA_DIR/ptb/ptb.train.txt + ptb.valid.txt
  $MX_DATA_DIR/voc/VOC2007/{Annotations,JPEGImages,ImageSets/Main}
      (config 4: the SSD data-path gate)
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd

DATA_DIR = os.environ.get("MX_DATA_DIR")

pytestmark = pytest.mark.skipif(
    not DATA_DIR, reason="MX_DATA_DIR not set (no real datasets offline); "
    "drop MNIST/PTB there to run the BASELINE parity gates")


def test_mnist_mlp_accuracy_parity():
    """BASELINE config 0: Gluon MLP on MNIST, imperative mx.cpu() —
    accuracy parity gate (reference example/gluon/mnist: ~97% @ 1 epoch)."""
    from mxnet_tpu.gluon.data.vision import MNIST
    from mxnet_tpu.gluon.data.vision import transforms as T

    root = os.path.join(DATA_DIR, "mnist")
    to_tensor = T.ToTensor()
    train = MNIST(root=root, train=True).transform_first(to_tensor)
    test = MNIST(root=root, train=False).transform_first(to_tensor)
    train_loader = gluon.data.DataLoader(train, batch_size=128,
                                         shuffle=True)
    test_loader = gluon.data.DataLoader(test, batch_size=256)

    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(128, activation="relu"),
            gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    for x, y in train_loader:
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(x.shape[0])
    metric = mx.metric.Accuracy()
    for x, y in test_loader:
        metric.update([y], [net(x)])
    assert metric.get()[1] > 0.95, metric.get()


def _ptb_corpus(path, vocab=None):
    with open(path) as f:
        words = f.read().replace("\n", " <eos> ").split()
    if vocab is None:
        vocab = {w: i for i, w in enumerate(sorted(set(words)))}
    ids = np.array([vocab[w] for w in words if w in vocab], np.int32)
    return ids, vocab


def test_ptb_lstm_perplexity_descends():
    """BASELINE config 3: PTB LSTM language model — perplexity gate.
    A short budgeted run must bring training perplexity under 300
    (random = |V| ≈ 10k; the reference's first-epoch ppl is far lower)."""
    train_ids, vocab = _ptb_corpus(
        os.path.join(DATA_DIR, "ptb", "ptb.train.txt"))
    V = len(vocab)
    seq, batch = 35, 32

    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    emb = gluon.nn.Embedding(V, 200)
    lstm = gluon.rnn.LSTM(200, num_layers=2, layout="NTC")
    out = gluon.nn.Dense(V, flatten=False)
    net.add(emb, lstm, out)
    net.initialize(mx.init.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})

    n_batches = min(300, (len(train_ids) - 1) // (seq * batch))
    losses = []
    for i in range(n_batches):
        s = i * seq * batch
        chunk = train_ids[s:s + seq * batch + 1]
        x = nd.array(chunk[:-1].reshape(batch, seq))
        y = nd.array(chunk[1:].reshape(batch, seq))
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(batch)
        losses.append(float(loss.mean().asnumpy().item()))
    ppl = float(np.exp(np.mean(losses[-20:])))
    assert ppl < 300, ppl


VOC_DIR = os.path.join(DATA_DIR or "", "voc", "VOC2007")


def _voc_to_det_rec(tmp_path, n_images=48, edge=256):
    """VOC2007 drop -> indexed det .rec in the reference --pack-label
    format (class_id + normalized boxes), via the real annotation XMLs."""
    import xml.etree.ElementTree as ET
    from mxnet_tpu import recordio
    from PIL import Image

    classes = ["aeroplane", "bicycle", "bird", "boat", "bottle", "bus",
               "car", "cat", "chair", "cow", "diningtable", "dog",
               "horse", "motorbike", "person", "pottedplant", "sheep",
               "sofa", "train", "tvmonitor"]
    cls_of = {c: i for i, c in enumerate(classes)}
    with open(os.path.join(VOC_DIR, "ImageSets", "Main",
                           "trainval.txt")) as f:
        ids = [l.strip().split()[0] for l in f if l.strip()][:n_images]
    prefix = os.path.join(str(tmp_path), "voc_det")
    w = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    kept = 0
    for i, img_id in enumerate(ids):
        xml = os.path.join(VOC_DIR, "Annotations", img_id + ".xml")
        jpg = os.path.join(VOC_DIR, "JPEGImages", img_id + ".jpg")
        if not (os.path.exists(xml) and os.path.exists(jpg)):
            continue
        root = ET.parse(xml).getroot()
        size = root.find("size")
        W = float(size.find("width").text)
        H = float(size.find("height").text)
        label = [2.0, 5.0]
        for obj in root.iter("object"):
            name = obj.find("name").text.strip().lower()
            if name not in cls_of:
                continue
            bb = obj.find("bndbox")
            label += [float(cls_of[name]),
                      float(bb.find("xmin").text) / W,
                      float(bb.find("ymin").text) / H,
                      float(bb.find("xmax").text) / W,
                      float(bb.find("ymax").text) / H]
        if len(label) == 2:
            continue
        img = np.asarray(Image.open(jpg).convert("RGB").resize(
            (edge, edge)), np.uint8)
        w.write_idx(kept, recordio.pack_img(
            recordio.IRHeader(0, label, kept, 0), img, quality=85))
        kept += 1
    w.close()
    return prefix, kept, len(classes)


def test_ssd_voc_pipeline_parity(tmp_path):
    """BASELINE config 4 drop contract: real VOC2007 annotations/images
    flow through pack_img -> ImageDetIter -> SSD targets -> loss descent
    and the VOC07 mAP metric accepts the resulting detections.  (The
    full-mAP parity number needs the full 16h train; this gate proves
    the data path end-to-end on the real files.)"""
    if not os.path.isdir(VOC_DIR):
        pytest.skip("no voc/VOC2007 under MX_DATA_DIR "
                    "(tools/prepare_data.py lays it out)")
    from mxnet_tpu.gluon.model_zoo.ssd import SSDMultiBoxLoss, ssd_toy
    from mxnet_tpu.image.detection import ImageDetIter
    from mxnet_tpu.metric import VOC07MApMetric

    edge = 128
    prefix, kept, n_classes = _voc_to_det_rec(tmp_path, edge=edge)
    assert kept >= 8, "VOC drop yielded too few readable images"
    it = ImageDetIter(path_imgrec=prefix + ".rec", batch_size=8,
                      data_shape=(3, edge, edge), shuffle=True,
                      rand_mirror=True)
    net = ssd_toy(classes=n_classes)
    net.initialize(mx.init.Xavier())
    loss_fn = SSDMultiBoxLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9})
    losses = []
    for epoch in range(4):
        it.reset()
        for batch in it:
            x = batch.data[0] / 255.0
            y = batch.label[0]
            with autograd.record():
                anchors, cls_preds, box_preds = net(x)
                loc_t, loc_m, cls_t = net.targets(anchors, cls_preds, y)
                loss = loss_fn(cls_preds, box_preds, cls_t, loc_t, loc_m)
            loss.backward()
            trainer.step(x.shape[0])
            losses.append(float(loss.mean().asnumpy().item()))
    assert losses[-1] < losses[0], losses[:3] + losses[-3:]

    # detections run through the VOC07 metric: label (A, 5) [cls, box],
    # pred (A, 6) [cls, score, box] per the metric's convention
    m = VOC07MApMetric(iou_thresh=0.5)
    it.reset()
    batch = next(it)
    anchors, cls_preds, box_preds = net(batch.data[0] / 255.0)
    det = net.detect(anchors, cls_preds, box_preds)
    m.update([batch.label[0]], [det])
    name, value = m.get()
    assert np.isfinite(value)
