"""Real-dataset convergence parity gates (BASELINE configs 0 and 3).

These are the accuracy/perplexity PARITY runs VERDICT round-2 weak #9 asks
to keep ready: they skip cleanly offline (no network in this environment)
and run the moment a data drop appears at ``MX_DATA_DIR``:

    MX_DATA_DIR=/data python -m pytest tests/test_real_data.py

Expected layout:
  $MX_DATA_DIR/mnist/train-images-idx3-ubyte(.gz) + the other 3 idx files
  $MX_DATA_DIR/ptb/ptb.train.txt + ptb.valid.txt
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd

DATA_DIR = os.environ.get("MX_DATA_DIR")

pytestmark = pytest.mark.skipif(
    not DATA_DIR, reason="MX_DATA_DIR not set (no real datasets offline); "
    "drop MNIST/PTB there to run the BASELINE parity gates")


def test_mnist_mlp_accuracy_parity():
    """BASELINE config 0: Gluon MLP on MNIST, imperative mx.cpu() —
    accuracy parity gate (reference example/gluon/mnist: ~97% @ 1 epoch)."""
    from mxnet_tpu.gluon.data.vision import MNIST
    from mxnet_tpu.gluon.data.vision import transforms as T

    root = os.path.join(DATA_DIR, "mnist")
    to_tensor = T.ToTensor()
    train = MNIST(root=root, train=True).transform_first(to_tensor)
    test = MNIST(root=root, train=False).transform_first(to_tensor)
    train_loader = gluon.data.DataLoader(train, batch_size=128,
                                         shuffle=True)
    test_loader = gluon.data.DataLoader(test, batch_size=256)

    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(128, activation="relu"),
            gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    for x, y in train_loader:
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(x.shape[0])
    metric = mx.metric.Accuracy()
    for x, y in test_loader:
        metric.update([y], [net(x)])
    assert metric.get()[1] > 0.95, metric.get()


def _ptb_corpus(path, vocab=None):
    with open(path) as f:
        words = f.read().replace("\n", " <eos> ").split()
    if vocab is None:
        vocab = {w: i for i, w in enumerate(sorted(set(words)))}
    ids = np.array([vocab[w] for w in words if w in vocab], np.int32)
    return ids, vocab


def test_ptb_lstm_perplexity_descends():
    """BASELINE config 3: PTB LSTM language model — perplexity gate.
    A short budgeted run must bring training perplexity under 300
    (random = |V| ≈ 10k; the reference's first-epoch ppl is far lower)."""
    train_ids, vocab = _ptb_corpus(
        os.path.join(DATA_DIR, "ptb", "ptb.train.txt"))
    V = len(vocab)
    seq, batch = 35, 32

    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    emb = gluon.nn.Embedding(V, 200)
    lstm = gluon.rnn.LSTM(200, num_layers=2, layout="NTC")
    out = gluon.nn.Dense(V, flatten=False)
    net.add(emb, lstm, out)
    net.initialize(mx.init.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})

    n_batches = min(300, (len(train_ids) - 1) // (seq * batch))
    losses = []
    for i in range(n_batches):
        s = i * seq * batch
        chunk = train_ids[s:s + seq * batch + 1]
        x = nd.array(chunk[:-1].reshape(batch, seq))
        y = nd.array(chunk[1:].reshape(batch, seq))
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(batch)
        losses.append(float(loss.mean().asnumpy().item()))
    ppl = float(np.exp(np.mean(losses[-20:])))
    assert ppl < 300, ppl
