"""Import conformance against FOREIGN onnx bytes (VERDICT r4 #8).

Every other ONNX test round-trips this repo's own writer, which cannot
catch a shared misreading of onnx.proto.  The fixtures here are authored
by an INDEPENDENT minimal protobuf encoder written directly from the
onnx.proto3 message spec (field numbers/wire types transcribed below) —
no code shared with mxnet_tpu.onnx — then imported and checked against
pure-numpy math.  The first run writes the bytes under tests/fixtures/
foreign_*.onnx; later runs verify the generator reproduces the
checked-in bytes exactly (fixture drift = spec-reading change).
"""
import os
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd

FIXDIR = os.path.join(os.path.dirname(__file__), "fixtures")


# ---------------------------------------------------------------------------
# Independent wire-format encoder (transcribed from onnx.proto3):
#   ModelProto:  ir_version=1(varint)  opset_import=8(msg)  graph=7(msg)
#   OperatorSetIdProto: domain=1(str) version=2(varint)
#   GraphProto:  node=1  name=2  initializer=5  input=11  output=12
#   NodeProto:   input=1  output=2  name=3  op_type=4  attribute=5
#   AttributeProto: name=1 f=2 i=3 s=4 floats=7 ints=8 strings=9 type=20
#   TensorProto: dims=1  data_type=2  float_data=4  name=8  raw_data=9
#   ValueInfoProto: name=1  type=2{tensor_type=1{elem_type=1 shape=2}}
#   TensorShapeProto.Dimension: dim_value=1  dim_param=2
# ---------------------------------------------------------------------------

def vint(n):
    out = b""
    while True:
        b7 = n & 0x7F
        n >>= 7
        out += bytes([b7 | (0x80 if n else 0)])
        if not n:
            return out


def tag(field, wire):
    return vint((field << 3) | wire)


def f_msg(field, payload):
    return tag(field, 2) + vint(len(payload)) + payload


def f_str(field, s):
    return f_msg(field, s.encode())


def f_int(field, v):
    return tag(field, 0) + vint(v)


def tensor(name, arr):
    arr = np.asarray(arr, np.float32)
    pb = b"".join(f_int(1, d) for d in arr.shape)
    pb += f_int(2, 1)                               # FLOAT
    pb += f_str(8, name)
    pb += f_msg(9, arr.tobytes())                   # raw_data
    return pb


def attr_int(name, v):
    return f_str(1, name) + f_int(3, v) + f_int(20, 2)


def attr_float(name, v):
    return f_str(1, name) + tag(2, 5) + struct.pack("<f", v) + f_int(20, 1)


def attr_strs(name, vals):
    return f_str(1, name) + b"".join(f_msg(9, v.encode()) for v in vals) \
        + f_int(20, 8)


def node(op, ins, outs, name, attrs=()):
    pb = b"".join(f_str(1, i) for i in ins)
    pb += b"".join(f_str(2, o) for o in outs)
    pb += f_str(3, name) + f_str(4, op)
    pb += b"".join(f_msg(5, a) for a in attrs)
    return pb


def vinfo(name, shape):
    dims = b"".join(f_msg(1, f_int(1, d)) for d in shape)
    ttype = f_int(1, 1) + f_msg(2, dims)
    return f_str(1, name) + f_msg(2, f_msg(1, ttype))


def model(graph_pb):
    return (f_int(1, 8)                             # ir_version
            + f_msg(8, f_str(1, "") + f_int(2, 13))  # opset 13
            + f_msg(7, graph_pb))


def write_or_verify(path, data):
    """First run pins the fixture; later runs must reproduce it."""
    if os.path.exists(path):
        with open(path, "rb") as f:
            assert f.read() == data, \
                "foreign fixture generator drifted from %s" % path
    else:
        with open(path, "wb") as f:
            f.write(data)


# ---------------------------------------------------------------------------
# fixture 1: Gemm + Relu                                                     |
# ---------------------------------------------------------------------------

def _gemm_relu_bytes(rng):
    W = rng.randn(3, 4).astype(np.float32)          # Gemm transB=1
    b = rng.randn(3).astype(np.float32)
    g = b""
    g += f_msg(1, node("Gemm", ["x", "W", "b"], ["h"], "gemm",
                       [attr_float("alpha", 1.0), attr_float("beta", 1.0),
                        attr_int("transA", 0), attr_int("transB", 1)]))
    g += f_msg(1, node("Relu", ["h"], ["y"], "relu"))
    g += f_str(2, "foreign_gemm")
    g += f_msg(5, tensor("W", W)) + f_msg(5, tensor("b", b))
    g += f_msg(11, vinfo("x", (2, 4)))
    g += f_msg(12, vinfo("y", (2, 3)))
    return model(g), W, b


def test_foreign_gemm_relu_import():
    rng = np.random.RandomState(11)
    data, W, b = _gemm_relu_bytes(rng)
    path = os.path.join(FIXDIR, "foreign_gemm.onnx")
    write_or_verify(path, data)
    s, arg, aux = mx.onnx.import_model(path)
    x = rng.randn(2, 4).astype(np.float32)
    args = {"x": nd.array(x)}
    args.update({k: v for k, v in arg.items()})
    out = s.bind(mx.cpu(), args).forward()[0].asnumpy()
    np.testing.assert_allclose(out, np.maximum(x @ W.T + b, 0),
                               rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# fixture 2: GRU (linear_before_reset=1), ONNX z,r,h gate order             |
# ---------------------------------------------------------------------------

def _gru_ref(x, h0, W, R, Wb, Rb, H):
    """Pure-numpy ONNX GRU (forward, linear_before_reset=1)."""
    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))
    T, N, _ = x.shape
    Wz, Wr, Wh = W[:H], W[H:2 * H], W[2 * H:]
    Rz, Rr, Rh = R[:H], R[H:2 * H], R[2 * H:]
    Wbz, Wbr, Wbh = Wb[:H], Wb[H:2 * H], Wb[2 * H:]
    Rbz, Rbr, Rbh = Rb[:H], Rb[H:2 * H], Rb[2 * H:]
    h = h0.copy()
    ys = []
    for t in range(T):
        xt = x[t]
        z = sig(xt @ Wz.T + h @ Rz.T + Wbz + Rbz)
        r = sig(xt @ Wr.T + h @ Rr.T + Wbr + Rbr)
        hh = np.tanh(xt @ Wh.T + r * (h @ Rh.T + Rbh) + Wbh)
        h = (1 - z) * hh + z * h
        ys.append(h.copy())
    return np.stack(ys)[:, None]                    # (T, 1, N, H)


def _gru_bytes(rng, T=4, N=2, I=3, H=5):
    W = (rng.randn(3 * H, I) * 0.4).astype(np.float32)
    R = (rng.randn(3 * H, H) * 0.4).astype(np.float32)
    B = (rng.randn(6 * H) * 0.2).astype(np.float32)
    g = b""
    g += f_msg(1, node("GRU", ["x", "W", "R", "B"], ["y"], "gru",
                       [attr_int("hidden_size", H),
                        attr_int("linear_before_reset", 1)]))
    g += f_str(2, "foreign_gru")
    g += f_msg(5, tensor("W", W[None]))
    g += f_msg(5, tensor("R", R[None]))
    g += f_msg(5, tensor("B", B[None]))
    g += f_msg(11, vinfo("x", (T, N, I)))
    g += f_msg(12, vinfo("y", (T, 1, N, H)))
    return model(g), W, R, B


def test_foreign_gru_import():
    rng = np.random.RandomState(7)
    T, N, I, H = 4, 2, 3, 5
    data, W, R, B = _gru_bytes(rng, T, N, I, H)
    path = os.path.join(FIXDIR, "foreign_gru.onnx")
    write_or_verify(path, data)
    s, arg, aux = mx.onnx.import_model(path)
    x = rng.randn(T, N, I).astype(np.float32)
    args = {"x": nd.array(x)}
    args.update(arg)
    out = s.bind(mx.cpu(), args).forward()[0].asnumpy()
    want = _gru_ref(x, np.zeros((N, H), np.float32), W, R,
                    B[:3 * H], B[3 * H:], H)
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_foreign_gru_lbr0_refused():
    """linear_before_reset=0 math differs from our cuDNN-semantics
    kernel: the importer must refuse, not silently mis-import."""
    rng = np.random.RandomState(7)
    H = 5
    W = rng.randn(3 * H, 3).astype(np.float32)
    R = rng.randn(3 * H, H).astype(np.float32)
    g = b""
    g += f_msg(1, node("GRU", ["x", "W", "R"], ["y"], "gru",
                       [attr_int("hidden_size", H)]))
    g += f_str(2, "gru_lbr0")
    g += f_msg(5, tensor("W", W[None])) + f_msg(5, tensor("R", R[None]))
    g += f_msg(11, vinfo("x", (2, 2, 3)))
    g += f_msg(12, vinfo("y", (2, 1, 2, H)))
    import tempfile
    path = os.path.join(tempfile.mkdtemp(), "lbr0.onnx")
    with open(path, "wb") as f:
        f.write(model(g))
    with pytest.raises(Exception, match="linear_before_reset"):
        mx.onnx.import_model(path)


def test_foreign_lstm_no_initial_states_binds_clean():
    """Foreign LSTMs commonly omit initial_h/initial_c: the importer must
    synthesize spec-mandated zeros for BOTH, value-blind (an inf in the
    data must not poison the zero state), leaving no hidden free vars."""
    rng = np.random.RandomState(3)
    T, N, I, H = 3, 2, 4, 5
    W = (rng.randn(4 * H, I) * 0.3).astype(np.float32)
    R = (rng.randn(4 * H, H) * 0.3).astype(np.float32)
    g = b""
    g += f_msg(1, node("LSTM", ["x", "W", "R"], ["y"], "lstm",
                       [attr_int("hidden_size", H)]))
    g += f_str(2, "lstm_nostate")
    g += f_msg(5, tensor("W", W[None])) + f_msg(5, tensor("R", R[None]))
    g += f_msg(11, vinfo("x", (T, N, I)))
    g += f_msg(12, vinfo("y", (T, 1, N, H)))
    import tempfile
    path = os.path.join(tempfile.mkdtemp(), "lstm_nostate.onnx")
    with open(path, "wb") as f:
        f.write(model(g))
    s, arg, aux = mx.onnx.import_model(path)
    x = rng.randn(T, N, I).astype(np.float32)
    args = {"x": nd.array(x)}
    args.update(arg)
    out = s.bind(mx.cpu(), args).forward()[0].asnumpy()  # binds: no free vars
    assert np.isfinite(out).all()
    # value-blind zero states: an inf in timestep 0 must only affect the
    # lanes the recurrence actually touches, not the h0/c0 synthesis
    x_inf = x.copy()
    x_inf[0, 0, 0] = np.inf
    args2 = {"x": nd.array(x_inf)}
    args2.update(arg)
    out2 = s.bind(mx.cpu(), args2).forward()[0].asnumpy()
    assert np.isfinite(out2[:, :, 1]).all()   # batch element 1 untouched
