"""Sparse NDArray tests.

Reference pattern: tests/python/unittest/test_sparse_ndarray.py /
test_sparse_operator.py — creation/roundtrip, cast_storage both ways,
retain, csr dot vs numpy, rowsparse lazy optimizer semantics (only touched
rows move), Embedding sparse_grad end to end, kvstore row_sparse_pull.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, kvstore
from mxnet_tpu.gluon import nn
from mxnet_tpu.ndarray import sparse


def _rand_rsp(shape=(10, 4), nnz_rows=(1, 4, 7), dtype=np.float32):
    dense = np.zeros(shape, dtype)
    for r in nnz_rows:
        dense[r] = np.random.randn(*shape[1:]).astype(dtype)
    return dense


def test_row_sparse_roundtrip():
    dense = _rand_rsp()
    rsp = sparse.row_sparse_array(dense, shape=dense.shape)
    assert rsp.stype == "row_sparse"
    assert list(rsp.indices.asnumpy()) == [1, 4, 7]
    np.testing.assert_array_equal(rsp.tostype("default").asnumpy(), dense)
    np.testing.assert_array_equal(rsp.asnumpy(), dense)


def test_row_sparse_from_pair():
    data = np.random.randn(2, 3).astype(np.float32)
    rsp = sparse.row_sparse_array((data, [0, 5]), shape=(8, 3))
    out = rsp.asnumpy()
    np.testing.assert_array_equal(out[0], data[0])
    np.testing.assert_array_equal(out[5], data[1])
    assert np.abs(out[[1, 2, 3, 4, 6, 7]]).sum() == 0


def test_csr_roundtrip_and_dot():
    np.random.seed(0)
    dense = np.random.randn(6, 5).astype(np.float32)
    dense[np.random.rand(6, 5) > 0.4] = 0
    csr = sparse.csr_matrix(dense)
    np.testing.assert_allclose(csr.asnumpy(), dense, rtol=1e-6)
    rhs = np.random.randn(5, 3).astype(np.float32)
    out = sparse.dot(csr, mx.nd.array(rhs))
    np.testing.assert_allclose(out.asnumpy(), dense @ rhs, rtol=1e-5)
    outT = sparse.dot(csr, mx.nd.array(np.random.randn(6, 2).astype(np.float32)
                                       ), transpose_a=True)
    assert outT.shape == (5, 2)


def test_csr_T_dot_matches_numpy():
    np.random.seed(1)
    dense = np.random.randn(4, 7).astype(np.float32)
    dense[np.random.rand(4, 7) > 0.5] = 0
    rhs = np.random.randn(4, 3).astype(np.float32)
    csr = sparse.csr_matrix(dense)
    out = sparse.dot(csr, mx.nd.array(rhs), transpose_a=True)
    np.testing.assert_allclose(out.asnumpy(), dense.T @ rhs, rtol=1e-5,
                               atol=1e-6)


def test_cast_storage_both_ways():
    dense = _rand_rsp()
    nd_dense = mx.nd.array(dense)
    rsp = nd_dense.tostype("row_sparse")
    assert rsp.stype == "row_sparse"
    back = rsp.tostype("default")
    np.testing.assert_array_equal(back.asnumpy(), dense)
    csr = mx.nd.array(dense).tostype("csr")
    assert csr.stype == "csr"
    np.testing.assert_array_equal(csr.asnumpy(), dense)


def test_retain():
    dense = _rand_rsp(nnz_rows=(1, 4, 7))
    rsp = sparse.row_sparse_array(dense, shape=dense.shape)
    kept = sparse.retain(rsp, [1, 3, 7])
    out = kept.asnumpy()
    np.testing.assert_array_equal(out[1], dense[1])
    np.testing.assert_array_equal(out[7], dense[7])
    assert np.abs(out[3]).sum() == 0  # requested but absent -> zero
    assert np.abs(out[4]).sum() == 0  # present but not requested -> dropped


def test_sparse_zeros():
    z = sparse.zeros("row_sparse", (5, 3))
    assert z.asnumpy().sum() == 0
    zc = sparse.zeros("csr", (4, 4))
    assert zc.asnumpy().sum() == 0


def test_lazy_sgd_only_touches_grad_rows():
    np.random.seed(2)
    w0 = np.random.randn(10, 4).astype(np.float32)
    weight = mx.nd.array(w0)
    gdense = _rand_rsp(nnz_rows=(2, 5))
    grad = sparse.row_sparse_array(gdense, shape=gdense.shape)
    opt = mx.optimizer.SGD(learning_rate=0.5, momentum=0.9, wd=0.1)
    state = opt.create_state(0, weight)
    opt.update(0, weight, grad, state)
    w1 = weight.asnumpy()
    untouched = [r for r in range(10) if r not in (2, 5)]
    # untouched rows identical — wd did NOT decay them (lazy semantics)
    np.testing.assert_array_equal(w1[untouched], w0[untouched])
    for r in (2, 5):
        expect = w0[r] - 0.5 * (gdense[r] + 0.1 * w0[r])
        np.testing.assert_allclose(w1[r], expect, rtol=1e-5)
    # momentum state only populated on touched rows
    mom = state.asnumpy()
    assert np.abs(mom[untouched]).sum() == 0
    assert np.abs(mom[[2, 5]]).sum() > 0


def test_lazy_adam_only_touches_grad_rows():
    np.random.seed(3)
    w0 = np.random.randn(8, 3).astype(np.float32)
    weight = mx.nd.array(w0)
    gdense = _rand_rsp(shape=(8, 3), nnz_rows=(0, 6))
    grad = sparse.row_sparse_array(gdense, shape=gdense.shape)
    opt = mx.optimizer.Adam(learning_rate=0.1)
    state = opt.create_state(0, weight)
    opt.update(0, weight, grad, state)
    w1 = weight.asnumpy()
    untouched = [r for r in range(8) if r not in (0, 6)]
    np.testing.assert_array_equal(w1[untouched], w0[untouched])
    assert not np.allclose(w1[[0, 6]], w0[[0, 6]])


def test_embedding_sparse_grad_training():
    np.random.seed(4)
    mx.random.seed(4)
    emb = nn.Embedding(20, 6, sparse_grad=True)
    emb.initialize()
    trainer = gluon.Trainer(emb.collect_params(), "sgd",
                            {"learning_rate": 1.0, "wd": 0.01})
    w0 = emb.weight.data().asnumpy().copy()
    ids = mx.nd.array(np.array([3, 7, 7, 11]), dtype="int32")
    with autograd.record():
        out = emb(ids)
        loss = (out * out).mean()
    loss.backward()
    trainer.step(4)
    w1 = emb.weight.data().asnumpy()
    touched = [3, 7, 11]
    untouched = [r for r in range(20) if r not in touched]
    np.testing.assert_array_equal(w1[untouched], w0[untouched])
    assert not np.allclose(w1[touched], w0[touched])


def test_kvstore_row_sparse_pull():
    kv = kvstore.create("local")
    val = mx.nd.array(np.arange(20, dtype=np.float32).reshape(5, 4))
    kv.init("emb", val)
    ids = mx.nd.array(np.array([0, 3]), dtype="int32")
    out = sparse.zeros("row_sparse", (5, 4))
    kv.row_sparse_pull("emb", out=out, row_ids=ids)
    np.testing.assert_array_equal(out.indices.asnumpy(), [0, 3])
    np.testing.assert_array_equal(out.data.asnumpy(), val.asnumpy()[[0, 3]])
    dense = out.asnumpy()
    assert np.abs(dense[[1, 2, 4]]).sum() == 0
    # return form (no out)
    res = kv.row_sparse_pull("emb", row_ids=ids)
    np.testing.assert_array_equal(res[0].data.asnumpy(), val.asnumpy()[[0, 3]])


# -- review-finding regressions ----------------------------------------------

def test_unsorted_pair_construction_sorts():
    data = np.array([[5., 5.], [1., 1.]], np.float32)
    rsp = sparse.row_sparse_array((data, [5, 0]), shape=(8, 2))
    np.testing.assert_array_equal(rsp.indices.asnumpy(), [0, 5])
    kept = sparse.retain(rsp, [0, 5])
    np.testing.assert_array_equal(kept.asnumpy()[0], [1., 1.])
    np.testing.assert_array_equal(kept.asnumpy()[5], [5., 5.])


def test_csr_shape_inference():
    csr = sparse.csr_matrix((np.ones(2, np.float32), [0, 1], [0, 1, 2]))
    assert csr.shape == (2, 2)
    np.testing.assert_array_equal(csr.asnumpy(), np.eye(2, dtype=np.float32))


def test_row_sparse_pull_numpy_and_list_ids():
    kv = kvstore.create("local")
    val = mx.nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    kv.init("w", val)
    out = sparse.zeros("row_sparse", (4, 3))
    kv.row_sparse_pull("w", out=out, row_ids=np.array([0, 2]))
    np.testing.assert_array_equal(out.data.asnumpy(), val.asnumpy()[[0, 2]])
    out.asnumpy()  # must not crash: indices are real NDArrays
    kv.row_sparse_pull("w", out=out, row_ids=[1, 3])
    np.testing.assert_array_equal(out.indices.asnumpy(), [1, 3])


def test_row_sparse_pull_keeps_declared_dtype():
    kv = kvstore.create("local")
    kv.init("w", mx.nd.array(np.arange(8, dtype=np.float32).reshape(4, 2)))
    out = sparse.zeros("row_sparse", (4, 2), dtype="float16")
    kv.row_sparse_pull("w", out=out, row_ids=np.array([1]))
    assert str(out.dtype) == "float16"
    assert str(out.data.dtype) == "float16"


def test_dense_to_rsp_stays_on_device():
    # fast path: dense NDArray -> row_sparse without full host copy
    g = mx.nd.array(_rand_rsp(shape=(64, 8), nnz_rows=(3, 9)))
    rsp = g.tostype("row_sparse")
    np.testing.assert_array_equal(rsp.indices.asnumpy(), [3, 9])
    np.testing.assert_array_equal(rsp.asnumpy(), g.asnumpy())


def test_sparse_save_load_roundtrip(tmp_path):
    """nd.save/load preserve storage types (reference: NDArray::Save
    writes kRowSparseStorage/kCSRStorage with their aux arrays — the old
    behavior silently densified)."""
    from mxnet_tpu import nd
    path = str(tmp_path / "sp.params")
    csr = nd.sparse.csr_matrix((np.array([1.5, 2.5], np.float32),
                                np.array([0, 2]), np.array([0, 1, 2])),
                               shape=(2, 3))
    rsp = nd.sparse.row_sparse_array((np.full((2, 3), 5.0, np.float32),
                                      np.array([1, 4])), shape=(6, 3))
    dense = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    nd.save(path, {"csr": csr, "rsp": rsp, "d": dense})
    loaded = nd.load(path)
    assert loaded["csr"].stype == "csr"
    assert loaded["rsp"].stype == "row_sparse"
    assert getattr(loaded["d"], "stype", "default") == "default"
    np.testing.assert_allclose(loaded["csr"].tostype("default").asnumpy(),
                               csr.tostype("default").asnumpy())
    np.testing.assert_allclose(loaded["rsp"].tostype("default").asnumpy(),
                               rsp.tostype("default").asnumpy())
    np.testing.assert_array_equal(loaded["csr"].indptr.asnumpy(),
                                  [0, 1, 2])
    np.testing.assert_array_equal(loaded["rsp"].indices.asnumpy(), [1, 4])
