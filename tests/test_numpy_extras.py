"""Round-5 numpy-surface additions: np.fft, polynomial family, windows,
new random distributions, npx.special / npx.stats (scipy-oracle lanes).

Reference: the mx.np surface tracks NumPy (python/mxnet/numpy/
multiarray.py); np.fft/poly/emath-adjacent names follow installed-NumPy
behavior.  npx.special / npx.stats are beyond-reference XLA primitives
oracled against installed scipy.
"""
import numpy as onp
import pytest
import scipy.special as ss
import scipy.stats as st

import mxnet_tpu as mx
from mxnet_tpu import npx

np = mx.np


def setup_module():
    mx.random.seed(0)
    onp.random.seed(0)


# -- np.fft -----------------------------------------------------------------

def test_fft_family_matches_numpy():
    x = onp.random.RandomState(0).randn(16).astype("float32")
    mxx = np.array(x)
    onp.testing.assert_allclose(np.fft.fft(mxx).asnumpy(),
                                onp.fft.fft(x), rtol=1e-4, atol=1e-4)
    onp.testing.assert_allclose(np.fft.ifft(np.fft.fft(mxx)).asnumpy(),
                                x, rtol=1e-4, atol=1e-5)
    onp.testing.assert_allclose(np.fft.rfft(mxx).asnumpy(),
                                onp.fft.rfft(x), rtol=1e-4, atol=1e-4)
    onp.testing.assert_allclose(np.fft.irfft(np.fft.rfft(mxx)).asnumpy(),
                                x, rtol=1e-4, atol=1e-5)
    onp.testing.assert_allclose(np.fft.hfft(np.fft.ihfft(mxx)).asnumpy(),
                                x, rtol=1e-3, atol=1e-4)


def test_fft_nd_axes_and_shift():
    x = onp.random.RandomState(1).randn(4, 8).astype("float32")
    mxx = np.array(x)
    onp.testing.assert_allclose(np.fft.fft2(mxx).asnumpy(),
                                onp.fft.fft2(x), rtol=1e-4, atol=1e-4)
    onp.testing.assert_allclose(
        np.fft.fftn(mxx, axes=(0,)).asnumpy(),
        onp.fft.fftn(x, axes=(0,)), rtol=1e-4, atol=1e-4)
    onp.testing.assert_allclose(
        np.fft.rfft2(mxx).asnumpy(), onp.fft.rfft2(x), rtol=1e-4,
        atol=1e-4)
    onp.testing.assert_allclose(
        np.fft.fftshift(np.fft.fftfreq(8)).asnumpy(),
        onp.fft.fftshift(onp.fft.fftfreq(8)), rtol=1e-6)
    onp.testing.assert_allclose(
        np.fft.ifftshift(np.fft.fftshift(mxx)).asnumpy(), x)
    onp.testing.assert_allclose(np.fft.rfftfreq(9, d=0.5).asnumpy(),
                                onp.fft.rfftfreq(9, d=0.5), rtol=1e-6)


def test_fft_gradient_flows():
    # gradient through irfft(rfft(x)) round-trip (real-valued chain)
    from mxnet_tpu import autograd, nd
    x2 = nd.random.normal(shape=(8,))
    x2.attach_grad()
    with autograd.record():
        z = np.fft.irfft(np.fft.rfft(x2))
        loss = (z * z).sum()
    loss.backward()
    onp.testing.assert_allclose(x2.grad.asnumpy(), 2 * x2.asnumpy(),
                                rtol=1e-4, atol=1e-5)


# -- polynomial family ------------------------------------------------------

def test_polynomial_family_matches_numpy():
    a = onp.array([1.0, -3.0, 2.0], "float32")
    b = onp.array([1.0, 1.0], "float32")
    onp.testing.assert_allclose(np.polyadd(np.array(a), np.array(b))
                                .asnumpy(), onp.polyadd(a, b))
    onp.testing.assert_allclose(np.polysub(np.array(a), np.array(b))
                                .asnumpy(), onp.polysub(a, b))
    onp.testing.assert_allclose(np.polymul(np.array(a), np.array(b))
                                .asnumpy(), onp.polymul(a, b))
    q, r = np.polydiv(np.array(a), np.array(b))
    qn, rn = onp.polydiv(a, b)
    onp.testing.assert_allclose(q.asnumpy(), qn, rtol=1e-5)
    onp.testing.assert_allclose(np.polyder(np.array(a)).asnumpy(),
                                onp.polyder(a))
    onp.testing.assert_allclose(np.polyint(np.array(a)).asnumpy(),
                                onp.polyint(a), rtol=1e-6)
    onp.testing.assert_allclose(np.polyder(np.array(a), m=2).asnumpy(),
                                onp.polyder(a, 2))


def test_polyfit_and_roots():
    xs = onp.linspace(0, 1, 20).astype("float32")
    ys = 2 * xs ** 2 + 1
    fit = np.polyfit(np.array(xs), np.array(ys), 2).asnumpy()
    onp.testing.assert_allclose(fit, [2.0, 0.0, 1.0], atol=1e-3)
    r = onp.sort(onp.real(np.roots(np.array([1.0, -3.0, 2.0])).asnumpy()))
    onp.testing.assert_allclose(r, [1.0, 2.0], atol=1e-4)
    # poly(roots) round-trips the monic coefficients
    c = np.poly(np.array([1.0, 2.0])).asnumpy()
    onp.testing.assert_allclose(onp.real(c), [1.0, -3.0, 2.0], atol=1e-5)


# -- windows / misc ---------------------------------------------------------

def test_windows_match_numpy():
    for name in ("blackman", "hamming", "hanning", "bartlett"):
        onp.testing.assert_allclose(getattr(np, name)(12).asnumpy(),
                                    getattr(onp, name)(12), atol=1e-6)
    onp.testing.assert_allclose(np.kaiser(12, 8.6).asnumpy(),
                                onp.kaiser(12, 8.6), atol=1e-5)


def test_unwrap_spacing_misc():
    p = onp.array([0.0, 3.0, 6.0, 9.0], "float32")
    onp.testing.assert_allclose(np.unwrap(np.array(p)).asnumpy(),
                                onp.unwrap(p), rtol=1e-5)
    assert np.spacing(np.array([1.0])).asnumpy()[0] == \
        onp.spacing(onp.float32(1.0))
    x = onp.arange(6.0, dtype="float32").reshape(2, 3)
    assert np.matrix_transpose(np.array(x)).shape == (3, 2)
    onp.testing.assert_allclose(
        np.histogram_bin_edges(np.array([1.0, 2.0, 3.0]), bins=4)
        .asnumpy(), onp.histogram_bin_edges(onp.array([1., 2., 3.]), 4))


def test_place_putmask_copyto_mgrid():
    arr = np.array([1.0, 2.0, 3.0, 4.0])
    np.place(arr, np.array([True, False, True, True]),
             np.array([9.0, 8.0]))
    onp.testing.assert_allclose(arr.asnumpy(), [9, 2, 8, 9])
    arr2 = np.array([1.0, 2.0, 3.0, 4.0])
    np.putmask(arr2, np.array([True, False, True, True]),
               np.array([9.0, 8.0]))
    onp.testing.assert_allclose(arr2.asnumpy(), [9, 2, 9, 8])
    # numpy oracles for the same semantics
    n1 = onp.array([1.0, 2.0, 3.0, 4.0])
    onp.place(n1, onp.array([True, False, True, True]),
              onp.array([9.0, 8.0]))
    onp.testing.assert_allclose(arr.asnumpy(), n1)
    n2 = onp.array([1.0, 2.0, 3.0, 4.0])
    onp.putmask(n2, onp.array([True, False, True, True]),
                onp.array([9.0, 8.0]))
    onp.testing.assert_allclose(arr2.asnumpy(), n2)
    dst = np.zeros((3,))
    np.copyto(dst, np.array([1.0, 2.0, 3.0]))
    onp.testing.assert_allclose(dst.asnumpy(), [1, 2, 3])
    g = np.mgrid[0:3, 0:2]
    onp.testing.assert_allclose(g[0].asnumpy(), onp.mgrid[0:3, 0:2][0])
    og = np.ogrid[0:3]
    onp.testing.assert_allclose(og.asnumpy(), onp.ogrid[0:3])


# -- new random distributions ----------------------------------------------

def test_random_dirichlet_wald_noncentral():
    d = np.random.dirichlet([1.0, 2.0, 3.0], size=(200,)).asnumpy()
    onp.testing.assert_allclose(d.sum(1), onp.ones(200), rtol=1e-5)
    onp.testing.assert_allclose(d.mean(0), [1 / 6, 2 / 6, 3 / 6],
                                atol=0.05)
    w = np.random.wald(3.0, 2.0, size=(40000,)).asnumpy()
    assert abs(w.mean() - 3.0) < 0.15
    assert (w > 0).all()
    nc = np.random.noncentral_chisquare(3.0, 2.0, size=(40000,)).asnumpy()
    assert abs(nc.mean() - 5.0) < 0.2          # mean = df + nonc


def test_random_logseries_vonmises_zipf():
    p = 0.5
    ls = np.random.logseries(p, size=(50000,)).asnumpy()
    want = -p / ((1 - p) * onp.log(1 - p))
    assert abs(ls.mean() - want) < 0.03
    assert ls.min() >= 1
    vm = np.random.vonmises(0.5, 4.0, size=(50000,)).asnumpy()
    assert (vm >= -onp.pi).all() and (vm <= onp.pi).all()
    cm = onp.angle(onp.exp(1j * vm).mean())
    assert abs(cm - 0.5) < 0.02
    # concentration: circular variance matches scipy's vonmises
    R = onp.abs(onp.exp(1j * vm).mean())
    assert abs(R - (ss.i1(4.0) / ss.i0(4.0))) < 0.01
    z = np.random.zipf(3.0, size=(50000,)).asnumpy()
    assert z.min() >= 1
    assert abs(z.mean() - ss.zeta(2.0) / ss.zeta(3.0)) < 0.05


def test_random_standard_families():
    sg = np.random.standard_gamma(2.0, size=(40000,)).asnumpy()
    assert abs(sg.mean() - 2.0) < 0.1
    sc = np.random.standard_cauchy(size=(1000,)).asnumpy()
    assert onp.isfinite(sc).all()
    t5 = np.random.standard_t(5.0, size=(40000,)).asnumpy()
    assert abs(t5.std() - onp.sqrt(5.0 / 3.0)) < 0.05
    tr = np.random.triangular(0.0, 0.5, 1.0, size=(40000,)).asnumpy()
    assert abs(tr.mean() - 0.5) < 0.02


def test_review_regressions():
    """Round-5 review findings: signed spacing, scalar place/putmask,
    copyto dtype preservation, vonmises kappa=0, zipf validation,
    bernoulli static n."""
    # spacing keeps numpy's SIGN convention (the round-5 duplicate
    # registration that dropped it was removed)
    assert np.spacing(np.array([-1.0])).asnumpy()[0] == \
        onp.spacing(onp.float32(-1.0))
    # scalar vals forms
    a1 = np.array([1.0, 2.0, 3.0])
    np.place(a1, np.array([True, False, True]), 5)
    onp.testing.assert_allclose(a1.asnumpy(), [5, 2, 5])
    a2 = np.array([1.0, 2.0, 3.0])
    np.putmask(a2, np.array([False, True, True]), 7.0)
    onp.testing.assert_allclose(a2.asnumpy(), [1, 7, 7])
    # copyto preserves destination dtype through a where mask
    dst = np.array([1, 2, 3], dtype="int32")
    np.copyto(dst, np.array([9.9, 9.9, 9.9]),
              where=np.array([True, False, True]))
    assert str(dst.dtype) == "int32"
    assert dst.asnumpy().tolist() == [9, 2, 9]
    # kappa=0 vonmises is the uniform circular distribution
    mx.random.seed(1)
    vm0 = np.random.vonmises(0.0, 0.0, size=(20000,)).asnumpy()
    assert onp.isfinite(vm0).all()
    assert abs(onp.abs(onp.exp(1j * vm0).mean())) < 0.03
    with pytest.raises(ValueError):
        np.random.zipf(1.0, size=(4,))
    with pytest.raises(TypeError):
        np.random.standard_gamma(np.array([1.0, 2.0]), size=(4,))
    # bernoulli numbers: B_0..B_3
    bn = npx.special.bernoulli(3).asnumpy()
    onp.testing.assert_allclose(bn, ss.bernoulli(3), rtol=1e-6)


# -- npx.special / npx.stats (scipy oracle) ---------------------------------

def test_npx_special_against_scipy():
    x = onp.array([0.1, 0.5, 0.9], "float32")
    a = onp.array([1.5, 2.0, 3.0], "float32")
    b = onp.array([2.0, 1.0, 0.5], "float32")
    cases = [
        (npx.special.expit, ss.expit, (x,)),
        (npx.special.logit, ss.logit, (x,)),
        (npx.special.ndtr, ss.ndtr, (x,)),
        (npx.special.ndtri, ss.ndtri, (x,)),
        (npx.special.xlogy, ss.xlogy, (a, b)),
        (npx.special.xlog1py, ss.xlog1py, (a, b)),
        (npx.special.entr, ss.entr, (x,)),
        (npx.special.rel_entr, ss.rel_entr, (a, b)),
        (npx.special.kl_div, ss.kl_div, (a, b)),
        (npx.special.i0e, ss.i0e, (a,)),
        (npx.special.i1, ss.i1, (a,)),
        (npx.special.i1e, ss.i1e, (a,)),
        (npx.special.betainc, ss.betainc, (a, b, x)),
        (npx.special.zeta, ss.zeta, (a, b)),
    ]
    for ours, ref, args in cases:
        got = ours(*[np.array(v) for v in args]).asnumpy()
        onp.testing.assert_allclose(got, ref(*args), rtol=2e-4,
                                    atol=1e-5, err_msg=ref.__name__)


def test_npx_special_second_batch_against_scipy():
    """Defensively-registered batch: only assert the names this jax build
    actually provides (absent ones are not registered either)."""
    a = onp.array([1.5, 2.0, 3.0], "float32")
    b = onp.array([2.0, 1.0, 0.5], "float32")
    k = onp.array([1.0, 2.0, 3.0], "float32")
    maybe = [
        ("betaln", (a, b), ss.betaln),
        ("factorial", (k,), lambda x: ss.factorial(x)),
        ("gammasgn", (a,), ss.gammasgn),
        ("poch", (a, b), ss.poch),
        ("spence", (a,), ss.spence),
        ("expi", (a,), ss.expi),
        ("exp1", (a,), ss.exp1),
        ("multigammaln", (a, 2), lambda x, d: ss.multigammaln(x, d)),
        ("hyp1f1", (a, b, onp.float32(0.5)),
         lambda x, y, z: ss.hyp1f1(x, y, z)),
    ]
    tested = 0
    for name, args, ref in maybe:
        ours = getattr(npx.special, name, None)
        if ours is None:
            continue
        mx_args = [np.array(v) if isinstance(v, onp.ndarray) else v
                   for v in args]
        got = ours(*mx_args).asnumpy()
        onp.testing.assert_allclose(got, ref(*args), rtol=2e-3,
                                    atol=1e-5, err_msg=name)
        tested += 1
    assert tested >= 4, "suspiciously few second-batch specials: %d" % tested


def test_npx_special_gradients():
    from mxnet_tpu import autograd, nd
    x = nd.array([0.3])
    x.attach_grad()
    with autograd.record():
        y = npx.special.expit(x)
    y.backward()
    s = ss.expit(0.3)
    onp.testing.assert_allclose(x.grad.asnumpy(), [s * (1 - s)], rtol=1e-5)


def test_npx_stats_against_scipy():
    x = onp.array([0.0, 1.0, -0.5], "float32")
    onp.testing.assert_allclose(
        npx.stats.norm.logpdf(np.array(x)).asnumpy(),
        st.norm.logpdf(x), rtol=1e-5)
    onp.testing.assert_allclose(
        npx.stats.norm.cdf(np.array(x)).asnumpy(),
        st.norm.cdf(x), rtol=1e-5)
    onp.testing.assert_allclose(
        npx.stats.gamma.logpdf(np.array([1.5]), np.array([2.0]))
        .asnumpy(), st.gamma.logpdf(1.5, 2.0), rtol=1e-5)
    onp.testing.assert_allclose(
        npx.stats.poisson.logpmf(np.array([2.0]), np.array([3.0]))
        .asnumpy(), st.poisson.logpmf(2, 3), rtol=1e-5)
    onp.testing.assert_allclose(
        npx.stats.t.logpdf(np.array([0.5]), np.array([5.0])).asnumpy(),
        st.t.logpdf(0.5, 5.0), rtol=1e-5)


# -- census artifact stays honest -------------------------------------------

def test_op_census_zero_missing_and_850_kernels(tmp_path):
    import json
    import subprocess
    import sys as _sys
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out_json = str(tmp_path / "census.json")
    r = subprocess.run([_sys.executable, "tools/op_census.py",
                        "--json", out_json],
                       capture_output=True, text=True, cwd=repo)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "MISSING: none" in r.stdout
    # the checked-in artifact must match the live registry — a renamed
    # or added op without a census regen is a stale round artifact
    with open(out_json) as f:
        live = json.load(f)
    with open(os.path.join(repo, "OP_CENSUS.json")) as f:
        committed = json.load(f)
    assert live == committed, \
        "OP_CENSUS.json is stale: rerun tools/op_census.py --json"
    from mxnet_tpu.ops import registry as reg
    uniq = set()
    for spec in reg._REGISTRY.values():
        fn = getattr(spec, "fn", None) or spec
        uniq.add(id(fn))
    assert len(uniq) >= 850, len(uniq)


def test_npx_stragglers_and_autograd_get_symbol():
    """2.x npx surface stragglers route through the registry; nd.eye
    matches numpy; autograd.get_symbol refuses with guidance."""
    x = mx.nd.array(onp.array([[1.0, 2.0], [3.0, 4.0]], "float32"))
    onp.testing.assert_allclose(npx.gamma(x).asnumpy(),
                                ss.gamma(x.asnumpy()), rtol=1e-5)
    al = npx.arange_like(x)
    assert al.size == 4
    rl = npx.reshape_like(mx.nd.array(onp.arange(4.0)), x)
    assert rl.shape == (2, 2)
    onp.testing.assert_allclose(mx.nd.eye(3, k=1).asnumpy(),
                                onp.eye(3, k=1))
    assert npx.num_gpus() == 0
    assert npx.cpu().device_type == "cpu"
    assert npx.current_device() is not None
    with pytest.raises(Exception, match="hybridize"):
        mx.autograd.get_symbol(x)
