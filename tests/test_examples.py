"""The examples/ scripts must stay runnable offline (reference pattern:
example/ scripts are smoke-run in CI)."""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, *args, timeout=600, cwd=None):
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    # pin explicitly: in the MX_TEST_CTX=tpu lane the conftest does NOT
    # set these, and an unpinned example subprocess would hang on a
    # wedged tunnel until its timeout
    env["JAX_PLATFORMS"] = "cpu"
    env["MX_FORCE_CPU"] = "1"
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "examples", script), *args],
                       capture_output=True, text=True, timeout=timeout,
                       env=env, cwd=cwd or REPO)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    return r.stdout


def test_mnist_example():
    out = _run("train_mnist_gluon.py", "--epochs", "1", "--hybridize")
    assert "final test accuracy" in out


def test_resnet_dp_example(tmp_path):
    out = _run("train_resnet_dp.py", "--steps", "2", "--batch-size", "8",
               "--image-size", "32", "--model", "resnet18_v1",
               cwd=str(tmp_path))
    assert "step 1 loss" in out
    for f in ("resnet_dp_trained-symbol.json",
              "resnet_dp_trained-0000.params"):
        assert os.path.exists(os.path.join(str(tmp_path), f))


def test_ssd_example():
    out = _run("train_ssd.py", "--epochs", "1")
    assert "mAP07" in out


def test_word_lm_example():
    """BASELINE config 3 example surface (reference example/rnn/word_lm):
    LSTM LM with truncated BPTT, perplexity + wps logging."""
    out = _run("word_lm.py", "--epochs", "1", "--max-batches", "8",
               "--batch-size", "8", "--bptt", "16", "--hidden", "32",
               "--embed", "16", "--vocab", "200")
    assert "Train-perplexity=" in out
    assert "final train perplexity" in out


def test_dist_async_example():
    """PS workflow example: 1 server + 2 workers converge async."""
    import subprocess
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "launch.py"),
                        "-n", "2", "-s", "1", "--launcher", "local", "--",
                        sys.executable,
                        os.path.join(REPO, "examples",
                                     "train_dist_async.py"),
                        "--steps", "25"],
                       capture_output=True, text=True, timeout=420, env=env)
    assert r.returncode == 0, (r.stdout[-1500:], r.stderr[-1500:])
    finals = [float(l.split("loss")[1].split("(")[0])
              for l in r.stdout.splitlines() if "FINAL" in l]
    assert len(finals) == 2
    assert all(v < 1.0 for v in finals), finals


def test_dcgan_example():
    """Adversarial two-Trainer loop (reference example/gluon/dcgan)."""
    out = _run("dcgan.py", "--epochs", "1", "--batch-size", "16",
               "--max-batches", "2")
    assert "lossD" in out and "lossG" in out


def test_super_resolution_example(tmp_path):
    """ESPCN + PixelShuffle + the canonical ONNX-export path
    (reference example/gluon/super_resolution)."""
    onnx_path = os.path.join(str(tmp_path), "sr.onnx")
    out = _run("super_resolution.py", "--epochs", "1", "--max-batches",
               "2", "--export", onnx_path, cwd=str(tmp_path))
    assert "psnr" in out
    assert os.path.exists(onnx_path) and os.path.getsize(onnx_path) > 1000


def test_lstm_bucketing_example():
    """Classic pre-Gluon stack: BucketSentenceIter + symbolic rnn cells +
    BucketingModule.fit (reference example/rnn/bucketing)."""
    out = _run("lstm_bucketing.py", "--num-epochs", "2", "--vocab", "80",
               "--num-hidden", "24", "--num-embed", "12",
               "--buckets", "10", "20", "30", "40", timeout=900)
    # epoch logs ride stderr (logging); stdout carries the final score.
    # Untrained-random scores ~110 on this config (uniform = vocab 80):
    # the bound must separate learning from a stall
    assert "final train perplexity" in out
    final = float(out.strip().splitlines()[-1].split(":")[1])
    assert final < 95, final


def test_symbolic_mnist_example():
    """Classic Module.fit workflow with auto-created symbol params
    (reference example/image-classification/train_mnist.py)."""
    out = _run("train_mnist_symbolic.py", "--num-epochs", "3",
               timeout=900)
    acc = float(out.strip().splitlines()[-1].split(":")[1])
    assert acc > 0.9, acc


def test_symbolic_lenet_example():
    """The conv branch: symbolic Convolution/Pooling auto-params."""
    out = _run("train_mnist_symbolic.py", "--network", "lenet",
               "--num-epochs", "1", timeout=900)
    acc = float(out.strip().splitlines()[-1].split(":")[1])
    assert acc > 0.9, acc


def test_quantize_model_example():
    """Post-training INT8 flow: train fp32 -> calibrate -> compare
    (reference example/quantization).  The quantized-layer count proves
    the rewrite actually engaged (a hybridize-cache bypass once made
    this comparison fp32-vs-fp32)."""
    out = _run("quantize_model.py", "--epochs", "2", timeout=900)
    lines = out.strip().splitlines()
    n_q = int([l for l in lines if l.startswith("quantized layers")][0]
              .split(":")[1])
    assert n_q == 4, out
    drop = float(lines[-1].split(":")[1])
    assert abs(drop) < 0.1, out


def test_feedforward_mnist_example():
    out = _run("train_mnist_feedforward.py", "--epochs", "4")
    assert "final test accuracy" in out
    assert "checkpoint roundtrip OK" in out


def test_long_context_example():
    out = _run("train_long_context.py", "--seq-len", "128", "--steps",
               "30", "--batch", "2", "--d-model", "32", "--heads", "2",
               "--layers", "1")
    assert "final loss" in out
    assert "sp=2" in out
