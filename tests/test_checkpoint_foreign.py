"""Foreign-bytes conformance for the checkpoint formats (VERDICT r4
weak #5: symbol.json / .params V2 round-trips had only ever read this
repo's own writing).

The fixtures here are authored by INDEPENDENT encoders transcribed from
the reference formats (src/ndarray/ndarray.cc NDArray::Save V2 dense
layout; the nnvm symbol.json schema) — struct-packed by hand in this
file with no code shared with mxnet_tpu — then loaded through the
public API and executed.  A reader bug that compensates for a writer
bug cannot pass these.
"""
import json
import os
import struct

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd

FIXDIR = os.path.join(os.path.dirname(__file__), "fixtures")


# ---------------------------------------------------------------------------
# Independent V2 .params encoder (reference dense layout:
#   file:  u64 list_magic=0x112, u64 reserved, u64 count, arrays...,
#          u64 name_count, (u64 len + utf8)...
#   array: u32 0xF993FAC9, i32 stype=0, u32 ndim, u32 dims...,
#          i32 devtype=1, i32 devid=0, i32 type_flag, raw bytes
# type_flag: 0=f32 1=f64 2=f16 3=u8 4=i32 5=i8 6=i64
# ---------------------------------------------------------------------------

_FLAG = {"float32": 0, "float64": 1, "uint8": 3, "int32": 4, "int64": 6}


def _enc_array(a):
    a = np.ascontiguousarray(a)
    out = struct.pack("<I", 0xF993FAC9) + struct.pack("<i", 0)
    out += struct.pack("<I", a.ndim)
    for d in a.shape:
        out += struct.pack("<I", d)
    out += struct.pack("<ii", 1, 0)
    out += struct.pack("<i", _FLAG[str(a.dtype)])
    return out + a.tobytes()


def _enc_params(named):
    out = struct.pack("<QQ", 0x112, 0)
    out += struct.pack("<Q", len(named))
    for _n, a in named:
        out += _enc_array(a)
    out += struct.pack("<Q", len(named))
    for n, _a in named:
        nb = n.encode("utf-8")
        out += struct.pack("<Q", len(nb)) + nb
    return out


def _write_or_verify(path, data):
    if os.path.exists(path):
        with open(path, "rb") as f:
            assert f.read() == data, \
                "foreign fixture generator drifted from %s" % path
    else:
        with open(path, "wb") as f:
            f.write(data)


def test_foreign_params_v2_loads():
    """nd.load on bytes this repo's writer never produced: dtype flags,
    shapes and name table must all decode to the right values."""
    rng = np.random.RandomState(9)
    named = [
        ("arg:fc_weight", rng.randn(3, 4).astype(np.float32)),
        ("arg:fc_bias", np.array([1.5, -2.0, 0.25], np.float32)),
        ("aux:step", np.array([7], np.int64)),
        ("bytes", np.arange(6, dtype=np.uint8).reshape(2, 3)),
        ("wide", rng.randn(2, 2).astype(np.float64)),
        ("ints", np.array([[1, -2], [3, -4]], np.int32)),
    ]
    data = _enc_params(named)
    path = os.path.join(FIXDIR, "foreign_v2.params")
    _write_or_verify(path, data)
    loaded = nd.load(path)
    assert sorted(loaded) == sorted(n for n, _ in named)
    for n, a in named:
        got = loaded[n].asnumpy()
        # x64 is off (TPU-first): 64-bit payloads load at 32-bit width;
        # KIND must survive exactly (same rule as the numpy sweep)
        assert np.dtype(got.dtype).kind == np.dtype(a.dtype).kind, \
            (n, got.dtype, a.dtype)
        if np.dtype(a.dtype).itemsize <= 4:
            assert str(got.dtype) == str(a.dtype), (n, got.dtype)
        if np.dtype(a.dtype).kind == "f":
            np.testing.assert_allclose(got.astype(np.float64),
                                       a.astype(np.float64),
                                       rtol=1e-6, err_msg=n)
        else:
            np.testing.assert_array_equal(got.astype(a.dtype), a,
                                          err_msg=n)


def test_foreign_unnamed_list_params_load():
    """name_count=0 files decode to a plain list (reference Save of a
    list rather than a dict)."""
    a0 = np.ones((2, 2), np.float32)
    a1 = np.arange(3, dtype=np.int32)
    data = struct.pack("<QQ", 0x112, 0) + struct.pack("<Q", 2) \
        + _enc_array(a0) + _enc_array(a1) + struct.pack("<Q", 0)
    import tempfile
    p = os.path.join(tempfile.mkdtemp(), "list.params")
    with open(p, "wb") as f:
        f.write(data)
    out = nd.load(p)
    assert isinstance(out, list) and len(out) == 2
    np.testing.assert_array_equal(out[0].asnumpy(), a0)
    np.testing.assert_array_equal(out[1].asnumpy(), a1)


# ---------------------------------------------------------------------------
# Foreign symbol.json: hand-written per the nnvm schema (nodes /
# arg_nodes / node_row_ptr / heads / attrs), deliberately formatted
# differently from this repo's tojson output.
# ---------------------------------------------------------------------------

FOREIGN_SYMBOL = {
    "nodes": [
        {"op": "null", "name": "data", "inputs": []},
        {"op": "null", "name": "fc_weight", "inputs": []},
        {"op": "null", "name": "fc_bias", "inputs": []},
        {"op": "FullyConnected", "name": "fc",
         "attrs": {"num_hidden": "3"},
         "inputs": [[0, 0, 0], [1, 0, 0], [2, 0, 0]]},
        {"op": "Activation", "name": "act",
         "attrs": {"act_type": "relu"}, "inputs": [[3, 0, 0]]},
        # reference JSON always carries the auto-created label node as
        # the loss head's second input
        {"op": "null", "name": "softmax_label", "inputs": []},
        {"op": "SoftmaxOutput", "name": "softmax",
         "inputs": [[4, 0, 0], [5, 0, 0]]},
    ],
    "arg_nodes": [0, 1, 2, 5],
    "node_row_ptr": [0, 1, 2, 3, 4, 5, 6, 7],
    "heads": [[6, 0, 0]],
    "attrs": {"mxnet_version": ["int", 10900]},
}


def test_foreign_symbol_json_loads_and_runs(tmp_path):
    """symbol.load on a hand-written nnvm-schema graph (compact JSON,
    v1.x version stamp, no auto-label node): composes, infers shapes,
    binds and runs — and interoperates with the foreign .params."""
    path = str(tmp_path / "foreign-symbol.json")
    with open(path, "w") as f:
        json.dump(FOREIGN_SYMBOL, f, separators=(",", ":"))
    s = mx.sym.load(path)
    assert s.list_arguments() == ["data", "fc_weight", "fc_bias",
                                  "softmax_label"]
    rng = np.random.RandomState(1)
    W = rng.randn(3, 4).astype(np.float32)
    b = np.array([1.5, -2.0, 0.25], np.float32)
    x = rng.randn(2, 4).astype(np.float32)
    args = {"data": nd.array(x), "fc_weight": nd.array(W),
            "fc_bias": nd.array(b),
            "softmax_label": nd.zeros((2,))}
    exe = s.bind(mx.cpu(), args)
    out = exe.forward()[0].asnumpy()
    # softmax(relu(xW^T + b)) computed independently
    h = np.maximum(x @ W.T + b, 0)
    e = np.exp(h - h.max(1, keepdims=True))
    np.testing.assert_allclose(out, e / e.sum(1, keepdims=True),
                               rtol=1e-5, atol=1e-6)


def test_foreign_checkpoint_pair_through_mx_model(tmp_path):
    """load_checkpoint consumes a (symbol.json, .params) pair authored
    entirely by the independent encoders, and Module predicts with it."""
    prefix = str(tmp_path / "foreign")
    with open(prefix + "-symbol.json", "w") as f:
        json.dump(FOREIGN_SYMBOL, f, indent=2)
    rng = np.random.RandomState(2)
    W = rng.randn(3, 4).astype(np.float32)
    b = rng.randn(3).astype(np.float32)
    with open(prefix + "-0007.params", "wb") as f:
        f.write(_enc_params([("arg:fc_weight", W), ("arg:fc_bias", b)]))
    symb, arg_params, aux_params = mx.model.load_checkpoint(prefix, 7)
    assert set(arg_params) == {"fc_weight", "fc_bias"}
    mod = mx.mod.Module(symb, data_names=("data",),
                        label_names=("softmax_label",), context=mx.cpu())
    x = rng.randn(2, 4).astype(np.float32)
    it = mx.io.NDArrayIter(x, np.zeros(2, np.float32), batch_size=2)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=False)
    mod.set_params(arg_params, aux_params)
    preds = mod.predict(it)
    first = preds[0] if isinstance(preds, list) else preds
    got = first.asnumpy() if hasattr(first, "asnumpy") else np.asarray(first)
    h = np.maximum(x @ W.T + b, 0)
    e = np.exp(h - h.max(1, keepdims=True))
    np.testing.assert_allclose(got.reshape(2, 3),
                               e / e.sum(1, keepdims=True),
                               rtol=1e-5, atol=1e-6)
