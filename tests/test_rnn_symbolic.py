"""mx.rnn symbolic cell API (reference pattern:
tests/python/unittest/test_rnn.py — build cells, unroll, infer shape,
bind, and compare fused vs unfused numerics)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd


def _embed(V=20, E=8):
    data = mx.sym.Variable("data")
    return mx.sym.Embedding(data=data, input_dim=V, output_dim=E,
                            name="embed")


def test_lstm_cell_unroll_shapes():
    T, N, H = 5, 4, 6
    cell = mx.rnn.LSTMCell(H, prefix="lstm_")
    outputs, states = cell.unroll(T, inputs=_embed(), merge_outputs=True)
    exe = outputs.simple_bind(mx.cpu(), data=(N, T))
    assert sorted(a for a in outputs.list_arguments() if "lstm" in a) == [
        "lstm_h2h_bias", "lstm_h2h_weight", "lstm_i2h_bias",
        "lstm_i2h_weight"]
    exe.arg_dict["data"][:] = nd.array(
        np.random.RandomState(0).randint(0, 20, (N, T)))
    out = exe.forward()
    assert out[0].shape == (N, T, H)
    assert len(states) == 2


def test_gru_residual_stack_and_zoneout():
    T, N, H, E = 4, 3, 8, 8
    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.GRUCell(H, prefix="g0_"))
    stack.add(mx.rnn.ResidualCell(mx.rnn.GRUCell(H, prefix="g1_")))
    stack.add(mx.rnn.DropoutCell(0.0))
    outputs, _ = stack.unroll(T, inputs=_embed(E=E), merge_outputs=True)
    exe = outputs.simple_bind(mx.cpu(), data=(N, T))
    exe.arg_dict["data"][:] = nd.array(
        np.random.RandomState(1).randint(0, 20, (N, T)))
    assert exe.forward()[0].shape == (N, T, H)


def test_zoneout_first_step_zones_against_zeros():
    """Reference ZoneoutCell zones the FIRST output against a zeros
    prev_output (mask*new), so with high zoneout some units of step-1
    output are exactly zero — not an unmasked pass-through."""
    N, H, E = 4, 16, 8
    cell = mx.rnn.ZoneoutCell(mx.rnn.GRUCell(H, prefix="g_"),
                              zoneout_outputs=0.5)
    outputs, _ = cell.unroll(1, inputs=_embed(E=E), merge_outputs=True)
    exe = outputs.simple_bind(mx.cpu(), data=(N, 1))
    rs = np.random.RandomState(3)
    exe.arg_dict["data"][:] = nd.array(rs.randint(0, 20, (N, 1)))
    for name, arr in exe.arg_dict.items():
        if name != "data":
            arr[:] = nd.array(rs.randn(*arr.shape) * 0.5)
    out = exe.forward(is_train=True)[0].asnumpy()
    n_zero = int((out == 0.0).sum())
    assert 0 < n_zero < out.size, n_zero


def test_cell_params_shared_across_steps():
    """Unrolling must reuse ONE weight set (RNNParams sharing)."""
    cell = mx.rnn.RNNCell(5, prefix="r_")
    outputs, _ = cell.unroll(6, inputs=_embed(), merge_outputs=True)
    args = [a for a in outputs.list_arguments() if a.startswith("r_")]
    assert sorted(args) == ["r_h2h_bias", "r_h2h_weight", "r_i2h_bias",
                            "r_i2h_weight"]


def test_fused_cell_matches_gluon_numerics():
    """FusedRNNCell (symbol) and gluon.rnn.LSTM (imperative) share the
    ops/rnn.py kernel — same blob in, same numbers out."""
    T, N, I, H = 5, 3, 4, 6
    rng = np.random.RandomState(2)
    x = rng.randn(T, N, I).astype(np.float32)

    fused = mx.rnn.FusedRNNCell(H, num_layers=1, mode="lstm",
                                prefix="fl_")
    data = mx.sym.Variable("data")
    out, _ = fused.unroll(T, inputs=data, layout="TNC")
    exe = out.simple_bind(mx.cpu(), data=(T, N, I))
    from mxnet_tpu.ops.rnn import rnn_param_size
    n = rnn_param_size(1, I, H, "lstm")
    blob = rng.randn(n).astype(np.float32) * 0.1
    exe.arg_dict["data"][:] = nd.array(x)
    exe.arg_dict["fl_parameters"][:] = nd.array(blob)
    sym_out = exe.forward()[0].asnumpy()

    gnet = mx.gluon.rnn.LSTM(H, num_layers=1)
    gnet.initialize()
    gnet(nd.zeros((T, N, I)))
    # gluon packs per-layer params into the same blob layout
    params = gnet.collect_params()
    gh = 4 * H
    ofs = 0
    for pname, cols in (("l0_i2h_weight", I), ("l0_h2h_weight", H)):
        size = gh * cols
        params[pname].set_data(nd.array(
            blob[ofs:ofs + size].reshape(gh, cols)))
        ofs += size
    for pname in ("l0_i2h_bias", "l0_h2h_bias"):
        params[pname].set_data(nd.array(blob[ofs:ofs + gh]))
        ofs += gh
    glu_out = gnet(nd.array(x)).asnumpy()
    np.testing.assert_allclose(sym_out, glu_out, rtol=1e-5, atol=1e-6)


def test_bidirectional_cell():
    T, N, H = 4, 2, 5
    bi = mx.rnn.BidirectionalCell(mx.rnn.LSTMCell(H, prefix="fl_"),
                                  mx.rnn.LSTMCell(H, prefix="bl_"))
    out, states = bi.unroll(T, inputs=_embed(), merge_outputs=True)
    exe = out.simple_bind(mx.cpu(), data=(N, T))
    exe.arg_dict["data"][:] = nd.array(
        np.random.RandomState(3).randint(0, 20, (N, T)))
    assert exe.forward()[0].shape == (N, T, 2 * H)
    assert len(states) == 4


def test_unfuse_geometry():
    fused = mx.rnn.FusedRNNCell(6, num_layers=2, mode="gru",
                                bidirectional=True, prefix="fg_")
    stack = fused.unfuse()
    out, _ = stack.unroll(3, inputs=_embed(), merge_outputs=True)
    exe = out.simple_bind(mx.cpu(), data=(2, 3))
    exe.arg_dict["data"][:] = nd.array(
        np.random.RandomState(4).randint(0, 20, (2, 3)))
    assert exe.forward()[0].shape == (2, 3, 12)


def test_classic_symbol_autovars():
    """Keyword inputs + auto-created parameter variables (the v1.x
    composition convention this round enables)."""
    data = mx.sym.Variable("data")
    c = mx.sym.Convolution(data=data, num_filter=4, kernel=(3, 3),
                           pad=(1, 1), name="c1")
    b = mx.sym.BatchNorm(data=c, name="bn1")
    f = mx.sym.FullyConnected(data=mx.sym.Flatten(b), num_hidden=3,
                              name="fc1")
    assert "c1_weight" in f.list_arguments()
    assert "bn1_gamma" in f.list_arguments()
    assert "bn1_moving_mean" in f.list_auxiliary_states()
    exe = f.simple_bind(mx.cpu(), data=(2, 3, 6, 6))
    assert exe.forward(is_train=True)[0].shape == (2, 3)
    # no_bias suppresses the bias variable
    g = mx.sym.FullyConnected(data=data, num_hidden=3, no_bias=True,
                              name="nb")
    assert "nb_bias" not in g.list_arguments()


def test_rnn_checkpoint_helpers(tmp_path):
    """rnn.save_rnn_checkpoint/load_rnn_checkpoint round-trip through
    cell pack/unpack; do_rnn_checkpoint is the callback form."""
    T, N, H, E = 3, 2, 6, 8
    cell = mx.rnn.LSTMCell(H, prefix="ck_")
    outputs, _ = cell.unroll(T, inputs=_embed(E=E), merge_outputs=True)
    exe = outputs.simple_bind(mx.cpu(), data=(N, T))
    rs = np.random.RandomState(0)
    args = {}
    for name, arr in exe.arg_dict.items():
        if name != "data":
            arr[:] = nd.array(rs.randn(*arr.shape) * 0.1)
            args[name] = arr.copy()
    prefix = str(tmp_path / "rnn-ck")
    mx.rnn.save_rnn_checkpoint(cell, prefix, 3, outputs, args, {})
    sym2, arg2, aux2 = mx.rnn.load_rnn_checkpoint(cell, prefix, 3)
    assert sorted(arg2) == sorted(args)
    for k in args:
        np.testing.assert_allclose(arg2[k].asnumpy(), args[k].asnumpy())
    # callback form writes on the matching epoch
    cb = mx.rnn.do_rnn_checkpoint(cell, str(tmp_path / "cb"), period=2)
    cb(1, outputs, args, {})       # epoch index 1 -> (1+1)%2==0 -> saves
    import os
    assert os.path.exists(str(tmp_path / "cb-0002.params"))


def test_module_checkpoint_callback(tmp_path):
    mod_sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=3,
                              name="mfc"), name="softmax")
    mod = mx.mod.Module(mod_sym, context=mx.cpu())
    X = np.random.RandomState(0).randn(16, 4).astype(np.float32)
    Y = np.random.RandomState(1).randint(0, 3, 16).astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=8)
    prefix = str(tmp_path / "mc")
    mx.mod  # namespace sanity
    mod.fit(it, num_epoch=2, optimizer="sgd",
            epoch_end_callback=mx.callback.module_checkpoint(mod, prefix))
    import os
    assert os.path.exists(prefix + "-0002.params")
    assert os.path.exists(prefix + "-symbol.json")


def test_fused_cell_unpack_pack_roundtrip():
    """FusedRNNCell.unpack_weights splits the cuDNN blob into per-gate
    i2h/h2h matrices (so rnn checkpoints hold per-gate layouts) and
    pack_weights inverts it exactly — including bidirectional stacks."""
    from mxnet_tpu.ops.rnn import rnn_param_size
    for mode, bidir, L in (("lstm", False, 1), ("gru", True, 2)):
        cell = mx.rnn.FusedRNNCell(6, num_layers=L, mode=mode,
                                   bidirectional=bidir, prefix="fz_")
        I = 5
        psize = rnn_param_size(L, I, 6, mode, bidirectional=bidir)
        rs = np.random.RandomState(0)
        blob = nd.array(rs.randn(psize).astype(np.float32))
        args = {"fz_parameters": blob, "other": nd.array(np.ones(2))}
        unpacked = cell.unpack_weights(args)
        assert "fz_parameters" not in unpacked
        assert "other" in unpacked
        gates = {"lstm": 4, "gru": 3}[mode]
        dirs = 2 if bidir else 1
        # per (layer, dir): i2h+h2h weights and biases per gate
        n_per_gate = L * dirs * 2 * 2
        assert len(unpacked) - 1 == gates * n_per_gate, len(unpacked)
        w00 = unpacked["fz_l0_i2h%s_weight"
                       % ("_i" if mode == "lstm" else "_r")]
        assert w00.shape == (6, I)
        repacked = cell.pack_weights(unpacked)
        np.testing.assert_allclose(repacked["fz_parameters"].asnumpy(),
                                   blob.asnumpy(), rtol=1e-6)
        assert "other" in repacked and len(repacked) == 2
