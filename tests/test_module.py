"""Module API tests.

Reference pattern: tests/python/unittest/test_module.py — bind/init/fit on
a small symbolic MLP, head-gradient correctness for the loss-output ops,
score/predict, checkpoint roundtrip through mx.model artifacts, Speedometer
and Monitor smoke.
"""
import logging
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io as mio, callback, monitor
from mxnet_tpu.module import Module

sym = mx.sym


def _mlp_softmax():
    data = sym.Variable("data")
    h = sym.FullyConnected(data, sym.Variable("fc1_weight"),
                           sym.Variable("fc1_bias"), num_hidden=32)
    h = sym.Activation(h, act_type="relu")
    out = sym.FullyConnected(h, sym.Variable("fc2_weight"),
                             sym.Variable("fc2_bias"), num_hidden=3)
    return sym.SoftmaxOutput(out, sym.Variable("softmax_label"),
                             normalization="batch", name="softmax")


def _toy_classification(n=240, dim=8, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, dim).astype(np.float32)
    Y = (X[:, :classes].argmax(axis=1)).astype(np.float32)
    return X, Y


def test_bind_shapes_and_params():
    mod = Module(_mlp_softmax(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 8))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(mx.init.Xavier())
    assert sorted(mod._param_names) == ["fc1_bias", "fc1_weight",
                                        "fc2_bias", "fc2_weight"]
    arg, aux = mod.get_params()
    assert arg["fc1_weight"].shape == (32, 8)
    assert aux == {}


def test_softmax_head_gradient_matches_formula():
    """backward through SoftmaxOutput must produce exactly (p - onehot)/N
    w.r.t. the logits, like src/operator/softmax_output.cc."""
    data = sym.Variable("data")
    out = sym.SoftmaxOutput(data, sym.Variable("softmax_label"),
                            normalization="null")
    mod = Module(out, context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 5))],
             label_shapes=[("softmax_label", (4,))], inputs_need_grad=True)
    mod.init_params()
    logits = np.random.RandomState(1).randn(4, 5).astype(np.float32)
    labels = np.array([0, 2, 4, 1], np.float32)
    batch = mio.DataBatch(data=[mx.nd.array(logits)],
                          label=[mx.nd.array(labels)])
    mod.forward(batch, is_train=True)
    p = mod.get_outputs()[0].asnumpy()
    np.testing.assert_allclose(
        p, np.exp(logits) / np.exp(logits).sum(1, keepdims=True), rtol=1e-5)
    mod.backward()
    g = mod.get_input_grads()[0]
    onehot = np.eye(5, dtype=np.float32)[labels.astype(int)]
    np.testing.assert_allclose(g.asnumpy(), p - onehot, rtol=1e-4, atol=1e-5)


def test_linear_regression_head_gradient():
    data = sym.Variable("data")
    out = sym.LinearRegressionOutput(data, sym.Variable("softmax_label"))
    mod = Module(out, context=mx.cpu())
    mod.bind(data_shapes=[("data", (6, 1))],
             label_shapes=[("softmax_label", (6, 1))], inputs_need_grad=True)
    mod.init_params()
    x = np.random.randn(6, 1).astype(np.float32)
    y = np.random.randn(6, 1).astype(np.float32)
    batch = mio.DataBatch(data=[mx.nd.array(x)], label=[mx.nd.array(y)])
    mod.forward(batch, is_train=True)
    mod.backward()
    np.testing.assert_allclose(mod.get_input_grads()[0].asnumpy(), x - y,
                               rtol=1e-5, atol=1e-6)


def test_module_fit_converges_and_scores():
    X, Y = _toy_classification()
    train = mio.NDArrayIter(X, Y, batch_size=24, shuffle=True)
    val = mio.NDArrayIter(X, Y, batch_size=24)
    mod = Module(_mlp_softmax(), context=mx.cpu())
    # lr sized for the reference gradient contract (per-example sums x
    # auto rescale_grad=1/batch in init_optimizer = mean gradients)
    mod.fit(train, eval_data=val, optimizer="sgd",
            optimizer_params={"learning_rate": 2.0},
            initializer=mx.init.Xavier(), num_epoch=12,
            batch_end_callback=callback.Speedometer(24, frequent=5))
    acc = mod.score(val, "acc")
    assert acc[0][1] > 0.9, acc
    preds = mod.predict(val)
    assert preds.shape == (240, 3)
    np.testing.assert_allclose(preds.asnumpy().sum(axis=1), 1.0, rtol=1e-4)


def test_module_checkpoint_roundtrip(tmp_path):
    X, Y = _toy_classification(n=48)
    train = mio.NDArrayIter(X, Y, batch_size=16)
    mod = Module(_mlp_softmax(), context=mx.cpu())
    mod.fit(train, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1}, num_epoch=2,
            epoch_end_callback=callback.do_checkpoint(
                str(tmp_path / "mlp")))
    assert os.path.isfile(str(tmp_path / "mlp-symbol.json"))
    assert os.path.isfile(str(tmp_path / "mlp-0002.params"))

    mod2 = Module.load(str(tmp_path / "mlp"), 2, context=mx.cpu())
    mod2.bind(data_shapes=[("data", (16, 8))],
              label_shapes=[("softmax_label", (16,))], for_training=False)
    train.reset()
    batch = next(train)
    mod.forward(batch, is_train=False)
    mod2.forward(batch, is_train=False)
    np.testing.assert_allclose(mod.get_outputs()[0].asnumpy(),
                               mod2.get_outputs()[0].asnumpy(), rtol=1e-5)


def test_set_get_params_and_save_checkpoint(tmp_path):
    mod = Module(_mlp_softmax(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 8))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer()
    arg, aux = mod.get_params()
    arg2 = {k: v * 0 for k, v in arg.items()}
    mod.set_params(arg2, aux)
    assert float(mod.get_params()[0]["fc1_weight"].asnumpy().sum()) == 0.0
    mod.save_checkpoint(str(tmp_path / "m"), 0)
    assert os.path.isfile(str(tmp_path / "m-0000.params"))


def test_monitor_smoke(caplog):
    X, Y = _toy_classification(n=24)
    train = mio.NDArrayIter(X, Y, batch_size=12)
    mod = Module(_mlp_softmax(), context=mx.cpu())
    mon = monitor.Monitor(interval=1, pattern=".*weight.*")
    with caplog.at_level(logging.INFO):
        mod.fit(train, optimizer="sgd", num_epoch=1, monitor=mon)
    msgs = [r.message for r in caplog.records if "fc1_weight" in r.message]
    assert msgs, "monitor produced no stats"


# -- review-finding regressions ----------------------------------------------

def test_init_params_allow_missing_semantics():
    mod = Module(_mlp_softmax(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 8))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(mx.init.Xavier())
    arg, aux = mod.get_params()
    partial = {"fc1_weight": arg["fc1_weight"]}
    with pytest.raises(mx.MXNetError):  # missing + allow_missing=False
        mod.init_params(arg_params=partial, force_init=True)
    # allow_missing=True initializes the absent ones (not left as-is)
    mod.set_params({k: v * 0 for k, v in arg.items()}, aux)
    mod.init_params(mx.init.One(), arg_params=partial,
                    allow_missing=True, force_init=True)
    assert float(mod.get_params()[0]["fc2_weight"].asnumpy().mean()) == 1.0


def test_saturated_logistic_gradient_not_zero():
    """Confidently-wrong saturated units must still get gradient (p - y)."""
    data = sym.Variable("data")
    out = sym.LogisticRegressionOutput(data, sym.Variable("softmax_label"))
    mod = Module(out, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 1))],
             label_shapes=[("softmax_label", (2, 1))], inputs_need_grad=True)
    mod.init_params()
    z = np.array([[30.0], [-30.0]], np.float32)   # sigmoid == exactly 1 / 0
    y = np.array([[0.0], [1.0]], np.float32)
    batch = mio.DataBatch(data=[mx.nd.array(z)], label=[mx.nd.array(y)])
    mod.forward(batch, is_train=True)
    mod.backward()
    g = mod.get_input_grads()[0].asnumpy()
    np.testing.assert_allclose(g, [[1.0], [-1.0]], atol=1e-6)


def test_module_load_restores_optimizer_states(tmp_path):
    X, Y = _toy_classification(n=48)
    train = mio.NDArrayIter(X, Y, batch_size=16)
    mod = Module(_mlp_softmax(), context=mx.cpu())
    mod.fit(train, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            num_epoch=2)
    mod.save_checkpoint(str(tmp_path / "m"), 2, save_optimizer_states=True)
    mod2 = Module.load(str(tmp_path / "m"), 2, load_optimizer_states=True)
    mod2.bind(data_shapes=train.provide_data,
              label_shapes=train.provide_label)
    mod2.init_optimizer(optimizer="sgd",
                        optimizer_params={"learning_rate": 0.1,
                                          "momentum": 0.9})
    s1 = mod._updater.states
    s2 = mod2._updater.states
    assert set(s1.keys()) == set(s2.keys()) and len(s1) > 0
    for k in s1:
        a, b = s1[k], s2[k]
        if isinstance(a, tuple):
            a, b = a[0], b[0]
        if a is None:
            assert b is None
        else:
            np.testing.assert_allclose(a.asnumpy(), b.asnumpy(), rtol=1e-6)


def test_infer_shape_clean_error_for_unknown_var():
    x = sym.Variable("x")
    w = sym.Variable("mystery")
    out = sym.broadcast_add(x, w)
    with pytest.raises(mx.MXNetError, match="mystery"):
        out.infer_shape(x=(2, 3))


def test_infer_shape_loss_label_rule():
    s = _mlp_softmax()
    args, outs, _ = s.infer_shape(data=(10, 8))  # no label shape given
    shapes = dict(zip(s.list_arguments(), args))
    assert shapes["softmax_label"] == (10,)
    assert outs == [(10, 3)]


def test_predict_without_labels_applies_transform():
    """Inference with a label-free iterator must still return probabilities."""
    mod = Module(_mlp_softmax(), context=mx.cpu())
    X, Y = _toy_classification(n=32)
    train = mio.NDArrayIter(X, Y, batch_size=8)
    mod.fit(train, optimizer="sgd", num_epoch=1)
    unlabeled = mio.NDArrayIter(X, batch_size=8)
    preds = mod.predict(unlabeled)
    np.testing.assert_allclose(preds.asnumpy().sum(axis=1), 1.0, rtol=1e-4)


def test_multi_head_labels_matched_by_name():
    """Each loss head must get ITS label, not the positional one."""
    data = sym.Variable("data")
    h1 = sym.LinearRegressionOutput(data, sym.Variable("lab_a"))
    h2 = sym.LinearRegressionOutput(data * 2.0, sym.Variable("lab_b"))
    group = sym.Group([h1, h2])
    # label_names deliberately in the OPPOSITE order of the heads
    mod = Module(group, label_names=("lab_b", "lab_a"), context=mx.cpu())
    mod.bind(data_shapes=[("data", (3, 2))],
             label_shapes=[("lab_b", (3, 2)), ("lab_a", (3, 2))],
             inputs_need_grad=True)
    mod.init_params()
    x = np.ones((3, 2), np.float32)
    la = np.zeros((3, 2), np.float32)         # head1 target
    lb = np.full((3, 2), 2.0, np.float32)     # head2 target (2x - 2 = 0)
    batch = mio.DataBatch(data=[mx.nd.array(x)],
                          label=[mx.nd.array(lb), mx.nd.array(la)])
    mod.forward(batch, is_train=True)
    mod.backward()
    # dL/dx = (x - la) + 2*(2x - lb) = 1 + 2*0 = 1 everywhere
    np.testing.assert_allclose(mod.get_input_grads()[0].asnumpy(), 1.0,
                               atol=1e-6)


def test_softmax_output_nd_with_ignore_label():
    data = sym.Variable("data")
    out = sym.SoftmaxOutput(data, sym.Variable("softmax_label"),
                            multi_output=True, use_ignore=True,
                            ignore_label=-1.0, normalization="valid")
    mod = Module(out, context=mx.cpu())
    B, C, T = 2, 4, 3
    mod.bind(data_shapes=[("data", (B, C, T))],
             label_shapes=[("softmax_label", (B, T))], inputs_need_grad=True)
    mod.init_params()
    z = np.random.RandomState(0).randn(B, C, T).astype(np.float32)
    y = np.array([[0, -1, 2], [-1, 3, 1]], np.float32)
    batch = mio.DataBatch(data=[mx.nd.array(z)], label=[mx.nd.array(y)])
    mod.forward(batch, is_train=True)
    p = mod.get_outputs()[0].asnumpy()
    np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-5)  # class axis 1
    mod.backward()
    g = mod.get_input_grads()[0].asnumpy()
    assert np.abs(g[0, :, 1]).sum() == 0     # ignored positions: zero grad
    assert np.abs(g[1, :, 0]).sum() == 0
    assert np.abs(g[0, :, 0]).sum() > 0


def test_set_params_rejects_extra():
    mod = Module(_mlp_softmax(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 8))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(mx.init.Xavier())
    arg, aux = mod.get_params()
    arg["bogus_weight"] = arg["fc1_weight"]
    with pytest.raises(mx.MXNetError, match="bogus_weight"):
        mod.set_params(arg, aux)
    mod.set_params(arg, aux, allow_extra=True)  # explicit opt-out works


def test_named_head_without_label_stays_inference():
    """A named loss head whose label is not fed must NOT steal another
    head's label positionally."""
    data = sym.Variable("data")
    h1 = sym.LinearRegressionOutput(data, sym.Variable("reg_label"))
    h2 = sym.SoftmaxOutput(data * 1.0, sym.Variable("softmax_label"))
    mod = Module(sym.Group([h1, h2]), label_names=("softmax_label",),
                 context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 3))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params()
    batch = mio.DataBatch(data=[mx.nd.array(np.ones((2, 3), np.float32))],
                          label=[mx.nd.array(np.array([0, 1], np.float32))])
    mod.forward(batch, is_train=True)
    # reg head got no label -> no cached grad; softmax head has one
    assert mod._head_grads[0] is None
    assert mod._head_grads[1] is not None


# ---------------------------------------------------------------------------
# BucketingModule (reference: python/mxnet/module/bucketing_module.py)
# ---------------------------------------------------------------------------


def _bucket_sym_gen(seq_len):
    """Variable-length mean-pool classifier: data (B, seq_len, 4) -> dense.
    Same parameter set for every bucket (the bucketing contract)."""
    data = sym.Variable("data")
    pooled = sym.mean(data, axis=1)            # (B, 4), length-independent
    out = sym.FullyConnected(pooled, sym.Variable("fc_weight"),
                             sym.Variable("fc_bias"), num_hidden=3)
    out = sym.SoftmaxOutput(out, sym.Variable("softmax_label"),
                            name="softmax")
    return out, ("data",), ("softmax_label",)


def _bucket_batch(seq_len, rng, bs=6):
    x = rng.randn(bs, seq_len, 4).astype(np.float32)
    y = rng.randint(0, 3, bs).astype(np.float32)
    return mio.DataBatch(
        data=[mx.nd.array(x)], label=[mx.nd.array(y)],
        bucket_key=seq_len,
        provide_data=[("data", (bs, seq_len, 4))],
        provide_label=[("softmax_label", (bs,))])


def test_bucketing_module_shares_weights_across_buckets():
    from mxnet_tpu.module import BucketingModule
    rng = np.random.RandomState(0)
    bm = BucketingModule(_bucket_sym_gen, default_bucket_key=10,
                         context=mx.cpu())
    bm.bind(data_shapes=[("data", (6, 10, 4))],
            label_shapes=[("softmax_label", (6,))])
    bm.init_params(mx.init.Xavier())
    bm.init_optimizer(optimizer="sgd",
                      optimizer_params=(("learning_rate", 0.5),))

    # drive three bucket lengths; every step must move the ONE shared weight
    w_prev = bm.get_params()[0]["fc_weight"].asnumpy().copy()
    for seq_len in (10, 5, 20, 5, 10):
        batch = _bucket_batch(seq_len, rng)
        bm.forward(batch, is_train=True)
        bm.backward()
        bm.update()
        w_now = bm.get_params()[0]["fc_weight"].asnumpy()
        assert not np.array_equal(w_now, w_prev), seq_len
        w_prev = w_now.copy()
    # one bound executor per DISTINCT bucket key, reused on revisits
    assert sorted(bm.buckets) == [5, 10, 20]
    # revisiting a bucket must NOT create a new module (the bucketed cache)
    mod_5 = bm.buckets[5]
    bm.forward(_bucket_batch(5, rng), is_train=True)
    assert bm.buckets[5] is mod_5
    # weight buffers are SHARED by identity, not copies
    master = bm.buckets[10]
    assert master._exec.arg_dict["fc_weight"] is \
        bm.buckets[5]._exec.arg_dict["fc_weight"]


def test_bucketing_module_trains_to_lower_loss():
    from mxnet_tpu.module import BucketingModule
    rng = np.random.RandomState(3)
    bm = BucketingModule(_bucket_sym_gen, default_bucket_key=8,
                         context=mx.cpu())
    bm.bind(data_shapes=[("data", (6, 8, 4))],
            label_shapes=[("softmax_label", (6,))])
    bm.init_params(mx.init.Xavier())
    bm.init_optimizer(optimizer="sgd",
                      optimizer_params=(("learning_rate", 0.3),))
    metric = mx.metric.create("acc")

    # learnable rule: class = argmax of mean-pooled first 3 dims
    def batch(seq_len):
        x = rng.randn(6, seq_len, 4).astype(np.float32)
        y = x.mean(axis=1)[:, :3].argmax(axis=1).astype(np.float32)
        return mio.DataBatch(
            data=[mx.nd.array(x)], label=[mx.nd.array(y)],
            bucket_key=seq_len,
            provide_data=[("data", (6, seq_len, 4))],
            provide_label=[("softmax_label", (6,))])

    for epoch in range(40):
        b = batch([4, 8, 12][epoch % 3])
        bm.forward(b, is_train=True)
        bm.backward()
        bm.update()
        if epoch >= 30:
            metric.update([b.label[0]], bm.get_outputs())
    assert metric.get()[1] > 0.6, metric.get()


def test_module_group2ctx_trains_across_devices():
    """Manual model parallelism through Module.bind(group2ctx=...): the two
    layer groups execute on different fake-mesh devices and a training
    loss with the SoftmaxOutput head still descends (the head rule aligns
    the label onto the head's device)."""
    with mx.AttrScope(ctx_group="a"):
        data = sym.Variable("data")
        h = sym.FullyConnected(data, sym.Variable("l1_weight"),
                               sym.Variable("l1_bias"), num_hidden=16,
                               name="l1")
        h = sym.Activation(h, act_type="relu")
    with mx.AttrScope(ctx_group="b"):
        o = sym.FullyConnected(h, sym.Variable("l2_weight"),
                               sym.Variable("l2_bias"), num_hidden=3,
                               name="l2")
        o = sym.SoftmaxOutput(o, sym.Variable("softmax_label"))
    mod = Module(o, context=mx.cpu(0))
    mod.bind(data_shapes=[("data", (8, 6))],
             label_shapes=[("softmax_label", (8,))],
             group2ctx={"a": mx.cpu(0), "b": mx.cpu(1)})
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.3),))
    rng = np.random.RandomState(0)
    x = rng.randn(8, 6).astype(np.float32)
    y = x[:, :3].argmax(axis=1).astype(np.float32)  # learnable rule
    metric = mx.metric.Accuracy()
    for epoch in range(30):
        batch = mio.DataBatch(data=[mx.nd.array(x)],
                              label=[mx.nd.array(y)])
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    mod.forward(mio.DataBatch(data=[mx.nd.array(x)],
                              label=[mx.nd.array(y)]), is_train=False)
    metric.update([mx.nd.array(y)], mod.get_outputs())
    assert metric.get()[1] > 0.8, metric.get()
    # the head really lives on device 1
    assert mod.get_outputs()[0].context == mx.cpu(1)


def test_module_shared_module_shares_buffers():
    """bind(shared_module=...) must share parameter buffers by identity
    (reference Module semantics): an update through one module is visible
    through the other."""
    def make_sym():
        d = sym.Variable("data")
        return sym.LinearRegressionOutput(
            sym.FullyConnected(d, sym.Variable("fc_weight"),
                               sym.Variable("fc_bias"), num_hidden=2,
                               name="fc"),
            sym.Variable("softmax_label"))
    master = Module(make_sym(), context=mx.cpu())
    master.bind(data_shapes=[("data", (4, 3))],
                label_shapes=[("softmax_label", (4, 2))])
    master.init_params(mx.init.Normal(1.0))
    child = Module(make_sym(), context=mx.cpu())
    child.bind(data_shapes=[("data", (2, 3))],
               label_shapes=[("softmax_label", (2, 2))],
               shared_module=master)
    assert child.params_initialized
    assert child._exec.arg_dict["fc_weight"] is \
        master._exec.arg_dict["fc_weight"]
    # mutate through master; child sees it
    master._exec.arg_dict["fc_weight"]._set_jax(
        master._exec.arg_dict["fc_weight"]._jax * 0 + 5.0)
    assert float(child._exec.arg_dict["fc_weight"].asnumpy()[0, 0]) == 5.0


def test_bucket_sentence_iter_with_bucketing_module():
    """The reference bucketing pipeline end-to-end: BucketSentenceIter bins
    variable-length sequences, BucketingModule routes each batch to its
    bucket's executables, training descends."""
    from mxnet_tpu.rnn import BucketSentenceIter
    from mxnet_tpu.module import BucketingModule
    rng = np.random.RandomState(0)
    V = 20
    sentences = []
    for _ in range(120):
        L = rng.choice([4, 7, 10])
        # learnable structure: next token = (token + 1) % V
        start = rng.randint(0, V)
        sentences.append([(start + i) % V for i in range(L)])
    it = BucketSentenceIter(sentences, batch_size=8, buckets=[4, 7, 10],
                            invalid_label=-1)
    assert it.default_bucket_key == 10
    seen_keys = {b.bucket_key for b in it}
    assert seen_keys == {4, 7, 10}
    it.reset()

    def sym_gen(seq_len):
        data = sym.Variable("data")
        emb = sym.Embedding(data, sym.Variable("emb_weight"), input_dim=V,
                            output_dim=16, name="emb")
        out = sym.FullyConnected(emb, sym.Variable("fc_weight"),
                                 sym.Variable("fc_bias"), num_hidden=V,
                                 flatten=False, name="fc")
        out = sym.SoftmaxOutput(out, sym.Variable("softmax_label"),
                                use_ignore=True, ignore_label=-1,
                                normalization="valid", name="softmax")
        return out, ("data",), ("softmax_label",)

    bm = BucketingModule(sym_gen, default_bucket_key=it.default_bucket_key,
                         context=mx.cpu())
    bm.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    bm.init_params(mx.init.Xavier())
    bm.init_optimizer(optimizer="adam",
                      optimizer_params=(("learning_rate", 0.05),))
    for epoch in range(6):
        it.reset()
        for batch in it:
            bm.forward(batch, is_train=True)
            bm.backward()
            bm.update()
    it.reset()
    correct = total = 0
    for batch in it:
        bm.forward(batch, is_train=False)
        # accuracy over non-padding positions only
        out = bm.get_outputs()[0].asnumpy().argmax(-1)
        y = batch.label[0].asnumpy()
        mask = y >= 0
        correct += int((out[mask] == y[mask]).sum())
        total += int(mask.sum())
    assert correct / total > 0.9, (correct, total)


def test_fast_path_matches_eager():
    """The whole-graph-jit step and the eager per-op tape must produce
    IDENTICAL parameters after several train steps (same init, same
    data) — the fast path is an execution strategy, not a semantics
    change."""
    import os as _os
    X, Y = _toy_classification()
    results = {}
    for mode in ("1", "0"):
        _os.environ["MX_MODULE_JIT"] = mode
        try:
            mx.random.seed(7)
            train = mio.NDArrayIter(X, Y, batch_size=24)
            mod = Module(_mlp_softmax(), context=mx.cpu())
            mod.bind(data_shapes=train.provide_data,
                     label_shapes=train.provide_label)
            mod.init_params(mx.init.Xavier(rnd_type="uniform",
                                           factor_type="avg", magnitude=2))
            mod.init_optimizer(optimizer="sgd",
                               optimizer_params={"learning_rate": 0.5,
                                                 "momentum": 0.9})
            for _ in range(2):
                train.reset()
                for batch in train:
                    mod.forward(batch, is_train=True)
                    mod.backward()
                    mod.update()
            results[mode] = {k: v.asnumpy()
                             for k, v in mod.get_params()[0].items()}
        finally:
            _os.environ.pop("MX_MODULE_JIT", None)
    for k in results["1"]:
        np.testing.assert_allclose(results["1"][k], results["0"][k],
                                   rtol=1e-4, atol=1e-5, err_msg=k)


def test_fast_path_batchnorm_aux_and_eval():
    """BatchNorm under the fused step: train updates moving stats, eval
    uses them (and leaves them alone), matching the eager path."""
    rng = np.random.RandomState(0)
    X = rng.randn(64, 3, 6, 6).astype(np.float32)
    Y = rng.randint(0, 2, 64)
    d = mx.sym.Variable("data")
    c = mx.sym.Convolution(data=d, num_filter=4, kernel=(3, 3),
                           name="c1")
    b = mx.sym.BatchNorm(data=c, name="bn1")
    f = mx.sym.FullyConnected(data=mx.sym.Flatten(b), num_hidden=2,
                              name="fc")
    net = mx.sym.SoftmaxOutput(data=f, name="softmax")
    train = mio.NDArrayIter(X, Y, batch_size=16)
    mod = Module(net, context=mx.cpu())
    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    mm0 = mod._exec.aux_dict["bn1_moving_mean"].asnumpy().copy()
    for batch in train:
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    mm1 = mod._exec.aux_dict["bn1_moving_mean"].asnumpy().copy()
    assert not np.allclose(mm0, mm1), "train must update moving stats"
    train.reset()
    for batch in train:
        mod.forward(batch, is_train=False)
    mm2 = mod._exec.aux_dict["bn1_moving_mean"].asnumpy()
    np.testing.assert_allclose(mm1, mm2, err_msg="eval must not touch")


def test_symbolic_resnet_trains_through_fused_step():
    """Deep classic symbol (conv/BN/residual adds, 46 args + 26 aux
    states, all auto-created) converges through the whole-graph-jitted
    Module step — the fused path's BN writebacks and residual graph
    structure at realistic depth."""

    def unit(data, nf, stride, dim_match, name):
        bn1 = mx.sym.BatchNorm(data=data, fix_gamma=False,
                               name=name + "_bn1")
        act1 = mx.sym.Activation(data=bn1, act_type="relu")
        conv1 = mx.sym.Convolution(data=act1, num_filter=nf,
                                   kernel=(3, 3), stride=stride,
                                   pad=(1, 1), no_bias=True,
                                   name=name + "_conv1")
        bn2 = mx.sym.BatchNorm(data=conv1, fix_gamma=False,
                               name=name + "_bn2")
        act2 = mx.sym.Activation(data=bn2, act_type="relu")
        conv2 = mx.sym.Convolution(data=act2, num_filter=nf,
                                   kernel=(3, 3), pad=(1, 1),
                                   no_bias=True, name=name + "_conv2")
        short = data if dim_match else mx.sym.Convolution(
            data=act1, num_filter=nf, kernel=(1, 1), stride=stride,
            no_bias=True, name=name + "_sc")
        return conv2 + short

    data = mx.sym.Variable("data")
    body = mx.sym.Convolution(data=data, num_filter=8, kernel=(3, 3),
                              pad=(1, 1), no_bias=True, name="conv0")
    for i, (nf, s) in enumerate([(8, (1, 1)), (16, (2, 2))]):
        body = unit(body, nf, s, False, "s%d_u1" % i)
        body = unit(body, nf, (1, 1), True, "s%d_u2" % i)
    bn = mx.sym.BatchNorm(data=body, fix_gamma=False, name="bn_final")
    act = mx.sym.Activation(data=bn, act_type="relu")
    pool = mx.sym.Pooling(data=act, global_pool=True, pool_type="avg",
                          kernel=(1, 1))
    net = mx.sym.SoftmaxOutput(
        data=mx.sym.FullyConnected(data=mx.sym.Flatten(pool),
                                   num_hidden=5, name="fc"),
        name="softmax")

    rng = np.random.RandomState(0)
    X = rng.randn(48, 3, 12, 12).astype(np.float32)
    Y = rng.randint(0, 5, 48).astype(np.float32)
    it = mio.NDArrayIter(X, Y, batch_size=16, shuffle=True)
    mod = Module(net, context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5,
                                         "momentum": 0.9})
    metric = mx.metric.CrossEntropy()
    losses = []
    for _ in range(5):
        it.reset()
        metric.reset()
        for batch in it:
            mod.forward(batch, is_train=True)
            mod.update_metric(metric, batch.label)
            mod.backward()
            mod.update()
        losses.append(metric.get()[1])
    assert mod._jit_ok is True, "fused path must engage"
    assert losses[-1] < losses[0] * 0.8, losses


def test_bucketing_module_checkpoint_roundtrip(tmp_path):
    """Reference: BucketingModule.save_checkpoint/load — default-bucket
    symbol + shared params round-trip."""
    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        fc = mx.sym.FullyConnected(data=mx.sym.Flatten(data),
                                   num_hidden=4, name="fc")
        return (mx.sym.SoftmaxOutput(data=fc, name="softmax"),
                ("data",), ("softmax_label",))

    from mxnet_tpu.module import BucketingModule as BM
    bm = BM(sym_gen, default_bucket_key=6, context=mx.cpu())
    bm.bind(data_shapes=[("data", (4, 6))],
            label_shapes=[("softmax_label", (4,))])
    bm.init_params(mx.init.Xavier())
    prefix = str(tmp_path / "bm")
    bm.save_checkpoint(prefix, 3)
    bm2 = BM.load(prefix, 3, sym_gen, default_bucket_key=6,
                  context=mx.cpu())
    bm2.bind(data_shapes=[("data", (4, 6))],
             label_shapes=[("softmax_label", (4,))])
    bm2.init_params()
    np.testing.assert_allclose(
        bm.get_params()[0]["fc_weight"].asnumpy(),
        bm2.get_params()[0]["fc_weight"].asnumpy())


def test_fused_path_grad_req_add():
    """grad_req='add' accumulates across backward calls on the fused
    whole-graph path, like the eager executor."""
    data = mx.sym.Variable("data")
    net = mx.sym.SoftmaxOutput(
        data=mx.sym.FullyConnected(data=data, num_hidden=3, name="fc"),
        name="softmax")
    X = np.random.RandomState(0).randn(8, 4).astype(np.float32)
    Y = np.random.RandomState(1).randint(0, 3, 8).astype(np.float32)
    it = mio.NDArrayIter(X, Y, batch_size=8)
    mod = Module(net, context=mx.cpu())
    mod.bind(data_shapes=it.provide_data,
             label_shapes=it.provide_label, grad_req="add")
    mod.init_params(mx.init.Xavier())
    batch = next(iter(it))
    mod.forward(batch, is_train=True)
    mod.backward()
    g1 = mod._exec.grad_dict["fc_weight"].asnumpy().copy()
    mod.forward(batch, is_train=True)
    mod.backward()
    np.testing.assert_allclose(mod._exec.grad_dict["fc_weight"].asnumpy(),
                               2 * g1, rtol=1e-5)
    assert mod._jit_ok is True


def test_multi_head_label_name_matching():
    """NDArrayIter sorts dict-fed label names; Module must match batch
    labels to its label_names by NAME (reference DataParallelExecutorGroup
    semantics), or a two-head fit silently trains each head on the other
    head's label and never converges."""
    rng = np.random.RandomState(0)
    X = rng.randn(192, 8).astype(np.float32)
    W = rng.randn(8, 3).astype(np.float32)
    Y = (X @ W).argmax(1).astype(np.float32)
    Yr = X @ rng.randn(8, 1).astype(np.float32)
    d = mx.sym.Variable("data")
    h1 = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(d, num_hidden=3, name="fc"), name="softmax")
    h2 = mx.sym.LinearRegressionOutput(
        mx.sym.FullyConnected(d, num_hidden=1, name="fc2"), name="lro")
    # module order (softmax_label, lro_label) != iterator's sorted order
    mod = Module(mx.sym.Group([h1, h2]), data_names=("data",),
                 label_names=("softmax_label", "lro_label"),
                 context=mx.cpu())
    it = mio.NDArrayIter({"data": X},
                         {"softmax_label": Y, "lro_label": Yr},
                         batch_size=32)
    assert [d_.name for d_ in it.provide_label][0] == "lro_label"
    mod.fit(it, num_epoch=10, optimizer="sgd",
            optimizer_params={"learning_rate": 0.3}, eval_metric="loss")
    it.reset()
    preds = mod.predict(it)
    acc = float((preds[0].asnumpy().argmax(1) == Y).mean())
    assert acc > 0.85, acc
