"""Loss tests vs numpy references (reference: tests/python/unittest/
test_loss.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import loss as gloss


def test_l2_loss():
    pred = mx.nd.array(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
    label = mx.nd.array(np.array([[1.5, 1.5], [3.0, 5.0]], np.float32))
    l = gloss.L2Loss()(pred, label).asnumpy()
    expect = 0.5 * ((np.array([[1, 2], [3, 4.]]) -
                     np.array([[1.5, 1.5], [3, 5.]])) ** 2).mean(axis=1)
    assert np.allclose(l, expect, atol=1e-6)


def test_l1_loss():
    pred = mx.nd.array([[1.0, -2.0]])
    label = mx.nd.array([[0.0, 0.0]])
    l = gloss.L1Loss()(pred, label).asnumpy()
    assert np.allclose(l, [1.5])


def test_softmax_ce_sparse_vs_dense():
    np.random.seed(0)
    logits = np.random.randn(6, 4).astype(np.float32)
    labels = np.random.randint(0, 4, 6)
    onehot = np.eye(4, dtype=np.float32)[labels]
    l_sparse = gloss.SoftmaxCrossEntropyLoss()(
        mx.nd.array(logits), mx.nd.array(labels)).asnumpy()
    l_dense = gloss.SoftmaxCrossEntropyLoss(sparse_label=False)(
        mx.nd.array(logits), mx.nd.array(onehot)).asnumpy()
    logp = logits - logits.max(-1, keepdims=True)
    logp = logp - np.log(np.exp(logp).sum(-1, keepdims=True))
    expect = -logp[np.arange(6), labels]
    assert np.allclose(l_sparse, expect, atol=1e-5)
    assert np.allclose(l_dense, expect, atol=1e-5)


def test_sigmoid_bce():
    np.random.seed(0)
    pred = np.random.randn(4, 3).astype(np.float32)
    label = (np.random.rand(4, 3) > 0.5).astype(np.float32)
    l = gloss.SigmoidBCELoss()(mx.nd.array(pred),
                               mx.nd.array(label)).asnumpy()
    p = 1 / (1 + np.exp(-pred))
    expect = -(label * np.log(p) + (1 - label) * np.log(1 - p)).mean(axis=1)
    assert np.allclose(l, expect, atol=1e-5)


def test_kl_div():
    np.random.seed(0)
    logits = np.random.randn(3, 5).astype(np.float32)
    target = np.random.rand(3, 5).astype(np.float32)
    target /= target.sum(-1, keepdims=True)
    logp = logits - logits.max(-1, keepdims=True)
    logp = (logp - np.log(np.exp(logp).sum(-1, keepdims=True)))
    l = gloss.KLDivLoss(from_logits=False)(
        mx.nd.array(logits), mx.nd.array(target)).asnumpy()
    expect = (target * (np.log(target + 1e-12) - logp)).mean(axis=-1)
    assert np.allclose(l, expect, atol=1e-5)


def test_huber_loss():
    pred = mx.nd.array([0.0, 2.0])
    label = mx.nd.array([0.5, 0.0])
    l = gloss.HuberLoss(rho=1.0)(pred, label).asnumpy()
    # |err|=0.5 -> 0.5*0.25 ; |err|=2 -> 2-0.5
    assert np.allclose(l, [0.125, 1.5], atol=1e-6)


def test_hinge_loss():
    pred = mx.nd.array([[0.3], [-2.0]])
    label = mx.nd.array([[1], [-1]])
    l = gloss.HingeLoss()(pred, label).asnumpy()
    assert np.allclose(l, [0.7, 0.0], atol=1e-6)


def test_loss_backward_flows():
    net_pred = mx.nd.array(np.random.randn(4, 3).astype(np.float32))
    net_pred.attach_grad()
    label = mx.nd.array([0, 1, 2, 0])
    with autograd.record():
        l = gloss.SoftmaxCrossEntropyLoss()(net_pred, label).sum()
    l.backward()
    g = net_pred.grad.asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0
    # softmax CE grad = p - onehot
    p = np.exp(net_pred.asnumpy())
    p /= p.sum(-1, keepdims=True)
    onehot = np.eye(3, dtype=np.float32)[[0, 1, 2, 0]]
    assert np.allclose(g, (p - onehot), atol=1e-5)


def test_ctc_loss_simple():
    """CTC over a trivial 1-label problem matches hand computation."""
    T, N, C = 3, 1, 3
    # logits heavily favor label 1 at every step
    logits = np.full((T, N, C), -5.0, np.float32)
    logits[:, 0, 1] = 5.0
    label = np.array([[1]], np.int32)
    l = gloss.CTCLoss(layout="TNC")(mx.nd.array(logits),
                                    mx.nd.array(label)).asnumpy()
    assert l.shape == (1,)
    assert np.isfinite(l).all()
    # near-perfect prediction → small loss
    assert l[0] < 1.0


def test_ctc_loss_grad():
    np.random.seed(0)
    logits = mx.nd.array(np.random.randn(5, 2, 4).astype(np.float32))
    logits.attach_grad()
    label = mx.nd.array(np.array([[1, 2], [3, 0]], np.int32))
    with autograd.record():
        l = gloss.CTCLoss(layout="TNC")(logits, label).sum()
    l.backward()
    assert np.isfinite(logits.grad.asnumpy()).all()


def test_triplet_loss():
    a = mx.nd.array(np.zeros((2, 3), np.float32))
    p = mx.nd.array(np.zeros((2, 3), np.float32))
    n = mx.nd.array(np.ones((2, 3), np.float32))
    l = gloss.TripletLoss(margin=1.0)(a, p, n).asnumpy()
    # d(a,p)=0, d(a,n)=3 -> max(0, 0-3+1)=0
    assert np.allclose(l, 0.0)
    l2 = gloss.TripletLoss(margin=5.0)(a, p, n).asnumpy()
    assert np.allclose(l2, 2.0)


def test_metrics_accuracy():
    from mxnet_tpu import metric
    acc = metric.Accuracy()
    pred = mx.nd.array([[0.9, 0.1], [0.2, 0.8], [0.7, 0.3]])
    label = mx.nd.array([0, 1, 1])
    acc.update([label], [pred])
    name, value = acc.get()
    assert name == "accuracy"
    assert abs(value - 2.0 / 3) < 1e-6


def test_metrics_composite_and_create():
    from mxnet_tpu import metric
    comp = metric.create(["accuracy", "mse"])
    assert isinstance(comp, metric.CompositeEvalMetric)
    topk = metric.create("top_k_accuracy", top_k=3)
    assert isinstance(topk, metric.TopKAccuracy)


def test_metric_perplexity():
    from mxnet_tpu import metric
    ppl = metric.Perplexity(ignore_label=None)
    pred = mx.nd.array([[0.5, 0.5], [0.9, 0.1]])
    label = mx.nd.array([0, 0])
    ppl.update([label], [pred])
    _, value = ppl.get()
    expect = np.exp(-(np.log(0.5) + np.log(0.9)) / 2)
    assert abs(value - expect) < 1e-5


def test_ctc_blank_last_matches_first():
    """blank_label='last' must equal 'first' under the channel remap."""
    np.random.seed(1)
    T, N, C = 6, 2, 5
    logits_first = np.random.randn(T, N, C).astype(np.float32)
    labels_first = np.array([[1, 2, 0], [3, 1, 4]], np.int32)  # 0-padded
    l_first = mx.nd.ctc_loss(mx.nd.array(logits_first),
                             mx.nd.array(labels_first)).asnumpy()
    # same problem expressed in 'last' layout: blank channel moved to end,
    # labels shifted down by 1, padding -1
    logits_last = np.concatenate([logits_first[..., 1:],
                                  logits_first[..., :1]], axis=-1)
    labels_last = np.where(labels_first > 0, labels_first - 1, -1)
    l_last = mx.nd.ctc_loss(mx.nd.array(logits_last),
                            mx.nd.array(labels_last),
                            blank_label="last").asnumpy()
    assert np.allclose(l_first, l_last, atol=1e-4)


def test_sdml_loss():
    """SDMLLoss (reference gluon.loss.SDMLLoss): matched pairs on the
    diagonal minimize the smoothed-retrieval KL; shuffled pairs score
    worse, and training on it aligns two towers."""
    import numpy as onp
    rng = onp.random.RandomState(0)
    x = nd.array(rng.randn(8, 16).astype(onp.float32))
    loss_fn = gluon.loss.SDMLLoss(smoothing_parameter=0.3)
    aligned = float(loss_fn(x, x).mean().asnumpy().item())
    perm = nd.array(x.asnumpy()[::-1].copy())
    shuffled = float(loss_fn(x, perm).mean().asnumpy().item())
    assert aligned < shuffled, (aligned, shuffled)
    # descends when training a projection to align two views
    net = gluon.nn.Dense(16)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-2})
    x1 = nd.array(rng.randn(16, 32).astype(onp.float32))
    x2 = x1 + 0.1 * nd.array(rng.randn(16, 32).astype(onp.float32))
    losses = []
    for _ in range(25):
        with autograd.record():
            L = loss_fn(net(x1), net(x2)).mean()
        L.backward()
        trainer.step(16)
        losses.append(float(L.asnumpy().item()))
    assert losses[-1] < losses[0], losses
