"""Contrib semantics: control flow (foreach/while_loop/cond) and the
detection op family.

Reference: tests/python/unittest/test_contrib_control_flow.py,
tests/python/unittest/test_contrib_operator.py (box_nms/MultiBox tests).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def test_foreach_cumsum():
    def body(x, state):
        new = state + x
        return new, new

    data = nd.array(np.arange(6, dtype=np.float32).reshape(3, 2))
    out, final = mx.contrib.foreach(body, data, nd.zeros((2,)))
    np.testing.assert_allclose(out.asnumpy(),
                               np.cumsum(data.asnumpy(), axis=0))
    np.testing.assert_allclose(final.asnumpy(), [6.0, 9.0])


def test_foreach_multiple_states():
    def body(x, states):
        s0, s1 = states
        return x + s0, [s0 + x, s1 * 2]

    data = nd.array(np.ones((4, 2), np.float32))
    out, states = mx.contrib.foreach(body, data,
                                     [nd.zeros((2,)), nd.ones((2,))])
    assert out.shape == (4, 2)
    np.testing.assert_allclose(states[0].asnumpy(), [4.0, 4.0])
    np.testing.assert_allclose(states[1].asnumpy(), [16.0, 16.0])


def test_foreach_grad():
    def body(x, state):
        new = state + x
        return new, new

    x = nd.array(np.ones((3, 2), np.float32))
    x.attach_grad()
    with autograd.record():
        _, final = mx.contrib.foreach(body, x, nd.zeros((2,)))
        loss = (final * final).sum()
    loss.backward()
    # final = sum_t x_t; d(final^2)/dx_t = 2*final = 6
    np.testing.assert_allclose(x.grad.asnumpy(), 6 * np.ones((3, 2)))


def test_while_loop():
    _, fin = mx.contrib.while_loop(
        lambda v: v[0] < 100, lambda v: [v[0] * 2],
        [nd.array([3.0])], max_iterations=10)
    np.testing.assert_allclose(fin[0].asnumpy(), [192.0])
    # bound shorter than convergence: stops at max_iterations
    _, fin = mx.contrib.while_loop(
        lambda v: v[0] < 100, lambda v: [v[0] * 2],
        [nd.array([3.0])], max_iterations=2)
    np.testing.assert_allclose(fin[0].asnumpy(), [12.0])


def test_while_loop_requires_bound():
    with pytest.raises(ValueError):
        mx.contrib.while_loop(lambda v: v[0] < 1, lambda v: [v[0]],
                              [nd.array([0.0])])


def test_cond():
    r = mx.contrib.cond(lambda v: v[0].sum() > 0,
                        lambda v: v[0] * 2, lambda v: v[0] - 1,
                        [nd.array([1.0, 2.0])])
    np.testing.assert_allclose(r.asnumpy(), [2.0, 4.0])
    r = mx.contrib.cond(lambda v: v[0].sum() > 100,
                        lambda v: v[0] * 2, lambda v: v[0] - 1,
                        [nd.array([1.0, 2.0])])
    np.testing.assert_allclose(r.asnumpy(), [0.0, 1.0])


def test_box_iou():
    a = nd.array([[0.0, 0.0, 2.0, 2.0]])
    b = nd.array([[1.0, 1.0, 3.0, 3.0], [4.0, 4.0, 5.0, 5.0]])
    iou = mx.contrib.nd.box_iou(a, b).asnumpy()
    np.testing.assert_allclose(iou, [[1.0 / 7.0, 0.0]], rtol=1e-5)


def test_box_nms_suppression():
    boxes = nd.array([[[0, 0.9, 0.0, 0.0, 1.0, 1.0],
                       [0, 0.8, 0.05, 0.05, 1.0, 1.0],
                       [1, 0.7, 0.5, 0.5, 0.9, 0.9],
                       [0, -1.0, 0.0, 0.0, 0.1, 0.1]]])
    out = mx.contrib.nd.box_nms(boxes, overlap_thresh=0.5).asnumpy()
    assert out[0, 0, 1] == pytest.approx(0.9)     # top box kept
    assert (out[0, 1] == -1).all()                # same-class overlap gone
    assert out[0, 2, 0] == 1                      # other class kept
    assert (out[0, 3] == -1).all()                # invalid score stays out
    # force_suppress ignores class ids
    out2 = mx.contrib.nd.box_nms(boxes, overlap_thresh=0.1,
                                 force_suppress=True).asnumpy()
    assert (out2[0, 2] == -1).all()


def test_box_nms_topk():
    boxes = nd.array([[[0.9, 0.0, 0.0, 0.2, 0.2],
                       [0.8, 0.4, 0.4, 0.6, 0.6],
                       [0.7, 0.8, 0.8, 1.0, 1.0]]])
    out = mx.contrib.nd.box_nms(boxes, overlap_thresh=0.5, topk=2,
                                coord_start=1, score_index=0,
                                id_index=-1).asnumpy()
    kept = (out[0, :, 0] > 0).sum()
    assert kept == 2


def test_multibox_prior_values():
    feat = nd.zeros((1, 4, 2, 2))
    anchors = mx.contrib.nd.MultiBoxPrior(feat, sizes=(0.5,),
                                          ratios=(1.0,)).asnumpy()
    assert anchors.shape == (1, 4, 4)
    # first anchor centered at (0.25, 0.25) with size 0.5
    np.testing.assert_allclose(anchors[0, 0], [0.0, 0.0, 0.5, 0.5],
                               atol=1e-6)


def test_multibox_target_matching():
    feat = nd.zeros((1, 4, 3, 3))
    anchors = mx.contrib.nd.MultiBoxPrior(feat, sizes=(0.4,), ratios=(1.0,))
    # one gt box near the center anchor; one padding row
    label = nd.array([[[1, 0.3, 0.3, 0.7, 0.7], [-1, 0, 0, 0, 0]]])
    cls_pred = nd.zeros((1, 3, 9))
    loc_t, loc_m, cls_t = mx.contrib.nd.MultiBoxTarget(anchors, label,
                                                       cls_pred)
    ct = cls_t.asnumpy()[0]
    assert (ct == 2).sum() >= 1          # class 1 → target 2 (bg=0)
    assert (ct == 0).sum() > 0           # background anchors exist
    lm = loc_m.asnumpy().reshape(9, 4)
    assert (lm.sum(axis=1) > 0).sum() == (ct > 0).sum()


def test_multibox_detection_decodes():
    feat = nd.zeros((1, 4, 2, 2))
    anchors = mx.contrib.nd.MultiBoxPrior(feat, sizes=(0.5,), ratios=(1.0,))
    N = anchors.shape[1]
    cls_prob = nd.array(np.tile([[0.1], [0.8], [0.1]], (1, 1, N)))
    loc_pred = nd.zeros((1, N * 4))
    det = mx.contrib.nd.MultiBoxDetection(cls_prob, loc_pred, anchors,
                                          nms_threshold=0.9).asnumpy()
    assert det.shape == (1, N, 6)
    top = det[0, det[0, :, 1].argmax()]
    assert top[0] == 0                  # class 0 (first fg class)
    assert top[1] == pytest.approx(0.8, abs=1e-5)
    # decoded box equals anchor when loc_pred == 0
    np.testing.assert_allclose(top[2:], anchors.asnumpy()[0, 0], atol=1e-5)


def test_foreach_matches_python_loop():
    """Property check vs an imperative python loop (reference pattern)."""
    W = nd.random.normal(shape=(4, 4))

    def body(x, h):
        new_h = nd.tanh(nd.dot(x, W) + h)
        return new_h, new_h

    data = nd.random.normal(shape=(5, 2, 4))
    out, final = mx.contrib.foreach(body, data, nd.zeros((2, 4)))
    h = nd.zeros((2, 4))
    for t in range(5):
        h = nd.tanh(nd.dot(data[t], W) + h)
    np.testing.assert_allclose(final.asnumpy(), h.asnumpy(), rtol=1e-5,
                               atol=1e-5)


def test_nd_contrib_namespace_parity():
    """mx.nd.contrib mirrors mx.contrib.nd (reference exposes both)."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    a = mx.nd.contrib.arange_like(nd.zeros((2, 5)), axis=1)
    assert a.asnumpy().tolist() == [0.0, 1.0, 2.0, 3.0, 4.0]
    for name in ("box_nms", "box_iou", "quadratic", "edge_id",
                 "sldwin_atten_score", "box_encode", "ROIAlign",
                 "MultiBoxPrior"):
        assert hasattr(mx.nd.contrib, name), name
    from mxnet_tpu.contrib import ndarray as contrib_nd
    assert mx.nd.contrib is contrib_nd
    import importlib
    mod = importlib.import_module("mxnet_tpu.ndarray.contrib")
    assert mod is contrib_nd
