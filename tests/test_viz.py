"""mx.viz (print_summary / plot_network) + the opperf harness.

Reference: python/mxnet/visualization.py, benchmark/opperf/opperf.py.
"""
import json
import os
import subprocess
import sys

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mlp_symbol():
    data = sym.Variable("data")
    h = sym.FullyConnected(data, sym.Variable("fc1_weight"),
                           sym.Variable("fc1_bias"), num_hidden=8,
                           name="fc1")
    h = sym.Activation(h, act_type="relu", name="relu1")
    out = sym.FullyConnected(h, sym.Variable("fc2_weight"),
                             sym.Variable("fc2_bias"), num_hidden=3,
                             name="fc2")
    return sym.SoftmaxOutput(out, sym.Variable("softmax_label"),
                             name="softmax")


def test_print_summary_counts_params(capsys):
    table = mx.viz.print_summary(_mlp_symbol(), shape={"data": (2, 4)})
    assert "fc1 (FullyConnected)" in table
    assert "fc2 (FullyConnected)" in table
    # fc1: 4*8+8 = 40; fc2: 8*3+3 = 27
    assert "Total params: 67" in table
    assert "67" in capsys.readouterr().out


def test_plot_network_dot_source(tmp_path):
    dot = mx.viz.plot_network(_mlp_symbol(), title="mlp")
    # the genuine graphviz package emits unquoted ids; the shim quotes —
    # normalize before asserting
    src = dot.source.replace('"', "")
    assert "digraph" in src
    assert "fc1 -> relu1" in src and "relu1 -> fc2" in src
    # weights hidden by default
    assert "fc1_weight" not in src
    full = mx.viz.plot_network(_mlp_symbol(), hide_weights=False)
    assert "fc1_weight" in full.source.replace('"', "")
    try:
        path = dot.render(str(tmp_path / "mlp"))
    except Exception:
        path = None  # graphviz package without the dot BINARY: fine
    if path:
        assert os.path.exists(path)


def test_opperf_harness_runs_subset():
    env = dict(os.environ, MX_FORCE_CPU="1", JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "opperf.py"),
         "--ops", "relu,softmax,_plus_scalar", "--runs", "5"],
        capture_output=True, text=True, timeout=300, env=env)
    assert r.returncode == 0, r.stderr
    summary = json.loads(r.stdout.strip().splitlines()[-1])
    assert summary["num_ops"] == 3
    assert summary["num_errors"] == 0
    assert summary["median_eager_us"] > 0
    assert summary["median_dispatch_overhead_us"] is not None


def test_tpu_lane_skips_cleanly_when_unreachable(tmp_path):
    """MX_TEST_CTX=tpu with a wedged/absent tunnel must SKIP, not hang:
    run one fast test file under the lane and require only skips."""
    env = dict(os.environ, MX_TEST_CTX="tpu")
    env.pop("MX_FORCE_CPU", None)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    # a wedged tunnel burns the FULL probe budget before skipping; 10s
    # proves the same skip path without 2 minutes of tier-1 wall time
    env["MX_TPU_PROBE_TIMEOUT"] = "10"
    r = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/test_viz.py::"
         "test_print_summary_counts_params", "-q", "--no-header"],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    out = r.stdout
    assert ("1 skipped" in out) or ("1 passed" in out), (out, r.stderr)
