"""mx.amp tests.

Reference pattern: tests/python/unittest/test_amp.py / test_contrib_amp.py —
list-driven casting, loss scaling semantics, converted-model dtype checks.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import amp, autograd, gluon
from mxnet_tpu.gluon import nn


@pytest.fixture(autouse=True)
def _amp_off_after():
    yield
    amp.turn_off()


def test_target_op_casts_down():
    amp.init()
    a = mx.nd.ones((4, 8))            # fp32
    b = mx.nd.ones((8, 2))
    out = mx.nd.dot(a, b)
    assert out.dtype == np.dtype("bfloat16").newbyteorder("=") or \
        str(out.dtype) == "bfloat16"
    np.testing.assert_allclose(out.asnumpy().astype(np.float32), 8.0)


def test_fp32_op_casts_up():
    amp.init()
    x = mx.nd.ones((2, 3), dtype="bfloat16")
    out = mx.nd.softmax(x)
    assert str(out.dtype) == "float32"


def test_widest_cast():
    amp.init()
    a = mx.nd.ones((4,), dtype="bfloat16")
    b = mx.nd.ones((4,), dtype="float32")
    out = a + b
    assert str(out.dtype) == "float32"


def test_conditional_fp32():
    amp.init()
    x = mx.nd.ones((4,), dtype="bfloat16")
    soft = mx.nd.Activation(x, act_type="softrelu")
    assert str(soft.dtype) == "float32"
    rel = mx.nd.Activation(x, act_type="relu")
    assert str(rel.dtype) == "bfloat16"


def test_off_by_default_and_turn_off():
    a = mx.nd.ones((2, 2))
    assert str(mx.nd.dot(a, a).dtype) == "float32"
    amp.init()
    assert str(mx.nd.dot(a, a).dtype) == "bfloat16"
    amp.turn_off()
    assert str(mx.nd.dot(a, a).dtype) == "float32"


def test_grads_flow_through_amp_casts():
    amp.init()
    w = mx.nd.array(np.random.randn(8, 2).astype(np.float32))
    w.attach_grad()
    x = mx.nd.array(np.random.randn(4, 8).astype(np.float32))
    with autograd.record():
        y = mx.nd.dot(x, w)
        loss = (y * y).mean()
    loss.backward()
    g = w.grad.asnumpy()
    assert g.dtype == np.float32          # master grad stays wide
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def _toy_trainer(dtype="float16"):
    net = nn.Dense(1, in_units=4)
    net.initialize()
    if dtype:
        net.cast(dtype)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1,
                             "multi_precision": dtype == "float16"})
    return net, trainer


def test_scale_loss_and_dynamic_scaler():
    amp.init(target_dtype="float16")
    net, trainer = _toy_trainer("float16")
    amp.init_trainer(trainer)
    scaler = trainer._amp_loss_scaler
    assert scaler.loss_scale > 1.0
    scaler.loss_scale = 1024.0  # keep loss*scale inside fp16 range
    s0 = scaler.loss_scale
    x = mx.nd.ones((2, 4), dtype="float16")
    y = mx.nd.ones((2, 1), dtype="float16")
    with autograd.record():
        out = net(x)
        loss = ((out - y) ** 2).mean()
        with amp.scale_loss(loss, trainer) as scaled:
            pass
    scaled.backward()
    # backward saw the scaled loss; trainer divides by the scale on update
    assert trainer._scale == pytest.approx(1.0 / s0)
    w_before = net.weight.data().asnumpy().copy()
    trainer.step(2)
    assert not np.allclose(net.weight.data().asnumpy(), w_before)


def test_overflow_skips_update_and_backs_off():
    amp.init(target_dtype="float16")
    net, trainer = _toy_trainer("float16")
    amp.init_trainer(trainer)
    scaler = trainer._amp_loss_scaler
    x = mx.nd.ones((2, 4), dtype="float16")
    with autograd.record():
        loss = net(x).mean()
    loss.backward()
    # poison the gradient
    net.weight.grad()[:] = mx.nd.full(net.weight.grad().shape, np.inf,
                                      dtype="float16")
    w_before = net.weight.data().asnumpy().copy()
    s0 = scaler.loss_scale
    trainer.step(2)
    np.testing.assert_array_equal(net.weight.data().asnumpy(), w_before)
    assert scaler.loss_scale == s0 / 2


def test_bf16_amp_training_converges():
    amp.init()  # bfloat16
    np.random.seed(0)
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(2))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    amp.init_trainer(trainer)
    sce = gluon.loss.SoftmaxCrossEntropyLoss()
    X = np.random.randn(128, 8).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.int32)
    losses = []
    for _ in range(30):
        x, y = mx.nd.array(X), mx.nd.array(Y)
        with autograd.record():
            loss = sce(net(x), y)
            with amp.scale_loss(loss, trainer) as scaled:
                pass
        scaled.backward()
        trainer.step(128)
        losses.append(float(loss.mean().asnumpy()))
    assert losses[-1] < 0.3 < losses[0]


def test_convert_hybrid_block_keeps_norms_fp32():
    net = nn.HybridSequential()
    net.add(nn.Dense(8), nn.BatchNorm(), nn.Dense(2))
    net.initialize()
    net(mx.nd.ones((2, 4)))
    amp.convert_hybrid_block(net, "bfloat16")
    assert str(net[0].weight.dtype) == "bfloat16"
    assert str(net[1].gamma.dtype) == "float32"
    assert str(net[2].weight.dtype) == "bfloat16"
    # runs end to end with AMP handling the dtype boundaries
    amp.init()
    out = net(mx.nd.ones((2, 4), dtype="bfloat16"))
    assert np.isfinite(out.asnumpy().astype(np.float32)).all()
