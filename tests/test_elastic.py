"""Elastic membership (ISSUE 16): resize a running job without losing it.

Chaos-marked.  The ladder, bottom-up:

  * KVStoreServer membership table: JOIN/LEAVE/MEMBERS epoch arithmetic,
    idempotency under SEQ retry, snapshot durability across a server
    restart
  * satellite 2 regression: a barrier parked against the OLD world is
    released (rebased, not double-fired) when a concurrent LEAVE or an
    MX_ELASTIC_EVICT_AFTER liveness eviction moves the membership epoch
    mid-wait — the eviction variant runs on the virtual clock, zero
    real waiting
  * PULLQ: the promoted cross-slice return leg ships the int8 wire
    tuple — decodes within quantization tolerance at a fraction of the
    fp32 bytes
  * launch.Supervisor elastic units (framework-free scripts,
    milliseconds each): budget-exhausted worker -> shrink-and-continue
    instead of whole-job teardown, LEAVE-on-behalf reaches a live
    parameter server, resize-file grow/shrink respawns the worker set
    under a bumped generation, a stale resize target is never re-applied
  * end-to-end through the CLI (slow): `launch.py --elastic
    --resize-file` grows 2->4 and shrinks 4->3 mid-fit and the final
    params match an uninterrupted run; a rank SIGKILLed past its restart
    budget shrinks the job instead of failing it
"""
import importlib.util
import os
import signal
import socket
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx                                      # noqa: F401
from mxnet_tpu import fault
from mxnet_tpu.kvstore.server import (KVStoreServer, recv_msg, send_msg,
                                      serve_forever)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_faults():
    fault.clear()
    yield
    fault.clear()


def _load_launch():
    spec = importlib.util.spec_from_file_location(
        "mx_launch_elastic_under_test",
        os.path.join(REPO, "tools", "launch.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


launch = _load_launch()


def _no_jitter_backoff(base=0.01):
    return fault.RetryPolicy(deadline=float("inf"), base=base,
                             max_delay=8.0, jitter=0.0)


# ---------------------------------------------------------------------------
# membership table: epochs, idempotency, durability
# ---------------------------------------------------------------------------

def test_join_leave_members_epoch_arithmetic():
    srv = KVStoreServer(num_workers=2)        # seeds {r0, r1} at epoch 0
    ok, (epoch, members) = srv.handle(("MEMBERS", None))
    assert ok and epoch == 0 and members == ["r0", "r1"]

    ok, (epoch, members) = srv.handle(("JOIN", "r2:boot"))
    assert ok and epoch == 1 and members == ["r0", "r1", "r2"]

    ok, (epoch, members) = srv.handle(("LEAVE", "r1:drain"))
    assert ok and epoch == 2 and members == ["r0", "r2"]


def test_join_and_leave_are_idempotent():
    """JOIN of a present rank and LEAVE of an absent rank are no-ops
    with NO epoch bump — that is the SEQ-retry safety contract, and it
    lets every worker of a fixed-size job JOIN at init unconditionally."""
    srv = KVStoreServer(num_workers=2)
    ok, (e1, m1) = srv.handle(("JOIN", "r0:again"))     # already a member
    assert ok and e1 == 0 and m1 == ["r0", "r1"]
    ok, (e2, m2) = srv.handle(("LEAVE", "r7:ghost"))    # never a member
    assert ok and e2 == 0 and m2 == ["r0", "r1"]
    # real mutations still move the clock
    srv.handle(("LEAVE", "r1:x"))
    ok, (e3, _) = srv.handle(("LEAVE", "r1:x"))         # replayed LEAVE
    assert ok and e3 == 1                               # bumped exactly once


def test_membership_survives_snapshot_restart(tmp_path):
    """The table and its epoch ride the snapshot: a restarted server
    sizes barriers against the RESIZED world, not the constructor's."""
    snap = str(tmp_path / "s.pkl")
    srv = KVStoreServer(num_workers=2, snapshot_path=snap)
    srv.handle(("JOIN", "r2:boot"))
    srv.handle(("LEAVE", "r0:drain"))
    srv2 = KVStoreServer(num_workers=2, snapshot_path=snap)   # restart
    ok, (epoch, members) = srv2.handle(("MEMBERS", None))
    assert ok and members == ["r1", "r2"]
    assert epoch == 2                         # monotonic across restart


# ---------------------------------------------------------------------------
# satellite 2: barrier release re-checks the membership epoch
# ---------------------------------------------------------------------------

def _park_barrier(srv, cid, out):
    def run():
        out.append(srv.handle_request(("SEQ", cid, 1, ("BARRIER", None))))
    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


def test_barrier_parked_against_old_world_releases_on_leave(monkeypatch):
    """r0 parks in a 2-member barrier; r1's LEAVE lands mid-wait.  The
    release path must rebase the count against the CURRENT epoch and
    free r0 — not strand it against arithmetic from the old world."""
    monkeypatch.setenv("MX_KVSTORE_BARRIER_TIMEOUT", "20")
    monkeypatch.delenv("MX_KVSTORE_STALE_TIMEOUT", raising=False)
    srv = KVStoreServer(num_workers=2)
    results = []
    t = _park_barrier(srv, "r0:live", results)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:        # wait until r0 is parked
        with srv._barrier_cv:
            if srv._barrier_waiting.get("r0"):
                break
        time.sleep(0.01)
    t0 = time.monotonic()
    ok, (epoch, members) = srv.handle(("LEAVE", "r1:drain"))
    assert ok and members == ["r0"]
    t.join(timeout=5)
    assert not t.is_alive()
    assert results and results[0][0] is True  # released, not timed out
    assert time.monotonic() - t0 < 5.0        # nowhere near the 20s budget
    # clean single fire: the next barrier in the 1-member world is
    # immediate (no leftover count from a double release)
    with srv._barrier_cv:
        assert srv._barrier_count == 0
    ok2, _ = srv.handle_request(("SEQ", "r0:live", 2, ("BARRIER", None)))
    assert ok2


def test_departed_ghost_arrival_cannot_double_release(monkeypatch):
    """The rebase discounts a DEPARTED rank's parked arrival: after r1
    arrives and then LEAVEs (preemption notice racing its own barrier),
    the count must rebase to the surviving members' arrivals only."""
    monkeypatch.setenv("MX_KVSTORE_BARRIER_TIMEOUT", "0.5")
    srv = KVStoreServer(num_workers=3)        # r0, r1, r2
    results = []
    threads = [_park_barrier(srv, "r0:a", results),
               _park_barrier(srv, "r1:b", results)]
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        with srv._barrier_cv:
            if (srv._barrier_waiting.get("r0")
                    and srv._barrier_waiting.get("r1")):
                break
        time.sleep(0.01)
    # r1 departs while parked: quorum shrinks 3 -> 2, but r1's own
    # arrival no longer counts — one live waiter (r0) of two members,
    # so the barrier must NOT fire for r0 until r2 shows up or timeout
    srv.handle(("LEAVE", "r1:gone"))
    for t in threads:
        t.join(timeout=10)
    assert len(results) == 2
    # r0 timed out (honest wait for r2 in the rebased 2-member world);
    # r1 was freed by the generation it observed — either outcome for
    # r1 is fine as long as r0 did not get a phantom release
    r0_result = [r for r in results if r[0] is False]
    assert r0_result, results
    assert "timed out" in str(r0_result[0][1])


def test_evict_after_shrinks_membership_on_virtual_clock(monkeypatch):
    """MX_ELASTIC_EVICT_AFTER turns a long-silent member into an
    involuntary LEAVE: the TABLE shrinks (epoch bump), the parked
    survivor's barrier releases, and the ghost is gone from MEMBERS —
    all on the virtual clock, zero real waiting."""
    monkeypatch.setenv("MX_ELASTIC_EVICT_AFTER", "30")
    monkeypatch.setenv("MX_KVSTORE_BARRIER_TIMEOUT", "300")
    monkeypatch.delenv("MX_KVSTORE_STALE_TIMEOUT", raising=False)
    with fault.use_virtual_time() as clk:
        srv = KVStoreServer(num_workers=2)
        srv.touch("r1:wedged")                # seen once...
        clk.advance(31.0)                     # ...then silent too long
        t0 = time.monotonic()
        ok, _ = srv.handle_request(("SEQ", "r0:live", 1, ("BARRIER",
                                                          None)))
        assert ok
        assert time.monotonic() - t0 < 10.0   # virtual, not the 300s
        ok, (epoch, members) = srv.handle(("MEMBERS", None))
        assert ok and members == ["r0"]       # permanent: table, not
        assert epoch == 1                     # per-barrier discounting


# ---------------------------------------------------------------------------
# PULLQ: quantized cross-slice return leg
# ---------------------------------------------------------------------------

def test_pullq_decodes_within_tolerance_at_a_fraction_of_the_bytes():
    from mxnet_tpu.kvstore import wire_codec as wc
    srv = KVStoreServer(num_workers=1)
    rng = np.random.RandomState(3)
    value = rng.uniform(-1, 1, size=4096).astype(np.float32)
    srv.handle(("INIT", "w", value))

    ok, full = srv.handle(("PULL", "w"))
    assert ok
    ok, wire = srv.handle(("PULLQ", "w"))
    assert ok and wc.is_wire_payload(wire)
    decoded = wc.decode_wire(wire)
    np.testing.assert_allclose(decoded, full, atol=0.02)   # int8 error

    q_bytes = sum(np.asarray(p).nbytes for p in wire
                  if isinstance(p, np.ndarray))
    assert q_bytes < full.nbytes / 3.0        # the wire win is real


def test_pullq_is_idempotent_and_bypasses_the_replay_cache():
    """PULLQ rides the PULL bypass: replaying the same seq answers
    fresh (no cache bloat, no stale-seq refusal for a read)."""
    srv = KVStoreServer(num_workers=1)
    srv.handle(("INIT", "w", np.ones(8, np.float32)))
    ok1, w1 = srv.handle_request(("SEQ", "r0:x", 5, ("PULLQ", "w")))
    ok2, w2 = srv.handle_request(("SEQ", "r0:x", 5, ("PULLQ", "w")))
    assert ok1 and ok2
    from mxnet_tpu.kvstore import wire_codec as wc
    np.testing.assert_allclose(wc.decode_wire(w1), wc.decode_wire(w2))


# ---------------------------------------------------------------------------
# Supervisor elastic units (framework-free subprocess scripts)
# ---------------------------------------------------------------------------

def _worker_env(rank, **extra):
    env = dict(os.environ)
    env["MX_PROCESS_ID"] = str(rank)
    env.update(extra)
    return env


def test_supervisor_elastic_shrinks_instead_of_tearing_down():
    """A worker burning its restart budget under --elastic retires from
    the job; the survivors run to completion and the job exits 0 (the
    non-elastic contract — teardown with the failing rank's code — is
    pinned by test_supervisor_budget_exhaustion_tears_down_whole_job)."""
    sup = launch.Supervisor(restart="on-failure", max_restarts=0,
                            backoff=_no_jitter_backoff(), elastic=True)
    bad = sup.add("rank 0", [sys.executable, "-c", "import sys; sys.exit(5)"],
                  _worker_env(0))
    ok = sup.add("rank 1",
                 [sys.executable, "-c",
                  "import time; time.sleep(0.3); print('SURVIVOR_OK')"],
                 _worker_env(1))
    rc = sup.run()
    assert rc == 0                            # shrink-and-continue
    assert bad.rc == 5 and bad.done           # retired, rc not folded
    assert ok.rc == 0


def test_supervisor_elastic_sigkill_past_budget_shrinks():
    """Satellite 3's involuntary-loss flavor: a rank killed by the OOM
    reaper (real SIGKILL, rc -9) past its budget shrinks the job too."""
    kill_me = "import os, signal; os.kill(os.getpid(), signal.SIGKILL)"
    sup = launch.Supervisor(restart="on-failure", max_restarts=1,
                            backoff=_no_jitter_backoff(), elastic=True)
    bad = sup.add("rank 0", [sys.executable, "-c", kill_me],
                  _worker_env(0))
    sup.add("rank 1", [sys.executable, "-c", "import time; time.sleep(0.3)"],
            _worker_env(1))
    rc = sup.run()
    assert rc == 0
    assert bad.restarts == 1                  # budget honestly spent first
    assert bad.rc == -signal.SIGKILL


def test_supervisor_elastic_without_survivors_still_tears_down():
    """Shrink-and-continue needs someone to continue: when the LAST
    worker exhausts its budget the job fails loudly, elastic or not."""
    sup = launch.Supervisor(restart="on-failure", max_restarts=0,
                            backoff=_no_jitter_backoff(), elastic=True)
    sup.add("rank 0", [sys.executable, "-c", "import sys; sys.exit(5)"],
            _worker_env(0))
    assert sup.run() == 5


def _start_ps(num_workers):
    port = launch._free_port()
    t = threading.Thread(target=serve_forever,
                         kwargs=dict(port=port, num_workers=num_workers),
                         daemon=True)
    t.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port),
                                     timeout=0.2).close()
            return port, t
        except OSError:
            time.sleep(0.05)
    raise RuntimeError("server did not come up on %d" % port)


def _ps_rpc(port, msg):
    raw = socket.create_connection(("127.0.0.1", port), timeout=5)
    try:
        send_msg(raw, msg)
        return recv_msg(raw, timeout=5)
    finally:
        raw.close()


def test_supervisor_sends_leave_on_behalf_of_the_dead_rank():
    """The shrink must reach the parameter server: the retired rank is
    LEAVEd out of the membership table so no barrier ever waits on it."""
    port, thread = _start_ps(num_workers=2)
    try:
        sup = launch.Supervisor(restart="on-failure", max_restarts=0,
                                backoff=_no_jitter_backoff(), elastic=True)
        sup.ps_addrs = ["127.0.0.1:%d" % port]
        sup.add("rank 1", [sys.executable, "-c", "import sys; sys.exit(3)"],
                _worker_env(1))
        sup.add("rank 0",
                [sys.executable, "-c", "import time; time.sleep(0.4)"],
                _worker_env(0))
        assert sup.run() == 0
        ok, (epoch, members) = _ps_rpc(port, ("MEMBERS", None))
        assert ok and members == ["r0"]       # r1 LEAVEd on its behalf
        assert epoch == 1
    finally:
        _ps_rpc(port, ("STOP", None))
        thread.join(timeout=10)


_RESIZE_WORKER = textwrap.dedent("""
    import os, time
    open(os.environ["MX_DONE_DIR"] + "/done.%s.gen%s" % (
        os.environ["MX_PROCESS_ID"],
        os.environ.get("MX_ELASTIC_EPOCH", "?")), "w").close()
    time.sleep(float(os.environ.get("MX_LINGER", "0")))
""")


def _resize_factory(tmp_path, linger="0"):
    def make_worker(rank, n, generation):
        env = _worker_env(rank, MX_DONE_DIR=str(tmp_path),
                          MX_LINGER=linger,
                          MX_ELASTIC="1",
                          MX_ELASTIC_EPOCH=str(generation))
        return ("rank %d" % rank, [sys.executable, "-c", _RESIZE_WORKER],
                env, None)
    return make_worker


def test_supervisor_resize_file_grows_the_worker_set(tmp_path):
    """Pre-staged resize target 3 with 1 running worker: the tick
    drains the old world and respawns ranks 0..2 under generation 1."""
    resize = tmp_path / "resize"
    resize.write_text("3")
    factory = _resize_factory(tmp_path)
    sup = launch.Supervisor(restart="never", elastic=True,
                            resize_file=str(resize), drain_timeout=5.0)
    sup.worker_factory = factory
    sup._resize_applied = 1
    name, argv, env, hb = factory(0, 1, 0)    # generation-0 world
    env["MX_LINGER"] = "30"                   # still running at the tick
    sup.add(name, argv, env, heartbeat=hb)
    t0 = time.monotonic()
    rc = sup.run()
    assert rc == 0
    assert time.monotonic() - t0 < 25         # drained, never slept 30
    assert sup.generation == 1
    for rank in range(3):
        assert (tmp_path / ("done.%d.gen1" % rank)).exists()


def test_supervisor_resize_file_shrinks_the_worker_set(tmp_path):
    port, thread = _start_ps(num_workers=2)
    try:
        resize = tmp_path / "resize"
        resize.write_text("1")
        factory = _resize_factory(tmp_path)
        sup = launch.Supervisor(restart="never", elastic=True,
                                resize_file=str(resize), drain_timeout=5.0)
        sup.worker_factory = factory
        sup.ps_addrs = ["127.0.0.1:%d" % port]
        sup._resize_applied = 2
        for rank in range(2):
            name, argv, env, hb = factory(rank, 2, 0)
            env["MX_LINGER"] = "30"
            sup.add(name, argv, env, heartbeat=hb)
        rc = sup.run()
        assert rc == 0
        assert (tmp_path / "done.0.gen1").exists()
        assert not (tmp_path / "done.1.gen1").exists()   # rank 1 removed
        ok, (_, members) = _ps_rpc(port, ("MEMBERS", None))
        assert ok and members == ["r0"]       # LEAVEd out of the quorum
    finally:
        _ps_rpc(port, ("STOP", None))
        thread.join(timeout=10)


def test_stale_resize_target_is_never_reapplied(tmp_path):
    """After an involuntary shrink the resize file still holds the OLD
    target; _check_resize must not let it 'heal' the world back up."""
    resize = tmp_path / "resize"
    resize.write_text("2")

    def boom(rank, n, generation):            # factory must not fire
        raise AssertionError("stale target re-applied")

    sup = launch.Supervisor(restart="never", elastic=True,
                            resize_file=str(resize))
    sup.worker_factory = boom
    sup._resize_applied = 2                   # target 2 already honoured
    sup._check_resize()                       # no-op, no AssertionError
    resize.write_text("0")                    # nonsense targets ignored
    sup._check_resize()
    resize.write_text("banana")
    sup._check_resize()
    assert sup.generation == 0


# ---------------------------------------------------------------------------
# end-to-end through the CLI (slow: real jax startup per worker)
# ---------------------------------------------------------------------------

def _clean_env(**extra):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)      # conftest's 8-dev count: workers pick own
    env.pop("MX_FAULT_INJECT", None)
    env.update(extra)
    return env


def _launch_argv(argv):
    return [sys.executable, os.path.join(REPO, "tools", "launch.py")] + argv


def _fit_argv(tmp_path, tag, epochs):
    fit = os.path.join(REPO, "tools", "chaos_fit.py")
    return [sys.executable, fit, "--epochs", str(epochs),
            "--ckpt-dir", str(tmp_path / tag), "--out", str(tmp_path / tag)]


def _reference_params(tmp_path, epochs):
    ref = subprocess.run(
        _launch_argv(["-n", "1", "--launcher", "local", "--"]
                     + _fit_argv(tmp_path, "ref", epochs)),
        capture_output=True, text=True, timeout=300, env=_clean_env())
    assert ref.returncode == 0, (ref.stdout, ref.stderr)
    return np.load(str(tmp_path / "ref.rank0.npz"))


def _assert_params_match(want, path, label):
    got = np.load(str(path))
    assert set(got.files) == set(want.files)
    for k in want.files:
        np.testing.assert_allclose(got[k], want[k], rtol=1e-5, atol=1e-6,
                                   err_msg="%s %s" % (label, k))


def _run_elastic_resize(tmp_path, tag, n0, n_new, epochs):
    """launch.py --elastic -n n0, flip the resize file to n_new once the
    generation-0 workers are up, wait for the job to finish exit 0."""
    resize = tmp_path / (tag + ".resize")
    proc = subprocess.Popen(
        _launch_argv(["-n", str(n0), "--launcher", "local",
                      "--elastic", "--resize-file", str(resize),
                      "--drain-timeout", "60", "--"]
                     + _fit_argv(tmp_path, tag, epochs)),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=_clean_env())
    try:
        # resize once the generation-0 world exists (first rank's
        # checkpoint dir appears); landing pre-, mid- or post-fit are
        # all legal interleavings the drain must absorb
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if (tmp_path / tag / "rank0").exists():
                break
            if proc.poll() is not None:
                break
            time.sleep(0.2)
        resize.write_text(str(n_new))
        out, err = proc.communicate(timeout=420)
    except Exception:
        proc.kill()
        raise
    assert proc.returncode == 0, (out, err)
    assert "elastic resize" in err, err
    return out, err


@pytest.mark.slow
def test_launch_elastic_grow_matches_uninterrupted(tmp_path):
    """Acceptance: grow 2->4 mid-fit.  Old ranks drain at an epoch
    boundary and auto-resume; new ranks join under generation 1; every
    final parameter set matches an uninterrupted single-rank run."""
    want = _reference_params(tmp_path, epochs=4)
    out, _err = _run_elastic_resize(tmp_path, "grow", 2, 4, epochs=4)
    assert out.count("CHAOS_FIT_DONE") >= 4
    for rank in range(4):
        _assert_params_match(want, tmp_path / ("grow.rank%d.npz" % rank),
                             "grow rank %d" % rank)


@pytest.mark.slow
def test_launch_elastic_shrink_matches_uninterrupted(tmp_path):
    """Acceptance: shrink 4->3 mid-fit with loss-trajectory parity (the
    params ARE the trajectory: same seeded data + deterministic resume
    means matching final params within fp tolerance)."""
    want = _reference_params(tmp_path, epochs=4)
    out, _err = _run_elastic_resize(tmp_path, "shrink", 4, 3, epochs=4)
    assert out.count("CHAOS_FIT_DONE") >= 3
    for rank in range(3):
        _assert_params_match(want, tmp_path / ("shrink.rank%d.npz" % rank),
                             "shrink rank %d" % rank)


@pytest.mark.slow
def test_launch_elastic_budget_exhausted_shrinks_and_continues(tmp_path):
    """A rank crashing past --max-restarts under --elastic retires; the
    survivor finishes exit 0 with correct params (vs the non-elastic
    contract where the whole job would fold to the crash's rc)."""
    want = _reference_params(tmp_path, epochs=2)
    crash_rank1 = textwrap.dedent("""
        import os, signal, sys
        if os.environ.get("MX_PROCESS_ID") == "1":
            os.kill(os.getpid(), signal.SIGKILL)
        os.execv(sys.executable, sys.argv[1:])   # argv[1] is the python exe
    """)
    r = subprocess.run(
        _launch_argv(["-n", "2", "--launcher", "local", "--elastic",
                      "--restart", "on-failure", "--max-restarts", "1",
                      "--", sys.executable, "-c", crash_rank1]
                     + _fit_argv(tmp_path, "loss", epochs=2)),
        capture_output=True, text=True, timeout=300, env=_clean_env())
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "elastic shrink" in r.stderr, r.stderr
    assert "CHAOS_FIT_DONE rank 0" in r.stdout
    _assert_params_match(want, tmp_path / "loss.rank0.npz", "survivor")
