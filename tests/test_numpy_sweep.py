"""Numpy-oracle SWEEP over the mx.np surface (VERDICT r4 next-item #5).

test_numpy.py samples edge semantics; this file sweeps them: every
unary/binary/reduction function runs against installed NumPy over a
shared corner battery — {0-d, empty, bool, int, NaN/inf, mixed-dtype
promotion pairs} — and every public name in mx.np must be claimed by
exactly one bucket (swept here / tested elsewhere / documented
divergence), so a new function cannot appear without oracle coverage.

Dtype rule: jax runs with x64 disabled (TPU-first), so NumPy's 64-bit
results are accepted at 32-bit width — KIND must match exactly, width is
normalized.  Genuine semantic divergences live in DIVERGENCES with a
justification each (VERDICT asks for <= 20; the list is checked).
"""
import warnings

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ndarray.ndarray import NDArray

np = mx.np

# ---------------------------------------------------------------------------
# documented, justified divergences from installed NumPy (<= 20 entries)
# ---------------------------------------------------------------------------
DIVERGENCES = {
    "roots": "jnp.roots(strip_zeros=False): static output shape len(p)-1 "
             "(jit requirement); numpy strips leading zero coefficients",
    "fromstring": "host-side constructor; numpy deprecated sep='' binary "
                  "mode raises here",
    "shares_memory": "chunk-identity model: views share their root chunk; "
                     "unrelated arrays never report overlap",
    "may_share_memory": "same chunk-identity model as shares_memory",
    "einsum_path": "returns numpy's own (path, report) on host arrays",
    "spacing": "inf/nan inputs return nan (numpy returns nan too); "
               "float32 width only (x64 off)",
    "sort": "NaNs sort last as in numpy, but kind=/stable= kwargs are "
            "accepted and ignored (XLA sort is always stable)",
    "argsort": "same stable-sort note as sort",
    "around": "banker's rounding matches numpy; decimals<0 on integer "
              "dtypes stays integer (numpy promotes to float64)",
    "round": "alias of around — same note",
    "float_power": "computes at float32 (x64 off); numpy promises >=f64",
    "ldexp": "int64 exponents truncate to int32 (x64 off)",
    "frexp": "mantissa float32, exponent int32 (x64 off)",
    "busday_count": "datetime64 calendar ops are out of scope (no XLA "
                    "representation); absent by design",
    "reciprocal": "integer input computes at float32; numpy's integer "
                  "reciprocal truncates to 0 for |x|>1 (a documented "
                  "numpy footgun, deliberately not reproduced)",
}
assert len(DIVERGENCES) <= 20, "divergence list must stay <= 20 entries"


# ---------------------------------------------------------------------------
# shared corner batteries
# ---------------------------------------------------------------------------

def _unary_inputs():
    return [
        onp.array([[-1.5, 0.0, 2.25], [0.5, -0.75, 3.0]], onp.float32),
        onp.array([[onp.nan, onp.inf, -onp.inf], [1.0, -1.0, 0.5]],
                  onp.float32),
        onp.float32(0.5),                       # 0-d
        onp.zeros((0,), onp.float32),           # empty
        onp.array([[1, 2], [3, 4]], onp.int32),
        onp.array([True, False, True]),
    ]


def _binary_pairs():
    f = onp.array([[1.5, -2.0, 0.25]], onp.float32)
    i = onp.array([[2, 3, 4]], onp.int32)
    b = onp.array([[True, False, True]])
    nanv = onp.array([[onp.nan, 1.0, onp.inf]], onp.float32)
    return [
        (f, f), (f, i), (i, i), (b, b), (b, i),
        (onp.float32(2.0), i),                  # 0-d x array promotion
        (nanv, f),                              # NaN/inf propagation
        (onp.zeros((0,), onp.float32), onp.zeros((0,), onp.float32)),
    ]


def _norm_dtype(dt):
    """KIND must match; width is normalized away: x64-off truncates
    numpy's 64-bit defaults, and numpy's value-based minimal promotion
    (exp(bool)->float16, power(bool,bool)->int8) picks narrower widths
    than jnp's uniform 32-bit results."""
    k = onp.dtype(dt).kind
    return {"f": "float", "i": "int", "u": "uint", "c": "complex",
            "b": "bool"}.get(k, str(onp.dtype(dt)))


def _to_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else onp.asarray(x)


def _compare(name, got, want, case):
    if isinstance(want, tuple):
        assert isinstance(got, (tuple, list)) and len(got) == len(want), \
            "%s%s: structure %r vs %r" % (name, case, got, want)
        for g, w in zip(got, want):
            _compare(name, g, w, case)
        return
    got = _to_np(got)
    want = onp.asarray(want)
    assert _norm_dtype(got.dtype) == _norm_dtype(want.dtype), \
        "%s%s: dtype %s vs numpy %s" % (name, case, got.dtype, want.dtype)
    assert got.shape == want.shape, \
        "%s%s: shape %s vs numpy %s" % (name, case, got.shape, want.shape)
    if want.dtype.kind in "fc":
        # numpy's value-based minimal promotion computes bool/int8 inputs
        # at float16: compare at THAT precision, not float32's
        rtol, atol = ((2e-3, 1e-3) if want.dtype.itemsize <= 2
                      else (2e-5, 1e-6))
        onp.testing.assert_allclose(
            got.astype(onp.float64), want.astype(onp.float64),
            rtol=rtol, atol=atol, equal_nan=True,
            err_msg="%s%s" % (name, case))
    else:
        onp.testing.assert_array_equal(got, want,
                                       err_msg="%s%s" % (name, case))


def _sweep_one(name, onp_fn, mx_fn, arg_tuples):
    ran = 0
    for args in arg_tuples:
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            try:
                want = onp_fn(*args)
            except Exception:
                continue    # numpy rejects this combo; acceptance here
                            # would be an extension, not a divergence
        if isinstance(want, onp.ndarray) and want.dtype.kind in "iu" \
                and any("divide by zero" in str(w.message)
                        or "invalid value" in str(w.message)
                        for w in caught):
            continue        # integer division by zero: C-level UB that
                            # numpy papers over with 0 — XLA's result is
                            # platform-defined, nothing to pin
        if isinstance(want, onp.ndarray) and want.dtype.kind in "mMOSU":
            continue        # non-numeric result: out of scope
        got = mx_fn(*[np.array(a) if isinstance(a, onp.ndarray)
                      else a for a in args])
        _compare(name, got, want, tuple(a.dtype if hasattr(a, "dtype")
                                        else type(a).__name__
                                        for a in args))
        ran += 1
    assert ran > 0, "%s: no oracle case executed" % name


# ---------------------------------------------------------------------------
# the buckets
# ---------------------------------------------------------------------------

UNARY = [
    # numpy-2.0 alias spellings are swept like their classic names
    "acos", "acosh", "asin", "asinh", "atan", "atanh", "bitwise_invert",
    "absolute", "abs", "fabs", "negative", "positive", "exp", "exp2",
    "expm1", "log", "log2", "log10", "log1p", "sqrt", "cbrt", "square",
    "reciprocal", "sin", "cos", "tan", "arcsin", "arccos", "arctan",
    "sinh", "cosh", "tanh", "arcsinh", "arccosh", "arctanh", "degrees",
    "radians", "deg2rad", "rad2deg", "rint", "fix", "floor", "ceil",
    "trunc", "sign", "signbit", "isnan", "isinf", "isfinite", "isneginf",
    "isposinf", "logical_not", "invert", "bitwise_not", "conj",
    "conjugate", "real", "imag", "angle", "i0", "sinc", "nan_to_num",
    "spacing", "iscomplex", "isreal",
]
BINARY = [
    "atan2", "pow", "bitwise_left_shift", "bitwise_right_shift",
    "add", "subtract", "multiply", "divide", "true_divide",
    "floor_divide", "mod", "remainder", "fmod", "power", "float_power",
    "maximum", "minimum", "fmax", "fmin", "arctan2", "hypot",
    "logaddexp", "logaddexp2", "copysign", "nextafter", "ldexp",
    "heaviside", "gcd", "lcm", "bitwise_and", "bitwise_or",
    "bitwise_xor", "left_shift", "right_shift", "equal", "not_equal",
    "less", "less_equal", "greater", "greater_equal", "logical_and",
    "logical_or", "logical_xor",
]
REDUCTIONS = [
    "sum", "prod", "mean", "std", "var", "max", "min", "amax", "amin",
    "nansum", "nanprod", "nanmean", "nanstd", "nanvar", "nanmax",
    "nanmin", "median", "nanmedian", "all", "any", "argmax", "argmin",
    "ptp", "cumsum", "cumprod", "count_nonzero", "logsumexp",
]


# functions whose DIVERGENCES entry concerns only non-float inputs: the
# float battery still sweeps them (partial divergence, not a free pass)
FLOAT_ONLY = {"reciprocal", "spacing"}


@pytest.mark.parametrize("name", UNARY)
def test_unary_sweep(name):
    if name in DIVERGENCES and name not in FLOAT_ONLY:
        pytest.skip("documented divergence: " + DIVERGENCES[name])
    onp_fn = getattr(onp, name, None)
    if onp_fn is None:      # e.g. logsumexp lives in scipy
        pytest.skip("no installed-numpy counterpart")
    mx_fn = getattr(np, name)
    inputs = _unary_inputs()
    if name in FLOAT_ONLY:
        inputs = [x for x in inputs
                  if onp.asarray(x).dtype.kind == "f"]
    _sweep_one(name, onp_fn, mx_fn, [(x,) for x in inputs])


@pytest.mark.parametrize("name", BINARY)
def test_binary_sweep(name):
    if name in DIVERGENCES:
        pytest.skip("documented divergence: " + DIVERGENCES[name])
    onp_fn = getattr(onp, name, None)
    if onp_fn is None:
        pytest.skip("no installed-numpy counterpart")
    mx_fn = getattr(np, name)
    _sweep_one(name, onp_fn, mx_fn, _binary_pairs())


def _reduction_cases():
    base = [
        onp.array([[1.5, -2.0, 0.25], [3.0, 0.0, -1.0]], onp.float32),
        onp.array([[onp.nan, 1.0, 2.0], [3.0, onp.nan, 4.0]],
                  onp.float32),
        onp.array([[1, 2, 3], [4, 5, 6]], onp.int32),
        onp.array([[True, False], [True, True]]),
        onp.float32(2.5),
    ]
    cases = []
    for x in base:
        cases.append(((x,), {}))
        if getattr(x, "ndim", 0) >= 2:
            cases.append(((x,), {"axis": 0}))
            cases.append(((x,), {"axis": 1}))
            cases.append(((x,), {"axis": 0, "keepdims": True}))
    return cases


@pytest.mark.parametrize("name", REDUCTIONS)
def test_reduction_sweep(name):
    if name in DIVERGENCES:
        pytest.skip("documented divergence: " + DIVERGENCES[name])
    onp_fn = getattr(onp, name, None)
    if onp_fn is None:
        pytest.skip("no installed-numpy counterpart")
    mx_fn = getattr(np, name)
    ran = 0
    for args, kw in _reduction_cases():
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            try:
                want = onp_fn(*args, **kw)
            except Exception:
                continue
            if name in ("argmax", "argmin") and "keepdims" in kw:
                continue
        try:
            got = mx_fn(*[np.array(a) for a in args], **kw)
        except TypeError:
            if "keepdims" in kw:
                continue    # keepdims unsupported on a few: acceptable?
            raise           # no: missing axis support is a sweep failure
        _compare(name, got, want,
                 (str(args[0].dtype), tuple(sorted(kw.items()))))
        ran += 1
    assert ran > 0, name


# ---------------------------------------------------------------------------
# full-surface accountability: every public np name is claimed somewhere
# ---------------------------------------------------------------------------

TESTED_ELSEWHERE = {
    # shape / indexing / manipulation semantics: tests/test_numpy.py
    "reshape", "transpose", "swapaxes", "moveaxis", "rollaxis", "flip",
    "fliplr", "flipud", "rot90", "roll", "concatenate", "stack",
    "vstack", "hstack", "dstack", "column_stack", "row_stack", "split",
    "array_split", "vsplit", "hsplit", "dsplit", "squeeze",
    "expand_dims", "broadcast_to", "broadcast_arrays", "atleast_1d",
    "atleast_2d", "atleast_3d", "ravel", "tile", "repeat", "pad",
    "flatnonzero", "nonzero", "where", "take", "take_along_axis",
    "put_along_axis", "choose", "compress", "extract", "select",
    "piecewise", "insert", "delete", "append", "resize", "unique",
    "trim_zeros", "ediff1d", "searchsorted", "sort", "argsort", "block",
    "argwhere", "argpartition", "partition", "lexsort", "msort", "diff",
    "gradient", "trapz", "trapezoid", "interp", "bincount", "digitize",
    "histogram", "histogram2d", "histogramdd", "apply_along_axis",
    "apply_over_axes", "packbits", "unpackbits",
    # creation: test_numpy.py
    "array", "asarray", "ascontiguousarray", "asanyarray", "empty",
    "empty_like", "zeros", "zeros_like", "ones", "ones_like", "full",
    "full_like", "arange", "linspace", "logspace", "geomspace", "eye",
    "identity", "diag", "diagflat", "diagonal", "tri", "tril", "triu",
    "vander", "meshgrid", "indices", "fromfunction", "frombuffer",
    "fromiter", "copy", "require",
    # linalg-ish on the main namespace: test_numpy.py
    "dot", "vdot", "inner", "outer", "matmul", "tensordot", "einsum",
    "kron", "cross", "trace",
    # round-5 additions: tests/test_numpy_extras.py
    "polyadd", "polysub", "polymul", "polydiv", "polyder", "polyint",
    "polyfit", "polyval", "poly", "kaiser", "bartlett", "blackman",
    "hamming", "hanning", "unwrap", "place", "putmask", "copyto",
    "histogram_bin_edges", "matrix_transpose", "real_if_close",
    "iscomplexobj", "isrealobj", "mgrid", "ogrid",
    # comparison-with-tolerance family: test_numpy.py
    "isclose", "allclose", "array_equal", "array_equiv",
    # set ops: test_numpy.py
    "isin", "in1d", "intersect1d", "union1d", "setdiff1d", "setxor1d",
    # statistics beyond reductions: test_numpy.py
    "average", "percentile", "quantile", "nanpercentile", "nanquantile",
    "corrcoef", "cov", "convolve", "correlate", "nanargmax",
    "nanargmin", "nancumsum", "nancumprod",
    # dtype/introspection helpers: test_numpy.py + here via _norm rules
    "result_type", "promote_types", "can_cast", "common_type",
    "min_scalar_type", "issubdtype", "iterable", "ndim", "shape",
    "size", "dtype", "isscalar", "clip", "ix_", "unravel_index",
    "ravel_multi_index", "diag_indices", "diag_indices_from",
    "tril_indices", "triu_indices", "tril_indices_from",
    "triu_indices_from", "mask_indices", "one_hot",
    # rounding family has dedicated semantics tests: test_numpy.py
    "floor_divide", "divmod", "modf", "frexp", "around", "round",
    # misc host-side helpers
    "set_printoptions", "get_printoptions", "may_share_memory",
    "shares_memory", "save", "load", "savez", "genfromtxt",
}

# module-level non-function attributes, namespaces and import plumbing
NON_FUNCTIONS = {
    "linalg", "random", "fft", "pi", "e", "inf", "nan", "newaxis",
    "euler_gamma", "float16", "float32", "float64", "int8", "int16",
    "int32", "int64", "uint8", "uint16", "uint32", "uint64", "bool_",
    "bool8", "complex64", "complex128", "intp", "ndarray", "generic",
    "number", "integer", "floating", "inexact", "signedinteger",
    "unsignedinteger", "NDArray", "finfo", "iinfo",
    # module internals visible in dir() (imports, helpers)
    "Any", "ModuleType", "annotations", "sys", "jax", "jnp", "invoke",
    "from_jax", "current_context", "may_promote",
}

TESTED_ELSEWHERE |= {
    # 2.x alias spellings of functions tested under their classic names
    "concat", "permute_dims", "round_", "divmod_", "astype", "pow",
    "broadcast_shapes", "fill_diagonal",
}


def test_every_public_name_is_claimed():
    """A new mx.np function cannot land without oracle coverage: every
    public name must be swept here, tested elsewhere (named), a
    documented divergence, or a non-function attribute."""
    claimed = (set(UNARY) | set(BINARY) | set(REDUCTIONS)
               | TESTED_ELSEWHERE | set(DIVERGENCES) | NON_FUNCTIONS)
    public = {n for n in dir(np) if not n.startswith("_")}
    unclaimed = sorted(n for n in public - claimed)
    assert not unclaimed, \
        "unclaimed mx.np names (add to a sweep bucket or document): %s" \
        % unclaimed
