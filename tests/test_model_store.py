"""model_zoo.model_store — pretrained-weight local store (reference:
python/mxnet/gluon/model_zoo/model_store.py get_model_file/purge and the
sha1-named cache layout + gluon.utils.check_sha1 gate)."""
import hashlib
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon.model_zoo import model_store, vision


def _sha1(path):
    h = hashlib.sha1()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _drop_weights(net, name, root, sha1_named=True):
    os.makedirs(root, exist_ok=True)
    tmp = os.path.join(root, name + ".params.tmp")
    net.save_parameters(tmp)
    if sha1_named:
        final = os.path.join(root,
                             "%s-%s.params" % (name, _sha1(tmp)[:8]))
    else:
        final = os.path.join(root, name + ".params")
    os.replace(tmp, final)
    return final


def test_get_model_pretrained_from_sha1_drop(tmp_path):
    """The VERDICT acceptance flow: drop reference-cache-named weights,
    get_model(name, pretrained=True, root=...) loads and predicts."""
    mx.random.seed(0)
    ref = vision.resnet50_v1(classes=10)
    ref.initialize(mx.init.Xavier())
    x = nd.random.normal(shape=(1, 3, 32, 32))
    want = ref(x)  # also finalizes deferred shapes so save has all params
    _drop_weights(ref, "resnet50_v1", str(tmp_path))

    net = vision.get_model("resnet50_v1", classes=10, pretrained=True,
                           root=str(tmp_path))
    got = net(x)
    np.testing.assert_allclose(got.asnumpy(), want.asnumpy(), rtol=1e-5,
                               atol=1e-6)


def test_get_model_file_sha1_check_rejects_corruption(tmp_path):
    net = vision.get_model("mobilenet0.25", classes=4)
    net.initialize()
    net(nd.zeros((1, 3, 32, 32)))
    path = _drop_weights(net, "mobilenet0.25", str(tmp_path))
    # flip a byte -> content sha1 no longer matches the name's short hash
    with open(path, "r+b") as f:
        f.seek(100)
        b = f.read(1)
        f.seek(100)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(OSError, match="sha1"):
        model_store.get_model_file("mobilenet0.25", root=str(tmp_path))


def test_get_model_file_flat_and_checkpoint_names(tmp_path):
    net = vision.get_model("squeezenet1.0", classes=4)
    net.initialize()
    net(nd.zeros((1, 3, 64, 64)))
    _drop_weights(net, "squeezenet1.0", str(tmp_path), sha1_named=False)
    p = model_store.get_model_file("squeezenet1.0", root=str(tmp_path))
    assert p.endswith("squeezenet1.0.params")
    # missing -> actionable offline error naming the drop location
    with pytest.raises(FileNotFoundError, match="MX_PRETRAINED_DIR"):
        model_store.get_model_file("alexnet", root=str(tmp_path))


def test_purge_clears_cache(tmp_path):
    net = vision.get_model("mobilenet0.25", classes=4)
    net.initialize()
    net(nd.zeros((1, 3, 32, 32)))
    _drop_weights(net, "mobilenet0.25", str(tmp_path))
    model_store.purge(str(tmp_path))
    with pytest.raises(FileNotFoundError):
        model_store.get_model_file("mobilenet0.25", root=str(tmp_path))


def test_corrupted_sha1_file_does_not_shadow_valid_flat_drop(tmp_path):
    net = vision.get_model("mobilenet0.25", classes=4)
    net.initialize()
    net(nd.zeros((1, 3, 32, 32)))
    bad = _drop_weights(net, "mobilenet0.25", str(tmp_path))
    with open(bad, "r+b") as f:
        f.seek(50)
        f.write(b"\xff")
    good = _drop_weights(net, "mobilenet0.25", str(tmp_path),
                         sha1_named=False)
    assert model_store.get_model_file("mobilenet0.25",
                                      root=str(tmp_path)) == good
