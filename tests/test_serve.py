"""Serving engine tests (ISSUE 9): bucket table + padding purity,
batcher coalescing on the injectable clock, explicit overload shedding,
the SEQ-wire PREDICT round trip with trace propagation and exactly-once
replay, hot-swap-under-load version integrity, and the foreign
symbol.json servable lane.
"""
import os
import socket
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import fault, nd, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.kvstore.wire_codec import (decode_array, encode_array,
                                          is_array_payload)
from mxnet_tpu.serve import (Batcher, BucketTable, ModelHost, Overloaded,
                             Servable, ServeClient, ServeServer,
                             serve_forever)
from mxnet_tpu.serve.demo import (DEMO_IN, demo_block, demo_example,
                                  demo_expected)
from mxnet_tpu.telemetry import registry


def _mk_host(buckets=(1, 2, 4, 8), version=1, scale=None):
    net = demo_block()
    if scale is not None:
        for p in net.collect_params().values():
            p.set_data(p.data() * scale)
    sv = Servable(net, name="demo", version=version,
                  buckets=BucketTable(buckets))
    host = ModelHost()
    host.deploy(sv, example=demo_example())
    return host, sv, net


@pytest.fixture(scope="module")
def shared_host():
    """One warmed (1,2,4,8)-bucket demo host for the read-only batcher
    tests — each test builds its own Batcher (cheap) but shares the
    warm cost (4 trace+compiles) across the module."""
    return _mk_host()


# ---------------------------------------------------------------------------
# wire codec
# ---------------------------------------------------------------------------

def test_npx_codec_roundtrip():
    for arr in (np.arange(12, dtype=np.float32).reshape(3, 4),
                np.zeros((2, 0, 5), np.int32),
                np.asarray(3.5, np.float64)):
        enc = encode_array(arr)
        assert is_array_payload(enc)
        out = decode_array(enc)
        assert out.dtype == arr.dtype and out.shape == arr.shape
        np.testing.assert_array_equal(out, arr)
        out += 1                      # decode must hand back writable


def test_npx_codec_accepts_ndarray_and_rejects_junk():
    enc = encode_array(nd.array(np.ones((2, 3), np.float32)))
    np.testing.assert_array_equal(decode_array(enc), np.ones((2, 3)))
    with pytest.raises(ValueError):
        decode_array(("NOPE", (1,), "float32", b""))


# ---------------------------------------------------------------------------
# bucket table
# ---------------------------------------------------------------------------

def test_bucket_table_selection():
    bt = BucketTable([8, 1, 4, 4, 2])
    assert bt.sizes == (1, 2, 4, 8)
    assert bt.bucket_for(1) == 1
    assert bt.bucket_for(3) == 4
    assert bt.bucket_for(8) == 8
    assert bt.bucket_for(9) is None
    assert bt.max_size == 8


def test_bucket_table_from_env(monkeypatch):
    monkeypatch.setenv("MX_SERVE_BUCKETS", "2, 8,32")
    bt = BucketTable.from_env()
    assert bt.sizes == (2, 8, 32)
    with pytest.raises(MXNetError):
        BucketTable([0, 4])


# ---------------------------------------------------------------------------
# padding correctness
# ---------------------------------------------------------------------------

def test_padded_rows_bit_equal_to_unpadded(shared_host):
    """The pad rows must be invisible: the same 5 real rows through the
    bucket-8 program give BIT-EQUAL outputs whether the other 3 slots
    hold zero padding or unrelated real rows."""
    _host, sv, _net = shared_host
    rng = np.random.RandomState(0)
    real = rng.randn(5, DEMO_IN).astype(np.float32)
    other = rng.randn(3, DEMO_IN).astype(np.float32)
    padded = np.concatenate([real, np.zeros((3, DEMO_IN), np.float32)])
    full = np.concatenate([real, other])
    out_pad = np.asarray(sv.dispatch(8, [padded])[0])
    out_full = np.asarray(sv.dispatch(8, [full])[0])
    np.testing.assert_array_equal(out_pad[:5], out_full[:5])


def test_batcher_padded_result_matches_eager(shared_host):
    """End to end through admission → pad → dispatch → scatter, the
    response equals the eager forward of the unpadded request."""
    host, _sv, net = shared_host
    b = Batcher(host, max_batch=8, max_delay_us=0, queue_cap=64)
    try:
        x = np.random.RandomState(1).randn(3, DEMO_IN).astype(np.float32)
        version, outs = b.submit([x]).result(timeout=30)
        assert version == 1
        assert outs[0].shape == (3, 8)
        np.testing.assert_allclose(outs[0], demo_expected(x, net=net),
                                   rtol=1e-5, atol=1e-6)
    finally:
        b.close()


def test_zero_retraces_after_warm(shared_host):
    host, sv, _net = shared_host
    b = Batcher(host, max_batch=8, max_delay_us=0, queue_cap=64)
    try:
        r0 = sv.retraces
        h0 = sv.bucket_hits
        rng = np.random.RandomState(2)
        for rows in (1, 2, 3, 5, 8, 7, 4):
            b.submit([rng.randn(rows, DEMO_IN).astype(np.float32)]
                     ).result(timeout=30)
        assert sv.retraces == r0, "serve-time retrace happened"
        assert sv.bucket_hits - h0 == 7
    finally:
        b.close()


# ---------------------------------------------------------------------------
# batcher coalescing (virtual clock) + overload
# ---------------------------------------------------------------------------

def test_batcher_coalesces_burst_into_one_dispatch(shared_host):
    """A queued burst coalesces into ceil(rows/max_batch) dispatches —
    deterministic because the batcher starts after the burst lands."""
    host, _sv, _net = shared_host
    b = Batcher(host, max_batch=4, max_delay_us=0, queue_cap=64,
                autostart=False)
    rng = np.random.RandomState(3)
    pendings = [b.submit([rng.randn(1, DEMO_IN).astype(np.float32)])
                for _ in range(8)]
    b0 = registry.value("serve.batches")
    b.start()
    for p in pendings:
        p.result(timeout=30)
    b.close()
    assert registry.value("serve.batches") - b0 == 2


@pytest.mark.chaos
def test_batcher_window_rides_virtual_clock(shared_host):
    """The max-delay coalescing window runs on the injectable clock: a
    lone request dispatches only after the batcher itself pumps the
    VIRTUAL deadline past MX_SERVE_MAX_DELAY_US (no real half-second
    sleep anywhere), and a burst that fills max_batch dispatches without
    waiting out the window."""
    host, _sv, _net = shared_host
    with fault.use_virtual_time() as clk:
        b = Batcher(host, max_batch=4, max_delay_us=500_000,
                    queue_cap=64)
        try:
            t0 = clk.now()
            x = np.zeros((1, DEMO_IN), np.float32)
            version, _outs = b.submit([x]).result(timeout=30)
            assert version == 1
            assert clk.now() - t0 >= 0.5, \
                "window expired without charging the virtual clock"
            # a full burst must NOT wait the window out: 4 rows fill
            # max_batch and dispatch immediately
            b0 = registry.value("serve.batches")
            t1 = clk.now()
            pendings = [b.submit([x]) for _ in range(4)]
            for p in pendings:
                p.result(timeout=30)
            assert registry.value("serve.batches") - b0 == 1
            occ = registry.find("serve.batch_occupancy").snapshot()
            assert occ["max"] >= 4
            assert clk.now() - t1 < 0.5, \
                "full batch still waited out the delay window"
        finally:
            b.close()


def test_overload_rejection_is_explicit(shared_host):
    host, _sv, _net = shared_host
    b = Batcher(host, max_batch=8, max_delay_us=0, queue_cap=4,
                autostart=False)
    rej0 = registry.value("serve.rejected")
    b.submit([np.zeros((2, DEMO_IN), np.float32)])
    b.submit([np.zeros((2, DEMO_IN), np.float32)])
    with pytest.raises(Overloaded):
        b.submit([np.zeros((1, DEMO_IN), np.float32)])
    assert registry.value("serve.rejected") - rej0 == 1
    b.close()           # fails the queued pendings loudly, leaks none


def test_admission_rejects_unservable_requests():
    host, _sv, _net = _mk_host(buckets=(1, 2, 4))
    b = Batcher(host, max_batch=4, max_delay_us=0, queue_cap=64,
                autostart=False)
    with pytest.raises(MXNetError, match="top bucket"):
        b.submit([np.zeros((5, DEMO_IN), np.float32)])
    with pytest.raises(MXNetError, match="signature"):
        b.submit([np.zeros((1, DEMO_IN + 1), np.float32)])
    with pytest.raises(MXNetError, match="disagree"):
        b.submit([np.zeros((1, DEMO_IN), np.float32),
                  np.zeros((2, DEMO_IN), np.float32)])
    b.close()


# ---------------------------------------------------------------------------
# hot swap
# ---------------------------------------------------------------------------

def test_hot_swap_mid_load_serves_only_complete_versions():
    """Requests racing a deploy must each be answered by exactly ONE
    fully-warmed version — the response values must match the tagged
    version's reference outputs bit-for-bit(ish), never a mix."""
    host, _sv1, net1 = _mk_host()
    net2 = demo_block()
    for p in net2.collect_params().values():
        p.set_data(p.data() * 2.0)
    sv2 = Servable(net2, name="demo", version=2,
                   buckets=BucketTable((1, 2, 4, 8)))
    b = Batcher(host, max_batch=4, max_delay_us=100, queue_cap=256)
    stop = threading.Event()
    results, errors = [], []
    lock = threading.Lock()
    rng = np.random.RandomState(4)
    xs = [rng.randn(2, DEMO_IN).astype(np.float32) for _ in range(8)]

    def load():
        i = 0
        while not stop.is_set():
            x = xs[i % len(xs)]
            try:
                version, outs = b.submit([x]).result(timeout=30)
                with lock:
                    results.append((x, version, outs[0]))
            except MXNetError as e:        # pragma: no cover - fails test
                with lock:
                    errors.append(e)
                return
            i += 1

    threads = [threading.Thread(target=load, daemon=True)
               for _ in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.15)
    host.deploy(sv2, example=demo_example())   # warm → flip → drain
    time.sleep(0.15)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    b.close()
    assert not errors, errors
    versions = {v for _x, v, _o in results}
    assert versions == {1, 2}, \
        "load did not straddle the swap: %r" % versions
    exp1 = {id(x): demo_expected(x, net=net1) for x in xs}
    exp2 = {id(x): demo_expected(x, net=net2) for x in xs}
    for x, version, out in results:
        want = exp1[id(x)] if version == 1 else exp2[id(x)]
        np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6,
                                   err_msg="v%d response mixed versions"
                                           % version)
    assert host.version == 2


def test_hot_swap_to_new_signature_fails_stragglers_explicitly():
    """A request admitted under v1's signature, then overtaken by a
    deploy whose signature differs, must get an explicit retryable
    error — never a serve-time retrace through the new version."""
    host, sv1, _net = _mk_host(buckets=(1, 2, 4))
    b = Batcher(host, max_batch=4, max_delay_us=0, queue_cap=16,
                autostart=False)
    p = b.submit([np.zeros((2, DEMO_IN), np.float32)])   # valid for v1
    net2 = nn.HybridSequential()
    net2.add(nn.Dense(4, in_units=DEMO_IN + 4))          # new signature
    net2.initialize(mx.init.Xavier())
    sv2 = Servable(net2, name="demo", version=2,
                   buckets=BucketTable((1, 2, 4)))
    host.deploy(sv2, example=[np.zeros((1, DEMO_IN + 4), np.float32)])
    r2 = sv2.retraces
    b.start()
    with pytest.raises(MXNetError, match="hot-swapped"):
        p.result(timeout=30)
    assert sv2.retraces == r2, "straggler forced a retrace through v2"
    b.close()


def test_dispatch_failure_is_a_reply_not_a_severed_connection():
    """Any dispatch-time exception (XLA error, broken model) must come
    back as a normal (False, reason) PREDICT reply — a severed
    connection would make the client replay the poison request on
    every replica."""
    from mxnet_tpu.serve.server import ServeServer
    host, sv, _net = _mk_host(buckets=(1, 2))
    state = ServeServer(host=host, max_delay_us=0, queue_cap=16)
    try:
        boom = RuntimeError("XLA exploded")

        def bad_dispatch(*a, **k):
            raise boom

        sv.dispatch = bad_dispatch
        ok, reason = state.handle(
            ("PREDICT", [encode_array(np.zeros((1, DEMO_IN),
                                               np.float32))]))
        assert ok is False
        assert "predict failed" in reason and "XLA exploded" in reason
    finally:
        state.close()


class _StubSpan:
    def event(self, *a, **k):
        pass


def test_replay_cache_is_bounded_lru(monkeypatch):
    """ISSUE 11 satellite: the replay cache is a bounded per-client LRU
    (MX_SERVE_REPLAY_CAP) — over-cap inserts evict the least-recently-
    touched RESOLVED entries (counted in serve.replay_evicted), a
    replay hit refreshes its client's recency, and in-flight entries
    are never evicted."""
    from mxnet_tpu import telemetry
    from mxnet_tpu.serve.server import ServeServer
    monkeypatch.setenv("MX_SERVE_REPLAY_CAP", "4")
    host, _sv, _net = _mk_host(buckets=(1,))
    state = ServeServer(host=host, max_delay_us=0, queue_cap=16)
    try:
        assert state._replay_cap == 4
        monkeypatch.setattr(state, "handle",
                            lambda inner, span=None, stream_fn=None:
                            (True, "ok"))
        span = _StubSpan()
        ev0 = telemetry.registry.value("serve.replay_evicted") or 0
        for i in range(4):
            state._handle_seq("c%d" % i, 1, ("PREDICT",), "PREDICT",
                              span)
        # touch c0 via a replay hit: it becomes most-recent
        assert state._handle_seq("c0", 1, ("PREDICT",), "PREDICT",
                                 span) == (True, "ok")
        # two new clients evict the LRU victims — c1 then c2, NOT the
        # just-replayed c0
        state._handle_seq("c4", 1, ("PREDICT",), "PREDICT", span)
        state._handle_seq("c5", 1, ("PREDICT",), "PREDICT", span)
        assert len(state._replay) <= 4
        assert "c0" in state._replay
        assert "c1" not in state._replay and "c2" not in state._replay
        assert (telemetry.registry.value("serve.replay_evicted")
                - ev0) == 2
        # in-flight entries survive eviction pressure
        pending = threading.Event()
        with state._replay_lock:
            state._replay.pop("c0")
            state._replay["inflight"] = [2, pending, None]
        for i in range(6, 16):
            state._handle_seq("c%d" % i, 1, ("PREDICT",), "PREDICT",
                              span)
        assert "inflight" in state._replay
    finally:
        state.close()


def test_model_host_rejects_stale_versions():
    host, _sv, _net = _mk_host(version=3)
    with pytest.raises(MXNetError, match="not newer"):
        host.deploy(Servable(demo_block(), name="demo", version=3,
                             buckets=BucketTable((1, 2))),
                    example=demo_example())
    # a DIFFERENT name is a new co-hosted model, not a stale redeploy
    # (ISSUE 20 multi-model host): its own version chain starts fresh
    host.deploy(Servable(demo_block(), name="demo-b", version=1,
                         buckets=BucketTable((1, 2))),
                example=demo_example())
    assert host.version_of("demo-b") == 1
    assert host.default_model == "demo"


# ---------------------------------------------------------------------------
# wire round trip
# ---------------------------------------------------------------------------

def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _start_replica(port, buckets=(1, 4), abort_event=None):
    state = ServeServer()
    state.host.deploy(
        Servable(demo_block(), version=1, buckets=BucketTable(buckets)),
        example=demo_example())
    stop_ev = threading.Event()
    t = threading.Thread(
        target=serve_forever,
        kwargs=dict(port=port, state=state, stop_event=stop_ev,
                    abort_event=abort_event),
        daemon=True)
    t.start()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port),
                                     timeout=0.2).close()
            return state, stop_ev, t
        except OSError:
            time.sleep(0.05)
    raise RuntimeError("serve replica did not come up on %d" % port)


@pytest.fixture
def replica(monkeypatch):
    monkeypatch.setenv("MX_KVSTORE_RETRY_DEADLINE", "20")
    monkeypatch.setenv("MX_KVSTORE_RETRY_BASE", "0.05")
    monkeypatch.setenv("MX_KVSTORE_RETRY_MAX", "0.25")
    port = _free_port()
    state, stop_ev, t = _start_replica(port)
    yield port, state
    stop_ev.set()
    t.join(timeout=10)
    fault.clear()


def _spans(name):
    return [e for e in telemetry.trace_events()
            if e["name"] == name and e["ph"] == "X"]


def test_predict_round_trip_with_trace_propagation(replica):
    """PREDICT over a real socket: correct values back, and the client
    span, server span and the batch's per-request event share one
    causal chain (wire-propagated trace context)."""
    port, _state = replica
    telemetry.start_tracing()
    try:
        telemetry.clear_trace()
        cli = ServeClient(["127.0.0.1:%d" % port], timeout=15)
        net = demo_block()
        x = np.random.RandomState(5).randn(3, DEMO_IN).astype(np.float32)
        version, outs = cli.predict([x])
        assert version == 1
        np.testing.assert_allclose(outs[0], demo_expected(x, net=net),
                                   rtol=1e-5, atol=1e-6)
        cli.close()
        # one causal chain: client span -> server child span -> batch
        # "request" event carrying the server span's ids
        cli_sp = _spans("serve.client.PREDICT")
        assert cli_sp
        cli_by_trace = {e["args"]["trace_id"]: e for e in cli_sp}
        srv_sp = [e for e in _spans("serve.server.PREDICT")
                  if e["args"]["trace_id"] in cli_by_trace]
        assert srv_sp, "no server span shares a client trace id"
        srv0 = srv_sp[0]
        cli0 = cli_by_trace[srv0["args"]["trace_id"]]
        assert srv0["args"]["parent_id"] == cli0["args"]["span_id"]
        reqev = [e for e in telemetry.trace_events()
                 if e["name"] == "request" and e["ph"] == "i" and
                 e["args"].get("req_trace") == srv0["args"]["trace_id"]]
        assert reqev, "batch span carries no event for this request"
        assert reqev[0]["args"]["req_span"] == srv0["args"]["span_id"]
    finally:
        telemetry.stop_tracing()


@pytest.mark.chaos
def test_lost_reply_is_replayed_exactly_once(replica):
    """A reply dropped after the server dispatched the PREDICT: the
    client replays the SAME seq on reconnect and the server answers
    from the exactly-once cache (no second dispatch burned)."""
    port, state = replica
    cli = ServeClient(["127.0.0.1:%d" % port], timeout=15)
    x = np.ones((1, DEMO_IN), np.float32)
    cli.predict([x])                       # connection warm
    b0 = registry.value("serve.batches")
    r0 = registry.value("serve.server_replays")
    fault.inject("serve.client.recv", action="close", after=0, count=1)
    version, outs = cli.predict([x])
    assert version == 1
    assert registry.value("serve.server_replays") == r0 + 1
    assert registry.value("serve.batches") == b0 + 1, \
        "the replayed PREDICT burned a second dispatch"
    cli.close()


def test_health_and_overload_over_the_wire(replica):
    port, state = replica
    cli = ServeClient(["127.0.0.1:%d" % port], timeout=15)
    h = cli.health()
    assert h["status"] == "serving" and h["version"] == 1
    assert h["buckets"] == [1, 4]
    # oversize request: a normal (False, reason) reply, not a hang
    with pytest.raises(MXNetError, match="top bucket"):
        cli.predict([np.zeros((5, DEMO_IN), np.float32)])
    cli.close()


def test_swap_over_the_wire(replica, tmp_path):
    port, state = replica
    cli = ServeClient(["127.0.0.1:%d" % port], timeout=15)
    net2 = demo_block()
    for p in net2.collect_params().values():
        p.set_data(p.data() * 0.5)
    net2(nd.zeros((1, DEMO_IN)))
    prefix = str(tmp_path / "v2")
    net2.export(prefix, epoch=0)
    assert cli.swap(prefix, epoch=0, input_names=("data",)) == [2]
    x = np.random.RandomState(6).randn(2, DEMO_IN).astype(np.float32)
    version, outs = cli.predict([x])
    assert version == 2
    np.testing.assert_allclose(outs[0], demo_expected(x, net=net2),
                               rtol=1e-4, atol=1e-5)
    assert state.host.version == 2
    cli.close()


@pytest.mark.chaos
def test_failover_loses_no_requests(monkeypatch):
    """Kill one of two replicas mid-stream: every request still gets a
    correct answer (sticky client + SEQ retry + rotation)."""
    monkeypatch.setenv("MX_KVSTORE_RETRY_DEADLINE", "20")
    monkeypatch.setenv("MX_KVSTORE_RETRY_BASE", "0.05")
    monkeypatch.setenv("MX_KVSTORE_RETRY_MAX", "0.25")
    p1, p2 = _free_port(), _free_port()
    ab1 = threading.Event()
    _s1, ev1, t1 = _start_replica(p1, buckets=(2,), abort_event=ab1)
    _s2, ev2, t2 = _start_replica(p2, buckets=(2,))
    try:
        cli = ServeClient(["127.0.0.1:%d" % p1, "127.0.0.1:%d" % p2],
                          timeout=15)
        net = demo_block()
        f0 = registry.value("serve.client_failovers")
        rng = np.random.RandomState(7)
        for i in range(8):
            if i == 3:
                ab1.set()              # sever replica 1 mid-load
            x = rng.randn(2, DEMO_IN).astype(np.float32)
            version, outs = cli.predict([x])
            np.testing.assert_allclose(outs[0],
                                       demo_expected(x, net=net),
                                       rtol=1e-5, atol=1e-6)
        assert registry.value("serve.client_failovers") > f0
        cli.stop()
        cli.close()
    finally:
        ab1.set()
        ev2.set()
        t1.join(timeout=10)
        t2.join(timeout=10)
        fault.clear()


# ---------------------------------------------------------------------------
# foreign symbol.json servable
# ---------------------------------------------------------------------------

def test_foreign_symbol_json_servable_matches_eager(tmp_path):
    """A servable hosted from an exported symbol.json + params pair (the
    deploy artifact every MXNet-era tool emits) answers exactly like the
    live block's eager forward."""
    net = nn.HybridSequential()
    net.add(nn.Dense(12, activation="relu"), nn.Dense(5))
    net.initialize(mx.init.Xavier())
    x = np.random.RandomState(8).randn(3, 7).astype(np.float32)
    y_eager = np.asarray(net(nd.array(x))._jax)
    prefix = str(tmp_path / "foreign")
    net.export(prefix, epoch=2)
    sv = Servable.from_checkpoint(prefix, epoch=2, input_names=("data",),
                                  version=1, buckets=BucketTable((4,)))
    host = ModelHost()
    host.deploy(sv, example=[np.zeros((1, 7), np.float32)])
    b = Batcher(host, max_batch=4, max_delay_us=0, queue_cap=16)
    try:
        version, outs = b.submit([x]).result(timeout=30)
        np.testing.assert_allclose(outs[0], y_eager,
                                   rtol=1e-5, atol=1e-6)
        assert sv.retraces == len(sv.buckets.sizes)   # warm only
    finally:
        b.close()


def test_serve_env_knobs_are_cataloged():
    from mxnet_tpu.base import ENV_CATALOG
    for name in ("MX_SERVE_BUCKETS", "MX_SERVE_MAX_BATCH",
                 "MX_SERVE_MAX_DELAY_US", "MX_SERVE_QUEUE_CAP",
                 "MX_SERVE_PORT", "MX_SERVE_ROOTS", "MX_SERVE_TIMEOUT"):
        assert name in ENV_CATALOG, name
