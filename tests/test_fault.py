"""Chaos suite: the fault-tolerance ladder under deterministic injection.

Every test is marked ``chaos`` and stays inside tier-1's `not slow`
selection: retry/backoff schedules run under mxnet_tpu.fault's virtual
clock wherever wall time doesn't matter, and the few tests that need
real sockets use sub-second knobs (MX_KVSTORE_RETRY_BASE=0.05 etc.).

Coverage, bottom-up:
  * RetryPolicy schedule + deadline math (virtual time, zero real sleep)
  * FaultInjector arming (ordinals, counts, env spec, virtual delay)
  * recv_msg timeout semantics (stalled peer raises, idle is fine)
  * server-side exactly-once replay cache (idempotent PUSH replay)
  * barrier: MX_KVSTORE_BARRIER_TIMEOUT + stale-worker eviction
  * dist_async end-to-end: worker survives a parameter-server restart
    (snapshot durability + client reconnect-and-replay), injected
    connection drops, and the loud terminal error past the deadline
  * crash-safe save_sharded (kill between write and commit)
  * resume_or_init / Module.fit auto-resume after an injected crash
"""
import os
import pickle
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import fault
from mxnet_tpu.base import MXNetError
from mxnet_tpu.kvstore.server import (KVStoreServer, recv_msg, send_msg,
                                      serve_forever)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_faults():
    fault.clear()
    yield
    fault.clear()


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------

def test_retry_policy_backoff_schedule_virtual():
    with fault.use_virtual_time() as clk:
        p = fault.RetryPolicy(deadline=10.0, base=0.5, max_delay=4.0,
                              jitter=0.0)
        attempts = list(p)
    # sleeps 0.5,1,2,4 = 7.5s; the next 4s delay would exceed deadline 10
    assert attempts == [0, 1, 2, 3, 4]
    assert clk.sleeps == [0.5, 1.0, 2.0, 4.0]


def test_deadline_survives_clock_regime_switch():
    # a budget anchored inside use_virtual_time() must not mis-fire when
    # the context exits (virtual ~0 vs real monotonic ~1e5), and vice versa
    with fault.use_virtual_time() as clk:
        dl = fault.Deadline(100.0)
        clk.advance(30.0)
        assert 69.0 < dl.remaining() <= 70.0
    # regime switched: the spanning interval is not charged
    assert 69.0 < dl.remaining() <= 70.0 and not dl.expired()

    dl2 = fault.Deadline(100.0)         # anchored on the real clock
    with fault.use_virtual_time() as clk:
        assert not dl2.expired()        # switch interval uncharged
        clk.advance(150.0)
        assert dl2.expired()            # virtual seconds count once inside


def test_retry_policy_jitter_is_bounded_and_seeded():
    import random
    p = fault.RetryPolicy(deadline=1, base=1.0, max_delay=8.0, jitter=0.5,
                          rng=random.Random(7))
    q = fault.RetryPolicy(deadline=1, base=1.0, max_delay=8.0, jitter=0.5,
                          rng=random.Random(7))
    for k in range(4):
        d_p, d_q = p.delay(k), q.delay(k)
        assert d_p == d_q                      # deterministic under a seed
        base = min(1.0 * 2 ** k, 8.0)
        assert base <= d_p <= base * 1.5


def test_retry_policy_reads_env(monkeypatch):
    monkeypatch.setenv("MX_KVSTORE_RETRY_DEADLINE", "3.5")
    monkeypatch.setenv("MX_KVSTORE_RETRY_BASE", "0.25")
    monkeypatch.setenv("MX_KVSTORE_RETRY_MAX", "1.5")
    monkeypatch.setenv("MX_KVSTORE_RETRY_JITTER", "0")
    p = fault.RetryPolicy.from_env()
    assert (p.deadline, p.base, p.max_delay, p.jitter) == (3.5, 0.25, 1.5, 0)


# ---------------------------------------------------------------------------
# FaultInjector
# ---------------------------------------------------------------------------

def test_inject_fires_on_exact_ordinals():
    fault.inject("t.site", action="error", after=2, count=2)
    fault.fire("t.site")                       # call 0: skipped
    fault.fire("t.site")                       # call 1: skipped
    with pytest.raises(fault.FaultError):
        fault.fire("t.site")                   # call 2: fires
    with pytest.raises(fault.FaultError):
        fault.fire("t.site")                   # call 3: fires
    fault.fire("t.site")                       # count exhausted
    assert fault.site_calls("t.site") == 5


def test_inject_close_runs_on_close_hook():
    closed = []
    fault.inject("t.close", action="close")
    with pytest.raises(fault.FaultError) as ei:
        fault.fire("t.close", on_close=lambda: closed.append(True))
    assert closed == [True]
    assert isinstance(ei.value, ConnectionError)   # transport-shaped


def test_inject_delay_is_virtual():
    fault.inject("t.delay", action="delay", delay=7.5)
    with fault.use_virtual_time() as clk:
        t0 = time.monotonic()
        fault.fire("t.delay")
        elapsed = time.monotonic() - t0
    assert clk.now() == 7.5                    # virtual clock advanced
    assert elapsed < 1.0                       # ...but no real sleep


def test_disarm_and_clear():
    rule = fault.inject("t.d", action="error", count=-1)
    fault.disarm(rule)
    fault.fire("t.d")                          # disarmed: no-op
    fault.inject("t.d", action="error", count=-1)
    fault.clear("t.d")
    fault.fire("t.d")


def test_arm_from_env_spec():
    rules = fault.arm_from_env(
        "a.site:error:after=1,count=3;b.site:delay:delay=0.5")
    assert len(rules) == 2
    assert (rules[0].site, rules[0].after, rules[0].count) == ("a.site", 1, 3)
    assert (rules[1].action, rules[1].delay) == ("delay", 0.5)
    with pytest.raises(ValueError):
        fault.arm_from_env("missing-action")
    with pytest.raises(ValueError):
        fault.arm_from_env("a:error:bogus=1")


def test_launch_py_forwards_fault_spec():
    """tools/launch.py --fault arms MX_FAULT_INJECT in every worker."""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "1", "--launcher", "local",
         "--fault", "kvstore.send:close:after=3", "--",
         sys.executable, "-c",
         "import os; print('SPEC=' + os.environ['MX_FAULT_INJECT'])"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "SPEC=kvstore.send:close:after=3" in r.stdout


# ---------------------------------------------------------------------------
# recv_msg timeout (satellite: a stalled peer must not hang the thread)
# ---------------------------------------------------------------------------

def test_recv_msg_times_out_on_silent_peer():
    a, b = socket.socketpair()
    try:
        t0 = time.monotonic()
        with pytest.raises(TimeoutError):
            recv_msg(a, timeout=0.15)
        assert time.monotonic() - t0 < 2.0
    finally:
        a.close()
        b.close()


def test_recv_msg_times_out_mid_message():
    a, b = socket.socketpair()
    try:
        # header promises 100 bytes; peer stalls after 10
        b.sendall(struct.pack("<Q", 100) + b"x" * 10)
        with pytest.raises(TimeoutError) as ei:
            recv_msg(a, timeout=0.15)
        assert "mid-message" in str(ei.value)
    finally:
        a.close()
        b.close()


def test_recv_msg_idle_block_still_bounds_started_message():
    """idle_block=True waits forever for a message to START, but once the
    first byte lands the rest is bounded — the server-loop posture."""
    a, b = socket.socketpair()
    try:
        b.sendall(b"\x01")                     # message started, then stall
        with pytest.raises(TimeoutError):
            recv_msg(a, timeout=0.15, idle_block=True)
    finally:
        a.close()
        b.close()


def test_recv_msg_default_from_env(monkeypatch):
    monkeypatch.setenv("MX_KVSTORE_RECV_TIMEOUT", "0.15")
    a, b = socket.socketpair()
    try:
        with pytest.raises(TimeoutError):
            recv_msg(a)
    finally:
        a.close()
        b.close()


def test_recv_msg_roundtrip_unaffected():
    a, b = socket.socketpair()
    try:
        send_msg(b, ("PING", "r0:x"))
        assert recv_msg(a, timeout=1.0) == ("PING", "r0:x")
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# server: exactly-once replay, heartbeat liveness, barrier eviction
# ---------------------------------------------------------------------------

def test_server_replay_cache_applies_push_exactly_once():
    srv = KVStoreServer(num_workers=1)
    srv.handle_request(("SEQ", "r0:x", 1, ("INIT", "w", np.ones(3))))
    ok, _ = srv.handle_request(("SEQ", "r0:x", 2, ("PUSH", "w", np.ones(3))))
    assert ok
    # reconnect-replay of the SAME seq: answered from cache, NOT re-applied
    ok2, _ = srv.handle_request(("SEQ", "r0:x", 2, ("PUSH", "w",
                                                    np.ones(3))))
    assert ok2
    ok3, val = srv.handle_request(("SEQ", "r0:x", 3, ("PULL", "w")))
    assert ok3
    np.testing.assert_allclose(val, 2.0)       # init 1 + exactly one push
    # a MUTATING seq from the past is refused, never silently re-run
    # (PULL/PING are idempotent and bypass the cache entirely)
    ok4, msg4 = srv.handle_request(("SEQ", "r0:x", 1, ("PUSH", "w",
                                                       np.ones(3))))
    assert not ok4 and "stale" in str(msg4)
    _, val2 = srv.handle_request(("SEQ", "r0:x", 4, ("PULL", "w")))
    np.testing.assert_allclose(val2, 2.0)      # store untouched by stale


def test_replay_cache_survives_snapshot_restart(tmp_path):
    """Exactly-once across the restart itself: a PUSH applied and
    snapshotted right before the crash is answered from the restored
    cache when the reconnecting client replays it — never re-applied."""
    snap = str(tmp_path / "s.pkl")
    srv = KVStoreServer(num_workers=1, snapshot_path=snap)
    srv.handle_request(("SEQ", "r0:x", 1, ("INIT", "w", np.ones(2))))
    srv.handle_request(("SEQ", "r0:x", 2, ("PUSH", "w", np.ones(2))))
    # crash after snapshot, before the reply reached the worker:
    srv2 = KVStoreServer(num_workers=1, snapshot_path=snap)   # restart
    ok, _ = srv2.handle_request(("SEQ", "r0:x", 2, ("PUSH", "w",
                                                    np.ones(2))))
    assert ok
    _, val = srv2.handle_request(("SEQ", "r0:x", 3, ("PULL", "w")))
    np.testing.assert_allclose(val, 2.0)       # once, not twice


def test_replay_cache_resolves_even_when_handler_faults():
    """A handler fault must still resolve the seq's cache entry with an
    error — a forever-pending entry would make every replay wait out the
    full window and starve the client's retry deadline."""
    srv = KVStoreServer(num_workers=1)
    with pytest.raises(Exception):
        srv.handle_request(("SEQ", "r0:x", 5, ("PUSH",)))   # malformed
    t0 = time.monotonic()
    ok, payload = srv.handle_request(("SEQ", "r0:x", 5, ("PUSH",)))
    assert time.monotonic() - t0 < 1.0       # instant, no in-flight wait
    assert not ok and "server error" in str(payload)


def test_concurrent_pushes_with_snapshot_do_not_race(tmp_path):
    """Snapshot writes are serialized: concurrent handler threads all
    snapshotting after their mutations must never collide on the temp
    file (the loser's os.replace used to throw FileNotFoundError)."""
    snap = str(tmp_path / "s.pkl")
    srv = KVStoreServer(num_workers=8, snapshot_path=snap)
    srv.handle_request(("SEQ", "r0:a", 1, ("INIT", "w", np.zeros(4))))
    errs = []

    def push(cid):
        try:
            ok, p = srv.handle_request(
                ("SEQ", cid, 2, ("PUSH", "w", np.ones(4))))
            assert ok, p
        except Exception as e:               # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=push, args=("r%d:c" % i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    srv.snapshot()                           # settle the final state
    srv2 = KVStoreServer(num_workers=8, snapshot_path=snap)
    np.testing.assert_allclose(srv2._store["w"], 8.0)


def test_server_ping_tracks_liveness():
    srv = KVStoreServer(num_workers=2)
    ok, payload = srv.handle(("PING", "r0:abc"))
    assert ok and payload == "PONG"
    assert "r0" in srv._last_seen


def test_barrier_timeout_env(monkeypatch):
    """Satellite: the hardcoded 120s barrier wait is now env-tunable."""
    monkeypatch.setenv("MX_KVSTORE_BARRIER_TIMEOUT", "0.3")
    monkeypatch.setenv("MX_KVSTORE_STALE_TIMEOUT", "30")
    srv = KVStoreServer(num_workers=2)
    t0 = time.monotonic()
    ok, payload = srv.handle(("BARRIER", None))
    elapsed = time.monotonic() - t0
    assert not ok and "timed out" in str(payload)
    assert 0.2 < elapsed < 3.0                 # honored 0.3, not 120


def test_barrier_releases_when_stale_worker_evicted(monkeypatch):
    """A wedged worker cannot hold BARRIER forever: once it goes silent
    past MX_KVSTORE_STALE_TIMEOUT it leaves the quorum and the live
    workers proceed."""
    monkeypatch.setenv("MX_KVSTORE_STALE_TIMEOUT", "0.25")
    monkeypatch.setenv("MX_KVSTORE_BARRIER_TIMEOUT", "20")
    srv = KVStoreServer(num_workers=2)
    srv.touch("r1:wedged")                     # seen once, then silent
    time.sleep(0.35)                           # past the stale window
    t0 = time.monotonic()
    ok, _ = srv.handle_request(("SEQ", "r0:live", 1, ("BARRIER", None)))
    assert ok
    assert time.monotonic() - t0 < 5.0         # released, no 20s strand


def test_barrier_waits_for_workers_never_seen(monkeypatch):
    """Eviction only applies to workers that went silent AFTER being
    seen — a worker still booting must be waited for."""
    monkeypatch.setenv("MX_KVSTORE_STALE_TIMEOUT", "0.2")
    monkeypatch.setenv("MX_KVSTORE_BARRIER_TIMEOUT", "0.4")
    srv = KVStoreServer(num_workers=2)         # worker 1 never connects
    ok, payload = srv.handle(("BARRIER", None))
    assert not ok and "timed out" in str(payload)


# ---------------------------------------------------------------------------
# dist_async end-to-end: server restart survival
# ---------------------------------------------------------------------------

def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _start_server(port, snapshot=None, num_workers=1):
    t = threading.Thread(
        target=serve_forever,
        kwargs=dict(port=port, num_workers=num_workers,
                    snapshot_path=snapshot),
        daemon=True)
    t.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port), timeout=0.2).close()
            return t
        except OSError:
            time.sleep(0.05)
    raise RuntimeError("server did not come up on %d" % port)


def _stop_server(port, thread):
    raw = socket.create_connection(("127.0.0.1", port), timeout=5)
    send_msg(raw, ("STOP", None))
    assert recv_msg(raw, timeout=5)[0]
    raw.close()
    thread.join(timeout=10)
    assert not thread.is_alive()


@pytest.fixture
def _fast_retries(monkeypatch):
    monkeypatch.setenv("MX_KVSTORE_RETRY_DEADLINE", "20")
    monkeypatch.setenv("MX_KVSTORE_RETRY_BASE", "0.05")
    monkeypatch.setenv("MX_KVSTORE_RETRY_MAX", "0.25")
    monkeypatch.setenv("MX_KVSTORE_HEARTBEAT", "0")   # no bg threads here
    monkeypatch.delenv("MX_PS_ROOTS", raising=False)


def _make_client(monkeypatch, port):
    from mxnet_tpu.kvstore.kvstore import KVStoreDistAsync
    monkeypatch.setenv("MX_PS_ROOT", "127.0.0.1:%d" % port)
    return KVStoreDistAsync()


def test_worker_survives_server_restart(_fast_retries, monkeypatch,
                                        tmp_path):
    """THE acceptance case: push, kill the PS mid-session, restart it on
    the same port (snapshot-backed), and the client's next pull succeeds
    within the retry deadline — no data loss, optimizer state intact."""
    from mxnet_tpu import optimizer
    port = _free_port()
    snap = str(tmp_path / "ps.pkl")
    t = _start_server(port, snapshot=snap)
    kv = _make_client(monkeypatch, port)
    try:
        kv.init("w", mx.nd.ones((4,)))
        kv.set_optimizer(optimizer.SGD(learning_rate=0.5))
        kv.push("w", mx.nd.ones((4,)))
        out = mx.nd.zeros((4,))
        kv.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), 0.5)

        _stop_server(port, t)                  # ...the server dies...

        # restart with a delay, while the client is already retrying
        def restart():
            time.sleep(0.4)
            _start_server(port, snapshot=snap)
        restarter = threading.Thread(target=restart, daemon=True)
        restarter.start()
        out2 = mx.nd.zeros((4,))
        t0 = time.monotonic()
        kv.pull("w", out=out2)                 # rides through the outage
        assert time.monotonic() - t0 < 20      # inside the retry deadline
        np.testing.assert_allclose(out2.asnumpy(), 0.5)   # no data loss

        # the restored server still applies the optimizer (snapshot
        # carried the SET_OPT blob + slot states, not just weights)
        kv.push("w", mx.nd.ones((4,)))
        kv.pull("w", out=out2)
        np.testing.assert_allclose(out2.asnumpy(), 0.0)
        restarter.join()
    finally:
        kv.stop_server()


def test_client_rides_through_injected_connection_drops(
        _fast_retries, monkeypatch):
    """Deterministic chaos: the kvstore.send site closes the connection
    twice; the RPC layer reconnects and replays without the caller ever
    noticing."""
    port = _free_port()
    t = _start_server(port)
    kv = _make_client(monkeypatch, port)
    try:
        kv.init("w", mx.nd.ones((2,)))
        fault.inject("kvstore.send", action="close", count=2)
        out = mx.nd.zeros((2,))
        kv.pull("w", out=out)                  # absorbed both drops
        np.testing.assert_allclose(out.asnumpy(), 1.0)
        assert fault.site_calls("kvstore.send") >= 3
    finally:
        fault.clear()
        kv.stop_server()
        t.join(timeout=10)


def test_terminal_error_after_retry_deadline(_fast_retries, monkeypatch):
    """Past the deadline the failure is LOUD: MXNetError naming the knob
    and the last transport error, not a hang or a silent None."""
    monkeypatch.setenv("MX_KVSTORE_RETRY_DEADLINE", "0.6")
    port = _free_port()
    t = _start_server(port)
    kv = _make_client(monkeypatch, port)
    kv.init("w", mx.nd.ones((2,)))
    _stop_server(port, t)                      # gone for good
    out = mx.nd.zeros((2,))
    t0 = time.monotonic()
    with pytest.raises(MXNetError) as ei:
        kv.pull("w", out=out)
    assert time.monotonic() - t0 < 10
    assert "MX_KVSTORE_RETRY_DEADLINE" in str(ei.value)


def test_heartbeat_thread_keeps_worker_live(monkeypatch, tmp_path):
    """With heartbeats on, a client that does NO data RPCs for longer
    than the stale window still counts as live (its rank stays fresh in
    the server's last-seen table)."""
    monkeypatch.setenv("MX_KVSTORE_RETRY_DEADLINE", "10")
    monkeypatch.setenv("MX_KVSTORE_HEARTBEAT", "0.1")
    monkeypatch.delenv("MX_PS_ROOTS", raising=False)
    port = _free_port()
    # in-process server STATE so the test can inspect last-seen directly
    srv = KVStoreServer(num_workers=1)
    stop = threading.Event()

    def serve():
        import socketserver

        class H(socketserver.BaseRequestHandler):
            def handle(self):
                while not stop.is_set():
                    try:
                        msg = recv_msg(self.request, timeout=1.0,
                                       idle_block=False)
                    except TimeoutError:
                        continue
                    except (ConnectionError, OSError):
                        return
                    ok, payload = srv.handle_request(msg)
                    send_msg(self.request, (ok, payload))

        class S(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        with S(("127.0.0.1", port), H) as s:
            threading.Thread(target=s.serve_forever, daemon=True).start()
            stop.wait()
            s.shutdown()

    threading.Thread(target=serve, daemon=True).start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port), timeout=0.2).close()
            break
        except OSError:
            time.sleep(0.05)
    kv = _make_client(monkeypatch, port)
    try:
        time.sleep(0.45)                       # > stale window, no data RPCs
        assert "r0" in srv._last_seen
        assert time.monotonic() - srv._last_seen["r0"] < 0.4
    finally:
        kv.close()
        stop.set()


# ---------------------------------------------------------------------------
# checkpoint: crash-safe save + resume
# ---------------------------------------------------------------------------

def test_save_sharded_survives_kill_mid_save(tmp_path):
    """Satellite: a kill between write and commit never corrupts the
    last restorable checkpoint; the orphan temp dir is swept later."""
    from mxnet_tpu.checkpoint import save_sharded, restore_sharded
    p = str(tmp_path / "ck")
    save_sharded(p, {"w": jnp.ones((4,))})
    fault.inject("checkpoint.commit", action="crash")
    with pytest.raises(SystemExit):
        save_sharded(p, {"w": jnp.zeros((4,))})
    fault.clear()
    out = restore_sharded(p, template={"w": jnp.ones((4,))})
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0)   # intact
    orphans = [e for e in os.listdir(tmp_path) if ".saving-" in e]
    assert orphans                              # the victim's debris...
    save_sharded(p, {"w": jnp.full((4,), 7.0)})
    out = restore_sharded(p, template={"w": jnp.ones((4,))})
    np.testing.assert_allclose(np.asarray(out["w"]), 7.0)
    assert not [e for e in os.listdir(tmp_path) if ".saving-" in e]


def test_save_sharded_heals_kill_inside_commit_window(tmp_path):
    """A kill between the two commit renames leaves the previous
    checkpoint at '<name>.replaced'; the next restore (or save) promotes
    it back instead of cold-starting."""
    from mxnet_tpu.checkpoint import save_sharded, restore_sharded
    p = str(tmp_path / "ck")
    save_sharded(p, {"w": jnp.ones((4,))})
    os.rename(p, p + ".replaced")              # mid-commit crash state
    out = restore_sharded(p, template={"w": jnp.ones((4,))})
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0)
    assert os.path.exists(p) and not os.path.exists(p + ".replaced")


def test_resume_or_init_continues_after_injected_crash(tmp_path):
    """Acceptance: a training loop resumed via resume_or_init continues
    from the last checkpointed step after an injected crash."""
    from mxnet_tpu.checkpoint import resume_or_init
    steps_run = []

    def run(total):
        state, start, mgr = resume_or_init(
            str(tmp_path / "run"), lambda: {"w": jnp.zeros((3,))})
        try:
            for step in range(start, total):
                fault.fire("train.step")       # chaos kill point
                state = {"w": state["w"] + 1.0}
                mgr.save(step, state)
                steps_run.append(step)
        finally:
            mgr.close()
        return state

    fault.inject("train.step", action="crash", after=3)
    with pytest.raises(SystemExit):
        run(6)                                 # dies entering step 3
    fault.clear()
    state = run(6)                             # restart: resumes at 3
    assert steps_run == [0, 1, 2, 3, 4, 5]     # no step repeated or lost
    np.testing.assert_allclose(np.asarray(state["w"]), 6.0)


def _mlp():
    from mxnet_tpu import symbol as sym
    data = sym.Variable("data")
    h = sym.FullyConnected(data, sym.Variable("fc1_weight"),
                           sym.Variable("fc1_bias"), num_hidden=16)
    h = sym.Activation(h, act_type="relu")
    out = sym.FullyConnected(h, sym.Variable("fc2_weight"),
                             sym.Variable("fc2_bias"), num_hidden=3)
    return sym.SoftmaxOutput(out, sym.Variable("softmax_label"),
                             normalization="batch", name="softmax")


def test_module_fit_auto_resumes_after_crash(tmp_path):
    """Acceptance: Module.fit(checkpoint_dir=...) checkpoints every
    epoch and a restarted fit resumes from latest_step()+1 with the
    restored params."""
    from mxnet_tpu import io as mio
    from mxnet_tpu.module import Module
    rng = np.random.RandomState(0)
    X = rng.randn(96, 8).astype(np.float32)
    Y = X[:, :3].argmax(axis=1).astype(np.float32)
    d = str(tmp_path / "fit")

    fault.inject("module.fit.epoch", action="crash", after=2)
    mod = Module(_mlp(), context=mx.cpu())
    with pytest.raises(SystemExit):
        mod.fit(mio.NDArrayIter(X, Y, batch_size=24), optimizer="sgd",
                optimizer_params={"learning_rate": 1.0}, num_epoch=5,
                checkpoint_dir=d)              # dies in epoch 2, saved 0-1
    fault.clear()

    epochs = []
    mod2 = Module(_mlp(), context=mx.cpu())
    mod2.fit(mio.NDArrayIter(X, Y, batch_size=24), optimizer="sgd",
             optimizer_params={"learning_rate": 1.0}, num_epoch=5,
             checkpoint_dir=d,
             batch_end_callback=lambda p: epochs.append(p.epoch))
    assert sorted(set(epochs)) == [2, 3, 4]    # resumed, not restarted
    # the resumed params came from the checkpoint, and the final fit
    # leaves a usable model
    acc = mod2.score(mio.NDArrayIter(X, Y, batch_size=24), "acc")
    assert acc[0][1] > 1.0 / 3.0 - 0.05, acc   # better than chance


def test_module_fit_resume_matches_uninterrupted_momentum_run(tmp_path):
    """Optimizer slot state (momentum) rides in the checkpoint sidecar:
    a crash+resume trajectory must match an uninterrupted run, not a
    cold-optimizer restart."""
    from mxnet_tpu import io as mio
    from mxnet_tpu.module import Module
    rng = np.random.RandomState(3)
    X = rng.randn(48, 8).astype(np.float32)
    Y = X[:, :3].argmax(axis=1).astype(np.float32)
    opt = {"learning_rate": 0.1, "momentum": 0.9}
    d = str(tmp_path / "fit")

    def fresh():
        mx.random.seed(42)                      # identical init each time
        return Module(_mlp(), context=mx.cpu())

    def data():
        return mio.NDArrayIter(X, Y, batch_size=24)   # deterministic order

    ref = fresh()                               # uninterrupted 4 epochs
    ref.fit(data(), optimizer="sgd", optimizer_params=opt, num_epoch=4)

    fault.inject("module.fit.epoch", action="crash", after=2)
    m = fresh()
    with pytest.raises(SystemExit):             # dies in epoch 2
        m.fit(data(), optimizer="sgd", optimizer_params=opt, num_epoch=4,
              checkpoint_dir=d)
    fault.clear()
    m2 = fresh()
    m2.fit(data(), optimizer="sgd", optimizer_params=opt, num_epoch=4,
           checkpoint_dir=d)                    # resumes epochs 2-3

    ref_arg, _ = ref.get_params()
    got_arg, _ = m2.get_params()
    for k in ref_arg:
        np.testing.assert_allclose(got_arg[k].asnumpy(),
                                   ref_arg[k].asnumpy(),
                                   rtol=1e-5, atol=1e-6)


def test_module_fit_resume_restores_exact_params(tmp_path):
    """The resumed run restores the checkpointed weights bit-for-bit
    before continuing (auto_resume=False still starts cold)."""
    from mxnet_tpu import io as mio
    from mxnet_tpu.module import Module
    rng = np.random.RandomState(1)
    X = rng.randn(48, 8).astype(np.float32)
    Y = X[:, :3].argmax(axis=1).astype(np.float32)
    d = str(tmp_path / "fit")
    mod = Module(_mlp(), context=mx.cpu())
    mod.fit(mio.NDArrayIter(X, Y, batch_size=24), optimizer="sgd",
            optimizer_params={"learning_rate": 0.5}, num_epoch=2,
            checkpoint_dir=d)
    arg, _ = mod.get_params()

    # resumed module: begin beyond num_epoch → pure restore, no training
    mod2 = Module(_mlp(), context=mx.cpu())
    mod2.fit(mio.NDArrayIter(X, Y, batch_size=24), optimizer="sgd",
             optimizer_params={"learning_rate": 0.5}, num_epoch=2,
             checkpoint_dir=d)
    arg2, _ = mod2.get_params()
    for k in arg:
        np.testing.assert_array_equal(arg[k].asnumpy(), arg2[k].asnumpy())
